"""Stage 1 for K heterogeneous groups — coupled SI network ODE.

Reference: `solve_SInetwork_hetero` (`src/extensions/heterogeneity/
heterogeneity_learning.jl:49-94`):

    dG_k/dt = (1 - G_k) · β_k · ω(t),   ω(t) = Σ_j dist_j · G_j(t)

The reference integrates with an adaptive solver and wraps each group in its
own interpolation object; here the state is a (K,) array advanced by RK4 on a
static grid (`core.ode.rk4`), so the whole family is one `lax.scan` and the
ω reduction is a dot product — a `psum` when the group axis is sharded.
PDFs come from the symbolic rhs g_k = (1-G_k)·β_k·ω exactly like
`compute_pdf_hetero` (`heterogeneity_learning.jl:114-134`), with no O(K²·n)
double loop: all groups evaluate in one broadcast.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sbr_tpu.core.integrate import cumulative_gauss_legendre
from sbr_tpu.core.ode import bs32, rk4
from sbr_tpu.models.params import LearningParamsHetero, SolverConfig
from sbr_tpu.models.results import LearningSolutionHetero


def hetero_rhs(t, G, args):
    """Coupled SI rhs (`heterogeneity_learning.jl:57-67`). G: (K,).

    With ``axis_name`` set (group axis sharded under shard_map), the ω
    reduction completes across shards with a psum — the only collective the
    coupled system needs (SURVEY §5.8(a))."""
    del t
    betas, dist, axis_name = args
    omega = jnp.dot(dist, G)
    if axis_name is not None:
        omega = lax.psum(omega, axis_name)
    return (1.0 - G) * betas * omega


def hetero_substeps(params: LearningParamsHetero, config: SolverConfig) -> int:
    """RK4 substeps keeping β_max · h ≲ 0.015 per microstep: global error
    ~(βh)^4 then sits near 1e-8, inside the 1e-6 CPU-match envelope even for
    the fast-group configs (reference example β_max=12.5,
    `scripts/2_heterogeneity.jl:38`)."""
    t0, t1 = params.tspan
    h0 = (t1 - t0) / (config.n_grid - 1)
    beta_max = float(max(params.betas))
    return max(config.ode_substeps, int(jnp.ceil(beta_max * h0 / 0.015)))


def solve_learning_hetero_arrays(
    betas: jnp.ndarray,
    dist: jnp.ndarray,
    x0: float,
    grid: jnp.ndarray,
    substeps: int,
    axis_name=None,
    adaptive_tols=None,
) -> LearningSolutionHetero:
    """Array-level coupled solve — the shard_map-compatible core.

    ``betas``/``dist`` are the (local slice of the) group axis; with
    ``axis_name`` the ω reductions psum across the sharded axis, so every
    shard integrates its groups against the GLOBAL mixing field.

    ``adaptive_tols=(rtol, atol)`` (ISSUE 9) integrates with the adaptive
    embedded pair `core.ode.bs32` instead of ``substeps`` fixed RK4
    micro-steps — smooth stretches take one step per save interval where
    `hetero_substeps` budgets for the fastest group's worst case. Only for
    the UNSHARDED path: under a sharded group axis the error norm would
    have to psum so every shard takes identical steps; the sharded entry
    keeps fixed RK4 (bit-exact sharding equivalence is one of its test
    contracts).
    """
    dtype = betas.dtype
    g0 = jnp.full(betas.shape, x0, dtype=dtype)
    if axis_name is not None:
        # The scan carry becomes device-varying (it mixes in the sharded
        # betas); mark the constant-filled initial state as varying too so
        # shard_map's manual-axes check accepts the loop.
        from sbr_tpu.parallel.compat import pcast

        g0 = pcast(g0, (axis_name,), to="varying")
    ode_flags = None
    if adaptive_tols is not None and axis_name is None:
        rtol, atol = adaptive_tols
        cdfs, ode_health = bs32(
            hetero_rhs, g0, grid, args=(betas, dist, None), rtol=rtol, atol=atol,
            with_health=True,
        )  # (n, K)
        # Only the flags ride along: ODE_BUDGET (an interval exhausted its
        # step cap and bridged unchecked) would otherwise be invisible —
        # the clip below hides even wild trajectories from downstream.
        ode_flags = ode_health.flags
    else:
        cdfs = rk4(hetero_rhs, g0, grid, args=(betas, dist, axis_name), substeps=substeps)  # (n, K)
    cdfs = jnp.clip(cdfs.T, 0.0, 1.0)  # (K, n)

    omega = jnp.einsum("k,kn->n", dist, cdfs)
    if axis_name is not None:
        omega = lax.psum(omega, axis_name)
    pdfs = (1.0 - cdfs) * betas[:, None] * omega[None, :]

    return LearningSolutionHetero(
        grid=grid,
        cdfs=cdfs,
        pdfs=pdfs,
        t0=grid[0],
        dt=grid[1] - grid[0],
        betas=betas,
        dist=dist,
        ode_flags=ode_flags,
    )


# ---------------------------------------------------------------------------
# Exact path: the coupled K-ODE reduced to one scalar quadrature.
#
# Substituting Ω(t) = ∫₀ᵗ ω(s) ds into dG_k/dt = (1-G_k)·β_k·ω(t) gives
# dG_k/dΩ = β_k·(1-G_k), i.e. the CLOSED FORM
#
#     G_k(Ω) = 1 - (1-x0)·e^{-β_k·Ω},
#
# and Ω itself satisfies the scalar autonomous ODE
#
#     dΩ/dt = ω(Ω) = Σ_j dist_j·G_j(Ω) = 1 - (1-x0)·Σ_j dist_j·e^{-β_j·Ω},
#
# which, being monotone, is solved by quadrature: t(Ω) = ∫₀^Ω dv/ω(v) with an
# ANALYTIC integrand. This replaces the RK4 scan entirely and — decisive for
# the β_k ≳ n_grid/η regime (VERDICT r4 task 4) — every group's transition is
# exact in Ω-space; the grid only has to resolve the scalar map t(Ω), whose
# knots are chosen below as the union of per-group G-quantile points (local
# spacing ~1/(β_k·n) through EVERY group's transition, the same inverse-CDF
# idea as `baseline/solver.py::_warped_grid`), log-spaced points through the
# early exponential ramp (ω varies on the scale x0/⟨β⟩ near Ω=0), and
# uniform-in-t points for the tail. The reference resolves the same structure
# with its adaptive solver (`heterogeneity_learning.jl:73-74` at eps tol).
# ---------------------------------------------------------------------------


def _omega_of(betas, dist, x0):
    """ω(Ω) evaluator, broadcasting over any Ω shape (K-sum on axis 0)."""

    def omega(v):
        v = jnp.asarray(v)
        e = jnp.exp(-betas.reshape(betas.shape + (1,) * v.ndim) * v[None])
        return 1.0 - (1.0 - x0) * jnp.sum(dist.reshape(dist.shape + (1,) * v.ndim) * e, axis=0)

    return omega


def _omega_knots(betas, dist, x0, omega_hi, n_q, n_log, dtype):
    """Quantile + log knots on [0, omega_hi] (pins added by the caller).

    Per-group quantile points place levels uniformly in each group's
    informed share: G_k levels L → Ω = -ln((1-L)/(1-x0))/β_k, clustering
    through every group's exponential knee at any β_k. When K exceeds the
    ``n_q`` budget, a β-sorted stride subsample of the groups gets one
    point each — nearby βs share transition regions, so log-spread
    representatives keep every decade of β covered. Log points cover the
    early ramp where ω grows from x0 on the Ω-scale x0/⟨β⟩. The output
    size is static: ≤ n_q + n_log always.
    """
    # Fill the n_q budget with (group, level) pairs: groups round-robin over
    # ≤ n_q β-sorted representatives, levels from a golden-ratio
    # low-discrepancy sequence. This covers (0, 1] finely in AGGREGATE
    # whatever the group structure — duplicate or near-equal βs pool their
    # slots into one densely-covered transition (a per-group uniform split
    # would collapse K identical groups onto per ≈ n_q/K distinct levels),
    # while widely separated βs each keep ~n_q/K spread levels of their own.
    k = betas.shape[0]
    n_sel = min(k, n_q)
    gidx = np.linspace(0, k - 1, n_sel).astype(np.int32)
    slots = np.arange(n_q) % n_sel
    sel = jnp.sort(betas)[gidx][slots]  # (n_q,)
    phi = 0.6180339887498949
    q = jnp.asarray((np.arange(1, n_q + 1) * phi) % 1.0, dtype=dtype)
    q = jnp.clip(q, 1.0 / (2 * n_q), 1.0)
    g_hi = 1.0 - (1.0 - x0) * jnp.exp(-sel * omega_hi)
    levels = jnp.clip(x0 + q * (g_hi - x0), x0, 1.0 - 1e-15)
    quant = -jnp.log((1.0 - levels) / (1.0 - x0)) / sel

    beta_ave = jnp.dot(dist, betas)
    lo = jnp.maximum(x0 / beta_ave * 1e-2, omega_hi * 1e-14)
    logs = jnp.exp(
        jnp.linspace(jnp.log(lo), jnp.log(omega_hi), n_log, dtype=dtype)
    )
    return jnp.clip(jnp.concatenate([quant.reshape(-1), logs]), 0.0, omega_hi)


def solve_learning_hetero_exact(
    params: LearningParamsHetero,
    config: SolverConfig | None = None,
    dtype=jnp.float64,
):
    """Exact hetero Stage 1 via the Ω reduction (module header above).

    Returns (t_grid, omega_grid, omega_vals) — the warped time grid, Ω at its
    knots, and ω(Ω) there — for `hetero_solution_from_omega` to expand into
    per-group arrays (kept separate so the sharded path can expand LOCAL
    group rows from the same replicated table).
    """
    if config is None:
        config = SolverConfig()
    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))
    t0, t1 = params.tspan
    if t0 != 0.0:
        raise ValueError(f"hetero exact path assumes tspan starting at 0, got {params.tspan}")
    betas = jnp.asarray(params.betas, dtype=dtype)
    dist = jnp.asarray(params.dist, dtype=dtype)
    x0 = jnp.asarray(params.x0, dtype=dtype)
    omega = _omega_of(betas, dist, x0)
    n = config.n_grid
    order = config.quad_order

    # Pass 1 — coarse map out to Ω = t1 (an upper bound: ω ≤ 1 ⇒ Ω(t) ≤ t),
    # to locate Ω₁ = Ω(t1).
    coarse = jnp.sort(
        jnp.concatenate(
            [
                jnp.zeros((1,), dtype),
                _omega_knots(betas, dist, x0, jnp.asarray(t1, dtype), n // 2, n // 8, dtype),
                jnp.linspace(jnp.zeros((), dtype), t1, n // 4),
            ]
        )
    )
    t_coarse = cumulative_gauss_legendre(lambda v: 1.0 / omega(v), coarse, order=order)
    omega1 = jnp.interp(jnp.asarray(t1, dtype), t_coarse, coarse)

    # Pass 2 — final grid on [0, Ω₁]: quantiles + logs + uniform-in-t knots
    # (inverted through the coarse map), endpoints pinned. The uniform
    # budget absorbs whatever the (static-sized) knot set didn't use, so
    # the total is exactly n_grid at any K.
    knots = _omega_knots(betas, dist, x0, omega1, n // 2, n // 8, dtype)
    n_unif = n - int(knots.shape[0]) - 2
    t_targets = jnp.linspace(jnp.zeros((), dtype), t1, n_unif)
    omega_unif = jnp.interp(t_targets, t_coarse, coarse)
    omega_grid = jnp.sort(
        jnp.concatenate([jnp.zeros((1,), dtype), knots, omega_unif, omega1[None]])
    )
    omega_grid = jnp.clip(omega_grid, 0.0, omega1).at[0].set(0.0).at[-1].set(omega1)

    t_grid = cumulative_gauss_legendre(lambda v: 1.0 / omega(v), omega_grid, order=order)
    # Pin the endpoint exactly: t(Ω₁) = t1 up to the coarse-map inversion
    # error; downstream takes grid[-1] as tspan_end.
    t_grid = t_grid.at[-1].set(t1)
    return t_grid, omega_grid, omega(omega_grid)


def hetero_solution_from_omega(
    betas_local, dist_local, x0, t_grid, omega_grid, omega_vals
) -> LearningSolutionHetero:
    """Expand the (replicated) Ω table into per-group rows — closed form, so
    the local group axis can be a shard: no collective is needed, the global
    coupling lives entirely inside the table."""
    cdfs = 1.0 - (1.0 - x0) * jnp.exp(-betas_local[:, None] * omega_grid[None, :])
    pdfs = (1.0 - cdfs) * betas_local[:, None] * omega_vals[None, :]
    return LearningSolutionHetero(
        grid=t_grid,
        cdfs=cdfs,
        pdfs=pdfs,
        t0=t_grid[0],
        dt=t_grid[1] - t_grid[0],
        betas=betas_local,
        dist=dist_local,
    )


def solve_learning_hetero(
    params: LearningParamsHetero,
    config: SolverConfig | None = None,
    dtype=jnp.float64,
) -> LearningSolutionHetero:
    """Solve the K-group system.

    With ``config.grid_warp > 0`` (default): the exact Ω-reduction on a
    transition-warped grid — correct at any β_k, including the
    β_k ≳ n_grid/η regime where a uniform grid swallows a fast group's
    transition (VERDICT r4 task 4). With ``grid_warp == 0``: the legacy
    RK4 scan on a uniform grid (kept as a differential oracle for the
    exact path and for bit-exact sharding-equivalence tests).
    """
    if config is None:
        config = SolverConfig()
    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))
    betas = jnp.asarray(params.betas, dtype=dtype)
    dist = jnp.asarray(params.dist, dtype=dtype)
    if config.grid_warp > 0.0:
        t_grid, omega_grid, omega_vals = solve_learning_hetero_exact(params, config, dtype)
        return hetero_solution_from_omega(
            betas, dist, jnp.asarray(params.x0, dtype=dtype), t_grid, omega_grid, omega_vals
        )
    t0, t1 = params.tspan
    grid = jnp.linspace(t0, t1, config.n_grid, dtype=dtype)
    return solve_learning_hetero_arrays(
        betas, dist, params.x0, grid, hetero_substeps(params, config),
        adaptive_tols=(config.ode_rtol, config.ode_atol) if config.adaptive else None,
    )
