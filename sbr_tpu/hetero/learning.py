"""Stage 1 for K heterogeneous groups — coupled SI network ODE.

Reference: `solve_SInetwork_hetero` (`src/extensions/heterogeneity/
heterogeneity_learning.jl:49-94`):

    dG_k/dt = (1 - G_k) · β_k · ω(t),   ω(t) = Σ_j dist_j · G_j(t)

The reference integrates with an adaptive solver and wraps each group in its
own interpolation object; here the state is a (K,) array advanced by RK4 on a
static grid (`core.ode.rk4`), so the whole family is one `lax.scan` and the
ω reduction is a dot product — a `psum` when the group axis is sharded.
PDFs come from the symbolic rhs g_k = (1-G_k)·β_k·ω exactly like
`compute_pdf_hetero` (`heterogeneity_learning.jl:114-134`), with no O(K²·n)
double loop: all groups evaluate in one broadcast.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sbr_tpu.core.ode import rk4
from sbr_tpu.models.params import LearningParamsHetero, SolverConfig
from sbr_tpu.models.results import LearningSolutionHetero


def hetero_rhs(t, G, args):
    """Coupled SI rhs (`heterogeneity_learning.jl:57-67`). G: (K,)."""
    del t
    betas, dist = args
    omega = jnp.dot(dist, G)
    return (1.0 - G) * betas * omega


def solve_learning_hetero(
    params: LearningParamsHetero,
    config: SolverConfig = SolverConfig(),
    dtype=jnp.float64,
) -> LearningSolutionHetero:
    """Solve the coupled K-group system on a static uniform grid.

    Substeps are scaled so the max per-microstep β·h stays small even for the
    fast-group configs (reference example β_max=12.5, `scripts/
    2_heterogeneity.jl:38`); RK4 at that resolution sits far below the
    pipeline's downstream tolerances.
    """
    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))
    t0, t1 = params.tspan
    grid = jnp.linspace(t0, t1, config.n_grid, dtype=dtype)
    betas = jnp.asarray(params.betas, dtype=dtype)
    dist = jnp.asarray(params.dist, dtype=dtype)
    k = betas.shape[0]
    g0 = jnp.full((k,), params.x0, dtype=dtype)

    # Keep β_max · h ≲ 0.015 per microstep: RK4 global error ~(βh)^4 then sits
    # near 1e-8, inside the 1e-6 CPU-match envelope for the fast-group configs.
    h0 = (t1 - t0) / (config.n_grid - 1)
    beta_max = float(max(params.betas))
    substeps = max(config.ode_substeps, int(jnp.ceil(beta_max * h0 / 0.015)))

    cdfs = rk4(hetero_rhs, g0, grid, args=(betas, dist), substeps=substeps)  # (n, K)
    cdfs = jnp.clip(cdfs.T, 0.0, 1.0)  # (K, n)

    omega = jnp.einsum("k,kn->n", dist, cdfs)
    pdfs = (1.0 - cdfs) * betas[:, None] * omega[None, :]

    return LearningSolutionHetero(
        grid=grid,
        cdfs=cdfs,
        pdfs=pdfs,
        t0=jnp.asarray(t0, dtype=dtype),
        dt=grid[1] - grid[0],
        betas=betas,
        dist=dist,
    )
