"""Stages 2+3 for K heterogeneous groups (reference
`src/extensions/heterogeneity/heterogeneity_solver.jl`).

Structure vs the reference:

- Per-group hazard rates: the reference loops groups calling the baseline
  `hazard_rate` (`heterogeneity_solver.jl:255`); here all K rows compute in
  one broadcast cumulative trapezoid on the shared [0, η] grid.
- Per-group buffers: `vmap` of the baseline crossing detector over group rows
  (`heterogeneity_solver.jl:258-263`).
- ξ bisection on the dist-weighted AW (`compute_ξ_hetero`,
  `heterogeneity_solver.jl:48-144`): bracket [0, 2·max τ̄_OUT], start from the
  dist-weighted midpoint guess, fixed halvings; the AW evaluation reduces the
  group axis with a dot product (a psum under a sharded group axis).
- First-crossing validation (`is_valid_equilibrium_hetero`,
  `heterogeneity_solver.jl:175-210`): the reference's backward scan for a
  down-crossing of κ before ξ* becomes a masked boolean-transition reduction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from sbr_tpu.baseline.solver import _root_tol, classify_cell
from sbr_tpu.core.integrate import cumtrapz
from sbr_tpu.core.rootfind import bisect, chandrupatla, first_upcrossing, last_downcrossing
from sbr_tpu.models.params import EconomicParams, SolverConfig
from sbr_tpu.models.results import AWHetero, EquilibriumResultHetero, LearningSolutionHetero


def hazard_rates_hetero(p, lam, lsh: LearningSolutionHetero, eta, config: SolverConfig):
    """All K hazard rates on a shared static [0, η] grid.

    h_k(τ̄) = p·e^{λτ̄}·g_k(τ̄) / (p·∫₀^τ̄ e^{λs}g_k ds + (1-p)·∫₀^η e^{λs}g_k ds)

    (baseline formula applied per group, `heterogeneity_solver.jl:255` →
    `solver.jl:153-185`). Returns (tau_grid (n,), hrs (K, n)).
    """
    dtype = lsh.cdfs.dtype
    eta = jnp.asarray(eta, dtype=dtype)
    p = jnp.asarray(p, dtype=dtype)
    lam = jnp.asarray(lam, dtype=dtype)
    if config.grid_warp > 0.0:
        # Inherit the learning grid's transition-resolving knots (the
        # reference's grid-inheritance idea, `solver.jl:155-165`): three
        # quarters of the budget uniform on [0, η] for tail coverage, one
        # quarter strided from the warped learning grid clipped at η
        # (points past η collapse onto η — zero-width intervals contribute
        # nothing to the quadrature and the crossing detectors guard flat
        # segments). Without the inherited knots, a fast group's hazard
        # spike (width ~1/β_k) vanishes between uniform samples once
        # β_k ≳ n_grid/η, exactly the round-3 heatmap artifact class
        # (VERDICT r4 task 4); the quarter share is enough — quantile knots
        # cluster ~1/(β_k·n) locally — while a larger inherited share
        # measurably degrades smooth-config crossing interpolation (the
        # K-degeneracy oracle moves by 2e-5 at a half/half split).
        n = config.n_grid
        n_u = n - n // 4
        idx = jnp.linspace(0, lsh.grid.shape[0] - 1, n - n_u).astype(jnp.int32)
        inherit = jnp.clip(lsh.grid[idx], 0.0, eta)
        uniform = jnp.linspace(jnp.zeros((), dtype), eta, n_u)
        tau_grid = jnp.sort(jnp.concatenate([uniform, inherit]))
        tau_grid = tau_grid.at[0].set(0.0).at[-1].set(eta)
    else:
        tau_grid = jnp.linspace(jnp.zeros((), dtype), eta, config.n_grid)

    g = lsh.pdf_at(tau_grid)  # (K, n)
    eg = jnp.exp(lam * tau_grid)[None, :] * g
    integ = cumtrapz(eg, x=tau_grid)  # (K, n)
    int_eta = integ[:, -1:]
    hrs = (p * jnp.exp(lam * tau_grid)[None, :] * g) / (p * integ + (1.0 - p) * int_eta)
    return tau_grid, hrs


def _cdf_rows_at(lsh: LearningSolutionHetero, t):
    """G_k(t_k) for per-group times t (K,): row-wise interpolation."""
    return jax.vmap(lambda row, tk: jnp.interp(tk, lsh.grid, row))(lsh.cdfs, t)


def _wreduce(x, axis_name):
    """Complete a group-axis reduction across shards when the axis is sharded
    (SURVEY §5.8(b): the weighted AW sum is a psum under a sharded K)."""
    return lax.psum(x, axis_name) if axis_name is not None else x


def compute_xi_hetero(
    tau_bar_in_uncs,
    tau_bar_out_uncs,
    lsh: LearningSolutionHetero,
    kappa,
    config: SolverConfig | None = None,
    axis_name=None,
    with_health: bool = False,
):
    """Bisection for the weighted AW root (`compute_ξ_hetero`,
    `heterogeneity_solver.jl:48-144`).

    Returns (xi, err, root_ok, is_increasing, first_crossing_ok). With
    ``axis_name`` (sharded group axis), all shards run the identical
    bisection on psum-completed AW values, so ξ is replicated by
    construction. With ``with_health`` the bisection's `diag.Health` is
    appended — its extra endpoint/final evaluations run the same
    psum-completed AW on every shard, so the health scalars replicate too.
    """
    if config is None:
        config = SolverConfig()
    dtype = lsh.cdfs.dtype
    kappa = jnp.asarray(kappa, dtype=dtype)
    dist = lsh.dist

    def aw_of(xi):
        t_out = jnp.minimum(tau_bar_out_uncs, xi)
        t_in = jnp.minimum(tau_bar_in_uncs, xi)
        local = jnp.dot(dist, _cdf_rows_at(lsh, t_out) - _cdf_rows_at(lsh, t_in))
        return _wreduce(local, axis_name)

    # Reference bracket/guess: ξ∈[0, 2·max τ̄_OUT], ξ₀ = Σ dist·(τ̄_IN+τ̄_OUT)/2
    # (`heterogeneity_solver.jl:53-60`).
    lo = jnp.zeros((), dtype=dtype)
    hi = 2.0 * jnp.max(tau_bar_out_uncs)
    if axis_name is not None:
        hi = lax.pmax(hi, axis_name)
    x0 = _wreduce(jnp.dot(dist, 0.5 * (tau_bar_in_uncs + tau_bar_out_uncs)), axis_name)

    if config.adaptive:
        # Convergence-masked Chandrupatla (ISSUE 9); under a sharded group
        # axis every f evaluation is psum-completed, so all shards see the
        # same iterates/termination and ξ stays replicated by construction.
        out = chandrupatla(
            lambda x: aw_of(x) - kappa,
            lo,
            hi,
            budget=config.bisect_iters,
            x0=x0,
            with_health=with_health,
        )
    else:
        out = bisect(
            lambda x: aw_of(x) - kappa,
            lo,
            hi,
            num_iters=config.bisect_iters,
            x0=x0,
            with_health=with_health,
        )
    xi, xi_health = out if with_health else (out, None)

    aw = aw_of(xi)
    err = jnp.abs(aw - kappa)
    root_ok = err <= _root_tol(dtype)

    # Slope check with ε = LOCAL grid spacing at ξ (`heterogeneity_solver.jl:
    # 77-81` — the reference's adaptive grid is tight through transitions;
    # the warped learning grid reproduces that, and a fixed ε = dt would
    # overshoot a fast group's transition entirely at large β_k, reading
    # every genuine equilibrium as "decreasing" — the round-3 false-eq
    # artifact class).
    n_l = lsh.grid.shape[0]
    i_xi = jnp.clip(jnp.searchsorted(lsh.grid, xi, side="right") - 1, 0, n_l - 2)
    # floor: the warped grid's sorted union can contain duplicate knots, and
    # ε = 0 would make the slope test vacuously true
    eps = jnp.maximum(lsh.grid[i_xi + 1] - lsh.grid[i_xi], 1e-9 * (lsh.grid[-1] - lsh.grid[0]))
    t_out = jnp.minimum(tau_bar_out_uncs, xi)
    t_in = jnp.minimum(tau_bar_in_uncs, xi)
    aw_eps = _wreduce(
        jnp.dot(dist, _cdf_rows_at(lsh, t_out + eps) - _cdf_rows_at(lsh, t_in + eps)), axis_name
    )
    is_increasing = aw_eps >= aw

    first_ok = _first_crossing_ok(xi, tau_bar_in_uncs, lsh, kappa, axis_name=axis_name)
    if with_health:
        return xi, err, root_ok, is_increasing, first_ok, xi_health
    return xi, err, root_ok, is_increasing, first_ok


def _first_crossing_ok(
    xi_star, tau_bar_in_uncs, lsh: LearningSolutionHetero, kappa, axis_name=None
):
    """Reject roots that are not the FIRST up-crossing of κ
    (`is_valid_equilibrium_hetero`, `heterogeneity_solver.jl:175-210`).

    AW(t; ξ*) may be multimodal across groups; if the path dips back below κ
    anywhere before ξ*, an earlier crossing must exist and the root is a false
    equilibrium. The reference's backward scan becomes a masked reduction over
    boolean transitions on the learning grid.
    """
    t = lsh.grid  # all groups share the grid (`heterogeneity_solver.jl:178`)
    tau_i = jnp.maximum(0.0, xi_star - tau_bar_in_uncs)  # (K,) τ_I_k
    # AW_path(t) = Σ_k dist_k·(G_k(t) − G_k(max(0, t − τ_I_k)))
    shifted = jnp.maximum(0.0, t[None, :] - tau_i[:, None])  # (K, n)
    g_shift = _cdf_rows_at(lsh, shifted)
    aw_path = _wreduce(jnp.einsum("k,kn->n", lsh.dist, lsh.cdfs - g_shift), axis_name)

    in_range = t <= xi_star
    above = jnp.logical_and(aw_path > kappa, in_range)
    # Down-crossing entirely inside the masked range ⇒ invalid.
    down = jnp.logical_and(jnp.logical_and(above[:-1], ~above[1:]), in_range[1:])
    return ~jnp.any(down)


def solve_equilibrium_hetero(
    lsh: LearningSolutionHetero,
    econ: EconomicParams,
    config: SolverConfig | None = None,
    tspan_end=None,
    axis_name=None,
    hazard_transform=None,
    kappa_transform=None,
) -> EquilibriumResultHetero:
    """Full hetero equilibrium (`solve_equilibrium_hetero`,
    `heterogeneity_solver.jl:241-293`), branchless with status codes.

    With ``axis_name`` (group axis sharded under shard_map), per-group
    stages stay local and only the weighted reductions cross shards; the
    returned scalars are replicated, per-group arrays sharded.

    Stage-transformer hooks (ISSUE 14, same contract as
    `baseline.solver.solve_equilibrium_core`): ``hazard_transform(tau_grid,
    hrs, None) -> (hrs, _, extra_health)`` rewrites the (K, n) per-group
    hazard rows between the hazard stage and the buffer crossings (the
    hetero family has no continuous-hazard refinement, so the middle slot
    is unused); ``kappa_transform(kappa)`` rewrites the threshold before
    the weighted-AW bisection. Both default to None — the bit-identical
    legacy path.
    """
    if config is None:
        config = SolverConfig()
    import time

    from sbr_tpu import obs

    t_start = time.perf_counter()
    dtype = lsh.cdfs.dtype
    if tspan_end is None:
        tspan_end = lsh.grid[-1]
    u = jnp.asarray(econ.u, dtype=dtype)
    nan = jnp.asarray(jnp.nan, dtype=dtype)

    # Host-boundary spans: no-ops under shard_map/jit (trace guard), so the
    # sharded group-axis path is untouched; the eager path gets the
    # per-stage wall split with honest fences.
    with obs.span("hetero.hazards", groups=int(lsh.cdfs.shape[0])) as sp:
        tau_grid, hrs = hazard_rates_hetero(econ.p, econ.lam, lsh, econ.eta, config)
        sp.sync(hrs)

    extra_health = ()
    if hazard_transform is not None:
        hrs, _, extra_health = hazard_transform(tau_grid, hrs, None)
    kappa_eff = econ.kappa if kappa_transform is None else kappa_transform(econ.kappa)

    with obs.span("hetero.buffers") as sp:
        default = jnp.asarray(tspan_end, dtype=dtype)
        tau_in_uncs, h_in = jax.vmap(
            lambda hr: first_upcrossing(tau_grid, hr, u, default, with_health=True)
        )(hrs)
        tau_out_uncs, h_out = jax.vmap(
            lambda hr: last_downcrossing(tau_grid, hr, u, default, with_health=True)
        )(hrs)
        sp.sync(tau_in_uncs, tau_out_uncs)

    # No group can optimally exit (`heterogeneity_solver.jl:266-272`); the
    # ALL-groups condition completes across shards as a summed crossing count.
    n_crossing = _wreduce(jnp.sum(tau_in_uncs != tau_out_uncs), axis_name)
    no_crossing = n_crossing == 0

    with obs.span("hetero.xi") as sp:
        xi_c, err, root_ok, increasing, first_ok, xi_health = compute_xi_hetero(
            tau_in_uncs, tau_out_uncs, lsh, kappa_eff, config,
            axis_name=axis_name, with_health=True,
        )
        sp.sync(xi_c)

    # Per-group crossing flags fold into one scalar mask via SUM-shaped
    # reductions only (diag.or_reduce_flags), so the same code completes
    # across shards when the group axis is sharded — OR has no collective,
    # per-bit presence counts psum like any other group reduction.
    from sbr_tpu.diag.health import as_out_crossing, or_reduce_flags

    group_flags = h_in.flags | as_out_crossing(h_out).flags  # (K_local,)
    cross_flags = or_reduce_flags(group_flags, lambda s: _wreduce(s, axis_name))
    # Adaptive coupled-K ODE flags (ISSUE 9): ODE_BUDGET from a bs32
    # interval that exhausted its step cap — None on the fixed-RK4 and
    # sharded paths, so their health bytes are untouched.
    ode_flags = getattr(lsh, "ode_flags", None)
    if ode_flags is not None:
        cross_flags = cross_flags | ode_flags
    health = xi_health.replace(flags=xi_health.flags | cross_flags)
    if extra_health:
        health = health.merge(*extra_health)

    run, status, converged, tolerance = classify_cell(
        no_crossing, root_ok, increasing, err, dtype, first_ok=first_ok
    )
    xi = jnp.where(run, xi_c, nan)

    from sbr_tpu.baseline.solver import _stamp_solve_time

    res = _stamp_solve_time(
        EquilibriumResultHetero(
            xi=xi,
            tau_bar_in_uncs=tau_in_uncs,
            tau_bar_out_uncs=tau_out_uncs,
            hrs=hrs,
            tau_grid=tau_grid,
            bankrun=run,
            status=status,
            converged=converged,
            tolerance=tolerance,
            health=health,
        ),
        t_start,
    )
    obs.log_health("hetero.equilibrium", res.health, res.status)
    return res


def get_aw_hetero(
    result: EquilibriumResultHetero, lsh: LearningSolutionHetero, axis_name=None
) -> AWHetero:
    """Group-decomposed AW curves on the learning grid (`get_AW_hetero`,
    `heterogeneity_solver.jl:316-375`).

    AW_k(t) = G_k(max(0, t−ξ+τ̄_OUT_k^CON)) − G_k(max(0, t−ξ+τ̄_IN_k^CON)),
    each branch zeroed before its own start; total is the dist-weighted sum.
    NaN ξ (no run) propagates NaN curves, mirroring the reference returning
    `nothing` (`heterogeneity_solver.jl:317-319`).
    """
    t = lsh.grid
    xi = result.xi
    nan_lane = jnp.isnan(xi)
    tau_in_con = jnp.minimum(result.tau_bar_in_uncs, xi)  # (K,)
    tau_out_con = jnp.minimum(result.tau_bar_out_uncs, xi)

    def branch(tau_con):
        shift = t[None, :] - xi + tau_con[:, None]  # (K, n)
        vals = _cdf_rows_at(lsh, jnp.maximum(shift, 0.0))
        # shift>=0 is False for NaN, which would silently zero no-run lanes;
        # re-inject NaN so no-run propagates as the sentinel, not as "zero
        # withdrawals".
        return jnp.where(nan_lane, jnp.nan, jnp.where(shift >= 0, vals, 0.0))

    aw_in_groups = branch(tau_in_con)
    aw_out_groups = branch(tau_out_con)
    aw_groups = aw_out_groups - aw_in_groups
    aw_cum = _wreduce(jnp.einsum("k,kn->n", lsh.dist, aw_groups), axis_name)
    return AWHetero(
        t_grid=t,
        aw_cum=aw_cum,
        aw_out_groups=aw_out_groups,
        aw_in_groups=aw_in_groups,
        aw_groups=aw_groups,
        aw_max=jnp.max(aw_cum),
    )
