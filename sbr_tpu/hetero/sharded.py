"""K-axis sharded heterogeneity pipeline (SURVEY §5.8, §7.2 step 4).

The reference's heterogeneity extension is a sequential K-group loop on one
CPU (`heterogeneity_solver.jl:255-263`); its only cross-group couplings are
the ω mixing field in the learning ODE (`heterogeneity_learning.jl:61`) and
the dist-weighted AW sum in bisection (`heterogeneity_solver.jl:87-97`).
Here the group axis shards over a 1-D device mesh via `shard_map`: every
per-group stage (ODE rows, hazards, buffer crossings, AW decomposition)
stays device-local, and exactly those two couplings — plus the bracket max
and the all-groups no-crossing test — cross shards as psum/pmax collectives
riding ICI. ξ and the status scalars come out replicated on every device;
the K=1000 parity config (BASELINE.md) runs at 125 groups/device on a
v4-8's 8 chips.

Equivalence with the single-device path is exact up to psum reduction
order (tested to 1e-9 at K=1000 on the 8-virtual-device CPU mesh).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sbr_tpu.hetero.learning import (
    hetero_solution_from_omega,
    hetero_substeps,
    solve_learning_hetero_arrays,
    solve_learning_hetero_exact,
)
from sbr_tpu.diag.health import Health
from sbr_tpu.hetero.solver import get_aw_hetero, solve_equilibrium_hetero
from sbr_tpu.models.params import ModelParamsHetero, SolverConfig
from sbr_tpu.models.results import AWHetero, EquilibriumResultHetero, LearningSolutionHetero


def solve_hetero_sharded(
    params: ModelParamsHetero,
    mesh: Mesh,
    config: SolverConfig | None = None,
    axis: str = "k",
    dtype=jnp.float64,
    with_aw: bool = True,
) -> Tuple[LearningSolutionHetero, EquilibriumResultHetero, Optional[AWHetero]]:
    """Full hetero solve with the group axis sharded over ``mesh[axis]``.

    Returns (learning, equilibrium, aw) with the same structure as the
    single-device `solve_learning_hetero` → `solve_equilibrium_hetero` →
    `get_aw_hetero` pipeline; per-group arrays come back sharded over the
    mesh, scalars and shared-grid curves replicated. K must be divisible by
    the mesh axis size (the K=1000 / 8-device parity config is).
    """
    if config is None:
        config = SolverConfig()
    k = params.learning.n_groups
    n_dev = mesh.shape[axis]
    if k % n_dev:
        raise ValueError(
            f"K = {k} groups must divide evenly over the {n_dev}-device mesh "
            f"axis {axis!r}; pad the group list or choose a compatible mesh."
        )
    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))
    t0, t1 = params.learning.tspan
    x0 = params.learning.x0
    substeps = hetero_substeps(params.learning, config)
    econ = params.economic

    exact = config.grid_warp > 0.0
    if exact:
        # Exact Ω path (round 5): the scalar t(Ω) table carries ALL the
        # cross-group coupling and is computed once outside shard_map with
        # the full betas (a replicated input); each shard then expands its
        # LOCAL group rows in closed form — the learning stage needs no
        # collective at all. The ω psum of the RK4 path disappears.
        tables = solve_learning_hetero_exact(params.learning, config, dtype)
        x0_c = jnp.asarray(x0, dtype=dtype)

    def fn(betas_l, dist_l, *tabs):
        # Trace-time retrace accounting (obs.prof). NOTE the enclosing jit
        # is rebuilt per call (closure over params/mesh), so every
        # solve_hetero_sharded call re-traces — the counter makes that cost
        # visible in the run manifest, but with an unbounded budget: the
        # per-call retrace is this entry point's known shape, not churn, so
        # it must not trip the over-budget warning on healthy runs.
        from sbr_tpu.obs import prof

        prof.note_trace("hetero.sharded", budget=1 << 30)
        if exact:
            lsh = hetero_solution_from_omega(betas_l, dist_l, x0_c, *tabs)
        else:
            grid = jnp.linspace(
                jnp.asarray(t0, dtype=dtype), jnp.asarray(t1, dtype=dtype), config.n_grid
            )
            lsh = solve_learning_hetero_arrays(
                betas_l, dist_l, x0, grid, substeps, axis_name=axis
            )
        res = solve_equilibrium_hetero(lsh, econ, config, axis_name=axis)
        aw = get_aw_hetero(res, lsh, axis_name=axis) if with_aw else None
        return lsh, res, aw

    spec_lsh = LearningSolutionHetero(
        grid=P(), cdfs=P(axis), pdfs=P(axis), t0=P(), dt=P(), betas=P(axis), dist=P(axis)
    )
    spec_res = EquilibriumResultHetero(
        xi=P(),
        tau_bar_in_uncs=P(axis),
        tau_bar_out_uncs=P(axis),
        hrs=P(axis),
        tau_grid=P(),
        bankrun=P(),
        status=P(),
        converged=P(),
        tolerance=P(),
        solve_time=P(),  # replicated scalar leaf (0.0 inside the traced body)
        # health scalars are replicated by construction: the ξ bisection
        # health is computed from psum-completed AW on every shard, and the
        # per-group crossing flags fold across shards via summed presence
        # counts (diag.or_reduce_flags) before entering the mask
        health=Health(residual=P(), bracket_width=P(), iterations=P(), flags=P()),
    )
    spec_aw = (
        AWHetero(
            t_grid=P(),
            aw_cum=P(),
            aw_out_groups=P(axis),
            aw_in_groups=P(axis),
            aw_groups=P(axis),
            aw_max=P(),
        )
        if with_aw
        else None
    )

    shard = NamedSharding(mesh, P(axis))
    betas = jax.device_put(jnp.asarray(params.learning.betas, dtype=dtype), shard)
    dist = jax.device_put(jnp.asarray(params.learning.dist, dtype=dtype), shard)

    table_args = tables if exact else ()
    from sbr_tpu.parallel.compat import shard_map

    fn_sharded = jax.jit(
        shard_map(
            fn,
            mesh=mesh,
            in_specs=(P(axis), P(axis)) + (P(),) * len(table_args),
            out_specs=(spec_lsh, spec_res, spec_aw),
        )
    )
    return fn_sharded(betas, dist, *table_args)
