"""Mesh construction and sharding helpers.

The reference has no parallelism of any kind (SURVEY §2: sweeps are
sequential loops, `scripts/1_baseline.jl:151,224`); this package is the
TPU-native subsystem that fills that absence — device meshes for the
embarrassingly-parallel sweep axes and the sharded agent/edge axis of the
social-learning simulation (`jax.sharding` + shard_map; collectives ride
ICI).
"""

from sbr_tpu.parallel.compat import pcast, shard_map
from sbr_tpu.parallel.distributed import (
    initialize_distributed,
    run_tiled_grid_multihost,
    tile_assignment,
)
from sbr_tpu.parallel.mesh import (
    balanced_2d,
    make_agent_mesh,
    make_grid_mesh,
    shard_axis_values,
)

__all__ = [
    "balanced_2d",
    "make_agent_mesh",
    "make_grid_mesh",
    "pcast",
    "shard_axis_values",
    "shard_map",
    "initialize_distributed",
    "run_tiled_grid_multihost",
    "tile_assignment",
]
