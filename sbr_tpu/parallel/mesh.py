"""Device-mesh construction for the framework's two parallel programs.

- 2-D (b, u) meshes for comparative-statics grids (`sweeps.beta_u_grid`):
  cells are independent, so the grid shards with no collectives and scales
  linearly across chips.
- 1-D agent meshes for the explicit-agent simulation (`social.agents`):
  agents/edges shard over one axis with psum/all_gather inside shard_map.

Nothing here is TPU-specific: the same meshes build over the virtual-CPU
platform for tests (tests/conftest.py) and the driver's multi-chip dry run.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def balanced_2d(n: int) -> Tuple[int, int]:
    """Most-square factorization (a, b) of n with a ≤ b.

    Used to fold a flat device list into a (b, u) grid mesh so both sweep
    axes scale; degenerates to (1, n) for primes.
    """
    for a in range(int(math.isqrt(n)), 0, -1):
        if n % a == 0:
            return a, n // a
    return 1, n


def _devices(devices=None):
    if devices is None:
        import jax

        devices = jax.devices()
    return list(devices)


def make_grid_mesh(
    devices: Optional[Sequence] = None,
    axis_names: Tuple[str, str] = ("b", "u"),
    shape: Optional[Tuple[int, int]] = None,
) -> Mesh:
    """2-D mesh over all (or the given) devices for β×u grid sweeps."""
    devices = _devices(devices)
    if shape is None:
        shape = balanced_2d(len(devices))
    if shape[0] * shape[1] != len(devices):
        raise ValueError(f"Mesh shape {shape} does not use {len(devices)} devices")
    return Mesh(np.asarray(devices).reshape(shape), axis_names)


def make_agent_mesh(devices: Optional[Sequence] = None, axis_name: str = "agents") -> Mesh:
    """1-D mesh over all (or the given) devices for agent/edge sharding."""
    devices = _devices(devices)
    return Mesh(np.asarray(devices), (axis_name,))


def shard_axis_values(mesh: Mesh, mesh_axes: Sequence[str], *value_arrays):
    """Place each 1-D parameter-value array on its own mesh axis
    (`NamedSharding(mesh, P(axis))`) — the input-side idiom shared by the
    mesh-sharded sweeps (`sweeps.beta_u_grid`, `sweeps.policy_sweep_interest`).
    Each mesh axis size must divide the matching array length."""
    import jax

    return tuple(
        jax.device_put(v, NamedSharding(mesh, PartitionSpec(ax)))
        for ax, v in zip(mesh_axes, value_arrays, strict=True)
    )
