"""Reusable mesh collectives beyond the lax builtins.

First resident: ``exclusive_psum`` — the exclusive prefix sum over a mesh
axis that distributed stable counting sort needs (each device must know
how many same-key items EARLIER devices hold). lax has ``psum`` (inclusive
of everyone) but no exclusive scan; this one is n_dev−1 ``ppermute``
rotations with an axis-index mask, so peak memory stays O(len(x)) instead
of the all_gather form's O(n_dev · len(x)) — at the 10^7-agent histogram
length that is the difference between 40 MB and 40·n_dev MB per device.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def exclusive_psum(x, axis: str, n_dev: int):
    """Exclusive prefix sum of ``x`` over mesh axis ``axis``: device d
    receives Σ_{d' < d} x_{d'} (zeros on device 0).

    Must be called inside shard_map with ``n_dev`` equal to the axis size
    (static — the rotation schedule unrolls). Deterministic: the sum is
    accumulated in device order, so integer inputs are exact and float
    inputs are reproducible across runs of the same mesh.
    """
    perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
    excl = jnp.zeros_like(x)
    cur = x
    for i in range(n_dev - 1):
        # after i+1 rotations device d holds x from device d-(i+1) (mod
        # n_dev); accumulate only genuine predecessors (no wraparound)
        cur = lax.ppermute(cur, axis, perm)
        mask = lax.axis_index(axis) > i
        excl = excl + jnp.where(mask, cur, jnp.zeros_like(cur))
    return excl
