"""Sharding API compatibility across jax versions.

The sharded code paths are written against the modern surface —
``jax.shard_map`` plus ``lax.pcast`` (the sharding-in-types system's
replicated→varying cast). Older jax (e.g. 0.4.x, the CPU CI image) has
shard_map only under ``jax.experimental.shard_map`` and no varying-type
system at all. These two shims bridge the gap:

- ``shard_map``: delegates to ``jax.shard_map`` when present; otherwise the
  experimental one with ``check_rep=False`` — without pcast there is no way
  to annotate replicated inputs as varying, and the replication checker is
  exactly the machinery pcast exists to satisfy. The per-device program is
  identical either way (equivalence vs the unsharded path is asserted by
  the sharded-vs-single-device test suites).
- ``pcast``: delegates to ``lax.pcast`` when present; otherwise identity —
  the cast has no runtime effect, it only adjusts the type system's
  replication bookkeeping, which the old API does not track.

Import from here, never from jax directly, in any sharded module.
"""

from __future__ import annotations

import jax
from jax import lax

_HAS_CAST = hasattr(lax, "pcast") or hasattr(lax, "pvary")

if hasattr(jax, "shard_map") and _HAS_CAST:
    shard_map = jax.shard_map
elif hasattr(jax, "shard_map"):
    # Modern shard_map but no varying cast at all: the manual-axes check
    # cannot be satisfied, so it must be disabled (kwarg name varies).
    def shard_map(f, *, mesh, in_specs, out_specs):
        for kw in ({"check_vma": False}, {"check_rep": False}, {}):
            try:
                return jax.shard_map(
                    f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
                )
            except TypeError:
                continue
        raise TypeError("no compatible jax.shard_map signature found")

else:
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs):
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


if hasattr(lax, "pcast"):
    pcast = lax.pcast
elif hasattr(lax, "pvary"):
    # The 0.6/0.7-era spelling of the replicated→varying cast.
    def pcast(x, axes, to="varying"):
        if to != "varying":
            raise NotImplementedError(f"pcast shim only supports to='varying', got {to!r}")
        return lax.pvary(x, axes)

else:

    def pcast(x, axes, to="varying"):
        del axes, to
        return x


__all__ = ["pcast", "shard_map"]
