"""Multi-host layer: `jax.distributed` bring-up and DCN sweep farming
(SURVEY §5.8 — the subsystem the reference lacks entirely; its sweeps are
sequential single-process loops, `scripts/1_baseline.jl:151,224`).

Two multi-host regimes, matching the framework's two parallel programs:

1. **One sharded program spanning hosts** (ICI/DCN collectives): call
   `initialize_distributed()` first, then build meshes over
   `jax.devices()` as usual — `parallel.mesh` helpers, the sharded agent
   sim, and the K-sharded hetero pipeline all work unchanged, since they
   address devices through named mesh axes and XLA routes collectives over
   ICI within a pod and DCN across pods.

2. **Embarrassingly-parallel sweep farming** (no collectives): β×u grid
   cells are independent, so hosts need not share a mesh at all —
   `run_tiled_grid_multihost` splits the tile list across processes and
   uses the shared checkpoint directory (`utils.checkpoint`) as the
   rendezvous: every finished tile is an atomically-renamed npz, any
   process can adopt any tile from disk, and the assembly pass is a pure
   cache read. A lost host costs only its unfinished tiles, which the
   survivors pick up — the failure-detection analogue of SURVEY §5.3 at
   the cross-host level.

Elastic scheduling (ISSUE 8, `sbr_tpu.resilience.elastic`): by default
(``SBR_ELASTIC`` unset/1) the sweep farm no longer freezes ownership at
launch at all — hosts announce heartbeats in the checkpoint dir, claim
tiles from the remaining queue under a deterministic throughput-weighted
plan with per-tile lease arbitration, and read/write a cross-run global
tile cache (``SBR_TILE_CACHE_DIR``). Hosts can join a live sweep and leave
it (gracefully or by dying) at any point; the assembled grid is
byte-identical regardless. ``SBR_ELASTIC=0`` (or ``elastic=False``)
selects the legacy static split + work stealing described below.

Work stealing (`sbr_tpu.resilience`): the filesystem barrier no longer
just times out on a dead peer. After ``steal_grace_s`` (env
``SBR_STEAL_GRACE_S``, default 300 s) with **no new tile landing** — the
grace clock resets on every drop in the missing count, so healthy slow
peers are never stolen from — a waiting process claims per-tile **lease
files** (atomic ``O_EXCL`` create, JSON ``{pid, host, ts, ttl_s}``) for
the stalled batch and computes the orphaned tiles itself in one pass; an
expired lease (holder died mid-steal, TTL ``SBR_STEAL_LEASE_TTL_S``,
default 900 s) is taken over. Two survivors racing an expired lease both
compute the tile — benign by construction, since tile writes are atomic
and deterministic for the same sweep. Every adoption is an obs ``repair``
event (action ``"adopt"``), so `report resilience` shows which host
picked up whose work.
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Optional

from sbr_tpu.models.params import ModelParams, SolverConfig
from sbr_tpu.resilience import faults


def _flight_recorder():
    """Process-wide flight recorder when ``SBR_FLIGHT`` is on, else None
    (env check before import — the structural-no-op contract)."""
    if os.environ.get("SBR_FLIGHT", "").strip() in ("", "0"):
        return None
    try:
        from sbr_tpu.obs import flight

        return flight.shared()
    except Exception:
        return None


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> bool:
    """Bring up `jax.distributed` for multi-host runs; single-process no-op.

    Returns True when distributed mode was initialized. With no arguments,
    initialization happens only if the standard coordination env vars are
    present (JAX_COORDINATOR_ADDRESS / cloud-TPU metadata), so single-host
    callers can invoke this unconditionally.
    """
    explicit = coordinator_address is not None or num_processes is not None
    env = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if not explicit and not env:
        return False

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    return True


def tile_assignment(n_tiles: int, n_processes: int, process_id: int) -> range:
    """Contiguous balanced split: process p owns tiles [start_p, end_p).

    Every tile is owned by exactly one process; sizes differ by at most 1.
    """
    if not 0 <= process_id < n_processes:
        raise ValueError(f"process_id {process_id} not in [0, {n_processes})")
    base, rem = divmod(n_tiles, n_processes)
    start = process_id * base + min(process_id, rem)
    return range(start, start + base + (1 if process_id < rem else 0))


def _log_adopt(tile_id: str, ok: bool) -> None:
    """Work-stealing adoption as an obs ``repair`` event (action "adopt")."""
    try:
        from sbr_tpu import obs

        obs.log_repair(action="adopt", target=tile_id, ok=ok)
    except Exception:
        pass  # telemetry must never sink the barrier


def _cleanup_leases(ckpt) -> None:
    """Drop leases for tiles that now exist (a completed steal, or a slow
    peer that finished after all): leases are scaffolding, not results."""
    for lease in ckpt.glob("tile_*.lease"):
        if lease.with_suffix(".npz").exists():
            try:
                lease.unlink()
            except OSError:
                pass


def _try_lease(ckpt, bi: int, ui: int, ttl_s: float) -> bool:
    """Claim the steal-lease for tile (bi, ui): atomic O_EXCL create, or
    take over a lease whose holder's TTL has lapsed. False = a live lease
    is held by another surviving process (let it work).

    The takeover path writes a per-claim NONCE and re-reads the lease
    after its ``os.replace``: two survivors racing the same expired lease
    both replace, but only the one whose nonce survives proceeds — the
    loser backs off instead of double-computing (ISSUE 8 satellite; the
    residual replace-after-read window is benign as before: tile writes
    are atomic and deterministic)."""
    import uuid

    lease = ckpt / f"tile_b{bi:05d}_u{ui:05d}.lease"
    nonce = uuid.uuid4().hex
    record = json.dumps(
        {
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "nonce": nonce,
            "ts": time.time(),
            "ttl_s": ttl_s,
        }
    )
    try:
        fd = os.open(lease, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        try:
            held = json.loads(lease.read_text())
            # Honor the TTL the HOLDER wrote (it sized the lease to its own
            # batch), falling back to ours for pre-TTL-field leases. Strict
            # `<`: a lease EXACTLY at its TTL boundary is expired.
            if time.time() - float(held.get("ts", 0.0)) < float(held.get("ttl_s", ttl_s)):
                return False
        except (OSError, ValueError):
            pass  # unreadable lease = a torn write from a dead holder
        tmp = ckpt / f"{lease.name}.{os.getpid()}.tmp"
        try:
            tmp.write_text(record)
            os.replace(tmp, lease)
            # Verify WE won: re-read and check our nonce survived. A racer
            # replacing after us makes its nonce the survivor; we yield.
            now_held = json.loads(lease.read_text())
        except (OSError, ValueError):
            return False
        return now_held.get("nonce") == nonce
    else:
        with os.fdopen(fd, "w") as f:
            f.write(record)
        return True


def run_tiled_grid_multihost(
    beta_values,
    u_values,
    base: ModelParams,
    checkpoint_dir: str,
    config: Optional[SolverConfig] = None,
    tile_shape=(256, 256),
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
    wait: bool = True,
    poll_s: float = 5.0,
    timeout_s: float = 24 * 3600.0,
    dtype=None,
    verbose: bool = False,
    work_steal: bool = True,
    steal_grace_s: Optional[float] = None,
    lease_ttl_s: Optional[float] = None,
    elastic: Optional[bool] = None,
    heartbeat_ttl_s: Optional[float] = None,
    tile_cache_dir=None,
):
    """Farm a β×u grid across processes via the shared checkpoint dir.

    **Elastic mode (the default — ISSUE 8).** Ownership is not a
    launch-time split at all: every host runs the elastic scheduler
    (`resilience.elastic.run_elastic_grid`) against the shared dir —
    heartbeat membership, a deterministic throughput-weighted claim plan
    over the remaining tile queue, per-tile lease arbitration, and the
    cross-run global tile cache (``tile_cache_dir`` /
    ``SBR_TILE_CACHE_DIR``). Hosts may join a running sweep at any time
    and adopt unowned/expired tiles immediately; a host that leaves
    (SIGTERM, or silence past the TTLs) has its tiles reclaimed without
    waiting for the end-of-sweep barrier. ``process_id``/``num_processes``
    are accepted but unused for ownership (any number of peers, fixed
    nowhere). Opt out per call (``elastic=False``), process-wide
    (``SBR_ELASTIC=0``), or by passing ``work_steal=False`` — whose
    own-share-only / dead-peer-times-out contract the always-adopting
    elastic scheduler cannot honor — to get the legacy static split below.

    **Legacy static split (``elastic=False``).** Each process computes only
    its `tile_assignment` share (plus anything already on disk);
    coordination is purely filesystem-level, so this works across hosts
    that share nothing but storage — no collectives, no jax.distributed
    requirement (use it when a mesh-spanning program is also running; not
    needed here).

    With ``wait`` (default), after finishing its share the process polls
    until every tile exists, then assembles and returns the full grid.
    With ``wait=False`` it returns None right after its own share — the
    pattern for worker processes whose results are consumed elsewhere.

    With ``work_steal`` (default), a process that has seen no barrier
    progress (no drop in the missing-tile count) for ``steal_grace_s``
    (env ``SBR_STEAL_GRACE_S``, default 300 s) adopts the stalled tiles
    under per-tile lease files (TTL ``lease_ttl_s`` /
    ``SBR_STEAL_LEASE_TTL_S``, default 900 s) instead of timing out — see
    the module docstring. ``timeout_s`` still bounds the whole barrier as
    the last line of defense.

    ``tile_shape="auto"`` resolves here, before the ownership split, via
    the obs.mem capacity planner (see `utils.checkpoint.run_tiled_grid`):
    the planner is deterministic in (grid, capacity, headroom), so
    same-capacity peers independently agree on the tile grid — and the
    checkpoint fingerprint, which hashes the resolved shape, fails loudly
    if a heterogeneous pool somehow disagrees. The ORIGINAL ``tile_shape``
    is what gets passed down to the `run_tiled_grid` calls (own share,
    steals, assembly): re-resolving "auto" there is free (probe footprints
    are cached) and hands each call its own plan record, which the OOM
    preflight consumes instead of paying a full worst-case-tile compile.
    """
    from sbr_tpu.resilience import elastic as elastic_mod
    from sbr_tpu.utils.checkpoint import (
        _tile_path,
        resolve_tile_shape,
        run_tiled_grid,
        tile_origins,
    )

    # work_steal=False promises "this process computes ONLY its own share
    # and a dead peer surfaces as TimeoutError" — a contract the elastic
    # scheduler (which always adopts unleased tiles) cannot honor, so that
    # flag selects the legacy static split even when elastic is on.
    if elastic_mod.elastic_enabled(elastic) and work_steal:
        return elastic_mod.run_elastic_grid(
            beta_values, u_values, base, checkpoint_dir, config=config,
            tile_shape=tile_shape, dtype=dtype, wait=wait, poll_s=poll_s,
            timeout_s=timeout_s, verbose=verbose, lease_ttl_s=lease_ttl_s,
            heartbeat_ttl_s=heartbeat_ttl_s, tile_cache_dir=tile_cache_dir,
        )

    if process_id is None or num_processes is None:
        import jax

        process_id = jax.process_index() if process_id is None else process_id
        num_processes = jax.process_count() if num_processes is None else num_processes

    import numpy as np

    nb, nu = len(np.asarray(beta_values)), len(np.asarray(u_values))
    # Resolve for OUR view of the tile grid (ownership split + barrier);
    # the original tile_shape still flows to run_tiled_grid (see docstring).
    resolved_shape, _plan = resolve_tile_shape(nb, nu, tile_shape, config, dtype)
    tiles = tile_origins(nb, nu, resolved_shape)
    owned = {tiles[i] for i in tile_assignment(len(tiles), num_processes, process_id)}

    run_tiled_grid(
        beta_values,
        u_values,
        base,
        config=config,
        tile_shape=tile_shape,
        checkpoint_dir=checkpoint_dir,
        dtype=dtype,
        verbose=verbose,
        tile_owner=lambda bi, ui: (bi, ui) in owned,
    )
    if not wait:
        return None

    # Filesystem barrier: every tile must exist before assembly. After the
    # steal grace period, missing tiles are adopted under leases instead of
    # waited on forever (a dead peer's share must not require a human). The
    # whole barrier runs under the graceful-shutdown envelope so a SIGTERM
    # during a poll sleep still releases any held leases (the registry in
    # `resilience.shutdown`) instead of stranding them until TTL.
    from pathlib import Path

    from sbr_tpu.resilience import shutdown

    if steal_grace_s is None:
        steal_grace_s = float(os.environ.get("SBR_STEAL_GRACE_S", "300"))
    if lease_ttl_s is None:
        lease_ttl_s = float(os.environ.get("SBR_STEAL_LEASE_TTL_S", "900"))

    ckpt = Path(checkpoint_dir)
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    # Stall detection: the grace clock measures time since the missing-tile
    # count last DROPPED, not since the barrier started — a healthy-but-slow
    # peer that keeps landing tiles is never stolen from; only a genuinely
    # stalled remainder triggers adoption.
    last_progress = t0
    n_missing_prev: Optional[int] = None
    with shutdown.graceful_shutdown(label="multihost_barrier"):
        while True:
            missing = [t for t in tiles if not _tile_path(ckpt, *t).exists()]
            if not missing:
                break
            if n_missing_prev is not None and len(missing) < n_missing_prev:
                last_progress = time.monotonic()
            n_missing_prev = len(missing)
            faults.fire("barrier.poll", target=f"missing={len(missing)}")
            if work_steal and time.monotonic() - last_progress >= steal_grace_s:
                # Lease the whole stalled batch first, then compute it in ONE
                # resilient pass (retry policy, verify-on-load, degrade ladder):
                # cached tiles are read exactly once, not once per adoption.
                leased = []
                for bi, ui in missing:
                    if _tile_path(ckpt, bi, ui).exists():
                        continue
                    if _try_lease(ckpt, bi, ui, lease_ttl_s):
                        # Register IMMEDIATELY: a SIGTERM between acquiring
                        # this lease and finishing the batch must still
                        # release it, not strand it until the TTL.
                        shutdown.release_on_exit(ckpt / f"tile_b{bi:05d}_u{ui:05d}.lease")
                        leased.append((bi, ui))
                if leased:
                    leased_set = set(leased)
                    if verbose:
                        print(f"  adopting {len(leased)} orphaned tile(s): {leased} …")
                    try:
                        run_tiled_grid(
                            beta_values, u_values, base, config=config,
                            tile_shape=tile_shape, checkpoint_dir=checkpoint_dir,
                            dtype=dtype, verbose=False,
                            tile_owner=lambda b, u: (b, u) in leased_set,
                        )
                        for bi, ui in leased:
                            _log_adopt(f"tile_b{bi:05d}_u{ui:05d}", ok=True)
                    finally:
                        for bi, ui in leased:
                            lease = ckpt / f"tile_b{bi:05d}_u{ui:05d}.lease"
                            try:
                                lease.unlink()
                            except OSError:
                                pass
                            shutdown.unregister_release(lease)
                    last_progress = time.monotonic()  # we made the progress
                    continue  # re-check the barrier immediately
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{len(missing)} tiles still missing after {timeout_s:.0f}s "
                    f"(first: {missing[0]}); a peer process likely died and its "
                    "tiles could not be adopted (work stealing "
                    f"{'on' if work_steal else 'off'}) — rerun with its "
                    "process_id (or a smaller num_processes) to adopt its tiles."
                )
            if verbose:
                print(f"  waiting on {len(missing)} peer tiles …")
            _fl = _flight_recorder()
            _t0 = time.monotonic()
            time.sleep(poll_s)
            if _fl is not None:
                _fl.mark("collectives", "barrier_poll", _t0, time.monotonic(),
                         tag=f"missing={len(missing)}")

    # Assembly: all tiles cached on disk — a pure read, no recompute.
    _cleanup_leases(ckpt)
    return run_tiled_grid(
        beta_values,
        u_values,
        base,
        config=config,
        tile_shape=tile_shape,
        checkpoint_dir=checkpoint_dir,
        dtype=dtype,
        verbose=verbose,
    )
