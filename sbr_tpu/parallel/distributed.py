"""Multi-host layer: `jax.distributed` bring-up and DCN sweep farming
(SURVEY §5.8 — the subsystem the reference lacks entirely; its sweeps are
sequential single-process loops, `scripts/1_baseline.jl:151,224`).

Two multi-host regimes, matching the framework's two parallel programs:

1. **One sharded program spanning hosts** (ICI/DCN collectives): call
   `initialize_distributed()` first, then build meshes over
   `jax.devices()` as usual — `parallel.mesh` helpers, the sharded agent
   sim, and the K-sharded hetero pipeline all work unchanged, since they
   address devices through named mesh axes and XLA routes collectives over
   ICI within a pod and DCN across pods.

2. **Embarrassingly-parallel sweep farming** (no collectives): β×u grid
   cells are independent, so hosts need not share a mesh at all —
   `run_tiled_grid_multihost` splits the tile list across processes and
   uses the shared checkpoint directory (`utils.checkpoint`) as the
   rendezvous: every finished tile is an atomically-renamed npz, any
   process can adopt any tile from disk, and the assembly pass is a pure
   cache read. A lost host costs only its unfinished tiles, which the
   survivors (or a retry) pick up — the failure-detection analogue of
   SURVEY §5.3 at the cross-host level.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from sbr_tpu.models.params import ModelParams, SolverConfig


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> bool:
    """Bring up `jax.distributed` for multi-host runs; single-process no-op.

    Returns True when distributed mode was initialized. With no arguments,
    initialization happens only if the standard coordination env vars are
    present (JAX_COORDINATOR_ADDRESS / cloud-TPU metadata), so single-host
    callers can invoke this unconditionally.
    """
    explicit = coordinator_address is not None or num_processes is not None
    env = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if not explicit and not env:
        return False

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    return True


def tile_assignment(n_tiles: int, n_processes: int, process_id: int) -> range:
    """Contiguous balanced split: process p owns tiles [start_p, end_p).

    Every tile is owned by exactly one process; sizes differ by at most 1.
    """
    if not 0 <= process_id < n_processes:
        raise ValueError(f"process_id {process_id} not in [0, {n_processes})")
    base, rem = divmod(n_tiles, n_processes)
    start = process_id * base + min(process_id, rem)
    return range(start, start + base + (1 if process_id < rem else 0))


def run_tiled_grid_multihost(
    beta_values,
    u_values,
    base: ModelParams,
    checkpoint_dir: str,
    config: Optional[SolverConfig] = None,
    tile_shape=(256, 256),
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
    wait: bool = True,
    poll_s: float = 5.0,
    timeout_s: float = 24 * 3600.0,
    dtype=None,
    verbose: bool = False,
):
    """Farm a β×u grid across processes via the shared checkpoint dir.

    Each process computes only its `tile_assignment` share (plus anything
    already on disk); coordination is purely filesystem-level, so this
    works across hosts that share nothing but storage — no collectives, no
    jax.distributed requirement (use it when a mesh-spanning program is
    also running; not needed here).

    With ``wait`` (default), after finishing its share the process polls
    until every tile exists, then assembles and returns the full grid.
    With ``wait=False`` it returns None right after its own share — the
    pattern for worker processes whose results are consumed elsewhere.
    """
    from sbr_tpu.utils.checkpoint import _tile_path, run_tiled_grid, tile_origins

    if process_id is None or num_processes is None:
        import jax

        process_id = jax.process_index() if process_id is None else process_id
        num_processes = jax.process_count() if num_processes is None else num_processes

    import numpy as np

    nb, nu = len(np.asarray(beta_values)), len(np.asarray(u_values))
    tiles = tile_origins(nb, nu, tile_shape)
    owned = {tiles[i] for i in tile_assignment(len(tiles), num_processes, process_id)}

    run_tiled_grid(
        beta_values,
        u_values,
        base,
        config=config,
        tile_shape=tile_shape,
        checkpoint_dir=checkpoint_dir,
        dtype=dtype,
        verbose=verbose,
        tile_owner=lambda bi, ui: (bi, ui) in owned,
    )
    if not wait:
        return None

    # Filesystem barrier: every tile must exist before assembly.
    from pathlib import Path

    ckpt = Path(checkpoint_dir)
    deadline = time.monotonic() + timeout_s
    while True:
        missing = [t for t in tiles if not _tile_path(ckpt, *t).exists()]
        if not missing:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"{len(missing)} tiles still missing after {timeout_s:.0f}s "
                f"(first: {missing[0]}); a peer process likely died — rerun "
                "with its process_id (or a smaller num_processes) to adopt "
                "its tiles."
            )
        if verbose:
            print(f"  waiting on {len(missing)} peer tiles …")
        time.sleep(poll_s)

    # Assembly: all tiles cached on disk — a pure read, no recompute.
    return run_tiled_grid(
        beta_values,
        u_values,
        base,
        config=config,
        tile_shape=tile_shape,
        checkpoint_dir=checkpoint_dir,
        dtype=dtype,
        verbose=verbose,
    )
