"""Stage 1 — learning dynamics.

The reference integrates the logistic SI ODE dx/dt = βx(1-x) with an adaptive
solver at machine-eps tolerance and interpolates the adaptive grid
(`src/baseline/learning.jl:41-54`). The ODE has the exact solution

    G(t) = x0 / (x0 + (1 - x0) * exp(-β t)),

so the TPU build evaluates Stage 1 in closed form: exact, grid-free,
overflow-safe for βt up to ~1e4·30 (the Figure-5 sweep reaches β = 1e4,
`scripts/1_baseline.jl:210-211`), and exactly differentiable. The PDF is the
symbolic g(t) = β·G·(1-G) the reference also uses
(`src/baseline/learning.jl:161-173`). A fixed-grid RK4 fallback exists for
dynamics with no closed form (hetero, HJB, forced social learning).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sbr_tpu.models.params import LearningParams, SolverConfig
from sbr_tpu.models.results import LearningSolution


def logistic_cdf(t, beta, x0):
    """Exact SI-model CDF G(t) = x0 / (x0 + (1-x0)·e^{-βt}).

    Written in the decaying-exponential form so large βt saturates to 1
    instead of overflowing (the naive x0·e^{βt} form overflows at βt ≈ 709).
    """
    return x0 / (x0 + (1.0 - x0) * jnp.exp(-beta * t))


def logistic_pdf(t, beta, x0):
    """Exact SI-model PDF g(t) = β·G(t)·(1-G(t)) (`learning.jl:167-170`)."""
    g = logistic_cdf(t, beta, x0)
    return beta * g * (1.0 - g)


def solve_learning(
    params: LearningParams,
    config: SolverConfig | None = None,
    dtype=jnp.float64,
) -> LearningSolution:
    """Solve Stage 1 on a static uniform grid (reference `solve_learning`,
    `learning.jl:109-124`).

    Returns a `LearningSolution` whose CDF/PDF evaluators use the closed form;
    the grid samples exist for plotting and for stages that consume sampled
    curves (e.g. as the social-learning initial guess,
    `social_learning_solver.jl:90-94`).
    """
    if config is None:
        config = SolverConfig()
    from sbr_tpu import obs

    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))  # x64-aware
    t0, t1 = params.tspan
    with obs.span("baseline.learning", n_grid=config.n_grid) as sp:
        grid = jnp.linspace(t0, t1, config.n_grid, dtype=dtype)
        beta = jnp.asarray(params.beta, dtype=dtype)
        x0 = jnp.asarray(params.x0, dtype=dtype)
        cdf = logistic_cdf(grid, beta, x0)
        pdf = logistic_pdf(grid, beta, x0)
        sp.sync(cdf, pdf)
    return LearningSolution(
        grid=grid,
        cdf=cdf,
        pdf=pdf,
        t0=jnp.asarray(t0, dtype=dtype),
        dt=grid[1] - grid[0],
        beta=beta,
        x0=x0,
        closed_form=True,
    )


def learning_solution_from_samples(grid, cdf, pdf) -> LearningSolution:
    """Wrap sampled curves on a uniform grid (ODE-backed stages).

    Mirrors the reference pattern of re-wrapping extension dynamics as a
    baseline `LearningResults` (`social_learning_solver.jl:135-137`).
    """
    grid = jnp.asarray(grid)
    return LearningSolution(
        grid=grid,
        cdf=jnp.asarray(cdf),
        pdf=jnp.asarray(pdf),
        t0=grid[0],
        dt=grid[1] - grid[0],
        beta=jnp.asarray(jnp.nan, dtype=grid.dtype),
        x0=cdf[0],
        closed_form=False,
    )
