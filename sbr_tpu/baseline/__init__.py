"""Baseline three-stage pipeline (reference `src/baseline/learning.jl`,
`src/baseline/solver.jl`)."""

from sbr_tpu.baseline.learning import logistic_cdf, logistic_pdf, solve_learning
from sbr_tpu.baseline.solver import (
    compute_xi,
    get_aw,
    hazard_rate,
    optimal_buffer,
    solve_equilibrium_baseline,
    solve_equilibrium_core,
)
