"""Stages 2+3 — hazard rate, optimal buffers, equilibrium crash time, and the
aggregate-withdrawal curves (reference `src/baseline/solver.jl`).

Design notes vs the reference:

- The hazard normalization integral is a parallel cumulative quadrature
  (`core.integrate`) instead of the sequential trapezoid loop
  (`solver.jl:172-175`). With the closed-form Stage-1 PDF the integrand is
  analytic, so composite Gauss-Legendre makes the hazard essentially exact.
- Buffer times come from vectorized crossing detection (`core.rootfind`)
  replacing the forward/backward scans of `solver.jl:229-261`.
- ξ comes from fixed-iteration bisection plus a post-hoc classification into
  the reference's 5 cases (`solver.jl:341-372`) as status codes: no branch
  diverges under vmap, and no-run cells surface as NaN exactly like the
  reference's sweeps expect (`scripts/1_baseline.jl:157-163`).
- Everything is a pure function of arrays: a single u-sweep is
  `vmap(solve_equilibrium_core, in_axes=(None, 0, ...))` with Stage 1 shared,
  the algebraic split the reference exploits manually at
  `scripts/1_baseline.jl:169`.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

from sbr_tpu.baseline.learning import logistic_cdf, logistic_pdf
from sbr_tpu.core.integrate import cumtrapz, cumulative_gauss_legendre
from sbr_tpu.core.rootfind import (
    bisect,
    chandrupatla,
    first_upcrossing,
    last_downcrossing,
    threshold_crossings_masked,
)
from sbr_tpu.models.params import EconomicParams, SolverConfig
from sbr_tpu.models.results import EquilibriumResult, LearningSolution, Status


def _stamp_solve_time(res, t0: float):
    """Attach wall-clock solve_time (device-fenced) to a result, skipping
    traced contexts — the convenience entries may run under jit/shard_map,
    where a host clock is meaningless and blocking is illegal."""
    if isinstance(res.xi, jax.core.Tracer):
        return res
    jax.block_until_ready(res)  # the WHOLE pytree — curves dispatch after xi
    return res.replace(solve_time=time.perf_counter() - t0)


def _root_tol(dtype) -> float:
    """Root-acceptance tolerance on |AW(ξ*) - κ|.

    The reference exits bisection at 10·eps(κ) (`solver.jl:310,438`). Fixed
    90-halving bisection lands far inside that when a root exists and far
    outside when none does, so the threshold only needs to separate the two
    regimes; SURVEY §7.3 notes 10·eps at f32 (~7e-7) is too tight, hence the
    dtype-aware ladder. The f64 value allows for TPU f64 transcendentals,
    whose composite error floor is ~5e-9 (measured on v5e: exp+divide chains
    land ~2e-9 off the CPU value).
    """
    return 1e-7 if jnp.dtype(dtype) == jnp.float64 else 1e-4


def _warped_grid(eta, beta, x0, n, warp, dtype):
    """Transition-resolving hazard grid for the closed-form logistic.

    A uniform [0, η] grid cannot see equilibria at large β: the whole
    logistic transition (width ~1/β around t* = logit((1-x0)/x0)/β) falls
    inside one cell once β ≳ n/η, and the solver mislabels genuinely
    running cells as false equilibria — measured against the reference's
    committed 5000×5000 heatmap raster, ALL 17,666 false-eq cells of the
    round-3 paper sweep sat in the 4 highest-β columns where the
    reference's adaptive grid (`learning.jl:51`) resolves the spike. The
    fix mirrors the reference's grid-inheritance idea in closed form: the
    grid is the SORTED UNION of ⌈(1-warp)·n⌉ uniform points with ⌊warp·n⌋
    points of the logistic inverse-CDF map

        t(q) = [logit(x0 + q·(G(η)-x0)) - logit(x0)] / β,

    which places them uniformly in G-space — i.e. clustered through the
    transition with local spacing ~1/(β·warp·n) at ANY β, while the uniform
    half keeps the flat tail covered. (A convex BLEND of the two maps does
    not work: the uniform component floors the local spacing at
    (1-warp)·η/n, which still swallows the transition once β ≳ n/η —
    measured before switching to the union form.) Duplicate knots from the
    union are harmless: zero-width intervals contribute nothing to the
    quadrature and cannot host a crossing (`core.rootfind._interp_cross`
    guards flat segments).
    """
    n_q = max(1, int(warp * n))
    n_u = n - n_q
    t_uniform = jnp.linspace(jnp.zeros((), dtype), eta, n_u)
    q = jnp.linspace(jnp.zeros((), dtype), jnp.ones((), dtype), n_q)
    g_eta = logistic_cdf(eta, beta, x0)
    levels = x0 + q * (g_eta - x0)

    # Saturation guard (ISSUE 13): once G(η) rounds to exactly 1 (f32 at
    # β·η ≳ 29), log1p(-1) is -inf and every arithmetic step a saturated
    # lane touches afterwards (the subtraction, the division by β) leaks
    # 0·inf = NaN into the β-cotangent under reverse-mode AD — on RUN
    # cells, since the poisoned lanes are only CLIPPED away, not removed.
    # The where-pair keeps forward values BIT-IDENTICAL (unsaturated lanes
    # compute the same expression; saturated lanes were +inf before and
    # are the +inf constant now, pinned to η by the clip either way) while
    # routing the differentiation path through a finite dummy whose
    # cotangent the selects zero out.
    logit = lambda v: jnp.log(v) - jnp.log1p(-v)
    sat = levels >= 1.0
    safe_levels = jnp.where(sat, jnp.asarray(0.5, dtype), levels)
    num = logit(safe_levels) - logit(jnp.asarray(x0, dtype))
    t_quant = jnp.where(sat, jnp.asarray(jnp.inf, dtype), num / beta)
    grid = jnp.sort(jnp.concatenate([t_uniform, t_quant]))
    # pin the endpoints exactly (t_quant hits 0/η only up to rounding)
    return jnp.clip(grid, 0.0, eta).at[0].set(0.0).at[-1].set(eta)


def warped_grid_index(t, eta, beta, x0, n, warp):
    """Bracketing-index guess into `_warped_grid`'s knots, in closed form.

    The warped grid is the sorted union of two ANALYTIC monotone sequences,
    so the rank of any query is arithmetic — no searchsorted: the count of
    uniform knots ≤ t is a floor division, and the count of quantile knots
    ≤ t inverts the same logistic map that placed them (t_j ≤ t  ⟺
    q_j ≤ (G(t)−x0)/(G(η)−x0)). The returned index is exact up to floating
    rounding at knot boundaries (≪ one knot — transition-region spacing is
    ~η/(warp·n) while the rank error is ~n·eps); pair with
    `core.interp.interp_guided`, which absorbs ±1.
    """
    dtype = jnp.result_type(t, jnp.float32)
    t = jnp.asarray(t, dtype)
    n_q = max(1, int(warp * n))
    n_u = n - n_q
    if n_u >= 2:
        cnt_u = jnp.clip(
            jnp.floor(t * ((n_u - 1) / eta)).astype(jnp.int32) + 1, 0, n_u
        )
    else:
        cnt_u = jnp.full(t.shape, n_u, jnp.int32)  # 0 or 1 knot at t=0
    g_eta = logistic_cdf(eta, beta, x0)
    ratio = (logistic_cdf(t, beta, x0) - x0) / (g_eta - x0)
    cnt_q = jnp.clip(jnp.floor(ratio * (n_q - 1)).astype(jnp.int32) + 1, 0, n_q)
    return cnt_u + cnt_q - 1


def hazard_grid_is_uniform(ls: LearningSolution, config: SolverConfig) -> bool:
    """Whether `_hazard_parts` will build a uniform grid — the single source
    of truth for callers that choose between uniform-stride and searchsorted
    interpolation over that grid (the interest path's HJB and V evaluators).
    Static: depends only on concrete config/solution metadata."""
    return not (ls.closed_form and config.grid_warp > 0.0)


def _hazard_parts(p, lam, ls: LearningSolution, eta, config: SolverConfig):
    """Hazard grid, values, and the cumulative normalization integral."""
    dtype = ls.cdf.dtype
    eta = jnp.asarray(eta, dtype=dtype)
    p = jnp.asarray(p, dtype=dtype)
    lam = jnp.asarray(lam, dtype=dtype)

    if ls.closed_form:
        beta, x0 = ls.beta, ls.x0
        if not hazard_grid_is_uniform(ls, config):
            tau_grid = _warped_grid(eta, beta, x0, config.n_grid, config.grid_warp, dtype)
        else:
            tau_grid = jnp.linspace(jnp.zeros((), dtype), eta, config.n_grid)

        def integrand(ts):
            return jnp.exp(lam * ts) * logistic_pdf(ts, beta, x0)

        integ = cumulative_gauss_legendre(integrand, tau_grid, order=config.quad_order)
        g_tau = logistic_pdf(tau_grid, beta, x0)
    else:
        # grid-backed Stage 1 (hetero groups, social fixed point): the
        # learning grid is uniform, so the hazard grid stays uniform too
        tau_grid = jnp.linspace(jnp.zeros((), dtype), eta, config.n_grid)
        g_tau = ls.pdf_at(tau_grid)
        eg = jnp.exp(lam * tau_grid) * g_tau
        integ = cumtrapz(eg, x=tau_grid)

    int_eta = integ[-1]
    hr = (p * jnp.exp(lam * tau_grid) * g_tau) / (p * integ + (1.0 - p) * int_eta)
    return tau_grid, hr, integ, int_eta


def hazard_rate(p, lam, ls: LearningSolution, eta, config: SolverConfig | None = None):
    """Hazard rate h(τ̄) on a static [0, η] grid (`solver.jl:153-185`).

    h(τ̄) = p·e^{λτ̄}·g(τ̄) / (p·∫₀^τ̄ e^{λs}g(s)ds + (1-p)·∫₀^η e^{λs}g(s)ds)

    Returns (tau_grid, hr). For p=1 the value at τ̄=0 is +inf, matching the
    reference's division by a zero integral (used only by the plotting layer's
    h_f decomposition, `plotting.jl:62-132`).
    """
    if config is None:
        config = SolverConfig()
    tau_grid, hr, _, _ = _hazard_parts(p, lam, ls, eta, config)
    return tau_grid, hr


def hazard_at_from_parts(
    tau, tau_grid, integ, int_eta, p, lam, beta, x0, nodes, weights
):
    """Continuous exact hazard h(τ̄) as a PURE function of its parts — one
    knot lookup plus a single Gauss-Legendre panel over the sub-interval,
    exact for the analytic closed-form integrand.

    Factored out of `_make_hazard_at`'s closure (ISSUE 13) so the grad
    subsystem can evaluate the SAME formula with every argument explicit:
    `sbr_tpu.grad.ift.implicit_root` needs the crossing residual
    h(τ̄; θ) − u as ``f(x, operand)`` with all tangent-carrying inputs in
    the operand pytree, and sharing this body (rather than transcribing it)
    guarantees the IFT linearization differentiates exactly the function
    whose root the forward refinement bisection found."""
    n = tau_grid.shape[0]
    # binary-search lookup: the grid may be warped (non-uniform)
    i = jnp.clip(jnp.searchsorted(tau_grid, tau, side="right") - 1, 0, n - 2)
    a = tau_grid[i]
    half = 0.5 * (tau - a)
    mid = 0.5 * (tau + a)
    xs = mid + half * nodes
    vals = jnp.exp(lam * xs) * logistic_pdf(xs, beta, x0)
    i_loc = integ[i] + half * jnp.dot(weights, vals)
    num = p * jnp.exp(lam * tau) * logistic_pdf(tau, beta, x0)
    return num / (p * i_loc + (1.0 - p) * int_eta)


def quad_nodes_weights(order: int, dtype):
    """Gauss-Legendre nodes/weights as jnp arrays of ``dtype`` (shared by
    the closure below and the grad subsystem's operand evaluator)."""
    import numpy as np

    nodes, weights = np.polynomial.legendre.leggauss(order)
    return jnp.asarray(nodes, dtype=dtype), jnp.asarray(weights, dtype=dtype)


def _make_hazard_at(p, lam, ls: LearningSolution, tau_grid, integ, int_eta, config: SolverConfig):
    """Continuous exact hazard evaluator for closed-form Stage 1.

    h at arbitrary τ̄ needs the normalization integral at τ̄; it is recovered as
    the precomputed knot value plus a single Gauss-Legendre panel over the
    sub-interval — exact for the analytic integrand, so buffer crossings can be
    refined to machine precision instead of the grid-linear-interp accuracy the
    reference settles for (`solver.jl:233-250`). Thin closure over
    `hazard_at_from_parts` (the shared pure form)."""
    dtype = tau_grid.dtype
    nodes, weights = quad_nodes_weights(config.quad_order, dtype)
    beta, x0 = ls.beta, ls.x0
    p = jnp.asarray(p, dtype=dtype)
    lam = jnp.asarray(lam, dtype=dtype)

    def hazard_at(tau):
        return hazard_at_from_parts(
            tau, tau_grid, integ, int_eta, p, lam, beta, x0, nodes, weights
        )

    return hazard_at


def optimal_buffer(
    u,
    tau_grid,
    hr,
    tspan_end,
    hazard_at=None,
    refine_iters: int = 60,
    with_health: bool = False,
    adaptive: bool = False,
):
    """Unconstrained buffer times (τ̄_IN, τ̄_OUT) where h crosses u
    (`solver.jl:211-264`), with the reference's boundary fallbacks.

    With ``hazard_at`` (continuous exact hazard), genuine crossings are
    refined by bisection within ±one grid interval of the coarse estimate;
    fallback lanes keep their grid values. With ``with_health`` a merged
    `diag.Health` of the two crossing detections (fallback rungs + NaN
    poison in the hazard/level) is appended; the refinement bisections stay
    health-free — in fallback lanes their brackets are legitimately
    degenerate and the coarse crossing flags already tell the story.

    ``adaptive`` (SolverConfig.numerics == "adaptive", ISSUE 9) swaps the
    two O(n) crossing scans for the fused blocked search
    (`core.rootfind.threshold_crossings_masked` — bit-identical indices,
    O(√n) per cell once the hazard tables hoist out of the sweeps' u axis)
    and the fixed-iteration refinement bisections for convergence-masked
    `chandrupatla` with the same iteration budget.
    """
    from sbr_tpu.diag.health import as_out_crossing

    default = jnp.asarray(tspan_end, dtype=hr.dtype)
    if adaptive:
        out = threshold_crossings_masked(
            tau_grid, hr, u, default, with_health=with_health
        )
        t_in, has_up, t_out, has_dn = out[:4]
        cross_health = out[4].merge(as_out_crossing(out[5])) if with_health else None
    elif with_health:
        t_in, has_up, h_in = first_upcrossing(
            tau_grid, hr, u, default, return_flag=True, with_health=True
        )
        t_out, has_dn, h_out = last_downcrossing(
            tau_grid, hr, u, default, return_flag=True, with_health=True
        )
        cross_health = h_in.merge(as_out_crossing(h_out))
    else:
        t_in, has_up = first_upcrossing(tau_grid, hr, u, default, return_flag=True)
        t_out, has_dn = last_downcrossing(tau_grid, hr, u, default, return_flag=True)
        cross_health = None
    if hazard_at is None:
        return (t_in, t_out, cross_health) if with_health else (t_in, t_out)

    eta = tau_grid[-1]
    n = tau_grid.shape[0]

    def bracket(t):
        # ±one LOCAL grid interval around the coarse crossing (the grid may
        # be warped, so the neighborhood width varies along the axis)
        i = jnp.clip(jnp.searchsorted(tau_grid, t, side="right") - 1, 0, n - 1)
        lo = tau_grid[jnp.maximum(i - 1, 0)]
        hi = tau_grid[jnp.minimum(i + 2, n - 1)]
        return lo, hi

    refine = (
        (lambda f, lo, hi: chandrupatla(f, lo, hi, budget=refine_iters))
        if adaptive
        else (lambda f, lo, hi: bisect(f, lo, hi, num_iters=refine_iters))
    )
    lo_i, hi_i = bracket(t_in)
    t_in_ref = refine(lambda t: hazard_at(t) - u, lo_i, hi_i)
    lo_o, hi_o = bracket(t_out)
    # down-crossing: u - h is locally increasing
    t_out_ref = refine(lambda t: u - hazard_at(t), lo_o, hi_o)
    t_in = jnp.where(has_up, t_in_ref, t_in)
    t_out = jnp.where(has_dn, t_out_ref, t_out)
    return (t_in, t_out, cross_health) if with_health else (t_in, t_out)


def compute_xi(
    tau_bar_in_unc,
    tau_bar_out_unc,
    ls: LearningSolution,
    kappa,
    config: SolverConfig | None = None,
    lo=None,
    hi=None,
    x0=None,
    with_health: bool = False,
):
    """Bisection for AW(ξ)=κ with first-crossing validation (`solver.jl:308-376`).

    AW(ξ) = G(min(ξ, τ̄_OUT)) - G(min(ξ, τ̄_IN)).

    Returns (xi_candidate, abs_error, root_ok, is_increasing):
    - root_ok: |AW(ξ*)-κ| under the dtype tolerance ladder — False reproduces
      the reference's interval-collapse / non-convergence NaN path.
    - is_increasing: finite-difference slope of the withdrawal path at ξ* with
      ε = the learning-grid spacing (`solver.jl:336-343`); False is the
      reference's "false equilibrium" (root on the decreasing branch).
    With ``with_health`` the bisection's `diag.Health` (final residual —
    identical to abs_error, XLA CSEs the shared evaluation — bracket width,
    bracket-validity and NaN flags) is appended.

    Under ``config.numerics == "adaptive"`` the fixed halvings become
    convergence-masked `chandrupatla` with ``bisect_iters`` as the budget;
    the health then carries ACTUAL per-cell iteration counts, not the
    budget. ``numerics="fixed"`` reproduces the reference update rule
    bit-for-bit.
    """
    if config is None:
        config = SolverConfig()
    dtype = ls.cdf.dtype
    kappa = jnp.asarray(kappa, dtype=dtype)
    lo = tau_bar_in_unc if lo is None else lo
    hi = tau_bar_out_unc if hi is None else hi

    def aw_of(xi):
        t_out = jnp.minimum(tau_bar_out_unc, xi)
        t_in = jnp.minimum(tau_bar_in_unc, xi)
        return ls.cdf_at(t_out) - ls.cdf_at(t_in)

    if config.adaptive:
        out = chandrupatla(
            lambda x: aw_of(x) - kappa,
            lo,
            hi,
            budget=config.bisect_iters,
            x0=x0,
            with_health=with_health,
        )
    else:
        out = bisect(
            lambda x: aw_of(x) - kappa,
            lo,
            hi,
            num_iters=config.bisect_iters,
            x0=x0,
            with_health=with_health,
        )
    xi, xi_health = out if with_health else (out, None)

    aw = aw_of(xi)
    err = jnp.abs(aw - kappa)
    root_ok = err <= _root_tol(dtype)

    t_out = jnp.minimum(tau_bar_out_unc, xi)
    t_in = jnp.minimum(tau_bar_in_unc, xi)
    if ls.closed_form:
        # The reference's ε is its LOCAL adaptive-grid spacing at ξ
        # (`solver.jl:336-339`) — tiny where G moves fast. A fixed ε = ls.dt
        # breaks at large β: with the transition width ~1/β ≪ dt, both
        # shifted evaluations land past the transition and every genuine
        # equilibrium reads as "decreasing" (measured: all 17,666 false-eq
        # cells of the round-3 paper heatmap were this artifact). In closed
        # form the ε→0 limit is exact: d/dε [G(t_out+ε) - G(t_in+ε)] at 0
        # is g(t_out) - g(t_in).
        is_increasing = logistic_pdf(t_out, ls.beta, ls.x0) >= logistic_pdf(
            t_in, ls.beta, ls.x0
        )
    else:
        eps = ls.dt
        aw_eps = ls.cdf_at(t_out + eps) - ls.cdf_at(t_in + eps)
        is_increasing = aw_eps >= aw
    if with_health:
        return xi, err, root_ok, is_increasing, xi_health
    return xi, err, root_ok, is_increasing


def get_aw(xi, tau_bar_in_unc, tau_bar_out_unc, tau_grid, ls: LearningSolution):
    """Aggregate-withdrawal curves on the hazard grid (`solver.jl:495-532`).

    AW_cum(t) = G(t-ξ+τ̄_OUT^CON) - G(t-ξ+τ̄_IN^CON) + G(0), with each branch
    zeroed before its own start time exactly as the reference's ifelse masks.
    Returns (aw_cum, aw_out, aw_in).
    """
    zero = jnp.zeros((), dtype=tau_grid.dtype)
    tau_in_con = jnp.minimum(tau_bar_in_unc, xi)
    tau_out_con = jnp.minimum(tau_bar_out_unc, xi)

    shift_in = tau_grid - xi + tau_in_con
    aw_in = jnp.where(shift_in >= 0, ls.cdf_at(jnp.maximum(shift_in, zero)), zero)
    shift_out = tau_grid - xi + tau_out_con
    aw_out = jnp.where(shift_out >= 0, ls.cdf_at(jnp.maximum(shift_out, zero)), zero)

    aw_cum = aw_out - aw_in + ls.cdf_at(zero)
    return aw_cum, aw_out, aw_in


def _aw_max_exact(xi, tau_bar_in_unc, tau_bar_out_unc, eta, ls: LearningSolution):
    """Exact max of the AW curve for closed-form Stage 1, in O(1).

    AW(t) = [G(t−ξ+τ̄_OUT^CON)]₊ − [G(t−ξ+τ̄_IN^CON)]₊ + G(0) is piecewise
    smooth on [0, η] with kinks where each branch's mask activates. On the
    both-branches-active piece, AW′ = g(out-arg) − g(in-arg) vanishes where
    the two pdf arguments straddle the logistic peak s* = ln((1−x0)/x0)/β
    symmetrically (the logistic pdf is symmetric about s*:
    logit G(s*±d) = ±βd), i.e. at t* = ξ + s* − (τ̄_IN^CON+τ̄_OUT^CON)/2.
    Where only the out-branch is active AW is increasing, so that piece's
    max sits at its right end — a kink. The global max therefore lies in
    {0, η, t*, ξ−τ̄_IN^CON, ξ−τ̄_OUT^CON}: five exact evaluations replace
    the reference's O(n) grid max (`solver.jl:566`), and the result is the
    TRUE maximum rather than a grid sample of it.
    """
    dtype = ls.cdf.dtype
    zero = jnp.zeros((), dtype=dtype)
    tau_in_con = jnp.minimum(tau_bar_in_unc, xi)
    tau_out_con = jnp.minimum(tau_bar_out_unc, xi)

    s_star = (jnp.log1p(-ls.x0) - jnp.log(ls.x0)) / ls.beta
    t_peak = xi + s_star - 0.5 * (tau_in_con + tau_out_con)
    candidates = jnp.stack(
        [
            zero,
            jnp.asarray(eta, dtype),
            jnp.clip(t_peak, 0.0, eta),
            jnp.clip(xi - tau_in_con, 0.0, eta),
            jnp.clip(xi - tau_out_con, 0.0, eta),
        ]
    )

    shift_in = candidates - xi + tau_in_con
    aw_in = jnp.where(shift_in >= 0, ls.cdf_at(jnp.maximum(shift_in, zero)), zero)
    shift_out = candidates - xi + tau_out_con
    aw_out = jnp.where(shift_out >= 0, ls.cdf_at(jnp.maximum(shift_out, zero)), zero)
    return jnp.max(aw_out - aw_in) + ls.cdf_at(zero)


def classify_cell(no_crossing, root_ok, increasing, err, dtype, first_ok=None):
    """Shared branchless outcome classification of one equilibrium cell —
    the ONE definition of the reference's 5-case split (`solver.jl:341-372`)
    used by the baseline, interest, hetero, and composed-scenario stacks
    (ISSUE 14: stage algebra defined once). ``first_ok`` adds the hetero
    family's first-crossing validation (`heterogeneity_solver.jl:175-210`)
    without disturbing the 3-condition stacks' bytes.

    Returns (run, status, converged, tolerance).
    """
    valid_slope = (
        increasing if first_ok is None else jnp.logical_and(increasing, first_ok)
    )
    run = jnp.logical_and(
        jnp.logical_not(no_crossing), jnp.logical_and(root_ok, valid_slope)
    )
    status = jnp.where(
        no_crossing,
        Status.NO_CROSSING,
        jnp.where(
            jnp.logical_not(root_ok),
            Status.NO_ROOT,
            jnp.where(valid_slope, Status.RUN, Status.FALSE_EQ),
        ),
    ).astype(jnp.int32)
    converged = jnp.logical_or(no_crossing, run)  # `solver.jl:432,447-455`
    tolerance = jnp.where(
        no_crossing, jnp.zeros((), dtype), jnp.where(run, err, jnp.asarray(jnp.inf, dtype))
    )
    return run, status, converged, tolerance


def solve_equilibrium_core(
    ls: LearningSolution,
    u,
    p,
    kappa,
    lam,
    eta,
    tspan_end,
    config: SolverConfig | None = None,
    hazard_transform=None,
    kappa_transform=None,
) -> EquilibriumResult:
    """Scalar-parameter equilibrium solve — the vmap/pjit unit of the sweeps.

    Faithful to `solve_equilibrium_baseline` (`solver.jl:413-462`) including
    the trivial no-crossing branch, expressed branchlessly via status codes.

    Stage-transformer hooks (ISSUE 14, the composable scenario engine):

    - ``hazard_transform(tau_grid, hr, hazard_at)`` →
      ``(hr, hazard_at, extra_health)`` rewrites the hazard between the
      hazard stage and the buffer crossings (interest's h − rV, policy
      modifiers); ``extra_health`` is a tuple of `diag.Health` merged after
      the ξ stage's, preserving the legacy merge order.
    - ``kappa_transform(kappa)`` rewrites the solvency threshold before the
      ξ bisection (lender-of-last-resort injections).

    Both default to None, on which path the function is bit-identical to
    its pre-scenario form — the parity anchor every composed reduction is
    measured against.
    """
    if config is None:
        config = SolverConfig()
    from sbr_tpu import obs

    dtype = ls.cdf.dtype
    u = jnp.asarray(u, dtype=dtype)
    nan = jnp.asarray(jnp.nan, dtype=dtype)

    # Spans are host-boundary no-ops inside traced code (sweeps, the social
    # while_loop); on the eager path they give the per-stage wall split.
    with obs.span("baseline.hazard") as sp:
        tau_grid, hr, integ, int_eta = _hazard_parts(p, lam, ls, eta, config)
        sp.sync(hr)
    hazard_at = (
        _make_hazard_at(p, lam, ls, tau_grid, integ, int_eta, config)
        if (ls.closed_form and config.refine_crossings)
        else None
    )
    extra_health = ()
    if hazard_transform is not None:
        hr, hazard_at, extra_health = hazard_transform(tau_grid, hr, hazard_at)
    if kappa_transform is not None:
        kappa = kappa_transform(kappa)
    with obs.span("baseline.buffers") as sp:
        tau_in_unc, tau_out_unc, cross_health = optimal_buffer(
            u, tau_grid, hr, tspan_end, hazard_at=hazard_at, with_health=True,
            adaptive=config.adaptive,
        )
        sp.sync(tau_in_unc, tau_out_unc)

    no_crossing = tau_in_unc == tau_out_unc

    with obs.span("baseline.xi") as sp:
        xi_c, err, root_ok, increasing, xi_health = compute_xi(
            tau_in_unc, tau_out_unc, ls, kappa, config, with_health=True
        )
        sp.sync(xi_c)
    health = cross_health.merge(xi_health, *extra_health)

    run, status, converged, tolerance = classify_cell(
        no_crossing, root_ok, increasing, err, dtype
    )

    xi = jnp.where(run, xi_c, nan)

    aw_cum, aw_out, aw_in = get_aw(xi, tau_in_unc, tau_out_unc, tau_grid, ls)
    aw_cum = jnp.where(run, aw_cum, nan)
    aw_out = jnp.where(run, aw_out, nan)
    aw_in = jnp.where(run, aw_in, nan)
    if ls.closed_form:
        # exact O(1) maximum — and it unhooks aw_max from the (n,) curves,
        # so the sweeps' lean cells dead-code-eliminate get_aw entirely
        aw_max = jnp.where(run, _aw_max_exact(xi, tau_in_unc, tau_out_unc, eta, ls), nan)
    else:
        aw_max = jnp.where(run, jnp.max(aw_cum), nan)

    return EquilibriumResult(
        xi=xi,
        tau_bar_in_unc=tau_in_unc,
        tau_bar_out_unc=tau_out_unc,
        tau_in=jnp.maximum(xi - tau_in_unc, 0.0),
        tau_out=jnp.maximum(xi - tau_out_unc, 0.0),
        bankrun=run,
        status=status,
        converged=converged,
        tolerance=tolerance,
        tau_grid=tau_grid,
        hr=hr,
        aw_cum=aw_cum,
        aw_out=aw_out,
        aw_in=aw_in,
        aw_max=aw_max,
        health=health,
    )


@functools.lru_cache(maxsize=None)
def _jitted_core(config: SolverConfig):
    """Jitted `solve_equilibrium_core`, cached per static config — the
    telemetry path of the convenience entry: one compiled program whose
    compile/execute split `obs.jit_call` can attribute (AOT lower/compile),
    where the eager path's op-by-op dispatch has no compile step to time."""

    def core(ls, u, p, kappa, lam, eta, tspan_end):
        # Trace-time retrace accounting (obs.prof): runs once per jit cache
        # miss, never at execute time — churn detection with zero graph cost.
        from sbr_tpu.obs import prof

        prof.note_trace("baseline.equilibrium")
        return solve_equilibrium_core(ls, u, p, kappa, lam, eta, tspan_end, config)

    return jax.jit(core)


def solve_equilibrium_baseline(
    ls: LearningSolution,
    econ: EconomicParams,
    config: SolverConfig | None = None,
    tspan_end=None,
) -> EquilibriumResult:
    """Convenience entry mirroring `solve_equilibrium_baseline(lr, econ)`
    (`solver.jl:413`). ``tspan_end`` defaults to the learning grid's end, the
    reference's `lr.params.tspan[2]` (`solver.jl:421`). The result carries
    wall-clock ``solve_time`` with a device fence, like every reference
    result struct (`solver.jl:414,458`).

    With telemetry active (`sbr_tpu.obs`), the solve runs as ONE jitted
    program through `obs.jit_call`, logging a stage span plus the
    compile/execute split and XLA cost analysis; results are the same pure
    function of the inputs either way (jit vs eager may differ in the last
    ulp of f64, well inside every tolerance in the package)."""
    if config is None:
        config = SolverConfig()
    from sbr_tpu import obs

    if tspan_end is None:
        tspan_end = ls.grid[-1]
    t0 = time.perf_counter()
    if obs.enabled():
        dtype = ls.cdf.dtype
        args = tuple(
            jnp.asarray(v, dtype)
            for v in (econ.u, econ.p, econ.kappa, econ.lam, econ.eta, tspan_end)
        )
        with obs.span("baseline.equilibrium"):
            res = obs.jit_call(
                "baseline.equilibrium", _jitted_core(config), ls, *args
            )
    else:
        res = solve_equilibrium_core(
            ls, econ.u, econ.p, econ.kappa, econ.lam, econ.eta, tspan_end, config
        )
    res = _stamp_solve_time(res, t0)
    obs.log_health("baseline.equilibrium", res.health, res.status)
    return res
