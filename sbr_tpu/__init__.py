"""sbr_tpu — TPU-native framework for social-bank-run equilibria.

A from-scratch JAX/XLA rebuild of the capabilities of the reference replication
package for "The Social Determinants of Bank Runs" (reference layout: Julia,
`src/baseline/{model,learning,solver}.jl` + three extensions). The design is
TPU-first, not a port:

- Fixed static-shape time grids instead of adaptive ODE grids (reference
  `src/baseline/learning.jl:51` inherits an adaptive grid everywhere).
- Closed-form Stage-1 logistic learning where the reference integrates an ODE
  (`src/baseline/learning.jl:41-54`), making Stage 1 exact and grid-free.
- Branchless masked compute (status codes) instead of data-dependent control
  flow (`scripts/1_baseline.jl:147-163`, `src/baseline/solver.jl:341-372`).
- vmap over economic parameters and shard_map over a `jax.sharding.Mesh` for
  comparative-statics sweeps (`scripts/1_baseline.jl:224-267` is a sequential
  double loop in the reference).

Subpackage map (reference component in parens):

- ``core``     — numerics substrate: interpolation, quadrature, crossing
                 detection, bisection, fixed-step ODE integrators.
- ``models``   — validated parameter/result pytrees (``src/baseline/model.jl``,
                 ``*_model.jl``).
- ``baseline`` — Stage 1-3 pipeline (``learning.jl``, ``solver.jl``).
- ``hetero``   — K-group heterogeneous learning speeds
                 (``extensions/heterogeneity/``).
- ``interest`` — positive interest rates via HJB value function
                 (``extensions/interest_rates/``).
- ``social``   — social learning: damped fixed point
                 (``extensions/social_learning/``) plus the explicit-agent
                 graph simulation (new capability).
- ``sweeps``   — vmapped / mesh-sharded comparative statics
                 (``scripts/1_baseline.jl`` sweeps).
- ``scenario`` — composable scenario engine (ISSUE 14): `ScenarioSpec`
                 pipelines — learning transformer × ordered hazard/policy
                 modifiers × N-bank contagion on an interbank exposure
                 network — with bit-identical legacy reductions and spec
                 fingerprints keyed through every cache (new capability).
- ``grad``     — differentiable equilibria: implicit-function-theorem
                 dξ/dθ through the fixed point (custom-JVP root rules),
                 sensitivity surfaces, withdrawal-curve calibration, and
                 gradient-based worst-case stress search (new capability).
- ``diag``     — in-jit numerical-health diagnostics: the `Health` pytree
                 (residuals, bracket widths, NaN/fallback flags) threaded
                 through every solver stack and sweep (new capability).
- ``obs``      — run telemetry: event logs, stage spans, jit attribution,
                 metrics, the report/health/gc CLI (new capability).
- ``resilience`` — deterministic fault injection, the unified retry
                 engine, self-healing checkpoints (sidecars, quarantine,
                 degrade ladder), graceful shutdown, chaos smoke (new
                 capability).
- ``serve``    — long-lived equilibrium query engine: micro-batched
                 queries padded into the vmapped solver, LRU + on-disk
                 result cache keyed by canonical params fingerprints,
                 serialized AOT executables reloaded across restarts, and
                 live windowed telemetry (`/metrics`, `/healthz`,
                 rolling ``live.json``) (new capability).
- ``parallel`` — mesh construction, sharding specs, collective helpers.
- ``figures``  — matplotlib parity layer for the 13 reference figures
                 (``src/baseline/plotting.jl``, script-inline figures).
- ``utils``    — timing/profiling, status codes, tile checkpointing.
"""

from sbr_tpu.models.params import (
    EconomicParams,
    LearningParams,
    ModelParams,
    make_model_params,
    with_overrides,
)
from sbr_tpu.baseline.learning import solve_learning
from sbr_tpu.baseline.solver import solve_equilibrium_baseline

__version__ = "0.1.0"


def enable_x64() -> None:
    """Enable float64 end to end (the reference runs at machine-eps float64).

    TPU executes f64 with a throughput penalty; the big sweeps default to f32
    with a re-derived tolerance ladder, while parity/correctness paths call
    this first.
    """
    import jax

    jax.config.update("jax_enable_x64", True)
