"""HJB value-function solver (reference `value_function_solver.jl:66-112`).

V(τ̄) satisfies, in reversed time τ̄ = ξ* − τ,

    V'(τ̄) = (h(τ̄) + δ)·(1 − V(τ̄)) + max(u + r·V(τ̄) − h(τ̄), 0),
    V(0)  = (u + δ)/(r + δ),

with h the hazard rate. The reference integrates adaptively and saves on the
hazard grid (`value_function_solver.jl:105` saveat); here the grid IS the
hazard grid and integration is RK4 `lax.scan` with substeps. The max() kink
(reentry option switching on/off) is the stiffness hazard SURVEY §7.3 flags:
RK4 handles it at the default resolution because the rhs stays Lipschitz —
only its derivative jumps — so the scheme drops to O(h²) locally at the one
kink crossing, an O(h³) global contribution, far below pipeline tolerances.
"""

from __future__ import annotations

import jax.numpy as jnp

from sbr_tpu.core.interp import interp_uniform
from sbr_tpu.core.ode import rk4
from sbr_tpu.models.params import SolverConfig


def solve_value_function(
    tau_grid, hr, delta, r, u, config: SolverConfig = SolverConfig(), uniform: bool = True
):
    """Integrate the HJB forward in τ̄ over ``tau_grid``; returns V samples.

    ``hr`` are hazard samples on the same grid; inside RK4 substeps the hazard
    is evaluated by linear interpolation — the same resolution the reference's
    interpolant provides (`value_function_solver.jl:89`). ``uniform=False``
    switches to searchsorted interpolation for warped (transition-resolving)
    grids; `core.ode.rk4` already takes non-uniform save intervals, so the
    scan itself needs no change. The flag must be a static Python bool — the
    caller knows it from ``config.grid_warp`` before tracing.
    """
    dtype = hr.dtype
    delta = jnp.asarray(delta, dtype=dtype)
    r = jnp.asarray(r, dtype=dtype)
    u = jnp.asarray(u, dtype=dtype)
    t0 = tau_grid[0]
    dt = tau_grid[1] - tau_grid[0]

    if uniform:
        hr_at = lambda t: interp_uniform(t, t0, dt, hr)
    else:
        hr_at = lambda t: jnp.interp(t, tau_grid, hr)

    v0 = (u + delta) / (r + delta)  # boundary at crash (`value_function_solver.jl:77,101`)

    def rhs(t, v, _):
        h = hr_at(t)
        reentry = jnp.maximum(u + r * v - h, 0.0)
        return (h + delta) * (1.0 - v) + reentry

    # The kink in max() halves the local order where it crosses; extra
    # substeps keep the global error budget comfortable.
    substeps = max(config.ode_substeps, 4)
    return rk4(rhs, v0, tau_grid, substeps=substeps)
