"""HJB value-function solver (reference `value_function_solver.jl:66-112`).

V(τ̄) satisfies, in reversed time τ̄ = ξ* − τ,

    V'(τ̄) = (h(τ̄) + δ)·(1 − V(τ̄)) + max(u + r·V(τ̄) − h(τ̄), 0),
    V(0)  = (u + δ)/(r + δ),

with h the hazard rate. The reference integrates adaptively and saves on the
hazard grid (`value_function_solver.jl:105` saveat); here the grid IS the
hazard grid and integration is RK4 `lax.scan` with substeps. The max() kink
(reentry option switching on/off) is the stiffness hazard SURVEY §7.3 flags:
RK4 handles it at the default resolution because the rhs stays Lipschitz —
only its derivative jumps — so the scheme drops to O(h²) locally at the one
kink crossing, an O(h³) global contribution, far below pipeline tolerances.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from sbr_tpu.core.interp import interp_guided, interp_uniform
from sbr_tpu.core.ode import bs32
from sbr_tpu.models.params import SolverConfig


def solve_value_function(
    tau_grid,
    hr,
    delta,
    r,
    u,
    config: SolverConfig | None = None,
    uniform: bool = True,
    index_fn=None,
    with_health: bool = False,
):
    """Integrate the HJB forward in τ̄ over ``tau_grid``; returns V samples.

    ``hr`` are hazard samples on the same grid; inside RK4 substeps the hazard
    is evaluated by linear interpolation — the same resolution the reference's
    interpolant provides (`value_function_solver.jl:89`). ``uniform=False``
    switches to non-uniform (warped, transition-resolving) grids;
    `core.ode.rk4` already takes non-uniform save intervals, so the scan
    itself needs no change. ``index_fn`` (t → bracketing-index guess, e.g.
    `baseline.solver.warped_grid_index`) replaces searchsorted with O(1)
    arithmetic + `interp_guided` — the hazard lookup sits inside the RK4
    scan's sequential substeps, where searchsorted's ~10 dependent gathers
    per evaluation were the measured 3.7× cost of honoring the warp in the
    (β,u,r) policy sweep. Both flags must be static at trace time.

    ``with_health`` appends a `diag.Health`: under adaptive numerics it is
    bs32's (whose ODE_BUDGET flag is the ONLY sign an interval exhausted its
    step cap and bridged unchecked — invisible in V itself); the fixed path
    returns a zero-flag placeholder because its failure modes (non-finite V)
    are already covered by the caller's finiteness probe, keeping fixed-mode
    health bytes identical to the pre-adaptive solver.
    """
    if config is None:
        config = SolverConfig()
    dtype = hr.dtype
    delta = jnp.asarray(delta, dtype=dtype)
    r = jnp.asarray(r, dtype=dtype)
    u = jnp.asarray(u, dtype=dtype)
    t0 = tau_grid[0]
    dt = tau_grid[1] - tau_grid[0]

    if uniform:
        hr_at = lambda t: interp_uniform(t, t0, dt, hr)
    elif index_fn is not None:
        hr_at = lambda t: interp_guided(t, tau_grid, hr, index_fn(t))
    else:
        hr_at = lambda t: jnp.interp(t, tau_grid, hr)

    v0 = (u + delta) / (r + delta)  # boundary at crash (`value_function_solver.jl:77,101`)

    def rhs_at(hv, v):
        return (hv + delta) * (1.0 - v) + jnp.maximum(u + r * v - hv, 0.0)

    if config.adaptive:
        # Adaptive embedded-pair integration (ISSUE 9): replaces the static
        # max(ode_substeps, 4) worst-case budget — the budget existed for
        # the one reentry kink where max() switches, but every interval
        # paid it. bs32's error control subdivides the kink-crossing
        # interval and single-steps the smooth rest. Hazard lookups here
        # are data-dependent (adaptive node times), so they interpolate
        # in-loop; the hoisted-node trick below is specific to the static
        # fixed-step schedule.
        return bs32(
            lambda t, v, _: rhs_at(hr_at(t), v),
            v0,
            tau_grid,
            rtol=config.ode_rtol,
            atol=config.ode_atol,
            with_health=with_health,
        )

    # The kink in max() halves the local order where it crosses; extra
    # substeps keep the global error budget comfortable.
    substeps = max(config.ode_substeps, 4)

    # Every RK4 stage time is a STATIC function of the save grid, so the
    # hazard lookups — the only data-dependent reads in the rhs — are
    # hoisted out of the sequential scan and evaluated VECTORIZED here
    # (throughput-bound), leaving the scan body pure arithmetic. Interp
    # inside the scan was a ~3-10-deep dependent-gather chain per stage
    # (uniform index math or warped searchsorted/guided), serialized 16×
    # per grid interval; hoisting it is most of the warp-honoring policy
    # sweep's recovery toward the uniform-grid throughput. Node times are
    # written with the exact FP associations the in-scan path used
    # (t = t0 + j·h; t + 0.5·h; t + h), so results are bit-identical.
    t0s = tau_grid[:-1]
    h = (tau_grid[1:] - t0s) / substeps
    tj = t0s[:, None] + jnp.arange(substeps, dtype=dtype) * h[:, None]  # (n-1, s)
    nodes = jnp.stack([tj, tj + 0.5 * h[:, None], tj + h[:, None]], axis=-1)
    hr_nodes = hr_at(nodes)  # (n-1, s, 3), one vectorized interp

    def interval(v, xs):
        hstep, hrow = xs
        for j in range(substeps):  # static unroll: all node reads static
            h1, hm, h2 = hrow[j, 0], hrow[j, 1], hrow[j, 2]
            k1 = rhs_at(h1, v)
            k2 = rhs_at(hm, v + 0.5 * hstep * k1)
            k3 = rhs_at(hm, v + 0.5 * hstep * k2)
            k4 = rhs_at(h2, v + hstep * k3)
            v = v + (hstep / 6.0) * (k1 + 2.0 * k2 + 2.0 * k3 + k4)
        return v, v

    _, vs = lax.scan(interval, v0, (h, hr_nodes))
    out = jnp.concatenate([v0[None], vs], axis=0)
    if not with_health:
        return out
    from sbr_tpu.diag.health import Health

    return out, Health.of_flags(jnp.int32(0), dtype)
