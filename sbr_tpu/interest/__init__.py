"""Positive-interest-rate extension (reference
`src/extensions/interest_rates/`).

Design (mirroring the reference's substitution trick,
`interest_rate_solver.jl:26-29`): solve the HJB value function V(τ̄) on the
hazard grid, replace the buffer threshold curve with the effective hazard
h − rV, and run the baseline Stages 2-3 machinery unchanged. Because V at
r=0 makes h − rV ≡ h, the r=0 fallback branch of the reference
(`interest_rate_solver.jl:89-101`) is the identity here — no branch needed,
which keeps the solver vmappable over r for policy sweeps.
"""

from sbr_tpu.interest.solver import solve_equilibrium_interest
from sbr_tpu.interest.value_function import solve_value_function

__all__ = ["solve_equilibrium_interest", "solve_value_function"]
