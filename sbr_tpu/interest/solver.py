"""Equilibrium with positive interest rates (reference
`interest_rate_solver.jl:51-150`).

Pipeline: baseline hazard → HJB value function on the hazard grid → effective
hazard h − rV for the buffer crossings → baseline ξ bisection and AW curves
unchanged. The reference branches on r>0 (`interest_rate_solver.jl:71-101`);
here V is always computed — at r=0 the effective hazard is identically h, so
the baseline fallback is algebraic rather than a code path, and r stays a
traced value (vmappable for (β,u,r) policy sweeps, BASELINE.md stretch
config).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from sbr_tpu.baseline.solver import (
    _hazard_parts,
    compute_xi,
    get_aw,
    hazard_grid_is_uniform,
    optimal_buffer,
    warped_grid_index,
)
from sbr_tpu.core.interp import interp_guided, interp_uniform
from sbr_tpu.interest.value_function import solve_value_function
from sbr_tpu.models.params import EconomicParamsInterest, SolverConfig
from sbr_tpu.models.results import EquilibriumResult, LearningSolution, Status


@struct.dataclass
class EquilibriumResultInterest:
    """Reference `SolvedModelInterest` (`interest_rate_model.jl:200-245`):
    baseline result + the value function and effective hazard on tau_grid."""

    base: EquilibriumResult
    v: jnp.ndarray  # (n,) value function V(τ̄) on tau_grid
    hr_effective: jnp.ndarray  # (n,) h − rV used for the buffer crossings

    def __repr__(self) -> str:
        from sbr_tpu.models.results import _fmt

        return (
            f"EquilibriumResultInterest(ξ={_fmt(self.base.xi)}, "
            f"bankrun={_fmt(self.base.bankrun)}, status={_fmt(self.base.status)}, "
            f"V(0)={_fmt(self.v[..., 0])}, solve_time={_fmt(self.base.solve_time, 3)}s)"
        )


def solve_equilibrium_interest_core(
    ls: LearningSolution,
    u,
    p,
    kappa,
    lam,
    eta,
    r,
    delta,
    tspan_end,
    config: SolverConfig | None = None,
) -> EquilibriumResultInterest:
    """Scalar-parameter interest-rate solve — the vmap unit for policy sweeps."""
    if config is None:
        config = SolverConfig()
    from sbr_tpu import obs

    dtype = ls.cdf.dtype
    u = jnp.asarray(u, dtype=dtype)
    r = jnp.asarray(r, dtype=dtype)
    nan = jnp.asarray(jnp.nan, dtype=dtype)

    # The hazard grid may be warped (transition-resolving, round-4 high-β
    # fix); both the HJB scan (non-uniform RK4 intervals + searchsorted
    # hazard interp) and V's evaluator below follow the grid, so a high-β
    # (β,u,r) policy sweep resolves the logistic transition exactly like
    # the baseline sweep does. ``warped`` is static (config is concrete at
    # trace time), so the uniform fast path costs nothing when warp is off.
    warped = not hazard_grid_is_uniform(ls, config)
    with obs.span("interest.hazard") as sp:
        tau_grid, hr, integ, int_eta = _hazard_parts(p, lam, ls, eta, config)
        sp.sync(hr)
    index_fn = None
    if warped:
        eta_c = jnp.asarray(eta, dtype=dtype)
        index_fn = lambda t: warped_grid_index(
            t, eta_c, ls.beta, ls.x0, config.n_grid, config.grid_warp
        )
    with obs.span("interest.value_function") as sp:
        v, v_health = solve_value_function(
            tau_grid, hr, delta, r, u, config, uniform=not warped, index_fn=index_fn,
            with_health=True,
        )
        sp.sync(v)
    hr_eff = hr - r * v  # `interest_rate_solver.jl:80-83`

    # Buffer crossings against the EFFECTIVE hazard (`interest_rate_solver.jl:88`).
    # With closed-form Stage 1, crossings refine against the exact hazard
    # minus r·V̂ (V linearly interpolated — it is known only on the grid);
    # at r = 0 this is bit-identical to the baseline's refined path, the
    # reference's r=0 fallback oracle (`interest_rate_solver.jl:89-101`).
    hazard_eff_at = None
    if ls.closed_form and config.refine_crossings:
        from sbr_tpu.baseline.solver import _make_hazard_at

        hazard_at = _make_hazard_at(p, lam, ls, tau_grid, integ, int_eta, config)
        t0 = tau_grid[0]
        dt = tau_grid[1] - tau_grid[0]
        if warped:
            v_at = lambda tau: interp_guided(tau, tau_grid, v, index_fn(tau))
        else:
            v_at = lambda tau: interp_uniform(tau, t0, dt, v)

        def hazard_eff_at(tau):
            return hazard_at(tau) - r * v_at(tau)

    with obs.span("interest.buffers") as sp:
        tau_in_unc, tau_out_unc, cross_health = optimal_buffer(
            u, tau_grid, hr_eff, tspan_end, hazard_at=hazard_eff_at, with_health=True,
            adaptive=config.adaptive,
        )
        sp.sync(tau_in_unc, tau_out_unc)
    no_crossing = tau_in_unc == tau_out_unc

    # ξ and AW use the baseline machinery on the word-of-mouth CDF unchanged
    # (`interest_rate_solver.jl:122`, `get_AW_functions_interest!:161-184`).
    with obs.span("interest.xi") as sp:
        xi_c, err, root_ok, increasing, xi_health = compute_xi(
            tau_in_unc, tau_out_unc, ls, kappa, config, with_health=True
        )
        sp.sync(xi_c)

    # Value-function finiteness probe: the HJB scan has no adaptive-solver
    # divergence exit, so a blown-up V would silently poison the effective
    # hazard — flag it (the crossing health already catches the NaN case
    # via hr_eff, this adds the Inf case and attributes it to V).
    from sbr_tpu.diag.health import NAN_OUTPUT, Health

    # ODE flags ride along (ISSUE 9): under adaptive numerics this is how
    # ODE_BUDGET — an interval that exhausted its step cap and bridged with
    # an error-unchecked step — reaches the per-cell health; the fixed
    # path's v_health carries zero flags by contract, so fixed-mode health
    # bytes are unchanged. Flags only: the HJB's attempt counts must not
    # perturb the root-find effective-iteration statistics.
    v_flags = jnp.where(
        jnp.any(~jnp.isfinite(v)), jnp.int32(NAN_OUTPUT), jnp.int32(0)
    ) | v_health.flags
    health = cross_health.merge(xi_health, Health.of_flags(v_flags, dtype))

    run = jnp.logical_and(~no_crossing, jnp.logical_and(root_ok, increasing))
    status = jnp.where(
        no_crossing,
        Status.NO_CROSSING,
        jnp.where(
            ~root_ok,
            Status.NO_ROOT,
            jnp.where(increasing, Status.RUN, Status.FALSE_EQ),
        ),
    ).astype(jnp.int32)

    xi = jnp.where(run, xi_c, nan)
    converged = jnp.logical_or(no_crossing, run)
    tolerance = jnp.where(
        no_crossing, jnp.zeros((), dtype), jnp.where(run, err, jnp.asarray(jnp.inf, dtype))
    )

    aw_cum, aw_out, aw_in = get_aw(xi, tau_in_unc, tau_out_unc, tau_grid, ls)
    aw_cum = jnp.where(run, aw_cum, nan)
    aw_out = jnp.where(run, aw_out, nan)
    aw_in = jnp.where(run, aw_in, nan)

    base = EquilibriumResult(
        xi=xi,
        tau_bar_in_unc=tau_in_unc,
        tau_bar_out_unc=tau_out_unc,
        tau_in=jnp.maximum(xi - tau_in_unc, 0.0),
        tau_out=jnp.maximum(xi - tau_out_unc, 0.0),
        bankrun=run,
        status=status,
        converged=converged,
        tolerance=tolerance,
        tau_grid=tau_grid,
        hr=hr,
        aw_cum=aw_cum,
        aw_out=aw_out,
        aw_in=aw_in,
        aw_max=jnp.where(run, jnp.max(aw_cum), nan),
        health=health,
    )
    return EquilibriumResultInterest(base=base, v=v, hr_effective=hr_eff)


def solve_equilibrium_interest(
    ls: LearningSolution,
    econ: EconomicParamsInterest,
    config: SolverConfig | None = None,
    tspan_end=None,
) -> EquilibriumResultInterest:
    """Convenience entry mirroring `solve_equilibrium_interest(lr, econ, model)`
    (`interest_rate_solver.jl:51`). The embedded baseline result carries
    device-fenced ``solve_time`` like the reference's structs."""
    if config is None:
        config = SolverConfig()
    import time

    from sbr_tpu.baseline.solver import _stamp_solve_time

    from sbr_tpu import obs

    t0 = time.perf_counter()
    if tspan_end is None:
        tspan_end = ls.grid[-1]
    # Whole-solve span (parity with baseline.equilibrium): the per-stage
    # spans inside the core nest under it, and with SBR_OBS_PROFILE=1 the
    # solve is one TraceAnnotation block on the profiler timeline.
    with obs.span("interest.equilibrium") as sp:
        res = solve_equilibrium_interest_core(
            ls,
            econ.u,
            econ.p,
            econ.kappa,
            econ.lam,
            econ.eta,
            econ.r,
            econ.delta,
            tspan_end,
            config,
        )
        sp.sync(res.base.xi, res.base.status)
    res = res.replace(base=_stamp_solve_time(res.base, t0))
    obs.log_health("interest.equilibrium", res.base.health, res.base.status)
    return res
