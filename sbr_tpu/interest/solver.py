"""Equilibrium with positive interest rates (reference
`interest_rate_solver.jl:51-150`).

Pipeline: baseline hazard → HJB value function on the hazard grid → effective
hazard h − rV for the buffer crossings → baseline ξ bisection and AW curves
unchanged. The reference branches on r>0 (`interest_rate_solver.jl:71-101`);
here V is always computed — at r=0 the effective hazard is identically h, so
the baseline fallback is algebraic rather than a code path, and r stays a
traced value (vmappable for (β,u,r) policy sweeps, BASELINE.md stretch
config).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from sbr_tpu.baseline.solver import (
    _hazard_parts,
    classify_cell,
    compute_xi,
    get_aw,
    hazard_grid_is_uniform,
    optimal_buffer,
    warped_grid_index,
)
from sbr_tpu.core.interp import interp_guided, interp_uniform
from sbr_tpu.interest.value_function import solve_value_function
from sbr_tpu.models.params import EconomicParamsInterest, SolverConfig
from sbr_tpu.models.results import EquilibriumResult, LearningSolution


@struct.dataclass
class EquilibriumResultInterest:
    """Reference `SolvedModelInterest` (`interest_rate_model.jl:200-245`):
    baseline result + the value function and effective hazard on tau_grid."""

    base: EquilibriumResult
    v: jnp.ndarray  # (n,) value function V(τ̄) on tau_grid
    hr_effective: jnp.ndarray  # (n,) h − rV used for the buffer crossings

    def __repr__(self) -> str:
        from sbr_tpu.models.results import _fmt

        return (
            f"EquilibriumResultInterest(ξ={_fmt(self.base.xi)}, "
            f"bankrun={_fmt(self.base.bankrun)}, status={_fmt(self.base.status)}, "
            f"V(0)={_fmt(self.v[..., 0])}, solve_time={_fmt(self.base.solve_time, 3)}s)"
        )


def effective_hazard_stage(
    tau_grid,
    hr,
    r,
    delta,
    u,
    config: SolverConfig,
    hazard_at=None,
    uniform: bool = True,
    index_fn=None,
):
    """The interest-rate stack's hazard transformer, factored out of
    `solve_equilibrium_interest_core` (ISSUE 14) so the composable scenario
    engine can splice the SAME stage into any composed pipeline:

    HJB value function V on the hazard grid → effective hazard h − r·V,
    plus — when the caller supplies a continuous ``hazard_at`` — the
    matching continuous effective-hazard evaluator (V linearly
    interpolated; it is known only on the grid). ``uniform``/``index_fn``
    select the V interpolation exactly as the legacy core does (uniform
    stride, warped-index guided, or searchsorted).

    Returns ``(hr_eff, hazard_eff_at, v, v_health)`` where ``v_health``
    carries the HJB ODE flags plus the V-finiteness probe (`NAN_OUTPUT` on
    a blown-up V) — the same flag set the legacy core merged by hand.
    """
    from sbr_tpu.diag.health import NAN_OUTPUT, Health

    dtype = hr.dtype
    r = jnp.asarray(r, dtype=dtype)
    v, ode_health = solve_value_function(
        tau_grid, hr, delta, r, u, config, uniform=uniform, index_fn=index_fn,
        with_health=True,
    )
    hr_eff = hr - r * v  # `interest_rate_solver.jl:80-83`

    hazard_eff_at = None
    if hazard_at is not None:
        t0 = tau_grid[0]
        dt = tau_grid[1] - tau_grid[0]
        if uniform:
            v_at = lambda tau: interp_uniform(tau, t0, dt, v)
        elif index_fn is not None:
            v_at = lambda tau: interp_guided(tau, tau_grid, v, index_fn(tau))
        else:
            v_at = lambda tau: jnp.interp(tau, tau_grid, v)

        def hazard_eff_at(tau):
            return hazard_at(tau) - r * v_at(tau)

    # Value-function finiteness probe: the HJB scan has no adaptive-solver
    # divergence exit, so a blown-up V would silently poison the effective
    # hazard — flag it. Flags only: the HJB's attempt counts must not
    # perturb the root-find effective-iteration statistics (ISSUE 9).
    v_flags = jnp.where(
        jnp.any(~jnp.isfinite(v)), jnp.int32(NAN_OUTPUT), jnp.int32(0)
    ) | ode_health.flags
    return hr_eff, hazard_eff_at, v, Health.of_flags(v_flags, dtype)


def solve_equilibrium_interest_core(
    ls: LearningSolution,
    u,
    p,
    kappa,
    lam,
    eta,
    r,
    delta,
    tspan_end,
    config: SolverConfig | None = None,
) -> EquilibriumResultInterest:
    """Scalar-parameter interest-rate solve — the vmap unit for policy sweeps."""
    if config is None:
        config = SolverConfig()
    from sbr_tpu import obs

    dtype = ls.cdf.dtype
    u = jnp.asarray(u, dtype=dtype)
    r = jnp.asarray(r, dtype=dtype)
    nan = jnp.asarray(jnp.nan, dtype=dtype)

    # The hazard grid may be warped (transition-resolving, round-4 high-β
    # fix); both the HJB scan (non-uniform RK4 intervals + searchsorted
    # hazard interp) and V's evaluator below follow the grid, so a high-β
    # (β,u,r) policy sweep resolves the logistic transition exactly like
    # the baseline sweep does. ``warped`` is static (config is concrete at
    # trace time), so the uniform fast path costs nothing when warp is off.
    warped = not hazard_grid_is_uniform(ls, config)
    with obs.span("interest.hazard") as sp:
        tau_grid, hr, integ, int_eta = _hazard_parts(p, lam, ls, eta, config)
        sp.sync(hr)
    index_fn = None
    if warped:
        eta_c = jnp.asarray(eta, dtype=dtype)
        index_fn = lambda t: warped_grid_index(
            t, eta_c, ls.beta, ls.x0, config.n_grid, config.grid_warp
        )

    # Continuous exact hazard for crossing refinement (closed-form Stage 1
    # only): the effective-hazard stage subtracts r·V̂ from it, so at r = 0
    # the refined path is bit-identical to the baseline's — the reference's
    # r=0 fallback oracle (`interest_rate_solver.jl:89-101`).
    hazard_at = None
    if ls.closed_form and config.refine_crossings:
        from sbr_tpu.baseline.solver import _make_hazard_at

        hazard_at = _make_hazard_at(p, lam, ls, tau_grid, integ, int_eta, config)

    with obs.span("interest.value_function") as sp:
        hr_eff, hazard_eff_at, v, v_health = effective_hazard_stage(
            tau_grid, hr, r, delta, u, config, hazard_at=hazard_at,
            uniform=not warped, index_fn=index_fn,
        )
        sp.sync(v)

    # Buffer crossings against the EFFECTIVE hazard (`interest_rate_solver.jl:88`).
    with obs.span("interest.buffers") as sp:
        tau_in_unc, tau_out_unc, cross_health = optimal_buffer(
            u, tau_grid, hr_eff, tspan_end, hazard_at=hazard_eff_at, with_health=True,
            adaptive=config.adaptive,
        )
        sp.sync(tau_in_unc, tau_out_unc)
    no_crossing = tau_in_unc == tau_out_unc

    # ξ and AW use the baseline machinery on the word-of-mouth CDF unchanged
    # (`interest_rate_solver.jl:122`, `get_AW_functions_interest!:161-184`).
    with obs.span("interest.xi") as sp:
        xi_c, err, root_ok, increasing, xi_health = compute_xi(
            tau_in_unc, tau_out_unc, ls, kappa, config, with_health=True
        )
        sp.sync(xi_c)

    # v_health carries the HJB ODE flags (ISSUE 9) plus the V-finiteness
    # probe (see `effective_hazard_stage`); merged last, preserving the
    # legacy merge order byte-for-byte.
    health = cross_health.merge(xi_health, v_health)

    run, status, converged, tolerance = classify_cell(
        no_crossing, root_ok, increasing, err, dtype
    )
    xi = jnp.where(run, xi_c, nan)

    aw_cum, aw_out, aw_in = get_aw(xi, tau_in_unc, tau_out_unc, tau_grid, ls)
    aw_cum = jnp.where(run, aw_cum, nan)
    aw_out = jnp.where(run, aw_out, nan)
    aw_in = jnp.where(run, aw_in, nan)

    base = EquilibriumResult(
        xi=xi,
        tau_bar_in_unc=tau_in_unc,
        tau_bar_out_unc=tau_out_unc,
        tau_in=jnp.maximum(xi - tau_in_unc, 0.0),
        tau_out=jnp.maximum(xi - tau_out_unc, 0.0),
        bankrun=run,
        status=status,
        converged=converged,
        tolerance=tolerance,
        tau_grid=tau_grid,
        hr=hr,
        aw_cum=aw_cum,
        aw_out=aw_out,
        aw_in=aw_in,
        aw_max=jnp.where(run, jnp.max(aw_cum), nan),
        health=health,
    )
    return EquilibriumResultInterest(base=base, v=v, hr_effective=hr_eff)


def solve_equilibrium_interest(
    ls: LearningSolution,
    econ: EconomicParamsInterest,
    config: SolverConfig | None = None,
    tspan_end=None,
) -> EquilibriumResultInterest:
    """Convenience entry mirroring `solve_equilibrium_interest(lr, econ, model)`
    (`interest_rate_solver.jl:51`). The embedded baseline result carries
    device-fenced ``solve_time`` like the reference's structs."""
    if config is None:
        config = SolverConfig()
    import time

    from sbr_tpu.baseline.solver import _stamp_solve_time

    from sbr_tpu import obs

    t0 = time.perf_counter()
    if tspan_end is None:
        tspan_end = ls.grid[-1]
    # Whole-solve span (parity with baseline.equilibrium): the per-stage
    # spans inside the core nest under it, and with SBR_OBS_PROFILE=1 the
    # solve is one TraceAnnotation block on the profiler timeline.
    with obs.span("interest.equilibrium") as sp:
        res = solve_equilibrium_interest_core(
            ls,
            econ.u,
            econ.p,
            econ.kappa,
            econ.lam,
            econ.eta,
            econ.r,
            econ.delta,
            tspan_end,
            config,
        )
        sp.sync(res.base.xi, res.base.status)
    res = res.replace(base=_stamp_solve_time(res.base, t0))
    obs.log_health("interest.equilibrium", res.base.health, res.base.status)
    return res
