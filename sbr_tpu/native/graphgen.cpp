// Native host-side graph preprocessing for the explicit-agent simulation.
//
// The TPU kernel (sbr_tpu/social/agents.py) consumes dst-sorted edge lists
// with a row-pointer table. Sorting 10^8 edges with numpy argsort is an
// O(E log E) comparison sort costing tens of seconds on the host; destination
// ids are small integers, so a counting sort builds the sorted edges, the
// row-pointer table, and the in-degree vector in one O(E + N) pass.
//
// The reference package has no native code at all (SURVEY §2 — pure Julia);
// this layer is the rebuild's native runtime component for the data-loading
// path, bound via ctypes (sbr_tpu/native/__init__.py) with a numpy fallback.
//
// Build: g++ -O3 -march=native -shared -fPIC graphgen.cpp -o libgraphgen.so

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Counting-sort edges by destination (stable in source order).
//
//   src, dst       : e input edges, dst values in [0, n)
//   src_out,dst_out: e sorted outputs
//   row_ptr        : n+1 outputs; edges of dst i occupy
//                    [row_ptr[i], row_ptr[i+1])
//   indeg          : n outputs; in-degree per destination
//
// Returns 0 on success, 1 on a dst id out of range.
int sort_edges_by_dst(const int32_t* src, const int32_t* dst, int64_t e,
                      int32_t n, int32_t* src_out, int32_t* dst_out,
                      int64_t* row_ptr, int32_t* indeg) {
  std::vector<int64_t> count(static_cast<size_t>(n) + 1, 0);
  for (int64_t i = 0; i < e; ++i) {
    const int32_t d = dst[i];
    if (d < 0 || d >= n) return 1;
    ++count[static_cast<size_t>(d) + 1];
  }
  for (int32_t i = 0; i < n; ++i) {
    indeg[i] = static_cast<int32_t>(count[static_cast<size_t>(i) + 1]);
    count[static_cast<size_t>(i) + 1] += count[i];
  }
  std::memcpy(row_ptr, count.data(), (static_cast<size_t>(n) + 1) * sizeof(int64_t));

  std::vector<int64_t> cursor(count.begin(), count.end() - 1);
  for (int64_t i = 0; i < e; ++i) {
    const int64_t p = cursor[dst[i]]++;
    src_out[p] = src[i];
    dst_out[p] = dst[i];
  }
  return 0;
}

// Sparse directed Erdős–Rényi edge sampling with a splitmix64 stream:
// e uniform (src, dst) pairs with self-loops re-drawn. Deterministic in seed.
void er_edges(int32_t n, int64_t e, uint64_t seed, int32_t* src, int32_t* dst) {
  uint64_t state = seed;
  auto next = [&state]() -> uint64_t {
    state += 0x9e3779b97f4a7c15ULL;
    uint64_t z = state;
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  };
  const uint64_t un = static_cast<uint64_t>(n);
  for (int64_t i = 0; i < e; ++i) {
    int32_t s = static_cast<int32_t>(next() % un);
    int32_t d = static_cast<int32_t>(next() % un);
    while (d == s && n > 1) d = static_cast<int32_t>(next() % un);
    src[i] = s;
    dst[i] = d;
  }
}

}  // extern "C"
