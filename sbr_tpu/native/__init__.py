"""Native host-side runtime components, bound via ctypes.

The reference package is pure Julia with no native layer (SURVEY §2); the
rebuild's accelerator path is XLA, and this package covers the host side of
the data pipeline where numpy is the bottleneck: graph preprocessing for
the 10^6-agent simulation (counting sort of 10^8-edge lists, O(E + N),
replacing numpy's O(E log E) argsort).

The shared library compiles lazily on first use with the system g++ and is
cached next to the source, keyed by source mtime. Every entry point has a
pure-numpy fallback, so the framework works identically (slower) where no
compiler is available. `sbr_tpu.social.agents` picks these up
automatically.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_SRC = Path(__file__).parent / "graphgen.cpp"
_LIB_NAME = "libsbr_graphgen.so"
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _build_lib() -> Optional[ctypes.CDLL]:
    """Compile (if stale/missing) and load the native library; None on any
    failure — callers fall back to numpy."""
    cache_dir = Path(
        os.environ.get("SBR_TPU_NATIVE_CACHE", Path.home() / ".cache" / "sbr_tpu_native")
    )
    try:
        cache_dir.mkdir(parents=True, exist_ok=True)
        lib_path = cache_dir / _LIB_NAME
        if not lib_path.exists() or lib_path.stat().st_mtime < _SRC.stat().st_mtime:
            with tempfile.TemporaryDirectory(dir=cache_dir) as tmp:
                tmp_so = Path(tmp) / _LIB_NAME
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", str(_SRC), "-o", str(tmp_so)],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp_so, lib_path)
        lib = ctypes.CDLL(str(lib_path))
    except Exception:
        return None

    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.sort_edges_by_dst.restype = ctypes.c_int
    lib.sort_edges_by_dst.argtypes = [
        i32p, i32p, ctypes.c_int64, ctypes.c_int32, i32p, i32p, i64p, i32p,
    ]
    lib.er_edges.restype = None
    lib.er_edges.argtypes = [ctypes.c_int32, ctypes.c_int64, ctypes.c_uint64, i32p, i32p]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if os.environ.get("SBR_NATIVE", "").strip() == "0":
        # Checked per call (not once) so a bench can measure the portable
        # numpy fallback alongside the native path in one process.
        return None
    if not _lib_tried:
        _lib = _build_lib()
        _lib_tried = True
    return _lib


def native_available() -> bool:
    return get_lib() is not None


def sort_edges_by_dst(
    src, dst, n: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Sort edges by destination; return (src, dst, indeg, row_ptr).

    Stable in source order, matching ``np.argsort(dst, kind="stable")``.
    ``row_ptr`` is int64 of length n+1 with edges of dst i in
    [row_ptr[i], row_ptr[i+1]). Uses the native counting sort when the
    library is available, numpy otherwise.
    """
    src = np.ascontiguousarray(src, dtype=np.int32)
    dst = np.ascontiguousarray(dst, dtype=np.int32)
    e = src.shape[0]

    lib = get_lib()
    if lib is not None:
        src_out = np.empty(e, np.int32)
        dst_out = np.empty(e, np.int32)
        row_ptr = np.empty(n + 1, np.int64)
        indeg = np.empty(n, np.int32)
        rc = lib.sort_edges_by_dst(src, dst, e, n, src_out, dst_out, row_ptr, indeg)
        if rc != 0:
            raise ValueError(f"dst ids out of range [0, {n})")
        return src_out, dst_out, indeg, row_ptr

    if e and (dst.min() < 0 or dst.max() >= n):  # match the native path's check
        raise ValueError(f"dst ids out of range [0, {n})")
    order = np.argsort(dst, kind="stable")
    src_out, dst_out = src[order], dst[order]
    indeg = np.bincount(dst, minlength=n).astype(np.int32)
    row_ptr = np.zeros(n + 1, np.int64)
    np.cumsum(indeg, out=row_ptr[1:])
    return src_out, dst_out, indeg, row_ptr


def er_edges_native(n: int, e: int, seed: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native uniform edge sampling (self-loops re-drawn); None when the
    library is unavailable. Deterministic in seed, independent of numpy's
    RNG stream."""
    lib = get_lib()
    if lib is None:
        return None
    src = np.empty(e, np.int32)
    dst = np.empty(e, np.int32)
    lib.er_edges(n, e, seed, src, dst)
    return src, dst
