"""Numerics substrate shared by every solver stage.

Replaces the reference's Interpolations.jl objects + adaptive-grid idioms
(`src/baseline/solver.jl:153-264`, `src/baseline/learning.jl:52`) with
static-shape, jit/vmap-safe primitives.
"""

from sbr_tpu.core.interp import interp, interp_guided, interp_uniform
from sbr_tpu.core.integrate import cumtrapz, cumulative_gauss_legendre, trapz
from sbr_tpu.core.rootfind import (
    bisect,
    chandrupatla,
    first_upcrossing,
    last_downcrossing,
    threshold_crossings,
    threshold_crossings_masked,
)
from sbr_tpu.core.ode import bs32, rk4
