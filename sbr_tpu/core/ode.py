"""Fixed-step and adaptive ODE integration as `lax.scan`.

The reference integrates everything with adaptive AutoTsit5(Rosenbrock23()) at
machine-eps tolerance (`src/baseline/learning.jl:51`,
`heterogeneity_learning.jl:74`, `value_function_solver.jl:105`). Adaptive
stepping produces dynamic shapes, which poison jit/vmap; here every solve uses
a static save grid with optional uniform substeps for accuracy. RK4 on a
2-4k-point grid delivers ~1e-10 global error on these smooth dynamics — below
every downstream tolerance in the pipeline.

`bs32` (ISSUE 9) recovers the reference's adaptive economics WITHOUT dynamic
shapes: a Bogacki–Shampine 3(2) embedded pair marches each save interval with
PI step-size control inside a budget-capped `lax.while_loop` (torchode's
per-instance stepping idea, arXiv:2210.12375, restricted to a fixed save
grid by clamping steps to the next save point). Smooth regions take one
cheap step per interval where the fixed path pays its worst-case substep
budget; the few stiff intervals subdivide until the local error passes.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from sbr_tpu.diag.health import ODE_BUDGET, Health


def rk4(f, y0, ts, args=None, substeps: int = 1, with_health: bool = False):
    """Integrate dy/dt = f(t, y, args) over save grid ``ts`` with classic RK4.

    - ``y0``: initial state, any array shape (scalar ODEs pass a 0-d array).
    - ``ts``: shape (n,) save points; integration uses ``substeps`` uniform
      RK4 steps inside each interval.
    - Returns ys with shape (n, *y0.shape); ys[0] == y0. With ``with_health``
      returns ``(ys, Health)`` flagging NaN in the initial state / grid and
      non-finite values anywhere in the trajectory (a blown-up fixed-step
      integration has no tolerance exit to catch it otherwise); iterations
      records the total micro-steps taken.
    """
    y0 = jnp.asarray(y0)
    ts = jnp.asarray(ts)

    def interval(y, tpair):
        t0, t1 = tpair
        h = (t1 - t0) / substeps

        def micro(i, y):
            t = t0 + i * h
            k1 = f(t, y, args)
            k2 = f(t + 0.5 * h, y + 0.5 * h * k1, args)
            k3 = f(t + 0.5 * h, y + 0.5 * h * k2, args)
            k4 = f(t + h, y + h * k3, args)
            return y + (h / 6.0) * (k1 + 2.0 * k2 + 2.0 * k3 + k4)

        y1 = lax.fori_loop(0, substeps, micro, y)
        return y1, y1

    tpairs = jnp.stack([ts[:-1], ts[1:]], axis=1)
    _, ys = lax.scan(interval, y0, tpairs)
    out = jnp.concatenate([y0[None], ys], axis=0)
    if not with_health:
        return out
    health = Health.of_nan_probe(
        nan_in=jnp.any(jnp.isnan(y0)) | jnp.any(jnp.isnan(ts)),
        nonfinite_out=jnp.any(~jnp.isfinite(out)),
        iterations=(int(ts.shape[0]) - 1) * substeps,
        dtype=out.dtype,
    )
    return out, health


def bs32(
    f,
    y0,
    ts,
    args=None,
    rtol: float = 1e-6,
    atol: float = 1e-9,
    max_steps_per_interval: int = 32,
    with_health: bool = False,
):
    """Integrate dy/dt = f(t, y, args) over save grid ``ts`` with an adaptive
    Bogacki–Shampine 3(2) embedded pair (module docstring).

    Same contract as `rk4`: returns ys with shape (n, *y0.shape), ys[0] ==
    y0, save points hit EXACTLY (steps clamp to the interval end, so no
    dense-output interpolation is needed). Step size and the PI controller's
    error memory carry across intervals, so a dense save grid costs one
    attempt per interval in smooth regions. Each interval is capped at
    ``max_steps_per_interval`` attempts; on exhaustion the remainder is
    bridged with one forced (error-unchecked) step and the Health carries
    the `ODE_BUDGET` flag — the adaptive analogue of a blown fixed-step
    integration, which `with_health=False` callers would otherwise never
    see. ``iterations`` records TOTAL attempts (accepted + rejected), the
    effective cost the fixed path budgets for up front.
    """
    y0 = jnp.asarray(y0)
    ts = jnp.asarray(ts)
    dtype = y0.dtype
    rtol_ = jnp.asarray(rtol, dtype)
    atol_ = jnp.asarray(atol, dtype)
    tiny = jnp.finfo(dtype).tiny
    safety = jnp.asarray(0.9, dtype)

    def step(t, y, h, k1):
        """One BS3(2) attempt of size ``h`` from cached ``k1 = f(t, y)``:
        (y3, error_estimate, k4). FSAL: on an accepted step k4 = f(t+h, y3)
        IS the next attempt's k1, and on a rejected one (t, y) are unchanged
        so the incoming k1 stays valid — 3 fresh evaluations per accepted
        step instead of 4."""
        k2 = f(t + 0.5 * h, y + 0.5 * h * k1, args)
        k3 = f(t + 0.75 * h, y + 0.75 * h * k2, args)
        y3 = y + h * (2.0 / 9.0 * k1 + 1.0 / 3.0 * k2 + 4.0 / 9.0 * k3)
        k4 = f(t + h, y3, args)
        err = h * (5.0 / 72.0 * k1 - 1.0 / 12.0 * k2 - 1.0 / 9.0 * k3 + 1.0 / 8.0 * k4)
        return y3, err, k4

    def err_norm(err, y, y3):
        scale = atol_ + rtol_ * jnp.maximum(jnp.abs(y), jnp.abs(y3))
        r = err / scale
        return jnp.sqrt(jnp.mean(jnp.square(r)))

    def interval(carry, tpair):
        y, h, errprev, nfails, nsteps, k1 = carry
        t0, t1 = tpair
        span = t1 - t0

        def cond(st):
            t, y, h, errprev, nsteps, k1 = st
            return (t < t1) & (nsteps < max_steps_per_interval)

        def body(st):
            t, y, h, errprev, nsteps, k1 = st
            h_eff = jnp.minimum(h, t1 - t)
            y3, err, k4 = step(t, y, h_eff, k1)
            norm = jnp.maximum(err_norm(err, y, y3), tiny)
            accept = norm <= 1.0
            # PI controller (Söderlind): history-weighted factor on accept,
            # plain contraction on reject; clamped to [0.2, 2] — growth
            # capped at 2 so a save-clamped step (norm ≪ 1 because h_eff was
            # span-limited) cannot fling h into reject/accept oscillation.
            fac = safety * norm ** (-0.7 / 3.0) * errprev ** (0.4 / 3.0)
            fac = jnp.clip(fac, 0.2, 2.0)
            fac = jnp.where(accept, fac, jnp.minimum(fac, 1.0) * safety)
            t2 = jnp.where(accept, jnp.minimum(t + h_eff, t1), t)
            # pin the endpoint exactly once the clamped step lands on it
            t2 = jnp.where(accept & (h >= t1 - t), t1, t2)
            y2 = jnp.where(accept, y3, y)
            return (
                t2,
                y2,
                jnp.maximum(h_eff * fac, tiny),
                jnp.where(accept, norm, errprev),
                nsteps + 1,
                jnp.where(accept, k4, k1),
            )

        h0 = jnp.minimum(jnp.maximum(h, tiny), span)
        t_f, y_f, h_f, errprev_f, n_used, k1_f = lax.while_loop(
            cond, body, (t0, y, h0, errprev, jnp.zeros((), jnp.int32), k1)
        )
        # Budget exhausted mid-interval: bridge the remainder unchecked.
        # Behind lax.cond, not jnp.where — unconditional evaluation would
        # pay 3 f-evals per interval for a branch almost never taken,
        # doubling the smooth-regime cost the adaptive path exists to win.
        # (Under vmap XLA lowers this to both-branches + select, no worse
        # than the where; un-vmapped callers skip the bridge entirely.)
        leftover = t1 - t_f
        exhausted = leftover > 0

        def bridge():
            y3, _, k4 = step(t_f, y_f, leftover, k1_f)
            return y3, k4

        y_out, k1_out = lax.cond(exhausted, bridge, lambda: (y_f, k1_f))
        # Zero-width intervals (duplicate knots on warped grids) skip the
        # loop entirely; keep the inherited step size instead of the
        # clamped-to-zero h0 the loop init would hand back.
        return (
            y_out,
            jnp.where(span > 0, h_f, h),
            errprev_f,
            nfails + exhausted.astype(jnp.int32),
            nsteps + n_used + exhausted.astype(jnp.int32),
            k1_out,
        ), y_out

    tpairs = jnp.stack([ts[:-1], ts[1:]], axis=1)
    h_init = ts[1] - ts[0]
    carry0 = (
        y0,
        jnp.asarray(h_init, dtype),
        jnp.ones((), dtype),
        jnp.zeros((), jnp.int32),
        jnp.zeros((), jnp.int32),
        f(ts[0], y0, args),
    )
    (_, _, _, nfails, nsteps, _), ys = lax.scan(interval, carry0, tpairs)
    out = jnp.concatenate([y0[None], ys], axis=0)
    if not with_health:
        return out
    health = Health.of_nan_probe(
        nan_in=jnp.any(jnp.isnan(y0)) | jnp.any(jnp.isnan(ts)),
        nonfinite_out=jnp.any(~jnp.isfinite(out)),
        iterations=nsteps,
        dtype=out.dtype,
    )
    health = health.replace(
        flags=health.flags
        | jnp.where(nfails > 0, jnp.int32(ODE_BUDGET), jnp.int32(0))
    )
    return out, health
