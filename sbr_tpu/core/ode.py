"""Fixed-step ODE integration as `lax.scan`.

The reference integrates everything with adaptive AutoTsit5(Rosenbrock23()) at
machine-eps tolerance (`src/baseline/learning.jl:51`,
`heterogeneity_learning.jl:74`, `value_function_solver.jl:105`). Adaptive
stepping produces dynamic shapes, which poison jit/vmap; here every solve uses
a static save grid with optional uniform substeps for accuracy. RK4 on a
2-4k-point grid delivers ~1e-10 global error on these smooth dynamics — below
every downstream tolerance in the pipeline.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from sbr_tpu.diag.health import Health


def rk4(f, y0, ts, args=None, substeps: int = 1, with_health: bool = False):
    """Integrate dy/dt = f(t, y, args) over save grid ``ts`` with classic RK4.

    - ``y0``: initial state, any array shape (scalar ODEs pass a 0-d array).
    - ``ts``: shape (n,) save points; integration uses ``substeps`` uniform
      RK4 steps inside each interval.
    - Returns ys with shape (n, *y0.shape); ys[0] == y0. With ``with_health``
      returns ``(ys, Health)`` flagging NaN in the initial state / grid and
      non-finite values anywhere in the trajectory (a blown-up fixed-step
      integration has no tolerance exit to catch it otherwise); iterations
      records the total micro-steps taken.
    """
    y0 = jnp.asarray(y0)
    ts = jnp.asarray(ts)

    def interval(y, tpair):
        t0, t1 = tpair
        h = (t1 - t0) / substeps

        def micro(i, y):
            t = t0 + i * h
            k1 = f(t, y, args)
            k2 = f(t + 0.5 * h, y + 0.5 * h * k1, args)
            k3 = f(t + 0.5 * h, y + 0.5 * h * k2, args)
            k4 = f(t + h, y + h * k3, args)
            return y + (h / 6.0) * (k1 + 2.0 * k2 + 2.0 * k3 + k4)

        y1 = lax.fori_loop(0, substeps, micro, y)
        return y1, y1

    tpairs = jnp.stack([ts[:-1], ts[1:]], axis=1)
    _, ys = lax.scan(interval, y0, tpairs)
    out = jnp.concatenate([y0[None], ys], axis=0)
    if not with_health:
        return out
    health = Health.of_nan_probe(
        nan_in=jnp.any(jnp.isnan(y0)) | jnp.any(jnp.isnan(ts)),
        nonfinite_out=jnp.any(~jnp.isfinite(out)),
        iterations=(int(ts.shape[0]) - 1) * substeps,
        dtype=out.dtype,
    )
    return out, health
