"""Branchless threshold-crossing detection and batched bracketing root-finds.

Replaces the reference's sequential scans and tolerance-triggered loops:

- `optimal_buffer`'s forward/backward crossing scans with early `break`
  (`src/baseline/solver.jl:229-261`) become boolean-transition argmax plus
  sub-grid linear interpolation.
- `compute_ξ`'s bisection with 5-case early exit (`src/baseline/solver.jl:
  308-376`) becomes a fixed-iteration `fori_loop`: 90 halvings shrink the
  bracket below 1e-26 of its width, far past the reference's 10*eps(κ)
  tolerance, and cost less on TPU than data-dependent exit.

Adaptive numerics (ISSUE 9) adds the convergence-masked siblings used when
`SolverConfig.numerics == "adaptive"`:

- `chandrupatla`: inverse-quadratic/bisection hybrid bracketing (Chandrupatla
  1997, the torchode/MPAX-style batched formulation) as a `lax.while_loop`
  whose cond is ``jnp.any(active) & (it < budget)`` — converged lanes freeze
  and stop contributing f-evaluations' results, and the whole batch exits
  when the slowest lane lands. Typical cells converge in ~10-25 evaluations
  instead of the fixed 90 halvings, and the per-lane iteration counts land
  in `diag.Health` as ACTUAL effective iterations (the fixed path can only
  report its budget).
- `threshold_crossings_masked`: both buffer crossings of one curve through
  a two-level block decomposition — per-block min/max tables reduce the
  O(n) boolean-transition scans to O(√n) block queries plus O(√n)
  within-block searches. The tables depend only on the curve, so under the
  sweeps' vmap² (where the hazard row is shared by every u-cell) XLA hoists
  them per-β and the per-cell cost drops from O(n_grid) to O(√n_grid) —
  the measured majority of grid-cell time at the bench shape. Selected
  indices and interpolation arithmetic are IDENTICAL to the scan path, so
  results are bit-identical (tested), including the fallback ladder and
  NaN-poison semantics.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from sbr_tpu.diag.health import (
    FALLBACK_IN_DEFAULT,
    FALLBACK_IN_KNOT,
    NAN_INPUT,
    NAN_OUTPUT,
    NO_BRACKET,
    NONFINITE_RESIDUAL,
    Health,
)
from sbr_tpu.obs.metrics import metrics


def _crossing_health(y, level, has_cross, has_above) -> Health:
    """Health of one crossing detection: which rung of the fallback ladder
    fired (generic IN-positioned bits; `diag.as_out_crossing` re-keys the
    down-crossing) plus NaN poison in the curve or the level — a fully
    NaN curve silently takes the ``default`` rung otherwise, the exact
    failure the flags exist to surface."""
    dtype = jnp.asarray(y).dtype
    flags = jnp.where(
        has_cross,
        jnp.int32(0),
        jnp.where(has_above, jnp.int32(FALLBACK_IN_KNOT), jnp.int32(FALLBACK_IN_DEFAULT)),
    )
    nan_in = jnp.any(jnp.isnan(y), axis=-1) | jnp.isnan(jnp.asarray(level, dtype))
    flags = flags | jnp.where(nan_in, jnp.int32(NAN_INPUT), jnp.int32(0))
    nan = jnp.full(flags.shape, jnp.nan, dtype)
    return Health(
        residual=nan,
        bracket_width=nan,
        iterations=jnp.zeros(flags.shape, jnp.int32),
        flags=flags,
    )


def first_upcrossing(x, y, level, default, return_flag: bool = False, with_health: bool = False):
    """First t where ``y`` crosses ``level`` from below, linearly interpolated.

    Fallback ladder mirrors `src/baseline/solver.jl:221-261`: if no up-crossing
    exists but some samples are above the level, return the first above-level
    knot; if nothing is above, return ``default``. With ``return_flag`` also
    returns whether a genuine interpolated crossing was found (callers use it
    to gate sub-grid refinement); with ``with_health`` a `diag.Health`
    recording the fallback rung and NaN poison is appended to the return.
    """
    above = y > level
    up = jnp.logical_and(~above[..., :-1], above[..., 1:])
    has_up = jnp.any(up, axis=-1)
    i = jnp.argmax(up, axis=-1)
    t_cross = _interp_cross(x, y, level, i)
    j = jnp.argmax(above, axis=-1)
    has_above = jnp.any(above, axis=-1)
    t = jnp.where(has_up, t_cross, jnp.where(has_above, x[j], default))
    out = (t, has_up) if return_flag else (t,)
    if with_health:
        out = out + (_crossing_health(y, level, has_up, has_above),)
    return out if len(out) > 1 else out[0]


def last_downcrossing(x, y, level, default, return_flag: bool = False, with_health: bool = False):
    """Last t where ``y`` crosses ``level`` from above, linearly interpolated.

    Fallbacks: last above-level knot if no down-crossing, ``default`` if
    nothing is above (`src/baseline/solver.jl:242-261`). Health (opt-in, see
    `first_upcrossing`) reports in the generic IN-positioned fallback bits;
    callers merging both crossings re-key it with `diag.as_out_crossing`.
    """
    above = y > level
    dn = jnp.logical_and(above[..., :-1], ~above[..., 1:])
    has_dn = jnp.any(dn, axis=-1)
    m = dn.shape[-1]
    i = m - 1 - jnp.argmax(dn[..., ::-1], axis=-1)
    t_cross = _interp_cross(x, y, level, i)
    n = above.shape[-1]
    j = n - 1 - jnp.argmax(above[..., ::-1], axis=-1)
    has_above = jnp.any(above, axis=-1)
    t = jnp.where(has_dn, t_cross, jnp.where(has_above, x[j], default))
    out = (t, has_dn) if return_flag else (t,)
    if with_health:
        out = out + (_crossing_health(y, level, has_dn, has_above),)
    return out if len(out) > 1 else out[0]


def _interp_cross(x, y, level, i):
    x1 = jnp.take(x, i)
    x2 = jnp.take(x, i + 1)
    y1 = jnp.take(y, i, axis=-1)
    y2 = jnp.take(y, i + 1, axis=-1)
    dy = y2 - y1
    # Guard dy==0 (flat segment); the crossing test already excludes it except
    # in degenerate fallback lanes, where the value is unused.
    safe = jnp.where(dy == 0, jnp.ones_like(dy), dy)
    return x1 + (level - y1) * (x2 - x1) / safe


def threshold_crossings(x, y, level, default):
    """(first up-crossing, last down-crossing) of ``y`` against ``level``.

    One call replaces the whole of `optimal_buffer`'s scan logic
    (`src/baseline/solver.jl:211-264`): returns (default, default) when the
    curve never exceeds ``level`` and (x[0], x[-1]) when it always does.
    """
    return (
        first_upcrossing(x, y, level, default),
        last_downcrossing(x, y, level, default),
    )


def bisect(f, lo, hi, num_iters: int = 90, x0=None, with_health: bool = False):
    """Fixed-iteration bisection for a root of ``f`` in [lo, hi].

    Reproduces the reference update rule exactly (`src/baseline/solver.jl:
    364-372`): positive error contracts the upper bound, negative the lower,
    and the next iterate is the midpoint of the retained half — starting from
    ``x0`` (the reference's ξ_guess, default bracket midpoint). Runs a fixed
    ``num_iters`` halvings instead of a tolerance exit; the caller classifies
    the returned candidate (root / no-root / false equilibrium) from f's value
    and slope, preserving the reference's NaN semantics without branching.

    Returns the final iterate. Fully vmappable when f broadcasts. With
    ``with_health`` returns ``(x, Health)``: the loop and the iterate are
    IDENTICAL (the carry is untouched), and three extra evaluations of ``f``
    (original endpoints + final iterate — cheap closed-form calls in every
    caller) fill in the final residual |f(x)|, the final bracket width, a
    no-sign-change bracket check, and NaN sentinels.
    """
    # Trace-time counters (obs.metrics jit-safety contract): host code that
    # counts bisection instances and their fixed iteration budgets as
    # programs are traced/eagerly run — compile-complexity attribution with
    # zero effect on the computation graph.
    metrics().inc("core.bisect.calls")
    metrics().inc("core.bisect.iters", num_iters)
    x = 0.5 * (lo + hi) if x0 is None else x0

    def body(_, state):
        lo, hi, x = state
        err = f(x)
        pos = err > 0
        lo2 = jnp.where(pos, lo, x)
        hi2 = jnp.where(pos, x, hi)
        xn = jnp.where(pos, 0.5 * (x + lo), 0.5 * (x + hi))
        return lo2, hi2, xn

    lo_f, hi_f, x = lax.fori_loop(0, num_iters, body, (lo, hi, x))
    if not with_health:
        return x

    res = jnp.abs(f(x))
    dtype = jnp.asarray(res).dtype
    no_bracket = f(jnp.asarray(lo, dtype)) * f(jnp.asarray(hi, dtype)) > 0
    nan_in = jnp.isnan(jnp.asarray(lo, dtype)) | jnp.isnan(jnp.asarray(hi, dtype))
    if x0 is not None:
        nan_in = nan_in | jnp.isnan(jnp.asarray(x0, dtype))
    flags = (
        jnp.where(no_bracket, jnp.int32(NO_BRACKET), jnp.int32(0))
        | jnp.where(~jnp.isfinite(res), jnp.int32(NONFINITE_RESIDUAL), jnp.int32(0))
        | jnp.where(nan_in, jnp.int32(NAN_INPUT), jnp.int32(0))
        | jnp.where(jnp.isnan(x), jnp.int32(NAN_OUTPUT), jnp.int32(0))
    )
    health = Health(
        residual=res,
        bracket_width=jnp.abs(jnp.asarray(hi_f, dtype) - lo_f),
        iterations=jnp.full(jnp.shape(flags), num_iters, jnp.int32),
        flags=flags,
    )
    return x, health


def chandrupatla(f, lo, hi, budget: int = 90, x0=None, atol=0.0, with_health: bool = False):
    """Convergence-masked Chandrupatla bracketing for a root of ``f`` in
    [lo, hi] — the adaptive-numerics sibling of `bisect` (ISSUE 9).

    Inverse-quadratic interpolation where the iterates justify it, bisection
    otherwise (Chandrupatla 1997), as a `lax.while_loop` whose cond is
    ``jnp.any(active) & (it < budget)``: a lane freezes once its bracket
    shrinks below ``2·eps·|x| + atol`` (or it hits an exact zero), and the
    loop exits when every lane has. Under vmap the batch runs as long as its
    SLOWEST lane — still typically ~10-25 iterations against the fixed
    90-halving budget. ``x0`` seeds the first probe (the reference's
    ξ_guess); without it the first probe is the midpoint, like `bisect`.

    Like `bisect`, no convergence exit is promised on degenerate input: a
    non-bracketing interval or NaN endpoints run to ``budget`` and the
    caller classifies the returned candidate from f's value. With
    ``with_health`` returns ``(x, Health)`` built entirely from the loop
    carry — final |f(x)|, final bracket width, PER-LANE iterations actually
    executed (the post-hoc effective-iteration telemetry that replaces the
    fixed path's trace-time budget counter), bracket-validity and NaN
    flags — at zero extra f-evaluations.
    """
    metrics().inc("core.chandrupatla.calls")
    b = jnp.asarray(lo)
    a = jnp.asarray(hi)
    dtype = jnp.result_type(a.dtype, b.dtype)
    a = a.astype(dtype)
    b = b.astype(dtype)
    fa = f(a)
    fb = f(b)
    shape = jnp.shape(fa + fb)
    eps = jnp.finfo(dtype).eps
    tiny = jnp.finfo(dtype).tiny
    atol_ = jnp.asarray(atol, dtype)

    a, b, fa, fb = (jnp.broadcast_to(v, shape) for v in (a, b, fa, fb))
    c, fc = a, fa
    if x0 is None:
        t = jnp.full(shape, 0.5, dtype)
    else:
        span = b - a
        safe = jnp.where(span == 0, jnp.ones_like(span), span)
        t = jnp.clip((jnp.asarray(x0, dtype) - a) / safe, 0.001, 0.999)
        t = jnp.broadcast_to(t, shape)

    def cond(st):
        it = st[0]
        active = st[-2]
        return jnp.any(active) & (it < budget)

    def body(st):
        it, a, b, c, fa, fb, fc, t, active, iters = st
        xt = a + t * (b - a)
        ft = f(xt)
        same = jnp.sign(ft) == jnp.sign(fa)
        c2 = jnp.where(same, a, b)
        fc2 = jnp.where(same, fa, fb)
        b2 = jnp.where(same, b, a)
        fb2 = jnp.where(same, fb, fa)
        a2, fa2 = xt, ft

        xm = jnp.where(jnp.abs(fa2) < jnp.abs(fb2), a2, b2)
        tol = 2.0 * eps * jnp.abs(xm) + atol_
        tlim = tol / jnp.maximum(jnp.abs(b2 - a2), tiny)
        converged = (tlim > 0.5) | (ft == 0)

        # IQI when the transformed iterates lie inside the parabola of
        # validity (Chandrupatla's criterion); bisection otherwise.
        xi_ = (a2 - b2) / (c2 - b2 + tiny)
        phi = (fa2 - fb2) / (fc2 - fb2 + tiny)
        iqi_ok = (phi * phi < xi_) & ((1.0 - phi) * (1.0 - phi) < 1.0 - xi_)
        t_iqi = fa2 / (fb2 - fa2 + tiny) * fc2 / (fb2 - fc2 + tiny) + (
            c2 - a2
        ) / (b2 - a2 + tiny) * fa2 / (fc2 - fa2 + tiny) * fb2 / (fc2 - fb2 + tiny)
        t2 = jnp.where(iqi_ok, jnp.clip(t_iqi, tlim, 1.0 - tlim), 0.5)

        keep = lambda new, old: jnp.where(active, new, old)
        still = active & ~converged
        return (
            it + 1,
            keep(a2, a),
            keep(b2, b),
            keep(c2, c),
            keep(fa2, fa),
            keep(fb2, fb),
            keep(fc2, fc),
            jnp.where(still, t2, t),
            still,
            iters + active.astype(jnp.int32),
        )

    active0 = jnp.ones(shape, bool)
    st = (
        jnp.zeros((), jnp.int32), a, b, c, fa, fb, fc, t, active0,
        jnp.zeros(shape, jnp.int32),
    )
    _, a_f, b_f, _, fa_f, fb_f, _, _, _, iters = lax.while_loop(cond, body, st)
    best_a = jnp.abs(fa_f) < jnp.abs(fb_f)
    x = jnp.where(best_a, a_f, b_f)
    if not with_health:
        return x

    res = jnp.abs(jnp.where(best_a, fa_f, fb_f))
    no_bracket = fa * fb > 0  # initial endpoint evaluations, already in hand
    nan_in = jnp.isnan(a) | jnp.isnan(b)
    if x0 is not None:
        nan_in = nan_in | jnp.isnan(jnp.asarray(x0, dtype))
    flags = (
        jnp.where(no_bracket, jnp.int32(NO_BRACKET), jnp.int32(0))
        | jnp.where(~jnp.isfinite(res), jnp.int32(NONFINITE_RESIDUAL), jnp.int32(0))
        | jnp.where(nan_in, jnp.int32(NAN_INPUT), jnp.int32(0))
        | jnp.where(jnp.isnan(x), jnp.int32(NAN_OUTPUT), jnp.int32(0))
    )
    health = Health(
        residual=res,
        bracket_width=jnp.abs(b_f - a_f),
        iterations=iters,
        flags=flags,
    )
    return x, health


def _crossing_block_size(n: int) -> int:
    """Static block size for `threshold_crossings_masked`: the power of two
    nearest √n, floored at 8 — balances the (B,) block pass against the
    O(s) within-block searches."""
    s = 8
    while s * s < n:
        s *= 2
    return s


def threshold_crossings_masked(x, y, level, default, with_health: bool = False):
    """Both buffer crossings of ``y`` against ``level`` via two-level block
    search — bit-identical results to `first_upcrossing` + `last_downcrossing`
    at O(√n) per-cell cost (module docstring).

    The index identities (proofs in tests/test_numerics.py against the scan
    path):

    - first up-crossing = e − 1 where d is the first not-above index and e
      the first above index past d: minimality of e makes every index in
      [d, e) not-above, so hr[e−1] ≤ u < hr[e] — and any earlier up-crossing
      would need a not-above index before d.
    - last down-crossing = e′ where d′ is the last not-above index and e′
      the last above index before d′ (mirror argument).
    - the fallback knots are the first/last above indices, found the same
      blocked way.

    "not above" is ``~(y > level)`` exactly as the scan's boolean complement,
    so NaN samples count as not-above on both paths and NaN levels disable
    every crossing identically. Block tables (per-block max for "contains
    above", NaN→−inf; per-block min for "contains not-above", NaN→−inf with
    +inf padding) depend only on ``y`` — under the sweeps' vmap² they hoist
    out of the u axis. 1-D ``y`` with scalar ``level``; batch via vmap.

    Returns ``(t_in, has_up, t_out, has_dn)``; with ``with_health`` appends
    the IN- and generic-keyed OUT-crossing healths (caller re-keys the OUT
    one with `diag.as_out_crossing`, like the scan pair).
    """
    y = jnp.asarray(y)
    x = jnp.asarray(x)
    n = y.shape[-1]
    s = _crossing_block_size(n)
    B = -(-n // s)
    pad = B * s - n
    dtype = y.dtype
    default = jnp.asarray(default, dtype)
    neg_inf = jnp.asarray(-jnp.inf, dtype)
    pos_inf = jnp.asarray(jnp.inf, dtype)

    nanmask = jnp.isnan(y)
    z = jnp.where(nanmask, neg_inf, y)
    z_above = jnp.pad(z, (0, pad), constant_values=-jnp.inf)
    z_below = jnp.pad(z, (0, pad), constant_values=jnp.inf)
    bmax = jnp.max(z_above.reshape(B, s), axis=-1)  # block contains above ⟺ bmax > level
    bmin = jnp.min(z_below.reshape(B, s), axis=-1)  # block contains not-above ⟺ bmin ≤ level
    y_pad = jnp.pad(y, (0, pad), constant_values=jnp.nan)

    idx_b = jnp.arange(B)
    idx_s = jnp.arange(s)

    abv_b = bmax > level
    nab_b = bmin <= level
    has_above = jnp.any(abv_b)
    has_nab = jnp.any(nab_b)

    def block(b):
        """Raw values + above/not-above element masks of block ``b``."""
        v = lax.dynamic_slice(y_pad, (b * s,), (s,))
        valid = (b * s + idx_s) < n
        above = valid & (v > level)
        nab = valid & ~(v > level)
        return above, nab

    first = lambda m: jnp.argmax(m)
    last_s = lambda m: s - 1 - jnp.argmax(m[::-1])
    last_b = lambda m: B - 1 - jnp.argmax(m[::-1])

    # Fallback knots: first / last above index.
    b_j = first(abv_b)
    above_j, _ = block(b_j)
    j_first = b_j * s + first(above_j)
    b_j2 = last_b(abv_b)
    above_j2, _ = block(b_j2)
    j_last = b_j2 * s + last_s(above_j2)

    # First up-crossing: d = first not-above, e = first above past d.
    b_d = first(nab_b)
    above_d, nab_d = block(b_d)
    d_off = first(nab_d)
    cand_in = above_d & (idx_s > d_off)
    e_in_ok = jnp.any(cand_in)
    abv_after = abv_b & (idx_b > b_d)
    b_e = first(abv_after)
    above_e, _ = block(b_e)
    e = jnp.where(e_in_ok, b_d * s + first(cand_in), b_e * s + first(above_e))
    has_up = has_nab & (e_in_ok | jnp.any(abv_after))
    i_up = jnp.clip(e - 1, 0, n - 2)

    # Last down-crossing: d' = last not-above, e' = last above before d'.
    b_d2 = last_b(nab_b)
    above_d2, nab_d2 = block(b_d2)
    d2_off = last_s(nab_d2)
    cand2_in = above_d2 & (idx_s < d2_off)
    e2_in_ok = jnp.any(cand2_in)
    abv_before = abv_b & (idx_b < b_d2)
    b_e2 = last_b(abv_before)
    above_e2, _ = block(b_e2)
    e2 = jnp.where(e2_in_ok, b_d2 * s + last_s(cand2_in), b_e2 * s + last_s(above_e2))
    has_dn = has_nab & (e2_in_ok | jnp.any(abv_before))
    i_dn = jnp.clip(e2, 0, n - 2)

    t_up = _interp_cross(x, y, level, i_up)
    t_dn = _interp_cross(x, y, level, i_dn)
    t_in = jnp.where(has_up, t_up, jnp.where(has_above, jnp.take(x, j_first), default))
    t_out = jnp.where(has_dn, t_dn, jnp.where(has_above, jnp.take(x, j_last), default))
    out = (t_in, has_up, t_out, has_dn)
    if with_health:
        out = out + (
            _crossing_health(y, level, has_up, has_above),
            _crossing_health(y, level, has_dn, has_above),
        )
    return out
