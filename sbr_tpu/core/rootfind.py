"""Branchless threshold-crossing detection and fixed-iteration bisection.

Replaces the reference's sequential scans and tolerance-triggered loops:

- `optimal_buffer`'s forward/backward crossing scans with early `break`
  (`src/baseline/solver.jl:229-261`) become boolean-transition argmax plus
  sub-grid linear interpolation.
- `compute_ξ`'s bisection with 5-case early exit (`src/baseline/solver.jl:
  308-376`) becomes a fixed-iteration `fori_loop`: 90 halvings shrink the
  bracket below 1e-26 of its width, far past the reference's 10*eps(κ)
  tolerance, and cost less on TPU than data-dependent exit.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from sbr_tpu.diag.health import (
    FALLBACK_IN_DEFAULT,
    FALLBACK_IN_KNOT,
    NAN_INPUT,
    NAN_OUTPUT,
    NO_BRACKET,
    NONFINITE_RESIDUAL,
    Health,
)
from sbr_tpu.obs.metrics import metrics


def _crossing_health(y, level, has_cross, has_above) -> Health:
    """Health of one crossing detection: which rung of the fallback ladder
    fired (generic IN-positioned bits; `diag.as_out_crossing` re-keys the
    down-crossing) plus NaN poison in the curve or the level — a fully
    NaN curve silently takes the ``default`` rung otherwise, the exact
    failure the flags exist to surface."""
    dtype = jnp.asarray(y).dtype
    flags = jnp.where(
        has_cross,
        jnp.int32(0),
        jnp.where(has_above, jnp.int32(FALLBACK_IN_KNOT), jnp.int32(FALLBACK_IN_DEFAULT)),
    )
    nan_in = jnp.any(jnp.isnan(y), axis=-1) | jnp.isnan(jnp.asarray(level, dtype))
    flags = flags | jnp.where(nan_in, jnp.int32(NAN_INPUT), jnp.int32(0))
    nan = jnp.full(flags.shape, jnp.nan, dtype)
    return Health(
        residual=nan,
        bracket_width=nan,
        iterations=jnp.zeros(flags.shape, jnp.int32),
        flags=flags,
    )


def first_upcrossing(x, y, level, default, return_flag: bool = False, with_health: bool = False):
    """First t where ``y`` crosses ``level`` from below, linearly interpolated.

    Fallback ladder mirrors `src/baseline/solver.jl:221-261`: if no up-crossing
    exists but some samples are above the level, return the first above-level
    knot; if nothing is above, return ``default``. With ``return_flag`` also
    returns whether a genuine interpolated crossing was found (callers use it
    to gate sub-grid refinement); with ``with_health`` a `diag.Health`
    recording the fallback rung and NaN poison is appended to the return.
    """
    above = y > level
    up = jnp.logical_and(~above[..., :-1], above[..., 1:])
    has_up = jnp.any(up, axis=-1)
    i = jnp.argmax(up, axis=-1)
    t_cross = _interp_cross(x, y, level, i)
    j = jnp.argmax(above, axis=-1)
    has_above = jnp.any(above, axis=-1)
    t = jnp.where(has_up, t_cross, jnp.where(has_above, x[j], default))
    out = (t, has_up) if return_flag else (t,)
    if with_health:
        out = out + (_crossing_health(y, level, has_up, has_above),)
    return out if len(out) > 1 else out[0]


def last_downcrossing(x, y, level, default, return_flag: bool = False, with_health: bool = False):
    """Last t where ``y`` crosses ``level`` from above, linearly interpolated.

    Fallbacks: last above-level knot if no down-crossing, ``default`` if
    nothing is above (`src/baseline/solver.jl:242-261`). Health (opt-in, see
    `first_upcrossing`) reports in the generic IN-positioned fallback bits;
    callers merging both crossings re-key it with `diag.as_out_crossing`.
    """
    above = y > level
    dn = jnp.logical_and(above[..., :-1], ~above[..., 1:])
    has_dn = jnp.any(dn, axis=-1)
    m = dn.shape[-1]
    i = m - 1 - jnp.argmax(dn[..., ::-1], axis=-1)
    t_cross = _interp_cross(x, y, level, i)
    n = above.shape[-1]
    j = n - 1 - jnp.argmax(above[..., ::-1], axis=-1)
    has_above = jnp.any(above, axis=-1)
    t = jnp.where(has_dn, t_cross, jnp.where(has_above, x[j], default))
    out = (t, has_dn) if return_flag else (t,)
    if with_health:
        out = out + (_crossing_health(y, level, has_dn, has_above),)
    return out if len(out) > 1 else out[0]


def _interp_cross(x, y, level, i):
    x1 = jnp.take(x, i)
    x2 = jnp.take(x, i + 1)
    y1 = jnp.take(y, i, axis=-1)
    y2 = jnp.take(y, i + 1, axis=-1)
    dy = y2 - y1
    # Guard dy==0 (flat segment); the crossing test already excludes it except
    # in degenerate fallback lanes, where the value is unused.
    safe = jnp.where(dy == 0, jnp.ones_like(dy), dy)
    return x1 + (level - y1) * (x2 - x1) / safe


def threshold_crossings(x, y, level, default):
    """(first up-crossing, last down-crossing) of ``y`` against ``level``.

    One call replaces the whole of `optimal_buffer`'s scan logic
    (`src/baseline/solver.jl:211-264`): returns (default, default) when the
    curve never exceeds ``level`` and (x[0], x[-1]) when it always does.
    """
    return (
        first_upcrossing(x, y, level, default),
        last_downcrossing(x, y, level, default),
    )


def bisect(f, lo, hi, num_iters: int = 90, x0=None, with_health: bool = False):
    """Fixed-iteration bisection for a root of ``f`` in [lo, hi].

    Reproduces the reference update rule exactly (`src/baseline/solver.jl:
    364-372`): positive error contracts the upper bound, negative the lower,
    and the next iterate is the midpoint of the retained half — starting from
    ``x0`` (the reference's ξ_guess, default bracket midpoint). Runs a fixed
    ``num_iters`` halvings instead of a tolerance exit; the caller classifies
    the returned candidate (root / no-root / false equilibrium) from f's value
    and slope, preserving the reference's NaN semantics without branching.

    Returns the final iterate. Fully vmappable when f broadcasts. With
    ``with_health`` returns ``(x, Health)``: the loop and the iterate are
    IDENTICAL (the carry is untouched), and three extra evaluations of ``f``
    (original endpoints + final iterate — cheap closed-form calls in every
    caller) fill in the final residual |f(x)|, the final bracket width, a
    no-sign-change bracket check, and NaN sentinels.
    """
    # Trace-time counters (obs.metrics jit-safety contract): host code that
    # counts bisection instances and their fixed iteration budgets as
    # programs are traced/eagerly run — compile-complexity attribution with
    # zero effect on the computation graph.
    metrics().inc("core.bisect.calls")
    metrics().inc("core.bisect.iters", num_iters)
    x = 0.5 * (lo + hi) if x0 is None else x0

    def body(_, state):
        lo, hi, x = state
        err = f(x)
        pos = err > 0
        lo2 = jnp.where(pos, lo, x)
        hi2 = jnp.where(pos, x, hi)
        xn = jnp.where(pos, 0.5 * (x + lo), 0.5 * (x + hi))
        return lo2, hi2, xn

    lo_f, hi_f, x = lax.fori_loop(0, num_iters, body, (lo, hi, x))
    if not with_health:
        return x

    res = jnp.abs(f(x))
    dtype = jnp.asarray(res).dtype
    no_bracket = f(jnp.asarray(lo, dtype)) * f(jnp.asarray(hi, dtype)) > 0
    nan_in = jnp.isnan(jnp.asarray(lo, dtype)) | jnp.isnan(jnp.asarray(hi, dtype))
    if x0 is not None:
        nan_in = nan_in | jnp.isnan(jnp.asarray(x0, dtype))
    flags = (
        jnp.where(no_bracket, jnp.int32(NO_BRACKET), jnp.int32(0))
        | jnp.where(~jnp.isfinite(res), jnp.int32(NONFINITE_RESIDUAL), jnp.int32(0))
        | jnp.where(nan_in, jnp.int32(NAN_INPUT), jnp.int32(0))
        | jnp.where(jnp.isnan(x), jnp.int32(NAN_OUTPUT), jnp.int32(0))
    )
    health = Health(
        residual=res,
        bracket_width=jnp.abs(jnp.asarray(hi_f, dtype) - lo_f),
        iterations=jnp.full(jnp.shape(flags), num_iters, jnp.int32),
        flags=flags,
    )
    return x, health
