"""Branchless threshold-crossing detection and fixed-iteration bisection.

Replaces the reference's sequential scans and tolerance-triggered loops:

- `optimal_buffer`'s forward/backward crossing scans with early `break`
  (`src/baseline/solver.jl:229-261`) become boolean-transition argmax plus
  sub-grid linear interpolation.
- `compute_ξ`'s bisection with 5-case early exit (`src/baseline/solver.jl:
  308-376`) becomes a fixed-iteration `fori_loop`: 90 halvings shrink the
  bracket below 1e-26 of its width, far past the reference's 10*eps(κ)
  tolerance, and cost less on TPU than data-dependent exit.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from sbr_tpu.obs.metrics import metrics


def first_upcrossing(x, y, level, default, return_flag: bool = False):
    """First t where ``y`` crosses ``level`` from below, linearly interpolated.

    Fallback ladder mirrors `src/baseline/solver.jl:221-261`: if no up-crossing
    exists but some samples are above the level, return the first above-level
    knot; if nothing is above, return ``default``. With ``return_flag`` also
    returns whether a genuine interpolated crossing was found (callers use it
    to gate sub-grid refinement).
    """
    above = y > level
    up = jnp.logical_and(~above[..., :-1], above[..., 1:])
    has_up = jnp.any(up, axis=-1)
    i = jnp.argmax(up, axis=-1)
    t_cross = _interp_cross(x, y, level, i)
    j = jnp.argmax(above, axis=-1)
    has_above = jnp.any(above, axis=-1)
    t = jnp.where(has_up, t_cross, jnp.where(has_above, x[j], default))
    if return_flag:
        return t, has_up
    return t


def last_downcrossing(x, y, level, default, return_flag: bool = False):
    """Last t where ``y`` crosses ``level`` from above, linearly interpolated.

    Fallbacks: last above-level knot if no down-crossing, ``default`` if
    nothing is above (`src/baseline/solver.jl:242-261`).
    """
    above = y > level
    dn = jnp.logical_and(above[..., :-1], ~above[..., 1:])
    has_dn = jnp.any(dn, axis=-1)
    m = dn.shape[-1]
    i = m - 1 - jnp.argmax(dn[..., ::-1], axis=-1)
    t_cross = _interp_cross(x, y, level, i)
    n = above.shape[-1]
    j = n - 1 - jnp.argmax(above[..., ::-1], axis=-1)
    has_above = jnp.any(above, axis=-1)
    t = jnp.where(has_dn, t_cross, jnp.where(has_above, x[j], default))
    if return_flag:
        return t, has_dn
    return t


def _interp_cross(x, y, level, i):
    x1 = jnp.take(x, i)
    x2 = jnp.take(x, i + 1)
    y1 = jnp.take(y, i, axis=-1)
    y2 = jnp.take(y, i + 1, axis=-1)
    dy = y2 - y1
    # Guard dy==0 (flat segment); the crossing test already excludes it except
    # in degenerate fallback lanes, where the value is unused.
    safe = jnp.where(dy == 0, jnp.ones_like(dy), dy)
    return x1 + (level - y1) * (x2 - x1) / safe


def threshold_crossings(x, y, level, default):
    """(first up-crossing, last down-crossing) of ``y`` against ``level``.

    One call replaces the whole of `optimal_buffer`'s scan logic
    (`src/baseline/solver.jl:211-264`): returns (default, default) when the
    curve never exceeds ``level`` and (x[0], x[-1]) when it always does.
    """
    return (
        first_upcrossing(x, y, level, default),
        last_downcrossing(x, y, level, default),
    )


def bisect(f, lo, hi, num_iters: int = 90, x0=None):
    """Fixed-iteration bisection for a root of ``f`` in [lo, hi].

    Reproduces the reference update rule exactly (`src/baseline/solver.jl:
    364-372`): positive error contracts the upper bound, negative the lower,
    and the next iterate is the midpoint of the retained half — starting from
    ``x0`` (the reference's ξ_guess, default bracket midpoint). Runs a fixed
    ``num_iters`` halvings instead of a tolerance exit; the caller classifies
    the returned candidate (root / no-root / false equilibrium) from f's value
    and slope, preserving the reference's NaN semantics without branching.

    Returns the final iterate. Fully vmappable when f broadcasts.
    """
    # Trace-time counters (obs.metrics jit-safety contract): host code that
    # counts bisection instances and their fixed iteration budgets as
    # programs are traced/eagerly run — compile-complexity attribution with
    # zero effect on the computation graph.
    metrics().inc("core.bisect.calls")
    metrics().inc("core.bisect.iters", num_iters)
    x = 0.5 * (lo + hi) if x0 is None else x0

    def body(_, state):
        lo, hi, x = state
        err = f(x)
        pos = err > 0
        lo2 = jnp.where(pos, lo, x)
        hi2 = jnp.where(pos, x, hi)
        xn = jnp.where(pos, 0.5 * (x + lo), 0.5 * (x + hi))
        return lo2, hi2, xn

    _, _, x = lax.fori_loop(0, num_iters, body, (lo, hi, x))
    return x
