"""Quadrature primitives.

The reference computes the hazard-rate normalization with a sequential
cumulative trapezoid over its adaptive grid (`src/baseline/solver.jl:172-175`).
On TPU that loop is a `cumsum` of per-interval trapezoid increments — fully
parallel. For integrands known in closed form (the baseline logistic PDF) a
composite Gauss-Legendre rule gives near-exact cumulative integrals at the
same O(n) cost.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from sbr_tpu.diag.health import Health
from sbr_tpu.obs.metrics import metrics


def _quad_health(values, csum, n_panels) -> Health:
    """Health of one cumulative quadrature: NaN among the integrand samples
    (poison propagates silently through cumsum otherwise) and non-finite
    values in the cumulative result; iterations counts panels."""
    return Health.of_nan_probe(
        nan_in=jnp.any(jnp.isnan(values)),
        nonfinite_out=jnp.any(~jnp.isfinite(csum)),
        iterations=n_panels,
        dtype=csum.dtype,
    )


def trapz(y, x=None, dx=1.0):
    """Trapezoid integral along the last axis."""
    if x is not None:
        d = jnp.diff(x)
    else:
        d = dx
    return jnp.sum(0.5 * (y[..., 1:] + y[..., :-1]) * d, axis=-1)


def cumtrapz(y, x=None, dx=1.0, with_health: bool = False):
    """Cumulative trapezoid along the last axis, zero at the first knot.

    Matches the reference recurrence
    ``int[i] = int[i-1] + 0.5*(f(t[i-1])+f(t[i]))*(t[i]-t[i-1])``
    (`src/baseline/solver.jl:172-175`) as one parallel cumsum. With
    ``with_health`` returns ``(out, Health)`` flagging NaN samples and a
    non-finite cumulative result.
    """
    if x is not None:
        d = jnp.diff(x)
    else:
        d = dx
    inc = 0.5 * (y[..., 1:] + y[..., :-1]) * d
    csum = jnp.cumsum(inc, axis=-1)
    zero = jnp.zeros(csum.shape[:-1] + (1,), dtype=csum.dtype)
    out = jnp.concatenate([zero, csum], axis=-1)
    if with_health:
        return out, _quad_health(y, out, int(y.shape[-1]) - 1)
    return out


def cumulative_gauss_legendre(f, grid, order: int = 8, with_health: bool = False):
    """Cumulative integral of callable ``f`` at the knots of ``grid``.

    Composite Gauss-Legendre with ``order`` nodes per interval: error
    O(h^{2*order}) per interval, effectively exact for the analytic integrands
    in this model (e^{λt} g(t) with closed-form g). ``f`` must accept an array
    of evaluation points and broadcast.

    Returns an array shaped like ``grid`` with value 0 at ``grid[0]``; with
    ``with_health`` also a `diag.Health` flagging NaN integrand samples and
    a non-finite cumulative result.
    """
    # Trace-time counter (see core.rootfind.bisect): quadrature instances ×
    # order, a proxy for the transcendental-evaluation volume per program.
    metrics().inc("core.quad_gl.calls")
    metrics().inc("core.quad_gl.node_evals", order * (int(grid.shape[0]) - 1))
    nodes, weights = np.polynomial.legendre.leggauss(order)
    a = grid[:-1]
    b = grid[1:]
    half = 0.5 * (b - a)
    mid = 0.5 * (a + b)
    # (order, n-1) evaluation points
    xs = mid[None, :] + half[None, :] * jnp.asarray(nodes, dtype=grid.dtype)[:, None]
    vals = f(xs)
    seg = half * jnp.tensordot(jnp.asarray(weights, dtype=grid.dtype), vals, axes=(0, 0))
    csum = jnp.cumsum(seg, axis=-1)
    zero = jnp.zeros(csum.shape[:-1] + (1,), dtype=csum.dtype)
    out = jnp.concatenate([zero, csum], axis=-1)
    if with_health:
        return out, _quad_health(vals, out, int(grid.shape[0]) - 1)
    return out
