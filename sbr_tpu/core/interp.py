"""Linear interpolation on static grids.

The reference threads `LinearInterpolation` objects through every stage
(`src/baseline/learning.jl:52`, `src/baseline/solver.jl:180`). Under jit those
become plain arrays on a known grid plus the gather-based evaluators here.
Extrapolation is clamped to the boundary values: the reference's interpolants
are only ever evaluated in-range (arguments are truncated at 0 and the grid
covers [0, 2η], see `src/baseline/solver.jl:511-520`), so clamping matches
observable behavior while staying total for masked/NaN lanes.
"""

from __future__ import annotations

import jax.numpy as jnp


def interp(x, xp, fp):
    """Linear interpolation of ``fp`` sampled at sorted knots ``xp``.

    Works for non-uniform knots (used where grids are log-spaced or inherited
    from another stage). Clamps outside [xp[0], xp[-1]].
    """
    x = jnp.asarray(x)
    return jnp.interp(x, xp, fp)


def interp_shared(x, xp, fp):
    """Linear interpolation of batched rows ``fp`` (..., n) sampled at SHARED
    sorted knots ``xp`` (n,), evaluated at ``x`` (scalar or any shape
    broadcastable against the batch dims); clamps outside [xp[0], xp[-1]].

    One searchsorted serves every row — the K-group learning family shares
    one (possibly non-uniform, transition-warped) grid, like the reference's
    groups share the adaptive grid (`heterogeneity_learning.jl:73-89`).
    Zero-width (duplicate) knots are guarded: the left value wins.
    """
    x = jnp.asarray(x)
    n = xp.shape[0]
    i0 = jnp.clip(jnp.searchsorted(xp, x, side="right") - 1, 0, n - 2)
    return _segment_blend(x, xp, fp, i0)


def _segment_blend(x, xp, fp, i0):
    """Clamped linear blend on bracket ``[xp[i0], xp[i0+1]]`` (shared tail of
    the non-uniform evaluators; zero-width segments: the left value wins)."""
    x0 = jnp.take(xp, i0)
    x1 = jnp.take(xp, i0 + 1)
    denom = jnp.where(x1 > x0, x1 - x0, 1.0)
    w = jnp.clip(((x - x0) / denom).astype(fp.dtype), 0.0, 1.0)
    f0 = jnp.take(fp, i0, axis=-1)
    f1 = jnp.take(fp, i0 + 1, axis=-1)
    return f0 * (1.0 - w) + f1 * w


def interp_guided(x, xp, fp, i_guess):
    """Linear interpolation at sorted knots ``xp`` with a caller-supplied
    bracketing-index guess accurate to ±1 knot.

    Replaces searchsorted's ~log₂(n) dependent gather-compare steps with a
    constant THREE gathers when the caller can compute the bracket
    analytically — the warped hazard grid is a union of two closed-form
    sequences, so its rank function is arithmetic, not a search
    (`baseline/solver.py::warped_grid_index`). Inside a sequential scan
    (the HJB RK4 substeps) the latency difference is the measured 3.7×
    policy-sweep regression of the warp-honoring interest path.

    The guess is corrected locally: start one knot below and step up at
    most twice, covering guesses in error by one either way. Knots may be
    duplicated (zero-width segments) provided ``fp`` is a pointwise
    function of ``xp`` — tied knots then carry equal values and every
    bracket choice interpolates identically. Clamps outside
    [xp[0], xp[-1]] like `interp`.
    """
    x = jnp.asarray(x)
    n = xp.shape[0]
    i0 = jnp.clip(jnp.asarray(i_guess, jnp.int32) - 1, 0, n - 2)
    i0 = jnp.where((x >= jnp.take(xp, i0 + 1)) & (i0 < n - 2), i0 + 1, i0)
    i0 = jnp.where((x >= jnp.take(xp, i0 + 1)) & (i0 < n - 2), i0 + 1, i0)
    return _segment_blend(x, xp, fp, i0)


def interp_uniform(x, t0, dt, fp):
    """Linear interpolation of ``fp`` sampled on the uniform grid t0 + i*dt.

    The hot-path evaluator: index arithmetic instead of searchsorted, so a
    vmapped sweep of equilibrium solves lowers to pure gathers. ``fp`` may have
    leading batch dimensions; interpolation runs along the last axis.
    """
    x = jnp.asarray(x)
    n = fp.shape[-1]
    s = (x - t0) / dt
    s = jnp.clip(s, 0.0, n - 1.0)
    i0 = jnp.clip(jnp.floor(s).astype(jnp.int32), 0, n - 2)
    w = (s - i0).astype(fp.dtype)
    f0 = jnp.take(fp, i0, axis=-1)
    f1 = jnp.take(fp, i0 + 1, axis=-1)
    return f0 * (1.0 - w) + f1 * w
