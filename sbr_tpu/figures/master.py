"""MASTER runner — the reference `MASTER.jl` equivalent as a CLI.

Runs the four workload sections (baseline, heterogeneity, interest rates,
social learning — reference `scripts/1_baseline.jl` … `4_social_learning.jl`)
and writes the 13 figure PDFs plus `replication_figures.tex` under the
output directory, printing the same kind of manifest/timing summary
(`MASTER.jl:31-110`).

Where the reference loops sequentially with early termination
(`1_baseline.jl:147-163,236-244`), this runner calls the vmapped sweeps and
recovers the early-termination accounting from the status grids.

Usage:
    python -m sbr_tpu.figures.master [--output DIR] [--sections 1,2,3,4]
                                     [--fast] [--f32]
"""

from __future__ import annotations

import argparse
import contextlib
import sys
import time
from pathlib import Path

# Paper-resolution extras, generated only with --paper (the reference ships
# this as the "couple hours" 5000×5000 grid, `1_baseline.jl:209-210`).
PAPER_HEATMAP = "baseline/comp_stat_cross_heatmap_AW_large.pdf"

# Framework extra (beyond the reference's 13 figures): the explicit-agent
# population run with the withdrawal window derived from the solved social
# fixed point, validating the equilibrium→agent loop (social/closure.py).
CLOSURE_FIG = "social_learning/agent_vs_fixed_point.pdf"

# Extra figures folded into a section's tex when present on disk.
_EXTRAS = {1: [PAPER_HEATMAP], 4: [CLOSURE_FIG]}

# The 13 reference figures (`MASTER.jl:31-88`), keyed by section.
MANIFEST = {
    1: [
        "baseline/learning_dynamics.pdf",
        "baseline/hazard_rate.pdf",
        "baseline/equilibrium_dynamics_main.pdf",
        "baseline/equilibrium_dynamics_fast.pdf",
        "baseline/equilibrium_dynamics_low_u.pdf",
        "baseline/comp_stat_u_panel_a.pdf",
        "baseline/comp_stat_u_panel_b.pdf",
        "baseline/comp_stat_cross_heatmap_AW.pdf",
    ],
    2: ["heterogeneity/aggregate_withdrawals_hetero.pdf"],
    3: ["interest_rates/value_function.pdf", "interest_rates/hazard_decomposition.pdf"],
    4: [
        "social_learning/social_learning_equilibrium.pdf",
        "social_learning/baseline_equilibrium.pdf",
    ],
}


# Live PdfPages collector for the combined figure document (the reference
# ships a LaTeX-compiled output/replication_figures.pdf; this image has no
# TeX toolchain, so the document is composed directly from the live figures
# at generation time). Set per-run by main(); None disables collection.
# _PDF_PENDING_HEADER holds a section header emitted lazily before the
# section's FIRST figure, so all-skipped sections leave no empty header.
_PDF_DOC = None
_PDF_PENDING_HEADER = None


def _save(fig, path: Path) -> None:
    global _PDF_PENDING_HEADER
    path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(path, bbox_inches="tight")
    if _PDF_DOC is not None:
        if _PDF_PENDING_HEADER is not None:
            _pdf_text_page(_PDF_DOC, [_PDF_PENDING_HEADER], size=18)
            _PDF_PENDING_HEADER = None
        _PDF_DOC.savefig(fig, bbox_inches="tight")
    import matplotlib.pyplot as plt

    plt.close(fig)
    print(f"  ✓ saved {path}")


def _pdf_text_page(doc, lines, size=20) -> None:
    """A text-only page (title / section header) in the combined document."""
    import matplotlib.pyplot as plt

    fig = plt.figure(figsize=(8.27, 11.69))  # A4 portrait
    fig.text(
        0.5, 0.6, "\n\n".join(lines), ha="center", va="center",
        fontsize=size, wrap=True,
    )
    doc.savefig(fig)
    plt.close(fig)


def run_baseline(figdir: Path, fast: bool) -> None:
    """Section 1: Figures 1-5 (`scripts/1_baseline.jl`)."""
    import numpy as np

    from sbr_tpu import make_model_params, solve_learning, with_overrides
    from sbr_tpu.baseline.solver import solve_equilibrium_baseline
    from sbr_tpu.figures.plotting import (
        plot_comp_stat_withdrawals_and_collapse,
        plot_equilibrium,
        plot_hazard_rate_decomposition,
        plot_heatmap_aw,
        plot_learning_distribution,
    )
    from sbr_tpu.models.params import LearningParams
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid, u_sweep

    m_base = make_model_params(beta=1.0, eta_bar=15.0, u=0.1, p=0.5, kappa=0.6, lam=0.01)
    lr_base = solve_learning(m_base.learning)

    # Figure 1: learning CDFs for β ∈ {0.5, 1, 2} on (0, 20)
    # (`1_baseline.jl:60-74`).
    print("Figure 1: learning dynamics")
    beta_values = [0.5, 1.0, 2.0]
    curves = [
        solve_learning(LearningParams(beta=b, tspan=(0.0, 20.0), x0=1e-4)) for b in beta_values
    ]
    _save(
        plot_learning_distribution(curves, (0.0, 20.0), beta_values),
        figdir / "baseline/learning_dynamics.pdf",
    )

    # Figures 2-3: main equilibrium (`1_baseline.jl:82-97`).
    print("Figures 2-3: main equilibrium")
    result = solve_equilibrium_baseline(lr_base, m_base.economic)
    print(f"  ξ* = {float(result.xi):.2f}, bankrun = {bool(result.bankrun)}")
    _save(
        plot_equilibrium(result, lr_base, m_base.economic, x_range=(0, 15)),
        figdir / "baseline/equilibrium_dynamics_main.pdf",
    )
    _save(
        plot_hazard_rate_decomposition(result, lr_base, m_base.economic),
        figdir / "baseline/hazard_rate.pdf",
    )

    # Figures 3bis/3ter: fast communication and low u (`1_baseline.jl:106-126`).
    print("Figures 3bis/3ter: fast β and low u")
    for name, overrides in (("fast", dict(beta=3.0)), ("low_u", dict(u=0.01))):
        m_alt = with_overrides(m_base, **overrides)
        lr_alt = solve_learning(m_alt.learning)
        res_alt = solve_equilibrium_baseline(lr_alt, m_alt.economic)
        print(f"  {name}: ξ* = {float(res_alt.xi):.2f}, bankrun = {bool(res_alt.bankrun)}")
        _save(
            plot_equilibrium(res_alt, lr_alt, m_alt.economic, x_range=(0, 15)),
            figdir / f"baseline/equilibrium_dynamics_{name}.pdf",
        )

    # Figure 4: u-sweep, paper resolution 5000 points over [0.001, 0.2]
    # (`1_baseline.jl:137-200`), vmapped with Stage 1 shared.
    from sbr_tpu.utils.status import status_summary

    n_u = 500 if fast else 5000
    print(f"Figure 4: u-sweep ({n_u} points)")
    sweep = u_sweep(lr_base, np.linspace(0.001, 0.2, n_u), m_base.economic)
    print(f"  {status_summary(sweep.status)} (no-run region recovered from status grid)")
    fig_a, fig_b = plot_comp_stat_withdrawals_and_collapse(
        sweep.u_values,
        sweep.max_withdrawals,
        sweep.collapse_times,
        m_base.economic.kappa,
        return_times=sweep.return_times,
    )
    _save(fig_a, figdir / "baseline/comp_stat_u_panel_a.pdf")
    _save(fig_b, figdir / "baseline/comp_stat_u_panel_b.pdf")

    # Figure 5: β×u heatmap, replication resolution 500×500
    # (`1_baseline.jl:210-284`); x-axis is average meeting time = 1/β.
    n_grid = 100 if fast else 500
    print(f"Figure 5: β×u heatmap ({n_grid}×{n_grid})")
    amt = np.linspace(1e-4, 1.0, n_grid)
    u_vals = np.linspace(0.001, 1.0, n_grid)
    grid = beta_u_grid(1.0 / amt, u_vals, m_base)
    print(f"  {status_summary(grid.status)}")
    # Reference stores (U, B) (`1_baseline.jl:213`); ours is (B, U).
    _save(
        plot_heatmap_aw(amt, u_vals, np.asarray(grid.max_aw).T),
        figdir / "baseline/comp_stat_cross_heatmap_AW.pdf",
    )


def run_paper_heatmap(figdir: Path, ckpt_dir: Path, res: int, tile: int) -> None:
    """Paper-resolution Figure-5 heatmap through the tiled checkpoint/resume
    machinery (`utils.checkpoint.run_tiled_grid`).

    The reference generates this 5000×5000 grid in "a couple hours" with no
    resume — a crash restarts from zero (`1_baseline.jl:209-210`). Here
    finished tiles persist under ``ckpt_dir``: re-running after an interrupt
    recomputes only missing tiles (kill it mid-run and re-invoke to see).
    Runs the f32 sweep path, validated against f64 at grid scale by
    tests/test_sweeps.py::test_f32_grid_reproduces_f64_no_run_region.
    """
    import jax.numpy as jnp
    import numpy as np

    from sbr_tpu import make_model_params
    from sbr_tpu.figures.plotting import plot_heatmap_aw
    from sbr_tpu.utils.checkpoint import run_tiled_grid
    from sbr_tpu.utils.status import status_summary

    m_base = make_model_params(beta=1.0, eta_bar=15.0, u=0.1, p=0.5, kappa=0.6, lam=0.01)
    amt = np.linspace(1e-4, 1.0, res)
    u_vals = np.linspace(0.001, 1.0, res)
    print(f"Paper heatmap: {res}×{res} grid, {tile}×{tile} tiles, resume dir {ckpt_dir}")
    grid = run_tiled_grid(
        1.0 / amt,
        u_vals,
        m_base,
        tile_shape=(tile, tile),
        checkpoint_dir=str(ckpt_dir),
        dtype=jnp.float32,
        verbose=True,
    )
    print(f"  {status_summary(grid.status)}")
    _save(
        plot_heatmap_aw(amt, u_vals, np.asarray(grid.max_aw).T),
        figdir / PAPER_HEATMAP,
    )


def run_heterogeneity(figdir: Path, fast: bool) -> None:
    """Section 2: two-group model figure (`scripts/2_heterogeneity.jl`)."""
    from sbr_tpu.figures.plotting import plot_aw_hetero
    from sbr_tpu.hetero.learning import solve_learning_hetero
    from sbr_tpu.hetero.solver import get_aw_hetero, solve_equilibrium_hetero
    from sbr_tpu.models.params import make_hetero_params

    # `2_heterogeneity.jl:38-49`: slow/fast learners.
    m = make_hetero_params(
        betas=[0.125, 12.5], dist=[0.9, 0.1], eta_bar=30.0, u=0.1, p=0.9, kappa=0.3, lam=0.1
    )
    lsh = solve_learning_hetero(m.learning)
    result = solve_equilibrium_hetero(lsh, m.economic)
    print(f"  hetero: ξ* = {float(result.xi):.2f}, bankrun = {bool(result.bankrun)}")
    aw = get_aw_hetero(result, lsh)
    print(f"  max AW = {float(aw.aw_max):.3f}")
    _save(
        plot_aw_hetero(result, aw, m.economic, m.learning.betas),
        figdir / "heterogeneity/aggregate_withdrawals_hetero.pdf",
    )


def run_interest(figdir: Path, fast: bool) -> None:
    """Section 3: value function + hazard decomposition with the rV
    threshold (`scripts/3_interest_rates.jl`)."""
    import numpy as np

    from sbr_tpu import solve_learning
    from sbr_tpu.figures.plotting import plot_hazard_rate_decomposition, plot_value_function
    from sbr_tpu.interest.solver import solve_equilibrium_interest
    from sbr_tpu.models.params import make_interest_params

    # `3_interest_rates.jl:37-46`.
    m = make_interest_params(
        beta=1.0, eta_bar=15.0, u=0.0, p=0.5, kappa=0.6, lam=0.01, r=0.06, delta=0.1
    )
    ls = solve_learning(m.learning)
    result = solve_equilibrium_interest(ls, m.economic)
    print(f"  interest: ξ* = {float(result.base.xi):.2f}, bankrun = {bool(result.base.bankrun)}")
    _save(plot_value_function(result, m.economic), figdir / "interest_rates/value_function.pdf")
    # Threshold curve u + rV(τ̄) on the hazard grid (`3_interest_rates.jl:141-146`).
    threshold = m.economic.u + m.economic.r * np.asarray(result.v)
    _save(
        plot_hazard_rate_decomposition(
            result.base, ls, m.economic, threshold_curve=threshold, threshold_label=r"$rV(\tau)$"
        ),
        figdir / "interest_rates/hazard_decomposition.pdf",
    )


def run_social(figdir: Path, fast: bool) -> set:
    """Section 4: social-learning fixed point vs word-of-mouth baseline
    (`scripts/4_social_learning.jl`)."""
    from sbr_tpu import make_model_params, solve_learning
    from sbr_tpu.baseline.solver import solve_equilibrium_baseline
    from sbr_tpu.figures.plotting import plot_equilibrium
    from sbr_tpu.social.solver import solve_equilibrium_social

    # `4_social_learning.jl:36-43`.
    m = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25)
    social = solve_equilibrium_social(m, tol=1e-4, max_iter=500)
    lr_wom = solve_learning(m.learning)
    baseline = solve_equilibrium_baseline(lr_wom, m.economic)

    # Cross-model comparison the reference prints (`4_social_learning.jl:65-81`).
    xi_s, xi_b = float(social.equilibrium.xi), float(baseline.xi)
    print(f"  social: ξ* = {xi_s:.2f} ({int(social.iterations)} iterations, "
          f"converged = {bool(social.converged)})")
    print(f"  baseline (WOM): ξ* = {xi_b:.2f}")
    if social.equilibrium.bankrun and baseline.bankrun:
        timing = "later" if xi_s > xi_b else "earlier"
        print(f"  Δξ* = {xi_s - xi_b:.3f} ({timing} with social learning)")

    # A no-run outcome legitimately skips its figure
    # (`4_social_learning.jl:104-118`); report the skip so the manifest
    # check can distinguish it from a failure.
    skipped = set()
    if bool(social.equilibrium.bankrun):
        _save(
            plot_equilibrium(social.equilibrium, social.learning, m.economic),
            figdir / "social_learning/social_learning_equilibrium.pdf",
        )
    else:
        print("  ! no social-learning equilibrium to plot (no bank run)")
        skipped.add("social_learning/social_learning_equilibrium.pdf")
    if bool(baseline.bankrun):
        _save(
            plot_equilibrium(baseline, lr_wom, m.economic),
            figdir / "social_learning/baseline_equilibrium.pdf",
        )
    else:
        print("  ! no baseline equilibrium to plot (no bank run)")
        skipped.add("social_learning/baseline_equilibrium.pdf")

    # Equilibrium→agent loop closure (VERDICT r2 task 2): feed the solved
    # fixed point's withdrawal window into the explicit-agent simulation and
    # plot both against the fixed point's own curves.
    if bool(social.equilibrium.bankrun):
        from sbr_tpu.figures.plotting import plot_agent_closure
        from sbr_tpu.social.closure import close_loop

        n, deg, dt = (20_000, 15.0, 0.1) if fast else (200_000, 60.0, 0.05)
        comp = close_loop(model=m, n_agents=n, avg_degree=deg, dt=dt, t_max=16.0, fp=social)
        print(
            f"  closure: {n:,} agents, window [{comp.exit_delay:.2f}, "
            f"{comp.reentry_delay:.2f}), AW sup-error {comp.err_aw_sup:.3f}"
        )
        _save(plot_agent_closure(comp), figdir / CLOSURE_FIG)
    return skipped


def write_tex(outdir: Path, sections: list, skip=()) -> Path:
    """Generate `replication_figures.tex` with the same section/figure
    structure as the reference (`output/replication_figures.tex:23-127`).
    Figures in ``skip`` (intentionally not generated, e.g. no-run outcomes)
    are omitted so the document always compiles."""
    titles = {
        1: "Baseline Model",
        2: "Heterogeneity Extension",
        3: "Interest Rates Extension",
        4: "Social Learning Extension",
    }
    captions = {
        PAPER_HEATMAP: r"Peak withdrawals over the $\beta \times u$ grid (paper resolution)",
        CLOSURE_FIG: "Explicit-agent population under the equilibrium withdrawal window vs the social-learning fixed point",
        "baseline/learning_dynamics.pdf": r"Learning dynamics for different communication speeds $\beta$",
        "baseline/hazard_rate.pdf": "Hazard rate decomposition: total hazard, belief fragility, and conditional hazard",
        "baseline/equilibrium_dynamics_main.pdf": "Equilibrium dynamics: aggregate withdrawals (main calibration)",
        "baseline/equilibrium_dynamics_fast.pdf": r"Equilibrium dynamics with fast communication ($\beta = 3$)",
        "baseline/equilibrium_dynamics_low_u.pdf": "Equilibrium dynamics with low deposit utility ($u = 0.01$)",
        "baseline/comp_stat_u_panel_a.pdf": "Comparative statics in $u$: peak withdrawals",
        "baseline/comp_stat_u_panel_b.pdf": "Comparative statics in $u$: collapse and return times",
        "baseline/comp_stat_cross_heatmap_AW.pdf": r"Peak withdrawals over the $\beta \times u$ grid",
        "heterogeneity/aggregate_withdrawals_hetero.pdf": "Aggregate withdrawals with heterogeneous learning speeds",
        "interest_rates/value_function.pdf": "Depositor value function with positive interest",
        "interest_rates/hazard_decomposition.pdf": "Hazard decomposition with the $rV$ threshold",
        "social_learning/social_learning_equilibrium.pdf": "Equilibrium under social learning from withdrawals",
        "social_learning/baseline_equilibrium.pdf": "Baseline (word-of-mouth) equilibrium at the same parameters",
    }
    lines = [
        r"\documentclass[12pt]{article}",
        r"\usepackage{graphicx}",
        r"\usepackage{float}",
        r"\usepackage[margin=1in]{geometry}",
        r"\title{Replication Figures\\The Social Determinants of Bank Runs\\"
        r"(sbr\_tpu TPU-native framework)}",
        r"\date{\today}",
        r"\begin{document}",
        r"\maketitle",
        r"\section*{Note}",
        "This document collects all figures generated by the sbr\\_tpu",
        "replication run, organized as in the reference package: the baseline",
        "model and its three extensions.",
    ]
    figdir = outdir / "figures"
    outdir.mkdir(parents=True, exist_ok=True)  # nothing else creates it when
    # no section ran (e.g. --sections "" smoke/paper-only invocations)
    for sec in sections:
        lines.append(rf"\section{{{titles[sec]}}}")
        # Extra figures join their section when present on disk.
        extras = [f for f in _EXTRAS.get(sec, []) if (figdir / f).exists()]
        for fig in MANIFEST[sec] + extras:
            if fig in skip:
                continue
            lines += [
                r"\begin{figure}[H]",
                r"    \centering",
                rf"    \includegraphics[width=0.7\textwidth]{{figures/{fig}}}",
                rf"    \caption{{{captions[fig]}}}",
                r"\end{figure}",
            ]
    lines.append(r"\end{document}")
    tex_path = outdir / "replication_figures.tex"
    tex_path.write_text("\n".join(lines) + "\n")
    return tex_path


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="Generate all replication figures (MASTER.jl equivalent)")
    parser.add_argument("--output", default="output", help="output directory (default: output/)")
    parser.add_argument("--sections", default="1,2,3,4", help="comma-separated sections to run")
    parser.add_argument("--fast", action="store_true", help="reduced sweep resolutions for smoke runs")
    parser.add_argument("--f32", action="store_true", help="run in float32 (default float64 parity mode)")
    parser.add_argument(
        "--obs",
        action="store_true",
        help="write run telemetry (events.jsonl + manifest.json) under OUTPUT/obs/; "
        "render with `python -m sbr_tpu.obs.report <run_dir>` "
        "(SBR_OBS=1 in the environment enables the same thing)",
    )
    parser.add_argument(
        "--paper",
        action="store_true",
        help="also generate the paper-resolution heatmap via tiled checkpoint/resume "
        "(the reference's 'couple hours' 5000x5000 grid; interruptible + resumable)",
    )
    parser.add_argument(
        "--platform",
        choices=("default", "cpu"),
        default="default",
        help="pin the JAX platform: 'cpu' avoids touching a (possibly hung) "
        "accelerator tunnel; 'default' uses whatever backend JAX selects",
    )
    parser.add_argument("--paper-res", type=int, default=5000, help="paper heatmap resolution")
    parser.add_argument("--paper-tile", type=int, default=500, help="paper heatmap tile size")
    parser.add_argument(
        "--checkpoint-dir",
        default=None,
        help="tile checkpoint dir for --paper (default: OUTPUT/checkpoints/heatmap_large)",
    )
    args = parser.parse_args(argv)

    # Headless backend for the CLI only — library imports leave the
    # user's matplotlib backend alone (sbr_tpu.figures.plotting docstring).
    import matplotlib

    matplotlib.use("Agg")

    import jax

    if args.platform == "cpu":
        from sbr_tpu.utils.platform import pin_cpu_platform

        pin_cpu_platform()
        # the pin silently no-ops if a backend is already initialized in
        # this process (programmatic callers), so verify unconditionally —
        # proceeding onto a non-CPU backend would defeat the flag's purpose
        if jax.devices()[0].platform != "cpu":
            print("error: --platform cpu requested but a non-CPU JAX "
                  "backend is already initialized in this process",
                  file=sys.stderr)
            return 1
    if not args.f32:
        jax.config.update("jax_enable_x64", True)
    # Persistent compilation cache: the run is compile-dominated (execution
    # is ms; the f64 vmapped sweeps and the fixed-point while_loop take
    # minutes to compile), so reruns should pay zero.
    jax.config.update("jax_compilation_cache_dir", str(Path.home() / ".cache/sbr_tpu_xla"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    outdir = Path(args.output)
    figdir = outdir / "figures"
    sections = sorted({int(s) for s in args.sections.split(",") if s.strip()})
    runners = {1: run_baseline, 2: run_heterogeneity, 3: run_interest, 4: run_social}
    names = {1: "Baseline", 2: "Heterogeneity", 3: "Interest Rates", 4: "Social Learning"}
    slugs = {1: "baseline", 2: "heterogeneity", 3: "interest_rates", 4: "social_learning"}

    from sbr_tpu import obs

    obs_ctx = (
        obs.run_context(label="figures", root=str(outdir / "obs"))
        if args.obs
        else contextlib.nullcontext()
    )

    t_start = time.time()
    skipped = set()
    # Combined figure document, composed from the live figures as they are
    # generated (the reference's output/replication_figures.pdf is the same
    # document compiled via LaTeX, unavailable in this image).
    global _PDF_DOC, _PDF_PENDING_HEADER
    # reset collector state at entry: a prior partial --sections run in the
    # same process leaves a stale pending header otherwise (ADVICE r3)
    _PDF_DOC = None
    _PDF_PENDING_HEADER = None
    doc_path = outdir / "replication_figures.pdf"
    doc_tmp = outdir / "replication_figures.pdf.tmp"
    doc = None
    # The combined document is only assembled on FULL-replication runs:
    # PdfPages cannot extend an existing file, so a partial --sections run
    # would otherwise replace a complete document with just its own slice
    # (the .tex document, which CAN reflect everything on disk, remains the
    # partial-run view). Figures render twice on full runs (disk + doc page)
    # — acceptable since all figures are small (the 5000x5000 heatmap is
    # rasterized, ~32 KB).
    if set(sections) == set(MANIFEST):
        from matplotlib.backends.backend_pdf import PdfPages

        outdir.mkdir(parents=True, exist_ok=True)
        # write to a temp path and rename on clean completion, so a crash
        # never destroys a previously complete document
        doc = PdfPages(doc_tmp)
        _pdf_text_page(
            doc,
            ["Replication Figures", "The Social Determinants of Bank Runs",
             "(sbr_tpu TPU-native framework)"],
            size=22,
        )
        _PDF_DOC = doc
    ok_run = False
    obs_run = None
    try:
        with obs_ctx as obs_run:
            for sec in sections:
                print("=" * 70)
                print(f"SECTION {sec}/4: {names[sec]}")
                print("=" * 70)
                _PDF_PENDING_HEADER = names[sec]
                t0 = time.time()
                with obs.span(f"figures.{slugs[sec]}", fast=args.fast):
                    skipped |= runners[sec](figdir, args.fast) or set()
                print(f"  section time: {time.time() - t0:.1f}s")

            if args.paper:
                print("=" * 70)
                print("PAPER-RESOLUTION HEATMAP (tiled, resumable)")
                print("=" * 70)
                _PDF_PENDING_HEADER = "Paper-resolution heatmap"
                t0 = time.time()
                ckpt = Path(args.checkpoint_dir) if args.checkpoint_dir else outdir / "checkpoints/heatmap_large"
                with obs.span("figures.paper_heatmap", res=args.paper_res, tile=args.paper_tile):
                    run_paper_heatmap(figdir, ckpt, args.paper_res, args.paper_tile)
                print(f"  paper heatmap time: {time.time() - t0:.1f}s")
        ok_run = True
    finally:
        if doc is not None:
            _PDF_DOC = None
            _PDF_PENDING_HEADER = None
            doc.close()
            if ok_run:
                import os as _os

                _os.replace(doc_tmp, doc_path)
            else:
                doc_tmp.unlink(missing_ok=True)

    # The tex document reflects everything present on disk (not just the
    # sections run now), so partial --sections runs extend rather than
    # clobber a previously generated full document.
    not_on_disk = {
        f for sec in MANIFEST for f in MANIFEST[sec] if not (figdir / f).exists()
    }
    tex_sections = [
        s
        for s in MANIFEST
        if set(MANIFEST[s]) - not_on_disk
        or any((figdir / f).exists() for f in _EXTRAS.get(s, []))
    ]
    tex_path = write_tex(outdir, tex_sections, skip=not_on_disk)
    total = time.time() - t_start

    print("=" * 70)
    print("REPLICATION COMPLETE")
    print(f"Total execution time: {total:.1f} seconds")
    expected = [f for sec in sections for f in MANIFEST[sec]]
    if args.paper:
        expected.append(PAPER_HEATMAP)
    print(f"Figures ({len(expected)} expected):")
    missing = []
    for fig in expected:
        if fig in skipped:
            print(f"  - {figdir / fig} (skipped: no-run outcome)")
            continue
        ok = (figdir / fig).exists()
        print(f"  {'✓' if ok else '✗'} {figdir / fig}")
        if not ok:
            missing.append(fig)
    if doc is not None and doc_path.exists():
        print(f"  ✓ {doc_path} (combined figure document)")
    print(f"  ✓ {tex_path}")
    if obs_run is not None:
        print(f"  ✓ {obs_run.run_dir} (run telemetry; "
              f"render: python -m sbr_tpu.obs.report {obs_run.run_dir})")
    return 1 if missing else 0


if __name__ == "__main__":
    sys.exit(main())
