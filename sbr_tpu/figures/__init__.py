"""Figure parity layer — matplotlib equivalents of the reference's 13 figures.

Reference: `src/baseline/plotting.jl` (four shared plot functions) plus the
extension scripts' inline figures (`scripts/2_heterogeneity.jl:97-124`,
`scripts/3_interest_rates.jl:80-183`, `scripts/4_social_learning.jl:101-119`).
The CLI runner (`python -m sbr_tpu.figures.master`) is the MASTER.jl
equivalent: it produces every figure PDF plus `replication_figures.tex`.
"""

from sbr_tpu.figures.plotting import (
    plot_aw_hetero,
    plot_comp_stat_withdrawals_and_collapse,
    plot_equilibrium,
    plot_hazard_rate_decomposition,
    plot_heatmap_aw,
    plot_learning_distribution,
    plot_value_function,
)

__all__ = [
    "plot_aw_hetero",
    "plot_comp_stat_withdrawals_and_collapse",
    "plot_equilibrium",
    "plot_hazard_rate_decomposition",
    "plot_heatmap_aw",
    "plot_learning_distribution",
    "plot_value_function",
]
