"""Matplotlib figure builders mirroring the reference's plot semantics.

Each builder returns a `matplotlib.figure.Figure`; saving is the caller's
job (the master runner writes PDFs). Curve data comes from the solver
result pytrees — figures never re-solve anything. Citations point at the
reference's plotting code (`src/baseline/plotting.jl`, script-inline
figures) whose *content* these reproduce; the implementation is matplotlib
idiom, not a port of Plots.jl calls.

The non-interactive Agg backend is selected by the master CLI entry point
(the reference forces the GR backend similarly, `scripts/1_baseline.jl:19`);
importing this module does NOT switch the backend, so interactive sessions
that import sbr_tpu.figures keep whatever backend they had.
"""

from __future__ import annotations

from typing import Optional, Sequence

import matplotlib.pyplot as plt
import numpy as np

from sbr_tpu.baseline.solver import get_aw, hazard_rate
from sbr_tpu.models.params import SolverConfig

# The reference's palette (`plotting.jl:31`, `2_heterogeneity.jl:92`).
_SERIES_COLORS = ["tab:blue", "tab:red", "tab:green", "tab:purple", "tab:orange"]
_GROUP_COLORS = ["royalblue", "darkgreen", "mediumvioletred", "darkorange"]


def _new_axes(title: str, xlabel: str, ylabel: str):
    fig, ax = plt.subplots(figsize=(6.4, 4.4))
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(True, alpha=0.4)
    return fig, ax


def plot_learning_distribution(
    learning_solutions: Sequence,
    tspan,
    beta_values: Sequence[float],
    labels: Optional[Sequence[str]] = None,
):
    """Figure 1 — learning CDFs for several β (`plotting.jl:24-40`)."""
    fig, ax = _new_axes("Learning Dynamics", "Time", "Fraction Informed")
    t = np.linspace(tspan[0], tspan[1], 1000)
    for i, ls in enumerate(learning_solutions):
        vals = np.asarray(ls.cdf_at(t))
        label = labels[i] if labels is not None else rf"$\beta = {beta_values[i]}$"
        ax.plot(t, vals, lw=1.5, color=_SERIES_COLORS[i % len(_SERIES_COLORS)], label=label)
    ax.legend(loc="lower right")
    return fig


def plot_hazard_rate_decomposition(
    result,
    ls,
    econ,
    config: SolverConfig | None = None,
    threshold_curve: Optional[np.ndarray] = None,
    threshold_label: Optional[str] = None,
):
    """Figure 2 — hazard decomposition h(τ) = π(τ)·h_f(τ) in normal time
    (`plotting.jl:62-132`).

    With ``threshold_curve`` (values on the result's tau_grid), draws the
    interest-rate extension's u + rV(τ) threshold instead of the flat u line
    (`scripts/3_interest_rates.jl:141-156`).
    """
    if config is None:
        config = SolverConfig()
    xi = float(result.xi)
    eta = float(econ.eta)
    u = float(econ.u)

    # Reversed-time components: total hazard (prior p) and conditional
    # fragile hazard (p = 1); belief π is their ratio, clamped to [0, 1].
    tau_grid, hr_fragile = hazard_rate(1.0, econ.lam, ls, eta, config)
    _, hr_total = hazard_rate(econ.p, econ.lam, ls, eta, config)
    tau_grid = np.asarray(tau_grid)
    h_f = np.asarray(hr_fragile)
    h = np.asarray(hr_total)
    with np.errstate(invalid="ignore", divide="ignore"):
        pi = np.clip(np.nan_to_num(h / h_f, nan=0.0), 0.0, 1.0)

    # Normal time t = ξ - τ̄ (`plotting.jl:95-104`): evaluate at τ̄ = ξ - t
    # for t ∈ [0, ξ], clamping into the hazard grid's domain.
    t_plot = np.linspace(0.0, xi, 1000)
    tau_eval = np.clip(xi - t_plot, 0.0, min(1.3 * xi, eta))
    h_t = np.interp(tau_eval, tau_grid, h)
    pi_t = np.interp(tau_eval, tau_grid, pi)
    h_f_t = np.interp(tau_eval, tau_grid, h_f)

    fig, ax = _new_axes(
        r"$h(\tau) = \pi(\tau) \times h_f(\tau)$", r"Time since learning $(\tau)$", "Hazard Rate"
    )
    ax.plot(t_plot, h_t, lw=1.5, color="mediumvioletred", label=r"$h(\tau)$ - Total hazard")
    ax.plot(t_plot, pi_t, lw=1.0, color="royalblue", label=r"$\pi(\tau)$ - Belief fragile")
    ax.plot(t_plot, h_f_t, lw=1.0, color="tomato", label=r"$h_f(\tau)$ - Conditional hazard")

    if threshold_curve is not None:
        thr_t = np.interp(tau_eval, tau_grid, np.asarray(threshold_curve))
        ax.plot(t_plot, thr_t, lw=1.0, color="darkgray")
        ax.annotate(
            threshold_label or r"$u + rV(\tau)$",
            (0.7 * xi, 1.15 * thr_t[len(thr_t) // 2]),
            color="darkgray",
            fontsize=10,
        )
    else:
        ax.axhline(u, color="darkgray", lw=1.0)
        ax.annotate(f"$u = {u}$", (0.7 * xi, 1.3 * u if u > 0 else 0.02), color="darkgray", fontsize=10)

    mid_h_f = float(np.interp(0.5 * (tau_eval[0] + tau_eval[-1]), tau_grid, h_f))
    ax.axvline(xi, color="darkgoldenrod", lw=1.5, ls="-.")
    ax.annotate(
        rf"$\xi = {xi:.1f}$", (1.02 * xi, mid_h_f), color="darkgoldenrod", fontsize=10, ha="left"
    )
    ax.set_xlim(0, 1.2 * xi)
    ax.set_ylim(0, 1.2 * mid_h_f)
    ax.legend(loc="upper left")
    return fig


def plot_equilibrium(result, ls, econ, x_range=None, y_range=None):
    """Figure 3 family — AW dynamics with ξ/κ annotations and the
    return-time arrow (`plotting.jl:156-210`)."""
    xi = float(result.xi)
    kappa = float(econ.kappa)
    eta = float(econ.eta)
    tau_in = float(result.tau_in)

    t_grid = np.arange(0.0, min(2.0 * xi, eta) + 1e-9, 0.1)
    aw_cum, aw_out, aw_in = (
        np.asarray(a)
        for a in get_aw(result.xi, result.tau_bar_in_unc, result.tau_bar_out_unc, t_grid, ls)
    )

    fig, ax = _new_axes("Aggregate Withdrawals", "Time", "AW(t)")
    ax.plot(t_grid, aw_cum, color="darkred", lw=2, label="AW")
    ax.plot(t_grid, aw_out, color="darkred", ls="--", label="Informed")
    ax.plot(t_grid, aw_in, color="royalblue", ls="--", label="Reentered")

    ax.axvline(xi, color="darkgoldenrod", lw=2)
    ax.annotate(rf"$\xi = {xi:.1f}$", (xi + 0.4, 0.9), color="darkgoldenrod", fontsize=7)
    ax.axhline(kappa, color="grey", lw=1)
    ax.annotate(rf"$\kappa = {kappa:.2f}$", (xi / 2, kappa + 0.015), color="grey", fontsize=7)

    # Two-sided "return after τ_IN" arrow (`plotting.jl:203-209`).
    x0 = 0.8 * xi
    y0 = float(np.interp(x0, t_grid, aw_out))
    ax.annotate(
        "",
        xy=(x0 + tau_in, y0),
        xytext=(x0, y0),
        arrowprops=dict(arrowstyle="<->", color="darkgreen", lw=2),
    )
    ax.annotate(
        f"Return after {tau_in:.2f}",
        (x0 + tau_in / 2, y0 - 0.04),
        color="darkgreen",
        fontsize=6,
        ha="center",
    )

    ax.set_ylim(y_range if y_range is not None else (0, 1))
    if x_range is not None:
        ax.set_xlim(x_range)
    ax.legend(loc="upper left")
    return fig


def _shade_no_run(ax, x_values, invalid_mask):
    """Grey 'No Bank Run' band over a contiguous NaN region
    (`plotting.jl:253-268`)."""
    idx = np.flatnonzero(invalid_mask)
    if idx.size > 1:
        ax.axvspan(x_values[idx[0]], x_values[idx[-1]], color="gray", alpha=0.2)
        y0, y1 = ax.get_ylim()
        ax.annotate(
            "No Bank Run",
            ((x_values[idx[0]] + x_values[idx[-1]]) / 2, (y0 + y1) / 2),
            fontsize=8,
            rotation=90,
            ha="center",
            va="center",
        )


def plot_comp_stat_withdrawals_and_collapse(
    u_values, max_withdrawals, collapse_times, kappa, return_times=None
):
    """Figure 4 — two panels: peak withdrawals and collapse/return times vs
    u, with no-run shading (`plotting.jl:233-302`). Returns (fig_a, fig_b)."""
    u_values = np.asarray(u_values)
    max_withdrawals = np.asarray(max_withdrawals)
    collapse_times = np.asarray(collapse_times)
    kappa = float(kappa)

    fig_a, ax_a = _new_axes("(a) Effect on Peak Withdrawals", "Deposit Utility (u)", "Peak Withdrawals")
    ax_a.plot(u_values, max_withdrawals, color="darkred")
    ax_a.set_ylim(0, 1)
    ax_a.axhline(kappa, color="grey", lw=1, ls="--")
    ax_a.annotate(f"$\\kappa = {kappa}$", (u_values[0] + 0.03, kappa + 0.025), color="grey", fontsize=8)
    _shade_no_run(ax_a, u_values, np.isnan(max_withdrawals))

    fig_b, ax_b = _new_axes("(b) Collapse Time and Return Time", "Deposit Utility (u)", "Time")
    valid = ~np.isnan(collapse_times)
    ax_b.plot(u_values[valid], collapse_times[valid], color="darkgoldenrod", ls="--", label="Collapse Time")
    if return_times is not None:
        return_times = np.asarray(return_times)
        valid_r = ~np.isnan(return_times)
        ax_b.plot(u_values[valid_r], return_times[valid_r], label="Return Time")
    _shade_no_run(ax_b, u_values, ~valid)
    ax_b.legend(loc="upper right")
    return fig_a, fig_b


def plot_heatmap_aw(ave_meeting_time, u_values, max_aw_matrix):
    """Figure 5 — β×u peak-withdrawal heatmap, x = average meeting time
    = 1/β (`scripts/1_baseline.jl:278-284`). ``max_aw_matrix`` is (U, B)
    like the reference's storage (`1_baseline.jl:213`)."""
    fig, ax = _new_axes("Peak Withdrawals", "Average meeting time", "Deposit Utility")
    ax.grid(False)
    mesh = ax.pcolormesh(
        np.asarray(ave_meeting_time),
        np.asarray(u_values),
        np.asarray(max_aw_matrix),
        cmap="viridis",
        alpha=0.8,
        shading="auto",
        # Embed as an image: at the paper's 5000×5000 resolution a vector
        # mesh would be 25M path objects and a several-hundred-MB PDF.
        rasterized=True,
    )
    fig.colorbar(mesh, ax=ax)
    return fig


def plot_aw_hetero(result, aw, econ, betas):
    """Heterogeneity figure — total AW plus per-group decomposition
    (`scripts/2_heterogeneity.jl:97-124`)."""
    xi = float(result.xi)
    kappa = float(econ.kappa)
    t = np.asarray(aw.t_grid)
    sel = t <= 2.0 * xi

    fig, ax = _new_axes("Aggregate Withdrawals - Heterogeneous Groups", "Time", "AW(t)")
    ax.plot(t[sel], np.asarray(aw.aw_cum)[sel], color="darkred", lw=2, label="Total AW")
    for k, beta_k in enumerate(betas):
        ax.plot(
            t[sel],
            np.asarray(aw.aw_groups)[k][sel],
            ls="--",
            color=_GROUP_COLORS[k % len(_GROUP_COLORS)],
            label=rf"Group {k + 1} ($\beta$={beta_k})",
        )
    ax.axhline(kappa, color="grey", lw=1)
    ax.annotate(rf"$\kappa = {kappa:.2f}$", (xi / 2, kappa + 0.015), color="grey", fontsize=7)
    ax.axvline(xi, color="darkgoldenrod", lw=2)
    ax.annotate(rf"$\xi = {xi:.1f}$", (xi + 0.4, kappa * 0.85), color="darkgoldenrod", fontsize=7)
    ax.legend(loc="upper left")
    return fig


def plot_value_function(result_interest, econ):
    """Interest-rate figure — V in normal time t = ξ - τ̄ with the terminal
    value δ/(δ-r) (`scripts/3_interest_rates.jl:83-113`)."""
    base = result_interest.base
    xi = float(base.xi)
    tau = np.asarray(base.tau_grid)
    v = np.asarray(result_interest.v)

    t = xi - tau
    keep = t >= 0
    order = np.argsort(t[keep])

    fig, ax = _new_axes("Value Function", "Time", "Value V(t)")
    ax.plot(t[keep][order], v[keep][order], color="royalblue", lw=2, label="V(t)")
    v_terminal = econ.delta / (econ.delta - econ.r)
    ax.axhline(
        v_terminal, color="darkgray", ls="--", lw=1, label=f"Terminal value = {v_terminal:.2f}"
    )
    ax.set_xlim(0, float(t[keep].max()))
    ax.legend(loc="upper left")
    return fig


def plot_agent_closure(comp):
    """Equilibrium→agent loop closure (VERDICT r2 task 2): the explicit-agent
    population, run with the withdrawal window derived FROM the solved social
    fixed point, against that fixed point's own AW(t) and G(t) curves
    (`social_learning_solver.jl:63-263` + `solver.jl:495-532`). ``comp`` is a
    `sbr_tpu.social.closure.LoopComparison`."""
    fig, (ax_aw, ax_g) = plt.subplots(1, 2, figsize=(11.0, 4.2))
    t = np.asarray(comp.t)
    for ax, fp, sim, name in (
        (ax_aw, comp.aw_fp, comp.aw_sim, "AW(t)"),
        (ax_g, comp.g_fp, comp.g_sim, "G(t)"),
    ):
        ax.plot(t, fp, color="tab:blue", lw=2, label="fixed point (ODE)")
        ax.plot(
            t, sim, color="tab:red", lw=1.2, ls="--",
            label=f"{comp.n_agents:,} agents" + (f" (mean of {comp.n_reps})" if comp.n_reps > 1 else ""),
        )
        ax.set_xlabel("Time")
        ax.set_ylabel(name)
        ax.grid(True, alpha=0.4)
    xi = float(comp.fp.equilibrium.xi)
    ax_aw.axvline(xi, color="darkgoldenrod", lw=1.5)
    ax_aw.annotate(rf"$\xi = {xi:.1f}$", (xi + 0.2, 0.02), color="darkgoldenrod", fontsize=7)
    ax_aw.set_title(
        rf"Withdrawals: window [$\xi-\bar\tau_{{OUT}}^{{CON}}$, $\xi-\bar\tau_{{IN}}^{{CON}}$)"
        rf" = [{comp.exit_delay:.2f}, {comp.reentry_delay:.2f})"
        f"; sup-error {comp.err_aw_sup:.3f}"
    )
    ax_g.set_title(f"Learning; RMS error {comp.err_g_rms:.3f}")
    ax_aw.legend(loc="upper left", fontsize=8)
    fig.tight_layout()
    return fig
