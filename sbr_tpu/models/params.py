"""Parameter pytrees with the reference's defaults, derivations, validation,
and copy-with-override semantics.

Mirrors `src/baseline/model.jl`, `extensions/heterogeneity/
heterogeneity_model.jl`, `extensions/interest_rates/interest_rate_model.jl`:

- η = η_bar / β when η is not given (`model.jl:161-164`); for the hetero
  family η = η_bar / ⟨β⟩ with ⟨β⟩ = Σ dist·β (`heterogeneity_model.jl:130-132`).
- default tspan = (0, 2η) (`model.jl:166-169`).
- copy-with-overrides carries the RESOLVED η of the base unless η is
  overridden explicitly (`model.jl:189-211` merges ``current`` — which pins
  η = base.economic.η — before re-invoking the keyword constructor). This is
  observable: the Figure-5 heatmap sweeps β via the copy constructor, so every
  cell keeps the base model's η = 15 rather than recomputing η_bar/β
  (`scripts/1_baseline.jl:226`). Parity requires reproducing it.

Fields are stored as plain floats / tuples so parameter structs are static
hashable jit arguments; sweeps vmap over raw value arrays fed into the scalar
solver entry points instead of stacking structs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np


def _check(cond, msg: str) -> None:
    """Validate a constructor invariant — skipping TRACED conditions.

    Parameter structs double as grad inputs (ISSUE 13): `sbr_tpu.grad`
    builds `make_model_params(beta=traced_scalar, ...)` inside jit/grad so
    parameter pytrees can flow through `jax.grad`. A traced comparison has
    no concrete truth value; forcing ``bool(cond)`` would raise jax's
    TracerBoolConversionError (and silently coercing the VALUE to float
    would cut the tangent — the historical bug this guard replaces). Traced
    invariants are therefore deferred: the numerics handle out-of-domain
    values the same way the solvers do (NaN/status codes), and concrete
    construction keeps failing loudly exactly as before.
    """
    try:
        ok = bool(cond)
    except Exception as err:
        # Only jax's trace-time concretization errors defer validation; any
        # other truth-evaluation failure (e.g. numpy's ambiguous-array
        # ValueError for a vector parameter) must stay a loud construction
        # error, exactly as before.
        if type(err).__name__ in (
            "TracerBoolConversionError", "ConcretizationTypeError",
        ):
            return
        raise
    if not ok:
        raise ValueError(msg)


@dataclasses.dataclass(frozen=True)
class LearningParams:
    """Stage-1 learning inputs (`model.jl:24-44`)."""

    beta: float
    tspan: Tuple[float, float]
    x0: float

    def __post_init__(self):
        _check(self.beta > 0, f"Communication speed beta must be positive, got {self.beta}")
        _check(len(self.tspan) == 2, "tspan must have length 2")
        _check(self.tspan[0] >= 0, f"Start time must be non-negative, got {self.tspan[0]}")
        _check(self.tspan[1] > self.tspan[0], f"End time must exceed start time, got {self.tspan}")
        _check(self.x0 >= 0, f"Initial condition x0 must be non-negative, got {self.x0}")


@dataclasses.dataclass(frozen=True)
class EconomicParams:
    """Stage-2/3 economic fundamentals (`model.jl:61-85`) plus the policy
    knobs of the composable scenario engine (ISSUE 14) — inert unless a
    `scenario.ScenarioSpec` activates the matching modifier:

    - insurance_cap: insured deposit fraction c ∈ [0, 1); the
      ``insurance_cap`` hazard modifier scales the run hazard by (1 − c)
      (insured depositors abstain from the withdrawal race).
    - suspension_t: convertibility-suspension time s ≥ 0; the
      ``suspension`` modifier zeroes the hazard for τ̄ ≥ s (no benefit to
      running once withdrawals are frozen).
    - lolr_rate: lender-of-last-resort injection rate ρ ≥ 0; the ``lolr``
      modifier raises the effective solvency threshold to κ·(1 + ρ)
      (injected reserves let the bank survive a larger withdrawal share).

    Validation defers on traced values exactly like every other field
    (`_check` — the PR 12 traced-scalar contract), so policy parameters
    can flow through `jax.grad`/vmap sweeps.
    """

    u: float
    p: float
    kappa: float
    lam: float
    eta_bar: float
    eta: float
    insurance_cap: float = 0.0
    suspension_t: float = 0.0
    lolr_rate: float = 0.0

    def __post_init__(self):
        _check(self.u >= 0, f"Utility flow u must be non-negative, got {self.u}")
        _check(0 <= self.p <= 1, f"Prior probability p must be in [0,1], got {self.p}")
        _check(0 < self.kappa < 1, f"Solvency threshold kappa must be in (0,1), got {self.kappa}")
        _check(self.lam > 0, f"Exponential rate lam must be positive, got {self.lam}")
        _check(self.eta_bar > 0, f"Raw awareness window eta_bar must be positive, got {self.eta_bar}")
        _check(self.eta > 0, f"Normalized awareness window eta must be positive, got {self.eta}")
        # NOT a chained comparison: `0 <= x < 1` evaluates `and` on the
        # first traced operand BEFORE _check could defer it.
        _check(
            self.insurance_cap >= 0,
            f"Insured fraction insurance_cap must be in [0,1), got {self.insurance_cap}",
        )
        _check(
            self.insurance_cap < 1,
            f"Insured fraction insurance_cap must be in [0,1), got {self.insurance_cap}",
        )
        _check(
            self.suspension_t >= 0,
            f"Suspension time suspension_t must be non-negative, got {self.suspension_t}",
        )
        _check(
            self.lolr_rate >= 0,
            f"LOLR injection rate lolr_rate must be non-negative, got {self.lolr_rate}",
        )


@dataclasses.dataclass(frozen=True)
class ModelParams:
    learning: LearningParams
    economic: EconomicParams


def make_model_params(
    beta: float = 1.0,
    eta: Optional[float] = None,
    eta_bar: float = 15.0,
    u: float = 0.1,
    p: float = 0.5,
    kappa: float = 0.6,
    lam: float = 0.01,
    tspan: Optional[Tuple[float, float]] = None,
    x0: float = 0.0001,
    insurance_cap: float = 0.0,
    suspension_t: float = 0.0,
    lolr_rate: float = 0.0,
) -> ModelParams:
    """Keyword constructor with the reference defaults (`model.jl:150-176`).
    The policy knobs (ISSUE 14) default to the inert values — a params
    struct without them is indistinguishable from the pre-scenario form."""
    if eta is None:
        eta = eta_bar / beta
    if tspan is None:
        tspan = (0.0, 2.0 * eta)
    return ModelParams(
        learning=LearningParams(beta=beta, tspan=tspan, x0=x0),
        economic=EconomicParams(
            u=u, p=p, kappa=kappa, lam=lam, eta_bar=eta_bar, eta=eta,
            insurance_cap=insurance_cap, suspension_t=suspension_t,
            lolr_rate=lolr_rate,
        ),
    )


def with_overrides(base: ModelParams, **kwargs) -> ModelParams:
    """Copy-with-overrides (`model.jl:189-211`).

    Pins the base's resolved eta and tspan unless explicitly overridden —
    including when only beta or eta_bar change (see module docstring).
    """
    current = dict(
        beta=base.learning.beta,
        eta=base.economic.eta,
        eta_bar=base.economic.eta_bar,
        u=base.economic.u,
        p=base.economic.p,
        kappa=base.economic.kappa,
        lam=base.economic.lam,
        tspan=base.learning.tspan,
        x0=base.learning.x0,
        insurance_cap=base.economic.insurance_cap,
        suspension_t=base.economic.suspension_t,
        lolr_rate=base.economic.lolr_rate,
    )
    unknown = set(kwargs) - set(current)
    _check(not unknown, f"Unknown parameter overrides: {sorted(unknown)}")
    current.update(kwargs)
    return make_model_params(**current)


# The scalar leaves of a baseline ModelParams, in `solve_param_cell`
# column order first (beta, u, p, kappa, lam, eta, t0, t1, x0) plus
# eta_bar and the policy knobs (ISSUE 14) — the full information content
# of the struct.
PARAMS_LEAF_NAMES = (
    "beta", "u", "p", "kappa", "lam", "eta", "t0", "t1", "x0", "eta_bar",
    "insurance_cap", "suspension_t", "lolr_rate",
)


def params_to_pytree(params: ModelParams) -> dict:
    """Flatten a `ModelParams` into a plain ``{name: scalar}`` dict — the
    grad-input form (ISSUE 13): plain dataclasses are not jax pytrees, so
    `jax.grad`/`vmap` take this dict instead. Lossless: carries the RESOLVED
    eta and tspan, never re-derives them (the copy-constructor pinning
    contract of `with_overrides` — see the module docstring)."""
    return {
        "beta": params.learning.beta,
        "u": params.economic.u,
        "p": params.economic.p,
        "kappa": params.economic.kappa,
        "lam": params.economic.lam,
        "eta": params.economic.eta,
        "t0": params.learning.tspan[0],
        "t1": params.learning.tspan[1],
        "x0": params.learning.x0,
        "eta_bar": params.economic.eta_bar,
        "insurance_cap": params.economic.insurance_cap,
        "suspension_t": params.economic.suspension_t,
        "lolr_rate": params.economic.lolr_rate,
    }


def pytree_to_params(tree: dict) -> ModelParams:
    """Rebuild a `ModelParams` from `params_to_pytree`'s dict, exactly:
    eta/tspan come from the tree verbatim (no η̄/β re-derivation), so
    ``pytree_to_params(params_to_pytree(p)) == p`` for every p — including
    trees whose leaves are traced jax scalars (validation defers, see
    `_check`)."""
    unknown = set(tree) - set(PARAMS_LEAF_NAMES)
    _check(not unknown, f"Unknown params leaves: {sorted(unknown)}")
    missing = set(PARAMS_LEAF_NAMES) - set(tree)
    _check(not missing, f"Missing params leaves: {sorted(missing)}")
    return ModelParams(
        learning=LearningParams(
            beta=tree["beta"], tspan=(tree["t0"], tree["t1"]), x0=tree["x0"]
        ),
        economic=EconomicParams(
            u=tree["u"], p=tree["p"], kappa=tree["kappa"], lam=tree["lam"],
            eta_bar=tree["eta_bar"], eta=tree["eta"],
            insurance_cap=tree["insurance_cap"],
            suspension_t=tree["suspension_t"],
            lolr_rate=tree["lolr_rate"],
        ),
    )


# ---------------------------------------------------------------------------
# Heterogeneity family (`heterogeneity_model.jl`)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LearningParamsHetero:
    """K-group learning inputs (`heterogeneity_model.jl:25-51`).

    betas/dist are tuples so the struct stays hashable; solvers convert to
    arrays at trace time.
    """

    betas: Tuple[float, ...]
    dist: Tuple[float, ...]
    tspan: Tuple[float, float]
    x0: float

    def __post_init__(self):
        _check(len(self.betas) > 0, "betas must be non-empty")
        _check(all(b > 0 for b in self.betas), f"All learning rates must be positive, got {self.betas}")
        _check(
            len(self.dist) == len(self.betas),
            f"Distribution length {len(self.dist)} must match betas length {len(self.betas)}",
        )
        _check(all(d >= 0 for d in self.dist), f"Distribution weights must be non-negative, got {self.dist}")
        _check(
            abs(sum(self.dist) - 1.0) < 1e-10,
            f"Distribution must sum to 1, got sum = {sum(self.dist)}",
        )
        _check(self.tspan[0] >= 0 and self.tspan[1] > self.tspan[0], f"Bad tspan {self.tspan}")
        _check(self.x0 >= 0, f"Initial condition x0 must be non-negative, got {self.x0}")

    @property
    def n_groups(self) -> int:
        return len(self.betas)


@dataclasses.dataclass(frozen=True)
class ModelParamsHetero:
    learning: LearningParamsHetero
    economic: EconomicParams


def make_hetero_params(
    betas,
    dist,
    eta_bar: float = 15.0,
    u: float = 0.1,
    p: float = 0.5,
    kappa: float = 0.6,
    lam: float = 0.01,
    tspan: Optional[Tuple[float, float]] = None,
    x0: float = 0.0001,
) -> ModelParamsHetero:
    """Keyword constructor (`heterogeneity_model.jl:115-144`): η = η_bar/⟨β⟩."""
    betas = tuple(float(b) for b in betas)
    dist = tuple(float(d) for d in dist)
    beta_ave = float(np.dot(betas, dist))
    eta = eta_bar / beta_ave
    if tspan is None:
        tspan = (0.0, 2.0 * eta)
    return ModelParamsHetero(
        learning=LearningParamsHetero(betas=betas, dist=dist, tspan=tspan, x0=x0),
        economic=EconomicParams(u=u, p=p, kappa=kappa, lam=lam, eta_bar=eta_bar, eta=eta),
    )


# ---------------------------------------------------------------------------
# Interest-rate family (`interest_rate_model.jl`)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EconomicParamsInterest(EconomicParams):
    """Baseline economics + interest rate r and maturity δ with r < δ
    (`interest_rate_model.jl:25-60`)."""

    r: float = 0.0
    delta: float = 0.1

    def __post_init__(self):
        super().__post_init__()
        _check(self.r >= 0, f"Interest rate r must be non-negative, got {self.r}")
        _check(self.delta > 0, f"Recovery rate delta must be positive, got {self.delta}")
        _check(
            self.r < self.delta,
            f"Interest rate r must be less than recovery rate delta, got r={self.r}, delta={self.delta}",
        )


@dataclasses.dataclass(frozen=True)
class ModelParamsInterest:
    learning: LearningParams
    economic: EconomicParamsInterest


def make_interest_params(
    beta: float = 1.0,
    eta: Optional[float] = None,
    eta_bar: float = 15.0,
    u: float = 0.1,
    p: float = 0.5,
    kappa: float = 0.6,
    lam: float = 0.01,
    r: float = 0.0,
    delta: float = 0.1,
    tspan: Optional[Tuple[float, float]] = None,
    x0: float = 0.0001,
    insurance_cap: float = 0.0,
    suspension_t: float = 0.0,
    lolr_rate: float = 0.0,
) -> ModelParamsInterest:
    """Keyword constructor (`interest_rate_model.jl:120-150`)."""
    if eta is None:
        eta = eta_bar / beta
    if tspan is None:
        tspan = (0.0, 2.0 * eta)
    return ModelParamsInterest(
        learning=LearningParams(beta=beta, tspan=tspan, x0=x0),
        economic=EconomicParamsInterest(
            u=u, p=p, kappa=kappa, lam=lam, eta_bar=eta_bar, eta=eta, r=r, delta=delta,
            insurance_cap=insurance_cap, suspension_t=suspension_t,
            lolr_rate=lolr_rate,
        ),
    )


# ---------------------------------------------------------------------------
# Numerics configuration (new: the reference inherits adaptive ODE grids,
# `learning.jl:74-81`; here grid resolution is an explicit static choice).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SolverConfig:
    """Static numerics knobs, passed as a hashable jit argument.

    - n_grid: points on the [0, tspan_end] learning grid and the [0, η]
      hazard grid (replaces the adaptive grid of `learning.jl:51`).
    - bisect_iters: fixed bisection halvings (replaces the 10*eps(κ)
      tolerance exit of `solver.jl:310`; 90 halvings over-satisfy it in f64).
      Under ``numerics="adaptive"`` the same number is the Chandrupatla
      BUDGET — the while_loop's hard cap; typical cells exit in ~10-25.
    - ode_substeps: RK4 substeps per save interval for ODE-backed stages.
    - quad_order: Gauss-Legendre nodes per interval for closed-form
      integrands.
    - refine_crossings: refine buffer-time crossings to machine precision by
      bisection on the continuous exact hazard (closed-form Stage 1 only).
      Default True for scalar parity solves; the sweep entry points default
      it OFF — grid AW_max accuracy is interpolation-bound anyway, and the
      embedded per-cell bisection-with-quadrature dominates the vmap²
      program's compile time.
    - numerics (ISSUE 9): ``"adaptive"`` routes the solver hot loop through
      convergence-masked kernels — `core.rootfind.chandrupatla` for every
      bracketing root-find, `core.rootfind.threshold_crossings_masked` for
      the buffer crossings, `core.ode.bs32` for the hetero coupled-K ODE
      and the interest HJB, and Anderson/secant acceleration on the social
      fixed point. ``"fixed"`` is the bit-exact escape hatch: the exact
      pre-adaptive code paths (fixed-iteration `bisect`, scan crossings,
      fixed-substep RK4, plain damping), preserving byte-identical outputs
      for the chaos/golden/parity suites and stable cross-run tile-cache
      keys. The default ``"auto"`` resolves at construction from
      SBR_NUMERICS (``adaptive`` when unset); the resolved value is what
      hashes/serializes, so fingerprints and jit caches see only concrete
      modes.
    - ode_rtol / ode_atol: local-error tolerances for the adaptive
      embedded-pair integrator (ignored under ``numerics="fixed"``).
    """

    n_grid: int = 4096
    bisect_iters: int = 90
    ode_substeps: int = 2
    quad_order: int = 8
    refine_crossings: bool = True
    # Fraction of hazard-grid points allocated through the logistic
    # inverse-CDF map (closed-form Stage 1 only): resolves the 1/β-wide
    # transition that a uniform grid loses at β ≳ n_grid/η — without it the
    # highest-β columns of the Figure-5 heatmap mislabel running cells as
    # false equilibria (see baseline/solver.py::_warped_grid). 0 disables.
    grid_warp: float = 0.5
    numerics: str = "auto"
    ode_rtol: float = 1e-6
    ode_atol: float = 1e-9

    def __post_init__(self):
        _check(self.n_grid >= 16, "n_grid too small")
        _check(self.bisect_iters >= 1, "bisect_iters must be >= 1")
        _check(self.ode_substeps >= 1, "ode_substeps must be >= 1")
        _check(self.quad_order >= 1, "quad_order must be >= 1")
        _check(0.0 <= self.grid_warp <= 1.0, "grid_warp must be in [0, 1]")
        if self.numerics == "auto":
            import os

            resolved = os.environ.get("SBR_NUMERICS", "").strip().lower() or "adaptive"
            object.__setattr__(self, "numerics", resolved)
        _check(
            self.numerics in ("adaptive", "fixed"),
            f"numerics must be 'adaptive', 'fixed', or 'auto', got {self.numerics!r}",
        )
        _check(self.ode_rtol > 0, "ode_rtol must be positive")
        _check(self.ode_atol > 0, "ode_atol must be positive")

    @property
    def adaptive(self) -> bool:
        """Whether the convergence-masked adaptive kernels are active."""
        return self.numerics == "adaptive"
