"""Validated parameter and result pytrees (reference `src/baseline/model.jl`,
`heterogeneity_model.jl`, `interest_rate_model.jl`)."""

from sbr_tpu.models.params import (
    EconomicParams,
    EconomicParamsInterest,
    LearningParams,
    LearningParamsHetero,
    ModelParams,
    ModelParamsHetero,
    ModelParamsInterest,
    SolverConfig,
    make_hetero_params,
    make_interest_params,
    make_model_params,
    with_overrides,
)
from sbr_tpu.models.results import (
    EquilibriumResult,
    LearningSolution,
    Status,
)
