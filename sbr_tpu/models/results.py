"""Result pytrees.

The reference carries solutions in mutable structs with lazily-cached
interpolation objects (`SolvedModel`, `src/baseline/solver.jl:55-109`;
`get_AW_functions!` cache at :553-576). Here results are immutable pytrees of
arrays: every derived curve is computed inside jit and XLA dead-code-eliminates
whatever the caller does not use, which subsumes the lazy-cache mechanism
without mutation. No-run cells carry NaN plus an integer status code instead
of Julia-side branching (`solver.jl:341-372`), preserving the reference's NaN
semantics inside vmap.
"""

from __future__ import annotations

import enum
from typing import Optional

import jax.numpy as jnp
from flax import struct

from sbr_tpu.diag.health import Health


def _fmt(x, digits: int = 6) -> str:
    """Human-readable scalar for result ``__repr__``s (reference `Base.show`,
    `model.jl:218-245`, `solver.jl:116-129`): 0-d arrays print as numbers,
    batched leaves summarize as shape — reprs must stay cheap and safe for
    vmapped results."""
    import jax as _jax

    try:
        arr = jnp.asarray(x)
    except Exception:
        return repr(x)
    # NB: under an active trace even jnp.asarray(0.0) yields a Tracer, so
    # the guard must run on the CONVERTED value
    if isinstance(arr, _jax.core.Tracer):
        return f"<traced {getattr(arr, 'aval', '?')}>"
    if arr.ndim > 0:
        return f"<{arr.shape} {arr.dtype}>"
    v = arr.item()
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, float):
        return f"{v:.{digits}g}"
    return str(v)


class Status(enum.IntEnum):
    """Per-cell outcome codes (SURVEY §5.5: structured status instead of prints).

    - RUN: valid bank-run equilibrium (reference: bankrun=true).
    - NO_CROSSING: u at/above the max of the (effective) hazard, buffers
      coincide — trivially no run (`solver.jl:429-433`).
    - NO_ROOT: bisection found no root of AW(ξ)=κ in the bracket
      (`solver.jl:316-324` interval-collapse / non-convergence → NaN).
    - FALSE_EQ: root lies on the decreasing branch of the withdrawal path —
      slope check rejected it (`solver.jl:353-362`).
    """

    RUN = 0
    NO_CROSSING = 1
    NO_ROOT = 2
    FALSE_EQ = 3


@struct.dataclass
class LearningSolution:
    """Stage-1 output (reference `LearningResults`, `learning.jl:74-81`).

    Uniform-grid samples of the CDF/PDF plus, when ``closed_form`` is set,
    the exact logistic parameters — in which case evaluators bypass
    interpolation entirely (the reference always interpolates its adaptive
    grid, `learning.jl:52`).
    """

    grid: jnp.ndarray  # (n,) uniform time grid over tspan
    cdf: jnp.ndarray  # (n,) G(t) samples
    pdf: jnp.ndarray  # (n,) g(t) samples
    t0: jnp.ndarray  # scalar, grid start
    dt: jnp.ndarray  # scalar, grid spacing
    beta: jnp.ndarray  # scalar learning rate (closed-form evaluation)
    x0: jnp.ndarray  # scalar initial condition
    closed_form: bool = struct.field(pytree_node=False, default=False)

    def cdf_at(self, t):
        from sbr_tpu.baseline.learning import logistic_cdf
        from sbr_tpu.core.interp import interp_uniform

        if self.closed_form:
            return logistic_cdf(t, self.beta, self.x0)
        return interp_uniform(t, self.t0, self.dt, self.cdf)

    def pdf_at(self, t):
        from sbr_tpu.baseline.learning import logistic_pdf
        from sbr_tpu.core.interp import interp_uniform

        if self.closed_form:
            return logistic_pdf(t, self.beta, self.x0)
        return interp_uniform(t, self.t0, self.dt, self.pdf)


@struct.dataclass
class LearningSolutionHetero:
    """K-group Stage-1 output (reference `LearningResultsHetero`,
    `heterogeneity_model.jl:195-211`).

    The group axis is the leading dimension of ``cdfs``/``pdfs`` — the
    reference's vector of per-group interpolation objects
    (`heterogeneity_learning.jl:80-89`) collapsed into batched arrays sharing
    one static grid (the reference's groups also share the adaptive grid).
    """

    grid: jnp.ndarray  # (n,) shared time grid over tspan (may be warped)
    cdfs: jnp.ndarray  # (K, n) per-group G_k(t)
    pdfs: jnp.ndarray  # (K, n) per-group g_k(t)
    t0: jnp.ndarray  # scalar, grid start
    dt: jnp.ndarray  # scalar, FIRST grid spacing (uniform-grid legacy field;
    # use local spacings of ``grid`` for anything resolution-sensitive)
    betas: jnp.ndarray  # (K,) group learning rates
    dist: jnp.ndarray  # (K,) group weights (simplex)
    # Flags from the adaptive coupled-K ODE (ISSUE 9): bs32's Health flags
    # (ODE_BUDGET when an interval exhausted its step cap and bridged with
    # an error-unchecked step) for the equilibrium solver to fold into the
    # per-cell health. None on the fixed-RK4 and sharded paths.
    ode_flags: Optional[jnp.ndarray] = None

    def cdf_at(self, t):
        """G_k at time(s) t: output shape (K, *t.shape). Searchsorted interp —
        the grid is transition-warped under the exact Ω path (round 5)."""
        from sbr_tpu.core.interp import interp_shared

        return interp_shared(t, self.grid, self.cdfs)

    def pdf_at(self, t):
        from sbr_tpu.core.interp import interp_shared

        return interp_shared(t, self.grid, self.pdfs)


@struct.dataclass
class EquilibriumResult:
    """Stage-2/3 output (reference `SolvedModel`, `solver.jl:55-109`).

    Scalars are 0-d arrays so a vmapped sweep yields batched results; curve
    fields live on the [0, η] hazard grid. ``xi`` is NaN when no run occurs,
    with ``status`` recording why.
    """

    xi: jnp.ndarray
    tau_bar_in_unc: jnp.ndarray  # reversed-time re-entry buffer
    tau_bar_out_unc: jnp.ndarray  # reversed-time exit buffer
    tau_in: jnp.ndarray  # normal time, max(ξ - τ̄_IN, 0) (`solver.jl:82`)
    tau_out: jnp.ndarray  # normal time, max(ξ - τ̄_OUT, 0)
    bankrun: jnp.ndarray  # bool
    status: jnp.ndarray  # int32 Status code
    converged: jnp.ndarray  # bool (reference `SolvedModel.converged`)
    tolerance: jnp.ndarray  # achieved |AW(ξ)-κ| (Inf when no root)
    tau_grid: jnp.ndarray  # (n,) hazard grid on [0, η]
    hr: jnp.ndarray  # (n,) hazard rate h(τ̄)
    aw_cum: jnp.ndarray  # (n,) cumulative aggregate withdrawals AW(t)
    aw_out: jnp.ndarray  # (n,) exits
    aw_in: jnp.ndarray  # (n,) re-entries
    aw_max: jnp.ndarray  # max of aw_cum (reference `AW_max`)
    # Host-side wall-clock of the convenience entry that produced this
    # result (reference `SolvedModel.solve_time`, `solver.jl:414,458`);
    # 0.0 for results created inside jitted sweeps, where a per-cell
    # host clock has no meaning. A pytree LEAF, not a static field: a
    # per-call wall-clock in the treedef would make every stamped result
    # a distinct pytree type (breaking tree_map across results and
    # retracing every jit that takes one).
    solve_time: float = 0.0
    # Numerical-health diagnostics (sbr_tpu.diag): residual/bracket/flags of
    # the crossing search and ξ bisection, computed in-jit alongside the
    # solve (XLA dead-code-eliminates it for callers that drop it). None
    # only for results assembled outside the solvers (tile checkpoints).
    health: Optional[Health] = None

    def __repr__(self) -> str:  # reference `Base.show`, `solver.jl:116-129`
        return (
            f"EquilibriumResult(ξ={_fmt(self.xi)}, bankrun={_fmt(self.bankrun)}, "
            f"status={_fmt(self.status)}, τ̄_IN={_fmt(self.tau_bar_in_unc)}, "
            f"τ̄_OUT={_fmt(self.tau_bar_out_unc)}, AW_max={_fmt(self.aw_max)}, "
            f"solve_time={_fmt(self.solve_time, 3)}s)"
        )


@struct.dataclass
class EquilibriumResultHetero:
    """K-group Stage-2/3 output (reference `SolvedModelHetero`,
    `heterogeneity_model.jl:238-294`).

    Group-resolved fields carry a leading K axis; ``status`` extends the
    baseline codes with the hetero-specific first-crossing rejection
    (`heterogeneity_solver.jl:175-210`), which maps onto FALSE_EQ.
    """

    xi: jnp.ndarray
    tau_bar_in_uncs: jnp.ndarray  # (K,)
    tau_bar_out_uncs: jnp.ndarray  # (K,)
    hrs: jnp.ndarray  # (K, n) per-group hazard rates on tau_grid
    tau_grid: jnp.ndarray  # (n,) hazard grid on [0, η]
    bankrun: jnp.ndarray  # bool
    status: jnp.ndarray  # int32 Status code
    converged: jnp.ndarray  # bool
    tolerance: jnp.ndarray  # achieved |AW(ξ)-κ|
    solve_time: float = 0.0  # pytree leaf; see EquilibriumResult.solve_time
    health: Optional[Health] = None  # see EquilibriumResult.health

    def __repr__(self) -> str:
        k = self.hrs.shape[0] if self.hrs.ndim >= 1 else "?"
        return (
            f"EquilibriumResultHetero(K={k}, ξ={_fmt(self.xi)}, "
            f"bankrun={_fmt(self.bankrun)}, status={_fmt(self.status)}, "
            f"solve_time={_fmt(self.solve_time, 3)}s)"
        )


@struct.dataclass
class AWHetero:
    """Group-decomposed aggregate-withdrawal curves on the learning grid
    (reference `get_AW_hetero`, `heterogeneity_solver.jl:316-375`)."""

    t_grid: jnp.ndarray  # (n,) learning grid
    aw_cum: jnp.ndarray  # (n,) Σ_k dist_k · AW_k
    aw_out_groups: jnp.ndarray  # (K, n)
    aw_in_groups: jnp.ndarray  # (K, n)
    aw_groups: jnp.ndarray  # (K, n) net per-group withdrawals
    aw_max: jnp.ndarray  # scalar
