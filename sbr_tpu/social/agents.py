"""Explicit-agent social learning on graphs (north-star extension).

Lifts the reference's representative-agent forced ODE
(`src/extensions/social_learning/social_learning_dynamics.jl:58-78`,
dG/dt = (1-G)·β·AW(t)) to explicit populations: N agents with heterogeneous
learning rates β_i on a directed graph, each learning from the withdrawal
actions of its in-neighbors. Per step of size dt:

    withdrawn_i(t) = informed_i ∧ (t ≥ t_inf_i + exit_delay)
                                ∧ (t < t_inf_i + reentry_delay)
    frac_i(t)      = (Σ_{j→i} withdrawn_j) / indegree_i    ← segmented reduce
    P(i informs)   = 1 - exp(-β_i · frac_i · dt)           ← exact hazard

Two exchangeable engines compute the per-destination withdrawn-neighbor
counts, bit-identical in results (tested):

- "gather": full recount every step — segmented reduction over dst-sorted
  edges as an exact int32 prefix sum plus row-pointer gathers
  (`_seg_counts`), the TPU-native form (a `segment_sum` scatter-add
  serializes on TPU: ~200 ms/step at 10^7 edges measured on v5e, vs ms for
  the prefix-sum form). Its wall is the per-edge `wd[src]` random gather
  (~1.3e8 elements/s on v5e's gather unit).
- "incremental": event-driven — an agent's withdrawal status changes at
  most twice per run, so counts are maintained by ±1 updates over changed
  agents' out-edges, with the full recount as the overflow fallback
  (3.6× end-to-end at the 10^6-agent ER shape, 2026-07-31 re-anchor;
  `_incremental_sim`). Under a mesh, out-edges are sharded by EDGE COUNT
  (src-sorted chunks of exactly E/n_dev), and the same engine choice
  machinery applies (`_sharded_incremental_sim`).

The default ("auto") picks by expected fallback steps: the hub tail (per-
chunk slice tail under a mesh) plus a logistic mass-change overflow
estimate (`_auto_engine`).

The withdrawal window mirrors the equilibrium strategy: from `get_AW`
(`src/baseline/solver.jl:495-532`), an agent informed at time s is withdrawn
at t iff s+ξ-τ̄_OUT^CON ≤ t < s+ξ-τ̄_IN^CON, i.e. exit_delay = ξ-τ̄_OUT^CON and
reentry_delay = ξ-τ̄_IN^CON. Defaults (0, ∞) are the immediate-exit behavior
of the fixed point's initial guess (`social_learning_solver.jl:90-94`); in
the dense-graph limit with immediate exit, AW(t)=G(t) and the dynamics reduce
to the baseline logistic dG/dt = β·G·(1-G) — the validation oracle
(SURVEY §4(e)).

Sharding (SURVEY §7.3 "million-agent graph sharding"): edges are sorted by
destination and sharded BY EDGE COUNT (balanced under scale-free degree
skew), agents block-sharded by id. Each device all-gathers the withdrawn
mask BITPACKED to N/8 bytes, reduces its local edge chunk into a
full-length count vector via its own row-pointer table, and a
`psum_scatter` resolves destinations straddling shards while delivering
each device only its own agent block (1/n_dev of a full psum's bytes).
All collectives are XLA natives riding ICI; see `_sharded_sim` for the
traffic analysis and why per-source halo exchange loses on random graphs.
"""

from __future__ import annotations

import dataclasses
import functools
from pathlib import Path
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sbr_tpu.parallel.compat import pcast, shard_map
from sbr_tpu.social.fused import infection_update

# Re-exported for back-compat: the counter-RNG primitives moved to
# sbr_tpu.social.rng in 0.8.0 so the fused kernel and the on-device graph
# generators share them (benchmarks/tests import them from here).
from sbr_tpu.social.rng import _agent_uniforms, _threefry2x32  # noqa: F401


# ---------------------------------------------------------------------------
# Graph generation (host-side, numpy; static inputs to the jitted kernel)
# ---------------------------------------------------------------------------


def erdos_renyi_edges(n: int, avg_degree: float, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Sparse directed Erdős–Rényi G(n, p) with p = avg_degree/(n-1).

    Uses the standard sparse sampling: draw E ~ Binomial(n(n-1), p) directed
    pairs uniformly (self-loops resampled away; duplicate edges have
    vanishing probability at sparse p and only perturb weights by O(1/n)).
    Returns (src, dst) int32 arrays. The pair stream comes from the native
    sampler (`native.er_edges_native`) when the compiled library is
    available, numpy otherwise — both deterministic in ``seed``, but the
    streams differ, so seeded graphs are reproducible per backend only.
    """
    from sbr_tpu.native import er_edges_native

    rng = np.random.default_rng(seed)
    p = avg_degree / max(n - 1, 1)
    e = int(rng.binomial(n * (n - 1), p))
    pair = er_edges_native(n, e, seed)
    if pair is not None:
        return pair
    src = rng.integers(0, n, size=e, dtype=np.int32)
    dst = rng.integers(0, n, size=e, dtype=np.int32)
    loops = src == dst
    dst[loops] = (dst[loops] + 1 + rng.integers(0, n - 1, size=loops.sum())) % n
    return src, dst


def scale_free_edges(
    n: int, avg_degree: float, gamma: float = 2.5, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Directed scale-free graph via the Chung–Lu power-law model.

    Both endpoints are drawn with probability ∝ w_i = (i+1)^{-1/(γ-1)}, so
    in- AND out-degree distributions have tail exponent γ — in-degree is the
    side that drives the learning dynamics (frac_i normalizes by indegree_i),
    so it must carry the heavy tail. Fully vectorized — no preferential-
    attachment loop — so 10^6-node graphs build in seconds.
    """
    rng = np.random.default_rng(seed)
    e = int(n * avg_degree)
    w = (np.arange(1, n + 1, dtype=np.float64)) ** (-1.0 / (gamma - 1.0))
    w /= w.sum()
    src = rng.choice(n, size=e, p=w).astype(np.int32)
    dst = rng.choice(n, size=e, p=w).astype(np.int32)
    loops = src == dst
    dst[loops] = (dst[loops] + 1 + rng.integers(0, n - 1, size=loops.sum())) % n
    return src, dst


# ---------------------------------------------------------------------------
# Simulation kernel
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AgentSimConfig:
    """Static simulation knobs (hashable jit argument).

    - n_steps: time steps of size dt over [0, n_steps·dt].
    - dt: step size; the per-step hazard integral is exact for piecewise-
      constant forcing, so dt controls only the forcing resolution.
    - exit_delay / reentry_delay: the equilibrium withdrawal window relative
      to each agent's informed time (see module docstring).
    - max_steps_per_launch: bound the number of steps in any single device
      execution; a run longer than this is split into host-level chunks
      that carry (informed, t_inf) between launches. Results are
      BIT-IDENTICAL to the unchunked run (tested): the step index is
      global across chunks, so times and the per-(agent, step) RNG stream
      are unchanged, and the withdrawn-neighbor counts are integers that
      rebuild exactly from the carried state at each chunk start. Use when
      a single launch would exceed an external execution deadline — the
      axon TPU tunnel on this rig kills the worker ("TPU worker process
      crashed") when one program runs longer than ~1-2 min, which the
      10^7-agent x 200-step recount (~1.3 s/step) hits — or to create
      natural checkpoint boundaries in very long simulations.
    """

    n_steps: int = 200
    dt: float = 0.1
    exit_delay: float = 0.0
    reentry_delay: float = float("inf")
    max_steps_per_launch: Optional[int] = None
    # Lowering of the incremental engines' per-step change compaction
    # ("searchsorted" | "searchsorted_blocked" | "scatter" — bit-identical,
    # see `_compact_ids`). A perf-only knob in the `engine="measure"`
    # spirit; default "searchsorted" since 0.7.0: 1.18× end-to-end on CPU
    # at the bench shape (ABLATE_COMPACT_cpu_2026-08-01.json), and at the
    # DEFAULT budget its budget·log₂N search gathers (~3×10⁵) are far
    # cheaper than the scatter's ~N colliding writes on any plausible TPU
    # cost model while the cumsum is shared — the exposure is a raised
    # budget, where the search cost grows and scatter's does not (the
    # queued TPU A/B measures both axes and confirms or reverts:
    # `ablate_compaction.py`, tpu_session.sh step 2).
    compact_impl: str = "searchsorted"
    # Per-agent RNG stream ("counter" | "foldin" — see `_agent_uniforms`).
    # Both are pure functions of (key, step, global id), so every
    # engine/sharding equivalence holds under either. "counter" (default
    # since 0.7.0) does one Threefry block per agent instead of foldin's
    # two-plus-construction — measured 2.3x END-TO-END on the CPU bench
    # shape (19.6M -> 45.1M agent-steps/s) and strictly less work on any
    # platform; "foldin" reproduces the realizations of pre-0.7 artifacts.
    rng_stream: str = "counter"
    # Lowering of the per-step draw→infection→update tail (ISSUE 10):
    # "auto" (default) resolves per platform — the fused Pallas kernel on
    # TPU/GPU, the fused-lax form elsewhere (bit-identical to "unfused" by
    # construction, so tier-1/CPU semantics are unchanged); "interpret"
    # runs the Pallas kernel under the interpreter (the testable-anywhere
    # path); "unfused" pins the pre-0.8 inline sequence (parity oracle).
    # See `sbr_tpu.social.fused`.
    fused: str = "auto"

    def __post_init__(self):
        if self.n_steps < 1:
            raise ValueError("n_steps must be >= 1")
        if self.dt <= 0:
            raise ValueError("dt must be positive")
        if self.max_steps_per_launch is not None and self.max_steps_per_launch < 1:
            raise ValueError("max_steps_per_launch must be >= 1 (or None)")
        if self.compact_impl not in (
            "scatter",
            "searchsorted",
            "searchsorted_blocked",
        ):
            raise ValueError(
                "compact_impl must be 'scatter', 'searchsorted', or "
                "'searchsorted_blocked'"
            )
        if self.rng_stream not in ("foldin", "counter"):
            raise ValueError("rng_stream must be 'foldin' or 'counter'")
        from sbr_tpu.social.fused import MODES

        if self.fused not in MODES:
            raise ValueError(f"fused must be one of {MODES}, got {self.fused!r}")


@struct.dataclass
class AgentSimResult:
    """Trajectories of the population aggregates plus final per-agent state.

    ``informed_frac``/``withdrawn_frac`` are sampled at step starts
    (t = k·dt), i.e. the explicit-population analogues of G(t) and AW(t).
    """

    t_grid: jnp.ndarray  # (n_steps,)
    informed_frac: jnp.ndarray  # (n_steps,)
    withdrawn_frac: jnp.ndarray  # (n_steps,)
    informed: jnp.ndarray  # (N,) bool, final
    t_inf: jnp.ndarray  # (N,) informed times (inf when never informed)
    # (n_steps,) bool: True where the step recomputed neighbor counts via
    # the full segmented recount — every step for the gather engines, only
    # budget-overflow steps for the incremental ones (for the sharded
    # incremental engine the flag is the psum'd any-device overflow, since
    # one device's overflow triggers the global recount). Engine
    # observability: the recount share is the term the `engine="auto"`
    # census predicts, so this field is its ground truth on any platform.
    # NOTE: telemetry, not simulation state — under `max_steps_per_launch`
    # each launch re-seeds its counts through the event path, so the flag
    # at chunk-start steps can differ from the unchunked run's (the counts
    # themselves are exact either way; the bit-identical guarantee covers
    # trajectories and agent state).
    full_recount_steps: Optional[jnp.ndarray] = None
    # Static host-side int (not a device array: N·n_steps overflows int32 at
    # the advertised 10^6-agent scale under default x32).
    agent_steps: int = struct.field(pytree_node=False, default=0)

    def __repr__(self) -> str:
        from sbr_tpu.models.results import _fmt

        rec = ""
        if self.full_recount_steps is not None:
            rec = f", recounts={int(np.asarray(self.full_recount_steps).sum())}"
        return (
            f"AgentSimResult(N={self.informed.shape[-1]}, "
            f"steps={self.t_grid.shape[-1]}, "
            f"final_G={_fmt(self.informed_frac[..., -1], 4)}, "
            f"final_AW={_fmt(self.withdrawn_frac[..., -1], 4)}{rec})"
        )


def _withdrawn(informed, t_inf, t, exit_delay, reentry_delay):
    return informed & (t >= t_inf + exit_delay) & (t < t_inf + reentry_delay)


def _compact_ids(mask, budget: int, dump: int, impl: str = "scatter"):
    """Ascending indices of True entries, padded with ``dump`` — the
    `jnp.nonzero(size=budget, fill_value=dump)[0]` contract. Runs every
    step of the incremental engines, where it is the largest clean-step
    cost; two bit-identical lowerings (incl. the overflow case, where both
    keep the first ``budget`` True indices — tested against each other):

    - "scatter": cumsum + scatter of the full id array (1.4× faster than
      the `jnp.nonzero` lowering on v5e at N=10⁶: 8.2 vs 11.1 ms
      standalone). Every one of the N writes lands — the ~N invalid ones
      all collide on the dump slot and are sliced away.
    - "searchsorted": rank j's id is the first index where the cumsum
      reaches j+1, so ``budget`` vectorized binary searches (log₂N gather
      rounds over the monotone cumsum) replace the N-write scatter
      entirely; for ranks beyond the population the search falls off the
      end at exactly ``mask.size`` → dump.
    - "searchsorted_blocked": same searches, but the cumsum is computed
      two-level — an axis-1 cumsum over a (N/128, 128) lane-blocked
      reshape (log₂128 = 7 full-width shifted adds) plus a 128×-shorter
      row-offset scan — in case the backend's 1-D scan at N is the wall
      rather than the scatter. Integer adds are exact, so the composed
      cumsum (and hence the output) is bit-identical.

    `benchmarks/ablate_compaction.py` A/Bs all three (plus the parts) on
    hardware; `AgentSimConfig.compact_impl` selects per run."""
    if impl in ("searchsorted", "searchsorted_blocked"):
        n_mask = mask.shape[0]
        if impl == "searchsorted_blocked":
            m = mask.astype(jnp.int32)
            pad = (-n_mask) % 128
            if pad:
                m = jnp.concatenate([m, jnp.zeros(pad, jnp.int32)])
            within = jnp.cumsum(m.reshape(-1, 128), axis=1)
            row_tot = within[:, -1]
            offs = jnp.cumsum(row_tot) - row_tot
            c = (within + offs[:, None]).reshape(-1)[:n_mask]
        else:
            c = jnp.cumsum(mask.astype(jnp.int32))
        q = jnp.arange(1, budget + 1, dtype=jnp.int32)
        res = jnp.searchsorted(c, q, side="left").astype(jnp.int32)
        return jnp.where(res >= n_mask, jnp.int32(dump), res)
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    idx = jnp.where(mask & (pos < budget), pos, budget)
    ids = jnp.arange(mask.shape[0], dtype=jnp.int32)
    return jnp.full(budget + 1, dump, jnp.int32).at[idx].set(ids)[:budget]


def _draw_seeds(rng, n: int, x0: float, exact_seeds: bool) -> np.ndarray:
    """Initial informed mask — the ONE definition of the seed draw order
    (shared by `_prep_inputs` and `simulate_agents`, whose bit-identical
    prepared-vs-direct guarantee depends on it)."""
    if exact_seeds:
        # Deterministic seed COUNT (exactly round(x0·n), ≥1 when x0>0): the
        # Bernoulli draw's binomial fluctuation in the number of initially
        # informed agents dominates the early stochastic-growth phase when
        # x0·n is O(1) — killing it makes the ODE comparison converge in N
        # (used by social.closure, the equilibrium→agent validation loop).
        k = max(1, int(round(x0 * n))) if x0 > 0 else 0
        informed0 = np.zeros(n, bool)
        if k:
            informed0[rng.choice(n, size=k, replace=False)] = True
        return informed0
    informed0 = rng.random(n) < x0
    if x0 > 0 and not informed0.any():  # guarantee ≥1 seed when x0>0
        informed0[rng.integers(0, n)] = True
    return informed0


def _canonicalize_graph(betas, src, dst, n: int, dtype):
    """Host-side canonicalization: per-agent β, in-degrees, dst-sorted edges
    with their row-pointer table — the ONE definition shared by
    `prepare_agent_graph` and `_prep_inputs` (whose consumers include the
    ablation benchmark asserting the production layout).

    Edges are sorted by destination so the per-step neighbor aggregation is
    a segmented reduction over contiguous edge ranges. On TPU that is
    implemented as an exact int32 prefix sum plus two row-pointer gathers —
    NOT `segment_sum`, whose scatter-add lowering serializes on TPU
    (measured ~200 ms/step at 10^7 edges vs ~ms for the cumsum form).
    ``row_ptr[i]`` is the first edge index with dst ≥ i, so edges of agent i
    occupy [row_ptr[i], row_ptr[i+1]). The sort is the native O(E+N)
    counting sort when the compiled library is available, numpy argsort
    otherwise (same stable order either way)."""
    from sbr_tpu.native import sort_edges_by_dst

    betas = np.broadcast_to(np.asarray(betas, dtype=dtype), (n,)).copy()
    src, dst, indeg_i, row_ptr = sort_edges_by_dst(src, dst, n)
    return betas, src, dst, indeg_i.astype(dtype), row_ptr.astype(np.int32)


def _prep_inputs(n: int, betas, x0: float, src, dst, seed: int, dtype, exact_seeds: bool = False):
    """Canonicalized graph + initial seeds (see `_canonicalize_graph`)."""
    betas, src, dst, indeg, row_ptr = _canonicalize_graph(betas, src, dst, n, dtype)
    informed0 = _draw_seeds(np.random.default_rng(seed), n, x0, exact_seeds)
    return betas, src, dst, indeg, row_ptr, informed0


def _auto_engine(
    edge_slices,
    max_degree: int,
    n_steps: int,
    n: int,
    beta_mean: float,
    dt: float,
    budget: int,
    waves: float = 2.0,
) -> str:
    """Engine choice for engine="auto" (single-device and sharded).

    The incremental engine falls back to the full recount on any step in
    which (a) a changed agent's edge slice exceeds ``max_degree``, or (b)
    the number of changed agents exceeds ``budget``. Pick incremental when
    its expected cost in recount units beats the gather engine's:

    Both mechanisms are counted per step against the logistic census
    trajectory (see the inline comments): budget overflow is deterministic
    in the step's expected change mass, and hub fallbacks saturate at one
    per step via 1−exp(−H·ΔG̃) — hub changes cluster into the transition
    steps, so the count is bounded by the steps in the active window, NOT
    by 2H. ``edge_slices`` is the whole out-degree vector single-device,
    or the per-agent MAX CHUNK SLICE under a mesh (edge-count sharding
    splits a hub's edges across chunks, so the sharded census is milder).

    ``waves`` is the number of change waves per agent the withdrawal
    window produces WITHIN the simulated horizon: 1 when only the entry
    wave can occur before T = n_steps·dt (e.g. the framework's default
    no-reentry window), 2 when exits land inside the horizon too, 0 when
    the window is empty or opens after T. `prepare_agent_graph` derives
    the exact value from its config; the conservative 2 is only the
    default for direct callers. (Resolves ADVICE r4: the old census
    hard-coded the factor 2, which measured as 44 predicted-vs-0 observed
    recount steps at the ER bench shape — see
    benchmarks/CENSUS_CALIBRATION_cpu_2026-08-01.json.)

    The decision compares EXPECTED COST, not fallback fraction: a fallback
    step costs one recount plus detection overhead (1+ε ≈ 1.15 recounts)
    while a non-fallback incremental step is far cheaper than a recount —
    ρ = 0.35 recounts here, conservative against the measured TPU ratio
    (`ENGINE_COMPARE_tpu_2026-07-31.json`: 26 vs 94 ms/step at the 10⁶-
    agent/10⁷-ER-edge bench shape, incremental 3.6× end-to-end — a shape
    the old "fallbacks ≤ n_steps/4" threshold misrouted to gather because
    its predicted ~57-step overflow band exceeded 50, fallback cost
    notwithstanding). ρ is the TPU cost structure by design — on CPU the
    recount runs at memory bandwidth and incremental is ~0.9×
    (`SHARDED_ENGINES_cpu8_*.json`); the census stays platform-independent
    so prepared graphs are portable, tuned for the hardware the framework
    targets.
    """
    fallback_steps = _census_fallback_steps(
        edge_slices, max_degree, n_steps, n, beta_mean, dt, budget, waves
    )
    rho, eps = 0.35, 0.15
    cost_incremental = fallback_steps * (1.0 + eps) + max(
        n_steps - fallback_steps, 0.0
    ) * rho
    return "incremental" if cost_incremental <= n_steps else "gather"


# The realized transition is wider than the mean-field logistic: degree-10
# neighbor fractions are quantized, so the explicit-agent band lags and
# spreads by ~25% (the scale-demo physics check measures band 0.40-0.43 vs
# the mean-field ~1/3 — benchmarks/RESULTS.md). The census runs its
# trajectory at β/1.25 to match: calibrated against measured recount
# telemetry over 6 shapes (ER + Chung-Lu γ∈{2.2,2.5,3.0}, constant and
# lognormal β; CENSUS_CALIBRATION_cpu_2026-08-01.json) — 5 of 6 within
# ~5% with the remaining barely-spreading contagion over-predicted, i.e.
# conservative toward the gather engine.
_CENSUS_BAND_STRETCH = 1.25


def _census_fallback_steps(
    edge_slices,
    max_degree: int,
    n_steps: int,
    n: int,
    beta_mean: float,
    dt: float,
    budget: int,
    waves: float = 2.0,
) -> float:
    """Predicted full-recount steps for the incremental engine — the
    quantity `_auto_engine`'s cost model consumes, exposed separately so
    it can be diffed against the measured ground truth
    (`AgentSimResult.full_recount_steps`;
    benchmarks/census_calibration.py)."""
    hubs = int((np.asarray(edge_slices) > max_degree).sum())
    fallback_steps = 0.0
    if beta_mean > 0:
        # Per-step change mass from the band-stretched logistic census
        # trajectory G(t) = x0/(x0+(1-x0)e^{-(β/S)t}) started at the
        # framework's default seed fraction (the census runs at prepare
        # time, before x0 is known; small-seed contagion is the
        # framework's domain, and a mid-trajectory caller mispredicts by
        # at most the measured engine gap, never correctness). ΔG̃ scales
        # ΔG by the window's change-wave count (see `_auto_engine`).
        x0c = 1e-4
        t = np.arange(n_steps + 1) * dt
        beta_eff = beta_mean / _CENSUS_BAND_STRETCH
        g = x0c / (x0c + (1.0 - x0c) * np.exp(-beta_eff * t))
        dgt = waves * np.diff(g)
        # A step falls back when the changed-agent count exceeds budget
        # (deterministic at the census mass) or ≥1 hub changes. Hub change
        # times follow the same dG law, so the expected number of
        # hub-fallback steps saturates per step — Σ(1-exp(-H·ΔG̃)) —
        # instead of the old 2·H count, which overcounted by orders of
        # magnitude once hubs clustered into the same transition steps
        # (H ≫ n_steps: measured incremental WIN of 1.42x at the 10^6
        # scale-free stretch shape that the round-4 census routed to
        # gather, ENGINE_COMPARE_sf_tpu_2026-07-31.json).
        overflow = (n * dgt > budget) if budget > 0 else np.zeros_like(dgt, bool)
        p_hub = -np.expm1(-hubs * dgt) if hubs > 0 else 0.0
        fallback_steps = float(np.sum(np.where(overflow, 1.0, p_hub)))
    return fallback_steps


def _census_waves(config: AgentSimConfig) -> float:
    """Change waves per agent within the simulated horizon T = n_steps·dt:
    the entry wave counts only if entries can occur before T, the exit
    wave only if exits can (earliest exit at reentry_delay for t=0
    seeds); an empty window (exit ≥ reentry) never changes anyone. The
    ONE definition shared by the engine="auto" census and the
    engine="measure" wide-cap gate."""
    horizon = config.n_steps * config.dt
    if config.exit_delay >= config.reentry_delay or config.exit_delay >= horizon:
        return 0.0
    return 1.0 + float(config.reentry_delay < horizon)


def _default_incremental_budget(n_block: int, floor: int = 4096) -> int:
    """Default per-step changed-agent budget for the incremental engines —
    the ONE definition shared by `prepare_agent_graph`'s auto census, its
    runtime budget, and the compaction ablation (which must time
    `_compact_ids` at the budget the engine actually uses). ``n_block`` is
    the per-device agent-block length (= n on a single device); the
    sharded path uses a lower floor (512) since its budget multiplies
    across devices."""
    return min(max(floor, n_block // 64), 65536)


def _resolve_engine_from_outdeg(
    outdeg_c,
    n: int,
    e: int,
    config: AgentSimConfig,
    mesh,
    mesh_axis: str,
    incremental_budget: Optional[int],
    d0: int,
    beta_mean: float,
) -> str:
    """The engine="auto" decision from a host out-degree census — the ONE
    resolution shared by `prepare_agent_graph` (which bincounts the
    canonicalized sources) and `graphgen.prepare_generated_graph` (which
    pulls the device histogram): census over the out-degree vector
    single-device, the per-chunk slice tail under a mesh, with the same
    effective-budget rules the runtime will use."""
    if e == 0:
        return "gather"
    outdeg_c = np.asarray(outdeg_c, dtype=np.int64)
    if mesh is None:
        census = outdeg_c
        budget_est = incremental_budget or _default_incremental_budget(n)
    else:
        # edge-count sharding splits hub edges across chunks, and the
        # per-device change budget multiplies across devices — census
        # and budget are both the per-device effective values
        n_dev_a = mesh.shape[mesh_axis]
        ec_a = max(1, -(-e // n_dev_a))
        out_ptr_c = np.concatenate([[0], np.cumsum(outdeg_c)])
        census = _max_chunk_slice(out_ptr_c, ec_a, n)
        # the same padded per-device block the runtime will use
        # (byte-aligned for the incremental candidate, ADVICE r4:
        # a ceil(n/n_dev) estimate drifted from the runtime budget
        # near block boundaries)
        n_gl_a = n + (-n) % (8 * n_dev_a)
        nb_a = n_gl_a // n_dev_a
        budget_est = (
            incremental_budget or _default_incremental_budget(nb_a, floor=512)
        ) * n_dev_a
    return _auto_engine(
        census,
        d0,
        config.n_steps,
        n,
        beta_mean,
        config.dt,
        int(budget_est),
        waves=_census_waves(config),
    )


def _max_chunk_slice(out_ptr: np.ndarray, ec: int, n: int) -> np.ndarray:
    """Per-agent largest out-edge slice under edge-count sharding with chunk
    size ``ec``: an agent's contiguous src-sorted edge range [start, end)
    lands in 1-2 chunks when deg ≤ ec (middle chunks of longer spans are
    full and clip to ec). This is the hub census the sharded auto choice
    uses — splitting tames hubs up to ~2× max_degree per chunk boundary."""
    starts = out_ptr[:-1].astype(np.int64)
    ends = out_ptr[1:].astype(np.int64)
    f = starts // ec
    piece1 = np.minimum(ends, (f + 1) * ec) - starts
    piece2 = np.clip(ends - (f + 1) * ec, 0, ec)
    return np.maximum(piece1, piece2)[:n]


def _seg_counts(active_src, row_ptr):
    """Per-destination neighbor counts from a dst-sorted edge activity mask.

    Exact integer prefix sum: P[k] = Σ_{e<k} active[e], counts_i =
    P[row_ptr[i+1]] − P[row_ptr[i]]. Exact for up to 2^31 edges; log-depth
    cumsum + gathers, all TPU-friendly primitives.
    """
    prefix = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(active_src.astype(jnp.int32))]
    )
    return prefix[row_ptr[1:]] - prefix[row_ptr[:-1]]


def _bit_recount(bits_global, src, row_ptr, axis):
    """Count this shard's dst-sorted edges against a gathered global bit
    mask and psum_scatter each device its own agent block's totals (one
    reduce_scatter resolves straddling ranges at 1/n_dev of a psum's
    bytes). The single body shared by every bitpacked recount path — the
    gather engine's "scatter" comm and the incremental engine's overflow
    fallback — so their byte-for-byte equivalence (the engines' bit-
    identity contract) lives in exactly one place."""
    active = (bits_global[src >> 3] >> (src & 7).astype(jnp.uint8)) & jnp.uint8(1)
    counts = _seg_counts(active, row_ptr)[:-1]  # (N,) this shard's edges
    return lax.psum_scatter(counts, axis, scatter_dimension=0, tiled=True)


def _bitpacked_block_counts(wd, src, row_ptr, axis):
    """Sharded full recount from a local withdrawn mask: pack to N/8 bytes,
    all_gather, then `_bit_recount`. Requires the local block byte-aligned."""
    wd_bits = jnp.packbits(wd, bitorder="little")  # (nb/8,) uint8
    bits_global = lax.all_gather(wd_bits, axis, tiled=True)  # (N/8,)
    return _bit_recount(bits_global, src, row_ptr, axis)


@functools.lru_cache(maxsize=None)
def _incremental_sim(config: AgentSimConfig, budget_agents: int, budget_deg: int):
    """Event-driven single-device kernel (engine="incremental").

    The gather kernel pays ~E random gathers EVERY step (`wd[src]` is the
    measured wall: ~78 ms of a ~95 ms step at 10^7 edges on v5e — the TPU
    gather unit issues ~1.3e8 elements/s), yet each agent changes withdrawal
    status at most twice in a whole run (enters the window, leaves it). So
    maintain the per-destination withdrawn-neighbor counts INCREMENTALLY:

        counts_i(k) = Σ_{j→i} wd_j(k)   (int32, exact by induction)

    Per step: dwd = wd(k) − wd(k−1) ∈ {−1,0,+1} elementwise; compact the
    changed agents (≤ budget_agents), expand their out-edges on a dense
    (budget_agents × budget_deg) grid, and scatter-add ±1 into counts. When
    the step exceeds either budget (mass simultaneous change, or a hub with
    out-degree > budget_deg changed), fall back to the full segmented
    recount for that step via `lax.cond` — the invariant holds either way,
    so results are BIT-IDENTICAL to the gather engine (tested), only faster:
    PER-STEP, ~26 ms clean vs ~95 ms for the full recount at the
    10^6-agent north-star shape; end-to-end 3.6× (5.2 s vs 18.9 s on v5e,
    ENGINE_COMPARE_tpu_2026-07-31 — ablations in benchmarks/RESULTS.md).

    Step 0 initializes counts from dwd vs an all-False previous mask, so the
    x0·N founding seeds enter through the same event path.
    """
    dt = config.dt

    @jax.jit
    def run(betas, src, row_ptr, indeg, dst2, out_ptr, outdeg, informed0, t_init, key, k0):
        # Trace-time retrace accounting (obs.prof): a churning graph shape
        # (edge-count drift between prepares) silently recompiles this
        # kernel — the counter makes that visible in the run manifest.
        from sbr_tpu.obs import prof

        prof.note_trace("social.agents.incremental")
        n = betas.shape[0]
        e = src.shape[0]
        dtype = betas.dtype
        t_inf0 = jnp.where(informed0, t_init, jnp.inf).astype(dtype)
        safe_deg = jnp.maximum(indeg, 1.0)
        ids = jnp.arange(n, dtype=jnp.uint32)
        d_lane = jnp.arange(budget_deg, dtype=jnp.int32)[None, :]

        def step(carry, k):
            informed, t_inf, counts, wd_prev = carry
            t = k.astype(dtype) * dt
            wd = _withdrawn(informed, t_inf, t, config.exit_delay, config.reentry_delay)
            dwd = wd.astype(jnp.int32) - wd_prev.astype(jnp.int32)
            changed = dwd != 0
            n_changed = jnp.sum(changed)

            cids = _compact_ids(changed, budget_agents, n, config.compact_impl)
            valid = cids < n
            cids_c = jnp.minimum(cids, n - 1).astype(jnp.int32)
            degs = jnp.where(valid, outdeg[cids_c], 0)
            overflow = (n_changed > budget_agents) | (jnp.max(degs) > budget_deg)

            def incr(c):
                starts = out_ptr[cids_c]
                emask = d_lane < degs[:, None]
                eidx = jnp.minimum(starts[:, None] + d_lane, e - 1)
                dsts = dst2[eidx]  # (budget_agents, budget_deg)
                sign = jnp.where(valid, dwd[cids_c], 0)
                delta = jnp.where(emask, sign[:, None], 0)
                return c.at[dsts.ravel()].add(delta.ravel())

            def full(_):
                return _seg_counts(wd[src], row_ptr)

            counts2 = lax.cond(overflow, full, incr, counts)
            informed2, t_inf2 = infection_update(
                informed, t_inf, counts2, betas, safe_deg, key, k, ids, t,
                dt, config.rng_stream, config.fused,
            )
            obs = (
                jnp.mean(informed.astype(dtype)),
                jnp.mean(wd.astype(dtype)),
                overflow,
            )
            return (informed2, t_inf2, counts2, wd), obs

        init = (informed0, t_inf0, jnp.zeros(n, jnp.int32), jnp.zeros(n, bool))
        (informed, t_inf, _, _), (gs, aws, recs) = lax.scan(
            step, init, jnp.arange(config.n_steps) + k0
        )
        t_grid = (jnp.arange(config.n_steps) + k0).astype(dtype) * dt
        return AgentSimResult(
            t_grid=t_grid,
            informed_frac=gs,
            withdrawn_frac=aws,
            informed=informed,
            t_inf=t_inf,
            full_recount_steps=recs,
            agent_steps=n * config.n_steps,
        )

    return run


@functools.lru_cache(maxsize=None)
def _single_device_sim(config: AgentSimConfig):
    dt = config.dt

    @jax.jit
    def run(betas, src, row_ptr, indeg, informed0, t_init, key, k0):
        from sbr_tpu.obs import prof

        prof.note_trace("social.agents.gather")
        n = betas.shape[0]
        dtype = betas.dtype
        t_inf0 = jnp.where(informed0, t_init, jnp.inf).astype(dtype)
        safe_deg = jnp.maximum(indeg, 1.0)

        ids = jnp.arange(n, dtype=jnp.uint32)

        def step(carry, k):
            informed, t_inf = carry
            t = k.astype(dtype) * dt
            wd = _withdrawn(informed, t_inf, t, config.exit_delay, config.reentry_delay)
            counts = _seg_counts(wd[src], row_ptr)
            informed2, t_inf2 = infection_update(
                informed, t_inf, counts, betas, safe_deg, key, k, ids, t,
                dt, config.rng_stream, config.fused,
            )
            obs = (jnp.mean(informed.astype(dtype)), jnp.mean(wd.astype(dtype)))
            return (informed2, t_inf2), obs

        (informed, t_inf), (gs, aws) = lax.scan(
            step, (informed0, t_inf0), jnp.arange(config.n_steps) + k0
        )
        t_grid = (jnp.arange(config.n_steps) + k0).astype(dtype) * dt
        return AgentSimResult(
            t_grid=t_grid,
            informed_frac=gs,
            withdrawn_frac=aws,
            informed=informed,
            t_inf=t_inf,
            # the gather engine recounts every step by construction
            full_recount_steps=jnp.ones(config.n_steps, bool),
            agent_steps=n * config.n_steps,
        )

    return run


@functools.lru_cache(maxsize=None)
def _sharded_sim(config: AgentSimConfig, mesh: Mesh, axis: str, n_true: int, comm: str):
    """shard_map kernel: agents block-sharded, edges count-sharded (sorted by
    dst). Neighbor aggregation uses the same prefix-sum/row-pointer form as
    the single-device kernel (`_seg_counts`), with a per-shard row-pointer
    table over the global segment ids (edge ranges are contiguous per shard).

    Per-step collectives, by ``comm``:
    - "scatter" (default): the withdrawn mask is BITPACKED before the
      all_gather (N/8 bytes instead of N), and the cross-shard count
      resolution is a `psum_scatter` that hands each device only its own
      agent block (N/n_dev · 4 bytes instead of a full-N psum). ~7× less
      ICI traffic per step than the naive form.
    - "allgather_psum": the naive form (bool all_gather + full-N psum +
      dynamic_slice), kept as the measurement baseline.

    Why not per-source halo exchange (gathering only the sources each
    shard's edges reference): on Erdős–Rényi / scale-free graphs edges have
    NO locality — with E/n_dev local edges a shard references ≈
    N·(1−exp(−E/(N·n_dev))) distinct sources (≈ 71% of all agents at
    E=10N, n_dev=8), so the "needed sources" set is nearly all of N and an
    index-based exchange costs MORE than the 1-bit-per-agent broadcast
    (plus irregular gathers). Halo exchange only wins on graphs partitioned
    for locality, which the framework does not assume.
    """
    dt = config.dt
    n_dev = mesh.shape[axis]

    def shard_fn(betas, src, row_ptr, indeg, informed0, t_init, key, k0):
        nb = betas.shape[0]  # local agent block
        dtype = betas.dtype
        idx = lax.axis_index(axis)
        offset = idx * nb
        # GLOBAL agent ids for this shard: the RNG stream is keyed by global
        # id (`_agent_uniforms`), so draws match the single-device kernel
        # bit-for-bit regardless of mesh size.
        ids = (offset + jnp.arange(nb)).astype(jnp.uint32)
        row_ptr = row_ptr[0]  # (N_global + 2,): local edge ranges incl. pad segment
        t_inf0 = jnp.where(informed0, t_init, jnp.inf).astype(dtype)
        safe_deg = jnp.maximum(indeg, 1.0)
        inv_n = 1.0 / n_true

        def neighbor_counts(wd):
            """Withdrawn in-neighbor count for this shard's own agent block."""
            if comm == "scatter":
                # nb is padded to a byte boundary (simulate_agents), so the
                # packed local masks concatenate into the global bit array.
                return _bitpacked_block_counts(wd, src, row_ptr, axis)
            wd_global = lax.all_gather(wd, axis, tiled=True)  # (N,) bool
            counts = _seg_counts(wd_global[src], row_ptr)[:-1]
            counts = lax.psum(counts, axis)  # straddling dst ranges
            return lax.dynamic_slice(counts, (offset,), (nb,))

        def step(carry, k):
            informed, t_inf = carry
            t = k.astype(dtype) * dt
            wd = _withdrawn(informed, t_inf, t, config.exit_delay, config.reentry_delay)
            # local edges carry global dst ids; the pad segment (dst = N) is
            # the last row of the pointer table and is dropped.
            informed2, t_inf2 = infection_update(
                informed, t_inf, neighbor_counts(wd), betas, safe_deg, key,
                k, ids, t, dt, config.rng_stream, config.fused,
            )
            g = lax.psum(jnp.sum(informed.astype(dtype)), axis) * inv_n
            aw = lax.psum(jnp.sum(wd.astype(dtype)), axis) * inv_n
            return (informed2, t_inf2), (g, aw)

        (informed, t_inf), (gs, aws) = lax.scan(
            step, (informed0, t_inf0), jnp.arange(config.n_steps) + k0
        )
        return gs, aws, informed, t_inf

    fn = jax.jit(
        shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P(axis), P(), P()),
            out_specs=(P(), P(), P(axis), P(axis)),
        )
    )
    return fn


@functools.lru_cache(maxsize=None)
def _sharded_incremental_sim(
    config: AgentSimConfig,
    mesh: Mesh,
    axis: str,
    n_true: int,
    budget_agents: int,
    budget_deg: int,
):
    """Event-driven kernel over a device mesh (engine="incremental" + mesh).

    Same invariant as `_incremental_sim` — counts_i(k) = Σ_{j→i} wd_j(k),
    maintained by ±1 updates over changed agents' out-edges — distributed
    with out-edges sharded BY EDGE COUNT: the src-sorted edge array is cut
    into exactly-balanced E/n_dev chunks (the same trick the gather path
    uses for its dst-sorted shards), so scale-free hubs no longer skew any
    per-device padding — a hub's out-edges simply SPLIT across consecutive
    chunks, each device updating its piece. Each device carries host-built
    (local_start, local_deg) tables mapping every global agent to the slice
    of its out-edges inside this chunk.

    Change detection is global: each step all_gathers the BITPACKED
    withdrawn mask (N/8 bytes — the same collective the fallback recount
    needs anyway) and XORs it against the carried previous mask, so every
    device sees every changed agent and serves the changes that own edges
    in its chunk. One `psum_scatter` then both sums the ±1 deltas across
    devices and hands each device its own agent block's slice. Overflow
    anywhere (visible-changed count or a local edge slice above budget;
    psum'd flag, so every device takes the same branch) falls back to the
    full bitpacked recount for that step, reusing the already-gathered
    mask — results stay BIT-IDENTICAL to every other engine/sharding
    combination (tested on skewed scale-free graphs).
    """
    dt = config.dt
    n_dev = mesh.shape[axis]

    def shard_fn(
        betas, src, row_ptr, indeg, dst2, lstart, ldeg, informed0, t_init, key, k0
    ):
        nb = betas.shape[0]
        ec = dst2.shape[0]  # this device's edge-count-balanced chunk
        n_gl = nb * n_dev
        dtype = betas.dtype
        idx = lax.axis_index(axis)
        offset = idx * nb
        ids = (offset + jnp.arange(nb)).astype(jnp.uint32)
        row_ptr = row_ptr[0]  # dst-sorted table for the fallback recount
        lstart = lstart[0]  # (n_gl,) this chunk's slice start per agent
        ldeg = ldeg[0]  # (n_gl,) this chunk's slice length per agent
        t_inf0 = jnp.where(informed0, t_init, jnp.inf).astype(dtype)
        safe_deg = jnp.maximum(indeg, 1.0)
        inv_n = 1.0 / n_true
        d_lane = jnp.arange(budget_deg, dtype=jnp.int32)[None, :]
        has_edges = ldeg > 0

        def bit_at(bits, pos):
            return ((bits[pos >> 3] >> (pos & 7).astype(jnp.uint8)) & 1).astype(
                jnp.int32
            )

        def step(carry, k):
            informed, t_inf, counts, prev_bits = carry
            t = k.astype(dtype) * dt
            wd = _withdrawn(informed, t_inf, t, config.exit_delay, config.reentry_delay)
            wd_bits = jnp.packbits(wd, bitorder="little")
            bits_global = lax.all_gather(wd_bits, axis, tiled=True)  # (n_gl/8,)
            changed = jnp.unpackbits(
                bits_global ^ prev_bits, bitorder="little"
            ).astype(bool)

            visible = changed & has_edges
            n_vis = jnp.sum(visible)
            cids = _compact_ids(visible, budget_agents, n_gl, config.compact_impl)
            valid = cids < n_gl
            cids_c = jnp.minimum(cids, n_gl - 1).astype(jnp.int32)
            degs = jnp.where(valid, ldeg[cids_c], 0)
            overflow = (n_vis > budget_agents) | (jnp.max(degs) > budget_deg)
            overflow_any = lax.psum(overflow.astype(jnp.int32), axis) > 0

            def incr(c):
                starts = lstart[cids_c]
                emask = d_lane < degs[:, None]
                eidx = jnp.minimum(starts[:, None] + d_lane, ec - 1)
                dsts = dst2[eidx]  # global destination ids
                dsts = jnp.where(emask, dsts, n_gl)  # pad lanes → dump slot
                sign = jnp.where(
                    valid, bit_at(bits_global, cids_c) - bit_at(prev_bits, cids_c), 0
                )
                delta = jnp.where(emask, sign[:, None], 0)
                buf = jnp.zeros(n_gl + 1, jnp.int32).at[dsts.ravel()].add(delta.ravel())
                return c + lax.psum_scatter(
                    buf[:n_gl], axis, scatter_dimension=0, tiled=True
                )

            def full(c):
                # the gather path's recount, reusing the already-gathered mask
                return _bit_recount(bits_global, src, row_ptr, axis)

            counts2 = lax.cond(overflow_any, full, incr, counts)
            informed2, t_inf2 = infection_update(
                informed, t_inf, counts2, betas, safe_deg, key, k, ids, t,
                dt, config.rng_stream, config.fused,
            )
            g = lax.psum(jnp.sum(informed.astype(dtype)), axis) * inv_n
            aw = lax.psum(jnp.sum(wd.astype(dtype)), axis) * inv_n
            return (informed2, t_inf2, counts2, bits_global), (g, aw, overflow_any)

        # fresh zero arrays are device-invariant constants; mark them varying
        # over the mesh axis so the scan carry types match the step outputs
        init = (
            informed0,
            t_inf0,
            pcast(jnp.zeros(nb, jnp.int32), (axis,), to="varying"),
            pcast(jnp.zeros(n_gl // 8, jnp.uint8), (axis,), to="varying"),
        )
        (informed, t_inf, _, _), (gs, aws, recs) = lax.scan(
            step, init, jnp.arange(config.n_steps) + k0
        )
        return gs, aws, recs, informed, t_inf

    fn = jax.jit(
        shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(axis),) * 9 + (P(), P()),
            out_specs=(P(), P(), P(), P(axis), P(axis)),
        )
    )
    return fn


@dataclasses.dataclass(frozen=True, eq=False)  # eq=False: fields are device arrays
class PreparedAgentGraph:
    """Device-resident graph structures, reusable across simulations.

    Everything about a simulate_agents call that does NOT depend on the
    seed / initial state: the dst-sorted edge arrays and row-pointer
    tables, per-agent β and in-degrees, the engine choice and its
    out-edge structures, mesh padding and shard tables — already uploaded
    (and sharded, when a mesh is given). Building this costs two O(E)
    host sorts plus ~100 MB of H2D at the 10⁷-edge north-star shape —
    several seconds that a per-call API pays on EVERY run; repeated
    simulations on ONE graph (benchmark reps, seed studies on a fixed
    network) should pay it once via ``prepare_agent_graph`` and pass
    ``prepared=`` to ``simulate_agents``. (Workloads that redraw the
    graph per repetition — e.g. `closure.close_loop`'s rep averaging,
    where graph randomness is part of the Monte-Carlo — gain nothing.)
    """

    n: int
    n_gl: int  # padded agent count (== n without a mesh)
    n_pad: int
    n_edges: int  # TRUE edge count (the device src array may carry pad edges)
    dtype: object  # np.dtype of the simulation floats
    mesh: Optional[Mesh]
    mesh_axis: str
    comm: str
    engine: str  # resolved: "gather" or "incremental"
    budget: int
    max_degree: int
    # device arrays (sharded over mesh_axis when mesh is not None)
    betas: object
    src: object  # dst-sorted edge sources (padded/sharded under a mesh)
    row_ptr: object
    indeg: object
    inc: Optional[tuple]  # engine-specific extra arrays, engine="incremental"
    # engine="measure" only: ((label, measured agent-steps/sec), ...) for
    # each timed candidate in measurement order (2, or 3 when the
    # widened-cap candidate ran — its label carries a "(max_degree=d)"
    # suffix, e.g. "incremental(max_degree=512)") — None otherwise
    measured_steps_per_sec: Optional[Tuple[Tuple[str, float], ...]] = None


def prepare_agent_graph(
    betas,
    src,
    dst,
    n: int,
    config: AgentSimConfig = AgentSimConfig(),
    mesh: Optional[Mesh] = None,
    mesh_axis: str = "agents",
    dtype=np.float32,
    comm: str = "scatter",
    engine: str = "auto",
    incremental_budget: Optional[int] = None,
    incremental_max_degree: Optional[int] = None,
    measure_probe: Optional[dict] = None,
    _canonical: Optional[tuple] = None,
) -> PreparedAgentGraph:
    """Host-side canonicalization + upload, factored out of simulate_agents.

    ``config`` enters only through the engine="auto" census (n_steps and dt
    set the expected fallback rate); the prepared graph is reusable with
    any config whose engine choice you are happy to keep.

    ``engine``: "gather" / "incremental" pick explicitly; "auto" (default)
    picks by the zero-overhead fallback-cost census; "measure" runs one
    timed simulation per candidate on this graph and hardware and keeps
    the faster one (the measured rates land on
    ``PreparedAgentGraph.measured_steps_per_sec``) — the right choice when
    one graph will be simulated many times and ~2 simulations of
    measurement overhead amortizes (engines are bit-identical in results,
    so the choice affects only throughput).

    ``incremental_max_degree``: out-degree cap of the incremental engines'
    dense per-step event grid; None (default) means the framework's 64.
    On a heavy hub tail the cap sets the recount rate (every changed agent
    above it forces a full recount), so ``engine="measure"`` additionally
    tries a widened cap when the census predicts a recount-heavy run and
    the cap was not pinned by the caller — the measured-fastest
    (engine, cap) pair wins (results are identical for any cap; only
    throughput differs).

    ``_canonical``: private — a `_canonicalize_graph(betas, src, dst, n,
    dtype)` result to reuse instead of re-sorting (the engine="measure"
    branch canonicalizes once and shares it with every candidate prepare,
    closing ADVICE r5's duplicate O(E) census; `graphgen` has no use for
    it — device-generated graphs never pass through the host sort).
    """
    dtype = np.dtype(dtype)
    md_pinned = incremental_max_degree is not None
    d0 = int(incremental_max_degree) if md_pinned else 64
    if engine not in ("auto", "gather", "incremental", "measure"):
        raise ValueError(f"Unknown engine {engine!r}")
    if comm not in ("scatter", "allgather_psum"):
        raise ValueError(f"Unknown comm strategy {comm!r}")
    if measure_probe is not None and engine != "measure":
        # same loud-rejection policy as the prepared= conflict guard: a
        # probe passed without engine="measure" would be silently ignored
        raise ValueError(
            f"measure_probe= only applies to engine='measure' (got engine={engine!r})"
        )

    if engine == "measure":
        # A/B-measure the engines on THIS graph, config, and hardware, and
        # return the winner's prepared graph. The census (engine="auto") is
        # a zero-overhead model and deliberately conservative on heavy hub
        # tails (benchmarks/RESULTS.md "Auto-engine census vs measurement":
        # it routed two scale-free shapes to gather where the measurement
        # said incremental by 1.42x and 1.14x); when the same graph will be
        # simulated many times, measuring IS the right decision procedure.
        # Cost: one warm-up (compile) + one timed full run of the given
        # config per candidate — ~2x a single simulation plus compiles —
        # plus one re-preparation of the winner (candidates are dropped
        # after timing so only ONE engine's device arrays are resident at a
        # time; holding both would double peak HBM at exactly the large
        # shapes this targets). The probe trajectory defaults to the
        # default seeding (x0=1e-4, seed=0); because incremental-engine
        # throughput depends on the change mass per step, callers whose
        # real runs start elsewhere should pass ``measure_probe`` (a dict
        # of simulate_agents state kwargs: x0 / seed / informed0 / t_inf0 /
        # exact_seeds) so the timed trajectory is representative. Engines
        # are bit-identical in RESULTS; the probe shapes only which one is
        # faster.
        import time as _time

        probe = dict(measure_probe or {})
        bad = set(probe) - {"x0", "seed", "informed0", "t_inf0", "exact_seeds"}
        if bad:
            raise ValueError(f"measure_probe: unknown keys {sorted(bad)}")
        # ADVICE r5 (agents.py:1105): canonicalize ONCE up front — the dst
        # range validation runs before any census math, the widened-cap
        # gate below reuses the canonicalized host out-degrees instead of
        # an extra O(E) device-to-host transfer + raw bincount, and every
        # candidate prepare shares the same canonical arrays instead of
        # re-sorting the edge list per candidate.
        if _canonical is None:
            _canonical = _canonicalize_graph(betas, src, dst, n, dtype)
        if np.size(src) == 0:
            # both candidates coerce to gather on an edgeless graph — no
            # measurement to run, and labeling a rate "incremental" would lie
            return prepare_agent_graph(
                betas, src, dst, n, config=config, mesh=mesh,
                mesh_axis=mesh_axis, dtype=dtype, comm=comm, engine="gather",
                incremental_budget=incremental_budget,
                incremental_max_degree=d0, _canonical=_canonical,
            )
        candidates = [("gather", d0), ("incremental", d0)]
        if not md_pinned:
            # On a heavy hub tail the cap d0 sets the recount rate; when the
            # census predicts a recount-heavy run AND widening actually
            # shrinks the hub set, an 8x-wider cap is worth a timed try
            # (measured on CPU telemetry: 144 -> 74 recount steps from
            # d=64 -> 512 at the stretch shape; the e2e winner is
            # hardware-dependent — the dense grid widens 8x too). The gate
            # mirrors the auto census's single-device inputs (window-derived
            # waves, default budget, global out-degrees) — under a mesh the
            # engine's true criterion is the per-chunk slice tail, but a
            # mis-gate here only costs one timed candidate or skips one,
            # never correctness.
            outdeg_m = np.bincount(_canonical[1], minlength=n)
            d_wide = 8 * d0
            predicted = _census_fallback_steps(
                outdeg_m, d0, config.n_steps, n,
                float(np.mean(_canonical[0], dtype=np.float64)),
                config.dt,
                incremental_budget or _default_incremental_budget(n),
                waves=_census_waves(config),
            )
            if predicted > 0.1 * config.n_steps and int(
                (outdeg_m > d_wide).sum()
            ) < int((outdeg_m > d0).sum()):
                candidates.append(("incremental", d_wide))
        measured = []
        pg_c = None
        cand_resident = None
        for cand in candidates:
            cand_eng, cand_d = cand
            is_speculative = cand_d != d0
            del pg_c  # previous candidate's device arrays, if any
            try:
                pg_c = prepare_agent_graph(
                    betas, src, dst, n, config=config, mesh=mesh,
                    mesh_axis=mesh_axis, dtype=dtype, comm=comm, engine=cand_eng,
                    incremental_budget=incremental_budget,
                    incremental_max_degree=cand_d, _canonical=_canonical,
                )
                cand_resident = cand
                res = simulate_agents(prepared=pg_c, config=config, **probe)
                float(res.informed_frac[-1])  # warm-up incl. compile
                t0 = _time.perf_counter()
                res = simulate_agents(prepared=pg_c, config=config, **probe)
                float(res.informed_frac[-1])  # device→host fence
                rate = n * config.n_steps / (_time.perf_counter() - t0)
            except Exception:
                # A speculative candidate must not abort a measure prep
                # that succeeds without it (its 8x dense grid can exceed
                # HBM at exactly the large shapes measure targets); the
                # baseline candidates keep the old failure envelope.
                if not is_speculative:
                    raise
                import warnings

                warnings.warn(
                    f"engine='measure': widened-cap candidate "
                    f"(max_degree={cand_d}) failed to prepare/run and was "
                    "dropped from the A/B",
                    RuntimeWarning,
                    stacklevel=2,
                )
                pg_c, cand_resident = None, None
                continue
            label = cand_eng if cand_d == d0 else f"{cand_eng}(max_degree={cand_d})"
            measured.append(((cand_eng, cand_d), label, rate))
            del res
        winner = max(measured, key=lambda t: t[2])[0]
        if winner != cand_resident:  # only the last candidate is resident
            del pg_c
            pg_c = prepare_agent_graph(
                betas, src, dst, n, config=config, mesh=mesh,
                mesh_axis=mesh_axis, dtype=dtype, comm=comm, engine=winner[0],
                incremental_budget=incremental_budget,
                incremental_max_degree=winner[1], _canonical=_canonical,
            )
        return dataclasses.replace(
            pg_c,
            measured_steps_per_sec=tuple((lbl, rate) for _, lbl, rate in measured),
        )

    from sbr_tpu.native import sort_edges_by_dst

    betas_h, src_h, dst_h, indeg_h, row_ptr_h = (
        _canonical
        if _canonical is not None
        else _canonicalize_graph(betas, src, dst, n, dtype)
    )

    if engine == "auto":
        # the census needs only out-degrees (and their cumsum under a
        # mesh) — an O(E) bincount, NOT the full edge re-sort, which is
        # deferred to the branch that actually runs incremental
        engine = _resolve_engine_from_outdeg(
            np.bincount(src_h, minlength=n), n, len(src_h), config, mesh,
            mesh_axis, incremental_budget, d0,
            float(np.mean(betas_h, dtype=np.float64)),
        )
    if engine == "incremental" and len(src_h) == 0:
        # the incremental kernel's dense out-edge grid cannot gather from an
        # empty edge array; the gather kernel handles E = 0 fine
        engine = "gather"

    if mesh is None:
        if engine == "incremental":
            # out-edge structure: the same edge multiset re-sorted by SOURCE
            # (dst2[e] = destination of the e-th src-sorted edge).
            dst2_h, _, outdeg_h, out_ptr_h = sort_edges_by_dst(dst_h, src_h, n)
            budget = incremental_budget
            if budget is None:
                budget = _default_incremental_budget(n)
            inc = (
                jnp.asarray(dst2_h),
                jnp.asarray(out_ptr_h.astype(np.int32)),
                jnp.asarray(outdeg_h),
            )
        else:
            budget, inc = 0, None
        return PreparedAgentGraph(
            n=n, n_gl=n, n_pad=0, n_edges=len(src_h), dtype=dtype, mesh=None,
            mesh_axis=mesh_axis, comm=comm, engine=engine, budget=int(budget),
            max_degree=int(d0),
            betas=jnp.asarray(betas_h), src=jnp.asarray(src_h),
            row_ptr=jnp.asarray(row_ptr_h), indeg=jnp.asarray(indeg_h), inc=inc,
        )

    n_dev = mesh.shape[mesh_axis]
    # agents: pad to a multiple of n_dev with inert agents (β=0, uninformed,
    # degree 0); aggregates normalize by the true N. The "scatter" path —
    # and the incremental engine, whose overflow fallback is the bitpacked
    # recount — additionally need each local block byte-aligned for packing.
    block = 8 * n_dev if (comm == "scatter" or engine == "incremental") else n_dev
    n_pad = (-n) % block
    if n_pad:
        betas_h = np.concatenate([betas_h, np.zeros(n_pad, betas_h.dtype)])
        indeg_h = np.concatenate([indeg_h, np.zeros(n_pad, indeg_h.dtype)])
    # edges arrive dst-sorted (contiguous destination ranges per shard); pad
    # with sentinel dst = N_padded (an extra segment dropped in the kernel).
    n_gl = n + n_pad
    src_h0, dst_h0 = src_h, dst_h  # unpadded, for the out-edge structure
    e_pad = (-len(src_h)) % n_dev
    if e_pad:
        src_h = np.concatenate([src_h, np.zeros(e_pad, np.int32)])
        dst_h = np.concatenate([dst_h, np.full(e_pad, n_gl, np.int32)])
    # Per-shard row-pointer tables over the global segment ids (plus the pad
    # segment): each shard's edge chunk is dst-sorted, so its pointers are a
    # searchsorted over that chunk.
    e_local = len(dst_h) // n_dev
    seg_ids = np.arange(n_gl + 2)
    row_ptrs_h = np.stack(
        [
            np.searchsorted(dst_h[d * e_local : (d + 1) * e_local], seg_ids, side="left")
            for d in range(n_dev)
        ]
    ).astype(np.int32)

    shard = NamedSharding(mesh, P(mesh_axis))
    put = lambda a: jax.device_put(jnp.asarray(a), shard)
    if engine == "incremental":
        # Out-edges sharded BY EDGE COUNT: the src-sorted edge array is cut
        # into exact E/n_dev chunks (sentinel destination n_gl pads the tail
        # into the delta dump slot); per-device (local_start, local_deg)
        # tables map every global agent to its slice inside each chunk, so
        # hub edges split across chunks instead of skewing any padding.
        nb = n_gl // n_dev
        dst2_all, _, _, out_ptr_all = sort_edges_by_dst(dst_h0, src_h0, n)
        e_all = int(out_ptr_all[-1])
        ec = max(1, -(-e_all // n_dev))
        dst2_sh = np.full(n_dev * ec, n_gl, np.int32)
        dst2_sh[:e_all] = dst2_all
        starts = out_ptr_all[:-1].astype(np.int64)
        ends = out_ptr_all[1:].astype(np.int64)
        lstart_h = np.zeros((n_dev, n_gl), np.int32)
        ldeg_h = np.zeros((n_dev, n_gl), np.int32)
        for d in range(n_dev):
            lo, hi = d * ec, (d + 1) * ec
            s = np.clip(starts, lo, hi)
            e_ = np.clip(ends, lo, hi)
            lstart_h[d, :n] = (s - lo).astype(np.int32)
            ldeg_h[d, :n] = (e_ - s).astype(np.int32)
        budget = incremental_budget
        if budget is None:
            budget = _default_incremental_budget(nb, floor=512)
        inc = (put(dst2_sh), put(lstart_h), put(ldeg_h))
    else:
        budget, inc = 0, None
    return PreparedAgentGraph(
        n=n, n_gl=n_gl, n_pad=n_pad, n_edges=len(src_h0), dtype=dtype, mesh=mesh,
        mesh_axis=mesh_axis, comm=comm, engine=engine, budget=int(budget),
        max_degree=int(d0),
        betas=put(betas_h), src=put(src_h), row_ptr=put(row_ptrs_h),
        indeg=put(indeg_h), inc=inc,
    )


def save_agent_state(path, result: AgentSimResult, seed: int, dt: float) -> None:
    """Persist a simulation's exact-resume state to ``path`` (atomic npz).

    Captures everything `simulate_agents` needs to continue ``result``'s
    trajectory bit-identically (the disk form of the in-memory
    ``step_offset``/``informed0``/``t_inf0`` resume surface; the graph is
    NOT stored — re-prepare it with `prepare_agent_graph`, it is
    deterministic in its inputs). ``seed`` must be the seed the run used:
    the per-(agent, step) RNG stream is keyed on it, so resuming under a
    different seed is a different (valid) realization, not a continuation.

    Cross-version note (ADVICE r5): 0.7.0 flipped the default
    ``rng_stream`` from "foldin" to "counter", so exact resume of a
    pre-0.7 artifact requires pinning
    ``AgentSimConfig(rng_stream="foldin")`` in the resuming call —
    resuming under the new default splices two (individually valid)
    streams and the combined trajectory reproduces as neither.
    """
    from sbr_tpu.utils.checkpoint import _save_atomic

    t0 = float(result.t_grid[..., 0])
    k0 = int(round(t0 / dt))
    _save_atomic(
        Path(path),
        dict(
            informed=np.asarray(result.informed),
            t_inf=np.asarray(result.t_inf),
            next_step=np.int64(k0 + result.t_grid.shape[-1]),
            seed=np.int64(seed),
            dt=np.float64(dt),
        ),
    )


def load_agent_state(path, dt: Optional[float] = None) -> dict:
    """Load a `save_agent_state` checkpoint as `simulate_agents` kwargs.

    Returns ``{"informed0", "t_inf0", "step_offset", "seed"}`` — splat into
    the resuming call (with the same graph and a config whose ``dt``
    matches; pass ``dt`` here to validate that early). Resumption is
    bit-identical to an uninterrupted run
    (tests/test_social.py::TestLaunchChunking) — for artifacts written by
    pre-0.7 versions, only under ``rng_stream="foldin"`` (the pre-0.7
    stream; the 0.7.0 default "counter" would splice a different stream
    mid-trajectory — see `save_agent_state`).
    """
    with np.load(Path(path)) as d:
        if dt is not None and abs(float(d["dt"]) - dt) > 1e-12:
            raise ValueError(
                f"checkpoint was written at dt={float(d['dt'])}, resuming "
                f"config has dt={dt} — the step grid would not line up"
            )
        return {
            "informed0": d["informed"],
            "t_inf0": d["t_inf"],
            "step_offset": int(d["next_step"]),
            "seed": int(d["seed"]),
        }


def simulate_agents(
    betas=None,
    src=None,
    dst=None,
    n: Optional[int] = None,
    x0: float = 1e-4,
    config: AgentSimConfig = AgentSimConfig(),
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    mesh_axis: str = "agents",
    dtype=np.float32,
    comm: str = "scatter",
    exact_seeds: bool = False,
    informed0=None,
    t_inf0=None,
    engine: str = "auto",
    incremental_budget: Optional[int] = None,
    incremental_max_degree: Optional[int] = None,
    prepared: Optional[PreparedAgentGraph] = None,
    step_offset: int = 0,
) -> AgentSimResult:
    """Simulate N explicit agents learning from neighbor withdrawals.

    Args:
      betas: scalar or (N,) per-agent learning rates (heterogeneous β_i is
        the agent-level generalization of the hetero extension's K groups).
      src, dst: directed edge lists; dst learns from src's actions.
      n: number of agents.
      x0: initial informed fraction (Bernoulli seeds; ≥1 agent guaranteed
        when x0 > 0, while x0 = 0 runs a genuinely seedless control).
      mesh: optional 1-D device mesh; shards agents and edges (see module
        docstring). Without it, runs single-device.
      comm: sharded-collective strategy for the GATHER engine — "scatter"
        (bitpacked all_gather + psum_scatter, default) or "allgather_psum"
        (naive baseline); both are bit-identical in results (`_sharded_sim`
        docstring). Ignored by engine="incremental", whose only recount path
        is the bitpacked scatter form.
      exact_seeds: seed exactly round(x0·n) agents instead of Bernoulli
        draws (see `_prep_inputs`; used by the closure validation).
      informed0: optional (N,) bool array overriding the seeded initial
        state entirely (x0/exact_seeds are then ignored).
      t_inf0: optional (N,) informed times for the initially-informed
        agents (entries where ``informed0`` is False are ignored). Values
        may be negative — "informed before the simulation window starts" —
        which places mid-trajectory starts correctly relative to the
        withdrawal window (used by `closure.close_loop`). Default 0.
      step_offset: global index of this call's first step; time starts at
        step_offset·dt and the per-(agent, step) RNG stream continues from
        there. With (informed0, t_inf0) taken from a previous result this
        resumes that simulation exactly — the launch-chunking loop
        (``config.max_steps_per_launch``) is built on it. Traced, so
        resuming does not recompile.
      engine: "incremental" maintains withdrawn-neighbor counts by
        event-driven ±1 updates (each agent changes status ≤ 2× per run) —
        3.6× faster end-to-end than "gather" at the 10^6-agent north-star
        shape (5.2 s vs 18.9 s on v5e, benchmarks/RESULTS.md) and
        BIT-IDENTICAL in results (fallback to the full recount on budget
        overflow keeps exactness); "gather" recounts all edges every step;
        "auto" (default) chooses by the expected fallback-step count
        (`_auto_engine`): hub fallbacks from the out-degree tail above
        ``incremental_max_degree`` (under a mesh, the per-CHUNK slice tail —
        edge-count sharding splits hub edges across devices) plus the
        logistic mass-change overflow estimate; a scale-free hub tail or a
        fast contagion (n·β·dt ≫ budget through the bulk) keeps "gather".
        For repeated simulations on one graph, prepare with
        ``prepare_agent_graph(..., engine="measure")`` to A/B-time the
        candidates on the actual hardware instead of trusting the census.
      incremental_budget: max changed agents handled incrementally per step
        (single-device default n//64 clamped to [4096, 65536]). With a mesh
        the budget — including an explicit value — is PER DEVICE, but the
        population it caps is the agents changed ANYWHERE globally that own
        out-edges in the device's E/n_dev chunk (change detection is global
        via the all_gathered bit mask); default nb//64 clamped to
        [512, 65536] for nb = N/n_dev, which is ~1/n_dev of the global
        change rate when ids are uncorrelated with the dynamics. Overflow
        steps fall back to the full recount.
      incremental_max_degree: out-degree cap per changed agent for the
        dense update grid; a changed agent above it triggers the fallback
        for that step (hubs change rarely — at most twice each). None
        (default) means the framework's 64; `prepare_agent_graph`'s
        engine="measure" may try a wider cap when it is not pinned.

    The simulation dtype defaults to float32: aggregates are O(1) means over
    ≥10^4 agents, where Monte-Carlo error dominates rounding by orders of
    magnitude — the f32 sweet spot for TPU (SURVEY §7.3 precision ladder).

    ``prepared``: a `PreparedAgentGraph` from `prepare_agent_graph` — the
    graph-side work (two O(E) host sorts, shard tables, ~100 MB H2D at the
    north-star shape) is then skipped entirely and the graph-related
    arguments (betas/src/dst/n/mesh/comm/engine/budgets/dtype) are ignored.
    Results are BIT-IDENTICAL with or without ``prepared`` (the seed stream
    is independent of graph preparation; tested).
    """
    if prepared is None:
        if betas is None or src is None or dst is None or n is None:
            raise ValueError("simulate_agents needs (betas, src, dst, n) or prepared=")
        if engine == "measure":
            # measure runs 2 warm-ups + 2 timed FULL simulations before the
            # real one — ~5x wall-clock hidden inside a one-shot call, with
            # the measured rates discarded. It only makes sense through
            # prepare_agent_graph, where the cost amortizes over reuse.
            raise ValueError(
                "engine='measure' is a prepare_agent_graph feature: prepare "
                "the graph once (the A/B timing amortizes over repeated "
                "simulations) and pass prepared= here"
            )
        prepared = prepare_agent_graph(
            betas, src, dst, n, config=config, mesh=mesh, mesh_axis=mesh_axis,
            dtype=dtype, comm=comm, engine=engine,
            incremental_budget=incremental_budget,
            incremental_max_degree=incremental_max_degree,
        )
    else:
        # ADVICE r4: graph-shaping arguments alongside prepared= were
        # silently ignored — a caller passing a different n or mesh got the
        # prepared graph's values with no signal. Reject conflicts loudly.
        conflicts = [
            name
            for name, passed in (
                # identity checks only — betas/src/dst may be numpy arrays,
                # where != would produce an ambiguous elementwise result
                ("betas", betas is not None), ("src", src is not None),
                ("dst", dst is not None), ("n", n is not None),
                ("mesh", mesh is not None),
                ("comm", comm != "scatter"), ("engine", engine != "auto"),
                ("incremental_budget", incremental_budget is not None),
                ("incremental_max_degree", incremental_max_degree is not None),
            )
            if passed
        ]
        if conflicts:
            raise ValueError(
                f"simulate_agents: {conflicts} conflict with prepared= — the "
                "prepared graph already fixes the graph/mesh/engine; rebuild "
                "it with prepare_agent_graph(...) to change them"
            )
    n = prepared.n
    dtype_np = prepared.dtype
    for name, arr in (("informed0", informed0), ("t_inf0", t_inf0)):
        # np.shape, not np.asarray: the latter would materialize a device
        # array to host, defeating the chunk-carry fast path below
        if arr is not None and np.shape(arr)[0] != n:
            raise ValueError(
                f"simulate_agents: {name} has length {np.shape(arr)[0]} "
                f"but the graph has n = {n} agents"
            )

    # per-call state: seeds and informed times (the ONLY seed-dependent host
    # work — O(N), milliseconds; `_draw_seeds` is the single definition of
    # the draw order, so prepared and direct calls match bit for bit).
    # Single-device fast path: state that is ALREADY a correctly-typed
    # device array (a previous result's informed/t_inf — the launch-chunking
    # loop's carry) is used as-is, skipping the ~2·O(N)-byte host round-trip
    # per chunk boundary (~6 s at 10^7 agents over the axon tunnel) and
    # letting consecutive launches pipeline on the device stream.
    def _device_ok(arr, want_dtype):
        return (
            prepared.mesh is None
            and isinstance(arr, jax.Array)
            and arr.dtype == want_dtype
            and arr.shape == (n,)
        )

    if informed0 is None:
        informed0_h = _draw_seeds(np.random.default_rng(seed), n, x0, exact_seeds)
    elif _device_ok(informed0, jnp.bool_):
        informed0_h = informed0
    else:
        informed0_h = np.ascontiguousarray(np.asarray(informed0, dtype=bool))
    if t_inf0 is None:
        t_init_h = np.zeros(n, dtype=dtype_np)
    elif _device_ok(t_inf0, jnp.dtype(dtype_np)):
        t_init_h = t_inf0
    else:
        t_init_h = np.ascontiguousarray(np.asarray(t_inf0, dtype=dtype_np))
    key = jax.random.PRNGKey(seed)

    # Launch chunking: split one long device execution into equal host-level
    # launches carrying (informed, t_inf); bit-identical to the unchunked
    # run (see AgentSimConfig.max_steps_per_launch). Chunk lengths are
    # equalized so at most two distinct programs compile.
    launch_cap = config.max_steps_per_launch
    if launch_cap is not None and launch_cap < config.n_steps:
        n_chunks = -(-config.n_steps // launch_cap)
        chunk_len = -(-config.n_steps // n_chunks)
        parts = []
        inf_c, tinf_c = informed0_h, t_init_h
        done = 0
        while done < config.n_steps:
            this_len = min(chunk_len, config.n_steps - done)
            cfg_c = dataclasses.replace(
                config, n_steps=this_len, max_steps_per_launch=None
            )
            part = simulate_agents(
                config=cfg_c, seed=seed, informed0=inf_c, t_inf0=tinf_c,
                prepared=prepared, step_offset=step_offset + done,
            )
            parts.append(part)
            inf_c, tinf_c = part.informed, part.t_inf
            done += this_len
            # cheap scalar fence per boundary: the axon tunnel mishandles a
            # deep async execute queue (measured 2.7x slowdown when three
            # 26 s launches were enqueued without an intervening fetch),
            # while a fenced boundary costs one RPC round-trip. The big
            # state arrays stay on device either way.
            float(part.informed_frac[-1])
        return AgentSimResult(
            t_grid=jnp.concatenate([p.t_grid for p in parts]),
            informed_frac=jnp.concatenate([p.informed_frac for p in parts]),
            withdrawn_frac=jnp.concatenate([p.withdrawn_frac for p in parts]),
            informed=parts[-1].informed,
            t_inf=parts[-1].t_inf,
            full_recount_steps=jnp.concatenate(
                [p.full_recount_steps for p in parts]
            ),
            agent_steps=sum(p.agent_steps for p in parts),
        )
    if config.max_steps_per_launch is not None:
        # non-binding cap (cap >= n_steps): normalize it out of the config so
        # the lru-cached kernel constructors don't compile a duplicate
        # program distinct from the cap=None entry
        config = dataclasses.replace(config, max_steps_per_launch=None)
    k0 = jnp.int32(step_offset)

    if prepared.mesh is None:
        if prepared.engine == "incremental":
            dst2_d, out_ptr_d, outdeg_d = prepared.inc
            run = _incremental_sim(config, prepared.budget, prepared.max_degree)
            return run(
                prepared.betas, prepared.src, prepared.row_ptr, prepared.indeg,
                dst2_d, out_ptr_d, outdeg_d,
                jnp.asarray(informed0_h), jnp.asarray(t_init_h), key, k0,
            )
        run = _single_device_sim(config)
        return run(
            prepared.betas, prepared.src, prepared.row_ptr, prepared.indeg,
            jnp.asarray(informed0_h), jnp.asarray(t_init_h), key, k0,
        )

    mesh = prepared.mesh
    mesh_axis = prepared.mesh_axis
    n_pad = prepared.n_pad
    if n_pad:
        informed0_h = np.concatenate([informed0_h, np.zeros(n_pad, bool)])
        t_init_h = np.concatenate([t_init_h, np.zeros(n_pad, t_init_h.dtype)])
    shard = NamedSharding(mesh, P(mesh_axis))
    key_repl = jax.device_put(key, NamedSharding(mesh, P()))
    informed0_d = jax.device_put(jnp.asarray(informed0_h), shard)
    t_init_d = jax.device_put(jnp.asarray(t_init_h), shard)
    if prepared.engine == "incremental":
        dst2_sh, lstart_d, ldeg_d = prepared.inc
        fn = _sharded_incremental_sim(
            config, mesh, mesh_axis, n, prepared.budget, prepared.max_degree
        )
        gs, aws, recs, informed, t_inf = fn(
            prepared.betas, prepared.src, prepared.row_ptr, prepared.indeg,
            dst2_sh, lstart_d, ldeg_d, informed0_d, t_init_d, key_repl, k0,
        )
    else:
        fn = _sharded_sim(config, mesh, mesh_axis, n, prepared.comm)
        gs, aws, informed, t_inf = fn(
            prepared.betas, prepared.src, prepared.row_ptr, prepared.indeg,
            informed0_d, t_init_d, key_repl, k0,
        )
        recs = jnp.ones(config.n_steps, bool)
    if n_pad:
        # The padding trim [:n] is not shard-aligned; all-gather the final
        # per-agent state (output-only, O(N) bytes) so the slice is local.
        replicated = NamedSharding(mesh, P())
        informed = jax.device_put(informed, replicated)
        t_inf = jax.device_put(t_inf, replicated)
    t_grid = (jnp.arange(config.n_steps) + k0).astype(gs.dtype) * config.dt
    return AgentSimResult(
        t_grid=t_grid,
        informed_frac=gs,
        withdrawn_frac=aws,
        informed=informed[:n],
        t_inf=t_inf[:n],
        full_recount_steps=recs,
        agent_steps=n * config.n_steps,
    )
