"""Social-learning extension: agents learn from observing withdrawals.

Two tiers, per SURVEY §7.2.6:

- :mod:`dynamics` / :mod:`solver` — the representative-agent damped
  fixed-point equilibrium of the reference
  (`src/extensions/social_learning/`), run entirely on device as one
  `lax.while_loop`.
- :mod:`agents` — the explicit-population extension (north star): 10^6
  agents on Erdős–Rényi / scale-free graphs, neighbor-withdrawal learning
  via `segment_sum`, sharded over a device mesh.
"""

from sbr_tpu.social.dynamics import solve_forced_learning
from sbr_tpu.social.solver import SocialFixedPointResult, solve_equilibrium_social
from sbr_tpu.social.agents import (
    AgentSimConfig,
    AgentSimResult,
    PreparedAgentGraph,
    erdos_renyi_edges,
    load_agent_state,
    prepare_agent_graph,
    save_agent_state,
    scale_free_edges,
    simulate_agents,
)
from sbr_tpu.social.closure import LoopComparison, close_loop, equilibrium_window

__all__ = [
    "solve_forced_learning",
    "SocialFixedPointResult",
    "solve_equilibrium_social",
    "AgentSimConfig",
    "AgentSimResult",
    "PreparedAgentGraph",
    "erdos_renyi_edges",
    "prepare_agent_graph",
    "scale_free_edges",
    "simulate_agents",
    "save_agent_state",
    "load_agent_state",
    "LoopComparison",
    "close_loop",
    "equilibrium_window",
]
