"""Social-learning extension: agents learn from observing withdrawals.

Two tiers, per SURVEY §7.2.6:

- :mod:`dynamics` / :mod:`solver` — the representative-agent damped
  fixed-point equilibrium of the reference
  (`src/extensions/social_learning/`), run entirely on device as one
  `lax.while_loop`.
- :mod:`agents` — the explicit-population extension (north star): 10^7+
  agents on Erdős–Rényi / scale-free / stochastic-block graphs,
  neighbor-withdrawal learning via `segment_sum`, sharded over a device
  mesh. Graphs can be generated ON DEVICE (:mod:`graphgen`, 0.8.0) so the
  edge list never transits host RAM, and every engine's per-step
  draw→infection→update tail runs as one fused kernel (:mod:`fused`).
"""

from sbr_tpu.social.dynamics import solve_forced_learning
from sbr_tpu.social.solver import SocialFixedPointResult, solve_equilibrium_social
from sbr_tpu.social.agents import (
    AgentSimConfig,
    AgentSimResult,
    PreparedAgentGraph,
    erdos_renyi_edges,
    load_agent_state,
    prepare_agent_graph,
    save_agent_state,
    scale_free_edges,
    simulate_agents,
)
from sbr_tpu.social.closure import LoopComparison, close_loop, equilibrium_window
from sbr_tpu.social.graphgen import (
    ErdosRenyiSpec,
    ScaleFreeSpec,
    StochasticBlockSpec,
    prepare_generated_graph,
)

__all__ = [
    "solve_forced_learning",
    "SocialFixedPointResult",
    "solve_equilibrium_social",
    "AgentSimConfig",
    "AgentSimResult",
    "PreparedAgentGraph",
    "erdos_renyi_edges",
    "prepare_agent_graph",
    "scale_free_edges",
    "simulate_agents",
    "save_agent_state",
    "load_agent_state",
    "LoopComparison",
    "close_loop",
    "equilibrium_window",
    "ErdosRenyiSpec",
    "ScaleFreeSpec",
    "StochasticBlockSpec",
    "prepare_generated_graph",
]
