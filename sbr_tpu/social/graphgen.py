"""On-device generative graph models (ISSUE 10 tentpole).

Every graph the agent engine consumed before 0.8.0 was born on the host
(`agents.erdos_renyi_edges` numpy / `native/graphgen.cpp`): at the
10^7–10^8-edge north-star scale the edge list transits host RAM and PCIe
before a single device step runs, and the host sort becomes the wall. This
module generates graphs ON DEVICE, chunk by chunk, with the same stateless
counter-RNG discipline as the simulation kernels (one Threefry-2x32 block
per edge id, `rng._threefry2x32`), directly into the canonical dst-sorted /
row-pointer layout — the edge list never materializes on the host.

The streams are BORN dst-sorted. Each spec factors its edge law as
(destination marginal) × (source conditional): the in-degree vector is ONE
host multinomial draw over the destination marginal (node-length, O(N)
host scalars — the only host work), its cumsum is the canonical row-pointer
table, the destination of edge position p is structural (the row whose
[row_ptr[d], row_ptr[d+1]) range contains p — a run-length `repeat`, never
a search per edge), and only the SOURCE is drawn per edge, keyed by the
edge id. Factoring this way deletes the device-side sort from the
canonical build entirely: XLA's CPU sort runs ~700 ns/element where a
Threefry draw runs ~4 ns/element, so a sort-then-canonicalize design would
be slower than the host path it replaces, and this one is bound by the
draw itself. Host parity stays bitwise and meaningful: the host
canonicalization of the raw stream is asserted equal to the device layout
(the stable dst-sort of a born-sorted stream is the identity, so the check
pins row-pointer/self-loop/degree bookkeeping, not sort order).

Three generative models (specs are frozen dataclasses, hashable jit keys):

- `ErdosRenyiSpec` — sparse directed G(n, p): E ~ Binomial(n(n−1), p)
  (host, O(1)), in-degrees multinomial-uniform, sources iid uniform —
  exactly the law of E iid uniform (src, dst) pairs.
- `ScaleFreeSpec` — Chung–Lu configuration model: in-degrees multinomial
  over weights (i+1)^{−1/(γ−1)}, sources drawn ∝ the same weights via a
  uint32-quantized inverse CDF, so in- AND out-degree tails carry
  exponent γ.
- `StochasticBlockSpec` — balanced contiguous blocks; the destination
  marginal is uniform (blocks are near-equal), and each edge keeps its
  SOURCE within the destination's block with probability ``p_in``
  (uniform over the other blocks otherwise).

The incremental engine's out-edge orientation additionally needs the
edges grouped by source in the host layout's (src, dst, raw id) order —
that one is a genuine DISTRIBUTED STABLE COUNTING SORT over the canonical
stream: chunk histograms accumulate into the out-pointer table, each chunk
is stable-sorted locally and scattered at
``out_ptr[s] + offset_of_prior_chunks[s] + rank_within_chunk`` — order by
(src, chunk, local rank) = (src, dst-sorted position) = (src, dst, raw
id), exactly the host re-sort's tie-break. It runs lazily (gather-engine
builds never pay it) and is the sort-bound part of an incremental-engine
build on CPU.

Under a mesh the same passes run inside shard_map with each device
generating ONLY its contiguous position range (no collectives at all for
the canonical layout — positions are pure functions of (seed, edge id));
the out-edge sort merges per-device histograms with
`parallel.collectives.exclusive_psum` (n_dev−1 ``ppermute`` rounds, O(N)
peak instead of all_gather's O(N·n_dev)) and a tiled `psum_scatter` that
both merges the scatter buffers and hands every device exactly its
edge-count-balanced shard — the same collective family the simulation
step already rides. Sharded generation is byte-identical to single-device
generation for the same seed (tested): positions depend only on (seed,
edge id), never on the mesh.

`prepare_generated_graph` packages the result as a `PreparedAgentGraph`
(both engines, single-device and mesh), so every existing simulate/
closure/bench path consumes generated graphs unchanged. The raw stream is
also exposed host-side (`generate_edges`) for parity tests and interop —
that path re-introduces the O(E) host transit and exists for verification,
not production.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sbr_tpu.parallel.collectives import exclusive_psum
from sbr_tpu.parallel.compat import shard_map
from sbr_tpu.social.rng import _threefry2x32

__all__ = [
    "ErdosRenyiSpec",
    "ScaleFreeSpec",
    "StochasticBlockSpec",
    "epoch_key_words",
    "epoch_indegrees",
    "generate_edges",
    "generate_tilted_sources",
    "plan_chunk_edges",
    "prepare_generated_graph",
    "tilt_threshold_table",
]


# ---------------------------------------------------------------------------
# Graph specs
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ErdosRenyiSpec:
    """Sparse directed Erdős–Rényi G(n, p) with p = avg_degree/(n−1)."""

    n: int
    avg_degree: float

    def __post_init__(self):
        _check_spec(self.n, self.avg_degree)

    def edge_count(self, seed: int) -> int:
        # Same edge-count law as the host sampler: E ~ Binomial(n(n−1), p),
        # drawn host-side (O(1) — no edge data touches the host).
        rng = np.random.default_rng(seed)
        p = self.avg_degree / max(self.n - 1, 1)
        return int(rng.binomial(self.n * (self.n - 1), min(p, 1.0)))


@dataclasses.dataclass(frozen=True)
class ScaleFreeSpec:
    """Chung–Lu power-law configuration model: both endpoints ∝
    (i+1)^{−1/(γ−1)}, so in- AND out-degree tails carry exponent γ
    (in-degree drives the learning dynamics — it must have the heavy
    tail)."""

    n: int
    avg_degree: float
    gamma: float = 2.5

    def __post_init__(self):
        _check_spec(self.n, self.avg_degree)
        if not (self.gamma > 1.0):
            raise ValueError("gamma must be > 1")

    def edge_count(self, seed: int) -> int:
        return int(self.n * self.avg_degree)


@dataclasses.dataclass(frozen=True)
class StochasticBlockSpec:
    """Balanced stochastic block model: ``n_blocks`` contiguous blocks of
    near-equal size; the destination marginal is uniform and each edge
    keeps its source inside the destination's block with probability
    ``p_in`` (uniform over the other blocks otherwise)."""

    n: int
    avg_degree: float
    n_blocks: int = 4
    p_in: float = 0.8

    def __post_init__(self):
        _check_spec(self.n, self.avg_degree)
        if self.n_blocks < 2:
            raise ValueError("n_blocks must be >= 2")
        if self.n < 2 * self.n_blocks:
            raise ValueError("need n >= 2*n_blocks (every block needs >= 2 nodes)")
        if not (0.0 <= self.p_in <= 1.0):
            raise ValueError("p_in must be in [0, 1]")

    def edge_count(self, seed: int) -> int:
        return int(self.n * self.avg_degree)


# Edge positions are int32 and the chunked loops index up to one chunk past
# E (pad lanes of the final chunk), so specs must leave chunk headroom below
# 2^31: chunks are clamped to _MAX_CHUNK everywhere, and 2^27 of headroom
# also absorbs the ER binomial's fluctuation around n·avg_degree (σ ~ √E ≪
# 2^27 at any representable E).
_MAX_CHUNK = 1 << 26
_MAX_EDGES = 2**31 - 2**27


def _check_spec(n: int, avg_degree: float) -> None:
    if n < 2:
        raise ValueError("need n >= 2 agents")
    if not (avg_degree > 0):
        raise ValueError("avg_degree must be positive")
    if n >= 2**31 or n * avg_degree >= _MAX_EDGES:
        raise ValueError(
            "graphgen is int32-indexed with chunk headroom: need n < 2^31 "
            "and E < 2^31 - 2^27"
        )


def _check_edges(e: int) -> int:
    """Runtime backstop for the position arithmetic: the drawn edge count
    (binomial for ER) must keep the final chunk's positions in int32."""
    if e >= _MAX_EDGES:
        raise ValueError(
            f"drawn edge count {e} leaves no int32 chunk headroom "
            f"(need E < {_MAX_EDGES})"
        )
    return e


def _spec_key_words(seed: int) -> Tuple[np.uint32, np.uint32]:
    """The (k0, k1) Threefry key words for a generation seed — derived via
    numpy's SeedSequence, NOT jax.random, so the stream is identical under
    any jax_default_prng_impl (rbg keys would violate the 2-word layout the
    counter draws need) and across processes (tested)."""
    k0, k1 = np.random.SeedSequence(seed).generate_state(2, np.uint32)
    return np.uint32(k0), np.uint32(k1)


def _spec_weights(spec) -> Optional[np.ndarray]:
    """The destination-marginal weight vector, or None for uniform."""
    if isinstance(spec, ScaleFreeSpec):
        return np.arange(1, spec.n + 1, dtype=np.float64) ** (
            -1.0 / (spec.gamma - 1.0)
        )
    return None


def epoch_key_words(seed: int, epoch: int) -> Tuple[np.uint32, np.uint32]:
    """Threefry key words for one panic-rewiring EPOCH (ISSUE 15): derived
    via SeedSequence((seed, 23, epoch)) — a distinct stream per epoch,
    independent of the base generation stream (salt 23 collides with
    neither `_spec_key_words`'s bare seed nor `_indeg_host`'s salt-1
    tuple), deterministic across processes like every graphgen stream."""
    k0, k1 = np.random.SeedSequence((seed, 23, epoch)).generate_state(2, np.uint32)
    return np.uint32(k0), np.uint32(k1)


def epoch_indegrees(spec, seed: int, epoch: int, e: int) -> np.ndarray:
    """Per-epoch in-degree vector for panic rewiring: the SAME destination
    marginal as the base spec (attention re-aims at sources, not at who
    listens — see `tilt_threshold_table`), redrawn per epoch from
    SeedSequence((seed, 1, epoch)) so epoch graphs are independent
    realizations yet deterministic in (seed, epoch) across processes."""
    rng = np.random.default_rng(np.random.SeedSequence((seed, 1, epoch)))
    w = _spec_weights(spec)
    if w is None:
        indeg = np.zeros(spec.n, np.int64)
        done = 0
        while done < e:
            take = min(1 << 24, e - done)
            indeg += np.bincount(
                rng.integers(0, spec.n, size=take), minlength=spec.n
            )
            done += take
        return indeg.astype(np.int32)
    return rng.multinomial(e, w / w.sum()).astype(np.int32)


def tilt_threshold_table(base_weights, wd, bias):
    """uint32-quantized inverse CDF of the panic-tilted SOURCE marginal
    (ISSUE 15): p(src = j) ∝ w_j · (1 + bias·wd_j), where ``wd`` is the
    current withdrawn mask. This is the "attention concentrates on
    withdrawing neighbors" law factored the graphgen way — the
    destination marginal (who has how many in-edges) is untouched, so
    the epoch stream is still BORN dst-sorted and no device sort ever
    runs; only the per-edge source draw consults this table.

    Traced (wd is simulation state): one device cumsum + normalize per
    epoch, O(N). Quantization to 2^32 buckets carries the same ≤2^-24
    relative bias as `_mulhi32`'s range map under f32 accumulation —
    vanishing against the generative model's own sampling noise,
    documented like the base generators'."""
    w = jnp.asarray(base_weights)
    t = w * (1.0 + jnp.asarray(bias, w.dtype) * wd.astype(w.dtype))
    cdf = jnp.cumsum(t)
    cdf = cdf / cdf[-1]
    thr = jnp.minimum(cdf * 4294967296.0, 4294967295.0)
    return thr.astype(jnp.uint32)


@functools.lru_cache(maxsize=32)
def _tilted_src_program(n: int, e: int, chunk: int, n_chunks: int):
    """Jitted chunked source-assembly for one (n, E, chunk plan): draws
    the dst-sorted source array of a rewired epoch from a tilted
    threshold TABLE argument (the wd-dependent part stays traced, so one
    program serves every epoch of a run — no per-epoch recompiles)."""

    @jax.jit
    def assemble(thr_table, k0, k1):
        from sbr_tpu.obs import prof

        prof.note_trace("social.graphgen.tilted_src")

        def body(c, out):
            c0 = c * jnp.int32(chunk)
            eid = (c0 + jnp.arange(chunk, dtype=jnp.int32)).astype(jnp.uint32)
            x0, _ = _threefry2x32(k0, k1, eid, jnp.zeros_like(eid))
            s = jnp.minimum(_searchsorted32(thr_table, x0, "right"), n - 1)
            return lax.dynamic_update_slice(out, s, (c0,))

        out = jnp.zeros(n_chunks * chunk, jnp.int32)
        return lax.fori_loop(0, n_chunks, body, out)[:e]

    return assemble


def generate_tilted_sources(n: int, e: int, key_words, thr_table,
                            chunk_edges=None):
    """dst-sorted SOURCE array of one rewired epoch: E counter-Threefry
    draws against the tilted inverse-CDF table, chunked under the same
    capacity plan as the base builds (``SBR_GRAPHGEN_BUDGET_BYTES`` et
    al. via `plan_chunk_edges`). Positions are pure functions of
    (epoch key, edge id), so the result is chunk-invariant and
    deterministic in-process and across processes (tested)."""
    if e == 0:
        return jnp.zeros(0, jnp.int32)
    chunk = (
        plan_chunk_edges(e, n)
        if chunk_edges in (None, "auto")
        else int(chunk_edges)
    )
    chunk = max(1, min(chunk, max(e, 1), _MAX_CHUNK))
    n_chunks = max(1, -(-e // chunk))
    k0, k1 = key_words
    run = _tilted_src_program(n, e, chunk, n_chunks)
    return run(thr_table, jnp.uint32(k0), jnp.uint32(k1))


def _indeg_host(spec, seed: int, e: int) -> np.ndarray:
    """The in-degree vector: ONE host multinomial draw over the spec's
    destination marginal (the only node-length host data in a build).
    Destinations are then STRUCTURAL: edge position p's destination is the
    row whose [row_ptr[d], row_ptr[d+1]) range contains p, which is what
    removes every device-side sort from the canonical build. Seeded from
    SeedSequence((seed, 1)) — independent of the edge-count draw,
    deterministic across processes (tested).

    Uniform marginals (ER, SBM) draw it as bincount(uniform ints) — the
    law is exactly Multinomial(E, uniform) and numpy's bounded-int stream
    runs ~2x faster than its sequential binomial chain. The draws are
    consumed in bounded chunks (numpy Generators continue the SAME bit
    stream across calls, so any chunking is bitwise the full draw —
    tested) and discarded after counting, keeping the host transient
    O(chunk) instead of the O(E) buffer the plan budget never sees; no
    edge-length data survives the call. Weighted marginals (scale-free)
    use the multinomial directly (node-length output)."""
    rng = np.random.default_rng(np.random.SeedSequence((seed, 1)))
    w = _spec_weights(spec)
    if w is None:
        indeg = np.zeros(spec.n, np.int64)
        done = 0
        while done < e:
            take = min(1 << 24, e - done)  # ≤128 MB int64 transient
            indeg += np.bincount(
                rng.integers(0, spec.n, size=take), minlength=spec.n
            )
            done += take
        return indeg.astype(np.int32)
    return rng.multinomial(e, w / w.sum()).astype(np.int32)


def _spec_tables(spec) -> Tuple[np.ndarray, ...]:
    """Host-built node-length lookup tables for the SOURCE conditional
    (O(N) scalars, built once per prepare — never per edge): the quantized
    inverse CDF for scale-free, block boundaries for SBM, nothing for ER."""
    if isinstance(spec, ErdosRenyiSpec):
        return ()
    if isinstance(spec, ScaleFreeSpec):
        cdf = np.cumsum(_spec_weights(spec))
        cdf /= cdf[-1]
        thr = np.minimum(np.floor(cdf * 2.0**32), 2.0**32 - 1).astype(np.uint32)
        return (thr,)
    if isinstance(spec, StochasticBlockSpec):
        b = spec.n_blocks
        starts = (np.arange(b + 1, dtype=np.int64) * spec.n + b - 1) // b
        return (starts.astype(np.uint32),)
    raise TypeError(f"unknown graph spec {type(spec).__name__}")


# ---------------------------------------------------------------------------
# Per-edge draws (pure functions of (key words, edge id, structural dst))
# ---------------------------------------------------------------------------


def _mulhi32(a, m):
    """floor(a·m / 2^32) in pure uint32 arithmetic (Lemire multiply-shift
    range map, TPU-safe: no uint64, works with x64 disabled). Bias is at
    most m/2^32 relative per value — ≤ 2.4% at m = 10^8, vanishing at test
    scales; acceptable for generative models, documented."""
    mask = jnp.uint32(0xFFFF)
    a_lo, a_hi = a & mask, a >> jnp.uint32(16)
    m = jnp.asarray(m, jnp.uint32)
    m_lo, m_hi = m & mask, m >> jnp.uint32(16)
    mid = a_hi * m_lo + ((a_lo * m_lo) >> jnp.uint32(16))
    mid2 = a_lo * m_hi + (mid & mask)
    return a_hi * m_hi + (mid >> jnp.uint32(16)) + (mid2 >> jnp.uint32(16))


def _searchsorted32(table, x, side):
    return jnp.searchsorted(table, x, side=side).astype(jnp.int32)


def _needs_dst(spec) -> bool:
    """Whether the source conditional reads the structural destination.
    ER and scale-free sources are marginal draws — their build never
    materializes a destination array at all (self-loops are ALLOWED in
    those streams: the expected count is E/n — ~10 edges at the 10^7-agent
    bench shape — and a self-edge only adds a 1/deg self-observation term,
    invisible against the dense-limit closure tolerances; the host sampler
    resamples them, so the two streams are different, equally valid,
    realizations of the same model). SBM conditions on the destination's
    block, which the structural layout provides for free."""
    return isinstance(spec, StochasticBlockSpec)


def _src_at(spec, tables, k0, k1, eid, dst):
    """Source node (int32 in [0, n)) of edge ``eid`` (uint32) given its
    structural destination ``dst`` (may be None for specs whose source is
    marginal — `_needs_dst`) — one Threefry block per edge."""
    n = spec.n
    x0, x1 = _threefry2x32(k0, k1, eid, jnp.zeros_like(eid))
    if isinstance(spec, ErdosRenyiSpec):
        return _mulhi32(x0, n).astype(jnp.int32)
    if isinstance(spec, ScaleFreeSpec):
        (thr,) = tables
        return jnp.minimum(_searchsorted32(thr, x0, "right"), n - 1)
    (starts,) = tables
    blk = _searchsorted32(starts, dst.astype(jnp.uint32), "right") - 1
    lo = starts[blk].astype(jnp.int32)
    size = (starts[blk + 1] - starts[blk]).astype(jnp.int32)
    within = x1 < jnp.uint32(min(int(spec.p_in * 2.0**32), 2**32 - 1))
    s_in = lo + _mulhi32(x0, size.astype(jnp.uint32)).astype(jnp.int32)
    # in-block self-loop rewire stays in-block: shift one slot (mod size)
    off2 = jnp.where(s_in - lo + 1 >= size, 0, s_in - lo + 1)
    s_in = jnp.where(s_in == dst, lo + off2, s_in)
    m_out = (jnp.int32(n) - size).astype(jnp.uint32)
    r = _mulhi32(x0, m_out).astype(jnp.int32)
    s_out = r + jnp.where(r >= lo, size, 0)
    return jnp.where(within, s_in, s_out)


def _dst_chunk(row_ptr, n: int, c0, chunk: int):
    """Structural destinations for the contiguous positions
    [c0, c0+chunk): run-lengths are the row spans clipped to the window —
    a `repeat`, never a per-edge search. Positions past E repeat the final
    value; callers mask or slice those lanes away."""
    reps = jnp.diff(jnp.clip(row_ptr, c0, c0 + chunk))
    return jnp.repeat(jnp.arange(n, dtype=jnp.int32), reps, total_repeat_length=chunk)


# ---------------------------------------------------------------------------
# Chunked device builds
# ---------------------------------------------------------------------------


def _chunk_scatter(out, offs, key, val, row_base, chunk: int, n: int):
    """Scatter one chunk's payloads at their global stable-sort positions.

    ``key`` ∈ [0, n] (n = sentinel bucket for invalid/pad lanes, whose
    ``row_base`` entry starts at E so they stream into the tail or fall
    off and are dropped); position = row start + prior-chunk offset +
    stable rank within the chunk. Returns the updated (out, offs)."""
    order = jnp.argsort(key, stable=True)
    k_s = key[order]
    hist = jnp.zeros(n + 1, jnp.int32).at[key].add(1)
    start_in_chunk = (jnp.cumsum(hist) - hist).astype(jnp.int32)[k_s]
    rank = jnp.arange(chunk, dtype=jnp.int32) - start_in_chunk
    pos = row_base[k_s] + offs[k_s] + rank
    out = out.at[pos].set(val[order], mode="drop")
    return out, offs + hist


@functools.lru_cache(maxsize=32)
def _single_device_programs(spec, chunk: int, n_chunks: int, e: int):
    """Jitted (outdeg hist, src assemble, inc assemble) programs for one
    (spec, chunk plan, E).

    E is static (array shapes depend on it), so a fresh edge count
    compiles a fresh program — the same per-shape cost the sim kernels
    pay; repeated builds of one spec/seed reuse the cache."""
    n = spec.n

    def chunk_draw(tables, k0, k1, row_ptr, c):
        c0 = c * jnp.int32(chunk)
        eid = c0 + jnp.arange(chunk, dtype=jnp.int32)
        d = _dst_chunk(row_ptr, n, c0, chunk) if _needs_dst(spec) else None
        s = _src_at(spec, tables, k0, k1, eid.astype(jnp.uint32), d)
        return eid, s

    @jax.jit
    def hist_out(tables, k0, k1, row_ptr):
        from sbr_tpu.obs import prof

        prof.note_trace("social.graphgen.hist_out")

        def body(c, h):
            eid, s = chunk_draw(tables, k0, k1, row_ptr, c)
            return h.at[jnp.where(eid < e, s, n)].add(1)

        return lax.fori_loop(0, n_chunks, body, jnp.zeros(n + 1, jnp.int32))

    @jax.jit
    def assemble_src(tables, k0, k1, row_ptr):
        # The canonical dst-sorted source array IS the draw itself: the
        # stream is born sorted, so chunk c writes positions [c·chunk,
        # (c+1)·chunk) verbatim — no sort, no scatter.
        from sbr_tpu.obs import prof

        prof.note_trace("social.graphgen.assemble_src")

        def body(c, out):
            _, s = chunk_draw(tables, k0, k1, row_ptr, c)
            return lax.dynamic_update_slice(out, s, (c * jnp.int32(chunk),))

        out = jnp.zeros(n_chunks * chunk, jnp.int32)
        return lax.fori_loop(0, n_chunks, body, out)[:e]

    @jax.jit
    def assemble_inc(src_srt, row_ptr, out_ptr):
        # Counting-sort pass over the CANONICAL stream: chunking by
        # dst-sorted position makes the stable tie-break exactly that
        # position, so the global order is (src, dst, raw id) — the host
        # `sort_edges_by_dst(dst_h, src_h, n)` re-sort, byte for byte.
        from sbr_tpu.obs import prof

        prof.note_trace("social.graphgen.assemble_inc")

        def body(c, carry):
            out, offs = carry
            c0 = c * jnp.int32(chunk)
            p = c0 + jnp.arange(chunk, dtype=jnp.int32)
            valid = p < e
            key = jnp.where(valid, src_srt[jnp.minimum(p, max(e - 1, 0))], n)
            d = _dst_chunk(row_ptr, n, c0, chunk)
            return _chunk_scatter(out, offs, key, d, out_ptr, chunk, n)

        out0 = jnp.zeros(e, jnp.int32)
        offs0 = jnp.zeros(n + 1, jnp.int32)
        out, _ = lax.fori_loop(0, n_chunks, body, (out0, offs0))
        return out

    return hist_out, assemble_src, assemble_inc


def plan_chunk_edges(e: int, n: int, budget_bytes: Optional[int] = None) -> int:
    """Capacity-planned generation chunk (edges per fori step).

    Deterministic in (e, n, budget): per-chunk scratch is ~12 int32 lanes
    per edge (Threefry temps, draws, sort keys/order and positions on the
    inc pass) plus the fixed N-vectors and the E-length output the
    chunking cannot avoid, so the chunk is the largest power of two whose
    scratch fits the budget — floored at 2^14 (tiny chunks drown in fori
    overhead) and capped at E. The budget defaults to
    ``SBR_GRAPHGEN_BUDGET_BYTES``, else headroom × device capacity
    (obs.mem), else a quarter of host MemAvailable on capacity-less CPU
    backends, else 1 GiB. The canonical RESULT is chunk-invariant
    (tested) — the plan affects peak memory and speed only, never bytes."""
    from sbr_tpu.obs import mem

    if budget_bytes is None:
        env = os.environ.get("SBR_GRAPHGEN_BUDGET_BYTES", "").strip()
        if env:
            budget_bytes = int(env)
        else:
            cap = mem.device_capacity()
            if cap:
                budget_bytes = int(mem.headroom() * cap)
            else:
                host = mem.host_available_bytes()
                budget_bytes = host // 4 if host else 1 << 30
    fixed = 6 * 4 * (n + 1) + 4 * e  # N-vectors + output buffer
    per_edge = 12 * 4
    chunk = max((budget_bytes - fixed) // per_edge, 1)
    chunk = 1 << min(max(int(math.floor(math.log2(max(chunk, 1)))), 14), 26)
    return int(min(chunk, max(e, 1)))


def _log_plan(rec: dict) -> None:
    try:
        from sbr_tpu.obs import runlog

        run = runlog.current_run()
        if run is not None:
            run.log_plan(rec)
    except Exception:
        pass


class _SingleBuild:
    """Single-device build context: the canonical layout (indeg, row_ptr,
    dst-sorted src) is sort-free — indeg is the host multinomial, src is
    the chunked draw; the out-degree census and the incremental
    orientation run lazily (gather-engine builds never pay the counting
    sort). Layout is `agents._canonicalize_graph` bitwise (tested) —
    ``row_ptr``'s last entry is E, which doubles as the sentinel bucket's
    start in the inc scatter pass."""

    def __init__(self, spec, seed: int, chunk_edges):
        self._spec = spec
        self._src_srt = None
        self._outdeg = None
        self.e = e = _check_edges(spec.edge_count(seed))
        chunk = (
            plan_chunk_edges(e, spec.n)
            if chunk_edges in (None, "auto")
            else int(chunk_edges)
        )
        chunk = max(1, min(chunk, max(e, 1), _MAX_CHUNK))
        n_chunks = max(1, -(-e // chunk))
        _log_plan(
            {
                "what": "graphgen.chunk", "spec": type(spec).__name__,
                "n": spec.n, "edges": e, "chunk_edges": chunk,
                "n_chunks": n_chunks,
            }
        )
        k0, k1 = _spec_key_words(seed)
        self._key = (k0, k1)
        self._tables = tuple(jnp.asarray(t) for t in _spec_tables(spec))
        self._hist_out, self._assemble, self._assemble_inc = (
            _single_device_programs(spec, chunk, n_chunks, e)
        )
        indeg_h = _indeg_host(spec, seed, e)
        self.indeg = jnp.asarray(indeg_h)
        self.row_ptr = jnp.asarray(
            np.concatenate([[0], np.cumsum(indeg_h)]).astype(np.int32)
        )

    def src_sorted(self):
        """dst-sorted edge sources — the gather-engine layout."""
        if self._src_srt is None:
            k0, k1 = self._key
            if self.e == 0:
                self._src_srt = jnp.zeros(0, jnp.int32)
            else:
                self._src_srt = self._assemble(self._tables, k0, k1, self.row_ptr)
        return self._src_srt

    @property
    def outdeg(self):
        """Out-degree census (one chunked scatter-add pass, lazy — only
        the auto-engine gate and the incremental orientation need it)."""
        if self._outdeg is None:
            k0, k1 = self._key
            if self.e == 0:
                self._outdeg = jnp.zeros(self._spec.n, jnp.int32)
            else:
                h = self._hist_out(self._tables, k0, k1, self.row_ptr)
                self._outdeg = h[: self._spec.n]
        return self._outdeg

    def inc_arrays(self):
        """(dst2, out_ptr) — the src-sorted out-edge structures the
        incremental engine adds, in the host layout's (src, dst, raw id)
        order (the counting-sort pass, see `assemble_inc`)."""
        out_ptr = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(self.outdeg)]
        ).astype(jnp.int32)
        dst2 = self._assemble_inc(self.src_sorted(), self.row_ptr, out_ptr)
        return dst2, out_ptr


# ---------------------------------------------------------------------------
# Mesh build (edge-count-sharded generation)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=16)
def _mesh_hist_program(spec, chunk: int, k_slots: int, e: int, mesh: Mesh, axis: str):
    """Jitted shard_map out-degree census: each device histograms only its
    own position range; `psum` merges."""
    n = spec.n
    n_dev = mesh.shape[axis]
    el = max(1, (e + (-e) % n_dev) // n_dev)
    n_tab = len(_spec_tables(spec))

    def hist_fn(*args):
        tables = args[:n_tab]
        (k0, k1), row_ptr = args[n_tab], args[n_tab + 1]
        idx = lax.axis_index(axis)

        def body(c, h):
            c0 = idx * jnp.int32(el) + c * jnp.int32(chunk)
            eid = c0 + jnp.arange(chunk, dtype=jnp.int32)
            d = (
                _dst_chunk(row_ptr, n, jnp.minimum(c0, max(e - 1, 0)), chunk)
                if _needs_dst(spec)
                else None
            )
            s = _src_at(spec, tables, k0, k1, eid.astype(jnp.uint32), d)
            ok = (eid < e) & (eid < (idx + 1) * jnp.int32(el))
            return h.at[jnp.where(ok, s, n)].add(1)

        h = lax.fori_loop(0, k_slots, body, jnp.zeros(n + 1, jnp.int32))
        return lax.psum(h, axis)

    rep = (P(),) * (n_tab + 2)
    return jax.jit(shard_map(hist_fn, mesh=mesh, in_specs=rep, out_specs=P()))


@functools.lru_cache(maxsize=16)
def _mesh_programs(
    spec, chunk: int, k_slots: int, e: int, n_gl: int, mesh: Mesh, axis: str,
    want_inc: bool,
):
    """Jitted shard_map (gather, inc) programs for a mesh build.

    Each device generates ONLY its contiguous position range
    [idx·el, (idx+1)·el) — the canonical layout needs no collectives at
    all (positions are pure functions of (seed, edge id)); the inc pass
    merges per-device histograms with `exclusive_psum` and delivers each
    device its edge shard with a tiled `psum_scatter`."""
    n = spec.n
    n_dev = mesh.shape[axis]
    e_pad = (-e) % n_dev
    el = max(1, (e + e_pad) // n_dev)
    ec = el  # inc edge-chunk size == the position-shard length
    n_tab = len(_spec_tables(spec))

    def local_src_dst(tables, k0, k1, row_ptr, idx):
        def body(c, carry):
            srcb, dstb = carry
            c0 = idx * jnp.int32(el) + c * jnp.int32(chunk)
            eid = c0 + jnp.arange(chunk, dtype=jnp.int32)
            d = _dst_chunk(row_ptr, n, jnp.minimum(c0, max(e - 1, 0)), chunk)
            s = _src_at(spec, tables, k0, k1, eid.astype(jnp.uint32), d)
            off = (c * jnp.int32(chunk),)
            return (
                lax.dynamic_update_slice(srcb, s, off),
                lax.dynamic_update_slice(dstb, d, off),
            )

        z = jnp.zeros(k_slots * chunk, jnp.int32)
        srcb, dstb = lax.fori_loop(0, k_slots, body, (z, z))
        return srcb[:el], dstb[:el]

    def gather_fn(*args):
        # dst-sorted orientation: local src shard + per-shard row table
        tables = args[:n_tab]
        (k0, k1), row_ptr = args[n_tab], args[n_tab + 1]
        idx = lax.axis_index(axis)
        src_l, dst_l = local_src_dst(tables, k0, k1, row_ptr, idx)
        gpos = idx * jnp.int32(el) + jnp.arange(el, dtype=jnp.int32)
        valid = gpos < e
        src_l = jnp.where(valid, src_l, jnp.int32(0))  # src pad = 0 (host rule)
        dst_l = jnp.where(valid, dst_l, jnp.int32(n_gl))
        seg_ids = jnp.arange(n_gl + 2, dtype=jnp.int32)
        table = jnp.searchsorted(dst_l, seg_ids, side="left").astype(jnp.int32)
        return src_l, table[None]

    def inc_fn(src_local, row_ptr, out_ptr):
        # src-sorted orientation: distributed counting sort over the
        # dst-sorted position shards (each device's chunk = its shard), so
        # the stable tie-break is the dst-sorted position and the global
        # order is (src, dst, raw id) — the host `sort_edges_by_dst`
        # re-sort of the canonical stream, byte for byte.
        idx = lax.axis_index(axis)
        gpos = (idx * jnp.int32(el) + jnp.arange(el, dtype=jnp.int32))
        valid = gpos < e
        key = jnp.where(valid, src_local, jnp.int32(n))
        d = _dst_chunk(
            row_ptr, n, jnp.minimum(idx * jnp.int32(el), max(e - 1, 0)), el
        )
        hist = jnp.zeros(n + 1, jnp.int32).at[key].add(1)
        prior = exclusive_psum(hist, axis, n_dev)
        out0 = jnp.zeros(n_dev * ec, jnp.int32)
        out, _ = _chunk_scatter(out0, prior, key, d, out_ptr, el, n)
        local = lax.psum_scatter(out, axis, scatter_dimension=0, tiled=True)
        gpos2 = idx * ec + jnp.arange(ec)
        local = jnp.where(gpos2 < e, local, jnp.int32(n_gl))
        lo = idx * ec
        starts = out_ptr[:-1]
        ends = out_ptr[1:]
        s_c = jnp.clip(starts, lo, lo + ec)
        e_c = jnp.clip(ends, lo, lo + ec)
        pad = jnp.zeros(n_gl - n, jnp.int32)
        lstart = jnp.concatenate([(s_c - lo).astype(jnp.int32), pad])
        ldeg = jnp.concatenate([(e_c - s_c).astype(jnp.int32), pad])
        return local, lstart[None], ldeg[None]

    rep = (P(),) * (n_tab + 2)
    gather_p = jax.jit(
        shard_map(gather_fn, mesh=mesh, in_specs=rep, out_specs=(P(axis), P(axis)))
    )
    inc_p = (
        jax.jit(
            shard_map(
                inc_fn, mesh=mesh, in_specs=(P(axis), P(), P()),
                out_specs=(P(axis), P(axis), P(axis)),
            )
        )
        if want_inc
        else None
    )
    return gather_p, inc_p


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def generate_edges(spec, seed: int = 0, chunk_edges=None) -> Tuple[np.ndarray, np.ndarray]:
    """The RAW (src, dst) edge stream as host numpy arrays (dst-sorted —
    the stream is born that way).

    This is the verification/interop surface (parity tests against the
    host canonicalization, degree statistics) — it deliberately pays the
    O(E) device→host transfer the production path
    (`prepare_generated_graph`) exists to avoid."""
    e = _check_edges(spec.edge_count(seed))
    if e == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    k0, k1 = _spec_key_words(seed)
    tables = tuple(jnp.asarray(t) for t in _spec_tables(spec))
    indeg_h = _indeg_host(spec, seed, e)
    row_ptr = jnp.asarray(np.concatenate([[0], np.cumsum(indeg_h)]).astype(np.int32))
    chunk = (
        max(1, min(int(chunk_edges), _MAX_CHUNK))
        if chunk_edges
        else min(max(e, 1), 1 << 22)
    )

    @functools.partial(jax.jit, static_argnames=("count",))
    def raw(tables, row_ptr, c0, count: int):
        eid = jnp.int32(c0) + jnp.arange(count, dtype=jnp.int32)
        d = _dst_chunk(row_ptr, spec.n, c0, count)
        s = _src_at(
            spec, tables, jnp.uint32(k0), jnp.uint32(k1), eid.astype(jnp.uint32), d
        )
        return s, d

    srcs, dsts = [], []
    done = 0
    while done < e:
        count = min(chunk, e - done)
        s, d = raw(tables, row_ptr, c0=done, count=count)
        srcs.append(np.asarray(s, np.int32))
        dsts.append(np.asarray(d, np.int32))
        done += count
    return np.concatenate(srcs), np.concatenate(dsts)


def prepare_generated_graph(
    spec,
    seed: int = 0,
    betas=1.0,
    config=None,
    mesh: Optional[Mesh] = None,
    mesh_axis: str = "agents",
    dtype=np.float32,
    comm: str = "scatter",
    engine: str = "auto",
    incremental_budget: Optional[int] = None,
    incremental_max_degree: Optional[int] = None,
    chunk_edges=None,
):
    """Device-generated `PreparedAgentGraph` — `prepare_agent_graph` with
    the host edge pipeline deleted.

    The graph named by ``(spec, seed)`` is generated and canonicalized on
    device (sharded over ``mesh`` when given, each device generating only
    its position range) and packaged with the same engine resolution,
    budget rules, and mesh padding as the host prepare — so
    ``simulate_agents(prepared=...)``, the closure loop, and the bench
    consume it unchanged, and the result is byte-identical to host-
    preparing the same raw stream (tested). ``engine="measure"`` is not
    offered here: measuring wants a resident reusable graph, which the
    host prepare already covers; generated graphs pick via the census or
    explicitly. ``engine="gather"`` builds never pay the out-degree census
    or the incremental orientation's counting sort — the canonical layout
    is sort-free.

    Only node-length data ever touches the host: β broadcast, the spec
    tables, the in-degree multinomial, and (for ``engine="auto"`` /
    ``"incremental"``) the out-degree census.
    """
    from sbr_tpu.social import agents as A

    if config is None:
        config = A.AgentSimConfig()
    dtype = np.dtype(dtype)
    if engine not in ("auto", "gather", "incremental"):
        raise ValueError(
            f"engine must be 'auto', 'gather', or 'incremental' for generated "
            f"graphs (got {engine!r})"
        )
    if comm not in ("scatter", "allgather_psum"):
        raise ValueError(f"Unknown comm strategy {comm!r}")
    n = spec.n
    d0 = int(incremental_max_degree) if incremental_max_degree is not None else 64
    betas_h = np.broadcast_to(np.asarray(betas, dtype=dtype), (n,)).copy()

    from sbr_tpu import obs

    if mesh is None:
        with obs.span("graphgen.build", spec=type(spec).__name__, n=n):
            built = _SingleBuild(spec, seed, chunk_edges)
            e = built.e
            if engine == "auto":
                engine = A._resolve_engine_from_outdeg(
                    np.asarray(built.outdeg), n, e, config, None, mesh_axis,
                    incremental_budget, d0,
                    float(np.mean(betas_h, dtype=np.float64)),
                )
            if engine == "incremental" and e == 0:
                engine = "gather"
            if engine == "incremental":
                budget = incremental_budget or A._default_incremental_budget(n)
                dst2, out_ptr = built.inc_arrays()
                inc = (dst2, out_ptr, built.outdeg)
            else:
                budget, inc = 0, None
            return A.PreparedAgentGraph(
                n=n, n_gl=n, n_pad=0, n_edges=e, dtype=dtype, mesh=None,
                mesh_axis=mesh_axis, comm=comm, engine=engine,
                budget=int(budget), max_degree=d0,
                betas=jnp.asarray(betas_h),
                src=built.src_sorted(), row_ptr=built.row_ptr,
                indeg=built.indeg.astype(dtype), inc=inc,
            )

    with obs.span("graphgen.build_sharded", spec=type(spec).__name__, n=n):
        return _prepare_mesh(
            spec, seed, betas_h, config, mesh, mesh_axis, dtype, comm, engine,
            incremental_budget, d0, chunk_edges,
        )


def _prepare_mesh(
    spec, seed, betas_h, config, mesh, mesh_axis, dtype, comm, engine,
    incremental_budget, d0, chunk_edges,
):
    from sbr_tpu.social import agents as A

    n = spec.n
    n_dev = mesh.shape[mesh_axis]
    e = _check_edges(spec.edge_count(seed))
    el = max(1, (e + (-e) % n_dev) // n_dev)
    chunk = (
        plan_chunk_edges(e, n)
        if chunk_edges in (None, "auto")
        else int(chunk_edges)
    )
    chunk = max(1, min(chunk, el, _MAX_CHUNK))
    k_slots = max(1, -(-el // chunk))
    _log_plan(
        {
            "what": "graphgen.chunk",
            "spec": type(spec).__name__,
            "n": n,
            "edges": e,
            "chunk_edges": chunk,
            "n_chunks": k_slots * int(n_dev),
            "n_dev": int(n_dev),
        }
    )
    k0, k1 = _spec_key_words(seed)
    tables = tuple(jnp.asarray(t) for t in _spec_tables(spec))
    kw = jnp.asarray(np.stack([k0, k1]))

    indeg_h = _indeg_host(spec, seed, e)
    row_ptr_g = jnp.asarray(np.concatenate([[0], np.cumsum(indeg_h)]).astype(np.int32))

    outdeg_h = None
    if engine == "auto" or engine == "incremental":
        hist_p = _mesh_hist_program(spec, chunk, k_slots, e, mesh, mesh_axis)
        outdeg_h = np.asarray(hist_p(*tables, kw, row_ptr_g)[:n])
    if engine == "auto":
        engine = A._resolve_engine_from_outdeg(
            outdeg_h, n, e, config, mesh, mesh_axis, incremental_budget, d0,
            float(np.mean(betas_h, dtype=np.float64)),
        )
    if engine == "incremental" and e == 0:
        engine = "gather"

    n_gl = _n_gl(n, n_dev, comm, engine)
    n_pad = n_gl - n
    gather_p, inc_p = _mesh_programs(
        spec, chunk, k_slots, e, n_gl, mesh, mesh_axis, engine == "incremental"
    )
    src_sh, row_tables = gather_p(*tables, kw, row_ptr_g)

    if engine == "incremental":
        out_ptr_g = jnp.asarray(
            np.concatenate([[0], np.cumsum(outdeg_h)]).astype(np.int32)
        )
        if os.environ.get("SBR_FLIGHT", "").strip() not in ("", "0"):
            # Flight-recorded launch of the exclusive_psum-bearing program
            # (collectives stream): block_until_ready gives the span an
            # honest device fence; the VALUES are untouched, so answers
            # stay bit-identical with the recorder on.
            import time as _time

            from sbr_tpu.obs import flight as _flight

            _t0 = _time.monotonic()
            dst2_sh, lstart_sh, ldeg_sh = jax.block_until_ready(
                inc_p(src_sh, row_ptr_g, out_ptr_g)
            )
            _flight.shared().mark(
                "collectives", "psum", _t0, _time.monotonic(), tag="inc"
            )
        else:
            dst2_sh, lstart_sh, ldeg_sh = inc_p(src_sh, row_ptr_g, out_ptr_g)
        nb = n_gl // n_dev
        budget = incremental_budget or A._default_incremental_budget(nb, floor=512)
        inc = (dst2_sh, lstart_sh, ldeg_sh)
    else:
        budget, inc = 0, None

    shard = NamedSharding(mesh, P(mesh_axis))
    if n_pad:
        betas_h = np.concatenate([betas_h, np.zeros(n_pad, betas_h.dtype)])
    indeg_p = np.concatenate([indeg_h, np.zeros(n_pad, indeg_h.dtype)]).astype(dtype)
    put = lambda a: jax.device_put(jnp.asarray(a), shard)
    return A.PreparedAgentGraph(
        n=n, n_gl=n_gl, n_pad=n_pad, n_edges=e, dtype=dtype, mesh=mesh,
        mesh_axis=mesh_axis, comm=comm, engine=engine, budget=int(budget),
        max_degree=d0,
        betas=put(betas_h), src=src_sh, row_ptr=row_tables,
        indeg=put(indeg_p), inc=inc,
    )


def _n_gl(n: int, n_dev: int, comm: str, engine: str) -> int:
    """Padded agent count — the same block rule as `prepare_agent_graph`:
    byte-aligned local blocks for the bitpacked paths."""
    block = 8 * n_dev if (comm == "scatter" or engine == "incremental") else n_dev
    return n + (-n) % block


# ---------------------------------------------------------------------------
# Self-check CLI (the CI graphgen-parity smoke)
# ---------------------------------------------------------------------------


def _selfcheck(n: int = 600, deg: float = 6.0, seed: int = 3) -> int:
    """Bitwise parity battery: device canonical layout vs the host sort of
    the same raw stream, chunk invariance, sharded-vs-single byte
    identity, and fused-vs-unfused simulation parity. Exit 0 on pass."""
    from sbr_tpu.social import agents as A

    failures = []

    def check(label, ok):
        print(("PASS  " if ok else "FAIL  ") + label)
        if not ok:
            failures.append(label)

    specs = [
        ErdosRenyiSpec(n=n, avg_degree=deg),
        ScaleFreeSpec(n=n, avg_degree=deg, gamma=2.5),
        StochasticBlockSpec(n=n, avg_degree=deg, n_blocks=4, p_in=0.8),
    ]
    for spec in specs:
        name = type(spec).__name__
        src, dst = generate_edges(spec, seed=seed)
        _, src_h, _, indeg_h, row_ptr_h = A._canonicalize_graph(
            1.0, src, dst, n, np.float32
        )
        built = _SingleBuild(spec, seed, None)
        check(f"{name}: device src == host canonical src",
              np.array_equal(np.asarray(built.src_sorted()), src_h))
        check(f"{name}: row_ptr match",
              np.array_equal(np.asarray(built.row_ptr), row_ptr_h.astype(np.int32)))
        check(f"{name}: indeg match",
              np.array_equal(np.asarray(built.indeg), indeg_h.astype(np.int32)))
        built2 = _SingleBuild(spec, seed, 97)
        check(f"{name}: chunk invariance (97-edge chunks)",
              np.array_equal(np.asarray(built.src_sorted()),
                             np.asarray(built2.src_sorted())))
        pg_d = prepare_generated_graph(spec, seed=seed, engine="incremental")
        pg_h = A.prepare_agent_graph(1.0, src, dst, n, engine="incremental")
        check(
            f"{name}: single-device incremental == host-prepared",
            all(
                np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(pg_d.inc, pg_h.inc)
            )
            and np.array_equal(np.asarray(pg_d.src), np.asarray(pg_h.src)),
        )
        check(
            f"{name}: chunk invariance (incremental, 97-edge chunks)",
            np.array_equal(
                np.asarray(built.inc_arrays()[0]), np.asarray(built2.inc_arrays()[0])
            ),
        )

    spec = specs[0]
    if len(jax.devices()) > 1:
        from sbr_tpu.parallel import make_agent_mesh

        mesh = make_agent_mesh()
        src, dst = generate_edges(spec, seed=seed)
        for eng in ("gather", "incremental"):
            pg_d = prepare_generated_graph(
                spec, seed=seed, mesh=mesh, engine=eng
            )
            pg_h = A.prepare_agent_graph(
                1.0, src, dst, n, mesh=mesh, engine=eng
            )
            ok = (
                np.array_equal(np.asarray(pg_d.src), np.asarray(pg_h.src))
                and np.array_equal(np.asarray(pg_d.row_ptr), np.asarray(pg_h.row_ptr))
                and np.array_equal(np.asarray(pg_d.indeg), np.asarray(pg_h.indeg))
            )
            if eng == "incremental":
                ok = ok and all(
                    np.array_equal(np.asarray(a), np.asarray(b))
                    for a, b in zip(pg_d.inc, pg_h.inc)
                )
            check(f"sharded {eng}: generated == host-prepared (byte-identical)", ok)

    import dataclasses as dc

    cfg = A.AgentSimConfig(n_steps=25, dt=0.1)
    pg = prepare_generated_graph(spec, seed=seed, config=cfg)
    r_def = A.simulate_agents(prepared=pg, x0=0.02, config=cfg, seed=1)
    r_unf = A.simulate_agents(
        prepared=pg, x0=0.02, config=dc.replace(cfg, fused="unfused"), seed=1
    )
    r_int = A.simulate_agents(
        prepared=pg, x0=0.02, config=dc.replace(cfg, fused="interpret"), seed=1
    )
    check("fused lax == unfused (bitwise)",
          np.array_equal(np.asarray(r_def.informed), np.asarray(r_unf.informed))
          and np.array_equal(np.asarray(r_def.t_inf), np.asarray(r_unf.t_inf)))
    check("fused pallas-interpret == unfused (bitwise)",
          np.array_equal(np.asarray(r_int.informed), np.asarray(r_unf.informed))
          and np.array_equal(np.asarray(r_int.t_inf), np.asarray(r_unf.t_inf)))

    print(f"\ngraphgen selfcheck: {len(failures)} failure(s)")
    return 1 if failures else 0


# CLI: `python -m sbr_tpu.social.graphgen_cli --selfcheck` — a sibling
# module, NOT a __main__ block here: this module is imported by the
# package __init__, so running it with -m would execute a second __main__
# copy (duplicate spec classes breaking isinstance dispatch, duplicate
# lru-cached program builders) behind a RuntimeWarning.
