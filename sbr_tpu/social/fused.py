"""Fused infection step: counter-Threefry draw → hazard → state update in
one kernel (ISSUE 10).

Every simulation engine in ``social.agents`` ends its step with the same
per-agent chain::

    frac   = counts / indeg
    p_inf  = 1 - exp(-β · frac · dt)
    draws  = threefry(key_step, agent_id)        ← N uniforms
    newly  = ~informed & (draws < p_inf)
    informed' = informed | newly
    t_inf'    = where(newly, t+dt, t_inf)

Unfused, each intermediate (frac, p_inf, draws, newly) is an N-length
array the backend may materialize between ops; at 10^7–10^8 agents those
five N-vectors per step are pure memory traffic. This module collapses the
chain into one kernel with three lowerings, selected by
``AgentSimConfig.fused``:

- ``"lax"`` — the chain as a single jnp helper, byte-for-byte the ops the
  pre-0.8 kernels inlined. XLA's fusion handles the rest on CPU; this is
  the default off-TPU, so tier-1 semantics are UNCHANGED by construction.
- ``"pallas"`` — a Pallas kernel, grid over agent blocks: each block loads
  (informed, t_inf, counts, β, indeg) once, runs the Threefry block and
  the hazard update in registers/VMEM, and writes only (informed',
  t_inf') — no materialized uniform or mask intermediates. Default on
  TPU/GPU backends for the counter stream at non-f64 dtypes.
- ``"interpret"`` — the same Pallas kernel under ``interpret=True``: runs
  everywhere (including the CPU tier-1 box), which is what makes the
  fused-vs-unfused bitwise parity testable without TPU hardware.

``"unfused"`` keeps the historical inline sequence (via
``rng._agent_uniforms``) and is the parity oracle; ``"auto"`` resolves per
platform (overridable via ``SBR_FUSED``). The lax and unfused paths are
the same arithmetic, and the Pallas kernel reuses the exact
`rng._threefry2x32` / `rng._uniform_from_bits` definitions, so CPU
fallback and interpret mode are bit-identical to unfused (tested); only a
compiled TPU Pallas `exp` could differ in ulps, which is why "auto" never
picks "pallas" where the tier-1 contracts run.

The foldin stream has no counter form (its draw is two chained Threefry
blocks through `jax.random.fold_in`), so pallas/interpret requests under
``rng_stream="foldin"`` resolve to the unfused path — pre-0.7 artifact
resumes stay exact under any ``fused`` setting.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from sbr_tpu.social.rng import (
    _agent_uniforms,
    _key_words,
    _threefry2x32,
    _uniform_from_bits,
)

MODES = ("auto", "unfused", "lax", "pallas", "interpret")

# Pallas block: one grid step updates this many agents. 8·128 matches the
# TPU (sublane, lane) tiling for f32; interpret mode uses the same value so
# the tested program structure is the deployed one.
_BLOCK = 1024


def resolve_mode(mode: str, dtype, rng_stream: str) -> str:
    """Concrete lowering for a requested ``AgentSimConfig.fused`` value.

    "auto" consults ``SBR_FUSED`` then the backend: pallas on tpu/gpu, lax
    elsewhere. Pallas variants degrade (never error) when the stream or
    dtype cannot express them: foldin has no counter form → unfused; f64
    needs uint64 words, which compiled TPU Pallas lacks → lax ("interpret"
    keeps f64: the interpreter runs full-width ops).
    """
    if mode not in MODES:
        raise ValueError(f"fused must be one of {MODES}, got {mode!r}")
    if mode == "auto":
        env = os.environ.get("SBR_FUSED", "").strip().lower()
        if env and env not in MODES:
            # a typo'd override must not silently fall through to the
            # platform default — the user believes they pinned a lowering
            raise ValueError(f"SBR_FUSED must be one of {MODES}, got {env!r}")
        mode = env if env and env != "auto" else "auto"
    if mode == "auto":
        mode = "pallas" if jax.default_backend() in ("tpu", "gpu") else "lax"
    if mode != "unfused" and rng_stream != "counter":
        # Every fused lowering (lax included) computes the counter draw
        # in-line; foldin draws only exist as `_agent_uniforms`' chained
        # fold_in blocks, so non-counter streams always run unfused.
        return "unfused"
    if mode == "pallas" and np.dtype(dtype) == np.float64:
        return "lax"
    return mode


def _update_lax(informed, t_inf, counts, betas, safe_deg, draws, t, dt):
    """The infection-update arithmetic — the ONE definition the unfused,
    lax, and Pallas (via identical jnp ops in-kernel) paths share."""
    dtype = betas.dtype
    frac = counts.astype(dtype) / safe_deg
    p_inf = 1.0 - jnp.exp(-betas * frac * dt)
    newly = (~informed) & (draws < p_inf)
    informed2 = informed | newly
    t_inf2 = jnp.where(newly, t + dt, t_inf)
    return informed2, t_inf2


@functools.lru_cache(maxsize=None)
def _pallas_update(n: int, dtype_name: str, dt: float, interpret: bool):
    """Build the Pallas fused-update callable for a fixed (N, dtype, dt).

    Cached per shape/config so the pallas_call machinery is constructed
    once per kernel program (mirrors the lru-cached sim constructors)."""
    from jax.experimental import pallas as pl

    dtype = jnp.dtype(dtype_name)
    n_pad = (-n) % _BLOCK
    n_b = (n + n_pad) // _BLOCK

    def kernel(kd_ref, t_ref, ids_ref, informed_ref, tinf_ref, counts_ref,
               betas_ref, deg_ref, inf2_ref, tinf2_ref):
        ids = ids_ref[...]
        x0, x1 = _threefry2x32(
            kd_ref[0], kd_ref[1], ids, jnp.zeros_like(ids)
        )
        draws = _uniform_from_bits(x0, x1, dtype)
        informed2, t_inf2 = _update_lax(
            informed_ref[...], tinf_ref[...], counts_ref[...], betas_ref[...],
            deg_ref[...], draws, t_ref[0], dt,
        )
        inf2_ref[...] = informed2
        tinf2_ref[...] = t_inf2

    block = pl.BlockSpec((_BLOCK,), lambda i: (i,))
    scalar2 = pl.BlockSpec((2,), lambda i: (0,))
    scalar1 = pl.BlockSpec((1,), lambda i: (0,))
    call = pl.pallas_call(
        kernel,
        grid=(n_b,),
        in_specs=[scalar2, scalar1, block, block, block, block, block, block],
        out_specs=[block, block],
        out_shape=[
            jax.ShapeDtypeStruct((n + n_pad,), jnp.bool_),
            jax.ShapeDtypeStruct((n + n_pad,), dtype),
        ],
        interpret=interpret,
    )

    def run(kd, t, ids, informed, t_inf, counts, betas, safe_deg):
        if n_pad:
            # inert pad lanes: β=0 ⇒ p_inf=0 ⇒ never newly-informed; the
            # Threefry draw for a pad id is computed and discarded
            ids = jnp.concatenate([ids, jnp.zeros(n_pad, ids.dtype)])
            informed = jnp.concatenate([informed, jnp.zeros(n_pad, jnp.bool_)])
            t_inf = jnp.concatenate([t_inf, jnp.zeros(n_pad, t_inf.dtype)])
            counts = jnp.concatenate([counts, jnp.zeros(n_pad, counts.dtype)])
            betas = jnp.concatenate([betas, jnp.zeros(n_pad, betas.dtype)])
            safe_deg = jnp.concatenate([safe_deg, jnp.ones(n_pad, safe_deg.dtype)])
        informed2, t_inf2 = call(kd, t, ids, informed, t_inf, counts, betas, safe_deg)
        return informed2[:n], t_inf2[:n]

    return run


def infection_update(informed, t_inf, counts, betas, safe_deg, key, step_k,
                     ids, t, dt, rng_stream: str, mode: str):
    """One fused infection step for every engine's per-agent tail.

    Pure function of (state, counts, key, step, global ids) — all the
    engine/sharding bit-identity contracts carry over unchanged because
    the draw stays keyed by global agent id (`rng._agent_uniforms`'s
    invariance). Returns (informed', t_inf').
    """
    dtype = betas.dtype
    mode = resolve_mode(mode, dtype, rng_stream)
    if mode == "unfused":
        draws = _agent_uniforms(key, step_k, ids, dtype, rng_stream)
        return _update_lax(informed, t_inf, counts, betas, safe_deg, draws, t, dt)
    step_key = jax.random.fold_in(key, step_k)
    words = _key_words(step_key)
    if words is None:
        # rbg/unsafe_rbg 4-word keys: no counter stream — the foldin
        # fallback is the unfused path (same degradation as _agent_uniforms)
        draws = _agent_uniforms(key, step_k, ids, dtype, rng_stream)
        return _update_lax(informed, t_inf, counts, betas, safe_deg, draws, t, dt)
    if mode == "lax":
        c0 = ids.astype(jnp.uint32)
        x0, x1 = _threefry2x32(words[0], words[1], c0, jnp.zeros_like(c0))
        draws = _uniform_from_bits(x0, x1, dtype)
        return _update_lax(informed, t_inf, counts, betas, safe_deg, draws, t, dt)
    run = _pallas_update(
        int(informed.shape[0]), jnp.dtype(dtype).name, float(dt),
        interpret=(mode == "interpret"),
    )
    kd = jnp.stack([words[0], words[1]])
    t_arr = jnp.reshape(jnp.asarray(t, dtype), (1,))
    return run(kd, t_arr, ids, informed, t_inf, counts, betas, safe_deg)
