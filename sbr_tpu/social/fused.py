"""Fused infection step: counter-Threefry draw → hazard → state update in
one kernel (ISSUE 10).

Every simulation engine in ``social.agents`` ends its step with the same
per-agent chain::

    frac   = counts / indeg
    p_inf  = 1 - exp(-β · frac · dt)
    draws  = threefry(key_step, agent_id)        ← N uniforms
    newly  = ~informed & (draws < p_inf)
    informed' = informed | newly
    t_inf'    = where(newly, t+dt, t_inf)

Unfused, each intermediate (frac, p_inf, draws, newly) is an N-length
array the backend may materialize between ops; at 10^7–10^8 agents those
five N-vectors per step are pure memory traffic. This module collapses the
chain into one kernel with three lowerings, selected by
``AgentSimConfig.fused``:

- ``"lax"`` — the chain as a single jnp helper, byte-for-byte the ops the
  pre-0.8 kernels inlined. XLA's fusion handles the rest on CPU; this is
  the default off-TPU, so tier-1 semantics are UNCHANGED by construction.
- ``"pallas"`` — a Pallas kernel, grid over agent blocks: each block loads
  (informed, t_inf, counts, β, indeg) once, runs the Threefry block and
  the hazard update in registers/VMEM, and writes only (informed',
  t_inf') — no materialized uniform or mask intermediates. Default on
  TPU/GPU backends for the counter stream at non-f64 dtypes.
- ``"interpret"`` — the same Pallas kernel under ``interpret=True``: runs
  everywhere (including the CPU tier-1 box), which is what makes the
  fused-vs-unfused bitwise parity testable without TPU hardware.

``"unfused"`` keeps the historical inline sequence (via
``rng._agent_uniforms``) and is the parity oracle; ``"auto"`` resolves per
platform (overridable via ``SBR_FUSED``). The lax and unfused paths are
the same arithmetic, and the Pallas kernel reuses the exact
`rng._threefry2x32` / `rng._uniform_from_bits` definitions, so CPU
fallback and interpret mode are bit-identical to unfused (tested); only a
compiled TPU Pallas `exp` could differ in ulps, which is why "auto" never
picks "pallas" where the tier-1 contracts run.

The foldin stream has no counter form (its draw is two chained Threefry
blocks through `jax.random.fold_in`), so pallas/interpret requests under
``rng_stream="foldin"`` resolve to the unfused path — pre-0.7 artifact
resumes stay exact under any ``fused`` setting.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from sbr_tpu.social.rng import (
    _agent_uniforms,
    _key_words,
    _threefry2x32,
    _uniform_from_bits,
)

MODES = ("auto", "unfused", "lax", "pallas", "interpret")

# Pallas block: one grid step updates this many agents. 8·128 matches the
# TPU (sublane, lane) tiling for f32; interpret mode uses the same value so
# the tested program structure is the deployed one.
_BLOCK = 1024


def resolve_mode(mode: str, dtype, rng_stream: str) -> str:
    """Concrete lowering for a requested ``AgentSimConfig.fused`` value.

    "auto" consults ``SBR_FUSED`` then the backend: pallas on tpu/gpu, lax
    elsewhere. Pallas variants degrade (never error) when the stream or
    dtype cannot express them: foldin has no counter form → unfused; f64
    needs uint64 words, which compiled TPU Pallas lacks → lax ("interpret"
    keeps f64: the interpreter runs full-width ops).
    """
    if mode not in MODES:
        raise ValueError(f"fused must be one of {MODES}, got {mode!r}")
    if mode == "auto":
        env = os.environ.get("SBR_FUSED", "").strip().lower()
        if env and env not in MODES:
            # a typo'd override must not silently fall through to the
            # platform default — the user believes they pinned a lowering
            raise ValueError(f"SBR_FUSED must be one of {MODES}, got {env!r}")
        mode = env if env and env != "auto" else "auto"
    if mode == "auto":
        mode = "pallas" if jax.default_backend() in ("tpu", "gpu") else "lax"
    if mode != "unfused" and rng_stream != "counter":
        # Every fused lowering (lax included) computes the counter draw
        # in-line; foldin draws only exist as `_agent_uniforms`' chained
        # fold_in blocks, so non-counter streams always run unfused.
        return "unfused"
    if mode == "pallas" and np.dtype(dtype) == np.float64:
        return "lax"
    return mode


def _update_lax(informed, t_inf, counts, betas, safe_deg, draws, t, dt):
    """The infection-update arithmetic — the ONE definition the unfused,
    lax, and Pallas (via identical jnp ops in-kernel) paths share."""
    dtype = betas.dtype
    frac = counts.astype(dtype) / safe_deg
    p_inf = 1.0 - jnp.exp(-betas * frac * dt)
    newly = (~informed) & (draws < p_inf)
    informed2 = informed | newly
    t_inf2 = jnp.where(newly, t + dt, t_inf)
    return informed2, t_inf2


@functools.lru_cache(maxsize=None)
def _pallas_update(n: int, dtype_name: str, dt: float, interpret: bool):
    """Build the Pallas fused-update callable for a fixed (N, dtype, dt).

    Cached per shape/config so the pallas_call machinery is constructed
    once per kernel program (mirrors the lru-cached sim constructors)."""
    from jax.experimental import pallas as pl

    dtype = jnp.dtype(dtype_name)
    n_pad = (-n) % _BLOCK
    n_b = (n + n_pad) // _BLOCK

    def kernel(kd_ref, t_ref, ids_ref, informed_ref, tinf_ref, counts_ref,
               betas_ref, deg_ref, inf2_ref, tinf2_ref):
        ids = ids_ref[...]
        x0, x1 = _threefry2x32(
            kd_ref[0], kd_ref[1], ids, jnp.zeros_like(ids)
        )
        draws = _uniform_from_bits(x0, x1, dtype)
        informed2, t_inf2 = _update_lax(
            informed_ref[...], tinf_ref[...], counts_ref[...], betas_ref[...],
            deg_ref[...], draws, t_ref[0], dt,
        )
        inf2_ref[...] = informed2
        tinf2_ref[...] = t_inf2

    block = pl.BlockSpec((_BLOCK,), lambda i: (i,))
    scalar2 = pl.BlockSpec((2,), lambda i: (0,))
    scalar1 = pl.BlockSpec((1,), lambda i: (0,))
    call = pl.pallas_call(
        kernel,
        grid=(n_b,),
        in_specs=[scalar2, scalar1, block, block, block, block, block, block],
        out_specs=[block, block],
        out_shape=[
            jax.ShapeDtypeStruct((n + n_pad,), jnp.bool_),
            jax.ShapeDtypeStruct((n + n_pad,), dtype),
        ],
        interpret=interpret,
    )

    def run(kd, t, ids, informed, t_inf, counts, betas, safe_deg):
        if n_pad:
            # inert pad lanes: β=0 ⇒ p_inf=0 ⇒ never newly-informed; the
            # Threefry draw for a pad id is computed and discarded
            ids = jnp.concatenate([ids, jnp.zeros(n_pad, ids.dtype)])
            informed = jnp.concatenate([informed, jnp.zeros(n_pad, jnp.bool_)])
            t_inf = jnp.concatenate([t_inf, jnp.zeros(n_pad, t_inf.dtype)])
            counts = jnp.concatenate([counts, jnp.zeros(n_pad, counts.dtype)])
            betas = jnp.concatenate([betas, jnp.zeros(n_pad, betas.dtype)])
            safe_deg = jnp.concatenate([safe_deg, jnp.ones(n_pad, safe_deg.dtype)])
        informed2, t_inf2 = call(kd, t, ids, informed, t_inf, counts, betas, safe_deg)
        return informed2[:n], t_inf2[:n]

    return run


def _belief_lax(informed, t_inf, belief, counts, awareness, safe_deg,
                thresholds, t, dt, llr0, llr1):
    """The Bayesian belief-update arithmetic — the ONE definition the lax
    and Pallas belief paths share (ISSUE 15).

    Per agent: the naive-Bayes log-likelihood-ratio rate of the observed
    withdrawn-neighbor fraction w = counts/deg,

        llr(w) = w·llr1 + (1−w)·llr0,   llr1 = log(q_run/q_calm) > 0,
                                        llr0 = log((1−q_run)/(1−q_calm)) < 0,

    accumulates into the per-agent belief Λ (the log-odds evidence
    integral); an uninformed agent joins the run the FIRST time
    a_i·Λ_i(t) crosses its private threshold θ_i. Crossing is absorbing
    (informed stays informed — the framework's infection semantics), so
    the first-crossing rule equals thresholding the running max of a·Λ,
    which is what the mean-field curve integrates
    (`infomodels.meanfield`)."""
    dtype = belief.dtype
    w = counts.astype(dtype) / safe_deg
    belief2 = belief + dt * (w * llr1 + (1.0 - w) * llr0)
    newly = (~informed) & (awareness * belief2 >= thresholds)
    informed2 = informed | newly
    t_inf2 = jnp.where(newly, t + dt, t_inf)
    return informed2, t_inf2, belief2


BELIEF_MODES = ("auto", "lax", "pallas", "interpret")


def resolve_belief_mode(mode: str, dtype) -> str:
    """Concrete lowering for the belief kernel. Unlike `resolve_mode`
    there is no RNG stream to respect (the Bayesian update is
    deterministic given the per-agent threshold draws), so "unfused"
    requests and foldin streams simply run the lax form — same
    arithmetic, one fewer name. f64 pallas degrades to lax exactly like
    the infection kernel (no uint64 in compiled TPU Pallas is moot here,
    but the f64 exp/compare path is untested on hardware — keep the
    conservative rule the infection kernel established)."""
    if mode == "unfused":
        mode = "lax"
    if mode not in BELIEF_MODES:
        raise ValueError(f"belief mode must be one of {BELIEF_MODES}, got {mode!r}")
    if mode == "auto":
        env = os.environ.get("SBR_FUSED", "").strip().lower()
        if env == "unfused":
            env = "lax"
        if env and env not in BELIEF_MODES:
            raise ValueError(
                f"SBR_FUSED must be one of {BELIEF_MODES} for the belief "
                f"kernel, got {env!r}"
            )
        mode = env if env and env != "auto" else "auto"
    if mode == "auto":
        mode = "pallas" if jax.default_backend() in ("tpu", "gpu") else "lax"
    if mode == "pallas" and np.dtype(dtype) == np.float64:
        return "lax"
    return mode


@functools.lru_cache(maxsize=None)
def _pallas_belief(n: int, dtype_name: str, dt: float, interpret: bool):
    """Pallas belief-update kernel for a fixed (N, dtype, dt): each
    1024-agent block loads (informed, t_inf, belief, counts, awareness,
    deg, θ) once, runs the llr accumulation and threshold crossing in
    VMEM, and writes (informed', t_inf', belief') — no materialized
    fraction/llr intermediates. Mirrors `_pallas_update`'s structure so
    the block/pad discipline stays in one idiom."""
    from jax.experimental import pallas as pl

    dtype = jnp.dtype(dtype_name)
    n_pad = (-n) % _BLOCK
    n_b = (n + n_pad) // _BLOCK

    def kernel(sc_ref, informed_ref, tinf_ref, belief_ref, counts_ref,
               aw_ref, deg_ref, thr_ref, inf2_ref, tinf2_ref, bel2_ref):
        informed2, t_inf2, belief2 = _belief_lax(
            informed_ref[...], tinf_ref[...], belief_ref[...], counts_ref[...],
            aw_ref[...], deg_ref[...], thr_ref[...],
            sc_ref[0], dt, sc_ref[1], sc_ref[2],
        )
        inf2_ref[...] = informed2
        tinf2_ref[...] = t_inf2
        bel2_ref[...] = belief2

    block = pl.BlockSpec((_BLOCK,), lambda i: (i,))
    scalar3 = pl.BlockSpec((3,), lambda i: (0,))
    call = pl.pallas_call(
        kernel,
        grid=(n_b,),
        in_specs=[scalar3] + [block] * 7,
        out_specs=[block, block, block],
        out_shape=[
            jax.ShapeDtypeStruct((n + n_pad,), jnp.bool_),
            jax.ShapeDtypeStruct((n + n_pad,), dtype),
            jax.ShapeDtypeStruct((n + n_pad,), dtype),
        ],
        interpret=interpret,
    )

    def run(sc, informed, t_inf, belief, counts, awareness, safe_deg, thr):
        if n_pad:
            # inert pad lanes: awareness 0 and threshold +inf ⇒ never cross
            informed = jnp.concatenate([informed, jnp.zeros(n_pad, jnp.bool_)])
            t_inf = jnp.concatenate([t_inf, jnp.zeros(n_pad, t_inf.dtype)])
            belief = jnp.concatenate([belief, jnp.zeros(n_pad, belief.dtype)])
            counts = jnp.concatenate([counts, jnp.zeros(n_pad, counts.dtype)])
            awareness = jnp.concatenate([awareness, jnp.zeros(n_pad, awareness.dtype)])
            safe_deg = jnp.concatenate([safe_deg, jnp.ones(n_pad, safe_deg.dtype)])
            thr = jnp.concatenate([thr, jnp.full(n_pad, jnp.inf, thr.dtype)])
        informed2, t_inf2, belief2 = call(
            sc, informed, t_inf, belief, counts, awareness, safe_deg, thr
        )
        return informed2[:n], t_inf2[:n], belief2[:n]

    return run


def belief_update(informed, t_inf, belief, counts, awareness, safe_deg,
                  thresholds, t, dt, llr0, llr1, mode: str):
    """One fused Bayesian observation step (ISSUE 15) — the belief-channel
    analogue of `infection_update`. Pure function of (state, counts,
    per-agent awareness/thresholds, llr constants); lax and Pallas
    lowerings share `_belief_lax`. Unlike the infection kernel — whose
    draw is integer Threefry, so every lowering is bit-identical — the
    belief accumulator is FLOAT arithmetic, and the interpreter's
    per-block programs may fuse the llr chain differently from the
    full-array XLA program (FMA/reassociation): lowerings agree to ≤1 ulp
    on beliefs (tested), and crossing decisions agree except at exact
    ulp-boundary thresholds (measure-zero under the logistic threshold
    draw). Deterministic runs pin ONE lowering via ``config.fused``.
    Returns (informed', t_inf', belief')."""
    dtype = belief.dtype
    mode = resolve_belief_mode(mode, dtype)
    if mode == "lax":
        return _belief_lax(
            informed, t_inf, belief, counts, awareness, safe_deg, thresholds,
            t, dt, llr0, llr1,
        )
    run = _pallas_belief(
        int(informed.shape[0]), jnp.dtype(dtype).name, float(dt),
        interpret=(mode == "interpret"),
    )
    sc = jnp.stack([
        jnp.asarray(t, dtype), jnp.asarray(llr0, dtype), jnp.asarray(llr1, dtype)
    ])
    return run(sc, informed, t_inf, belief, counts, awareness, safe_deg, thresholds)


def infection_update(informed, t_inf, counts, betas, safe_deg, key, step_k,
                     ids, t, dt, rng_stream: str, mode: str):
    """One fused infection step for every engine's per-agent tail.

    Pure function of (state, counts, key, step, global ids) — all the
    engine/sharding bit-identity contracts carry over unchanged because
    the draw stays keyed by global agent id (`rng._agent_uniforms`'s
    invariance). Returns (informed', t_inf').
    """
    dtype = betas.dtype
    mode = resolve_mode(mode, dtype, rng_stream)
    if mode == "unfused":
        draws = _agent_uniforms(key, step_k, ids, dtype, rng_stream)
        return _update_lax(informed, t_inf, counts, betas, safe_deg, draws, t, dt)
    step_key = jax.random.fold_in(key, step_k)
    words = _key_words(step_key)
    if words is None:
        # rbg/unsafe_rbg 4-word keys: no counter stream — the foldin
        # fallback is the unfused path (same degradation as _agent_uniforms)
        draws = _agent_uniforms(key, step_k, ids, dtype, rng_stream)
        return _update_lax(informed, t_inf, counts, betas, safe_deg, draws, t, dt)
    if mode == "lax":
        c0 = ids.astype(jnp.uint32)
        x0, x1 = _threefry2x32(words[0], words[1], c0, jnp.zeros_like(c0))
        draws = _uniform_from_bits(x0, x1, dtype)
        return _update_lax(informed, t_inf, counts, betas, safe_deg, draws, t, dt)
    run = _pallas_update(
        int(informed.shape[0]), jnp.dtype(dtype).name, float(dt),
        interpret=(mode == "interpret"),
    )
    kd = jnp.stack([words[0], words[1]])
    t_arr = jnp.reshape(jnp.asarray(t, dtype), (1,))
    return run(kd, t_arr, ids, informed, t_inf, counts, betas, safe_deg)
