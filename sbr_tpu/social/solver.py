"""Damped fixed-point equilibrium for social learning
(reference `src/extensions/social_learning/social_learning_solver.jl:63-263`).

Algorithm (faithful to the reference, SURVEY §3.5):

1. tspan is overridden to (0, η) (`social_learning_solver.jl:81`).
2. Init AW⁽⁰⁾ = baseline word-of-mouth CDF (`:90-94`).
3. Iterate: forced learning from AW⁽ⁿ⁻¹⁾ → baseline equilibrium → candidate
   AW; on inner no-run, ξ⁽ⁿ⁾ = ξ⁽ⁿ⁻¹⁾ + η/500, aborting past η (`:149-191`);
   sup-norm convergence checked on the UNDAMPED candidate (`:168-171`),
   else damp AW⁽ⁿ⁾ = ½AW⁽ⁿ⁻¹⁾ + ½AW̃⁽ⁿ⁾ (`:183-187`).

TPU-native differences (SURVEY §7.3 "fixed-point loop on device"):

- The entire iteration is ONE `lax.while_loop` inside jit — no host
  round-trips between the ~40 outer iterations.
- Every curve lives on one static uniform grid over [0, η], so the
  reference's separate 1000-pt comparison grid (`:105`) is unnecessary: the
  sup-norm is taken on the native grid (finer, so the criterion is at least
  as strict).
- The forced ODE is solved exactly (see `dynamics.py`), not adaptively.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from sbr_tpu.baseline.learning import logistic_cdf
from sbr_tpu.baseline.solver import get_aw, solve_equilibrium_core
from sbr_tpu.models.params import ModelParams, SolverConfig
from sbr_tpu.models.results import EquilibriumResult, LearningSolution
from sbr_tpu.social.dynamics import solve_forced_learning


@struct.dataclass
class SocialFixedPointResult:
    """Fixed-point output: the last inner equilibrium (what the reference
    returns, `social_learning_solver.jl:262`) plus the iteration metadata the
    reference computes but drops (`LearningResultsSocial` is defined yet
    unused — SURVEY §3.5 note)."""

    equilibrium: EquilibriumResult  # from the final inner solve
    learning: LearningSolution  # final forced-learning curves on the grid
    aw: jnp.ndarray  # (n,) final AW samples on [0, η]
    grid: jnp.ndarray  # (n,) uniform grid over [0, η]
    xi: jnp.ndarray  # final ξ iterate (incl. no-run increments)
    iterations: jnp.ndarray  # int32
    converged: jnp.ndarray  # bool — fixed-point convergence
    aborted: jnp.ndarray  # bool — ξ search exceeded η (`:155-160`)
    error: jnp.ndarray  # last undamped sup-norm error
    # Per-iteration telemetry ring (last HISTORY_LEN iterations): the
    # reference prints per-iteration error/ξ when verbose
    # (`social_learning_solver.jl:124-241`); a non-converging fixed point
    # on device is undebuggable without it (VERDICT r3 #7). NaN-filled
    # slots are iterations that never ran.
    history_err: jnp.ndarray = None  # (HISTORY_LEN,)
    history_xi: jnp.ndarray = None  # (HISTORY_LEN,)
    # Numerical health (sbr_tpu.diag): the final inner equilibrium's health
    # merged with the fixed-point level — residual = final undamped sup-norm
    # error, iterations = outer steps, FP_* flags for non-convergence/abort,
    # NAN_OUTPUT if the converged AW curve itself carries non-finite values.
    health: "jnp.ndarray" = None
    solve_time: float = 0.0  # pytree leaf; see EquilibriumResult.solve_time

    def history(self):
        """(err, ξ) per iteration in chronological order, trimmed to the
        iterations that actually ran (host-side helper)."""
        import numpy as np

        n = int(self.iterations)
        ln = self.history_err.shape[-1]
        err = np.asarray(self.history_err)
        xi = np.asarray(self.history_xi)
        if n <= ln:
            return err[:n], xi[:n]
        k = n % ln
        return np.concatenate([err[k:], err[:k]]), np.concatenate([xi[k:], xi[:k]])

    def curves_on(self, t):
        """(G, AW) interpolated onto host times ``t`` (host-side helper —
        the mean-field curves every agent-level comparison measures
        against; shared by `closure.close_loop` and the bench's mega-scale
        agents workload so the two interpolate identically)."""
        import numpy as np

        t = np.asarray(t, dtype=np.float64)
        grid = np.asarray(self.grid, dtype=np.float64)
        g = np.interp(t, grid, np.asarray(self.learning.cdf, dtype=np.float64))
        aw = np.interp(t, grid, np.asarray(self.aw, dtype=np.float64))
        return g, aw

    def __repr__(self) -> str:
        from sbr_tpu.models.results import _fmt

        return (
            f"SocialFixedPointResult(ξ={_fmt(self.xi)}, "
            f"iterations={_fmt(self.iterations)}, converged={_fmt(self.converged)}, "
            f"error={_fmt(self.error, 3)}, aborted={_fmt(self.aborted)}, "
            f"bankrun={_fmt(self.equilibrium.bankrun)}, "
            f"solve_time={_fmt(self.solve_time, 3)}s)"
        )


HISTORY_LEN = 64


@struct.dataclass
class _LoopState:
    aw: jnp.ndarray
    xi: jnp.ndarray
    it: jnp.ndarray
    converged: jnp.ndarray
    aborted: jnp.ndarray
    err: jnp.ndarray
    hist_err: jnp.ndarray  # (HISTORY_LEN,) telemetry ring
    hist_xi: jnp.ndarray
    res: EquilibriumResult
    ls: LearningSolution
    # Anderson(1) secant memory (numerics="adaptive", ISSUE 9): the previous
    # iterate and its fixed-point residual. Carried as zeros (and never
    # read) under numerics="fixed", so the fixed path's values are
    # untouched.
    prev_aw: jnp.ndarray = None
    prev_r: jnp.ndarray = None


@functools.lru_cache(maxsize=None)
def _build_fixed_point(
    config: SolverConfig, tol: float, max_iter: int, damping: float, verbose: bool = False
):
    """Jitted fixed-point program, cached per static numerics config.

    ``verbose`` streams one line per iteration from INSIDE the on-device
    while_loop via `jax.debug.print` — the reference's verbose threading
    (`social_learning_solver.jl:124-241`) without leaving the device."""

    @jax.jit
    def run(beta, x0, u, p, kappa, lam, eta, grid):
        # Trace-time retrace accounting (obs.prof): one count per jit cache
        # miss of the fixed-point program (e.g. a churning grid dtype).
        from sbr_tpu.obs import prof

        prof.note_trace("social.fixed_point")
        dtype = grid.dtype
        tol_ = jnp.asarray(tol, dtype=dtype)
        alpha = jnp.asarray(damping, dtype=dtype)

        def step(aw, xi_prev):
            ls = solve_forced_learning(beta, aw, grid, x0)
            res = solve_equilibrium_core(ls, u, p, kappa, lam, eta, eta, config)
            # inner no-run: increment ξ by η/500 (`social_learning_solver.jl:155`)
            xi_new = jnp.where(res.bankrun, res.xi, xi_prev + eta / 500.0)
            exceeded = jnp.logical_and(~res.bankrun, xi_new > eta)
            aw_new, _, _ = get_aw(xi_new, res.tau_bar_in_unc, res.tau_bar_out_unc, grid, ls)
            return ls, res, xi_new, exceeded, aw_new

        def cond(s: _LoopState):
            return (s.it < max_iter) & (~s.converged) & (~s.aborted)

        adaptive = config.adaptive

        def body(s: _LoopState):
            ls, res, xi_new, exceeded, aw_new = step(s.aw, s.xi)
            err = jnp.max(jnp.abs(aw_new - s.aw))
            conv = jnp.logical_and(err < tol_, ~exceeded)
            if adaptive:
                # Anderson(1)/secant acceleration on the damping update
                # (ISSUE 9): extrapolate along the last two fixed-point
                # residuals. Gated to the NEAR-CONVERGED regime (previous
                # undamped residual under 10·tol): far from the fixed point
                # the map is only piecewise smooth (inner status flips, the
                # no-run ξ-march), and secant extrapolation there measurably
                # wanders — it landed 2.6e-3 off the true ξ at the Figure-12
                # parameters, outside the golden envelope, while the gated
                # form polishes the damped tail in a few steps and lands
                # CLOSER to the tol→0 fixed point than plain damping does.
                # Remaining safeguards: degenerate secant denominators,
                # non-finite extrapolations, and inner no-run iterations
                # fall back to the reference's damped update; γ is clamped
                # so a near-parallel residual pair cannot fling the iterate.
                r_k = aw_new - s.aw
                dr = r_k - s.prev_r
                denom = jnp.sum(dr * dr)
                tiny = jnp.asarray(jnp.finfo(dtype).tiny, dtype)
                gamma = jnp.sum(dr * r_k) / jnp.where(denom > tiny, denom, 1.0)
                gamma = jnp.clip(gamma, -5.0, 5.0)
                accel = s.aw + alpha * r_k - gamma * (s.aw - s.prev_aw + alpha * dr)
                accel_ok = (
                    (s.it > 0)
                    & (s.err < 10.0 * tol_)
                    & (denom > tiny)
                    & jnp.all(jnp.isfinite(accel))
                    & res.bankrun
                )
                aw_step = jnp.where(accel_ok, accel, (1.0 - alpha) * s.aw + alpha * aw_new)
                prev_aw, prev_r = s.aw, r_k
            else:
                aw_step = (1.0 - alpha) * s.aw + alpha * aw_new
                prev_aw, prev_r = s.prev_aw, s.prev_r
            aw_next = jnp.where(conv, aw_new, aw_step)
            aw_next = jnp.where(exceeded, s.aw, aw_next)
            if verbose:
                jax.debug.print(
                    "[social fp] iter {i}: err={e:.3e} xi={x:.6f} bankrun={b}",
                    i=s.it + 1, e=err, x=xi_new, b=res.bankrun,
                )
            slot = jnp.mod(s.it, HISTORY_LEN)
            return _LoopState(
                aw=aw_next,
                xi=xi_new,
                it=s.it + 1,
                converged=conv,
                aborted=exceeded,
                err=err,
                hist_err=s.hist_err.at[slot].set(err),
                hist_xi=s.hist_xi.at[slot].set(xi_new),
                res=res,
                ls=ls,
                prev_aw=prev_aw,
                prev_r=prev_r,
            )

        aw0 = logistic_cdf(grid, beta, x0)  # word-of-mouth init (`:90-94`)
        shapes = jax.eval_shape(lambda a, x: step(a, x)[:2], aw0, jnp.zeros((), dtype))
        ls0, res0 = jax.tree_util.tree_map(lambda sh: jnp.zeros(sh.shape, sh.dtype), shapes)
        init = _LoopState(
            aw=aw0,
            xi=jnp.zeros((), dtype),
            it=jnp.zeros((), jnp.int32),
            converged=jnp.zeros((), bool),
            aborted=jnp.zeros((), bool),
            err=jnp.asarray(jnp.inf, dtype),
            hist_err=jnp.full((HISTORY_LEN,), jnp.nan, dtype),
            hist_xi=jnp.full((HISTORY_LEN,), jnp.nan, dtype),
            res=res0,
            ls=ls0,
            prev_aw=jnp.zeros_like(aw0),
            prev_r=jnp.zeros_like(aw0),
        )
        final = jax.lax.while_loop(cond, body, init)

        # Fixed-point-level health, merged with the last inner solve's
        # (which rode the while_loop carry inside final.res). max_iter
        # exhaustion is the only way out with neither converged nor aborted.
        from sbr_tpu.diag.health import FP_ABORTED, FP_NOT_CONVERGED, NAN_OUTPUT, Health

        not_conv = (~final.converged) & (~final.aborted)
        fp_flags = (
            jnp.where(not_conv, jnp.int32(FP_NOT_CONVERGED), jnp.int32(0))
            | jnp.where(final.aborted, jnp.int32(FP_ABORTED), jnp.int32(0))
            | jnp.where(
                jnp.any(~jnp.isfinite(final.aw)), jnp.int32(NAN_OUTPUT), jnp.int32(0)
            )
        )
        nan = jnp.asarray(jnp.nan, dtype)
        fp_health = Health(
            residual=final.err,
            bracket_width=nan,
            iterations=final.it,
            flags=fp_flags,
        )
        return SocialFixedPointResult(
            equilibrium=final.res,
            learning=final.ls,
            aw=final.aw,
            grid=grid,
            xi=final.xi,
            iterations=final.it,
            converged=final.converged,
            aborted=final.aborted,
            error=final.err,
            history_err=final.hist_err,
            history_xi=final.hist_xi,
            health=final.res.health.merge(fp_health),
        )

    return run


def solve_equilibrium_social(
    model: ModelParams,
    config: SolverConfig | None = None,
    tol: float = 1e-4,
    max_iter: int = 250,
    damping: float = 0.5,
    dtype=None,
    verbose: bool = False,
) -> SocialFixedPointResult:
    """Solve the social-learning equilibrium
    (`solve_equilibrium_social_learning`, `social_learning_solver.jl:63`).

    Defaults mirror the reference signature (tol=1e-4, max_iter=250, α=0.5);
    the Figure-12/13 script calls with max_iter=500
    (`scripts/4_social_learning.jl:55-56`).
    """
    if config is None:
        config = SolverConfig()
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    import time

    from sbr_tpu import obs
    from sbr_tpu.baseline.solver import _stamp_solve_time

    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))
    econ = model.economic
    eta = econ.eta
    grid = jnp.linspace(jnp.zeros((), dtype), jnp.asarray(eta, dtype), config.n_grid)
    run = _build_fixed_point(
        config, float(tol), int(max_iter), float(damping), bool(verbose)
    )
    args = (
        jnp.asarray(model.learning.beta, dtype),
        jnp.asarray(model.learning.x0, dtype),
        jnp.asarray(econ.u, dtype),
        jnp.asarray(econ.p, dtype),
        jnp.asarray(econ.kappa, dtype),
        jnp.asarray(econ.lam, dtype),
        jnp.asarray(eta, dtype),
        grid,
    )
    t0 = time.perf_counter()
    with obs.span("social.fixed_point", n_grid=config.n_grid, max_iter=int(max_iter)) as sp:
        res = obs.jit_call("social.fixed_point", run, *args)
        sp.sync(res.aw, res.xi)
    res = _stamp_solve_time(res, t0)
    _log_fixed_point(res)
    return res


def _log_fixed_point(res: SocialFixedPointResult) -> None:
    """Host-boundary telemetry for a finished fixed-point solve: iteration
    count, convergence flags, and the damping residual trace (the per-
    iteration err/ξ ring the reference prints when verbose) — computed from
    the RETURNED arrays only, per the obs jit-safety contract."""
    from sbr_tpu import obs
    from sbr_tpu.obs.metrics import metrics

    if isinstance(res.iterations, jax.core.Tracer):
        return  # traced caller (jit/vmap): no host values to log, same
        # guard as baseline.solver._stamp_solve_time
    n_iter = int(res.iterations)
    metrics().inc("social.fixed_point.solves")
    metrics().inc("social.fixed_point.iterations", n_iter)
    metrics().observe("social.fixed_point.solve_s", float(res.solve_time))
    if not obs.enabled():
        return
    err_trace, xi_trace = res.history()
    obs.event(
        "fixed_point",
        stage="social.fixed_point",
        iterations=n_iter,
        converged=bool(res.converged),
        aborted=bool(res.aborted),
        error=float(res.error),
        xi=float(res.xi),
        bankrun=bool(res.equilibrium.bankrun),
        history_err=[float(e) for e in err_trace],
        history_xi=[float(x) for x in xi_trace],
    )
    obs.log_status("social.fixed_point", res.equilibrium.status)
    obs.log_health("social.fixed_point", res.health, res.equilibrium.status)
