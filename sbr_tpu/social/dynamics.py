"""Forced learning dynamics dG/dt = (1-G)·β·AW(t)
(reference `src/extensions/social_learning/social_learning_dynamics.jl:58-78`).

The reference integrates this with an adaptive machine-eps ODE solver against
a piecewise-linear interpolant of AW. But the equation is separable: for ANY
forcing A W with cumulative integral A(t) = ∫₀ᵗ AW(s) ds,

    G(t) = 1 - (1 - x0) · exp(-β · A(t)).

For the piecewise-linear AW the reference actually feeds the solver, A(t) is
the trapezoid cumulative — exact. So Stage 1 of every fixed-point iteration
collapses to a `cumsum` + `exp`: no scan, no adaptive stepping, and the result
is the exact solution of the same forced ODE the reference approximates. The
PDF is the symbolic g = (1-G)·β·AW the reference also uses
(`social_learning_dynamics.jl:98-114`).
"""

from __future__ import annotations

import jax.numpy as jnp

from sbr_tpu.core.integrate import cumtrapz
from sbr_tpu.models.results import LearningSolution


def solve_forced_learning(beta, aw_samples, grid, x0) -> LearningSolution:
    """Exact solve of dG/dt=(1-G)·β·AW(t) for piecewise-linear AW samples.

    Args:
      beta: learning rate (sensitivity to observed withdrawals).
      aw_samples: (n,) AW(t) sampled on ``grid``.
      grid: (n,) uniform time grid.
      x0: initial informed fraction G(0).

    Returns a `LearningSolution` with sampled CDF/PDF (closed_form=False —
    downstream consumers interpolate, exactly as the reference wraps the
    forced solution in a baseline `LearningResults`,
    `social_learning_solver.jl:135-137`).
    """
    dtype = jnp.asarray(aw_samples).dtype
    beta = jnp.asarray(beta, dtype=dtype)
    x0 = jnp.asarray(x0, dtype=dtype)
    dt = grid[1] - grid[0]
    big_a = cumtrapz(aw_samples, dx=dt)
    cdf = 1.0 - (1.0 - x0) * jnp.exp(-beta * big_a)
    pdf = (1.0 - cdf) * beta * aw_samples
    return LearningSolution(
        grid=grid,
        cdf=cdf,
        pdf=pdf,
        t0=grid[0],
        dt=dt,
        beta=beta,
        x0=x0,
        closed_form=False,
    )
