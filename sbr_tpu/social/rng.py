"""Counter-RNG primitives shared by the agent kernels, the fused infection
step, and the on-device graph generators.

The per-(agent, step) and per-edge random streams are pure functions of
(key words, counter) built on one Threefry-2x32 block — stateless, so any
chunking/sharding/fusion of the consuming computation draws bit-identical
randomness. Extracted from ``social.agents`` (0.8.0) so ``social.fused``
(the Pallas infection kernel) and ``social.graphgen`` (on-device edge
generation) can share the block function without importing the 1.7-kloc
agents module; ``agents`` re-exports the old private names.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _threefry2x32(k0, k1, c0, c1):
    """One Threefry-2x32 block (Salmon et al. 2011), vectorized over the
    counter arrays — bit-exact vs `jax._src.prng.threefry_2x32` (tested).
    Re-implemented on public jnp ops so the counter RNG stream does not
    depend on a private JAX API (and so it can run INSIDE a Pallas kernel,
    where only jnp/lax ops lower)."""

    def rotl(x, r):
        return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))

    ks = (k0, k1, jnp.uint32(0x1BD11BDA) ^ k0 ^ k1)
    x0 = c0 + ks[0]
    x1 = c1 + ks[1]
    rot_a, rot_b = (13, 15, 26, 6), (17, 29, 16, 24)
    for i in range(5):
        for r in rot_a if i % 2 == 0 else rot_b:
            x0 = x0 + x1
            x1 = rotl(x1, r)
            x1 = x1 ^ x0
        j = i + 1
        x0 = x0 + ks[j % 3]
        x1 = x1 + ks[(j + 1) % 3] + jnp.uint32(j)
    return x0, x1


def _uniform_from_bits(x0, x1, dtype):
    """[0, 1) uniform from one Threefry block's words — the ONE definition
    shared by `_agent_uniforms`, the fused Pallas kernel, and the graph
    generators, so every consumer's draws are bit-identical by construction.

    f64 uses both words for the 52-bit mantissa; narrower dtypes use x0's
    high bits via the mantissa-or trick, with the half-precision clamp
    below 1.0 (ADVICE r5: the cast can round draws within ~2^-11 of 1.0 up
    to exactly 1.0, breaking the [0,1) contract)."""
    if np.dtype(dtype) == np.float64:
        hi = x0.astype(jnp.uint64) << jnp.uint64(32)
        mant = (hi | x1.astype(jnp.uint64)) >> jnp.uint64(12)
        one_to_two = jax.lax.bitcast_convert_type(
            mant | jnp.uint64(0x3FF0000000000000), jnp.float64
        )
        return one_to_two - 1.0
    mant = (x0 >> jnp.uint32(9)) | jnp.uint32(0x3F800000)
    one_to_two = jax.lax.bitcast_convert_type(mant, jnp.float32)
    u = (one_to_two - 1.0).astype(dtype)
    if jnp.finfo(dtype).bits < 32:
        u = jnp.minimum(u, jnp.asarray(1.0 - jnp.finfo(dtype).epsneg, dtype))
    return u


def _key_words(step_key):
    """The (k0, k1) uint32 words of a 2-word threefry key, or None for
    rbg/unsafe_rbg 4-word layouts (no contract that the first two words
    vary per step — the counter consumers must fall back to foldin)."""
    kd = (
        step_key
        if getattr(step_key, "dtype", None) == jnp.uint32
        else jax.random.key_data(step_key)
    )
    if kd.shape[-1] != 2:
        return None
    return kd[0], kd[1]


def _agent_uniforms(key, step_k, ids, dtype, impl: str = "counter"):
    """Per-agent uniform draw as a pure function of (key, step, GLOBAL agent id).

    Keying the stream by global agent id — not by device or array position —
    makes the simulation invariant to sharding: a single-device run and an
    n-device run draw bit-identical randomness per agent, so the two paths
    are exactly equivalent (tested), not merely statistically close.

    Two streams, both with that invariance (`AgentSimConfig.rng_stream`;
    the default here matches the config default):

    - "counter" (default since 0.7.0): one Threefry block per agent — the
      per-step key pair hashes the id directly as the block counter, and
      the uniform is built from the block's first word (both words for
      f64's 52-bit mantissa).
    - "foldin": uniform(fold_in(fold_in(key, step), id)) — two full
      Threefry blocks per agent per step plus the vmapped key
      construction (~16x the CPU cost); the stream every pre-0.7
      committed measurement used.

    A run is comparable across engines/shardings/platforms under either
    stream, but the streams are different (equally valid) realizations.

    The counter path requires the 2-word threefry key layout (ADVICE r5):
    under jax_default_prng_impl=rbg/unsafe_rbg key data is 4 uint32 words
    with no contract that the first two vary per step, which would silently
    degrade the stream to half the key material. A non-2-word layout falls
    back to the foldin path, which is layout-agnostic by construction.
    """
    step_key = jax.random.fold_in(key, step_k)
    words = _key_words(step_key) if impl == "counter" else None
    if words is not None:
        c0 = ids.astype(jnp.uint32)
        x0, x1 = _threefry2x32(words[0], words[1], c0, jnp.zeros_like(c0))
        return _uniform_from_bits(x0, x1, dtype)
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(step_key, ids)
    return jax.vmap(lambda k: jax.random.uniform(k, (), dtype=dtype))(keys)
