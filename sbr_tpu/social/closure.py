"""Closing the equilibrium→agent loop (VERDICT r2 task 2).

The explicit-agent simulation (`agents.py`) exposes the withdrawal window
(exit_delay, reentry_delay) that the equilibrium strategy implies — from
`get_AW` (`src/baseline/solver.jl:495-532`), an agent informed at time s is
withdrawn at t iff s + ξ − τ̄_OUT^CON ≤ t < s + ξ − τ̄_IN^CON — but nothing in
rounds 1-2 ever FED a solved equilibrium's window back into the simulation.
This module does exactly that:

1. solve the social-learning fixed point
   (`src/extensions/social_learning/social_learning_solver.jl:63-263`) at the
   Figure-12 calibration;
2. derive exit_delay = ξ − τ̄_OUT^CON and reentry_delay = ξ − τ̄_IN^CON from
   the returned equilibrium;
3. simulate N explicit agents on a dense random graph with that window;
4. compare the agent-level withdrawn/informed fractions against the fixed
   point's AW(t) and G(t) curves.

In the dense-graph limit each agent's observed withdrawn-neighbor fraction
concentrates on the population AW(t), so the simulation IS the fixed-point
dynamics plus Monte-Carlo noise: the sup/RMS errors shrink as N grows
(tested in `tests/test_social.py`). This validates the *withdrawal* physics
of the agent extension against the equilibrium — not just the learning
physics (logistic limit) that the earlier oracle covered.

Known O(dt) biases (common to all N, so they do not affect the
convergence-in-N assertion): informed times are rounded up to step ends,
and the forcing is frozen over each step.

Known O(x0) biases (ADVICE r3 — listed so a future tighter-tolerance test
does not chase them as bugs): the fixed point's AW from the reference's
`get_AW` carries a permanent +G(0)=x0 "initial withdrawals" offset, while
simulated founders re-enter after their window closes; and mid-start seed
quantiles below G(0)=x0 clamp to t=0 in the inverse-CDF placement. Both
are ~1e-4 at the default x0 and are absorbed by the 0.03 test tolerances.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from sbr_tpu.models.params import ModelParams, SolverConfig, make_model_params
from sbr_tpu.social.agents import AgentSimConfig, erdos_renyi_edges, simulate_agents
from sbr_tpu.social.solver import SocialFixedPointResult, solve_equilibrium_social


def equilibrium_window(eq) -> tuple:
    """(exit_delay, reentry_delay) implied by an equilibrium's strategy.

    The constrained buffers τ̄^CON = min(τ̄^UNC, ξ) are exactly the ones
    `get_AW` shifts the CDF by (`src/baseline/solver.jl:495-532`): an agent
    informed at s withdraws during [s + ξ − τ̄_OUT^CON, s + ξ − τ̄_IN^CON).
    """
    xi = float(eq.xi)
    if not np.isfinite(xi):
        raise ValueError("equilibrium has no bank run (xi is NaN) — no window to derive")
    tau_in_con = min(float(eq.tau_bar_in_unc), xi)
    tau_out_con = min(float(eq.tau_bar_out_unc), xi)
    return xi - tau_out_con, xi - tau_in_con


@dataclasses.dataclass(frozen=True)
class LoopComparison:
    """Fixed point vs agent simulation on the shared time grid ``t``."""

    fp: SocialFixedPointResult
    exit_delay: float
    reentry_delay: float
    t: np.ndarray  # (n_steps,) simulation grid
    aw_fp: np.ndarray  # fixed-point AW(t) on t
    aw_sim: np.ndarray  # mean agent withdrawn_frac on t
    g_fp: np.ndarray  # fixed-point forced-learning G(t) on t
    g_sim: np.ndarray  # mean agent informed_frac on t
    n_agents: int
    n_reps: int
    err_aw_sup: float
    err_aw_rms: float
    err_g_rms: float
    # Per-member AW trajectories, (n_reps, n_steps) — populated when the
    # ``seeds`` axis is used (ISSUE 15): the raw material of population-
    # level ξ-distribution queries (`infomodels.population`).
    aw_seeds: Optional[np.ndarray] = None
    # The information model the members ran under (None = legacy gossip).
    infomodel: Optional[object] = None


def close_loop(
    model: Optional[ModelParams] = None,
    n_agents: int = 100_000,
    avg_degree: float = 20.0,
    dt: float = 0.1,
    t_max: Optional[float] = None,
    g0: Optional[float] = 0.02,
    n_reps: int = 1,
    seed: int = 0,
    config: SolverConfig | None = None,
    tol: float = 1e-4,
    max_iter: int = 500,
    mesh=None,
    fp: Optional[SocialFixedPointResult] = None,
    graph=None,
    infomodel=None,
    seeds: Optional[Sequence[int]] = None,
    tolerance: Optional[float] = None,
) -> LoopComparison:
    """Solve the fixed point, feed its window to the agent sim, compare.

    Defaults: Figure-12 calibration (β=0.9, η̄=30, u=0.5, p=0.99, κ=0.25,
    λ=0.25, `scripts/4_social_learning.jl:36-43`), Erdős–Rényi graph dense
    enough for the mean-field limit.

    ``graph`` selects the graph source: None (default) samples a host
    Erdős–Rényi graph per rep (`erdos_renyi_edges` — the pre-0.8 path); a
    `sbr_tpu.social.graphgen` spec (ErdosRenyiSpec / ScaleFreeSpec /
    StochasticBlockSpec with ``spec.n == n_agents``) generates the graph
    ON DEVICE per rep (`prepare_generated_graph`) — no edge data transits
    the host, which is what lets the loop close at 10^7-agent scale; the
    device ER stream is a different (equally valid) realization of the
    same model, so host-vs-device comparisons agree statistically, not
    bitwise. Non-ER specs compare network scenarios against the same
    mean-field curves (the dense-limit concentration argument needs high
    average degree to hold tightly).

    ``g0`` selects a MID-TRAJECTORY start: the simulation begins at the time
    t0 where the fixed point's G reaches g0, with round(g0·N) agents seeded
    at the exact stratified quantiles of G restricted to [0, t0] (so their
    re-entry times are distributed as the mean-field state prescribes).
    Rationale: at the Figure-12 calibration x0 = 1e-4, so a from-scratch
    population carries only x0·N founding seeds and the early branching
    noise is a time-shift of the whole trajectory that decays only as
    1/√(x0·N) — at mid-start the effective seed count is g0·N and the
    comparison converges at test-scale N. ``g0=None`` runs from scratch
    (founders at t=0), retaining the founding-seed noise as part of what the
    run shows. ``n_reps`` independent populations are averaged.

    ``fp`` supplies a precomputed fixed point (skipping the solve — the most
    expensive step); it must come from the same ``model``.

    ``infomodel`` (ISSUE 15): an `infomodels.InfoModelSpec` — the loop
    then closes THAT model against ITS mean-field fixed point
    (`infomodels.meanfield.solve_fixed_point_info`): gossip-reducible
    specs run exactly the legacy path; bayes specs compare the
    belief-threshold population against the closed-form observer curves
    (mid-start initializes the informed set as the threshold-ordered
    prefix the mean-field mass prescribes, plus the shared evidence
    level Λ(t0) — uniform seeding would double-count the panic-prone
    tail); rewire specs compare against the attention-tilted curves.
    Requires a graphgen ``graph`` spec (defaulted to Erdős–Rényi at
    (n_agents, avg_degree)); ``mesh`` is rejected (the info engines are
    single-device).

    ``seeds`` (ISSUE 15 satellite): an explicit sequence of member seeds
    replacing the ``n_reps`` ladder — the GRAPH IS PREPARED ONCE (at
    ``seed``) and reused for every member, so an S-member population
    sweep pays one canonicalization/H2D instead of S (static dynamics;
    rewiring regenerates per epoch by design). Per-member randomness
    (initial seeds, RNG streams, bayes thresholds) still varies by
    member seed via the counter RNG. The per-member AW trajectories land
    on ``LoopComparison.aw_seeds`` — the raw material of population
    ξ-distribution queries.

    ``tolerance``: optional — recorded on the obs ``closure`` event so
    `report infomodel` can gate err_aw_sup against it; no behavior
    change.
    """
    if config is None:
        config = SolverConfig()
    if model is None:
        model = make_model_params(
            beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25
        )
    if infomodel is not None:
        if mesh is not None:
            raise ValueError(
                "infomodel= runs the single-device info engines; mesh= is "
                "not supported (shard the gossip path via graph=/mesh= "
                "without an infomodel)"
            )
        if graph is None:
            from sbr_tpu.social.graphgen import ErdosRenyiSpec

            graph = ErdosRenyiSpec(n=n_agents, avg_degree=avg_degree)
        if fp is None:
            from sbr_tpu.infomodels.meanfield import solve_fixed_point_info

            fp = solve_fixed_point_info(
                infomodel, model, config=config, tol=tol, max_iter=max_iter
            )
    elif fp is None:
        fp = solve_equilibrium_social(model, config=config, tol=tol, max_iter=max_iter)
    exit_delay, reentry_delay = equilibrium_window(fp.equilibrium)

    grid = np.asarray(fp.grid, dtype=np.float64)
    g_curve = np.asarray(fp.learning.cdf, dtype=np.float64)
    eta = float(model.economic.eta)
    beta = float(model.learning.beta)
    x0 = float(model.learning.x0)

    bayes = infomodel is not None and infomodel.channel == "bayes"
    t0 = 0.0
    informed0 = t_inf0 = None
    m_curve = None
    if g0 is not None:
        if not (x0 < g0 < float(g_curve[-1])):
            raise ValueError(f"g0={g0} outside the fixed point's G range")
        # G is monotone: invert by interpolation for t0 and the seed times.
        t0 = float(np.interp(g0, g_curve, grid))
        k = max(1, int(round(g0 * n_agents)))
        quantiles = (np.arange(k) + 0.5) * (g0 / k)
        s = np.interp(quantiles, g_curve, grid)  # informed times in [0, t0]
        if bayes:
            # Bayes mid-start is NOT exchangeable: the informed set at t0
            # is exactly the agents whose private threshold the shared
            # evidence level has crossed — the threshold-ordered prefix —
            # so seeding uniformly chosen agents would double-count the
            # panic-prone tail (the unseeded low-θ agents would all cross
            # again at t0+). Build the mean-field evidence curve
            # M(t) = cummax ∫ llr(w_obs(AW)) once; each member below
            # seeds {i: a_i·M(t0) ≥ θ_i} with crossing times M⁻¹(θ_i/a_i)
            # and starts every belief at the shared level Λ(t0).
            from sbr_tpu.infomodels.meanfield import observed_fraction

            llr0_c, llr1_c = infomodel.llr
            w_obs = np.asarray(
                observed_fraction(np.asarray(fp.aw, np.float64), infomodel)
            )
            llr_curve = w_obs * llr1_c + (1.0 - w_obs) * llr0_c
            dt_grid = float(grid[1] - grid[0])
            lam_curve = np.concatenate(
                [[0.0], np.cumsum((llr_curve[1:] + llr_curve[:-1]) * 0.5 * dt_grid)]
            )
            m_curve = np.maximum.accumulate(lam_curve)

    t_end = eta if t_max is None else float(t_max)
    n_steps = max(int(round((t_end - t0) / dt)), 2)
    sim_cfg = AgentSimConfig(
        n_steps=n_steps, dt=dt, exit_delay=exit_delay, reentry_delay=reentry_delay
    )

    if graph is not None and graph.n != n_agents:
        raise ValueError(
            f"graph spec n={graph.n} does not match n_agents={n_agents}"
        )

    member_seeds = (
        [int(sd) for sd in seeds]
        if seeds is not None
        else [seed + 1000 * rep for rep in range(n_reps)]
    )
    if not member_seeds:
        raise ValueError("seeds must be non-empty")
    n_reps = len(member_seeds)

    # Seeds axis (ISSUE 15 satellite): the graph-side work happens ONCE
    # (prepared at the base ``seed``) and every member reuses the device
    # arrays; only per-member state (initial seeds, RNG streams, bayes
    # thresholds) varies. Rewiring specs regenerate per epoch by design —
    # there is nothing to share.
    shared_pg = None
    if seeds is not None and (infomodel is None or infomodel.dynamics == "static"):
        if infomodel is not None:
            from sbr_tpu.infomodels.engine import _agent_fields
            from sbr_tpu.social.graphgen import prepare_generated_graph

            if infomodel.channel == "gossip":
                betas_arg = (
                    np.asarray(
                        _agent_fields(infomodel, n_agents, seed, beta, np.float32)[0]
                    )
                    if infomodel.groups
                    else beta
                )
                shared_pg = prepare_generated_graph(
                    graph, seed=seed, betas=betas_arg, config=sim_cfg
                )
            else:
                shared_pg = prepare_generated_graph(
                    graph, seed=seed, betas=1.0, config=sim_cfg, engine="gather"
                )
        elif graph is not None:
            from sbr_tpu.social.graphgen import prepare_generated_graph

            shared_pg = prepare_generated_graph(
                graph, seed=seed, betas=beta, config=sim_cfg, mesh=mesh
            )
        else:
            from sbr_tpu.social.agents import prepare_agent_graph

            src, dst = erdos_renyi_edges(n_agents, avg_degree, seed=seed)
            shared_pg = prepare_agent_graph(
                beta, src, dst, n_agents, config=sim_cfg, mesh=mesh
            )

    aw_acc = g_acc = None
    aw_rows = [] if seeds is not None else None
    t = None
    for rep_seed in member_seeds:
        belief0 = None
        if g0 is not None and not bayes:
            rng = np.random.default_rng(rep_seed + 17)
            informed0 = np.zeros(n_agents, dtype=bool)
            chosen = rng.choice(n_agents, size=len(s), replace=False)
            informed0[chosen] = True
            t_inf0 = np.zeros(n_agents)
            t_inf0[chosen] = s - t0  # sim clock starts at t0: seeds are ≤ 0
        if infomodel is not None:
            from sbr_tpu.infomodels.engine import _agent_fields, simulate_info

            if g0 is not None and bayes:
                _, thr_d, aware_d = _agent_fields(
                    infomodel, n_agents, rep_seed, beta, np.float32
                )
                ratio = np.asarray(thr_d, np.float64) / np.asarray(aware_d, np.float64)
                m0 = float(np.interp(t0, np.asarray(grid), m_curve))
                informed0 = ratio <= m0
                t_inf0 = np.zeros(n_agents)
                # crossing times M⁻¹(θ/a) on [0, t0], sim clock at t0
                t_inf0[informed0] = (
                    np.interp(ratio[informed0], m_curve, np.asarray(grid)) - t0
                )
                belief0 = m0
            sim = simulate_info(
                infomodel, graph, beta=beta, x0=x0, config=sim_cfg,
                seed=rep_seed, exact_seeds=True, informed0=informed0,
                t_inf0=t_inf0, prepared=shared_pg, belief0=belief0,
            )
        elif graph is not None:
            from sbr_tpu.social.graphgen import prepare_generated_graph

            pg = (
                shared_pg
                if shared_pg is not None
                else prepare_generated_graph(
                    graph, seed=rep_seed, betas=beta, config=sim_cfg, mesh=mesh
                )
            )
            sim = simulate_agents(
                prepared=pg,
                x0=x0,
                config=sim_cfg,
                seed=rep_seed,
                exact_seeds=True,
                informed0=informed0,
                t_inf0=t_inf0,
            )
        elif shared_pg is not None:
            sim = simulate_agents(
                prepared=shared_pg,
                x0=x0,
                config=sim_cfg,
                seed=rep_seed,
                exact_seeds=True,
                informed0=informed0,
                t_inf0=t_inf0,
            )
        else:
            src, dst = erdos_renyi_edges(n_agents, avg_degree, seed=rep_seed)
            sim = simulate_agents(
                beta,
                src,
                dst,
                n_agents,
                x0=x0,
                config=sim_cfg,
                seed=rep_seed,
                mesh=mesh,
                exact_seeds=True,
                informed0=informed0,
                t_inf0=t_inf0,
            )
        aw = np.asarray(sim.withdrawn_frac, dtype=np.float64)
        g = np.asarray(sim.informed_frac, dtype=np.float64)
        if aw_rows is not None:
            aw_rows.append(aw)
        aw_acc = aw if aw_acc is None else aw_acc + aw
        g_acc = g if g_acc is None else g_acc + g
        if t is None:
            t = t0 + np.asarray(sim.t_grid, dtype=np.float64)
    aw_sim = aw_acc / n_reps
    g_sim = g_acc / n_reps

    g_fp, aw_fp = fp.curves_on(t)

    d = aw_sim - aw_fp
    dg = g_sim - g_fp
    comp = LoopComparison(
        fp=fp,
        exit_delay=exit_delay,
        reentry_delay=reentry_delay,
        t=t,
        aw_fp=aw_fp,
        aw_sim=aw_sim,
        g_fp=g_fp,
        g_sim=g_sim,
        n_agents=n_agents,
        n_reps=n_reps,
        err_aw_sup=float(np.max(np.abs(d))),
        err_aw_rms=float(np.sqrt(np.mean(d**2))),
        err_g_rms=float(np.sqrt(np.mean(dg**2))),
        aw_seeds=np.stack(aw_rows) if aw_rows else None,
        infomodel=infomodel,
    )
    from sbr_tpu import obs

    # Infomodel runs ONLY: a legacy close_loop emitting these events would
    # defeat `report infomodel`'s exit-3 no-data guard — a gate pointed at
    # a run whose info battery never executed must not read green.
    if infomodel is not None and obs.enabled():
        obs.log_infomodel(
            "closure",
            channel=infomodel.channel,
            dynamics=infomodel.dynamics,
            n_agents=n_agents,
            n_reps=n_reps,
            err_aw_sup=comp.err_aw_sup,
            err_g_rms=comp.err_g_rms,
            tolerance=tolerance,
        )
    return comp
