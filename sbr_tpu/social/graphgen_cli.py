"""CLI for the on-device graph-generation self-check (the CI
graphgen-parity smoke).

Lives OUTSIDE `graphgen` itself (which `sbr_tpu.social.__init__` imports):
`python -m` on a package-imported module executes a second ``__main__``
copy of it — duplicate spec classes that break `isinstance` dispatch and
duplicate lru-cached program builders, behind a RuntimeWarning. This
module is not imported by the package, so `-m` runs exactly one copy of
everything.
"""

from __future__ import annotations


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.social.graphgen_cli",
        description="On-device graph generation self-check (bitwise parity "
        "vs the host canonical layout; CI smoke)",
    )
    parser.add_argument("--selfcheck", action="store_true")
    parser.add_argument("--n", type=int, default=600)
    parser.add_argument("--deg", type=float, default=6.0)
    parser.add_argument("--seed", type=int, default=3)
    parser.add_argument("--obs-dir", default=None,
                        help="record the verdict as an audit probe event "
                        "under this obs run root")
    args = parser.parse_args(argv)
    if not args.selfcheck:
        parser.print_help()
        return 2
    from sbr_tpu.obs import audit
    from sbr_tpu.social.graphgen import _selfcheck

    # Legacy entrypoint, audit protocol (ISSUE 17): the selfcheck's nonzero
    # return becomes a drift verdict + exit 1; output is unchanged.
    return audit.run_legacy_cli(
        "graphgen.layout",
        lambda: _selfcheck(args.n, args.deg, args.seed),
        obs_dir=args.obs_dir,
    )


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
