"""Gradient-based worst-case stress search (ISSUE 13).

Replaces brute-force grid scans for "what is the smallest shock that tips
this bank into a run?" with first-order search on a differentiable
run-margin:

    margin(θ) = max( u − max_τ̄ h(τ̄; θ),          # a crossing must exist
                     κ − [G(τ̄_OUT) − G(τ̄_IN)] )   # AW must be able to reach κ

Both binding constraints of a bank-run equilibrium are smooth in θ away
from ties, and ``margin < 0`` is (to grid resolution) the run region: the
first term flips when the hazard peak clears the outside option u, the
second when the reachable withdrawal mass clears the solvency threshold κ.
On the no-crossing side the buffers coincide at the default, the G-term
collapses to κ > 0, and the max is governed by the crossing term alone —
so the surrogate is consistent across the regime boundary.

`stress_search` runs projected gradient DESCENT on the margin inside a box
(only the ``wrt`` parameters move, clipped to ``bounds`` each step — the
projection), then bisects along the straight segment from θ₀ to the first
flipped iterate for the margin-zero boundary: the returned shock is the
smallest parameter perturbation ALONG THE DISCOVERED DIRECTION that flips
the cell, refined far below any grid scan's resolution. For a 1-D search
(e.g. ``wrt=("kappa",)``) this is exactly the minimal κ shock. The result
is validated against the REAL forward solver (`solve_param_cell` status at
the flipped point), never against the surrogate alone.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sbr_tpu.baseline.learning import logistic_cdf, solve_learning
from sbr_tpu.baseline.solver import _hazard_parts
from sbr_tpu.core.rootfind import first_upcrossing, last_downcrossing
from sbr_tpu.grad.cell import BASE_KEYS
from sbr_tpu.models.params import ModelParams, SolverConfig, params_to_pytree
from sbr_tpu.obs import prof

# Default search boxes (natural parameter space).
DEFAULT_BOUNDS: Dict[str, Tuple[float, float]] = {
    "beta": (1e-3, 1e4),
    "u": (1e-6, 10.0),
    "kappa": (1e-4, 1.0 - 1e-4),
    "p": (1e-4, 1.0 - 1e-4),
    "lam": (1e-6, 10.0),
}


@dataclasses.dataclass(frozen=True)
class StressResult:
    """Outcome of one worst-case search (host-side)."""

    flipped: bool  # a run-triggering perturbation was found
    validated: bool  # the real solver confirms RUN at the flipped point
    params0: dict  # starting θ (natural space, floats)
    params_flipped: Optional[dict]  # boundary-refined flipped θ (or None)
    delta: Optional[dict]  # params_flipped − params0 per wrt dim
    shock_norm: Optional[float]  # L2 norm of delta (the shock size)
    margin0: float  # starting margin (> 0: no run)
    margin_final: float  # margin at the returned point
    steps: int  # gradient steps taken until the first flip (or budget)


def run_margin(theta: dict, config: SolverConfig, dtype):
    """The differentiable run-margin (module docstring); negative ⇒ the
    cell supports a bank-run equilibrium, up to grid resolution."""
    from sbr_tpu.sweeps.baseline_sweeps import _TracedLearning

    theta = {k: jnp.asarray(theta[k], dtype) for k in BASE_KEYS}
    ls = solve_learning(
        _TracedLearning(theta["beta"], (theta["t0"], theta["t1"]), theta["x0"]),
        config, dtype=dtype,
    )
    tau_grid, hr, _, _ = _hazard_parts(
        theta["p"], theta["lam"], ls, theta["eta"], config
    )
    # Hazard peak vs the outside option. hr[0] can be +inf only at p=1
    # (the plotting-layer degenerate); exclude nothing — inf just makes
    # the crossing constraint trivially satisfied, as it should.
    m_cross = theta["u"] - jnp.max(hr)
    default = jnp.asarray(theta["t1"], dtype)
    t_in = first_upcrossing(tau_grid, hr, theta["u"], default)
    t_out = last_downcrossing(tau_grid, hr, theta["u"], default)
    reach = logistic_cdf(t_out, theta["beta"], theta["x0"]) - logistic_cdf(
        t_in, theta["beta"], theta["x0"]
    )
    m_root = theta["kappa"] - reach
    return jnp.maximum(m_cross, m_root)


@functools.lru_cache(maxsize=None)
def _margin_fns(config: SolverConfig, dtype_name: str, wrt: tuple):
    """Jitted (margin, ∂margin/∂wrt) programs, cached per (config, dtype,
    wrt) like every other grad entry point — θ values enter as ARGUMENTS,
    so repeated `stress_search` calls (a sweep of searches, the report's
    stress table) reuse two compiled programs instead of re-tracing the
    whole Stage-1+hazard pipeline per call."""
    dtype = jnp.dtype(dtype_name)

    def margin(wv, rest):
        prof.note_trace("grad.stress_margin")
        return run_margin({**rest, **wv}, config, dtype)

    return jax.jit(margin), jax.jit(jax.grad(margin, argnums=0))


def stress_search(
    params: ModelParams,
    wrt=("kappa",),
    bounds: Optional[Dict[str, Tuple[float, float]]] = None,
    steps: int = 200,
    lr: float = 0.02,
    margin_eps: float = 1e-6,
    config: Optional[SolverConfig] = None,
    dtype=None,
) -> StressResult:
    """Find the smallest shock along the steepest-descent path that flips
    ``params`` from no-run into a bank run (module docstring).

    ``lr`` is a RELATIVE step (each parameter moves by lr·|scale| per
    step, scale = max(|θ₀|, bound width)); ``margin_eps`` is how far past
    the boundary the returned point sits (a point exactly ON the boundary
    is numerically ambiguous for the forward solver).
    """
    from sbr_tpu import obs
    from sbr_tpu.grad.api import _resolve
    from sbr_tpu.sweeps.baseline_sweeps import solve_param_cell

    config, dtype = _resolve(config, dtype)
    wrt = tuple(wrt)
    unknown = set(wrt) - set(DEFAULT_BOUNDS)
    if not wrt or unknown:
        raise ValueError(
            f"wrt must be a non-empty subset of {tuple(DEFAULT_BOUNDS)}, got {wrt!r}"
        )
    box = {**DEFAULT_BOUNDS, **(bounds or {})}

    theta0 = {k: jnp.asarray(v, dtype) for k, v in params_to_pytree(params).items()
              if k != "eta_bar"}
    rest = {k: v for k, v in theta0.items() if k not in wrt}
    m_fn, g_fn = _margin_fns(config, dtype.name, wrt)
    margin_of = lambda wv: m_fn(wv, rest)
    grad_of = lambda wv: g_fn(wv, rest)

    scale = {
        k: max(abs(float(theta0[k])), (box[k][1] - box[k][0]) * 0.05)
        for k in wrt
    }
    wv = {k: theta0[k] for k in wrt}
    m0 = float(margin_of(wv))

    def clip(wv):
        return {
            k: jnp.clip(v, box[k][0], box[k][1]) for k, v in wv.items()
        }

    with obs.span("grad.stress_search", wrt=list(wrt), steps=steps):
        flipped = m0 < 0  # already a run: zero shock
        n_steps = 0
        wv_prev = dict(wv)
        if not flipped:
            for i in range(steps):
                g = grad_of(wv)
                wv_prev = dict(wv)
                wv = clip({
                    k: wv[k] - lr * scale[k] * jnp.sign(g[k]) for k in wrt
                })
                n_steps = i + 1
                m = float(margin_of(wv))
                if m < 0:
                    flipped = True
                    break
                if all(float(wv[k]) == float(wv_prev[k]) for k in wrt):
                    break  # pinned at the box: no flip reachable

        result_kwargs = dict(
            params0={k: float(theta0[k]) for k in BASE_KEYS},
            margin0=m0, steps=n_steps,
        )
        if not flipped:
            obs.event("grad", action="stress_done", flipped=False,
                      margin0=m0, margin_final=float(margin_of(wv)), steps=n_steps)
            return StressResult(
                flipped=False, validated=False, params_flipped=None, delta=None,
                shock_norm=None, margin_final=float(margin_of(wv)),
                **result_kwargs,
            )

        # Bisect the segment θ₀ → flipped iterate for the margin boundary,
        # then step margin_eps past it: the minimal shock along the path.
        lo_t, hi_t = (0.0, 0.0) if m0 < 0 else (0.0, 1.0)
        wv_flip = dict(wv)

        def at(t):
            return {
                k: theta0[k] + t * (wv_flip[k] - theta0[k]) for k in wrt
            }

        if m0 >= 0:
            for _ in range(60):
                mid = 0.5 * (lo_t + hi_t)
                if float(margin_of(at(mid))) < 0:
                    hi_t = mid
                else:
                    lo_t = mid
            # Nudge past the boundary until margin <= -margin_eps. The step
            # GROWS geometrically from the bisection residual: after 60
            # halvings hi_t - lo_t ~ 2^-60, so a constant-step walk could
            # never cover the O(margin_eps / slope) distance the contract
            # needs — doubling reaches any t <= 1 within ~60 iterations.
            t_star = hi_t
            step_t = max(hi_t - lo_t, 1e-9)
            for _ in range(60):
                if float(margin_of(at(t_star))) <= -margin_eps:
                    break
                t_star = min(1.0, t_star + step_t)
                step_t *= 2.0
                if t_star >= 1.0:
                    break
            wv_star = at(t_star)
        else:
            wv_star = {k: theta0[k] for k in wrt}

        theta_star = {**theta0, **wv_star}
        # Validate against the REAL forward solver, not the surrogate.
        xi, _, _, status, _ = solve_param_cell(
            *(theta_star[k] for k in BASE_KEYS), config, dtype
        )
        validated = int(status) == 0
        delta = {k: float(wv_star[k]) - float(theta0[k]) for k in wrt}
        shock = float(jnp.sqrt(sum(jnp.asarray(d) ** 2 for d in delta.values())))
        m_final = float(margin_of(wv_star))
        obs.event(
            "grad", action="stress_done", flipped=True, validated=bool(validated),
            margin0=m0, margin_final=m_final, steps=n_steps, shock_norm=shock,
            **{f"delta_{k}": v for k, v in delta.items()},
        )
        return StressResult(
            flipped=True, validated=bool(validated),
            params_flipped={k: float(v) for k, v in theta_star.items()},
            delta=delta, shock_norm=shock, margin_final=m_final,
            **result_kwargs,
        )
