"""Public differentiable-equilibria API (ISSUE 13).

Three entry points over `grad.cell`'s differentiable pipeline:

- `xi_and_grad(params)` — one equilibrium plus dξ/dθ for the requested
  parameters, as a `GradResult` with grad-trust flags.
- `interest_xi_and_grad(params)` — the same for the interest-rate stack
  (θ additionally spans r and δ; the HJB stage differentiates via the
  fixed-RK4 recompute rule, see grad/cell.py).
- `sensitivity_surface(beta_values, u_values, base)` — the Figure-5 grid
  with ∂ξ/∂θ surfaces next to ξ: `jax.value_and_grad` vmapped over both
  axes exactly like `sweeps.beta_u_grid` vmaps the forward cell, one
  jitted program cached per (config, dtype, wrt).

Flag semantics (bits live in `diag.health` so `flag_names` decodes them):

- ``GRAD_AT_NONEQUILIBRIUM`` — the differentiated root candidate is not a
  RUN equilibrium (status says why); dξ/dθ describes the candidate root,
  not an equilibrium. The slope check rejecting a false equilibrium lands
  here.
- ``GRAD_ILL_CONDITIONED``  — |AW'(ξ)| ≤ `SBR_GRAD_APRIME_TOL` (default
  √eps): the IFT division is blowing up, e.g. ξ at the withdrawal-curve
  peak where the equilibrium is about to vanish.
- ``GRAD_NONFINITE``        — a computed gradient is NaN/Inf.

`GRAD_UNTRUSTED_MASK` collects all three; a host-side `flag_census` feeds
the obs event stream (`report grad` renders and gates it).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from sbr_tpu.diag.health import (
    GRAD_AT_NONEQUILIBRIUM,
    GRAD_ILL_CONDITIONED,
    GRAD_NONFINITE,
)
from sbr_tpu.grad.cell import (
    BASE_KEYS,
    INTEREST_KEYS,
    aprime_tol,
    baseline_cell,
    interest_cell,
)
from sbr_tpu.models.params import (
    ModelParams,
    SolverConfig,
    params_to_pytree,
)
from sbr_tpu.obs import prof

GRAD_UNTRUSTED_MASK = GRAD_AT_NONEQUILIBRIUM | GRAD_ILL_CONDITIONED | GRAD_NONFINITE

WRT_DEFAULT = ("beta", "u", "kappa")


@struct.dataclass
class GradResult:
    """One differentiated equilibrium. ``grads`` maps parameter name →
    dξ/dθ of the ROOT CANDIDATE (well-defined across run boundaries, where
    the NaN-masked ξ is not); trust them per ``flags``."""

    xi: jnp.ndarray  # NaN-masked, identical to the forward solver's
    xi_candidate: jnp.ndarray  # the unmasked differentiated root
    grads: dict  # name -> dξ/dθ
    aw_prime: jnp.ndarray  # AW'(ξ), the IFT denominator
    status: jnp.ndarray  # int32 Status code
    flags: jnp.ndarray  # int32 GRAD_* bitmask

    @property
    def trusted(self):
        return (self.flags & GRAD_UNTRUSTED_MASK) == 0


@struct.dataclass
class SensitivitySurface:
    """(B, U) sensitivity grids next to the ξ grid (Figure-5 shaped)."""

    beta_values: jnp.ndarray
    u_values: jnp.ndarray
    xi: jnp.ndarray  # (B, U), NaN-masked
    grads: dict  # name -> (B, U) dξ/dθ surfaces
    aw_prime: jnp.ndarray  # (B, U)
    status: jnp.ndarray  # (B, U) int32
    flags: jnp.ndarray  # (B, U) int32 GRAD_* bitmask


def _resolve(config: Optional[SolverConfig], dtype):
    if config is None:
        config = SolverConfig(refine_crossings=False)
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    return config, jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))


def _validate_wrt(wrt, keys) -> Tuple[str, ...]:
    wrt = tuple(wrt)
    unknown = set(wrt) - set(keys)
    if not wrt or unknown:
        raise ValueError(f"wrt must be a non-empty subset of {keys}, got {wrt!r}")
    return wrt


def _with_nonfinite_flag(flags, grads: dict):
    bad = jnp.zeros(jnp.shape(flags), bool)
    for g in grads.values():
        bad = bad | ~jnp.isfinite(g)
    return flags | jnp.where(bad, jnp.int32(GRAD_NONFINITE), jnp.int32(0))


def _cell_outputs(cell, theta: dict, wrt, config, dtype, tol_ap=None):
    """value_and_grad of one cell w.r.t. the ``wrt`` sub-dict; returns
    (xi, xi_candidate, grads dict, aw_prime, status, flags). Raw (unjitted)
    so callers can jit/vmap the composition — the serve engine embeds this
    inside its own batch programs."""
    wrt_vals = {k: theta[k] for k in wrt}
    rest = {k: v for k, v in theta.items() if k not in wrt}

    def value_fn(wv):
        out = cell({**rest, **wv}, config, dtype, aprime_tol_=tol_ap)
        return out["xi_candidate"], out

    (xi_c, out), grads = jax.value_and_grad(value_fn, has_aux=True)(wrt_vals)
    flags = _with_nonfinite_flag(out["flags"], grads)
    return out["xi"], xi_c, grads, out["aw_prime"], out["status"], flags


def cell_value_and_grads(theta: dict, wrt, config: SolverConfig, dtype,
                         interest: bool = False, aprime_tol_=None):
    """In-jit building block: baseline/interest cell value + grads from a
    θ dict of traced scalars (see `_cell_outputs` for the return shape).
    ``aprime_tol_`` must be resolved by the CALLER when the composition is
    cached/serialized — the env default is read at trace time and would
    otherwise freeze into the program (see `_scalar_fn`)."""
    cell = interest_cell if interest else baseline_cell
    keys = INTEREST_KEYS if interest else BASE_KEYS
    return _cell_outputs(
        cell, {k: theta[k] for k in keys}, wrt, config, dtype, tol_ap=aprime_tol_
    )


@functools.lru_cache(maxsize=None)
def _scalar_fn(config: SolverConfig, dtype_name: str, wrt: tuple, interest: bool,
               tol_ap: float):
    # ``tol_ap`` is resolved by the caller AT CALL TIME and is part of this
    # cache key: the env knob SBR_GRAD_APRIME_TOL would otherwise be baked
    # into the first-built program and silently ignored afterwards.
    dtype = jnp.dtype(dtype_name)
    keys = INTEREST_KEYS if interest else BASE_KEYS

    def fn(*vals):
        prof.note_trace("grad.cell")
        theta = dict(zip(keys, vals))
        return cell_value_and_grads(
            theta, wrt, config, dtype, interest=interest, aprime_tol_=tol_ap
        )

    return jax.jit(fn)


def _theta_values(params, keys, dtype) -> tuple:
    tree = params_to_pytree(
        params if isinstance(params, ModelParams)
        else ModelParams(params.learning, params.economic)
    )
    tree.pop("eta_bar")
    if "r" in keys:
        tree["r"] = params.economic.r
        tree["delta"] = params.economic.delta
    return tuple(jnp.asarray(tree[k], dtype) for k in keys)


def _log_flag_census(stage: str, status, flags) -> None:
    """Host-boundary flag census → one obs ``grad`` event (enabled runs
    only), the stream `report grad` folds and gates."""
    from sbr_tpu import obs

    if not obs.enabled():
        return
    obs.event("grad", action="flags", stage=stage, **flag_census(status, flags))


def flag_census(status, flags) -> dict:
    """JSON-ready counts of the grad-trust bits over a (batched) result.

    ``nonfinite_run`` is the GATE signal (`report grad`): a NaN/Inf
    gradient at a healthy RUN equilibrium is a genuine defect, while
    non-finite gradients on non-equilibrium lanes are the expected face of
    degenerate brackets (those lanes are already flagged untrusted)."""
    import numpy as np

    flags = np.atleast_1d(np.asarray(flags, dtype=np.int64)).ravel()
    status = np.atleast_1d(np.asarray(status)).ravel()
    nonfinite = (flags & GRAD_NONFINITE) != 0
    return {
        "cells": int(flags.size),
        "run_cells": int((status == 0).sum()),
        "at_nonequilibrium": int(((flags & GRAD_AT_NONEQUILIBRIUM) != 0).sum()),
        "ill_conditioned": int(((flags & GRAD_ILL_CONDITIONED) != 0).sum()),
        "nonfinite": int(nonfinite.sum()),
        "nonfinite_run": int((nonfinite & (status == 0)).sum()),
        "untrusted": int(((flags & GRAD_UNTRUSTED_MASK) != 0).sum()),
    }


def scenario_xi_and_grad(
    spec,
    params,
    wrt=None,
    config: Optional[SolverConfig] = None,
    dtype=None,
) -> GradResult:
    """ξ and dξ/dθ for a composed scenario (ISSUE 14) — the gradient-
    coverage matrix entry point.

    Coverage: baseline- and interest-reducible `ScenarioSpec`s keep full
    IFT gradients (they route to `xi_and_grad` / `interest_xi_and_grad`,
    so the forward value stays bit-identical to the composed solve, which
    dispatches to the same legacy cells). Every other composition —
    hetero/social learning stages, policy modifiers, multi-bank — raises
    `NotImplementedError` LOUDLY rather than returning a gradient of a
    different pipeline; see README "Composable scenarios" for the matrix
    and the roadmap of what would extend it (hetero's coupled-K ODE runs
    a while_loop with no adjoint; the social fixed point needs its own
    outer IFT rule)."""
    red = spec.grad_reduction()
    if red == "baseline":
        return xi_and_grad(params, wrt=wrt or WRT_DEFAULT, config=config, dtype=dtype)
    if red == "interest":
        return interest_xi_and_grad(
            params, wrt=wrt or ("beta", "u", "kappa", "r"), config=config, dtype=dtype
        )
    raise NotImplementedError(
        f"gradient coverage: spec (learning={spec.learning!r}, "
        f"modifiers={spec.modifiers}, banks={spec.banks}) does not reduce to a "
        "grad-covered stack — only baseline- and interest-reducible "
        "compositions keep IFT gradients (README 'Composable scenarios')"
    )


def xi_and_grad(
    params: ModelParams,
    wrt=WRT_DEFAULT,
    config: Optional[SolverConfig] = None,
    dtype=None,
) -> GradResult:
    """ξ and dξ/dθ for one parameter point (baseline stack).

    ``wrt`` selects the differentiated parameters (subset of
    `grad.cell.BASE_KEYS`). The forward value is bit-identical to
    `solve_param_cell`'s; each gradient costs one residual linearization
    at the fixed point (grad/ift.py), not a solver re-run.
    """
    from sbr_tpu import obs

    config, dtype = _resolve(config, dtype)
    wrt = _validate_wrt(wrt, BASE_KEYS)
    fn = _scalar_fn(config, dtype.name, wrt, False, aprime_tol(dtype))
    vals = _theta_values(params, BASE_KEYS, dtype)
    with obs.span("grad.xi_and_grad") as sp:
        xi, xi_c, grads, aw_prime, status, flags = obs.jit_call(
            "grad.xi_and_grad", fn, *vals
        )
        sp.sync(xi_c)
    _log_flag_census("grad.xi_and_grad", status, flags)
    return GradResult(
        xi=xi, xi_candidate=xi_c, grads=dict(grads), aw_prime=aw_prime,
        status=status, flags=flags,
    )


def interest_xi_and_grad(
    params,
    wrt=("beta", "u", "kappa", "r"),
    config: Optional[SolverConfig] = None,
    dtype=None,
) -> GradResult:
    """ξ and dξ/dθ for the interest-rate stack (`ModelParamsInterest`).
    θ additionally spans ``r`` and ``delta``; the HJB value-function stage
    differentiates through the fixed-RK4 recompute rule (grad/cell.py)."""
    from sbr_tpu import obs

    config, dtype = _resolve(config, dtype)
    wrt = _validate_wrt(wrt, INTEREST_KEYS)
    fn = _scalar_fn(config, dtype.name, wrt, True, aprime_tol(dtype))
    vals = _theta_values(params, INTEREST_KEYS, dtype)
    with obs.span("grad.interest_xi_and_grad") as sp:
        xi, xi_c, grads, aw_prime, status, flags = obs.jit_call(
            "grad.interest_xi_and_grad", fn, *vals
        )
        sp.sync(xi_c)
    _log_flag_census("grad.interest_xi_and_grad", status, flags)
    return GradResult(
        xi=xi, xi_candidate=xi_c, grads=dict(grads), aw_prime=aw_prime,
        status=status, flags=flags,
    )


@functools.lru_cache(maxsize=None)
def _surface_fn(config: SolverConfig, dtype_name: str, wrt: tuple, tol_ap: float):
    dtype = jnp.dtype(dtype_name)

    def cell(beta, u, p, kappa, lam, eta, t0, t1, x0):
        prof.note_trace("grad.surface")
        theta = dict(zip(BASE_KEYS, (beta, u, p, kappa, lam, eta, t0, t1, x0)))
        return cell_value_and_grads(theta, wrt, config, dtype, aprime_tol_=tol_ap)

    bcast = (None,) * 7
    return jax.jit(
        jax.vmap(jax.vmap(cell, in_axes=(None, 0) + bcast), in_axes=(0, None) + bcast)
    )


def sensitivity_surface(
    beta_values,
    u_values,
    base: ModelParams,
    wrt=WRT_DEFAULT,
    config: Optional[SolverConfig] = None,
    dtype=None,
) -> SensitivitySurface:
    """∂ξ/∂θ surfaces over the Figure-5 β×u grid, one jitted vmap² program.

    Same copy-constructor semantics as `sweeps.beta_u_grid`: η and tspan
    stay pinned at the base model's resolved values for every β. The ξ
    grid is bit-identical to the forward sweep's (same cell, same config);
    each gradient surface replaces an entire brute-force perturbed re-sweep
    of the grid — the instant-sensitivity product the ROADMAP names.
    """
    from sbr_tpu import obs
    from sbr_tpu.obs.metrics import metrics

    config, dtype = _resolve(config, dtype)
    wrt = _validate_wrt(wrt, BASE_KEYS)
    beta_values = jnp.asarray(beta_values, dtype=dtype)
    u_values = jnp.asarray(u_values, dtype=dtype)
    econ = base.economic
    tspan = base.learning.tspan
    scalars = tuple(
        jnp.asarray(v, dtype)
        for v in (econ.p, econ.kappa, econ.lam, econ.eta, tspan[0], tspan[1],
                  base.learning.x0)
    )
    fn = _surface_fn(config, dtype.name, wrt, aprime_tol(dtype))
    n_b, n_u = int(beta_values.shape[0]), int(u_values.shape[0])
    with obs.span("grad.sensitivity_surface", n_beta=n_b, n_u=n_u) as sp:
        xi, xi_c, grads, aw_prime, status, flags = obs.jit_call(
            "grad.sensitivity_surface", fn, beta_values, u_values, *scalars
        )
        sp.sync(status)
    metrics().inc("grad.surface.cells", n_b * n_u)
    _log_flag_census("grad.sensitivity_surface", status, flags)
    return SensitivitySurface(
        beta_values=beta_values, u_values=u_values, xi=xi,
        grads=dict(grads), aw_prime=aw_prime, status=status, flags=flags,
    )


@functools.lru_cache(maxsize=None)
def _value_fn(config: SolverConfig, dtype_name: str):
    dtype = jnp.dtype(dtype_name)

    def fn(*vals):
        prof.note_trace("grad.cell_value")
        return baseline_cell(dict(zip(BASE_KEYS, vals)), config, dtype)["xi_candidate"]

    return jax.jit(fn)


def xi_value(params: ModelParams, config: Optional[SolverConfig] = None, dtype=None):
    """The grad pipeline's forward value alone (the FD-oracle probe):
    ``xi_candidate`` at ``params`` through a VALUE-ONLY jitted program —
    no gradients are computed, so an FD sweep pays the plain forward cost
    per probe instead of the value-and-grad program's."""
    config, dtype = _resolve(config, dtype)
    fn = _value_fn(config, dtype.name)
    return fn(*_theta_values(params, BASE_KEYS, dtype))
