"""Calibration: fit (β, u, κ) to observed withdrawal curves (ISSUE 13).

The inverse problem the paper never poses: given samples of the cumulative
aggregate-withdrawal curve AW(t) — the observable a regulator actually has
— recover the structural parameters. With IFT gradients the model curve
AW(t; θ) is differentiable in θ END TO END (θ → hazard → buffers → ξ →
curve), so the fit is plain first-order optimization over the closed-form
loss instead of a derivative-free search over forward solves.

Identification: the cumulative curve alone does NOT pin (u, κ). AW(t) =
[G(t−ξ+τ_OUT^CON)]₊ − [G(t−ξ+τ_IN^CON)]₊ + G(0) depends on θ only through
β (shape) and the two branch START TIMES ξ−τ^CON — and when ξ ≤ τ̄_OUT the
out-branch start collapses to 0, leaving TWO observables for three
parameters: a one-dimensional (u, κ) ridge of perfect fits (measured: Adam
drives the curve MSE to ~1e-17 with κ off by 0.24). The missing observable
is in the data anyway: a real withdrawal series ENDS at the crash — so the
fit takes the observed crash time ξ_obs alongside the curve, which closes
the system (τ_IN from the start time, u from h(τ_IN) = u, κ from
AW(ξ) = κ). Pass ``xi_obs=None`` to reproduce the ridge deliberately.

Mechanics:

- **Loss**: mean squared error of `grad.cell.aw_cum_at` (the closed-form
  AW curve at the differentiable ξ/buffers) against the observations,
  plus ``xi_weight · (ξ(θ) − ξ_obs)²`` when the crash time is given.
- **Parameterization**: optimization runs UNCONSTRAINED in transformed
  space — log β, log u, logit κ — so box constraints (β, u > 0,
  κ ∈ (0, 1)) hold by construction and no projection step is needed.
- **Optimizer**: Adam with fixed hyperparameters, one jitted
  `value_and_grad` step, host loop with early exit on loss/step
  tolerance. Deterministic: same data + init ⇒ same trajectory (no RNG
  anywhere). The jitted step is cached per (config, dtype, wrt), so
  repeated fits (the bench workload, sweeps of fits) pay one compile.
- **Instrumentation**: the fit runs under an `obs.span` and emits ``grad``
  events — ``calib_start``, ``calib_step`` every ``log_every`` steps,
  ``calib_done`` with the converged verdict — the series
  `report grad RUN_DIR` renders and gates on.

`synth_withdrawals` generates the deterministic test fixture: AW samples
from a known θ*, optionally with seeded Gaussian noise.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from sbr_tpu.grad.cell import BASE_KEYS, aw_cum_at, baseline_cell
from sbr_tpu.models.params import ModelParams, SolverConfig, params_to_pytree
from sbr_tpu.obs import prof

# Parameters the calibrator may fit, with their unconstrained transforms.
_TRANSFORMS = {
    "beta": (jnp.log, jnp.exp),
    "u": (jnp.log, jnp.exp),
    "kappa": (lambda v: jnp.log(v) - jnp.log1p(-v), jax.nn.sigmoid),
    "lam": (jnp.log, jnp.exp),
    "p": (lambda v: jnp.log(v) - jnp.log1p(-v), jax.nn.sigmoid),
}
CALIBRATABLE = tuple(_TRANSFORMS)


@dataclasses.dataclass(frozen=True)
class CalibResult:
    """One calibration outcome (host-side, JSON-friendly)."""

    params: dict  # fitted values, natural space, plain floats
    loss: float  # final MSE
    steps: int  # steps actually run
    converged: bool  # loss tol or step tol met within the budget
    loss_history: tuple  # per-step losses (for convergence rendering)


def _raw_of(theta: dict, wrt) -> dict:
    return {k: _TRANSFORMS[k][0](jnp.asarray(theta[k])) for k in wrt}


def _nat_of(raw: dict) -> dict:
    return {k: _TRANSFORMS[k][1](v) for k, v in raw.items()}


@functools.lru_cache(maxsize=None)
def _step_fn(config: SolverConfig, dtype_name: str, wrt: tuple, lr: float,
             use_xi: bool, xi_weight: float):
    """One jitted Adam step on the transformed parameters."""
    dtype = jnp.dtype(dtype_name)
    b1, b2, eps = 0.9, 0.999, 1e-8

    def loss_fn(raw, rest, t_obs, aw_obs, xi_obs):
        theta = {**rest, **_nat_of(raw)}
        out = baseline_cell(theta, config, dtype)
        aw = aw_cum_at(
            t_obs, out["xi_candidate"], out["tau_in"], out["tau_out"],
            theta["beta"], theta["x0"],
        )
        loss = jnp.mean((aw - aw_obs) ** 2)
        if use_xi:
            loss = loss + xi_weight * (out["xi_candidate"] - xi_obs) ** 2
        return loss

    def step(raw, m, v, t, rest, t_obs, aw_obs, xi_obs):
        prof.note_trace("grad.calibrate_step")
        loss, g = jax.value_and_grad(loss_fn)(raw, rest, t_obs, aw_obs, xi_obs)
        t = t + 1
        upd = {}
        m2, v2 = {}, {}
        for k in raw:
            m2[k] = b1 * m[k] + (1 - b1) * g[k]
            v2[k] = b2 * v[k] + (1 - b2) * g[k] ** 2
            mhat = m2[k] / (1 - b1 ** t)
            vhat = v2[k] / (1 - b2 ** t)
            upd[k] = raw[k] - lr * mhat / (jnp.sqrt(vhat) + eps)
        return upd, m2, v2, t, loss, g

    return jax.jit(step)


def synth_withdrawals(
    params: ModelParams,
    n_obs: int = 64,
    noise: float = 0.0,
    seed: int = 0,
    config: Optional[SolverConfig] = None,
    dtype=None,
):
    """Deterministic calibration fixture: ``(t_obs, aw_obs, xi)`` sampled
    from the model at ``params`` on a uniform grid over [0, η], plus
    optional seeded Gaussian noise of scale ``noise`` on the curve. ``xi``
    is the planted crash time — the curve's observed endpoint (module
    docstring: without it the inverse problem has a (u, κ) ridge)."""
    from sbr_tpu.grad.api import _resolve

    config, dtype = _resolve(config, dtype)
    theta = {k: jnp.asarray(v, dtype) for k, v in params_to_pytree(params).items()
             if k != "eta_bar"}
    out = baseline_cell(theta, config, dtype)
    t_obs = jnp.linspace(jnp.zeros((), dtype), theta["eta"], n_obs)
    aw = aw_cum_at(
        t_obs, out["xi_candidate"], out["tau_in"], out["tau_out"],
        theta["beta"], theta["x0"],
    )
    if noise > 0.0:
        key = jax.random.PRNGKey(seed)
        aw = aw + noise * jax.random.normal(key, aw.shape, dtype)
    return t_obs, aw, out["xi_candidate"]


def fit_withdrawals(
    t_obs,
    aw_obs,
    init: ModelParams,
    wrt=("beta", "u", "kappa"),
    xi_obs=None,
    xi_weight: float = 1e-2,
    steps: int = 400,
    lr: float = 0.05,
    loss_tol: float = 1e-12,
    step_tol: float = 1e-10,
    log_every: int = 25,
    config: Optional[SolverConfig] = None,
    dtype=None,
) -> CalibResult:
    """Fit ``wrt`` ⊆ {β, u, κ, λ, p} to observed (t, AW) samples by Adam
    over the IFT-differentiable model curve (module docstring).

    ``init`` supplies both the starting point and the held-fixed
    parameters — INCLUDING the resolved η and tspan, which are never
    re-derived from β mid-fit (build init via `with_overrides` on the same
    base as the data so η matches). The starting point must be a RUN cell:
    in no-crossing territory the withdrawal curve is identically flat, its
    gradient is exactly zero, and the fit cannot move (the ``converged``
    verdict requires the loss to actually improve, so a dead start reports
    ``converged=False`` rather than a silent non-fit).

    Converged: the loss drops under ``loss_tol``, or the BEST loss seen
    stops improving (by a relative ``step_tol``) for a 40-step window
    after having improved at least 2× from the start — the noise-floor
    case. A stall WITHOUT improvement (dead gradient) and an exhausted
    step budget both report ``converged=False``.
    """
    from sbr_tpu import obs
    from sbr_tpu.grad.api import _resolve

    config, dtype = _resolve(config, dtype)
    wrt = tuple(wrt)
    unknown = set(wrt) - set(CALIBRATABLE)
    if not wrt or unknown:
        raise ValueError(f"wrt must be a non-empty subset of {CALIBRATABLE}, got {wrt!r}")

    theta0 = {k: jnp.asarray(v, dtype) for k, v in params_to_pytree(init).items()
              if k != "eta_bar"}
    rest = {k: v for k, v in theta0.items() if k not in wrt}
    raw = _raw_of(theta0, wrt)
    m = {k: jnp.zeros((), dtype) for k in wrt}
    v = {k: jnp.zeros((), dtype) for k in wrt}
    t_obs = jnp.asarray(t_obs, dtype)
    aw_obs = jnp.asarray(aw_obs, dtype)
    use_xi = xi_obs is not None
    xi_arg = jnp.asarray(xi_obs if use_xi else 0.0, dtype)

    step = _step_fn(config, dtype.name, wrt, float(lr), use_xi, float(xi_weight))
    losses = []
    converged = False
    t = 0
    # Best-iterate tracking: Adam oscillates near the optimum, so "loss
    # didn't improve over the last step" is noise, not convergence. The
    # fit stalls only when the BEST loss seen hasn't improved for a whole
    # window, and the returned parameters are the best iterate's.
    best_loss = float("inf")
    best_step_i = -1
    best_raw = raw
    stall_window = 40
    with obs.span("grad.calibrate", n_obs=int(t_obs.shape[0]), steps=steps):
        obs.event("grad", action="calib_start", wrt=list(wrt), steps=steps,
                  lr=lr, n_obs=int(t_obs.shape[0]), with_xi=use_xi)
        for i in range(steps):
            raw_before = raw
            raw, m, v, t, loss, _ = step(raw, m, v, t, rest, t_obs, aw_obs, xi_arg)
            loss_f = float(loss)  # the loss AT raw_before
            losses.append(loss_f)
            if loss_f < best_loss * (1.0 - step_tol):
                best_loss, best_step_i, best_raw = loss_f, i, raw_before
            if (i % max(log_every, 1)) == 0:
                obs.event("grad", action="calib_step", step=i, loss=loss_f)
            if loss_f <= loss_tol:
                best_loss, best_raw = loss_f, raw_before
                converged = True
                break
            if i - best_step_i >= stall_window:
                # Stalled: converged at a (noise) floor only if the fit
                # actually improved — a dead-gradient stall is a non-fit.
                converged = best_loss < 0.5 * losses[0]
                break
        fitted = {k: float(val) for k, val in _nat_of(best_raw).items()}
        final_loss = best_loss if losses else float("nan")
        obs.event(
            "grad", action="calib_done", steps=len(losses), loss=final_loss,
            converged=bool(converged), **{f"fit_{k}": v for k, v in fitted.items()},
        )
    return CalibResult(
        params=fitted,
        loss=final_loss,
        steps=len(losses),
        converged=bool(converged),
        loss_history=tuple(losses),
    )
