"""Differentiable equilibria (ISSUE 13): implicit-function-theorem
gradients through the Stage 2–3 solve, calibration of structural
parameters to observed withdrawal curves, and gradient-based worst-case
stress search.

- `grad.ift`       — `implicit_root`: custom-JVP root differentiation
  (one linearization at the fixed point; zero backprop through solver
  iterations).
- `grad.cell`      — the differentiable baseline/interest cells (the
  grad twin of `sweeps.baseline_sweeps.solve_param_cell`; bit-identical
  primal ξ).
- `grad.api`       — `xi_and_grad`, `interest_xi_and_grad`,
  `sensitivity_surface`, grad-trust flags.
- `grad.calibrate` — `fit_withdrawals` + the `synth_withdrawals` fixture.
- `grad.stress`    — `run_margin`, `stress_search`.
- `grad.parity`    — the CI IFT-vs-FD battery
  (``python -m sbr_tpu.grad.parity``).

Stack coverage: baseline and interest (both closed-form Stage 1; the
interest HJB stage differentiates via the fixed-RK4 recompute rule). The
hetero stack is deliberately NOT grad-capable yet — its coupled-K ODE
runs an adjoint-less `lax.while_loop` and its sharded path would nest
custom rules under `shard_map` (rationale in grad/cell.py).

Composed scenarios (ISSUE 14): `scenario_xi_and_grad(spec, params)`
covers exactly the baseline- and interest-REDUCIBLE `ScenarioSpec`s (the
composed solve dispatches to the same legacy cells, so the primal stays
bit-identical); hetero/social learning stages, policy modifiers, and
multi-bank contagion raise `NotImplementedError` loudly — gradient
coverage is part of the composition matrix (README "Composable
scenarios"), never a silent wrong answer.
"""

from sbr_tpu.grad.api import (
    GRAD_UNTRUSTED_MASK,
    GradResult,
    SensitivitySurface,
    cell_value_and_grads,
    flag_census,
    interest_xi_and_grad,
    scenario_xi_and_grad,
    sensitivity_surface,
    xi_and_grad,
    xi_value,
)
from sbr_tpu.grad.calibrate import CalibResult, fit_withdrawals, synth_withdrawals
from sbr_tpu.grad.ift import implicit_root
from sbr_tpu.grad.stress import StressResult, run_margin, stress_search

__all__ = [
    "CalibResult",
    "GRAD_UNTRUSTED_MASK",
    "GradResult",
    "SensitivitySurface",
    "StressResult",
    "cell_value_and_grads",
    "fit_withdrawals",
    "flag_census",
    "implicit_root",
    "interest_xi_and_grad",
    "run_margin",
    "scenario_xi_and_grad",
    "sensitivity_surface",
    "stress_search",
    "synth_withdrawals",
    "xi_and_grad",
    "xi_value",
]
