"""Grad-parity battery: IFT gradients vs central finite differences.

The CI smoke step (ISSUE 13): a seeded sample of parameter points in f64,
dξ/dβ, dξ/du, dξ/dκ from `grad.api.xi_and_grad` against central
differences of the same forward value, exit 1 on any relative disagreement
beyond tolerance. This is a STRUCTURAL gate, not just numerics: backprop
leaking through the root-finder iterations yields an exact 0 gradient
(grad/ift.py), which fails the match — so a pass proves the IFT rules
carry the derivative.

    python -m sbr_tpu.grad.parity [--n 6] [--seed 0] [--tol 1e-5] [--json]
"""

from __future__ import annotations

import argparse
import json
import sys


def run_battery(n: int = 6, seed: int = 0, tol: float = 1e-5, config=None) -> dict:
    """Sample ``n`` seeded parameter points in the run region and compare
    IFT vs FD for each wrt dimension. Returns a JSON-ready report with
    per-point worst relative errors; ``report["ok"]`` is the verdict."""
    import numpy as np

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from sbr_tpu.grad import api
    from sbr_tpu.models.params import SolverConfig, make_model_params, with_overrides

    if config is None:
        # Refinement ON: the battery's crossings are then TRUE roots of the
        # continuous hazard (IFT-differentiated), smooth in θ — so central
        # differences are a valid oracle at 1e-5. The unrefined grid
        # estimator is piecewise smooth with O(Δτ) derivative kinks at knot
        # handoffs, which FD straddles (~1e-3 apparent error that is the
        # ORACLE's, not the gradient's).
        config = SolverConfig(n_grid=512, bisect_iters=90, refine_crossings=True)
    rng = np.random.RandomState(seed)
    wrt = ("beta", "u", "kappa")
    points = []
    checked = 0
    worst = 0.0
    # Sample inside the classic run region of the Figure-5 grid; points
    # that land on non-run cells are reported but not FD-compared (the
    # candidate root is still differentiable, but the oracle battery gates
    # on equilibria — flags cover the rest).
    for i in range(n):
        beta = float(rng.uniform(0.8, 2.0))
        u = float(rng.uniform(0.05, 0.14))
        kappa = float(rng.uniform(0.4, 0.7))
        params = make_model_params(beta=beta, u=u, kappa=kappa)
        res = api.xi_and_grad(params, wrt=wrt, config=config, dtype=jnp.float64)
        entry = {
            "beta": beta, "u": u, "kappa": kappa,
            "status": int(res.status), "flags": int(res.flags),
            "xi": float(res.xi_candidate),
        }
        if int(res.status) == 0 and int(res.flags) == 0:
            checked += 1
            rels = {}
            for k in wrt:
                h = 1e-6 * max(1.0, abs(entry[k]))
                # with_overrides PINS the resolved η/tspan (the reference's
                # copy-constructor semantics): the FD probe varies ONE θ
                # entry, matching the IFT partial derivative. Rebuilding via
                # make_model_params would re-derive η = η̄/β and measure the
                # total derivative along that constraint instead.
                pp = with_overrides(params, **{k: entry[k] + h})
                pm = with_overrides(params, **{k: entry[k] - h})
                fd = (
                    float(api.xi_value(pp, config=config, dtype=jnp.float64))
                    - float(api.xi_value(pm, config=config, dtype=jnp.float64))
                ) / (2 * h)
                ift = float(res.grads[k])
                rel = abs(ift - fd) / max(abs(fd), 1e-12)
                rels[k] = {"ift": ift, "fd": fd, "rel": rel}
                worst = max(worst, rel)
            entry["rel_errors"] = rels
        points.append(entry)
    ok = checked > 0 and worst <= tol
    return {
        "n_points": n,
        "n_checked": checked,
        "worst_rel": worst,
        "tol": tol,
        "ok": bool(ok),
        "points": points,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.grad.parity",
        description="IFT-vs-finite-difference gradient parity battery "
        "(f64); exit 1 on disagreement beyond tolerance",
    )
    parser.add_argument("--n", type=int, default=6, help="parameter points (default 6)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--tol", type=float, default=1e-5,
                        help="max allowed relative error (default 1e-5)")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    parser.add_argument("--obs-dir", default=None,
                        help="record the verdict as an audit probe event "
                        "under this obs run root")
    args = parser.parse_args(argv)

    def _check() -> int:
        report = run_battery(n=args.n, seed=args.seed, tol=args.tol)
        if args.json:
            print(json.dumps(report))
        else:
            for pt in report["points"]:
                rels = pt.get("rel_errors")
                if rels is None:
                    print(f"  skip  β={pt['beta']:.3f} u={pt['u']:.3f} κ={pt['kappa']:.3f} "
                          f"(status {pt['status']}, flags {pt['flags']})")
                    continue
                line = " ".join(
                    f"d{k}: {v['rel']:.2e}" for k, v in rels.items()
                )
                print(f"  ok    β={pt['beta']:.3f} u={pt['u']:.3f} κ={pt['kappa']:.3f}  {line}")
            print(
                f"grad parity: {report['n_checked']}/{report['n_points']} run points, "
                f"worst rel {report['worst_rel']:.3e} vs tol {report['tol']:g} "
                f"-> {'OK' if report['ok'] else 'FAIL'}"
            )
        if not report["ok"]:
            print("grad parity FAILED", file=sys.stderr)
            return 1
        return 0

    # Legacy entrypoint, audit protocol (ISSUE 17): the battery executes
    # through the unified registry runner so its verdict lands as an
    # ``audit`` probe event; flags, output, and exit code are unchanged.
    from sbr_tpu.obs import audit

    return audit.run_legacy_cli("grad.ift_fd", _check, obs_dir=args.obs_dir)


if __name__ == "__main__":
    sys.exit(main())
