"""Differentiable equilibrium cells (ISSUE 13 tentpole).

One fully-parameterized Stage 2–3 solve, mirroring
`sweeps.baseline_sweeps.solve_param_cell` stage for stage, but built so
`jax.grad` flows θ → ξ end to end:

- **Stage 1 + hazard** are closed-form/quadrature arithmetic — plain
  autodiff (the warped grid, the cumulative Gauss-Legendre integral, and
  the hazard ratio are all smooth in θ).
- **Buffer crossings**: the coarse crossing is the boolean-transition
  argmax plus linear interpolation (`core.rootfind.first_upcrossing`) —
  the selected indices are integers (no tangents), and the interpolation
  arithmetic differentiates directly, giving the exact derivative of the
  grid estimator. With ``config.refine_crossings`` the crossing is the
  exact root of h(τ̄; θ) = u and is wrapped in `ift.implicit_root` with the
  SHARED `baseline.solver.hazard_at_from_parts` residual: the gradient
  linearizes exactly the function whose root the refinement solver found.
- **ξ**: `ift.implicit_root` around the SAME `compute_xi` the forward
  solvers run (bit-identical primal ξ, asserted in tests), residual
  F(ξ, θ) = AW(ξ; θ) − κ in closed form. dξ/dθ = −F_θ/F_ξ: one division
  at the fixed point, zero backprop through bisection/Chandrupatla
  iterations (reverse mode through those is an exact 0 — see grad/ift.py).
- **Interest stack** (`interest_cell`): the HJB value-function stage is
  integrated with the FIXED RK4 `lax.scan` under `jax.checkpoint` — the
  recompute-rule treatment of the ODE stage (torchode, PAPERS.md): jax's
  native scan adjoint differentiates it, remat bounds the adjoint's
  memory to O(√n) residency, and under an adaptive forward config the
  gradient path simply recomputes the trajectory with the deterministic
  fixed-step scheme (agreeing within the ODE tolerance). This is WHY the
  grad layer covers interest rather than hetero first: interest keeps
  Stage 1 closed form, so the only non-analytic stage is this one scan —
  while hetero's coupled-K ODE runs bs32's `lax.while_loop` (no adjoint
  exists) and its sharded path would put custom rules under `shard_map`,
  whose autodiff interaction is unproven on the jax 0.4.x compat shims.

Every ``theta`` is a flat dict of scalars (see `models.params
.params_to_pytree`); classification (status, grad flags) happens on
`stop_gradient` values so booleans/ints never carry tangents.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
from jax import lax

from sbr_tpu.baseline.learning import logistic_cdf, logistic_pdf, solve_learning
from sbr_tpu.baseline.solver import (
    _hazard_parts,
    _root_tol,
    compute_xi,
    hazard_at_from_parts,
    hazard_grid_is_uniform,
    quad_nodes_weights,
    warped_grid_index,
)
from sbr_tpu.core.rootfind import bisect, chandrupatla, first_upcrossing, last_downcrossing
from sbr_tpu.diag.health import (
    GRAD_AT_NONEQUILIBRIUM,
    GRAD_ILL_CONDITIONED,
)
from sbr_tpu.grad.ift import implicit_root
from sbr_tpu.models.params import SolverConfig
from sbr_tpu.models.results import Status
from sbr_tpu.obs import prof

# θ keys of the baseline cell, `solve_param_cell` column order.
BASE_KEYS = ("beta", "u", "p", "kappa", "lam", "eta", "t0", "t1", "x0")
# θ keys of the interest cell (baseline + rate/maturity).
INTEREST_KEYS = BASE_KEYS + ("r", "delta")


def aprime_tol(dtype, override: float | None = None) -> float:
    """|AW'(ξ)| threshold below which dξ/dθ = −F_θ/AW'(ξ) is flagged
    `GRAD_ILL_CONDITIONED`. Default √eps of the dtype (≈1.5e-8 in f64);
    ``SBR_GRAD_APRIME_TOL`` overrides globally, an explicit argument wins."""
    if override is not None:
        return float(override)
    env = os.environ.get("SBR_GRAD_APRIME_TOL", "").strip()
    if env:
        return float(env)
    return float(jnp.finfo(jnp.dtype(dtype)).eps) ** 0.5


def _fixed_ode(config: SolverConfig) -> SolverConfig:
    """The gradient path's ODE numerics: the deterministic fixed-step
    scheme (the recompute rule — module docstring). Root-find numerics keep
    the caller's mode; only the ODE stage is pinned."""
    if not config.adaptive:
        return config
    return dataclasses.replace(config, numerics="fixed")


def _ls_of(beta, t0, t1, x0, config: SolverConfig, dtype):
    """Closed-form Stage 1 from traced scalars (free to rebuild; unused
    sampled curves are dead-code-eliminated by XLA)."""
    from sbr_tpu.sweeps.baseline_sweeps import _TracedLearning

    return solve_learning(_TracedLearning(beta, (t0, t1), x0), config, dtype=dtype)


def _crossing_ops(theta: dict, tau_grid, hr, integ, int_eta, config: SolverConfig, dtype):
    """Buffer times (τ̄_IN, τ̄_OUT), differentiable. Mirrors
    `baseline.solver.optimal_buffer`: coarse scan crossings (direct AD),
    then — under ``config.refine_crossings`` — IFT-wrapped refinement
    against the continuous exact hazard."""
    default = jnp.asarray(theta["t1"], dtype)
    u = theta["u"]
    t_in, has_up = first_upcrossing(tau_grid, hr, u, default, return_flag=True)
    t_out, has_dn = last_downcrossing(tau_grid, hr, u, default, return_flag=True)
    if not config.refine_crossings:
        return t_in, t_out

    nodes, weights = quad_nodes_weights(config.quad_order, dtype)
    # ALL tangent carriers ride the operand (the implicit_root contract) —
    # including the coarse crossings, so the solve closures derive their
    # brackets from exactly the forward path's coarse estimates (primal
    # bit-identity) without re-deriving the grid hazard.
    op = {
        "tau_grid": tau_grid,
        "integ": integ,
        "int_eta": int_eta,
        "p": theta["p"],
        "lam": theta["lam"],
        "beta": theta["beta"],
        "x0": theta["x0"],
        "u": u,
        "t_in_coarse": t_in,
        "t_out_coarse": t_out,
    }

    def hz(x, o):
        return hazard_at_from_parts(
            x, o["tau_grid"], o["integ"], o["int_eta"], o["p"], o["lam"],
            o["beta"], o["x0"], nodes, weights,
        )

    n = tau_grid.shape[0]

    def bracket(o, t):
        # ±one LOCAL grid interval around the coarse crossing, exactly as
        # optimal_buffer.bracket (the grid may be warped).
        g = o["tau_grid"]
        i = jnp.clip(jnp.searchsorted(g, t, side="right") - 1, 0, n - 1)
        return g[jnp.maximum(i - 1, 0)], g[jnp.minimum(i + 2, n - 1)]

    refine = (
        (lambda f, lo, hi: chandrupatla(f, lo, hi, budget=60))
        if config.adaptive
        else (lambda f, lo, hi: bisect(f, lo, hi, num_iters=60))
    )

    def solve_in(o):
        prof.note_trace("grad.root_solve")
        lo, hi = bracket(o, o["t_in_coarse"])
        return refine(lambda x: hz(x, o) - o["u"], lo, hi)

    def solve_out(o):
        prof.note_trace("grad.root_solve")
        lo, hi = bracket(o, o["t_out_coarse"])
        return refine(lambda x: o["u"] - hz(x, o), lo, hi)

    t_in_ref = implicit_root(lambda x, o: hz(x, o) - o["u"], solve_in, op)
    t_out_ref = implicit_root(lambda x, o: o["u"] - hz(x, o), solve_out, op)
    t_in = jnp.where(has_up, t_in_ref, t_in)
    t_out = jnp.where(has_dn, t_out_ref, t_out)
    return t_in, t_out


def _aw_residual(x, o):
    """F(ξ, θ) = AW(ξ) − κ in closed form — the ξ-root's IFT residual,
    formula-identical to `compute_xi.aw_of` with closed-form Stage 1."""
    t_out = jnp.minimum(o["t_out"], x)
    t_in = jnp.minimum(o["t_in"], x)
    return (
        logistic_cdf(t_out, o["beta"], o["x0"])
        - logistic_cdf(t_in, o["beta"], o["x0"])
        - o["kappa"]
    )


def _xi_and_class(theta: dict, t_in, t_out, config: SolverConfig, dtype, tol_ap: float):
    """IFT-wrapped ξ root + the forward solver's exact classification."""
    op = {
        "beta": theta["beta"],
        "x0": theta["x0"],
        "kappa": theta["kappa"],
        "t0": theta["t0"],
        "t1": theta["t1"],
        "t_in": t_in,
        "t_out": t_out,
    }

    def solve(o):
        # The SAME solver entry the forward stacks call (compute_xi →
        # bisect/chandrupatla per config), on primal values: the grad
        # cell's ξ is bit-identical to solve_param_cell's by construction.
        # Pure function of ``o`` — no tracer-carrying closure capture.
        prof.note_trace("grad.root_solve")
        ls = _ls_of(o["beta"], o["t0"], o["t1"], o["x0"], config, dtype)
        xi, _, _, _ = compute_xi(o["t_in"], o["t_out"], ls, o["kappa"], config)
        return xi

    xi_c = implicit_root(_aw_residual, solve, op)

    # Classification on stop_gradient values — booleans/status carry no
    # tangents, and the formulas mirror solve_equilibrium_core exactly.
    sg = lax.stop_gradient
    op_s = sg(op)
    xi_s = sg(xi_c)
    err = jnp.abs(_aw_residual(xi_s, op_s))
    root_ok = err <= _root_tol(dtype)
    beta_s, x0_s = op_s["beta"], op_s["x0"]
    increasing = logistic_pdf(jnp.minimum(op_s["t_out"], xi_s), beta_s, x0_s) >= (
        logistic_pdf(jnp.minimum(op_s["t_in"], xi_s), beta_s, x0_s)
    )
    no_crossing = op_s["t_in"] == op_s["t_out"]
    run = (~no_crossing) & root_ok & increasing
    status = jnp.where(
        no_crossing,
        Status.NO_CROSSING,
        jnp.where(
            ~root_ok,
            Status.NO_ROOT,
            jnp.where(increasing, Status.RUN, Status.FALSE_EQ),
        ),
    ).astype(jnp.int32)

    # AW'(ξ) — the IFT denominator — by autodiff of the shared residual at
    # the (stop-gradient) fixed point: the conditioning check measures
    # exactly the division `implicit_root` performs.
    aw_prime = jax.grad(_aw_residual, argnums=0)(xi_s, op_s)
    flags = jnp.where(
        status != jnp.int32(Status.RUN),
        jnp.int32(GRAD_AT_NONEQUILIBRIUM),
        jnp.int32(0),
    ) | jnp.where(
        jnp.abs(aw_prime) <= tol_ap, jnp.int32(GRAD_ILL_CONDITIONED), jnp.int32(0)
    )

    nan = jnp.asarray(jnp.nan, dtype)
    xi = jnp.where(run, xi_c, nan)
    return {
        "xi": xi,
        "xi_candidate": xi_c,
        "tau_in": t_in,
        "tau_out": t_out,
        "status": status,
        "flags": flags,
        "aw_prime": aw_prime,
        "residual": err,
    }


def baseline_cell(theta: dict, config: SolverConfig, dtype, aprime_tol_: float | None = None):
    """Differentiable baseline Stage 2–3 solve from a θ dict (BASE_KEYS).

    Returns a dict: ``xi`` (NaN-masked like the forward solver, with zero
    tangent on non-run lanes), ``xi_candidate`` (the unmasked root — the
    quantity to differentiate near run boundaries), buffers, ``status``,
    grad-trust ``flags``, ``aw_prime`` (the IFT denominator), ``residual``.
    """
    theta = {k: jnp.asarray(theta[k], dtype) for k in BASE_KEYS}
    tol_ap = aprime_tol(dtype, aprime_tol_)
    ls = _ls_of(theta["beta"], theta["t0"], theta["t1"], theta["x0"], config, dtype)
    tau_grid, hr, integ, int_eta = _hazard_parts(
        theta["p"], theta["lam"], ls, theta["eta"], config
    )
    t_in, t_out = _crossing_ops(theta, tau_grid, hr, integ, int_eta, config, dtype)
    return _xi_and_class(theta, t_in, t_out, config, dtype, tol_ap)


def interest_cell(theta: dict, config: SolverConfig, dtype, aprime_tol_: float | None = None):
    """Differentiable interest-rate Stage 2–3 solve (INTEREST_KEYS).

    Baseline hazard → HJB value function (fixed RK4 scan under
    `jax.checkpoint` — the recompute rule, module docstring) → effective
    hazard h − rV → grid buffer crossings (direct AD; refinement is pinned
    OFF on this stack: the effective hazard is only grid-known through V)
    → the SAME IFT ξ root as baseline."""
    from sbr_tpu.interest.value_function import solve_value_function

    theta = {k: jnp.asarray(theta[k], dtype) for k in INTEREST_KEYS}
    tol_ap = aprime_tol(dtype, aprime_tol_)
    ls = _ls_of(theta["beta"], theta["t0"], theta["t1"], theta["x0"], config, dtype)
    tau_grid, hr, integ, int_eta = _hazard_parts(
        theta["p"], theta["lam"], ls, theta["eta"], config
    )

    warped = not hazard_grid_is_uniform(ls, config)
    cfg_ode = _fixed_ode(config)

    # Every tangent carrier enters as an explicit argument (never closure)
    # so the remat boundary cannot mis-handle it; the warped index guesses
    # are integers — tangent-free by construction — and the interpolated
    # VALUES flow through tau_grid/hr.
    def v_solve(tau_grid_, hr_, delta_, r_, u_, eta_, beta_, x0_):
        index_fn = (
            (lambda t: warped_grid_index(
                t, eta_, beta_, x0_, config.n_grid, config.grid_warp
            ))
            if warped
            else None
        )
        return solve_value_function(
            tau_grid_, hr_, delta_, r_, u_, cfg_ode,
            uniform=not warped, index_fn=index_fn,
        )

    v = jax.checkpoint(v_solve)(
        tau_grid, hr, theta["delta"], theta["r"], theta["u"],
        theta["eta"], theta["beta"], theta["x0"],
    )
    hr_eff = hr - theta["r"] * v

    default = jnp.asarray(theta["t1"], dtype)
    t_in, _ = first_upcrossing(tau_grid, hr_eff, theta["u"], default, return_flag=True)
    t_out, _ = last_downcrossing(tau_grid, hr_eff, theta["u"], default, return_flag=True)
    return _xi_and_class(theta, t_in, t_out, config, dtype, tol_ap)


def aw_cum_at(t, xi, tau_in_unc, tau_out_unc, beta, x0):
    """Cumulative aggregate-withdrawal curve AW(t) in closed form,
    differentiable in every argument — `baseline.solver.get_aw`'s formula
    freed from the LearningSolution wrapper, for the calibration loss:
    AW(t) = [G(t−ξ+τ_OUT^CON)]₊ − [G(t−ξ+τ_IN^CON)]₊ + G(0)."""
    t = jnp.asarray(t)
    zero = jnp.zeros((), dtype=t.dtype)
    tau_in_con = jnp.minimum(tau_in_unc, xi)
    tau_out_con = jnp.minimum(tau_out_unc, xi)
    shift_in = t - xi + tau_in_con
    aw_in = jnp.where(
        shift_in >= 0, logistic_cdf(jnp.maximum(shift_in, zero), beta, x0), zero
    )
    shift_out = t - xi + tau_out_con
    aw_out = jnp.where(
        shift_out >= 0, logistic_cdf(jnp.maximum(shift_out, zero), beta, x0), zero
    )
    return aw_out - aw_in + logistic_cdf(zero, beta, x0)
