"""Implicit-function-theorem differentiation of fixed points (ISSUE 13).

Every root the pipeline computes — the buffer-crossing times where
h(τ̄) = u, the crash time ξ where AW(ξ) = κ — comes out of an iterative
bracketing solver (`core.rootfind.bisect` / `chandrupatla`). Two facts make
naive autodiff of those solvers useless:

- **Reverse mode through the iterations is wrong, not just slow.** A
  bisection iterate is a chain of midpoint SELECTIONS: as a function of the
  parameters it is piecewise constant, so backprop through the loop returns
  an exact 0 everywhere it is defined (verified in tests/test_grad.py).
  The convergence-masked `chandrupatla` is a `lax.while_loop`, which jax
  cannot reverse-differentiate at all.
- **The derivative is available for free at the fixed point.** If
  f(x*, θ) = 0 defines x*(θ) and ∂f/∂x ≠ 0 there, the implicit function
  theorem gives dx*/dθ = −(∂f/∂θ)/(∂f/∂x): ONE linearization of the
  residual at the solution, no iteration history (MPAX's differentiable-
  optimization pattern in PAPERS.md; torchode's adjoint treatment of
  adaptive solvers is the same move for ODE time-stepping).

`implicit_root` packages that as a `jax.custom_jvp` rule: the forward call
runs whatever solver the caller provides (while_loops and all — the custom
rule means jax never tries to differentiate it), and the tangent is the IFT
linear solve (scalar roots ⇒ a division). Because the JVP is linear in the
operand tangents, jax transposes it automatically, so `jax.grad`,
`jax.vmap`, `jax.jit`, and compositions all work; under `vmap` the "linear
solve" is a per-lane division, i.e. a diagonal solve across the batch.

Contract: ALL tangent-carrying inputs must flow through ``operand`` (a
pytree of arrays). ``f(x, operand)`` and ``solve(operand)`` must be pure
functions of their arguments — closure capture of traced values would leak
tracers past the custom rule.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def implicit_root(f, solve, operand, fx_floor: float | None = None):
    """x*(operand) with IFT derivatives: forward = ``solve(operand)``,
    tangent = −(∂f/∂operand · d operand)/(∂f/∂x) at x*.

    - ``f(x, operand) -> residual``: the defining equation, differentiable
      in both slots (closed-form arithmetic in every caller here).
    - ``solve(operand) -> x*``: any root-finder; never differentiated.
    - ``fx_floor``: |∂f/∂x| is floored at this magnitude (sign-preserving)
      before the division, so a genuinely ill-conditioned root (AW'(ξ) → 0
      at the withdrawal-curve peak) yields a large-but-finite tangent
      instead of Inf/NaN poison; callers flag it via `GRAD_ILL_CONDITIONED`
      from their own |∂f/∂x| check. Defaults to √tiny of the dtype: the
      division then overflows only for |∂f/∂θ| beyond max·√tiny (~3e19 in
      f32, ~1e154 in f64) — flooring at tiny itself would re-break the
      finiteness guarantee for any O(1) numerator.
    """

    @jax.custom_jvp
    def rooted(operand):
        return solve(operand)

    @rooted.defjvp
    def _rooted_jvp(primals, tangents):
        (op,), (dop,) = primals, tangents
        x = solve(op)
        fx = jax.grad(f, argnums=0)(x, op)
        floor = jnp.asarray(
            float(jnp.finfo(jnp.result_type(x)).tiny) ** 0.5
            if fx_floor is None
            else fx_floor,
            fx.dtype,
        )
        fx_safe = jnp.where(
            jnp.abs(fx) >= floor, fx, jnp.where(fx >= 0, floor, -floor)
        )
        # ∂f/∂θ · dθ without materializing the full Jacobian: one JVP of
        # the residual in the operand slot at fixed x. Linear in ``dop`` —
        # the property jax's transpose machinery needs to derive the VJP.
        _, ft_dot = jax.jvp(lambda o: f(x, o), (op,), (dop,))
        return x, -ft_dot / fx_safe

    return rooted(operand)
