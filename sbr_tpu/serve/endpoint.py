"""HTTP exposition + the query route for the serving engine (ISSUE 7
tentpole, part 3; ISSUE 11 makes workers routable).

A stdlib-only (``http.server``) thread serving four routes off an
`Engine`:

- ``/metrics``  — Prometheus text exposition 0.0.4: lifetime counters,
  rolling-window gauges (p50/p95/p99, hit rate, occupancy, divergent
  cells), the cumulative log-bucket latency histogram, and the XLA
  compile/trace counters (the acceptance gate scrapes THESE to prove zero
  post-warmup compiles — counters, not logs).
- ``/healthz``  — JSON ready/degraded/unhealthy with reasons, wired to the
  resilience retry budget and the per-window `Health` divergence state
  (`Engine.healthz`). HTTP 200 for ready/degraded, 503 for unhealthy, so
  a dumb load-balancer probe needs no JSON parsing.
- ``/statz``    — the full JSON live snapshot (same document as the
  rolling ``live.json``).
- ``/query``    — POST a JSON parameter document (``make_model_params``
  keywords, e.g. ``{"beta": 1.2, "u": 0.3}``, plus optional ``scenario``
  and ``"grads": true`` — ISSUE 13: the answer then carries IFT
  sensitivities dξ/d{β,u,κ} + ``grad_flags`` next to ξ, cached under
  their own fingerprint tag) and get one served equilibrium back,
  ``degraded``/``source`` labeled.
  The deadline rides the ``X-SBR-Deadline-Ms`` header (remaining ms —
  what the fleet router propagates); a query shed at admission gets an
  explicit ``429`` with a ``Retry-After`` header (the engine's measured
  service-time estimate), never a silently growing queue. Solver outage
  with an empty degradation ladder is a ``503``; malformed parameter
  documents are ``400``.

Distributed tracing (ISSUE 16): when ``SBR_TRACE_SAMPLE`` > 0 (or the
router already minted a trace and sent ``X-SBR-Trace-Id`` /
``X-SBR-Parent-Span``), ``/query`` owns a per-request ``worker.request``
root span, threads the `obs.trace.TraceContext` into the engine (admission
/ queue / cache / batch / dispatch child spans), echoes the trace id as a
response header AND a ``trace_id`` body field, and commits the finished
trace to the run dir's ``trace.jsonl`` — SLO-breach requests always kept
as tail-latency exemplars.

Only ``/query`` mutates engine state (it serves traffic); the other three
only read. ``port=0`` binds an ephemeral port (tests, parallel CI); the
bound port is `.port`.
"""

from __future__ import annotations

import json
import math
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from sbr_tpu.obs import trace as qtrace

# The make_model_params keywords a /query document may carry (everything
# else is 400 — a typo like "bta" must not silently serve defaults),
# including the ISSUE-14 policy knobs; ``r``/``delta`` route the document
# through make_interest_params so scenario queries with the "interest"
# modifier are expressible over the wire.
_PARAM_KEYS = (
    "beta", "eta", "eta_bar", "u", "p", "kappa", "lam", "tspan", "x0",
    "insurance_cap", "suspension_t", "lolr_rate", "r", "delta",
)


def _json_safe(value):
    """JSON floats can't carry NaN/Inf (degraded answers have NaN
    tau_bar_in); encode them as None, the cross-language convention."""
    if isinstance(value, float) and not math.isfinite(value):
        return None
    return value


def query_result_doc(result) -> dict:
    """The wire form of one `QueryResult` (shared with the router)."""
    doc = {
        "xi": _json_safe(result.xi),
        "tau_bar_in": _json_safe(result.tau_bar_in),
        "aw_max": _json_safe(result.aw_max),
        "status": int(result.status),
        "flags": int(result.flags),
        "residual": _json_safe(result.residual),
        "source": result.source,
        "degraded": bool(result.degraded),
        "scenario": result.scenario,
        "latency_ms": round(result.latency_s * 1e3, 3),
    }
    # Sensitivities (ISSUE 13): present only on grads=true answers; a
    # degraded grads answer has none (the tile cache stores no grads).
    if getattr(result, "grads", None) is not None:
        doc["grads"] = {k: _json_safe(v) for k, v in result.grads.items()}
    if getattr(result, "grad_flags", None) is not None:
        doc["grad_flags"] = int(result.grad_flags)
    return doc


class ServeEndpoint:
    """Expose ``engine`` over HTTP on a daemon thread."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0) -> None:
        self.engine = engine
        endpoint = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route access logs off stdout
                print(f"[serve.endpoint] {fmt % args}", file=sys.stderr)

            def _send(self, code: int, body: bytes, ctype: str,
                      headers=None) -> None:
                self._last_code = code
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                # Echo the trace id on EVERY response (including 429/503)
                # so a client can link a shed or failed query to its
                # waterfall without parsing the body.
                ctx = getattr(self, "_trace_ctx", None)
                if ctx is not None:
                    self.send_header(qtrace.TRACE_HEADER, ctx.trace_id)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _commit_trace(self, ctx, root_id, t0w, t0m) -> None:
                """Close + persist this request's trace: the worker-side
                root span ("worker.request", parented to the router's
                forward span when one minted the trace) and everything the
                engine attached under it. An SLO-breach request is always
                kept as a tail-latency exemplar, whatever the sampling
                verdict said."""
                try:
                    dur = time.monotonic() - t0m
                    attrs = getattr(self, "_trace_attrs", None) or {}
                    ctx.add(
                        "worker.request", t0w, dur,
                        parent=ctx.remote_parent, span_id=root_id,
                        status=getattr(self, "_last_code", None), **attrs,
                    )
                    writer = endpoint.engine.trace_writer()
                    if writer is not None:
                        slo = qtrace.slo_ms()
                        breach = slo is not None and dur * 1e3 > slo
                        writer.commit(ctx, exemplar=breach)
                except Exception:
                    pass  # tracing must never break serving

            def do_POST(self):
                t0w, t0m = time.time(), time.monotonic()
                self._trace_ctx = None
                self._trace_attrs = None
                self._last_code = None
                ctx = root_id = None
                try:
                    path = self.path.split("?", 1)[0]
                    if path != "/query":
                        self._send(404, b'{"error": "not found"}', "application/json")
                        return
                    # Distributed tracing (ISSUE 16): adopt the router's
                    # trace (header presence == sampled) or mint one on a
                    # direct hit; None when tracing is off — the zero-
                    # overhead path.
                    ctx = qtrace.from_headers(
                        self.headers.get(qtrace.TRACE_HEADER),
                        self.headers.get(qtrace.PARENT_HEADER),
                        service="worker",
                    )
                    if ctx is not None:
                        self._trace_ctx = ctx
                        root_id = ctx.alloc_id()
                        ctx.parent_id = root_id
                    try:
                        n = int(self.headers.get("Content-Length") or 0)
                        doc = json.loads(self.rfile.read(n).decode() or "{}")
                        if not isinstance(doc, dict):
                            raise ValueError("query body must be a JSON object")
                    except (ValueError, UnicodeDecodeError) as err:
                        self._send(
                            400, json.dumps({"error": f"bad query body: {err}"}).encode(),
                            "application/json",
                        )
                        return
                    deadline_ms = None
                    raw = self.headers.get("X-SBR-Deadline-Ms")
                    try:
                        if raw is not None:
                            deadline_ms = float(raw)
                        elif doc.get("deadline_ms") is not None:
                            deadline_ms = float(doc["deadline_ms"])
                    except (TypeError, ValueError):
                        self._send(400, b'{"error": "bad deadline"}', "application/json")
                        return
                    # ``scenario`` is a free-form tag (legacy) OR a composed
                    # ScenarioSpec document (ISSUE 14) — a JSON object routes
                    # the query through the scenario engine, answered and
                    # cached by spec fingerprint.
                    scenario_doc = doc.get("scenario")
                    spec = None
                    if isinstance(scenario_doc, dict):
                        from sbr_tpu.scenario import ScenarioSpec

                        try:
                            spec = ScenarioSpec.from_doc(scenario_doc)
                        except (TypeError, ValueError) as err:
                            self._send(
                                400,
                                json.dumps({"error": f"bad scenario: {err}"}).encode(),
                                "application/json",
                            )
                            return
                    scenario = (
                        "default" if spec is not None else str(scenario_doc or "default")
                    )
                    # ``population`` (ISSUE 15): a population-level what-if
                    # object — S seeds of an information model on a graph
                    # spec, answered as quantiles + run-probability, cached
                    # by infomodel fingerprint.
                    population_doc = doc.get("population")
                    if population_doc is not None and spec is not None:
                        self._send(
                            400,
                            b'{"error": "population and scenario are mutually exclusive"}',
                            "application/json",
                        )
                        return
                    grads = bool(doc.get("grads", False))
                    if population_doc is not None and grads:
                        self._send(
                            400,
                            b'{"error": "grads are not supported on population queries"}',
                            "application/json",
                        )
                        return
                    if spec is not None and grads:
                        # Gradient coverage is part of the composition
                        # matrix (grad.scenario_xi_and_grad); the serve
                        # grads route covers plain queries only — reject
                        # rather than silently dropping the request.
                        self._send(
                            400,
                            b'{"error": "grads are not supported on scenario queries"}',
                            "application/json",
                        )
                        return
                    unknown = (
                        set(doc) - set(_PARAM_KEYS)
                        - {"scenario", "deadline_ms", "grads", "population"}
                    )
                    if unknown:
                        self._send(
                            400,
                            json.dumps(
                                {"error": f"unknown parameter(s): {sorted(unknown)}"}
                            ).encode(),
                            "application/json",
                        )
                        return
                    from sbr_tpu.models.params import (
                        make_interest_params,
                        make_model_params,
                    )
                    from sbr_tpu.serve.engine import DeadlineExceeded

                    try:
                        kw = {k: doc[k] for k in _PARAM_KEYS if k in doc}
                        if "tspan" in kw:
                            kw["tspan"] = tuple(float(v) for v in kw["tspan"])
                        # Modifier-gated parameters are consumed ONLY by
                        # their modifier; on any other query the pipeline
                        # would silently ignore them while fingerprinting
                        # them — so a knob without its modifier is a loud
                        # 400, never a 200 carrying the unmodified answer.
                        gated = {
                            "r": "interest", "delta": "interest",
                            "insurance_cap": "insurance_cap",
                            "suspension_t": "suspension",
                            "lolr_rate": "lolr",
                        }
                        active = spec.modifiers if spec is not None else ()
                        orphaned = sorted(
                            k for k, mod in gated.items()
                            if k in kw and mod not in active
                        )
                        if orphaned:
                            raise ValueError(
                                f"parameter(s) {orphaned} require a scenario "
                                "object with the matching modifier(s) "
                                f"({sorted({gated[k] for k in orphaned})})"
                            )
                        maker = (
                            make_interest_params
                            if ("r" in kw or "delta" in kw)
                            else make_model_params
                        )
                        params = maker(**kw)
                    except (TypeError, ValueError) as err:
                        self._send(
                            400, json.dumps({"error": f"bad parameters: {err}"}).encode(),
                            "application/json",
                        )
                        return
                    try:
                        if population_doc is not None:
                            try:
                                rec = endpoint.engine.query_population(
                                    params, population_doc, deadline_ms=deadline_ms
                                )
                            except (TypeError, ValueError) as err:
                                # Malformed population/graph/infomodel
                                # objects are CLIENT errors — 400, never a
                                # retryable 503.
                                self._send(
                                    400,
                                    json.dumps(
                                        {"error": f"bad population query: {err}"}
                                    ).encode(),
                                    "application/json",
                                )
                                return
                            if ctx is not None:
                                rec = {**rec, "trace_id": ctx.trace_id}
                                self._trace_attrs = {"route": "population",
                                                     "source": rec.get("source")}
                            self._send(
                                200, json.dumps(rec).encode(), "application/json"
                            )
                            return
                        if spec is not None:
                            try:
                                rec = endpoint.engine.query_scenario(
                                    params, spec, deadline_ms=deadline_ms
                                )
                            except (TypeError, ValueError) as err:
                                # Spec × params incompatibility (the
                                # composition matrix): a CLIENT error —
                                # 400, never a retryable 503 the router
                                # would fail over on (no worker can ever
                                # serve it).
                                self._send(
                                    400,
                                    json.dumps(
                                        {"error": f"unservable scenario: {err}"}
                                    ).encode(),
                                    "application/json",
                                )
                                return
                            if ctx is not None:
                                rec = {**rec, "trace_id": ctx.trace_id}
                                self._trace_attrs = {"route": "scenario",
                                                     "source": rec.get("source")}
                            self._send(
                                200, json.dumps(rec).encode(), "application/json"
                            )
                            return
                        result = endpoint.engine.query(
                            params, scenario=scenario, deadline_ms=deadline_ms,
                            grads=grads, trace=ctx,
                        )
                    except DeadlineExceeded as err:
                        if ctx is not None:
                            self._trace_attrs = {"shed": True}
                        body = json.dumps(
                            {"error": "deadline", "detail": str(err),
                             "retry_after_s": err.retry_after_s}
                        ).encode()
                        self._send(
                            429, body, "application/json",
                            {"Retry-After": f"{err.retry_after_s:g}"},
                        )
                        return
                    except Exception as err:
                        # Solver down AND the degradation ladder empty: an
                        # honest 503 the router can fail over on.
                        self._send(
                            503,
                            json.dumps({"error": "dispatch failed",
                                        "detail": repr(err)}).encode(),
                            "application/json",
                        )
                        return
                    rdoc = query_result_doc(result)
                    if ctx is not None:
                        rdoc["trace_id"] = ctx.trace_id
                        self._trace_attrs = {
                            "source": result.source,
                            "degraded": True if result.degraded else None,
                        }
                    self._send(
                        200, json.dumps(rdoc).encode(), "application/json"
                    )
                except BrokenPipeError:
                    pass
                except Exception as err:  # the route must never kill serving
                    try:
                        self._send(
                            500, json.dumps({"error": repr(err)}).encode(),
                            "application/json",
                        )
                    except Exception:
                        pass
                finally:
                    if ctx is not None:
                        self._commit_trace(ctx, root_id, t0w, t0m)

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        self._send(
                            200,
                            endpoint.engine.prometheus().encode(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/healthz":
                        doc = endpoint.engine.healthz()
                        code = 503 if doc.get("status") == "unhealthy" else 200
                        self._send(code, json.dumps(doc).encode(), "application/json")
                    elif path == "/statz":
                        self._send(
                            200,
                            json.dumps(endpoint.engine.statz(), default=str).encode(),
                            "application/json",
                        )
                    else:
                        self._send(404, b'{"error": "not found"}', "application/json")
                except BrokenPipeError:
                    pass  # client went away mid-write; nothing to salvage
                except Exception as err:  # exposition must never kill serving
                    try:
                        self._send(
                            500, json.dumps({"error": repr(err)}).encode(),
                            "application/json",
                        )
                    except Exception:
                        pass

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.host = host
        self.port = int(self.httpd.server_address[1])
        self._started = False
        self._thread: threading.Thread = threading.Thread(
            target=self.httpd.serve_forever, name="sbr-serve-http", daemon=True
        )

    def start(self) -> "ServeEndpoint":
        self._started = True
        self._thread.start()
        return self

    def close(self) -> None:
        try:
            if self._started:
                # shutdown() handshakes with a RUNNING serve_forever loop;
                # calling it on a never-started server deadlocks forever
                # (socketserver's own documented trap) — so only the bound
                # socket is released on that path.
                self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:
            pass

    def __enter__(self) -> "ServeEndpoint":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
