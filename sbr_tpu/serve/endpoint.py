"""HTTP exposition for the serving engine (ISSUE 7 tentpole, part 3).

A stdlib-only (``http.server``) thread serving three read-only routes off
an `Engine`:

- ``/metrics``  — Prometheus text exposition 0.0.4: lifetime counters,
  rolling-window gauges (p50/p95/p99, hit rate, occupancy, divergent
  cells), the cumulative log-bucket latency histogram, and the XLA
  compile/trace counters (the acceptance gate scrapes THESE to prove zero
  post-warmup compiles — counters, not logs).
- ``/healthz``  — JSON ready/degraded/unhealthy with reasons, wired to the
  resilience retry budget and the per-window `Health` divergence state
  (`Engine.healthz`). HTTP 200 for ready/degraded, 503 for unhealthy, so
  a dumb load-balancer probe needs no JSON parsing.
- ``/statz``    — the full JSON live snapshot (same document as the
  rolling ``live.json``).

No jax import, no engine mutation: handlers only read. ``port=0`` binds
an ephemeral port (tests, parallel CI); the bound port is `.port`.
"""

from __future__ import annotations

import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class ServeEndpoint:
    """Expose ``engine`` over HTTP on a daemon thread."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0) -> None:
        self.engine = engine
        endpoint = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route access logs off stdout
                print(f"[serve.endpoint] {fmt % args}", file=sys.stderr)

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        self._send(
                            200,
                            endpoint.engine.prometheus().encode(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/healthz":
                        doc = endpoint.engine.healthz()
                        code = 503 if doc.get("status") == "unhealthy" else 200
                        self._send(code, json.dumps(doc).encode(), "application/json")
                    elif path == "/statz":
                        self._send(
                            200,
                            json.dumps(endpoint.engine.statz(), default=str).encode(),
                            "application/json",
                        )
                    else:
                        self._send(404, b'{"error": "not found"}', "application/json")
                except BrokenPipeError:
                    pass  # client went away mid-write; nothing to salvage
                except Exception as err:  # exposition must never kill serving
                    try:
                        self._send(
                            500, json.dumps({"error": repr(err)}).encode(),
                            "application/json",
                        )
                    except Exception:
                        pass

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.host = host
        self.port = int(self.httpd.server_address[1])
        self._started = False
        self._thread: threading.Thread = threading.Thread(
            target=self.httpd.serve_forever, name="sbr-serve-http", daemon=True
        )

    def start(self) -> "ServeEndpoint":
        self._started = True
        self._thread.start()
        return self

    def close(self) -> None:
        try:
            if self._started:
                # shutdown() handshakes with a RUNNING serve_forever loop;
                # calling it on a never-started server deadlocks forever
                # (socketserver's own documented trap) — so only the bound
                # socket is released on that path.
                self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:
            pass

    def __enter__(self) -> "ServeEndpoint":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
