"""Fleet router: throughput-weighted query routing with failover, hedged
retries, deadline propagation, and per-worker circuit breaking (ISSUE 11
tentpole, part 2).

The same stdlib-http shape as `serve.endpoint` — one daemon-thread
``ThreadingHTTPServer`` — but in front of N workers instead of one engine:

- **Routing.** The worker table comes from the shared fleet dir's
  heartbeats (`serve.fleet.live_workers`, refreshed at most every
  ``SBR_FLEET_POLL_S``). Each worker carries an EWMA service-latency
  estimate, seeded from the perf history's ``serve_p50_ms``
  (`obs.history.recent_median`) so a cold router starts from the
  fleet-typical rate, then updated from measured forwards. A query goes
  to the admissible (breaker-closed, heartbeat-live) worker with the
  lowest projected finish time ``ewma_ms × (1 + inflight)`` — fast idle
  workers absorb proportionally more traffic, ties break by host id so
  every router instance ranks identically.
- **Failover.** A forward that fails (connection refused/reset, timeout,
  worker 5xx) records a breaker failure and re-dispatches to the next
  best worker — at-most-once side effects are structural: results are
  pure and fingerprint-keyed, so a duplicate dispatch can only produce
  the identical bytes (the chaos fleet smoke asserts byte-identity under
  a mid-run worker kill). A worker 429 is NOT failed over: shedding is
  deliberate backpressure and re-trying it elsewhere would defeat it.
- **Hedging.** With ``SBR_ROUTER_HEDGE_MS`` set, a forward that has not
  returned within the hedge budget launches ONE secondary request on the
  next-best peer; the first response wins, and exactly one latency
  sample is recorded per query (hedged wins never double-count — tested
  deadline semantics).
- **Deadlines.** The client's deadline (``X-SBR-Deadline-Ms`` header or
  body field, else ``SBR_SERVE_DEADLINE_MS``) is decremented by elapsed
  routing time and propagated to the worker on every attempt; expired
  deadlines shed with 429 + ``Retry-After`` at the router without
  touching a worker.
- **Breakers.** One `serve.fleet.CircuitBreaker` per worker: consecutive
  forward failures open it (the worker stops absorbing traffic), a
  cooldown later one half-open probe decides. Transitions are obs
  ``fleet`` events and `/healthz` degraded reasons.

Telemetry: every failover/hedge/shed/loss is an obs ``fleet`` event
(manifest roll-up included), and a rolling ``fleet.json`` snapshot lands
in the run dir via `RunContext.live_snapshot` — ``python -m
sbr_tpu.obs.report fleet RUN_DIR`` renders and GATES it (exit 1 on lost
queries or a breaker stuck open).

No jax import anywhere: the router is pure host networking and runs on
boxes that must never wake an accelerator backend.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from sbr_tpu.obs import trace as qtrace
from sbr_tpu.obs.metrics import DEFAULT_LATENCY_BOUNDS_MS, LogHistogram
from sbr_tpu.serve.fleet import (
    CircuitBreaker,
    _env_float,
    default_deadline_ms,
    live_workers,
)

SCHEMA = "sbr-fleet/1"


class _Worker:
    """Router-side view of one fleet worker."""

    __slots__ = ("host", "url", "ewma_ms", "inflight", "breaker", "forwards",
                 "failures", "last_hb", "quarantined")

    def __init__(self, host: str, url: str, ewma_ms: float, breaker: CircuitBreaker):
        self.host = host
        self.url = url
        self.ewma_ms = ewma_ms
        self.inflight = 0
        self.breaker = breaker
        self.forwards = 0
        self.failures = 0
        self.last_hb: dict = {}
        # Numerics-audit quarantine (ISSUE 17): set while the worker's
        # heartbeat reports audit status "drift" — treated exactly like an
        # open breaker by _candidates (routed around, capacity not
        # correctness), cleared only when a heartbeat reads clean again
        # (drift latches worker-side, so in practice: a worker restart).
        self.quarantined = False

    def score(self) -> float:
        return self.ewma_ms * (1.0 + self.inflight)


class _ForwardError(RuntimeError):
    """One failed forward attempt (connection error, timeout, worker 5xx)."""


class _Shed(RuntimeError):
    """A 429 from admission (router-side deadline check or a worker)."""

    def __init__(self, msg: str, retry_after_s: float = 0.1) -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class Router:
    """The fleet front door (see module docstring).

    Construction binds the listen socket; `start()` serves on a daemon
    thread. ``run_dir`` starts an owned obs run (like `Engine`) whose
    events + rolling ``fleet.json`` are what ``report fleet`` gates.
    """

    def __init__(self, fleet_root, host: str = "127.0.0.1", port: int = 0,
                 run=None, run_dir: Optional[str] = None,
                 poll_s: Optional[float] = None,
                 hedge_ms: Optional[float] = None,
                 forward_timeout_s: Optional[float] = None) -> None:
        self.fleet_root = fleet_root
        self.poll_s = poll_s if poll_s is not None else _env_float("SBR_FLEET_POLL_S", 0.5)
        self.hedge_ms = hedge_ms if hedge_ms is not None else _env_float("SBR_ROUTER_HEDGE_MS", None)
        self.forward_timeout_s = (
            forward_timeout_s if forward_timeout_s is not None
            else _env_float("SBR_ROUTER_TIMEOUT_S", 30.0)
        )
        self.default_deadline_ms = default_deadline_ms()

        self._owned_run = None
        if run is None and run_dir is not None:
            from sbr_tpu import obs

            run = self._owned_run = obs.start_run(label="router", run_dir=run_dir)
        if run is None:
            from sbr_tpu import obs

            run = obs.active_run()
        self._run = run

        self._workers: Dict[str, _Worker] = {}
        self._workers_lock = threading.Lock()
        self._scanned_at = 0.0
        self._seed_ms = self._seed_latency_ms()

        # Counters under a lock: unlike LiveMetrics (whose hot path runs on
        # the single batcher thread and tolerates a dropped WINDOW count),
        # these are mutated by N concurrent handler threads and "failed"
        # gates the zero-lost-queries contract — a torn increment there
        # would be a silent pass on a run that lost a query.
        self.counters: Dict[str, int] = {
            k: 0
            for k in (
                "queries", "completed", "failed", "shed", "degraded",
                "failover", "hedged", "hedge_wins", "forward_errors",
                "client_errors",
            )
        }
        self._counters_lock = threading.Lock()
        self.latency_hist = LogHistogram(DEFAULT_LATENCY_BOUNDS_MS)
        self.started_at = time.time()
        self._last_write = 0.0

        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                print(f"[serve.router] {fmt % args}", file=sys.stderr)

            def _send(self, code: int, body: bytes, ctype="application/json",
                      headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                try:
                    if self.path.split("?", 1)[0] != "/query":
                        self._send(404, b'{"error": "not found"}')
                        return
                    n = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(n)
                    code, out, headers = router.handle_query(
                        body, self.headers.get("X-SBR-Deadline-Ms"),
                        trace_header=self.headers.get(qtrace.TRACE_HEADER),
                        parent_header=self.headers.get(qtrace.PARENT_HEADER),
                    )
                    self._send(code, out, headers=headers)
                except BrokenPipeError:
                    pass
                except Exception as err:
                    try:
                        self._send(500, json.dumps({"error": repr(err)}).encode())
                    except Exception:
                        pass

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/healthz":
                        doc = router.healthz()
                        code = 503 if doc["status"] == "unhealthy" else 200
                        self._send(code, json.dumps(doc).encode())
                    elif path == "/statz":
                        self._send(200, json.dumps(router.statz(), default=str).encode())
                    elif path == "/metrics":
                        self._send(
                            200, router.prometheus().encode(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    else:
                        self._send(404, b'{"error": "not found"}')
                except BrokenPipeError:
                    pass
                except Exception as err:
                    try:
                        self._send(500, json.dumps({"error": repr(err)}).encode())
                    except Exception:
                        pass

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.host = host
        self.port = int(self.httpd.server_address[1])
        self._started = False
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="sbr-router-http", daemon=True
        )

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Router":
        self._started = True
        self._thread.start()
        return self

    def close(self) -> None:
        try:
            if self._started:
                self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:
            pass
        self._write_fleet_snapshot(force=True)
        if self._owned_run is not None:
            from sbr_tpu.obs import runlog

            runlog._finalize_if_active(self._owned_run)

    def __enter__(self) -> "Router":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- membership / cost model ---------------------------------------------
    def _seed_latency_ms(self) -> float:
        """Cold-start EWMA seed: the fleet-typical serve p50 from the perf
        history (`history.recent_median`), else 50 ms. Deterministic — the
        same history seeds every router instance identically."""
        try:
            from sbr_tpu.obs import history

            med = history.recent_median("serve_p50_ms")
            if med is not None and med > 0:
                return float(med)
        except Exception:
            pass
        return 50.0

    def refresh_workers(self, force: bool = False) -> None:
        """Sync the worker table with the fleet dir's live heartbeats (at
        most every ``poll_s`` unless forced). Vanished heartbeats (expired
        TTL or a graceful drain's withdrawal) drop the worker — reclaim is
        instant for drained workers, TTL-bounded for silent deaths."""
        now = time.monotonic()
        if not force and now - self._scanned_at < self.poll_s:
            return
        with self._workers_lock:
            if not force and now - self._scanned_at < self.poll_s:
                return
            self._scanned_at = now
            live = live_workers(self.fleet_root)
            for host, rec in live.items():
                w = self._workers.get(host)
                if w is None:
                    w = self._workers[host] = _Worker(
                        host, str(rec["url"]), self._seed_ms,
                        CircuitBreaker(
                            on_transition=self._breaker_logger(host)
                        ),
                    )
                    self._log_fleet("worker_join", worker=host, url=w.url)
                w.url = str(rec["url"])
                w.last_hb = rec
                drifted = (rec.get("audit") or {}).get("status") == "drift"
                if drifted and not w.quarantined:
                    w.quarantined = True
                    self._log_fleet(
                        "audit_quarantine", worker=host,
                        probes=",".join(
                            (rec.get("audit") or {}).get("drift_probes") or []
                        ),
                    )
                elif w.quarantined and not drifted:
                    w.quarantined = False
                    self._log_fleet("audit_unquarantine", worker=host)
            for host in list(self._workers):
                if host not in live:
                    self._log_fleet("worker_lost", worker=host)
                    del self._workers[host]

    def _breaker_logger(self, host: str):
        def on_transition(old: str, new: str) -> None:
            self._log_fleet(f"breaker_{new}", worker=host, previous=old)

        return on_transition

    def _candidates(self, exclude=()) -> list:
        """Admissible workers, best first (see module docstring). Uses the
        breaker's side-effect-free `admissible()` — `allow()` (which grants
        the single half-open probe) is called by `_forward` only for the
        worker actually sent to, so ranking can never strand a breaker in
        half-open with a probe nobody owns."""
        self.refresh_workers()
        with self._workers_lock:
            workers = [
                w for h, w in self._workers.items() if h not in exclude
            ]
        # An audit-quarantined worker is handled like an open breaker:
        # numerically drifted answers must not reach clients, so the
        # worker keeps serving canaries but receives no queries.
        admissible = [
            w for w in workers if w.breaker.admissible() and not w.quarantined
        ]
        return sorted(admissible, key=lambda w: (w.score(), w.host))

    # -- the query path ------------------------------------------------------
    def handle_query(self, body: bytes, deadline_header: Optional[str],
                     trace_header: Optional[str] = None,
                     parent_header: Optional[str] = None) -> tuple:
        """Route one query; returns (status_code, body_bytes, headers).

        Distributed tracing (ISSUE 16): the router MINTS the trace here
        (or adopts an inbound ``X-SBR-Trace-Id``), owns the
        ``router.request`` root span, propagates the id on every forward,
        and commits the finished trace to its own run dir — SLO breaches
        (``SBR_SERVE_SLO_MS`` as resolved at the router) always kept as
        tail-latency exemplars. The trace id is echoed as a response
        header on every outcome, including sheds and failures."""
        ctx = qtrace.from_headers(trace_header, parent_header, service="router")
        root_id = ctx.alloc_id() if ctx is not None else None
        t0w, t0 = time.time(), time.monotonic()
        code, out, headers = self._handle_routed(body, deadline_header, t0,
                                                 ctx, root_id)
        if ctx is not None:
            dur = time.monotonic() - t0
            outcome = (
                "completed" if code == 200
                else "shed" if code == 429
                else "client_error" if 400 <= code < 500
                else "failed"
            )
            ctx.add("router.request", t0w, dur, parent=ctx.remote_parent,
                    span_id=root_id, status=code, outcome=outcome)
            try:
                writer = qtrace.writer_for(self._run)
                if writer is not None:
                    slo = qtrace.slo_ms()
                    breach = slo is not None and dur * 1e3 > slo
                    writer.commit(ctx, exemplar=breach)
            except Exception:
                pass  # tracing must never break routing
            headers = dict(headers)
            headers[qtrace.TRACE_HEADER] = ctx.trace_id
        return code, out, headers

    def _handle_routed(self, body: bytes, deadline_header: Optional[str],
                       t0: float, trace=None, parent=None) -> tuple:
        """The routing body behind `handle_query` (deadline resolution,
        failover loop, counters); returns (status_code, body_bytes,
        headers)."""
        self._inc("queries")
        deadline_ms = None
        try:
            if deadline_header is not None:
                deadline_ms = float(deadline_header)
            else:
                try:
                    doc = json.loads(body.decode() or "{}")
                    if isinstance(doc, dict) and doc.get("deadline_ms") is not None:
                        deadline_ms = float(doc["deadline_ms"])
                except (ValueError, UnicodeDecodeError):
                    pass  # the worker 400s malformed bodies — not our job
        except (TypeError, ValueError):
            # A malformed header is the CLIENT's error, never a routing
            # loss — `report fleet` gates on "failed", and one typo must
            # not trip a zero-lost-queries gate on a healthy fleet.
            self._inc("client_errors")
            return 400, b'{"error": "bad deadline"}', {}
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline = (
            t0 + deadline_ms / 1e3 if deadline_ms is not None else None
        )

        try:
            code, out = self._route(body, deadline, t0, trace=trace,
                                    parent=parent)
        except _Shed as err:
            self._inc("shed")
            self._log_fleet("shed", reason=str(err))
            self._write_fleet_snapshot()
            return (
                429,
                json.dumps({"error": "deadline", "detail": str(err),
                            "retry_after_s": err.retry_after_s}).encode(),
                {"Retry-After": f"{err.retry_after_s:g}"},
            )
        except Exception as err:
            self._inc("failed")
            self._log_fleet("lost", error=repr(err))
            self._write_fleet_snapshot()
            return 503, json.dumps({"error": repr(err)}).encode(), {}
        if 400 <= code < 500:
            # Worker-answered client error, passed through verbatim: not a
            # completion, not a loss, and not a latency sample.
            self._inc("client_errors")
            self._write_fleet_snapshot()
            return code, out, {}
        self._inc("completed")
        try:
            if json.loads(out.decode()).get("degraded"):
                self._inc("degraded")
        except (ValueError, UnicodeDecodeError, AttributeError):
            pass
        # Exactly ONE latency sample per query — a hedged win must not
        # record both racers (tested deadline/hedging semantics).
        self.latency_hist.record((time.monotonic() - t0) * 1e3)
        self._write_fleet_snapshot()
        return code, out, {}

    def _remaining_ms(self, deadline: Optional[float]) -> Optional[float]:
        if deadline is None:
            return None
        return (deadline - time.monotonic()) * 1e3

    def _route(self, body: bytes, deadline: Optional[float], t0: float,
               trace=None, parent=None) -> tuple:
        """Failover loop: try admissible workers best-first until one
        answers, hedging stragglers when configured."""
        remaining = self._remaining_ms(deadline)
        if remaining is not None and remaining <= 0:
            raise _Shed("deadline expired before routing", retry_after_s=0.1)
        tried: set = set()
        last_err: Optional[Exception] = None
        while True:
            candidates = self._candidates(exclude=tried)
            if not candidates:
                if last_err is not None:
                    raise last_err
                raise RuntimeError("no admissible fleet workers")
            worker = candidates[0]
            hedge_peer = candidates[1] if len(candidates) > 1 else None
            tried.add(worker.host)
            try:
                if self.hedge_ms is not None and hedge_peer is not None:
                    code, out = self._forward_hedged(
                        worker, hedge_peer, body, deadline,
                        trace=trace, parent=parent,
                    )
                else:
                    code, out = self._forward(worker, body, deadline,
                                              trace=trace, parent=parent)
            except _Shed:
                raise
            except Exception as err:
                last_err = err
                self._inc("forward_errors")
                if self._candidates(exclude=tried):
                    self._inc("failover")
                    self._log_fleet(
                        "failover", worker=worker.host, error=repr(err),
                    )
                    continue
                raise
            return code, out

    def _forward(self, worker: _Worker, body: bytes,
                 deadline: Optional[float], trace=None, parent=None,
                 role: Optional[str] = None) -> tuple:
        """One forward attempt to one worker; raises `_ForwardError` on
        anything failover-able, `_Shed` on a worker 429.

        With ``trace`` set, the attempt propagates ``X-SBR-Trace-Id`` (and
        this span's id as ``X-SBR-Parent-Span`` — the worker's request span
        parents to it) and records one ``router.forward`` span per attempt,
        outcome-labeled, so the aggregator sees failover and hedge
        causality per query. ``role`` labels hedge racers
        ("primary"/"hedge")."""
        from sbr_tpu.resilience import faults
        from sbr_tpu.resilience.faults import InjectedFault

        remaining = self._remaining_ms(deadline)
        if remaining is not None and remaining <= 0:
            raise _Shed("deadline expired mid-routing", retry_after_s=0.1)
        # Take the breaker's admission at SEND time (this may be the one
        # half-open probe — from here on this forward owes an outcome). A
        # False means another thread won the probe between ranking and
        # sending: move on without charging a failure.
        if not worker.breaker.allow():
            raise _ForwardError(f"worker {worker.host} breaker not admitting")
        timeout = self.forward_timeout_s
        if remaining is not None:
            timeout = min(timeout, max(remaining / 1e3, 0.05))
        headers = {"Content-Type": "application/json"}
        if remaining is not None:
            headers["X-SBR-Deadline-Ms"] = f"{remaining:g}"
        fid = None
        if trace is not None:
            fid = trace.alloc_id()
            headers[qtrace.TRACE_HEADER] = trace.trace_id
            headers[qtrace.PARENT_HEADER] = fid
        req = urllib.request.Request(
            worker.url + "/query", data=body, headers=headers, method="POST"
        )
        worker.inflight += 1
        worker.forwards += 1
        t0w, t0 = time.time(), time.monotonic()
        outcome, err_name, code = "ok", None, None
        try:
            faults.fire("router.forward", target=worker.host)
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                out = resp.read()
                code = resp.status
        except InjectedFault as err:
            outcome, err_name = "error", "injected"
            worker.failures += 1
            worker.breaker.record_failure()
            raise _ForwardError(f"injected forward fault: {err}") from err
        except urllib.error.HTTPError as err:
            body_bytes = err.read()
            code = err.code
            if err.code == 429:
                # Backpressure is deliberate: pass it through, don't dodge
                # it by hammering a peer with the same unmeetable deadline.
                outcome = "shed"
                retry_after = 0.1
                try:
                    retry_after = float(err.headers.get("Retry-After") or 0.1)
                except (TypeError, ValueError):
                    pass
                worker.breaker.record_success()  # the worker is healthy
                raise _Shed(
                    f"worker {worker.host} shed the query",
                    retry_after_s=retry_after,
                ) from err
            if 400 <= err.code < 500:
                # A client error (bad params, bad body) is the CLIENT's
                # fault: re-sending the same bytes to a peer would 4xx
                # everywhere, charge every breaker, and finally read as a
                # "lost" query on a healthy fleet. Pass it through — the
                # worker answered correctly.
                outcome = "client_error"
                worker.breaker.record_success()
                return err.code, body_bytes
            outcome, err_name = "error", f"http_{err.code}"
            worker.failures += 1
            worker.breaker.record_failure()
            raise _ForwardError(
                f"worker {worker.host} returned {err.code}: "
                f"{body_bytes[:200]!r}"
            ) from err
        except (OSError, urllib.error.URLError) as err:
            rem = self._remaining_ms(deadline)
            if rem is not None and rem <= 1.0:
                # The QUERY's deadline clamped this forward's timeout and
                # has now run out: the deadline expired in flight — that is
                # the client's budget, not evidence against the worker.
                # Charging the breaker here would let a burst of
                # tight-deadline traffic open breakers on healthy workers;
                # crediting a success would be equally unearned — release
                # any held probe with no verdict.
                outcome, err_name = "shed", "deadline_in_flight"
                worker.breaker.record_abandoned()
                raise _Shed(
                    f"deadline exhausted in flight on {worker.host}",
                    retry_after_s=0.1,
                ) from err
            outcome, err_name = "error", type(err).__name__
            worker.failures += 1
            worker.breaker.record_failure()
            raise _ForwardError(f"worker {worker.host} unreachable: {err}") from err
        finally:
            worker.inflight = max(worker.inflight - 1, 0)
            if trace is not None:
                trace.add(
                    "router.forward", t0w, time.monotonic() - t0,
                    parent=parent, span_id=fid, worker=worker.host,
                    outcome=outcome, status=code, role=role, error=err_name,
                )
        worker.breaker.record_success()
        dur_ms = (time.monotonic() - t0) * 1e3
        worker.ewma_ms = 0.3 * dur_ms + 0.7 * worker.ewma_ms
        return code, out

    def _forward_hedged(self, worker: _Worker, peer: _Worker, body: bytes,
                        deadline: Optional[float], trace=None,
                        parent=None) -> tuple:
        """Primary forward with one hedge: if the primary hasn't answered
        within ``hedge_ms``, race a secondary on ``peer``; first response
        wins. The loser is abandoned (its duplicate dispatch is benign —
        results are pure and fingerprint-keyed)."""
        import queue as _queue

        outcomes: "_queue.Queue" = _queue.Queue()

        def attempt(w: _Worker, role: str) -> None:
            try:
                code, out = self._forward(w, body, deadline,
                                          trace=trace, parent=parent,
                                          role=role)
            except Exception as err:  # noqa: BLE001 — collected, not dropped
                outcomes.put(("error", err, w, role))
            else:
                outcomes.put(("ok", (code, out), w, role))

        threading.Thread(target=attempt, args=(worker, "primary"), daemon=True).start()
        try:
            first = outcomes.get(timeout=self.hedge_ms / 1e3)
        except _queue.Empty:
            first = None
        if first is not None:
            kind, payload, _, _ = first
            if kind == "ok":
                return payload
            raise payload
        # Primary is a straggler: hedge on the peer; first response wins,
        # the loser is abandoned (benign duplicate — pure results).
        self._inc("hedged")
        self._log_fleet("hedge", worker=worker.host, peer=peer.host)
        threading.Thread(target=attempt, args=(peer, "hedge"), daemon=True).start()
        first = outcomes.get()
        kind, payload, w, role = first
        if kind == "error":
            # The first finisher failed; the other racer decides.
            kind, payload, w, role = outcomes.get()
        if kind == "ok":
            if role == "hedge":
                self._inc("hedge_wins")
                self._log_fleet("hedge_win", worker=w.host)
            return payload
        raise payload

    # -- exposition ----------------------------------------------------------
    def healthz(self) -> dict:
        self.refresh_workers()
        with self._workers_lock:
            workers = dict(self._workers)
        open_breakers = [h for h, w in workers.items() if w.breaker.state == "open"]
        quarantined = [h for h, w in workers.items() if w.quarantined]
        routable = [
            h for h, w in workers.items()
            if w.breaker.state != "open" and not w.quarantined
        ]
        reasons = []
        status = "ready"
        if not routable:
            status = "unhealthy"
            reasons.append("no routable workers")
        elif open_breakers:
            status = "degraded"
            reasons.append(f"breaker open for: {', '.join(sorted(open_breakers))}")
        if quarantined and status != "unhealthy":
            status = "degraded"
            reasons.append(
                f"audit quarantine for: {', '.join(sorted(quarantined))}"
            )
        if self.counters["failed"]:
            status = "unhealthy" if status == "unhealthy" else "degraded"
            reasons.append(f"{self.counters['failed']} lost quer(ies)")
        return {"status": status, "reasons": reasons,
                "workers": len(workers), "routable": len(routable),
                "quarantined": len(quarantined)}

    def statz(self) -> dict:
        self.refresh_workers()
        with self._workers_lock:
            workers = {
                h: {
                    "url": w.url,
                    "ewma_ms": round(w.ewma_ms, 3),
                    "inflight": w.inflight,
                    "forwards": w.forwards,
                    "failures": w.failures,
                    "breaker": w.breaker.state,
                    "breaker_age_s": (
                        round(w.breaker.age_s(), 3)
                        if w.breaker.age_s() is not None else None
                    ),
                    "healthz": (w.last_hb or {}).get("healthz"),
                    "qps": (w.last_hb or {}).get("qps"),
                    "quarantined": w.quarantined,
                    "audit": (w.last_hb or {}).get("audit"),
                    "prewarm": (w.last_hb or {}).get("prewarm"),
                    "flight": (w.last_hb or {}).get("flight"),
                }
                for h, w in self._workers.items()
            }
        fleet_demand = self.fleet_demand()
        fleet_prewarm = self.fleet_prewarm()
        fleet_flight = self.fleet_flight()
        return {
            "schema": SCHEMA,
            "ts": round(time.time(), 3),
            "started_at": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.localtime(self.started_at)
            ),
            "counters": dict(self.counters),
            "latency_ms": self.latency_hist.summary(),
            "workers": workers,
            "healthz": self.healthz(),
            "hedge_ms": self.hedge_ms,
            "default_deadline_ms": self.default_deadline_ms,
            # Fleet demand surface (ISSUE 18): absent — not null-valued —
            # when no worker heartbeats a demand block, so a demand-off
            # fleet's /statz and fleet.json stay byte-free of the key.
            **({"demand": fleet_demand} if fleet_demand is not None else {}),
            # Fleet prewarm roll-up (ISSUE 19): same absent-when-off
            # contract — a prewarm-off fleet's /statz stays byte-free.
            **({"prewarm": fleet_prewarm} if fleet_prewarm is not None else {}),
            # Fleet utilization roll-up (ISSUE 20): same absent-when-off
            # contract — a flight-off fleet's /statz stays byte-free.
            **({"flight": fleet_flight} if fleet_flight is not None else {}),
        }

    def fleet_flight(self) -> "Optional[dict]":
        """Roll the workers' heartbeat flight blocks (ISSUE 20) up into
        one fleet utilization view: dispatch-weighted mean device-busy /
        host-gap fractions plus summed dispatch and dropped-record
        counts. Returns None when no worker published a block — a
        flight-off fleet keeps the structural no-op."""
        with self._workers_lock:
            blocks = [
                ((w.last_hb or {}).get("flight"), h)
                for h, w in sorted(self._workers.items())
            ]
        blocks = [(b, h) for b, h in blocks if isinstance(b, dict)]
        if not blocks:
            return None
        dispatches = sum(int(b.get("dispatches") or 0) for b, _ in blocks)
        dropped = sum(int(b.get("dropped_records") or 0) for b, _ in blocks)
        weighted = [
            (float(b["device_busy_frac"]),
             max(int(b.get("dispatches") or 0), 1))
            for b, _ in blocks if b.get("device_busy_frac") is not None
        ]
        busy = None
        if weighted:
            wsum = sum(w for _, w in weighted)
            busy = round(sum(f * w for f, w in weighted) / wsum, 4)
        return {
            "workers": [h for _, h in blocks],
            "dispatches": dispatches,
            "dropped_records": dropped,
            "device_busy_frac": busy,
            "host_gap_frac": (
                round(1.0 - busy, 4) if busy is not None else None
            ),
        }

    def fleet_prewarm(self) -> "Optional[dict]":
        """Roll the workers' heartbeat prewarm blocks (ISSUE 19) up into
        one fleet view: per-plan progress summed across sweepers plus the
        worst per-worker status. Returns None when no worker published a
        block — a prewarm-off fleet keeps the structural no-op."""
        with self._workers_lock:
            blocks = [
                ((w.last_hb or {}).get("prewarm"), h)
                for h, w in sorted(self._workers.items())
            ]
        blocks = [(b, h) for b, h in blocks if isinstance(b, dict)]
        if not blocks:
            return None
        plans: Dict[str, dict] = {}
        worst, worst_rank = "idle", 0
        ranks = {"rejected": 3, "budget_exhausted": 3, "sweeping": 2,
                 "done": 1, "no_cache": 1, "idle": 0}
        for b, host in blocks:
            status = str(b.get("status") or "idle")
            if ranks.get(status, 0) > worst_rank:
                worst, worst_rank = status, ranks.get(status, 0)
            fp = b.get("plan")
            if fp:
                p = plans.setdefault(
                    str(fp),
                    {"tiles_done": 0, "tiles_total": 0, "abandoned": 0,
                     "workers": []},
                )
                p["tiles_done"] += int(b.get("tiles_done") or 0)
                p["tiles_total"] = max(
                    p["tiles_total"], int(b.get("tiles_total") or 0)
                )
                p["abandoned"] += int(b.get("abandoned") or 0)
                p["workers"].append(host)
        return {
            "status": worst,
            "workers": [h for _, h in blocks],
            "plans": plans,
        }

    def fleet_demand(self) -> "Optional[dict]":
        """Merge the workers' heartbeat demand surfaces (ISSUE 18) into
        the fleet demand surface — the prefetch advisor's fleet-wide
        input. Workers are folded in sorted-host order, so the merged
        sketch is deterministic for a given set of heartbeats. Returns
        None (and, deliberately, does not even import `obs.demand`) when
        no worker published a block: a demand-off fleet keeps the
        structural no-op."""
        with self._workers_lock:
            blocks = [
                ((w.last_hb or {}).get("demand"), h)
                for h, w in sorted(self._workers.items())
            ]
        blocks = [(b, h) for b, h in blocks if isinstance(b, dict)]
        if not blocks:
            return None
        from sbr_tpu.obs import demand as _demand

        merged = _demand.merge_surfaces([b for b, _ in blocks])
        merged["workers"] = [h for _, h in blocks]
        merged["hot_bins"] = _demand.hot_bins(merged)
        return merged

    def prometheus(self) -> str:
        lines = []
        for k, v in sorted(self.counters.items()):
            name = f"sbr_fleet_{k}_total"
            lines += [f"# TYPE {name} counter", f"{name} {int(v)}"]
        with self._workers_lock:
            workers = dict(self._workers)
        lines.append("# TYPE sbr_fleet_workers gauge")
        lines.append(f"sbr_fleet_workers {len(workers)}")
        lines.append("# TYPE sbr_fleet_worker_ewma_ms gauge")
        for h, w in sorted(workers.items()):
            lines.append(f'sbr_fleet_worker_ewma_ms{{worker="{h}"}} {w.ewma_ms:g}')
        lines += self.latency_hist.to_prometheus("sbr_fleet_latency_ms")
        # Fleet demand surface gauges (ISSUE 18): appended only when some
        # worker heartbeats a demand block — a demand-off fleet's
        # exposition stays byte-free of sbr_demand.
        demand = self.fleet_demand()
        if demand is not None:
            hot = demand.get("hot_bins") or []
            hot_q = sum(h.get("count", 0) for h in hot)
            hot_warm = sum(h.get("warm", 0) for h in hot)
            cov = hot_warm / hot_q if hot_q else 0.0
            lines += [
                "# TYPE sbr_demand_fleet_window_queries gauge",
                f"sbr_demand_fleet_window_queries {int(demand.get('queries') or 0)}",
                "# TYPE sbr_demand_fleet_workers gauge",
                f"sbr_demand_fleet_workers {len(demand.get('workers') or [])}",
                "# TYPE sbr_demand_fleet_hot_bins gauge",
                f"sbr_demand_fleet_hot_bins {len(hot)}",
                "# TYPE sbr_demand_fleet_hot_warm_coverage gauge",
                f"sbr_demand_fleet_hot_warm_coverage {cov:g}",
            ]
        # Fleet prewarm gauges (ISSUE 19): same byte-free-when-off rule.
        prewarm = self.fleet_prewarm()
        if prewarm is not None:
            plans = prewarm.get("plans") or {}
            lines += [
                "# TYPE sbr_prewarm_fleet_workers gauge",
                f"sbr_prewarm_fleet_workers {len(prewarm.get('workers') or [])}",
                "# TYPE sbr_prewarm_fleet_tiles_done gauge",
                "sbr_prewarm_fleet_tiles_done "
                f"{sum(p['tiles_done'] for p in plans.values())}",
                "# TYPE sbr_prewarm_fleet_tiles_abandoned gauge",
                "sbr_prewarm_fleet_tiles_abandoned "
                f"{sum(p['abandoned'] for p in plans.values())}",
            ]
        # Fleet utilization gauges (ISSUE 20): same byte-free-when-off rule.
        flight = self.fleet_flight()
        if flight is not None:
            busy = flight.get("device_busy_frac")
            lines += [
                "# TYPE sbr_flight_fleet_workers gauge",
                f"sbr_flight_fleet_workers {len(flight.get('workers') or [])}",
                "# TYPE sbr_flight_fleet_dispatches gauge",
                f"sbr_flight_fleet_dispatches {int(flight.get('dispatches') or 0)}",
                "# TYPE sbr_flight_fleet_dropped_records gauge",
                "sbr_flight_fleet_dropped_records "
                f"{int(flight.get('dropped_records') or 0)}",
                "# TYPE sbr_flight_fleet_device_busy_frac gauge",
                f"sbr_flight_fleet_device_busy_frac "
                f"{busy if busy is not None else 0:g}",
            ]
        return "\n".join(lines) + "\n"


    def _inc(self, name: str, n: int = 1) -> None:
        """Locked counter increment (see the counters comment in __init__)."""
        with self._counters_lock:
            self.counters[name] = self.counters.get(name, 0) + n

    # -- telemetry -----------------------------------------------------------
    def _log_fleet(self, action: str, **fields) -> None:
        if self._run is None:
            return
        try:
            self._run.log_fleet(action, **fields)
        except Exception:
            pass

    def _write_fleet_snapshot(self, force: bool = False,
                              min_interval_s: float = 0.5) -> None:
        """Rolling ``fleet.json`` in the run dir (atomic rename via
        `live_snapshot`) — what ``report fleet`` reads on a RUNNING or
        finished router."""
        if self._run is None:
            return
        now = time.monotonic()
        if not force and now - self._last_write < min_interval_s:
            return
        self._last_write = now
        try:
            self._run.live_snapshot(self.statz(), name="fleet.json")
        except Exception:
            pass


def main(argv=None) -> int:
    """Run a standalone router: ``python -m sbr_tpu.serve.router
    --fleet-dir DIR [--port P] [--run-dir D]``. Prints one JSON readiness
    line and serves until SIGTERM/SIGINT (graceful shutdown finalizes the
    obs run and the final fleet.json)."""
    import argparse

    from sbr_tpu.resilience.shutdown import graceful_shutdown

    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.serve.router",
        description="Serving-fleet router (throughput-weighted routing, "
        "failover, hedging, deadlines)",
    )
    parser.add_argument("--fleet-dir", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--run-dir", default=None)
    args = parser.parse_args(argv)

    router = Router(args.fleet_dir, port=args.port, run_dir=args.run_dir).start()
    print(json.dumps({"url": f"http://127.0.0.1:{router.port}"}), flush=True)
    with graceful_shutdown(label="serve_router"):
        try:
            while True:
                time.sleep(1.0)
        finally:
            router.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
