"""Long-lived equilibrium query engine (ISSUE 7 tentpole, part 1).

The paper's pipeline solves one equilibrium per invocation; the ROADMAP's
north star is a "bank-run weather service" answering equilibrium queries
for millions of users. This engine is that serving layer, with
observability as its load-bearing spine (`serve.live`, `serve.endpoint`):

- **Queries** are (`ModelParams`, scenario tag) pairs. `query` /
  `query_many` are synchronous; `submit` returns a ticket. A background
  micro-batcher thread drains the queue and batches concurrent queries
  into ONE vmapped dispatch of the same `solve_param_cell` the β×u grid
  sweeps run — a served query and a sweep cell can never drift.
- **Pad-to-bucket batching**: batch shapes are padded up to a fixed bucket
  ladder (``SBR_SERVE_BUCKETS``, default 1,8,64,512) so the engine
  compiles at most one executable per bucket and NEVER retraces on
  arbitrary batch sizes (`prof.note_trace("serve.batch")` counts the
  traces; `/metrics` exposes them). Padded lanes repeat the first query
  and are discarded — vmap lanes are independent, so per-query results
  are bitwise identical across bucket sizes (asserted by
  tests/test_serve.py).
- **Result cache**: an in-memory LRU plus an optional on-disk layer
  (``SBR_SERVE_CACHE_DIR``), keyed by the canonical params fingerprint
  (`sbr_tpu.utils.checkpoint.params_fingerprint`) combined with the
  solver config + dtype — the same keying a future cross-run sweep cache
  uses.
- **AOT executable cache**: compiled bucket executables are serialized
  (`jax.experimental.serialize_executable`) into the cache dir and
  RELOADED on restart, skipping the ~2 s first-call compile visible in
  every BENCH_r*.json. Reload is a deserialize, not a compile: a warm
  restart shows zero ``serve.batch`` traces and zero backend compiles on
  ``/metrics``. Every step degrades gracefully when the backend cannot
  serialize (the engine just compiles).
- **Resilience**: dispatches run under the unified retry engine
  (``SBR_SERVE_RETRY_*``) with a shared `RetryBudget`
  (``SBR_SERVE_RETRY_BUDGET``); `/healthz` folds the budget state and the
  per-window divergent-cell counts (from the solver's `Health` pytree)
  into ready/degraded/unhealthy.
- **Deadlines & degradation (ISSUE 11)**: every query may carry a
  deadline (``SBR_SERVE_DEADLINE_MS`` default); admission SHEDS queries
  that cannot meet it (`DeadlineExceeded` → HTTP 429 + ``Retry-After``)
  against the measured dispatch-time EWMA — never silent queue growth —
  while a deadline expiring mid-batch still returns (the batch is paid
  for). A dispatch circuit breaker (``SBR_BREAKER_*``) opens on
  consecutive failures; while the solver path is down, batches climb the
  degradation ladder: exact LRU/disk hit → the PR 7 global tile cache
  (`serve.fleet.TileCacheBridge`, answers labeled ``degraded``) → error.
  The on-disk result cache carries sha256 sidecars and is verified on
  read (mismatches quarantined + recomputed, `resilience.heal`).

The pickle-based executable cache trusts its cache directory (same trust
model as the tile checkpoints beside it) — point ``SBR_SERVE_CACHE_DIR``
at storage you own.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import os
import pickle
import queue
import tempfile
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import List, Optional, Tuple

import numpy as np

from sbr_tpu.diag.health import DIVERGENT_MASK
from sbr_tpu.models.params import ModelParams, SolverConfig
from sbr_tpu.resilience import retry
from sbr_tpu.serve.fleet import CircuitBreaker, TileCacheBridge, default_deadline_ms
from sbr_tpu.serve.live import LiveMetrics
from sbr_tpu.utils.checkpoint import canonicalize, params_fingerprint

# Bump when the batch program's semantics change: invalidates serialized
# executables AND cached results (both encode solver behavior).
_PROGRAM_VERSION = 1

_SHUTDOWN = object()


class DeadlineExceeded(RuntimeError):
    """Query shed at admission: its deadline has already passed, or the
    engine's measured service time says it cannot be met. Maps to HTTP 429
    + ``Retry-After`` at the endpoint (ISSUE 11 backpressure — an explicit
    rejection, never silent queue growth). ``retry_after_s`` is the
    engine's service-time estimate — when the caller comes back with at
    least that much deadline, admission will accept."""

    def __init__(self, msg: str, retry_after_s: float = 0.1) -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class SolverUnavailable(RuntimeError):
    """The dispatch circuit breaker is open: the solver path is presumed
    down and batches short-circuit to the degradation ladder instead of
    burning the retry budget per batch."""


def default_buckets() -> Tuple[int, ...]:
    """Batch-size bucket ladder from ``SBR_SERVE_BUCKETS`` (comma-separated,
    ascending; default 1,8,64,512). Queries are padded up to the smallest
    bucket that fits, so at most ``len(buckets)`` executables ever compile.
    A malformed env value falls back to the default ladder WITH a stderr
    warning — an operator typo must neither crash engine construction nor
    silently serve a different compile/memory profile."""
    import sys

    env = os.environ.get("SBR_SERVE_BUCKETS", "").strip()
    if env:
        try:
            vals = sorted({int(v) for v in env.split(",") if v.strip()})
            if vals and all(v > 0 for v in vals):
                return tuple(vals)
            raise ValueError("buckets must be positive integers")
        except ValueError as err:
            print(
                f"[sbr_tpu.serve] ignoring invalid SBR_SERVE_BUCKETS={env!r} "
                f"({err}); using default ladder",
                file=sys.stderr,
            )
    return (1, 8, 64, 512)


def slo_ms() -> Optional[float]:
    """The p99 latency SLO (``SBR_SERVE_SLO_MS``); None when unset."""
    env = os.environ.get("SBR_SERVE_SLO_MS", "").strip()
    return float(env) if env else None


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (env defaults resolved at construction)."""

    buckets: Tuple[int, ...] = dataclasses.field(default_factory=default_buckets)
    max_wait_ms: float = 2.0  # micro-batch assembly window
    lru_max: int = 4096
    cache_dir: Optional[str] = None  # results + serialized executables
    # Upper bound on on-disk result-cache entries (the traffic model is
    # millions of arbitrary queries — without a cap the results/ tree
    # grows one file per distinct fingerprint forever). Checked every 512
    # disk writes; oldest entries (mtime) pruned first. 0 disables.
    disk_cap: int = 100_000

    def __post_init__(self):
        # _bucket_for assumes an ascending ladder (first bucket >= n wins);
        # normalize here so a hand-built ServeConfig((64, 8, 1)) cannot
        # silently pad singleton traffic to 64 lanes.
        buckets = tuple(sorted({int(b) for b in self.buckets}))
        if not buckets or buckets[0] <= 0:
            raise ValueError(f"buckets must be positive integers, got {self.buckets!r}")
        object.__setattr__(self, "buckets", buckets)

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        kw = dict(
            buckets=default_buckets(),
            cache_dir=os.environ.get("SBR_SERVE_CACHE_DIR", "").strip() or None,
        )
        env_lru = os.environ.get("SBR_SERVE_LRU", "").strip()
        if env_lru:
            kw["lru_max"] = int(env_lru)
        env_cap = os.environ.get("SBR_SERVE_DISK_CAP", "").strip()
        if env_cap:
            kw["disk_cap"] = int(env_cap)
        kw.update(overrides)
        return cls(**kw)


@dataclasses.dataclass(frozen=True)
class QueryResult:
    """One served equilibrium: the lean per-cell outputs plus provenance.
    ``degraded`` marks a degradation-ladder answer (source "tilecache"):
    the numbers came from a swept tile in the global cache while the
    solver path was unavailable — ``tau_bar_in``/``residual`` are NaN
    there (tiles don't store them), and ``grads`` stays None even when the
    query asked for sensitivities (tiles don't store those either).

    ``grads`` (ISSUE 13): dξ/dθ for θ ∈ {β, u, κ} when the query carried
    ``grads=true`` — IFT sensitivities served next to ξ, fingerprint-keyed
    and cached exactly like any result; ``grad_flags`` is the grad-trust
    bitmask (`diag.health.GRAD_*`)."""

    xi: float
    tau_bar_in: float
    aw_max: float
    status: int
    flags: int
    residual: float
    source: str  # "lru" | "disk" | "coalesced" | "computed" | "tilecache"
    scenario: str
    latency_s: float
    degraded: bool = False
    grads: Optional[dict] = None  # {"beta": .., "u": .., "kappa": ..}
    grad_flags: Optional[int] = None

    @property
    def divergent(self) -> bool:
        return bool(self.flags & DIVERGENT_MASK)


class _Ticket:
    __slots__ = ("params", "scenario", "key", "t0", "t_wall0", "t_popped",
                 "deadline", "event", "result", "error", "grads",
                 "trace", "span_id", "tparent")

    def __init__(self, params: ModelParams, scenario: str, key: str,
                 deadline: Optional[float] = None, grads: bool = False,
                 trace=None) -> None:
        self.params = params
        self.scenario = scenario
        self.key = key
        self.grads = grads
        self.t0 = time.monotonic()
        # Distributed tracing (ISSUE 16): `trace` is an obs.trace
        # TraceContext (or None, the untraced fast path — every
        # instrumentation site below is a single None check). The engine's
        # spans for this query hang off `span_id` ("engine.query", emitted
        # at fulfillment), which itself attaches to the caller's
        # `parent_id` (the endpoint's request span).
        self.trace = trace
        self.span_id = trace.alloc_id() if trace is not None else None
        self.tparent = trace.parent_id if trace is not None else None
        # Wall-clock twin of t0: span records join across processes on the
        # wall axis (`wall()` maps any monotonic instant onto it).
        self.t_wall0 = time.time()
        self.t_popped: Optional[float] = None  # when the batcher took it
        # Absolute monotonic deadline, or None. Admission already shed the
        # unmeetable; a ticket whose deadline expires while still QUEUED
        # is shed at batch formation (no dispatch burned); one whose
        # deadline expires once its batch is DISPATCHED is not cancelled —
        # that compute is already paid for, the caller still gets its
        # answer (tested deadline semantics).
        self.deadline = deadline
        self.event = threading.Event()
        self.result: Optional[QueryResult] = None
        self.error: Optional[BaseException] = None

    def wall(self, mono: float) -> float:
        """Wall-clock time of monotonic instant ``mono`` (span timestamps)."""
        return self.t_wall0 + (mono - self.t0)

    def wait(self, timeout: Optional[float] = None) -> QueryResult:
        if not self.event.wait(timeout):
            raise TimeoutError(f"query not fulfilled within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.result


@functools.lru_cache(maxsize=None)
def _batch_fn(config: SolverConfig, dtype_name: str):
    """Jitted 1-D micro-batch program: `solve_param_cell` vmapped with
    EVERY parameter per-lane (the grid program broadcasts economics; a
    served batch mixes arbitrary params). Cached per (config, dtype) so
    buckets share one traced definition — each bucket shape still
    compiles its own executable, counted by ``serve.batch`` traces."""
    import jax
    import jax.numpy as jnp

    from sbr_tpu.obs import prof
    from sbr_tpu.sweeps.baseline_sweeps import solve_param_cell

    dtype = jnp.dtype(dtype_name)

    def fn(beta, u, p, kappa, lam, eta, t0, t1, x0):
        prof.note_trace("serve.batch")

        def cell(*cols):
            return solve_param_cell(*cols, config, dtype)

        return jax.vmap(cell)(beta, u, p, kappa, lam, eta, t0, t1, x0)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _grad_batch_fn(config: SolverConfig, dtype_name: str, aprime_tol_: float):
    """Jitted 1-D micro-batch program for ``grads=true`` queries: the
    forward `solve_param_cell` (the served ξ/status/flags are EXACTLY the
    plain program's) plus the IFT value-and-grad of the grad twin cell —
    dξ/dβ, dξ/du, dξ/dκ per lane with grad-trust flags. Cached per
    (config, dtype, resolved SBR_GRAD_APRIME_TOL — part of the key so an
    env change cannot be silently ignored by a warm program); traces
    counted as ``serve.grad_batch``."""
    import jax
    import jax.numpy as jnp

    from sbr_tpu.grad.api import WRT_DEFAULT, cell_value_and_grads
    from sbr_tpu.grad.cell import BASE_KEYS
    from sbr_tpu.obs import prof
    from sbr_tpu.sweeps.baseline_sweeps import solve_param_cell

    dtype = jnp.dtype(dtype_name)

    def fn(beta, u, p, kappa, lam, eta, t0, t1, x0):
        prof.note_trace("serve.grad_batch")

        def cell(*cols):
            xi, tau_in, aw_max, status, health = solve_param_cell(*cols, config, dtype)
            theta = dict(zip(BASE_KEYS, cols))
            _, _, grads, _, _, gflags = cell_value_and_grads(
                theta, WRT_DEFAULT, config, dtype, aprime_tol_=aprime_tol_
            )
            return (
                xi, tau_in, aw_max, status, health,
                grads["beta"], grads["u"], grads["kappa"], gflags,
            )

        return jax.vmap(cell)(beta, u, p, kappa, lam, eta, t0, t1, x0)

    return jax.jit(fn)


def _query_columns(params_list: List[ModelParams], dtype) -> list:
    """The 9 per-lane parameter vectors in `solve_param_cell` order."""
    rows = [
        (
            p.learning.beta,
            p.economic.u,
            p.economic.p,
            p.economic.kappa,
            p.economic.lam,
            p.economic.eta,
            p.learning.tspan[0],
            p.learning.tspan[1],
            p.learning.x0,
        )
        for p in params_list
    ]
    cols = np.asarray(rows, dtype=dtype).T
    return [np.ascontiguousarray(c) for c in cols]


class Engine:
    """The long-lived serving engine (see module docstring).

    Construction is cheap (no compile, no dispatch); executables compile
    lazily per bucket on first use — or load from the serialized cache.
    Use as a context manager, or call `start()` / `close()` explicitly.
    Without `start()` the engine still serves `query_many` synchronously
    in the calling thread (the deterministic path tests exercise)."""

    def __init__(
        self,
        config: Optional[SolverConfig] = None,
        dtype=None,
        serve: Optional[ServeConfig] = None,
        run=None,
        run_dir: Optional[str] = None,
    ) -> None:
        import jax
        import jax.numpy as jnp

        # Sweep-default numerics (refinement OFF), matching beta_u_grid.
        self.config = config if config is not None else SolverConfig(refine_crossings=False)
        if dtype is None:
            dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        self.dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))
        self.serve = serve or ServeConfig.from_env()
        self.live = LiveMetrics()

        from sbr_tpu import obs
        from sbr_tpu.obs import prof

        # The compile listeners normally install when a RunContext starts;
        # an engine without a run still promises live XLA compile counters
        # on /metrics (the zero-post-warmup-compile gate reads them), so
        # install unconditionally — idempotent, no-op on jax builds
        # without jax.monitoring.
        prof.install()

        self._owned_run = None
        if run is None and run_dir is not None:
            run = self._owned_run = obs.start_run(label="serve", run_dir=run_dir)
        self._run = run if run is not None else obs.active_run()

        self._lru: "OrderedDict[str, dict]" = OrderedDict()
        self._lru_lock = threading.Lock()
        self._disk_writes = 0
        self._execs: dict = {}
        self._exec_meta = {"loaded": 0, "compiled": 0, "serialized": 0, "aot": "enabled"}
        self._cfg_tag = canonicalize((self.config, self.dtype.name, _PROGRAM_VERSION))

        self._retry = retry.policy_from_env(
            "SBR_SERVE_RETRY", max_attempts=2, base_delay_s=0.05,
            multiplier=2.0, max_delay_s=2.0,
        )
        budget_env = os.environ.get("SBR_SERVE_RETRY_BUDGET", "").strip()
        self._budget_total = int(budget_env) if budget_env else 8
        # Unlike a sweep (one bounded run), a server lives for days: a
        # LIFETIME budget would let 8 unrelated recovered hiccups spread
        # over a week permanently latch /healthz unhealthy. The budget
        # refreshes every SBR_SERVE_RETRY_REFILL_S (default 900 s) — the
        # time-based refill now lives in RetryBudget itself (shared with
        # the elastic sweep scheduler), so it still fail-fasts a genuinely
        # dead backend (many failures within one refill window) without
        # ratcheting.
        refill_env = os.environ.get("SBR_SERVE_RETRY_REFILL_S", "").strip()
        self._budget_refill_s = float(refill_env) if refill_env else 900.0
        self.retry_budget = retry.RetryBudget(
            self._budget_total, refill_s=self._budget_refill_s or None
        )

        # Dispatch circuit breaker (ISSUE 11): consecutive dispatch
        # failures open it; while open, batches short-circuit straight to
        # the degradation ladder (no device attempt, no retry-budget burn)
        # until the cooldown admits one half-open probe. Transitions land
        # as obs `fleet` events and /healthz degraded reasons.
        self.breaker = CircuitBreaker(on_transition=self._on_breaker)
        # Degradation-ladder rung 2: the PR 7 global tile cache, indexed
        # per-cell via the store-side meta sidecars (serving↔sweep bridge).
        self.bridge = TileCacheBridge()
        # Per-query deadline default (SBR_SERVE_DEADLINE_MS) and the
        # admission-control service-time estimate: an EWMA of measured
        # dispatch durations — a deadline shorter than the typical service
        # time cannot be met and is shed at admission (429), never queued.
        self.default_deadline_ms = default_deadline_ms()
        self._service_ewma_s: Optional[float] = None

        self._queue: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # Serializes the closed-check+enqueue in submit against close()'s
        # closed-flag flip: without it a submit racing close could land a
        # ticket after the batcher's final drain and strand its waiter.
        self._close_lock = threading.Lock()

        # Numerics-audit canaries (ISSUE 17): scheduled background solves
        # of tiny golden surfaces, off the hot path. SBR_AUDIT=0 (the
        # default) must be a STRUCTURAL no-op — the module is not even
        # imported, so no new code paths, traces, or threads exist (the
        # prof trace-counter witness in tests/test_audit.py).
        self.audit = None
        if os.environ.get("SBR_AUDIT", "").strip() not in ("", "0"):
            from sbr_tpu.obs import audit as _audit

            self.audit = _audit.AuditScheduler(engine=self)

        # Workload demand observatory (ISSUE 18): the rolling (β, u)
        # demand histogram + heavy-hitter sketch + answer-source labels
        # feeding the prefetch advisor. Same structural-no-op contract as
        # the audit gate above: SBR_DEMAND=0 (the default) never imports
        # the module — no tracker, no events, /metrics byte-free of
        # ``sbr_demand``, answers bit-identical.
        self.demand = None
        if os.environ.get("SBR_DEMAND", "").strip() not in ("", "0"):
            from sbr_tpu.obs import demand as _demand

            self.demand = _demand.DemandTracker(
                run=self._run, coverage_fn=self._demand_coverage
            )

        # Self-healing prefetch controller (ISSUE 19): consumes the demand
        # advisor's ranked tile plan and pre-warms the tile cache in the
        # idle windows, so the degradation ladder answers outages warm.
        # Same structural-no-op contract: SBR_PREWARM=0 (the default)
        # never imports the module — no thread, no leases, /metrics
        # byte-free of ``sbr_prewarm``, answers bit-identical.
        self.prewarm = None
        if os.environ.get("SBR_PREWARM", "").strip() not in ("", "0"):
            from sbr_tpu.serve import prewarm as _prewarm

            self.prewarm = _prewarm.PrewarmController(
                engine=self, config=self.config, dtype=self.dtype,
            )

        # Dispatch-pipeline flight recorder (ISSUE 20): ring-buffer
        # begin/end records across admission → queue → batch formation →
        # dispatch → unpack, folded into device-busy / host-gap fractions
        # (the baseline ruler for the ROADMAP item-1 async-dispatch work).
        # Same structural-no-op contract: SBR_FLIGHT=0 (the default) never
        # imports the module — no recorder, /metrics byte-free of
        # ``sbr_flight``, zero new XLA traces, answers bit-identical.
        self.flight = None
        if os.environ.get("SBR_FLIGHT", "").strip() not in ("", "0"):
            from sbr_tpu.obs import flight as _flight

            # The PROCESS-WIDE recorder: sweeps/collectives records from
            # in-process prewarm sweepers land on the same timeline.
            self.flight = _flight.shared()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "Engine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="sbr-serve-batcher", daemon=True
            )
            self._thread.start()
        if self.audit is not None:
            self.audit.start()
        if self.prewarm is not None:
            self.prewarm.start()
        return self

    def close(self) -> None:
        with self._close_lock:
            if self._closed:
                return
            # Under the lock: any submit that saw _closed == False has
            # already enqueued, so the batcher's shutdown drain (or the
            # sweep below) is guaranteed to see its ticket.
            self._closed = True
        if self._thread is not None:
            self._stop.set()
            self._queue.put(_SHUTDOWN)
            self._thread.join(timeout=30.0)
            # A submit that raced close() may have slipped a ticket in after
            # the batcher drained; fail it rather than strand its waiter.
            while True:
                try:
                    t = self._queue.get_nowait()
                except queue.Empty:
                    break
                if t is not _SHUTDOWN:
                    t.error = RuntimeError("engine closed before the query was served")
                    t.event.set()
        if self.prewarm is not None:
            self.prewarm.close()
        if self.audit is not None:
            self.audit.close()
        if self.demand is not None:
            self.demand.close(self._run)
        if self.flight is not None:
            self.flight.close(self._run)
        w = self.live.window()
        self.live.maybe_write(self._run, self._live_extra(window=w), window=w, force=True)
        if self._run is not None:
            try:
                self._run.event("serve_summary", **self.live.snapshot())
            except Exception:
                pass
        if self._owned_run is not None:
            from sbr_tpu.obs import runlog

            runlog._finalize_if_active(self._owned_run)

    def __enter__(self) -> "Engine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- deadlines / admission ----------------------------------------------
    def _on_breaker(self, old: str, new: str) -> None:
        """Breaker transition observer: obs `fleet` event + stderr note."""
        if self._run is not None:
            try:
                self._run.log_fleet(
                    f"breaker_{new}", scope="serve.dispatch", previous=old,
                    failures=self.breaker.consecutive_failures,
                )
            except Exception:
                pass

    def service_estimate_s(self) -> Optional[float]:
        """EWMA of measured dispatch durations (None before any dispatch)."""
        return self._service_ewma_s

    def _admit(self, deadline_ms: Optional[float]) -> Optional[float]:
        """Admission control: resolve the query's deadline (explicit, else
        ``SBR_SERVE_DEADLINE_MS``, else none) and SHED — an explicit
        `DeadlineExceeded`, zero solver work — when it has already expired
        or is shorter than the measured service time. Returns the absolute
        monotonic deadline (None = no deadline)."""
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        if deadline_ms is None:
            return None
        est = self._service_ewma_s
        retry_after = round(max(est or 0.05, 0.05), 3)
        if deadline_ms <= 0:
            self.live.record_shed()
            if self.flight is not None:
                self.flight.point("engine", "shed", tag="expired")
            if self._run is not None:
                try:
                    self._run.log_fleet("shed", reason="expired",
                                        deadline_ms=float(deadline_ms))
                except Exception:
                    pass
            raise DeadlineExceeded(
                f"deadline already expired ({deadline_ms:g} ms)",
                retry_after_s=retry_after,
            )
        if est is not None and deadline_ms / 1e3 < est:
            self.live.record_shed()
            if self.flight is not None:
                self.flight.point("engine", "shed", tag="unmeetable")
            if self._run is not None:
                try:
                    self._run.log_fleet("shed", reason="unmeetable",
                                        deadline_ms=float(deadline_ms),
                                        service_est_s=round(est, 4))
                except Exception:
                    pass
            raise DeadlineExceeded(
                f"deadline {deadline_ms:g} ms under the measured service "
                f"time ({est * 1e3:.1f} ms)",
                retry_after_s=retry_after,
            )
        return time.monotonic() + deadline_ms / 1e3

    # -- public query API ---------------------------------------------------
    def submit(self, params: ModelParams, scenario: str = "default",
               deadline_ms: Optional[float] = None, grads: bool = False,
               trace=None) -> _Ticket:
        """Enqueue one query for the micro-batcher (requires `start()`).
        Raises once the engine is closed — a ticket enqueued after the
        batcher drained would block its waiter forever — and sheds
        (`DeadlineExceeded`) when the deadline cannot be met. With
        ``grads`` the answer carries dξ/d{β,u,κ} next to ξ (ISSUE 13),
        cached under its own fingerprint tag. ``trace`` (an `obs.trace`
        TraceContext) attaches the engine's per-layer spans to the caller's
        request span."""
        t_adm_w = time.time() if trace is not None else 0.0
        t_adm = time.monotonic()
        try:
            deadline = self._admit(deadline_ms)
        except DeadlineExceeded:
            if trace is not None:
                trace.add("engine.admission", t_adm_w, time.monotonic() - t_adm,
                          parent=trace.parent_id, shed=True)
            raise
        ticket = _Ticket(
            params, scenario, self._result_key(params, grads), deadline, grads,
            trace=trace,
        )
        if trace is not None:
            trace.add("engine.admission", t_adm_w, time.monotonic() - t_adm,
                      parent=ticket.span_id)
        if self.flight is not None:
            self.flight.mark("engine", "admission", t_adm, time.monotonic())
        with self._close_lock:
            if self._closed:
                raise RuntimeError("engine is closed")
            self._queue.put(ticket)
        self.live.queue_depth = self._queue.qsize()
        return ticket

    def query(
        self, params: ModelParams, scenario: str = "default",
        timeout: Optional[float] = None, deadline_ms: Optional[float] = None,
        grads: bool = False, trace=None,
    ) -> QueryResult:
        """Synchronous single query. Batched with concurrent submitters
        when the engine is started; solved inline otherwise."""
        if self._thread is None:
            return self.query_many(
                [params], scenario=scenario, deadline_ms=deadline_ms,
                grads=grads, traces=[trace] if trace is not None else None,
            )[0]
        return self.submit(
            params, scenario, deadline_ms=deadline_ms, grads=grads, trace=trace
        ).wait(timeout)

    def query_many(
        self, params_list: List[ModelParams], scenario: str = "default",
        timeout: Optional[float] = None, deadline_ms: Optional[float] = None,
        grads: bool = False, traces=None,
    ) -> List[QueryResult]:
        """Solve a list of queries. Started engine: all enqueue at once (the
        natural micro-batch). Unstarted: processed inline in this thread —
        the deterministic, thread-free path. ``traces`` (optional, parallel
        to ``params_list``) carries one `obs.trace` TraceContext — or
        None — per query."""
        if self._closed:
            raise RuntimeError("engine is closed")
        any_trace = traces is not None and any(tr is not None for tr in traces)
        t_adm_w = time.time() if any_trace else 0.0
        t_adm = time.monotonic()
        try:
            deadline = self._admit(deadline_ms)
        except DeadlineExceeded:
            if any_trace:
                dur = time.monotonic() - t_adm
                for tr in traces:
                    if tr is not None:
                        tr.add("engine.admission", t_adm_w, dur,
                               parent=tr.parent_id, shed=True)
            raise
        tickets = [
            _Ticket(p, scenario, self._result_key(p, grads), deadline, grads,
                    trace=traces[i] if traces is not None else None)
            for i, p in enumerate(params_list)
        ]
        if any_trace:
            dur = time.monotonic() - t_adm
            for t in tickets:
                if t.trace is not None:
                    t.trace.add("engine.admission", t_adm_w, dur, parent=t.span_id)
        if self.flight is not None:
            self.flight.mark("engine", "admission", t_adm, time.monotonic())
        if self._thread is None:
            self._process(tickets)
        else:
            with self._close_lock:
                if self._closed:
                    raise RuntimeError("engine is closed")
                for t in tickets:
                    self._queue.put(t)
            self.live.queue_depth = self._queue.qsize()
        return [t.wait(timeout) for t in tickets]

    # -- health / exposition -------------------------------------------------
    def healthz(self, window: Optional[dict] = None) -> dict:
        """Ready/degraded/unhealthy verdict with reasons — `/healthz` body.

        unhealthy: the batcher thread died, or the shared retry budget is
        exhausted (every future transient failure will fail fast until the
        next refill — see ``SBR_SERVE_RETRY_REFILL_S``).
        degraded: divergent cells, dispatch errors, sheds, or ladder
        answers in the current window, a partially consumed retry budget
        (since the last refill), an open/half-open dispatch breaker, or a
        p99 over ``SBR_SERVE_SLO_MS``.

        ``window`` (a prior `LiveMetrics.window()` result) lets a caller
        building a larger document share ONE fold between the verdict and
        the window it embeds — the scrape-coherence contract
        (`serve.live._window_fold`).
        """
        self._maybe_refill_budget()
        reasons = []
        status = "ready"
        if self._thread is not None and not self._thread.is_alive() and not self._closed:
            status = "unhealthy"
            reasons.append("batcher thread dead")
        if self.retry_budget.total > 0 and self.retry_budget.remaining == 0:
            status = "unhealthy"
            reasons.append("retry budget exhausted")
        if status != "unhealthy":
            if window is None:
                window = self.live.window()
            if window.get("divergent_cells", 0):
                status = "degraded"
                reasons.append(f"{int(window['divergent_cells'])} divergent cell(s) in window")
            if window.get("errors", 0):
                status = "degraded"
                reasons.append(f"{int(window['errors'])} dispatch error(s) in window")
            if window.get("shed", 0):
                status = "degraded"
                reasons.append(f"{int(window['shed'])} shed quer(ies) in window")
            if window.get("degraded", 0):
                status = "degraded"
                reasons.append(
                    f"{int(window['degraded'])} degraded-ladder answer(s) in window"
                )
            if self.breaker.state != "closed":
                status = "degraded"
                reasons.append(
                    f"dispatch breaker {self.breaker.state} "
                    f"({self.breaker.consecutive_failures} consecutive failure(s))"
                )
            if self.retry_budget.used > 0:
                status = "degraded"
                reasons.append(
                    f"retry budget {self.retry_budget.used}/{self.retry_budget.total} consumed"
                )
            slo = slo_ms()
            p99 = (window.get("latency_ms") or {}).get("p99")
            if slo is not None and p99 is not None and p99 > slo:
                status = "degraded"
                reasons.append(f"window p99 {p99:.3f} ms over SLO {slo:g} ms")
            if self.audit is not None and self.audit.status == "drift":
                status = "degraded"
                reasons.append(
                    "audit_drift: " + (",".join(self.audit.drift_probes) or "?")
                )
        return {"status": status, "reasons": reasons}

    def _maybe_refill_budget(self) -> None:
        """Apply the budget's own time-based refill (retry.RetryBudget
        handles the cadence; in-flight dispatches keep drawing on the same
        object, which is fine: the pool bounds failures per window)."""
        self.retry_budget.maybe_refill()

    def statz(self) -> dict:
        """Full live snapshot — `/statz` body and the `live.json` document.
        The embedded window AND the healthz verdict derive from ONE fold of
        the slot ring (the scrape-coherence satellite): a scrape racing a
        window rotation can never mix two windows in one document."""
        window = self.live.window()
        return self.live.snapshot(self._live_extra(window=window), window=window)

    def prometheus(self) -> str:
        from sbr_tpu.obs import trace as qtrace

        extra = {
            "sbr_serve_execs_loaded": ("counter", self._exec_meta["loaded"]),
            "sbr_serve_execs_compiled": ("counter", self._exec_meta["compiled"]),
            "sbr_serve_lru_entries": ("gauge", len(self._lru)),
            "sbr_serve_retry_budget_remaining": ("gauge", self.retry_budget.remaining),
        }
        # The worker's RESOLVED SLO (read per scrape, like healthz does):
        # the fleet aggregator (`report slo`) reads each worker's own value
        # instead of assuming a fleet-wide one. Absent gauge == no SLO set.
        slo = slo_ms()
        if slo is not None:
            extra["sbr_serve_slo_ms"] = ("gauge", slo)
        text = self.live.to_prometheus(extra)
        # Per-layer span-duration histograms (committed trace spans only;
        # empty exposition while tracing is off).
        hist_lines = qtrace.layer_prometheus()
        # Audit canary status + per-probe duration histograms. SBR_AUDIT=0
        # contributes NOTHING here, not even a zero gauge — tests assert
        # the exposition is byte-free of sbr_audit when the audit is off.
        if self.audit is not None:
            hist_lines = list(hist_lines or []) + self.audit.prometheus_lines()
        # Demand observatory gauges: same byte-free-when-off contract —
        # SBR_DEMAND=0 engines have no tracker, so no sbr_demand_* lines.
        if self.demand is not None:
            hist_lines = list(hist_lines or []) + self.demand.prometheus_lines()
        # Prefetch controller gauges: byte-free when SBR_PREWARM=0.
        if self.prewarm is not None:
            hist_lines = list(hist_lines or []) + self.prewarm.prometheus_lines()
        # Flight-recorder utilization gauges + per-stream latency
        # histograms: byte-free when SBR_FLIGHT=0.
        if self.flight is not None:
            hist_lines = list(hist_lines or []) + self.flight.prometheus_lines()
        if hist_lines:
            text = text.rstrip("\n") + "\n" + "\n".join(hist_lines) + "\n"
        return text

    def trace_writer(self):
        """This engine's span sink (`obs.trace.TraceWriter`), or None when
        the engine has no run directory — the root-span owner (endpoint,
        loadgen) commits finished traces here."""
        from sbr_tpu.obs import trace as qtrace

        return qtrace.writer_for(self._run)

    def _live_extra(self, window: Optional[dict] = None) -> dict:
        return {
            "healthz": self.healthz(window=window),
            "retry_budget": {
                "total": self.retry_budget.total,
                "used": self.retry_budget.used,
                "remaining": self.retry_budget.remaining,
            },
            "slo": {"slo_ms": slo_ms()},
            "breaker": {
                "state": self.breaker.state,
                "consecutive_failures": self.breaker.consecutive_failures,
            },
            "deadline": {
                "default_ms": self.default_deadline_ms,
                "service_est_s": (
                    round(self._service_ewma_s, 6)
                    if self._service_ewma_s is not None
                    else None
                ),
            },
            "engine": {
                "buckets": list(self.serve.buckets),
                "dtype": self.dtype.name,
                "lru_entries": len(self._lru),
                "lru_max": self.serve.lru_max,
                "cache_dir": self.serve.cache_dir,
                **self._exec_meta,
            },
            **({"audit": self.audit.snapshot()} if self.audit is not None else {}),
            **({"demand": self.demand.snapshot()} if self.demand is not None else {}),
            **({"prewarm": self.prewarm.snapshot()} if self.prewarm is not None else {}),
            **({"flight": self.flight.heartbeat_block()} if self.flight is not None else {}),
        }

    def _demand_coverage(self) -> Optional[dict]:
        """The advisor's tile-cache coverage input: the cell index of this
        engine's configured global tile cache (None when no cache is
        bridged — distinct from an empty cache)."""
        if self.demand is None or not self.bridge.available:
            return None
        from sbr_tpu.obs import demand as _demand

        return _demand.coverage_from_cache_dir(self.bridge.cache.root)

    # -- batcher loop --------------------------------------------------------
    def _loop(self) -> None:
        max_bucket = max(self.serve.buckets)
        while True:
            try:
                item = self._queue.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    break
                # write_due first: building the extras (healthz + window
                # fold) 20×/s on an idle server just to hit the write
                # throttle would be pure waste.
                if self._run is not None and self.live.write_due():
                    w = self.live.window()
                    self.live.maybe_write(self._run, self._live_extra(window=w), window=w)
                    if self.demand is not None:
                        self.demand.maybe_write(self._run)
                    if self.flight is not None:
                        self.flight.maybe_write(self._run)
                continue
            batch, shutdown = [], item is _SHUTDOWN
            if not shutdown:
                item.t_popped = time.monotonic()
                batch.append(item)
                deadline = time.monotonic() + self.serve.max_wait_ms / 1e3
                while len(batch) < max_bucket:
                    budget = deadline - time.monotonic()
                    try:
                        nxt = self._queue.get(timeout=max(budget, 0.0))
                    except queue.Empty:
                        break
                    if nxt is _SHUTDOWN:
                        shutdown = True
                        break
                    nxt.t_popped = time.monotonic()
                    batch.append(nxt)
            else:
                # Drain everything still queued so no ticket hangs forever.
                while True:
                    try:
                        nxt = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is not _SHUTDOWN:
                        nxt.t_popped = time.monotonic()
                        batch.append(nxt)
            self.live.queue_depth = self._queue.qsize()
            if self.flight is not None:
                self.flight.point("engine", "queue_depth",
                                  val=self.live.queue_depth)
            if batch:
                self.live.inflight = len(batch)
                try:
                    self._process(batch)
                finally:
                    self.live.inflight = 0
                if self._run is not None and self.live.write_due():
                    w = self.live.window()
                    self.live.maybe_write(self._run, self._live_extra(window=w), window=w)
                    if self.demand is not None:
                        self.demand.maybe_write(self._run)
                    if self.flight is not None:
                        self.flight.maybe_write(self._run)
            if shutdown:
                break

    # -- processing ----------------------------------------------------------
    def _process(self, tickets: List[_Ticket]) -> None:
        """Serve a batch of tickets: cache lookups first, then the misses in
        bucket-padded dispatches. Identical queries inside one batch are
        COALESCED into a single lane — a million users asking the same
        question is this service's expected traffic shape, so duplicates
        must cost one solve, not N. Never raises — failures land on
        tickets."""
        groups: "OrderedDict[str, List[_Ticket]]" = OrderedDict()
        t_proc = time.monotonic()
        fl = self.flight
        for t in tickets:
            if fl is not None:
                popped = t.t_popped if t.t_popped is not None else t_proc
                fl.mark("engine", "queue", t.t0, popped)
            if t.trace is not None:
                # Queue wait: enqueue → the batcher taking the ticket
                # (inline query_many never queues: ~0).
                popped = t.t_popped if t.t_popped is not None else t_proc
                t.trace.add("engine.queue", t.wall(t.t0), popped - t.t0,
                            parent=t.span_id)
            t_lk = time.monotonic()
            rec, source = self._lookup(t.key)
            if fl is not None:
                fl.mark("engine", "cache", t_lk, time.monotonic(), tag=source or "miss")
            if t.trace is not None:
                # Per-layer cache outcome: LRU always probed; disk only on
                # an LRU miss (attr omitted when not consulted, "off" when
                # no cache dir is configured).
                disk = (
                    None if source == "lru"
                    else "hit" if source == "disk"
                    else "miss" if self.serve.cache_dir
                    else "off"
                )
                t.trace.add(
                    "engine.cache", t.wall(t_lk), time.monotonic() - t_lk,
                    parent=t.span_id,
                    lru="hit" if source == "lru" else "miss", disk=disk,
                )
            if rec is not None:
                # A cache hit is free: serve it even past its deadline (a
                # late answer beats a late rejection at zero device cost).
                self._fulfill(t, rec, source)
            elif t.deadline is not None and time.monotonic() > t.deadline:
                # Expired while QUEUED: the batch has not started, so shed
                # now instead of burning a full dispatch on a dead query —
                # admission's estimate cannot see queue wait, this check
                # can. (A deadline expiring once the batch IS dispatched
                # still returns: that compute is already paid for.)
                self.live.record_shed()
                if fl is not None:
                    fl.point("engine", "shed", tag="queue-expired")
                if self._run is not None:
                    try:
                        self._run.log_fleet("shed", reason="queue-expired",
                                            scenario=t.scenario)
                    except Exception:
                        pass
                est = self._service_ewma_s
                t.error = DeadlineExceeded(
                    "deadline expired while queued",
                    retry_after_s=round(max(est or 0.05, 0.05), 3),
                )
                if t.trace is not None:
                    t.trace.add(
                        "engine.query", t.t_wall0, time.monotonic() - t.t0,
                        parent=t.tparent, span_id=t.span_id,
                        shed="queue-expired",
                    )
                t.event.set()
            else:
                groups.setdefault(t.key, []).append(t)
        unique = [g[0] for g in groups.values()]
        max_bucket = max(self.serve.buckets)
        # Plain and grads queries dispatch through DIFFERENT compiled
        # programs; partition before chunking (the key tag already keeps
        # their cache entries and coalescing groups apart).
        partitions = [
            [t for t in unique if not t.grads],
            [t for t in unique if t.grads],
        ]
        for part in partitions:
            self._process_chunks(part, groups, max_bucket)

    def _process_chunks(self, unique: List[_Ticket], groups, max_bucket: int) -> None:
        fl = self.flight
        for i in range(0, len(unique), max_bucket):
            chunk = unique[i : i + max_bucket]
            n = len(chunk)
            bucket = self._bucket_for(n)
            t_d0w, t_d0m = time.time(), time.monotonic()
            if fl is not None:
                # Batch formation: first pop → dispatch start (what the
                # device waits out between consecutive dispatches).
                popped0 = min(
                    (t.t_popped for t in chunk if t.t_popped is not None),
                    default=t_d0m,
                )
                fl.mark("engine", "batch", popped0, t_d0m, tag=f"b{bucket}")
            try:
                # Positional call for the plain path: `_dispatch(params)` is
                # a stubbing point (tests monkeypatch it for failure
                # injection) and its plain signature stays stable.
                records = (
                    self._dispatch([t.params for t in chunk], grads=True)
                    if chunk[0].grads
                    else self._dispatch([t.params for t in chunk])
                )
            except BaseException as err:
                # Degradation ladder (ISSUE 11): the solver path is down
                # (breaker open, retry budget exhausted, fault-injected).
                # The exact LRU/disk rung already missed above, so the next
                # rung is the PR 7 global tile cache — answer from a swept
                # tile when one mathematically matches, labeled
                # ``degraded``; only then fail the ticket (the endpoint's
                # 503). Degraded answers are never cached: the moment the
                # solver recovers, fresh dispatches must take over.
                for t in chunk:
                    t_lad = time.monotonic()
                    rec = self._degraded_rec(t)
                    lad_dur = time.monotonic() - t_lad
                    for dup in groups[t.key]:
                        if dup.trace is not None:
                            # The tile-cache rung of the degradation ladder
                            # — its own cache-layer span, hit or miss.
                            dup.trace.add(
                                "engine.tilecache", dup.wall(t_lad), lad_dur,
                                parent=dup.span_id, hit=rec is not None,
                            )
                        if rec is not None:
                            self._fulfill(dup, dict(rec), "tilecache", degraded=True)
                        else:
                            self.live.record_error()
                            if dup.trace is not None:
                                dup.trace.add(
                                    "engine.query", dup.t_wall0,
                                    time.monotonic() - dup.t0,
                                    parent=dup.tparent, span_id=dup.span_id,
                                    error=type(err).__name__,
                                )
                            dup.error = err
                            dup.event.set()
                continue
            t_up = time.monotonic()
            for t, rec in zip(chunk, records):
                # A divergent result (DIVERGENT_MASK flag) is served — the
                # caller sees the flags and decides — but never CACHED: a
                # cached hit would replay the poisoned numbers forever
                # (surviving restarts via the disk layer) while /healthz
                # recovered as the window rolled past the original solve.
                # Recomputing each time keeps the divergence visible to the
                # live window and leaves the door open for a heal-ladder
                # retry to succeed on transient poison.
                if not (rec["flags"] & DIVERGENT_MASK):
                    self._store(t.key, rec)
                disp_dur = time.monotonic() - t_d0m
                for j, dup in enumerate(groups[t.key]):
                    if dup.trace is not None:
                        # Batch formation (pop → dispatch start, includes
                        # waiting out earlier chunks) and the dispatch
                        # itself, with the padded-lane share this query's
                        # bucket paid for.
                        popped = dup.t_popped if dup.t_popped is not None else t_d0m
                        dup.trace.add(
                            "engine.batch", dup.wall(popped),
                            max(t_d0m - popped, 0.0), parent=dup.span_id,
                            n=n, coalesced=True if j else None,
                        )
                        dup.trace.add(
                            "engine.dispatch", t_d0w, disp_dur,
                            parent=dup.span_id, bucket=bucket,
                            padded_lanes=bucket - n,
                            padded_share=round((bucket - n) / bucket, 4),
                        )
                    self._fulfill(dup, rec, "computed" if j == 0 else "coalesced")
            if fl is not None:
                # Result unpack: store + fulfill after the device fence.
                fl.mark("engine", "unpack", t_up, time.monotonic(),
                        tag=f"b{bucket}")

    def _degraded_rec(self, t: _Ticket) -> Optional[dict]:
        """The tile-cache rung of the degradation ladder for one ticket
        (None when the bridge has no mathematically-matching cell). Every
        outcome is an obs ``fleet`` event — the ladder must be observable
        end-to-end (`report fleet`, the manifest ``fleet`` block)."""
        try:
            rec = self.bridge.lookup(t.params, self.config, self.dtype.name)
        except Exception:
            rec = None  # a broken bridge must never mask the real error
        if self._run is not None:
            try:
                self._run.log_fleet(
                    "degraded" if rec is not None else "ladder_exhausted",
                    scenario=t.scenario, key=t.key[:12],
                )
            except Exception:
                pass
        return rec

    def _fulfill(self, t: _Ticket, rec: dict, source: str,
                 degraded: bool = False) -> None:
        latency = time.monotonic() - t.t0
        rec = dict(rec)
        # Grad records are a superset of the plain shape; fold the dξ/dθ
        # keys into the structured ``grads`` field. A degraded (tilecache)
        # answer to a grads query has none — grads stays None.
        grads = None
        grad_flags = rec.pop("grad_flags", None)
        if "dxi_dbeta" in rec:
            grads = {
                "beta": rec.pop("dxi_dbeta"),
                "u": rec.pop("dxi_du"),
                "kappa": rec.pop("dxi_dkappa"),
            }
        t.result = QueryResult(
            source=source, scenario=t.scenario, latency_s=latency,
            degraded=degraded, grads=grads, grad_flags=grad_flags, **rec
        )
        if t.trace is not None:
            # The engine-level root span for this query: admission/queue/
            # cache/batch/dispatch spans above all parent to it.
            t.trace.add(
                "engine.query", t.t_wall0, latency,
                parent=t.tparent, span_id=t.span_id, source=source,
                degraded=True if degraded else None,
                divergent=True if t.result.divergent else None,
            )
        self.live.record_query(
            latency, source, scenario=t.scenario, divergent=t.result.divergent
        )
        if self.demand is not None:
            self.demand.record_params(
                t.params, scenario=t.scenario, source=source, grads=t.grads
            )
        t.event.set()

    def _bucket_for(self, n: int) -> int:
        for b in self.serve.buckets:
            if b >= n:
                return b
        return max(self.serve.buckets)

    def _dispatch(self, params_list: List[ModelParams], grads: bool = False) -> List[dict]:
        """One padded vmapped dispatch under the retry policy; returns one
        plain-float record per query (the cacheable form). Guarded by the
        dispatch circuit breaker: while open, raise `SolverUnavailable`
        without touching the device (the ladder answers), until the
        cooldown lets one half-open probe through. The ``serve.dispatch``
        fault point fires inside the retried scope, so injected transients
        are first retried and can then exhaust into a real outage. With
        ``grads`` the batch runs the grad program (`_grad_batch_fn`) and
        records carry dξ/dθ + grad-trust flags."""
        import jax.numpy as jnp

        self._maybe_refill_budget()
        if not self.breaker.allow():
            raise SolverUnavailable(
                f"dispatch breaker open "
                f"({self.breaker.consecutive_failures} consecutive failure(s))"
            )
        n = len(params_list)
        bucket = self._bucket_for(n)
        cols = _query_columns(params_list, self.dtype)
        if bucket > n:
            pad = bucket - n
            cols = [np.concatenate([c, np.repeat(c[:1], pad)]) for c in cols]
        exec_ = self._exec(bucket, grads=grads)
        args = [jnp.asarray(c) for c in cols]

        def run():
            from sbr_tpu.resilience import faults

            faults.fire("serve.dispatch", target=f"bucket{bucket}")
            out = exec_(*args)
            if grads:
                xi, tau_in, aw_max, status, health, db, du, dk, gflags = out
                gextra = tuple(np.asarray(v) for v in (db, du, dk, gflags))
            else:
                xi, tau_in, aw_max, status, health = out
                gextra = None
            # Device→host fetch inside the retried scope: a transient that
            # surfaces at fetch time must count against THIS dispatch.
            return (
                np.asarray(xi),
                np.asarray(tau_in),
                np.asarray(aw_max),
                np.asarray(status),
                np.asarray(health.flags),
                np.asarray(health.residual),
                gextra,
            )

        t_disp = time.monotonic()
        try:
            xi, tau_in, aw_max, status, flags, residual, gextra = self._retry.call(
                run, scope=f"serve.dispatch[{bucket}]", budget=self.retry_budget
            )
        except BaseException:
            self.breaker.record_failure()
            raise
        self.breaker.record_success()
        # Admission control's service-time estimate: EWMA of measured
        # dispatch durations (includes retry backoff — what a caller
        # actually waits for the solver path).
        dur = time.monotonic() - t_disp
        self._service_ewma_s = (
            dur if self._service_ewma_s is None
            else 0.3 * dur + 0.7 * self._service_ewma_s
        )
        if self.flight is not None:
            # The honest device span: run() only returns after np.asarray
            # forces every output, so [t_disp, t_disp+dur] covers transfer
            # + compute + fetch (+ retry backoff — what the device path
            # actually occupied).
            self.flight.mark("engine", "dispatch", t_disp, t_disp + dur,
                             tag=f"b{bucket}")
            self.flight.point("engine", "occupancy", tag=f"b{bucket}",
                              val=round(n / bucket, 4))
        self.live.record_batch(n, bucket)
        if self._run is not None:
            try:
                self._run.event(
                    "serve_batch", n=n, bucket=bucket,
                    divergent=int(((flags[:n] & DIVERGENT_MASK) != 0).sum()),
                )
            except Exception:
                pass
        records = []
        for i in range(n):
            rec = {
                "xi": float(xi[i]),
                "tau_bar_in": float(tau_in[i]),
                "aw_max": float(aw_max[i]),
                "status": int(status[i]),
                "flags": int(flags[i]),
                "residual": float(residual[i]),
            }
            if gextra is not None:
                db, du, dk, gflags = gextra
                rec.update(
                    dxi_dbeta=float(db[i]), dxi_du=float(du[i]),
                    dxi_dkappa=float(dk[i]), grad_flags=int(gflags[i]),
                )
            records.append(rec)
        return records

    # -- composed scenarios (ISSUE 14) ---------------------------------------
    def query_scenario(self, params, spec, deadline_ms: Optional[float] = None) -> dict:
        """Serve one composed-scenario query (`scenario.ScenarioSpec`) —
        the `POST /query` ``scenario``-object route.

        Runs in the calling thread (scenario programs are per-spec compiled
        and cached; arbitrary specs don't micro-batch across each other),
        with the engine's admission control and the same LRU + on-disk
        result cache — keyed by `scenario.spec_fingerprint(spec, params,
        config, dtype)`, which bakes in SCENARIO_PROGRAM_VERSION, so stale
        pipeline math can never be replayed. Returns a JSON-ready record:
        per-bank lists for multi-bank specs, scalars otherwise, plus
        ``scenario_fingerprint`` / ``source`` / ``latency_ms``."""
        from sbr_tpu.scenario import spec_fingerprint

        self._admit(deadline_ms)
        t0 = time.monotonic()
        key = spec_fingerprint(spec, (params, self._cfg_tag), self.config,
                               self.dtype.name)
        rec, source = self._scenario_lookup(key)
        if rec is None:
            rec = self._solve_scenario(params, spec, key)
            self._scenario_store(key, rec)
            source = "computed"
        latency = time.monotonic() - t0
        self.live.record_query(latency, source, scenario=f"spec:{key[:12]}")
        if self.demand is not None:
            self.demand.record_params(
                params, scenario=f"spec:{key[:12]}", source=source,
                kind="scenario",
            )
        return {**rec, "source": source, "latency_ms": round(latency * 1e3, 3)}

    def _solve_scenario(self, params, spec, key: str) -> dict:
        """One composed solve → the cacheable JSON record (non-finite
        floats encoded as None, the wire convention)."""
        import math as math_

        import numpy as np_

        from sbr_tpu import scenario as scen

        res = scen.solve(spec, params, config=self.config, dtype=self.dtype)

        def safe(v):
            v = float(v)
            return v if math_.isfinite(v) else None

        if spec.banks > 1:
            h = res.health
            rec = {
                "xi": [safe(v) for v in np_.asarray(res.xi)],
                "status": [int(v) for v in np_.asarray(res.status)],
                "aw_max": [safe(v) for v in np_.asarray(res.aw_max)],
                "flags": [int(v) for v in np_.asarray(h.flags)],
                "kappa_eff": [safe(v) for v in np_.asarray(res.kappa_eff)],
                "iterations": int(res.iterations),
                "converged": bool(res.converged),
                "banks": spec.banks,
            }
        else:
            rec = {
                "xi": safe(res.xi),
                "status": int(np_.asarray(res.status)),
                "flags": int(np_.asarray(res.health.flags)),
                "residual": safe(np_.asarray(res.health.residual)),
                "banks": 1,
            }
        rec["scenario_fingerprint"] = key
        return rec

    def _scenario_lookup(self, key: str) -> tuple:
        """LRU + verified-disk lookup for scenario records (stored
        verbatim — their shape varies by spec, unlike plain-query
        records). Same probe skeleton as `_lookup` (`_cache_probe`), so
        the verify-on-read/quarantine discipline can never drift between
        the two record kinds."""
        return self._cache_probe(key, self._parse_scenario_record)

    @staticmethod
    def _parse_scenario_record(path: Path):
        import json

        rec = json.loads(path.read_text())
        if not isinstance(rec, dict) or "scenario_fingerprint" not in rec:
            return None
        return rec

    def _scenario_store(self, key: str, rec: dict, write_disk: bool = True) -> None:
        self._store(key, rec, write_disk=write_disk)

    # -- population what-ifs (ISSUE 15) --------------------------------------
    def query_population(self, params, pop_doc: dict,
                         deadline_ms: Optional[float] = None) -> dict:
        """Serve one population-level what-if query (`POST /query` with a
        ``population`` object): "the ξ distribution over S seeds at these
        params" — S agent populations under an `infomodels.InfoModelSpec`
        on a graphgen spec, reduced to crossing-time quantiles + run
        probability against the model's mean-field fixed point.

        Runs in the calling thread (population programs are per-spec
        compiled; arbitrary specs don't micro-batch), under the engine's
        admission control, cached in the same LRU + verified-disk layers
        keyed by `infomodels.population_fingerprint` — which bakes in
        INFOMODEL_PROGRAM_VERSION, so stale engine math can never be
        replayed. Returns a JSON-ready record with ``source`` /
        ``population_fingerprint`` / ``latency_ms``."""
        from sbr_tpu.infomodels import population as pop

        kw = pop.parse_population_doc(pop_doc)
        self._admit(deadline_ms)
        t0 = time.monotonic()
        key = pop.population_fingerprint(
            kw, (params, self._cfg_tag), self.config, self.dtype.name
        )
        rec, source = self._cache_probe(key, self._parse_population_record)
        if rec is None:
            rec = pop.population_query(
                kw["spec"], kw["graph"], params, seeds=kw["seeds"],
                vary=kw["vary"], seed=kw["seed"], dt=kw["dt"],
                config=self.config, **(
                    {"g0": kw["g0"]} if "g0" in kw else {}
                ),
            )
            rec["population_fingerprint"] = key
            self._store(key, rec)
            source = "computed"
        latency = time.monotonic() - t0
        self.live.record_query(latency, source, scenario=f"pop:{key[:12]}")
        if self.demand is not None:
            self.demand.record_params(
                params, scenario=f"pop:{key[:12]}", source=source,
                kind="population",
            )
        return {**rec, "source": source, "latency_ms": round(latency * 1e3, 3)}

    @staticmethod
    def _parse_population_record(path: Path):
        import json

        rec = json.loads(path.read_text())
        if not isinstance(rec, dict) or "population_fingerprint" not in rec:
            return None
        return rec

    # -- result cache --------------------------------------------------------
    def _result_key(self, params: ModelParams, grads: bool = False) -> str:
        # Grads records carry grad_flags computed under the resolved
        # ill-conditioning tolerance — it joins the key so a cached answer
        # (LRU or disk, surviving restarts) can never replay flags from a
        # different SBR_GRAD_APRIME_TOL.
        tag = (self._cfg_tag, "grads", self._aprime_tol()) if grads else self._cfg_tag
        return params_fingerprint((params, tag))

    def _result_path(self, key: str) -> Optional[Path]:
        if not self.serve.cache_dir:
            return None
        return Path(self.serve.cache_dir) / "results" / key[:2] / f"{key}.json"

    def _cache_probe(self, key: str, parse_disk) -> tuple:
        """Shared LRU + verified-disk probe (plain AND scenario records):
        LRU hit first; else sha256 verify-on-read (ISSUE 11 — a digest
        mismatch is quarantined beside the cache as evidence, never
        silently deleted, and the query recomputes; sidecar-less entries
        from pre-sidecar builds verify as "legacy" and stay trusted), then
        ``parse_disk(path)``. A parser returning None — or raising
        OSError/ValueError/KeyError/TypeError (unreadable OR
        parseable-but-wrong-shape: a torn write can leave valid non-dict
        JSON, which must not kill the batcher thread) — rejects the entry
        and the query recomputes."""
        with self._lru_lock:
            rec = self._lru.get(key)
            if rec is not None:
                self._lru.move_to_end(key)
                return dict(rec), "lru"
        path = self._result_path(key)
        if path is not None and path.exists():
            from sbr_tpu.resilience import heal

            try:
                if heal.verify_file(path) == "mismatch":
                    heal.quarantine(path, reason="serve-cache-mismatch")
                    return None, None
            except OSError:
                return None, None
            try:
                rec = parse_disk(path)
            except (OSError, ValueError, KeyError, TypeError):
                return None, None
            if rec is None:
                return None, None
            self._store(key, rec, write_disk=False)
            return dict(rec), "disk"
        return None, None

    def _lookup(self, key: str) -> tuple:
        return self._cache_probe(key, self._parse_plain_record)

    @staticmethod
    def _parse_plain_record(path: Path) -> dict:
        import json

        raw = json.loads(path.read_text())
        rec = {
            "xi": float(raw["xi"]),
            "tau_bar_in": float(raw["tau_bar_in"]),
            "aw_max": float(raw["aw_max"]),
            "status": int(raw["status"]),
            "flags": int(raw["flags"]),
            "residual": float(raw["residual"]),
        }
        # Grad records are a superset (ISSUE 13): a grads=true entry
        # restored from disk must keep its sensitivities.
        for k in ("dxi_dbeta", "dxi_du", "dxi_dkappa"):
            if k in raw:
                rec[k] = float(raw[k])
        if "grad_flags" in raw:
            rec["grad_flags"] = int(raw["grad_flags"])
        return rec

    def _store(self, key: str, rec: dict, write_disk: bool = True) -> None:
        with self._lru_lock:
            self._lru[key] = dict(rec)
            self._lru.move_to_end(key)
            while len(self._lru) > self.serve.lru_max:
                self._lru.popitem(last=False)
        path = self._result_path(key)
        if write_disk and path is not None:
            import json

            try:
                path.parent.mkdir(parents=True, exist_ok=True)
                fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
                with os.fdopen(fd, "w") as f:
                    f.write(json.dumps(rec))
                os.replace(tmp, path)
                # sha256 sidecar for verify-on-read (best-effort, after the
                # rename — the window leaves a "legacy"-trusted entry, same
                # discipline as the tile checkpoints).
                try:
                    from sbr_tpu.resilience import heal

                    heal.write_sidecar(path)
                except OSError:
                    pass
                self._disk_writes += 1
                if self._disk_writes % 512 == 0:
                    self._prune_disk_cache()
            except OSError:
                pass  # the disk layer is best-effort; the LRU already has it

    def _prune_disk_cache(self) -> None:
        """Bound the results/ tree at ``disk_cap`` entries, evicting oldest
        by mtime (the serve-cache analogue of `report gc`'s run retention).
        Best-effort and rare (every 512 writes) — a concurrent reader of a
        pruned entry just recomputes."""
        cap = self.serve.disk_cap
        if cap <= 0 or not self.serve.cache_dir:
            return
        try:
            root = Path(self.serve.cache_dir) / "results"
            # quarantine/ dirs hold verify-on-read EVIDENCE: they neither
            # count toward the cap nor get pruned here (an explicit
            # `report gc` clears them, same contract as the tile caches).
            entries = [
                (p.stat().st_mtime, p)
                for p in root.rglob("*.json")
                if "quarantine" not in p.parts
            ]
            if len(entries) <= cap:
                return
            entries.sort()
            for _, p in entries[: len(entries) - cap]:
                try:
                    p.unlink()
                except OSError:
                    pass
                try:  # the verify-on-read sidecar goes with its entry
                    Path(str(p) + ".sha256").unlink()
                except OSError:
                    pass
            if self._run is not None:
                self._run.event(
                    "serve_cache_prune", removed=len(entries) - cap, cap=cap
                )
        except OSError:
            pass

    # -- executable cache -----------------------------------------------------
    def _exec_path(self, bucket: int, grads: bool = False) -> Optional[Path]:
        if not self.serve.cache_dir:
            return None
        import jax

        d = jax.devices()[0]
        key = hashlib.sha256(
            canonicalize(
                (
                    self._cfg_tag,
                    int(bucket),
                    jax.__version__,
                    d.platform,
                    d.device_kind,
                    # grads execs bake the resolved ill-conditioning
                    # tolerance into the program; a different tolerance
                    # must miss, not reload a stale executable.
                    ("grads", self._aprime_tol()) if grads else "plain",
                )
            ).encode()
        ).hexdigest()[:24]
        kind = "grad_batch" if grads else "batch"
        return Path(self.serve.cache_dir) / "execs" / f"serve_{kind}_{bucket}_{key}.pkl"

    def _abstract_args(self, bucket: int) -> tuple:
        import jax

        return tuple(jax.ShapeDtypeStruct((bucket,), self.dtype) for _ in range(9))

    def _aprime_tol(self) -> float:
        """The resolved SBR_GRAD_APRIME_TOL for this engine's dtype, read
        per call so grads programs/executables key on the CURRENT value."""
        from sbr_tpu.grad.cell import aprime_tol

        return aprime_tol(self.dtype)

    def _exec(self, bucket: int, grads: bool = False):
        """The compiled executable for one (bucket, program-kind) shape:
        in-memory, else deserialized from the cache dir (restart warm
        path), else freshly lowered + compiled (and serialized back,
        best-effort). ``grads`` selects the grad batch program."""
        cache_key = (bucket, grads, self._aprime_tol() if grads else None)
        exec_ = self._execs.get(cache_key)
        if exec_ is not None:
            return exec_
        path = self._exec_path(bucket, grads)
        if path is not None and path.exists():
            try:
                from jax.experimental.serialize_executable import deserialize_and_load

                payload, in_tree, out_tree = pickle.loads(path.read_bytes())
                exec_ = deserialize_and_load(payload, in_tree, out_tree)
                self._execs[cache_key] = exec_
                self._exec_meta["loaded"] += 1
                if self._run is not None:
                    self._run.event("serve_exec", bucket=bucket, source="deserialized",
                                    grads=grads, path=str(path))
                return exec_
            except Exception as err:
                # A stale/foreign blob must never sink serving: recompile.
                self._exec_meta["aot"] = f"reload failed ({type(err).__name__})"
        from sbr_tpu import obs

        t0 = time.monotonic()
        with obs.span(f"serve.compile[{bucket}{'g' if grads else ''}]"):
            fn = (
                _grad_batch_fn(self.config, self.dtype.name, self._aprime_tol())
                if grads
                else _batch_fn(self.config, self.dtype.name)
            )
            compiled = fn.lower(*self._abstract_args(bucket)).compile()
        self._execs[cache_key] = compiled
        self._exec_meta["compiled"] += 1
        if self._run is not None:
            try:
                self._run.event(
                    "serve_exec", bucket=bucket, source="compiled", grads=grads,
                    compile_s=round(time.monotonic() - t0, 3),
                )
            except Exception:
                pass
        if path is not None:
            self._serialize_exec(compiled, path, bucket)
        return compiled

    def _serialize_exec(self, compiled, path: Path, bucket: int) -> None:
        try:
            from jax.experimental.serialize_executable import serialize

            blob = pickle.dumps(serialize(compiled))
            path.parent.mkdir(parents=True, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
            self._exec_meta["serialized"] += 1
        except Exception as err:
            # Backends without executable serialization (or read-only cache
            # dirs) just pay the compile on each restart — record why.
            self._exec_meta["aot"] = f"disabled ({type(err).__name__}: {err})"
