"""Live windowed metrics for the serving engine (ISSUE 7 tentpole, part 2).

Everything PRs 1-5 built is per-run, post-hoc: a JSONL directory inspected
after the process exits. A long-lived query server needs the opposite —
streaming, windowed, queryable-while-alive telemetry, the way torchode
(PAPERS.md) exposes per-solve step/accept statistics as first-class outputs
rather than logs. This module is that layer:

- **Windowed aggregation** via a ring of time slots: the window (default
  60 s, ``SBR_SERVE_WINDOW_S``) is divided into `_N_SLOTS` slots, each
  holding a log-bucketed latency histogram (`obs.metrics.LogHistogram`)
  plus plain counters. Recording touches only the current slot; snapshots
  fold the live slots. Slots are replaced by single reference assignment
  and counters are int increments, so the hot path needs NO lock under
  CPython — the worst cross-thread race drops one count from a rolling
  window, never corrupts state (same contract as `LogHistogram`).
- **Lifetime totals** next to the window: Prometheus counters must be
  monotone, and `report serve` wants both views.
- **Rolling ``live.json``**: `maybe_write` snapshots the document into the
  run directory via `RunContext.live_snapshot` (atomic rename, like the
  manifest) at a bounded cadence, so ``python -m sbr_tpu.obs.report serve
  RUN_DIR`` can read a RUNNING server — and the final write at engine
  close leaves the post-hoc artifact.

Latency histograms are log-bucketed (bounded relative error), so p50/p95/
p99 are derivable from the buckets — both here and by any Prometheus
backend scraping ``/metrics``.

No jax import anywhere: live metrics are pure host accounting.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

from sbr_tpu.obs.metrics import DEFAULT_LATENCY_BOUNDS_MS as LATENCY_BOUNDS_MS
from sbr_tpu.obs.metrics import LogHistogram

_N_SLOTS = 12

SCHEMA = "sbr-serve-live/1"


def window_seconds() -> float:
    env = os.environ.get("SBR_SERVE_WINDOW_S", "").strip()
    return float(env) if env else 60.0


class _Slot:
    """One time slot of the rolling window."""

    __slots__ = ("epoch", "hist", "counters")

    def __init__(self, epoch: int) -> None:
        self.epoch = epoch
        self.hist = LogHistogram(LATENCY_BOUNDS_MS)
        self.counters: Dict[str, float] = {}

    def inc(self, name: str, n: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n


# Counter keys shared by slots and totals. "queries" counts fulfilled
# queries; "cache_hits" = LRU or disk hits, split out as "disk_hits";
# "computed" = queries that went through a device dispatch; "shed" =
# queries rejected at admission because their deadline could not be met
# (ISSUE 11 backpressure — an explicit 429, never silent queue growth);
# "degraded" = queries answered from the degradation ladder (global tile
# cache) while the solver path was unavailable.
_COUNTERS = (
    "queries",
    "cache_hits",
    "disk_hits",
    "cache_misses",
    "computed",
    "errors",
    "divergent_cells",
    "shed",
    "degraded",
    "batches",
    "batch_queries",
    "padded_lanes",
)


class LiveMetrics:
    """Windowed + lifetime serving metrics (see module docstring).

    ``time_fn`` is injectable so tests can drive window expiry without
    sleeping."""

    _MAX_SCENARIOS = 64  # distinct tags tracked; overflow folds into _other

    def __init__(self, window_s: Optional[float] = None, time_fn=time.monotonic) -> None:
        self.window_s = float(window_s) if window_s else window_seconds()
        self._slot_s = self.window_s / _N_SLOTS
        self._time = time_fn
        self._slots = [_Slot(-1) for _ in range(_N_SLOTS)]
        self.totals: Dict[str, float] = {k: 0 for k in _COUNTERS}
        self.total_hist = LogHistogram(LATENCY_BOUNDS_MS)
        self.scenarios: Dict[str, int] = {}
        self.queue_depth = 0
        self.inflight = 0
        self.started_at = time.time()
        self._t0 = self._time()
        self._last_write = 0.0

    # -- recording (engine threads) -----------------------------------------
    def _slot(self) -> _Slot:
        epoch = int(self._time() / self._slot_s)
        pos = epoch % _N_SLOTS
        slot = self._slots[pos]
        if slot.epoch != epoch:
            # Replace stale slot wholesale: one reference assignment, so a
            # concurrent reader folds either the old or the new slot.
            slot = _Slot(epoch)
            self._slots[pos] = slot
        return slot

    def record_query(
        self,
        latency_s: float,
        source: str,
        scenario: str = "default",
        divergent: bool = False,
    ) -> None:
        """One fulfilled query: ``source`` is "lru", "disk", "coalesced"
        (deduplicated against an identical query in the same batch — no
        device work, so it counts as a cache hit), or "computed"."""
        ms = latency_s * 1e3
        slot = self._slot()
        slot.hist.record(ms)
        self.total_hist.record(ms)
        keys = ["queries"]
        if source in ("lru", "disk", "coalesced"):
            keys.append("cache_hits")
            if source == "disk":
                keys.append("disk_hits")
        elif source == "tilecache":
            # Degradation-ladder answer (ISSUE 11): served from the global
            # tile cache while the solver path was down — neither a cache
            # hit (it bypassed the serve caches) nor a computed query.
            keys.append("degraded")
        else:
            keys += ["cache_misses", "computed"]
        if divergent:
            keys.append("divergent_cells")
        for k in keys:
            slot.inc(k)
            self.totals[k] += 1
        # Scenario tags are caller-chosen strings: cap the table so a
        # long-lived server with per-request-derived tags cannot grow the
        # snapshot (and its 0.5 s rewrites) without bound — the same
        # bounded-memory contract as the histograms and the disk cap.
        if scenario in self.scenarios or len(self.scenarios) < self._MAX_SCENARIOS:
            self.scenarios[scenario] = self.scenarios.get(scenario, 0) + 1
        else:
            self.scenarios["_other"] = self.scenarios.get("_other", 0) + 1

    def record_error(self, n: int = 1) -> None:
        self._slot().inc("errors", n)
        self.totals["errors"] += n

    def record_shed(self, n: int = 1) -> None:
        """One query rejected at admission (deadline unmeetable): explicit
        load shedding, counted so backpressure is observable, never silent."""
        self._slot().inc("shed", n)
        self.totals["shed"] += n

    def record_batch(self, n_queries: int, bucket: int) -> None:
        """One device dispatch: ``bucket`` lanes launched for ``n_queries``
        real queries (occupancy = batch_queries / padded capacity)."""
        slot = self._slot()
        slot.inc("batches")
        slot.inc("batch_queries", n_queries)
        slot.inc("padded_lanes", bucket - n_queries)
        self.totals["batches"] += 1
        self.totals["batch_queries"] += n_queries
        self.totals["padded_lanes"] += bucket - n_queries

    # -- reading (endpoint / snapshot threads) ------------------------------
    def _window_fold(self) -> tuple:
        """(hist, counters) folded over the slots still inside the window.

        ONE fold is one coherent read of the 12-slot ring (ISSUE 11
        satellite): every consumer of a given exposition — the `/statz`
        document's ``window`` section, the ``healthz`` verdict embedded in
        the same document, the Prometheus gauges of one scrape — must
        derive from a SINGLE fold, passed down as a ``window`` dict, not
        re-fold per reader. Two folds taken microseconds apart can span a
        slot rotation and disagree (a scrape racing `record_query` would
        report a healthz divergence count from a different window than the
        ``divergent_cells`` gauge beside it)."""
        min_epoch = int(self._time() / self._slot_s) - _N_SLOTS + 1
        hist = LogHistogram(LATENCY_BOUNDS_MS)
        counters: Dict[str, float] = {k: 0 for k in _COUNTERS}
        for slot in list(self._slots):
            if slot.epoch < min_epoch:
                continue
            hist.add(slot.hist)
            # list() snapshot: the recording thread may insert a new counter
            # key mid-iteration (lock-free contract — a torn read drops one
            # count from a rolling window, never raises).
            for k, v in list(slot.counters.items()):
                counters[k] = counters.get(k, 0) + v
        return hist, counters

    @staticmethod
    def _derived(counters: Dict[str, float]) -> dict:
        q = counters.get("queries", 0)
        hits = counters.get("cache_hits", 0)
        launched = counters.get("batch_queries", 0) + counters.get("padded_lanes", 0)
        return {
            "hit_rate": round(hits / q, 4) if q else None,
            "occupancy": (
                round(counters.get("batch_queries", 0) / launched, 4) if launched else None
            ),
        }

    def window(self) -> dict:
        """The rolling-window view, from exactly ONE fold of the slot ring
        (counters, derived rates, and both latency renderings all come from
        the same (hist, counters) pair — internally consistent by
        construction). Callers that embed the window into a larger document
        alongside window-derived verdicts (`Engine.statz`) take this dict
        once and pass it down instead of re-folding."""
        hist, counters = self._window_fold()
        return {
            "window_s": self.window_s,
            **{k: counters.get(k, 0) for k in _COUNTERS},
            **self._derived(counters),
            "latency_ms": hist.summary(),
            "latency_hist_ms": hist.to_dict(),
        }

    def snapshot(self, extra: Optional[dict] = None,
                 window: Optional[dict] = None) -> dict:
        """The full live document — `live.json` body and `/statz` payload.
        ``window`` (a prior `window()` result) lets the caller share one
        fold between this document and any window-derived extras (the
        healthz verdict) — see `_window_fold` on why that matters."""
        from sbr_tpu.obs import prof

        doc = {
            "schema": SCHEMA,
            "ts": round(time.time(), 3),
            "started_at": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.localtime(self.started_at)
            ),
            "uptime_s": round(self._time() - self._t0, 3),
            "queue_depth": self.queue_depth,
            "inflight": self.inflight,
            "totals": {
                **{k: self.totals.get(k, 0) for k in _COUNTERS},
                **self._derived(self.totals),
                "latency_ms": self.total_hist.summary(),
            },
            "window": window if window is not None else self.window(),
            "scenarios": dict(sorted(list(self.scenarios.items()))),
            # Compile/retrace counters ride along so a scrape — not a log
            # grep — proves "zero post-warmup compiles" (acceptance gate).
            "compile": {
                "traces": {
                    k: v for k, v in sorted(prof.trace_counts().items())
                    if k.startswith("serve.")
                },
                **{
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in prof.compile_totals().items()
                },
            },
        }
        if extra:
            doc.update(extra)
        return doc

    def write_due(self, min_interval_s: float = 0.5) -> bool:
        """Whether `maybe_write` would actually write — callers on hot/idle
        loops check this FIRST so the snapshot document (healthz + window
        fold) is only built when a write will land, not 20×/s only for the
        throttle to discard it."""
        return self._time() - self._last_write >= min_interval_s

    def maybe_write(self, run, extra: Optional[dict] = None,
                    min_interval_s: float = 0.5, force: bool = False,
                    window: Optional[dict] = None) -> bool:
        """Write the rolling ``live.json`` through ``run.live_snapshot``
        at a bounded cadence (``force`` for the final write at close).
        ``window`` shares the caller's fold like `snapshot`. Returns
        whether a write happened; never raises — live telemetry must not
        sink the serving path."""
        if run is None:
            return False
        now = self._time()
        if not force and now - self._last_write < min_interval_s:
            return False
        self._last_write = now
        try:
            run.live_snapshot(self.snapshot(extra, window=window))
            return True
        except Exception:
            return False

    # -- prometheus exposition ----------------------------------------------
    def to_prometheus(self, extra: Optional[dict] = None) -> str:
        """Prometheus text exposition (0.0.4): lifetime counters, window
        gauges, the cumulative latency histogram, and compile counters.
        ``extra`` maps name -> (type, value) for engine-owned series."""
        from sbr_tpu.obs import prof

        lines = []
        for k in _COUNTERS:
            name = f"sbr_serve_{k}_total"
            lines += [f"# TYPE {name} counter", f"{name} {int(self.totals.get(k, 0))}"]
        hist, counters = self._window_fold()
        derived = self._derived(counters)
        window_gauges = {
            "sbr_serve_queue_depth": self.queue_depth,
            "sbr_serve_inflight": self.inflight,
            "sbr_serve_window_queries": counters.get("queries", 0),
            "sbr_serve_window_hit_rate": derived["hit_rate"],
            "sbr_serve_window_occupancy": derived["occupancy"],
            "sbr_serve_window_divergent_cells": counters.get("divergent_cells", 0),
            "sbr_serve_window_shed": counters.get("shed", 0),
            "sbr_serve_window_degraded": counters.get("degraded", 0),
        }
        for q in (0.5, 0.95, 0.99):
            v = hist.quantile(q)
            window_gauges[f"sbr_serve_window_latency_ms_p{int(q * 100)}"] = v
        for name, v in window_gauges.items():
            lines += [f"# TYPE {name} gauge", f"{name} {'NaN' if v is None else f'{v:g}'}"]
        lines += self.total_hist.to_prometheus("sbr_serve_latency_ms")
        totals = prof.compile_totals()
        lines += [
            "# TYPE sbr_serve_xla_compiles_total counter",
            f"sbr_serve_xla_compiles_total {int(totals.get('compiles', 0))}",
            "# TYPE sbr_serve_backend_compile_seconds_total counter",
            f"sbr_serve_backend_compile_seconds_total {totals.get('backend_compile_s', 0.0):g}",
        ]
        traces = {k: v for k, v in sorted(prof.trace_counts().items()) if k.startswith("serve.")}
        lines.append("# TYPE sbr_serve_traces_total counter")
        for k, v in traces.items():
            lines.append(f'sbr_serve_traces_total{{program="{k}"}} {int(v)}')
        if not traces:
            lines.append('sbr_serve_traces_total{program="serve.batch"} 0')
        for name, (typ, value) in (extra or {}).items():
            lines += [f"# TYPE {name} {typ}", f"{name} {value:g}"]
        return "\n".join(lines) + "\n"
