"""Equilibrium serving layer: a long-lived query engine with live,
queryable-while-alive telemetry (ISSUE 7; the ROADMAP's "bank-run weather
service" serving item).

- ``serve.engine``   — `Engine`: micro-batched (`ModelParams`, scenario)
  queries padded to a fixed bucket ladder into one vmapped dispatch of
  the sweeps' `solve_param_cell`; LRU + on-disk result cache keyed by
  `utils.checkpoint.params_fingerprint`; serialized AOT executables
  reloaded across restarts.
- ``serve.live``     — `LiveMetrics`: lock-free windowed aggregation
  (log-bucket latency histograms with derivable p50/p95/p99, cache and
  batch-occupancy counters, per-window divergent cells) snapshotted to a
  rolling ``live.json`` in the run dir.
- ``serve.endpoint`` — `ServeEndpoint`: stdlib HTTP ``/metrics``
  (Prometheus), ``/healthz`` (ready/degraded/unhealthy), ``/statz``, and
  the ``/query`` route (JSON params in, labeled equilibrium out; 429 +
  ``Retry-After`` on admission shed).
- ``serve.fleet``    — fleet membership (elastic heartbeats in a shared
  ``SBR_FLEET_DIR``), the `CircuitBreaker` state machine, the
  `TileCacheBridge` degradation-ladder rung, and the worker process
  entry (``python -m sbr_tpu.serve.fleet``) with graceful SIGTERM drain.
- ``serve.router``   — `Router`: throughput-weighted routing over the
  live workers with failover, hedged retries, deadline propagation, and
  per-worker circuit breakers (``python -m sbr_tpu.serve.router``).
- ``serve.loadgen``  — ``python -m sbr_tpu.serve.loadgen``: seeded
  deterministic query mix for CI and bench; ``--fleet N`` drives a
  multi-process worker fleet through an in-process router (the SLO bench
  and the chaos fleet smoke ride it).

Gate a (running or finished) server with
``python -m sbr_tpu.obs.report serve RUN_DIR [--json]`` and a router run
with ``python -m sbr_tpu.obs.report fleet RUN_DIR [--json]`` (exit 1 on
lost queries or a breaker stuck open).
"""

from sbr_tpu.serve.endpoint import ServeEndpoint
from sbr_tpu.serve.engine import (
    DeadlineExceeded,
    Engine,
    QueryResult,
    ServeConfig,
    SolverUnavailable,
)
from sbr_tpu.serve.fleet import CircuitBreaker, TileCacheBridge
from sbr_tpu.serve.live import LiveMetrics
from sbr_tpu.serve.router import Router

__all__ = [
    "CircuitBreaker",
    "DeadlineExceeded",
    "Engine",
    "LiveMetrics",
    "QueryResult",
    "Router",
    "ServeConfig",
    "ServeEndpoint",
    "SolverUnavailable",
    "TileCacheBridge",
]
