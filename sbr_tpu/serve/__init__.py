"""Equilibrium serving layer: a long-lived query engine with live,
queryable-while-alive telemetry (ISSUE 7; the ROADMAP's "bank-run weather
service" serving item).

- ``serve.engine``   — `Engine`: micro-batched (`ModelParams`, scenario)
  queries padded to a fixed bucket ladder into one vmapped dispatch of
  the sweeps' `solve_param_cell`; LRU + on-disk result cache keyed by
  `utils.checkpoint.params_fingerprint`; serialized AOT executables
  reloaded across restarts.
- ``serve.live``     — `LiveMetrics`: lock-free windowed aggregation
  (log-bucket latency histograms with derivable p50/p95/p99, cache and
  batch-occupancy counters, per-window divergent cells) snapshotted to a
  rolling ``live.json`` in the run dir.
- ``serve.endpoint`` — `ServeEndpoint`: stdlib HTTP ``/metrics``
  (Prometheus), ``/healthz`` (ready/degraded/unhealthy), ``/statz``.
- ``serve.loadgen``  — ``python -m sbr_tpu.serve.loadgen``: seeded
  deterministic query mix for CI and bench.

Gate a (running or finished) server with
``python -m sbr_tpu.obs.report serve RUN_DIR [--json]``.
"""

from sbr_tpu.serve.endpoint import ServeEndpoint
from sbr_tpu.serve.engine import Engine, QueryResult, ServeConfig
from sbr_tpu.serve.live import LiveMetrics

__all__ = ["Engine", "LiveMetrics", "QueryResult", "ServeConfig", "ServeEndpoint"]
