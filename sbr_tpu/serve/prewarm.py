"""Self-healing prefetch controller (ISSUE 19 tentpole): autonomous
plan-driven sweeps that turn the degradation ladder into a *prefetch*
ladder.

PR 18 closed the observation half of the serving↔sweep loop — the demand
observatory merges fleet (β, u) surfaces and ranks `advisor_plan.json`
tiles — but nothing acted on the plan. This module is the actuator:

- **`PrewarmController`** runs on fleet workers (gated by ``SBR_PREWARM``,
  the `AuditScheduler` idiom: a daemon thread that only works while the
  engine is idle and `/healthz` is "ready" — prefetch never rides a batch
  window or a sick solver) and as a standalone sweeper role
  (``python -m sbr_tpu.serve.prewarm --plan PLAN --once``).
- **Crash-safe tile execution on the PR 7 elastic substrate.** Each plan
  tile is claimed with the same O_EXCL lease files the elastic scheduler
  uses (`parallel.distributed._try_lease` — expired-lease takeover with
  nonce verification), so N sweepers sharing a state dir never duplicate
  a tile and a SIGKILLed sweeper's claims are adopted by a peer at the
  lease TTL. Sweeper liveness rides `elastic.Heartbeat` files beside the
  leases. Tiles run through `utils.checkpoint.tile_runner`/`produce`
  with ``scenario_spec=None``, so every computed tile lands in the
  cross-run `TileCache` WITH its ``cell_tag`` meta sidecar — exactly
  what `serve.fleet.TileCacheBridge` needs to serve the cell warm during
  a breaker-open outage.
- **Retries** are `RetryPolicy` exponential backoff under a per-plan
  `RetryBudget` (``SBR_PREWARM_RETRY_*`` knobs); a tile that exhausts its
  attempts is counted failed and skipped, never spun on.
- **Plan epochs.** The controller watches the plan file's mtime; a new
  ``plan_fingerprint`` abandons the stale plan's remaining tiles at the
  next tile boundary (counted ``abandon`` reason "stale") and starts the
  new epoch in its own fingerprint-keyed state dir.
- **Hard work budget.** ``SBR_PREWARM_BUDGET_TILES`` /
  ``SBR_PREWARM_BUDGET_SECONDS`` bound one plan's sweep; exhaustion
  abandons the remainder (reason "budget" — `report prewarm` gates on
  it: an underprovisioned budget must fail loudly, not pass cold).
- **Fail-closed on program-version mismatch.** A plan stamped with a
  ``program_version`` other than the current
  `sweeps.baseline_sweeps.GRID_PROGRAM_VERSION` is rejected outright,
  and the cache key itself embeds the version — a prewarmed tile can
  never be served by a solver generation that didn't produce it.

``SBR_PREWARM=0`` (the default) is a STRUCTURAL no-op: the engine never
imports this module, ``/metrics`` stays byte-free of ``sbr_prewarm``,
zero new XLA traces, answers bit-identical (tests/test_prewarm.py).

Fault points (`resilience.faults`): ``prewarm.plan_load`` before every
plan read/parse and ``prewarm.sweep`` before every tile attempt — the
chaos ``--prewarm`` drill hangs a sweeper mid-plan through the latter.

Module import stays jax-free (stdlib only): `obs.report gc
--prewarm-keep` and the chaos driver import it on boxes that must never
wake a backend.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

#: The advisor plan schema this controller executes (obs.demand).
PLAN_SCHEMA = "sbr-demand-advisor/1"

_DONE_PREFIX = "done_"


# ---------------------------------------------------------------------------
# Knobs
# ---------------------------------------------------------------------------


def enabled() -> bool:
    """``SBR_PREWARM`` truthy — the engine-side structural gate."""
    return os.environ.get("SBR_PREWARM", "").strip() not in ("", "0")


def poll_s() -> float:
    raw = os.environ.get("SBR_PREWARM_POLL_S", "").strip()
    return float(raw) if raw else 2.0


def budget_tiles(value: Optional[int] = None) -> Optional[int]:
    """Hard per-plan tile budget (``SBR_PREWARM_BUDGET_TILES``, default
    256; <= 0 disables the bound)."""
    if value is None:
        raw = os.environ.get("SBR_PREWARM_BUDGET_TILES", "").strip()
        value = int(raw) if raw else 256
    value = int(value)
    return value if value > 0 else None


def budget_seconds(value: Optional[float] = None) -> Optional[float]:
    """Hard per-plan wall-clock budget (``SBR_PREWARM_BUDGET_SECONDS``,
    default unbounded; <= 0 disables the bound)."""
    if value is None:
        raw = os.environ.get("SBR_PREWARM_BUDGET_SECONDS", "").strip()
        if not raw:
            return None
        value = float(raw)
    value = float(value)
    return value if value > 0 else None


def lease_ttl_s(value: Optional[float] = None) -> float:
    """Tile-lease TTL: the elastic scheduler's knob, shared verbatim —
    adoption timing is one fleet-wide property, not a prewarm one."""
    if value is not None:
        return float(value)
    return float(os.environ.get("SBR_STEAL_LEASE_TTL_S", "900"))


def state_dir(value=None, cache_root=None) -> Optional[Path]:
    """Where sweepers rendezvous (leases + heartbeats + done markers per
    plan epoch): ``SBR_PREWARM_STATE_DIR``, else ``<tile cache>/_prewarm``
    (the two-hex shard layout never collides with an underscore name).
    None = no tile cache configured, nothing to prewarm into."""
    root = value or os.environ.get("SBR_PREWARM_STATE_DIR", "").strip()
    if root:
        return Path(root)
    if cache_root is not None:
        return Path(cache_root) / "_prewarm"
    return None


def _program_version() -> int:
    from sbr_tpu.sweeps.baseline_sweeps import GRID_PROGRAM_VERSION

    return int(GRID_PROGRAM_VERSION)


# ---------------------------------------------------------------------------
# Plan loading
# ---------------------------------------------------------------------------


def load_plan(path) -> Optional[dict]:
    """Read + validate one advisor plan, through the ``prewarm.plan_load``
    fault point. Returns None on a missing/torn/alien file or an injected
    fault (the controller keeps its current epoch), and raises nothing:
    plan ingestion must never take down a serve worker."""
    from sbr_tpu.resilience import faults
    from sbr_tpu.resilience.faults import InjectedFault

    path = Path(path)
    try:
        faults.fire("prewarm.plan_load", target=path.name)
        doc = json.loads(path.read_text())
    except (InjectedFault, OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("schema") != PLAN_SCHEMA:
        return None
    if not doc.get("plan_fingerprint") or not isinstance(doc.get("tiles"), list):
        return None
    return doc


def _plan_tiles(plan: dict) -> List[dict]:
    """The executable tile list, in advisor rank order.

    Each advisor tile (one hot bin's heavy-hitter β/u axes) is EXPANDED
    into one executable tile per distinct β: `cell_tag` includes the
    derived η and tspan — which `make_model_params` resolves from β
    (η = η̄/β, tspan = (0, 2η)) — so a single sweep base can only ever
    match queries at its own β. Per-β tiles, each swept under the
    canonical base for that β, are exactly the cells the serving pool's
    queries tag-match (asserted by the chaos ``--prewarm`` drill's
    byte-identity replay).

    Lease coordinates (and the matching done-marker names) are the
    tile's INDEX in this fully sorted expansion — deterministic across
    sweepers because the plan bytes are fingerprint-keyed."""
    expanded = []
    for t in plan.get("tiles") or []:
        try:
            bi, ui = (int(v) for v in str(t["bin"]).split(","))
            betas = sorted({float(b) for b in t["betas"]})
            us = sorted({float(u) for u in t["us"]})
        except (KeyError, TypeError, ValueError):
            continue
        if not betas or not us:
            continue
        for b in betas:
            expanded.append({
                "bin": f"{bi},{ui}", "beta": b, "us": us,
                "rank": t.get("rank"),
            })
    expanded.sort(key=lambda t: (t["rank"] is None, t["rank"], t["bin"], t["beta"]))
    for n, t in enumerate(expanded):
        t["id"] = f"t{n:05d}_00000"
        t["lease"] = (n, 0)
        t["betas"] = [t.pop("beta")]
    return expanded


def _log_prewarm(action: str, **fields) -> None:
    """Guarded telemetry hook (the `elastic._log_sched` shape): only
    touches obs when a run is already active."""
    try:
        from sbr_tpu import obs

        obs.log_prewarm(action, **fields)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# The controller
# ---------------------------------------------------------------------------


class PrewarmController:
    """Plan-driven background prefetch sweeps (see module docstring).

    Embedded in a serve engine (``engine=...``) it defers to traffic:
    tiles only run while the engine is idle AND `/healthz` is "ready".
    Standalone (``engine=None``, the sweeper role) it is always
    admissible. ``run_cycle()``/`step()` are the synchronous test hooks;
    the `start()`ed daemon thread drives the same methods."""

    def __init__(self, engine=None, plan_file=None, state_root=None,
                 base=None, config=None, dtype=None, cache_dir=None,
                 max_tiles: Optional[int] = None,
                 max_seconds: Optional[float] = None,
                 ttl_s: Optional[float] = None,
                 scenario_spec=None) -> None:
        self.engine = engine
        self._plan_file = Path(plan_file) if plan_file else None
        self._state_root = Path(state_root) if state_root else None
        self._base = base
        self._config = config
        self._dtype = dtype
        self._cache_dir = cache_dir
        self._max_tiles = budget_tiles(max_tiles)
        self._max_seconds = budget_seconds(max_seconds)
        self._ttl_s = lease_ttl_s(ttl_s)
        self.scenario_spec = scenario_spec

        self.status = "idle"  # idle|sweeping|done|budget_exhausted|rejected|no_cache
        self.counts: Dict[str, int] = {
            "plans": 0, "plans_rejected": 0, "plan_errors": 0,
            "tiles_done": 0, "computed": 0, "cache": 0, "local": 0,
            "failed": 0, "adopted": 0,
            "abandoned_stale": 0, "abandoned_budget": 0,
        }
        self._plan: Optional[dict] = None
        self._plan_fp: Optional[str] = None
        self._plan_mtime: Optional[float] = None
        self._plan_dir: Optional[Path] = None
        self._tiles: List[dict] = []
        self._failed_tiles: set = set()
        self._tiles_run = 0
        self._plan_started: Optional[float] = None
        self._warm: Optional[int] = None
        self._cache = None
        self._cache_resolved = False
        self._retry_budget = None
        self._policy = None
        self._hb = None

        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --
    def start(self) -> "PrewarmController":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="sbr-prewarm", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self._withdraw_hb()

    def _withdraw_hb(self) -> None:
        if self._hb is not None:
            try:
                self._hb.withdraw()
            except Exception:
                pass
            self._hb = None

    # -- scheduling --
    def _admissible(self) -> bool:
        """Yield-to-traffic admission: an embedded controller never takes
        the device while the engine has inflight/queued work or /healthz
        is anything but "ready" (the `AuditScheduler` idle check plus the
        health verdict — a degraded window means the ladder may be
        answering queries, the worst moment to add solver load)."""
        eng = self.engine
        if eng is None:
            return True
        try:
            if eng.live.inflight or eng.live.queue_depth or eng._queue.qsize():
                return False
            return eng.healthz()["status"] == "ready"
        except Exception:
            return False

    def _loop(self) -> None:
        next_poll = 0.0
        while not self._stop.wait(0.25):
            now = time.monotonic()
            if now >= next_poll:
                self.poll_plan()
                next_poll = now + poll_s()
            if self.status != "sweeping" or not self._admissible():
                continue
            self.step()

    def run_cycle(self) -> Optional[dict]:
        """One synchronous controller beat: refresh the plan, then run one
        tile if admissible (the test hook)."""
        self.poll_plan()
        if self.status != "sweeping" or not self._admissible():
            return None
        return self.step()

    # -- cache / plan resolution --
    def _resolve_cache(self):
        if not self._cache_resolved:
            from sbr_tpu.resilience.elastic import default_tile_cache

            self._cache = default_tile_cache(self._cache_dir)
            self._cache_resolved = True
        return self._cache

    def _resolve_plan_file(self) -> Optional[Path]:
        if self._plan_file is not None:
            return self._plan_file
        raw = os.environ.get("SBR_PREWARM_PLAN", "").strip()
        if raw:
            return Path(raw)
        run = getattr(self.engine, "_run", None)
        if run is not None and getattr(run, "run_dir", None):
            return Path(run.run_dir) / "advisor_plan.json"
        return None

    def poll_plan(self) -> bool:
        """(Re)load the watched plan when its file changed; a new
        fingerprint abandons the stale epoch's remaining tiles at this
        boundary. Returns True while an epoch is active."""
        cache = self._resolve_cache()
        if cache is None:
            self.status = "no_cache"
            return False
        path = self._resolve_plan_file()
        if path is None:
            if self._plan is None:
                self.status = "idle"
            return self._plan is not None
        try:
            mtime = path.stat().st_mtime
        except OSError:
            return self._plan is not None
        if self._plan is not None and mtime == self._plan_mtime:
            return True
        plan = load_plan(path)
        if plan is None:
            with self._lock:
                self.counts["plan_errors"] += 1
            _log_prewarm("plan_error", path=str(path))
            self._plan_mtime = mtime  # don't re-parse a torn file every tick
            return self._plan is not None
        self._plan_mtime = mtime
        if plan["plan_fingerprint"] == self._plan_fp:
            return True
        self._adopt_plan(plan)
        return self._plan is not None

    def _adopt_plan(self, plan: dict) -> None:
        # Epoch boundary: count the outgoing plan's unfinished tiles as
        # stale-abandoned (the new plan supersedes their demand evidence).
        if self._plan is not None and self.status == "sweeping":
            remaining = sum(
                1 for t in self._tiles
                if not self._tile_done(t) and t["id"] not in self._failed_tiles
            )
            if remaining:
                with self._lock:
                    self.counts["abandoned_stale"] += remaining
                _log_prewarm("abandon", reason="stale", count=remaining,
                             fingerprint=self._plan_fp)
        self._withdraw_hb()

        fp = plan["plan_fingerprint"]
        pv = plan.get("program_version")
        if pv is not None and int(pv) != _program_version():
            # Fail closed: a plan ranked against another solver generation
            # must not drive sweeps whose cache keys it cannot describe.
            with self._lock:
                self.counts["plans_rejected"] += 1
            self._plan, self._plan_fp, self._tiles = None, None, []
            self.status = "rejected"
            _log_prewarm("plan_reject", fingerprint=fp, reason="program_version",
                         plan_version=int(pv), running_version=_program_version())
            return

        self._plan, self._plan_fp = plan, fp
        self._tiles = _plan_tiles(plan)
        self._failed_tiles = set()
        self._tiles_run = 0
        self._plan_started = time.monotonic()
        self._warm = None
        self.status = "sweeping" if self._tiles else "done"
        with self._lock:
            self.counts["plans"] += 1

        root = state_dir(self._state_root, cache_root=self._cache.root)
        self._plan_dir = root / f"plan_{fp}"
        self._plan_dir.mkdir(parents=True, exist_ok=True)
        snap = self._plan_dir / "plan.json"
        if not snap.exists():
            from sbr_tpu.obs.demand import write_plan

            write_plan({**plan, "program_version": _program_version()}, snap)
        from sbr_tpu.resilience import elastic

        self._hb = elastic.Heartbeat(self._plan_dir)
        self._hb.beat(role="prewarm", plan=fp, tiles_done=0)

        from sbr_tpu.utils import checkpoint as ckpt

        self._retry_budget = ckpt.default_retry_budget(max(len(self._tiles), 1))
        from sbr_tpu.resilience import retry

        self._policy = retry.policy_from_env(
            "SBR_PREWARM_RETRY", max_attempts=3, base_delay_s=0.2,
            multiplier=2.0, max_delay_s=30.0,
        )
        _log_prewarm("plan", fingerprint=fp, tiles=len(self._tiles),
                     surface_queries=plan.get("surface_queries"))
        if not self._tiles:
            self._finish_plan()

    # -- tile state --
    def _done_path(self, t: dict) -> Path:
        return self._plan_dir / f"{_DONE_PREFIX}{t['id']}.json"

    def _tile_done(self, t: dict) -> bool:
        """A done marker from the CURRENT program version only — a marker
        left by another solver generation describes cache entries this
        generation can never serve, so the tile must re-run."""
        try:
            doc = json.loads(self._done_path(t).read_text())
            return int(doc.get("program_version", -1)) == _program_version()
        except (OSError, ValueError, TypeError):
            return False

    def _mark_done(self, t: dict, source: str, key: Optional[str]) -> None:
        path = self._done_path(t)
        tmp = path.with_name(path.name + ".tmp")
        doc = {
            "tile": t["id"], "source": source, "key": key,
            "plan": self._plan_fp, "ts": time.time(),
            "program_version": _program_version(),
            "host": self._hb.host if self._hb is not None else None,
        }
        tmp.write_text(json.dumps(doc, sort_keys=True))
        os.replace(tmp, path)

    def _over_budget(self) -> Optional[str]:
        if self._max_tiles is not None and self._tiles_run >= self._max_tiles:
            return f"tile budget {self._max_tiles} spent"
        if self._max_seconds is not None and self._plan_started is not None \
                and time.monotonic() - self._plan_started >= self._max_seconds:
            return f"time budget {self._max_seconds:g}s spent"
        return None

    # -- execution --
    def step(self) -> Optional[dict]:
        """Claim and run ONE plan tile; returns its outcome dict, or None
        when nothing was runnable this beat (all claimed elsewhere, all
        done, or the budget closed the plan)."""
        if self.status != "sweeping" or self._plan_dir is None:
            return None
        over = self._over_budget()
        if over is not None:
            remaining = sum(
                1 for t in self._tiles
                if not self._tile_done(t) and t["id"] not in self._failed_tiles
            )
            with self._lock:
                self.counts["abandoned_budget"] += remaining
            self.status = "budget_exhausted"
            _log_prewarm("abandon", reason="budget", count=remaining,
                         detail=over, fingerprint=self._plan_fp)
            self._withdraw_hb()
            return None

        from sbr_tpu.parallel.distributed import _try_lease
        from sbr_tpu.resilience import shutdown

        pending = False
        for t in self._tiles:
            if t["id"] in self._failed_tiles or self._tile_done(t):
                continue
            li, lj = t["lease"]
            lease = self._plan_dir / f"tile_b{li:05d}_u{lj:05d}.lease"
            contested = lease.exists()  # pre-claim: takeover == adoption
            if not _try_lease(self._plan_dir, li, lj, self._ttl_s):
                pending = True
                continue
            shutdown.release_on_exit(lease)
            if contested:
                with self._lock:
                    self.counts["adopted"] += 1
                _log_prewarm("adopt", tile=t["id"], fingerprint=self._plan_fp)
            try:
                source, key = self._run_tile(t)
            except Exception as err:  # noqa: BLE001 — tile failure, not crash
                self._failed_tiles.add(t["id"])
                with self._lock:
                    self.counts["failed"] += 1
                _log_prewarm("tile_failed", tile=t["id"], error=repr(err),
                             fingerprint=self._plan_fp)
                return {"tile": t["id"], "source": "failed", "error": repr(err)}
            finally:
                try:
                    lease.unlink()
                except OSError:
                    pass
                shutdown.unregister_release(lease)
            self._mark_done(t, source, key)
            self._tiles_run += 1
            with self._lock:
                self.counts["tiles_done"] += 1
                self.counts[source] = self.counts.get(source, 0) + 1
            if self._hb is not None:
                try:
                    self._hb.beat(role="prewarm", plan=self._plan_fp,
                                  tiles_done=self.counts["tiles_done"])
                except Exception:
                    pass
            _log_prewarm("tile", tile=t["id"], source=source,
                         cells=len(t["betas"]) * len(t["us"]),
                         fingerprint=self._plan_fp)
            if not pending and self._all_done():
                self._finish_plan()
            return {"tile": t["id"], "source": source, "key": key}
        if not pending and self._all_done():
            self._finish_plan()
        return None

    def _all_done(self) -> bool:
        return all(
            self._tile_done(t) or t["id"] in self._failed_tiles
            for t in self._tiles
        )

    def _tile_base(self, t: dict):
        """The canonical sweep base for one per-β tile: η and tspan are
        RE-DERIVED from the tile's β exactly as `make_model_params` does
        for a serving query (η = η̄/β, tspan = (0, 2η)) — pinning a fixed
        base's η across βs would compute a different cell than the query
        asks for, and the bridge's cell-tag match would rightly refuse
        it. Economics other than the swept pair come from ``base`` when
        the embedding passed one, else the reference defaults."""
        from sbr_tpu.models.params import make_model_params

        beta = t["betas"][0]
        b = self._base
        if b is None:
            return make_model_params(beta=beta)
        return make_model_params(
            beta=beta, eta_bar=b.economic.eta_bar, p=b.economic.p,
            kappa=b.economic.kappa, lam=b.economic.lam, x0=b.learning.x0,
            insurance_cap=b.economic.insurance_cap,
            suspension_t=b.economic.suspension_t,
            lolr_rate=b.economic.lolr_rate,
        )

    def _runner(self, t: dict):
        from sbr_tpu.utils import checkpoint as ckpt

        betas, us = t["betas"], t["us"]
        return ckpt.tile_runner(
            betas, us, self._tile_base(t), None,
            config=self._config, tile_shape=(len(betas), len(us)),
            dtype=self._dtype, retry_budget=self._retry_budget,
            tile_cache=self._cache, scenario_spec=self.scenario_spec,
        )

    def _run_tile(self, t: dict) -> tuple:
        """Produce one plan tile through the elastic tile runner (cache
        check first — an already-swept tile is a free "cache" hit), behind
        the ``prewarm.sweep`` fault point and the retry policy."""
        from sbr_tpu.resilience import faults

        runner = self._runner(t)

        def attempt():
            faults.fire("prewarm.sweep", target=t["id"])
            return runner.produce(0, 0)

        source, _ = self._policy.call(
            attempt, scope=f"prewarm.{t['id']}", budget=self._retry_budget
        )
        key = None
        try:
            key = runner.cache_key(0, 0)
        except Exception:
            pass
        return source, key

    def _finish_plan(self) -> None:
        """Plan drained: verify the hot region is actually warm (every
        tile's cache entry present under the CURRENT program version) and
        latch the verdict — `report prewarm` gates a completed-but-cold
        plan to exit 1."""
        warm = 0
        for t in self._tiles:
            try:
                key = self._runner(t).cache_key(0, 0)
                if key and self._cache.path(key).exists():
                    warm += 1
            except Exception:
                continue
        self._warm = warm
        self.status = "done"
        _log_prewarm("plan_done", fingerprint=self._plan_fp,
                     tiles=len(self._tiles), warm=warm,
                     failed=len(self._failed_tiles))
        self._withdraw_hb()

    def drain(self, timeout_s: Optional[float] = None,
              idle_sleep_s: float = 0.25) -> dict:
        """Run the ACTIVE plan to a terminal state (done / budget / no
        plan): the sweeper-role loop. Keeps polling while peers hold
        leases — a killed peer's tiles become claimable at the lease TTL
        and are adopted here. Returns the final `snapshot()`."""
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        while True:
            self.poll_plan()
            if self.status != "sweeping":
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if not self._admissible():
                time.sleep(idle_sleep_s)
                continue
            if self.step() is None and self.status == "sweeping":
                time.sleep(idle_sleep_s)  # leases pending expiry elsewhere
        return self.snapshot()

    # -- surfacing --
    def snapshot(self) -> dict:
        with self._lock:
            counts = dict(self.counts)
        return {
            "status": self.status,
            "plan_fingerprint": self._plan_fp,
            "tiles_total": len(self._tiles),
            "tiles_done": counts["tiles_done"],
            "warm": self._warm,
            "counts": counts,
            "budget": {
                "tiles": self._max_tiles,
                "seconds": self._max_seconds,
                "tiles_run": self._tiles_run,
            },
        }

    def heartbeat_block(self) -> dict:
        """The compact block riding fleet worker heartbeats (what the
        router's /statz roll-up aggregates)."""
        with self._lock:
            counts = dict(self.counts)
        return {
            "status": self.status,
            "plan": self._plan_fp,
            "tiles_done": counts["tiles_done"],
            "tiles_total": len(self._tiles),
            "abandoned": counts["abandoned_stale"] + counts["abandoned_budget"],
        }

    def status_gauge(self) -> int:
        """``sbr_prewarm_status``: 1 done, 0 idle/sweeping/no_cache,
        -1 rejected/budget_exhausted."""
        return {"done": 1, "rejected": -1, "budget_exhausted": -1}.get(self.status, 0)

    def prometheus_lines(self) -> list:
        with self._lock:
            counts = dict(self.counts)
        return [
            "# TYPE sbr_prewarm_status gauge",
            f"sbr_prewarm_status {self.status_gauge()}",
            "# TYPE sbr_prewarm_tiles_done counter",
            f"sbr_prewarm_tiles_done {counts['tiles_done']}",
            "# TYPE sbr_prewarm_tiles_failed counter",
            f"sbr_prewarm_tiles_failed {counts['failed']}",
            "# TYPE sbr_prewarm_tiles_abandoned counter",
            "sbr_prewarm_tiles_abandoned "
            f"{counts['abandoned_stale'] + counts['abandoned_budget']}",
        ]


# ---------------------------------------------------------------------------
# Retention (report gc --prewarm-keep)
# ---------------------------------------------------------------------------


def gc_prewarm_files(state_root=None, keep: int = 4,
                     ttl_s: Optional[float] = None) -> list:
    """Prune prewarm state, matching the gc retention contract: keep the
    ``keep`` most-recent ``plan_<fp>`` epoch dirs under the state root
    (``SBR_PREWARM_STATE_DIR`` / ``<tile cache>/_prewarm``); older epochs
    are removed ONLY when no live lease or heartbeat remains (a sweeper
    still drains there). Inside every surviving epoch, leases whose tile
    already carries a done marker are debris and removed. The newest
    epoch — the active plan — is never a candidate. Returns removed
    paths."""
    import shutil

    ttl = lease_ttl_s(ttl_s)
    removed: list = []
    root = state_dir(state_root)
    if root is None or not root.is_dir():
        return removed
    epochs = sorted(
        (p for p in root.iterdir() if p.is_dir() and p.name.startswith("plan_")),
        key=lambda p: p.stat().st_mtime,
    )
    now = time.time()

    def _live(d: Path) -> bool:
        from sbr_tpu.resilience import elastic

        if elastic.live_hosts(d, now=now):
            return True
        for lease in d.glob("tile_*.lease"):
            try:
                doc = json.loads(lease.read_text())
                age = now - float(doc.get("ts", 0.0))
                if age < float(doc.get("ttl_s") or ttl):
                    return True
            except (OSError, ValueError, TypeError):
                if now - lease.stat().st_mtime < ttl:
                    return True
        return False

    keep = max(int(keep), 1)  # the active epoch is always kept
    for d in epochs[: max(len(epochs) - keep, 0)]:
        if _live(d):
            continue
        try:
            shutil.rmtree(d)
            removed.append(str(d))
        except OSError:
            pass
    for d in epochs:
        if not d.is_dir():
            continue  # just removed above
        for lease in d.glob("tile_*.lease"):
            # tile_b00001_u00002.lease -> done_t00001_00002.json
            stem = lease.stem.replace("tile_b", "t").replace("_u", "_")
            if (d / f"{_DONE_PREFIX}{stem}.json").exists():
                try:
                    lease.unlink()
                    removed.append(str(lease))
                except OSError:
                    pass
    return removed


# ---------------------------------------------------------------------------
# Standalone sweeper role
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    """``python -m sbr_tpu.serve.prewarm``: the dedicated sweeper role —
    drain an advisor plan into the shared tile cache from a box with no
    serve engine (always admissible). ``--once`` exits when the plan
    reaches a terminal state; ``--watch`` keeps following the plan file
    across epochs until SIGTERM. Prints one readiness JSON line, then
    (``--once``) one summary JSON line.

    Exit codes: 0 plan done warm, 1 terminal-but-degraded (budget
    exhausted, rejected, failed/cold tiles), 2 setup error."""
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.serve.prewarm",
        description="Autonomous prefetch sweeper: execute advisor-plan "
        "tiles into the shared tile cache (crash-safe leases; N sweepers "
        "cooperate)",
    )
    parser.add_argument("--plan", required=True,
                        help="advisor_plan.json to execute (watched for "
                        "new fingerprints under --watch)")
    parser.add_argument("--state-dir", default=None, dest="state_dir",
                        help="sweeper rendezvous dir (default "
                        "SBR_PREWARM_STATE_DIR or <cache>/_prewarm)")
    parser.add_argument("--cache-dir", default=None, dest="cache_dir",
                        help="tile cache root (default SBR_TILE_CACHE_DIR)")
    parser.add_argument("--n-grid", type=int, default=192, dest="n_grid",
                        help="solver grid — MUST match the serving "
                        "engines' config or the cell tags never match")
    parser.add_argument("--bisect-iters", type=int, default=40, dest="bisect_iters")
    parser.add_argument("--budget-tiles", type=int, default=None, dest="budget_tiles")
    parser.add_argument("--budget-seconds", type=float, default=None,
                        dest="budget_seconds")
    parser.add_argument("--timeout-s", type=float, default=None, dest="timeout_s",
                        help="--once: give up draining after this long")
    parser.add_argument("--run-dir", default=None,
                        help="obs run dir for prewarm telemetry "
                        "(report prewarm gates it)")
    parser.add_argument("--once", action="store_true",
                        help="drain the current plan to a terminal state, "
                        "then exit (default: --watch)")
    parser.add_argument("--platform", default=None,
                        help="pin a jax platform before backend init (cpu)")
    parser.add_argument("--json", action="store_true",
                        help="print the final snapshot as JSON (--once)")
    args = parser.parse_args(argv)

    if args.platform and args.platform.lower() == "cpu":
        from sbr_tpu.utils.platform import pin_cpu_platform

        pin_cpu_platform()

    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.resilience.elastic import default_tile_cache
    from sbr_tpu.resilience.shutdown import graceful_shutdown

    if default_tile_cache(args.cache_dir) is None:
        print("[prewarm] no tile cache configured (--cache-dir / "
              "SBR_TILE_CACHE_DIR) — nothing to prewarm into", file=sys.stderr)
        return 2

    config = SolverConfig(
        n_grid=args.n_grid, bisect_iters=args.bisect_iters,
        refine_crossings=False,
    )
    run = None
    if args.run_dir:
        from sbr_tpu import obs

        run = obs.start_run(label="prewarm", run_dir=args.run_dir)
    ctl = PrewarmController(
        engine=None, plan_file=args.plan, state_root=args.state_dir,
        config=config, cache_dir=args.cache_dir,
        max_tiles=args.budget_tiles, max_seconds=args.budget_seconds,
    )
    print(json.dumps({"role": "prewarm", "pid": os.getpid(),
                      "plan": str(args.plan)}), flush=True)
    rc = 0
    with graceful_shutdown(label="prewarm"):
        try:
            if args.once:
                snap = ctl.drain(timeout_s=args.timeout_s)
                counts = snap["counts"]
                degraded = (
                    snap["status"] != "done"
                    or counts["failed"] > 0
                    or counts["abandoned_budget"] > 0
                    or (snap["warm"] is not None
                        and snap["warm"] < snap["tiles_total"])
                )
                rc = 1 if degraded else 0
                if args.json:
                    print(json.dumps(snap, default=str))
                else:
                    print(f"[prewarm] {snap['status']}: "
                          f"{snap['tiles_done']}/{snap['tiles_total']} tile(s), "
                          f"warm {snap['warm']}", file=sys.stderr)
            else:
                ctl.start()
                while True:
                    time.sleep(0.5)
        finally:
            ctl.close()
            if run is not None:
                from sbr_tpu.obs import runlog

                runlog._finalize_if_active(run)
    return rc


if __name__ == "__main__":
    sys.exit(main())
