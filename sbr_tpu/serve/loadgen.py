"""Seeded deterministic load generator for the serving engine (ISSUE 7).

``python -m sbr_tpu.serve.loadgen`` drives a reproducible query mix
against an in-process `Engine` + `ServeEndpoint`, scrapes its own
``/metrics`` and ``/healthz`` over HTTP (counters, not logs — the
acceptance contract), and prints ONE JSON summary line. CI and the bench
harness both ride this:

- the query stream is a seeded sample over a fixed parameter pool, so the
  same ``--seed``/``--pool``/``--queries`` always produces the same mix
  (and therefore the same cache-hit trajectory);
- a **warmup phase** queries every pool member once (each miss compiles/
  computes), then the **measured phase** replays the seeded mix — with
  ``--assert-warm`` the run exits 1 unless the measured phase shows a
  cache hit rate >= the floor AND zero new XLA compiles on the scraped
  counters (the serve-smoke CI gate);
- ``--run-dir`` lands the engine's rolling ``live.json`` in an obs run
  directory that ``python -m sbr_tpu.obs.report serve`` renders and gates.

Exit codes: 0 ok, 1 failed assertion (--assert-warm), 2 setup error.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import urllib.request
from typing import List

from sbr_tpu.models.params import ModelParams, SolverConfig, make_model_params


def build_pool(seed: int, pool: int) -> List[ModelParams]:
    """``pool`` distinct parameter points, deterministically derived from
    ``seed``: β and u swept over their Figure-4/5 ranges, everything else
    at the reference defaults (η stays pinned like the sweeps)."""
    rng = random.Random(seed)
    out = []
    for _ in range(pool):
        out.append(
            make_model_params(
                beta=round(rng.uniform(0.5, 4.0), 6),
                u=round(rng.uniform(0.02, 0.9), 6),
            )
        )
    return out


def query_mix(seed: int, pool_size: int, n: int) -> List[int]:
    """Seeded stream of pool indices: repeated-mix traffic (each index
    drawn uniformly), the shape a warm cache should mostly absorb."""
    rng = random.Random(seed + 1)
    return [rng.randrange(pool_size) for _ in range(n)]


def _scrape(port: int, path: str) -> tuple:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
        return resp.status, resp.read().decode()


def _metric_value(text: str, name: str) -> float:
    """Parse one un-labeled sample from Prometheus exposition text."""
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    return float("nan")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.serve.loadgen",
        description="Drive a seeded deterministic query mix against an "
        "in-process serving engine; scrape /metrics + /healthz; print one "
        "JSON summary line",
    )
    parser.add_argument("--queries", type=int, default=200, help="measured-phase queries")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--pool", type=int, default=24, help="distinct parameter points")
    parser.add_argument("--group", type=int, default=16,
                        help="queries submitted per query_many group")
    parser.add_argument("--n-grid", type=int, default=192, dest="n_grid")
    parser.add_argument("--bisect-iters", type=int, default=40, dest="bisect_iters")
    parser.add_argument("--buckets", default=None,
                        help="comma-separated batch buckets (default: SBR_SERVE_BUCKETS or 1,8,64)")
    parser.add_argument("--run-dir", default=None,
                        help="obs run dir for the rolling live.json (report serve reads it)")
    parser.add_argument("--cache-dir", default=None,
                        help="on-disk result + executable cache (default: SBR_SERVE_CACHE_DIR)")
    parser.add_argument("--platform", default=None,
                        help="pin a jax platform before backend init (e.g. cpu)")
    parser.add_argument("--assert-warm", action="store_true",
                        help="exit 1 unless measured-phase hit rate >= floor and "
                        "zero new XLA compiles after warmup (scraped from /metrics)")
    parser.add_argument("--hit-floor", type=float, default=0.5,
                        help="cache-hit-rate floor for --assert-warm (default 0.5)")
    args = parser.parse_args(argv)

    if args.platform:
        if args.platform.lower() == "cpu":
            from sbr_tpu.utils.platform import pin_cpu_platform

            pin_cpu_platform()
        else:
            # The only supported pin is cpu (the axon sitecustomize ignores
            # JAX_PLATFORMS; other backends are jax's default selection) —
            # silently ignoring a typo would misattribute the numbers.
            print(
                f"[loadgen] unsupported --platform {args.platform!r} "
                "(only 'cpu' can be pinned; omit the flag for the default backend)",
                file=sys.stderr,
            )
            return 2

    from sbr_tpu.serve.endpoint import ServeEndpoint
    from sbr_tpu.serve.engine import Engine, ServeConfig, default_buckets

    if args.buckets:
        try:
            buckets = tuple(sorted({int(v) for v in args.buckets.split(",") if v.strip()}))
            if not buckets or any(b <= 0 for b in buckets):
                raise ValueError(f"buckets must be positive ints, got {args.buckets!r}")
        except ValueError as err:
            print(f"[loadgen] bad --buckets: {err}", file=sys.stderr)
            return 2
    else:
        import os

        buckets = default_buckets() if os.environ.get("SBR_SERVE_BUCKETS") else (1, 8, 64)
    serve_cfg = ServeConfig.from_env(buckets=buckets, **(
        {"cache_dir": args.cache_dir} if args.cache_dir else {}
    ))
    config = SolverConfig(
        n_grid=args.n_grid, bisect_iters=args.bisect_iters, refine_crossings=False
    )

    pool = build_pool(args.seed, args.pool)
    mix = query_mix(args.seed, args.pool, args.queries)

    engine = Engine(config=config, serve=serve_cfg, run_dir=args.run_dir)
    engine.start()
    endpoint = ServeEndpoint(engine).start()
    print(f"[loadgen] endpoint on 127.0.0.1:{endpoint.port}", file=sys.stderr)
    try:
        # Warmup: every pool member once — compiles the bucket executables
        # and fills the result cache. Its counters are the baseline the
        # measured phase is compared against.
        for i in range(0, len(pool), args.group):
            engine.query_many(pool[i : i + args.group], scenario="warmup")
        _, warm_metrics = _scrape(endpoint.port, "/metrics")
        warm_compiles = _metric_value(warm_metrics, "sbr_serve_xla_compiles_total")
        warm_queries = _metric_value(warm_metrics, "sbr_serve_queries_total")
        warm_hits = _metric_value(warm_metrics, "sbr_serve_cache_hits_total")
        # Measured-phase quantiles via histogram delta (LogHistogram.delta):
        # lifetime quantiles would be dominated by the warmup's multi-second
        # compile latencies (the same isolation bench_serve uses).
        hist_before = engine.live.total_hist.copy()

        for i in range(0, len(mix), args.group):
            group = [pool[j] for j in mix[i : i + args.group]]
            engine.query_many(group, scenario="mix")

        _, metrics_text = _scrape(endpoint.port, "/metrics")
        health_code, health_body = _scrape(endpoint.port, "/healthz")
        try:  # /statz must serve a coherent document; a bad body is a
            statz = json.loads(_scrape(endpoint.port, "/statz")[1])  # finding,
            statz_ok = isinstance((statz.get("totals") or {}).get("queries"), (int, float))
        except (OSError, ValueError):  # not a loadgen traceback
            statz, statz_ok = {}, False

        post_compiles = _metric_value(metrics_text, "sbr_serve_xla_compiles_total")
        queries_total = _metric_value(metrics_text, "sbr_serve_queries_total")
        hits_total = _metric_value(metrics_text, "sbr_serve_cache_hits_total")
        measured_queries = queries_total - warm_queries
        measured_hits = hits_total - warm_hits
        hit_rate = measured_hits / measured_queries if measured_queries else 0.0
        compile_delta = post_compiles - warm_compiles

        lat = engine.live.total_hist.delta(hist_before).summary()
        summary = {
            "queries": int(measured_queries),
            "warmup_queries": int(warm_queries),
            "pool": args.pool,
            "seed": args.seed,
            "buckets": list(buckets),
            "cache_hit_rate": round(hit_rate, 4),
            "post_warmup_xla_compiles": int(compile_delta),
            "p50_ms": lat.get("p50"),
            "p99_ms": lat.get("p99"),
            "healthz": json.loads(health_body),
            "healthz_http": health_code,
            "statz_ok": statz_ok,
            "occupancy": (statz.get("totals") or {}).get("occupancy"),
            "endpoint_port": endpoint.port,
            "run_dir": args.run_dir,
        }
    finally:
        endpoint.close()
        engine.close()

    failures = []
    if args.assert_warm:
        if hit_rate < args.hit_floor:
            failures.append(
                f"measured cache hit rate {hit_rate:.3f} < floor {args.hit_floor}"
            )
        if compile_delta != 0:
            failures.append(
                f"{int(compile_delta)} XLA compile(s) after warmup (expected 0)"
            )
        if health_code != 200:
            failures.append(f"/healthz returned {health_code}")
        if not statz_ok:
            failures.append("/statz did not serve a coherent snapshot")
    summary["failures"] = failures
    print(json.dumps(summary))
    for f in failures:
        print(f"[loadgen] ASSERTION FAILED: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
