"""Seeded deterministic load generator for the serving engine (ISSUE 7)
and the multi-process serving fleet (ISSUE 11).

``python -m sbr_tpu.serve.loadgen`` drives a reproducible query mix
against an in-process `Engine` + `ServeEndpoint`, scrapes its own
``/metrics`` and ``/healthz`` over HTTP (counters, not logs — the
acceptance contract), and prints ONE JSON summary line. CI and the bench
harness both ride this:

- the query stream is a seeded sample over a fixed parameter pool, so the
  same ``--seed``/``--pool``/``--queries`` always produces the same mix
  (and therefore the same cache-hit trajectory);
- a **warmup phase** queries every pool member once (each miss compiles/
  computes), then the **measured phase** replays the seeded mix — with
  ``--assert-warm`` the run exits 1 unless the measured phase shows a
  cache hit rate >= the floor AND zero new XLA compiles on the scraped
  counters (the serve-smoke CI gate);
- ``--run-dir`` lands the engine's rolling ``live.json`` in an obs run
  directory that ``python -m sbr_tpu.obs.report serve`` renders and gates.

**Fleet mode** (``--fleet N``): spawn N worker subprocesses
(``python -m sbr_tpu.serve.fleet``, heartbeats in a scratch fleet dir),
front them with an in-process `serve.router.Router`, and drive the SAME
seeded mix over HTTP through the router. The summary gains the fleet SLO
headline (``fleet_p99_ms`` measured-phase client latency,
``fleet_failover_count``, ``fleet_shed_rate``) — history schema 7 —
and fleet mode always asserts ZERO lost queries (any non-200 answer
that is not a deliberate 429 shed fails the run). ``--fleet-kill-after
K`` SIGKILLs one worker after K measured queries (the chaos fleet smoke:
the router must fail over with zero lost queries and byte-identical
answers); ``--answers-out`` writes the per-query answer list for
cross-run byte-identity comparison. ``--run-dir`` is the ROUTER's obs
run dir (``report fleet`` gates it); workers land their own run dirs
beside it.

**Distributed tracing** (ISSUE 16): with ``SBR_TRACE_SAMPLE > 0`` every
query carries an ``X-SBR-Trace-Id`` (fleet mode: minted by the router;
direct mode: minted here, with a ``loadgen.query`` root span committed to
the engine's run dir), and ``--trace-out PATH`` writes one JSONL row per
measured query — trace id, client latency, source, degraded, status,
plus the query's (β, u) coordinates and scenario/kind tags (ISSUE 18) —
the client-side half a ``report trace`` waterfall joins against, and the
replay input `python -m sbr_tpu.obs.demand replay` rebuilds demand
surfaces from (its reader is backfill-tolerant: rows from older traces
without (β, u) are counted as legacy and skipped).

Exit codes: 0 ok, 1 failed assertion (--assert-warm / fleet loss), 2
setup error.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional

from sbr_tpu.models.params import ModelParams, SolverConfig, make_model_params


def build_pool(seed: int, pool: int) -> List[ModelParams]:
    """``pool`` distinct parameter points, deterministically derived from
    ``seed``: β and u swept over their Figure-4/5 ranges, everything else
    at the reference defaults (η stays pinned like the sweeps)."""
    rng = random.Random(seed)
    out = []
    for _ in range(pool):
        out.append(
            make_model_params(
                beta=round(rng.uniform(0.5, 4.0), 6),
                u=round(rng.uniform(0.02, 0.9), 6),
            )
        )
    return out


def query_mix(seed: int, pool_size: int, n: int) -> List[int]:
    """Seeded stream of pool indices: repeated-mix traffic (each index
    drawn uniformly), the shape a warm cache should mostly absorb."""
    rng = random.Random(seed + 1)
    return [rng.randrange(pool_size) for _ in range(n)]


def _scrape(port: int, path: str) -> tuple:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
        return resp.status, resp.read().decode()


# ---------------------------------------------------------------------------
# Fleet mode (ISSUE 11): N worker subprocesses behind an in-process router
# ---------------------------------------------------------------------------


def params_doc(p: ModelParams) -> dict:
    """The /query wire form of one pool member (full precision: repr
    round-trips floats exactly, so a routed query solves the identical
    cell a direct `Engine.query` would)."""
    return {
        "beta": p.learning.beta,
        "u": p.economic.u,
        "p": p.economic.p,
        "kappa": p.economic.kappa,
        "lam": p.economic.lam,
        "eta": p.economic.eta,
        "tspan": list(p.learning.tspan),
        "x0": p.learning.x0,
    }


def spawn_worker(fleet_dir: str, n_grid: int, bisect_iters: int, buckets: str,
                 run_dir: Optional[str] = None, cache_dir: Optional[str] = None,
                 platform: Optional[str] = "cpu", heartbeat_ttl: float = 30.0,
                 timeout_s: float = 180.0,
                 extra_env: Optional[dict] = None) -> dict:
    """Spawn one fleet worker subprocess and wait for its readiness line.
    Returns ``{"proc", "url", "host", "pid"}``; raises on startup timeout
    (the worker is killed first). ``extra_env`` overlays the inherited
    environment for THIS worker only (the chaos audit proof plants its
    per-worker ``audit.canary`` fault plan through it)."""
    argv = [
        sys.executable, "-m", "sbr_tpu.serve.fleet",
        "--fleet-dir", str(fleet_dir),
        "--n-grid", str(n_grid),
        "--bisect-iters", str(bisect_iters),
        "--buckets", buckets,
        "--heartbeat-ttl", str(heartbeat_ttl),
    ]
    if platform:
        argv += ["--platform", platform]
    if run_dir:
        argv += ["--run-dir", str(run_dir)]
    if cache_dir:
        argv += ["--cache-dir", str(cache_dir)]
    env = {**os.environ, **{k: str(v) for k, v in extra_env.items()}} if extra_env else None
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=sys.stderr.fileno(), text=True,
        env=env,
    )
    line: dict = {}
    err: Optional[str] = None

    def read_ready():
        nonlocal err
        raw = ""
        try:
            raw = proc.stdout.readline()
            line.update(json.loads(raw))
        except Exception as e:  # noqa: BLE001 — surfaced below
            err = f"{e!r} (raw={raw!r})"

    t = threading.Thread(target=read_ready, daemon=True)
    t.start()
    t.join(timeout_s)
    if not line.get("url"):
        proc.kill()
        raise RuntimeError(
            f"fleet worker failed to become ready within {timeout_s:.0f}s"
            + (f": {err}" if err else "")
        )
    return {"proc": proc, "url": line["url"], "host": line.get("host"),
            "pid": line.get("pid", proc.pid)}


def run_fleet(args) -> dict:
    """The fleet driver behind ``--fleet`` (and `bench.py`'s fleet
    workload): N subprocess workers + in-process router, the seeded
    warmup + measured mix over HTTP, optional mid-run worker kill.
    Returns the summary dict (including ``failures`` and, when requested,
    the per-query ``answers`` written to ``--answers-out``)."""
    from sbr_tpu.obs.metrics import DEFAULT_LATENCY_BOUNDS_MS, LogHistogram
    from sbr_tpu.serve.router import Router

    from sbr_tpu.obs import trace as qtrace

    pool = build_pool(args.seed, args.pool)
    mix = query_mix(args.seed, args.pool, args.queries)
    docs = [json.dumps(params_doc(p)).encode() for p in pool]

    scratch = tempfile.mkdtemp(prefix="sbr_fleet_")
    fleet_dir = args.fleet_dir or os.path.join(scratch, "fleet")
    workers = []
    router = None
    failures: List[str] = []
    answers: List[Optional[dict]] = [None] * len(mix)
    trace_rows: List[Optional[dict]] = [None] * len(mix)
    hist = LogHistogram(DEFAULT_LATENCY_BOUNDS_MS)
    killed: dict = {}
    try:
        audit_fault = getattr(args, "audit_fault", None)
        for i in range(args.fleet):
            wrun = (
                os.path.join(args.run_dir + "_workers", f"w{i}")
                if args.run_dir else None
            )
            # --audit-fault plants the fault plan in worker 0 ONLY: the
            # chaos audit proof corrupts one worker's canaries and expects
            # the peers to stay clean (no false positives).
            wenv = {"SBR_FAULT_PLAN": audit_fault} if (audit_fault and i == 0) else None
            workers.append(spawn_worker(
                fleet_dir, args.n_grid, args.bisect_iters,
                args.buckets or "1,8,64", run_dir=wrun,
                cache_dir=args.cache_dir,
                platform=args.platform or "cpu",
                extra_env=wenv,
            ))
        router = Router(fleet_dir, run_dir=args.run_dir, poll_s=0.2).start()
        base = f"http://127.0.0.1:{router.port}/query"
        print(f"[loadgen] fleet of {len(workers)} worker(s) behind {base}",
              file=sys.stderr)

        def post(doc: bytes, timeout: float = 300.0) -> tuple:
            req = urllib.request.Request(
                base, data=doc, headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    return resp.status, json.loads(resp.read()), dict(resp.headers)
            except urllib.error.HTTPError as e:
                hdrs = dict(e.headers or {})
                try:
                    return e.code, json.loads(e.read()), hdrs
                except ValueError:
                    return e.code, {}, hdrs
            except OSError as e:
                # Connection reset / refused / client timeout (URLError is
                # an OSError): this MUST surface as a counted failure —
                # an exception escaping the recording thread would vanish
                # silently and let the zero-lost assertion pass on a run
                # that actually lost a query.
                return 599, {"error": repr(e)}, {}

        # Warmup: every pool member, --group at a time (concurrency spreads
        # the pool over the workers, so every worker compiles its buckets).
        def run_group(indices, record):
            threads = []
            for pos, pool_idx in indices:
                t = threading.Thread(
                    target=record, args=(pos, pool_idx), daemon=True
                )
                threads.append(t)
                t.start()
            for t in threads:
                t.join()

        def warm_one(pos, pool_idx):
            code, doc, _ = post(docs[pool_idx])
            if code != 200:
                failures.append(f"warmup query {pos} (pool {pool_idx}) -> {code}")

        for i in range(0, len(pool), args.group):
            run_group([(j, j) for j in range(i, min(i + args.group, len(pool)))],
                      warm_one)

        # Measured phase: the seeded mix; after --fleet-kill-after
        # completions, SIGKILL one worker mid-run (the chaos fleet proof).
        completed = [0]
        kill_lock = threading.Lock()

        def maybe_kill():
            if args.fleet_kill_after is None or killed or len(workers) < 2:
                return
            with kill_lock:
                if killed or completed[0] < args.fleet_kill_after:
                    return
                # Kill the router's FAVORITE worker (most forwards): its
                # EWMA score is the lowest, so the router keeps preferring
                # it after death — the hardest failover case, and the one
                # that deterministically drives its breaker open.
                stats = router.statz()["workers"]
                victim = max(
                    workers,
                    key=lambda w: stats.get(w["host"], {}).get("forwards", 0),
                )
                killed.update(host=victim["host"], pid=victim["pid"])
                os.kill(victim["pid"], signal.SIGKILL)
                print(f"[loadgen] SIGKILLed worker {victim['host']} "
                      f"(pid {victim['pid']}) after {completed[0]} queries",
                      file=sys.stderr)

        def measured_one(pos, pool_idx):
            t0 = time.monotonic()
            code, doc, hdrs = post(docs[pool_idx])
            dur_ms = (time.monotonic() - t0) * 1e3
            if code == 200:
                hist.record(dur_ms)
                answers[pos] = doc
            elif code == 429:
                answers[pos] = {"shed": True}
            else:
                failures.append(f"measured query {pos} (pool {pool_idx}) -> {code}: {doc}")
            # Per-query trace row (--trace-out): the trace id from the
            # response body when the worker answered, else the router's
            # echoed header (sheds and errors still carry it).
            tid = doc.get("trace_id") if isinstance(doc, dict) else None
            if tid is None:
                tid = hdrs.get(qtrace.TRACE_HEADER)
            trace_rows[pos] = {
                "query": pos,
                "pool": pool_idx,
                "trace_id": tid,
                "latency_ms": round(dur_ms, 3),
                "status": code,
                "source": doc.get("source") if isinstance(doc, dict) else None,
                "degraded": bool(doc.get("degraded")) if isinstance(doc, dict) else None,
                # Demand-replay input contract (ISSUE 18): the query's
                # (β, u) coordinates + scenario/kind tags let
                # `python -m sbr_tpu.obs.demand replay` rebuild the demand
                # surface offline from this trace alone.
                "beta": pool[pool_idx].learning.beta,
                "u": pool[pool_idx].economic.u,
                "scenario": "mix",
                "kind": "plain",
            }
            completed[0] += 1
            maybe_kill()

        t0 = time.monotonic()
        for i in range(0, len(mix), args.group):
            run_group(
                [(j, mix[j]) for j in range(i, min(i + args.group, len(mix)))],
                measured_one,
            )
        measured_s = time.monotonic() - t0

        # --audit-wait N: hold the fleet up (idle — the measured phase is
        # over, so canaries are free to run) until every worker's heartbeat
        # shows either >= N completed audit cycles or a drift verdict, then
        # let the router's next refresh apply the quarantine. The canary
        # cadence comes from SBR_AUDIT_INTERVAL_S in the workers' env.
        audit_wait = int(getattr(args, "audit_wait", 0) or 0)
        if audit_wait > 0:
            deadline = time.monotonic() + float(
                getattr(args, "audit_wait_s", None) or 120.0
            )
            while time.monotonic() < deadline:
                blocks = {
                    h: (w.get("audit") or {})
                    for h, w in router.statz()["workers"].items()
                }
                if blocks and all(
                    b.get("status") == "drift" or int(b.get("cycles") or 0) >= audit_wait
                    for b in blocks.values()
                ):
                    break
                time.sleep(0.25)
            else:
                failures.append(
                    f"audit-wait: not every worker reached {audit_wait} "
                    f"canary cycle(s) in time"
                )

        router_stats = router.statz()
    finally:
        if router is not None:
            router.close()
        for w in workers:
            if w["pid"] != killed.get("pid"):
                try:
                    w["proc"].terminate()
                except OSError:
                    pass
        for w in workers:
            try:
                w["proc"].wait(timeout=30)
            except Exception:
                w["proc"].kill()
        import shutil

        # The scratch dir (and the fleet rendezvous inside it, unless the
        # caller supplied their own) is ours: repeated bench/chaos runs
        # must not accumulate sbr_fleet_* debris in /tmp.
        shutil.rmtree(scratch, ignore_errors=True)

    counters = router_stats["counters"]
    shed = counters.get("shed", 0)
    n_answered = sum(1 for a in answers if a is not None and "shed" not in a)
    summary = {
        "fleet": args.fleet,
        "queries": len(mix),
        "answered": n_answered,
        "pool": args.pool,
        "seed": args.seed,
        "fleet_p99_ms": hist.quantile(0.99),
        "fleet_p50_ms": hist.quantile(0.5),
        "fleet_failover_count": counters.get("failover", 0),
        "fleet_shed_rate": round(shed / max(len(mix), 1), 4),
        "fleet_qps": round(len(mix) / measured_s, 1) if measured_s else 0.0,
        "fleet_lost": counters.get("failed", 0),
        "fleet_degraded": counters.get("degraded", 0),
        "killed_worker": killed.get("host"),
        "router_counters": counters,
        "run_dir": args.run_dir,
    }
    # Audit canary census (ISSUE 17): per-worker status from the final
    # heartbeats plus the router's quarantine view — absent entirely when
    # no worker reported an audit block (SBR_AUDIT off).
    audit_blocks = {
        h: w.get("audit")
        for h, w in (router_stats.get("workers") or {}).items()
        if w.get("audit")
    }
    if audit_blocks:
        summary["audit"] = {
            "workers": audit_blocks,
            "quarantined": sorted(
                h for h, w in router_stats["workers"].items()
                if w.get("quarantined")
            ),
        }
    # Fleet mode ALWAYS asserts zero lost queries: with a live peer, every
    # failure mode in scope (worker death, breaker, straggler) must be
    # absorbed by failover, not surfaced to the client.
    if len(failures) > 0:
        summary["failures"] = failures
    else:
        summary["failures"] = []
    if args.answers_out:
        with open(args.answers_out, "w") as fh:
            json.dump(answers, fh)
    trace_out = getattr(args, "trace_out", None)  # optional, like audit_fault
    if trace_out:
        _write_trace_rows(trace_out, trace_rows)
        summary["trace_out"] = trace_out
        summary["traced_queries"] = sum(
            1 for r in trace_rows if r is not None and r.get("trace_id")
        )
    return summary


def _write_trace_rows(path: str, rows: List[Optional[dict]]) -> None:
    """``--trace-out``: one JSONL row per measured query (trace id, client
    latency, source, degraded, status, (β, u) + scenario/kind demand
    tags) — the client-side half that joins a loadgen run against
    ``report trace`` waterfalls by trace id, and the offline input
    ``obs.demand replay`` rebuilds demand surfaces from."""
    with open(path, "w") as fh:
        for row in rows:
            if row is not None:
                fh.write(json.dumps(row) + "\n")


def _metric_value(text: str, name: str) -> float:
    """Parse one un-labeled sample from Prometheus exposition text."""
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    return float("nan")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.serve.loadgen",
        description="Drive a seeded deterministic query mix against an "
        "in-process serving engine; scrape /metrics + /healthz; print one "
        "JSON summary line",
    )
    parser.add_argument("--queries", type=int, default=200, help="measured-phase queries")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--pool", type=int, default=24, help="distinct parameter points")
    parser.add_argument("--group", type=int, default=16,
                        help="queries submitted per query_many group")
    parser.add_argument("--n-grid", type=int, default=192, dest="n_grid")
    parser.add_argument("--bisect-iters", type=int, default=40, dest="bisect_iters")
    parser.add_argument("--buckets", default=None,
                        help="comma-separated batch buckets (default: SBR_SERVE_BUCKETS or 1,8,64)")
    parser.add_argument("--run-dir", default=None,
                        help="obs run dir for the rolling live.json (report serve reads it)")
    parser.add_argument("--cache-dir", default=None,
                        help="on-disk result + executable cache (default: SBR_SERVE_CACHE_DIR)")
    parser.add_argument("--platform", default=None,
                        help="pin a jax platform before backend init (e.g. cpu)")
    parser.add_argument("--assert-warm", action="store_true",
                        help="exit 1 unless measured-phase hit rate >= floor and "
                        "zero new XLA compiles after warmup (scraped from /metrics)")
    parser.add_argument("--hit-floor", type=float, default=0.5,
                        help="cache-hit-rate floor for --assert-warm (default 0.5)")
    parser.add_argument("--fleet", type=int, default=0, metavar="N",
                        help="fleet mode: N worker subprocesses behind an "
                        "in-process router; asserts zero lost queries")
    parser.add_argument("--fleet-dir", default=None,
                        help="shared fleet rendezvous dir (default: scratch)")
    parser.add_argument("--fleet-kill-after", type=int, default=None, metavar="K",
                        dest="fleet_kill_after",
                        help="SIGKILL one worker after K measured queries "
                        "(fleet mode; needs >= 2 workers)")
    parser.add_argument("--answers-out", default=None, dest="answers_out",
                        help="write the per-query answer list (JSON) here "
                        "(fleet mode; byte-identity comparisons)")
    parser.add_argument("--trace-out", default=None, dest="trace_out",
                        help="write per-measured-query JSONL rows (trace id, "
                        "latency, source, degraded) here; trace ids are null "
                        "unless SBR_TRACE_SAMPLE > 0")
    parser.add_argument("--audit-fault", default=None, dest="audit_fault",
                        help="fault plan (inline JSON or path) planted as "
                        "SBR_FAULT_PLAN in worker 0 ONLY (fleet mode; the "
                        "chaos audit.canary corruption proof)")
    parser.add_argument("--audit-wait", type=int, default=0, dest="audit_wait",
                        metavar="N",
                        help="after the measured phase, wait until every "
                        "worker heartbeat shows >= N audit canary cycles or "
                        "a drift verdict (fleet mode; needs SBR_AUDIT=1 in "
                        "the workers' env)")
    parser.add_argument("--audit-wait-s", type=float, default=120.0,
                        dest="audit_wait_s",
                        help="timeout for --audit-wait (default 120 s)")
    args = parser.parse_args(argv)

    if args.fleet:
        try:
            summary = run_fleet(args)
        except Exception as err:  # noqa: BLE001 — setup failures exit 2
            print(f"[loadgen] fleet setup failed: {err!r}", file=sys.stderr)
            return 2
        failures = list(summary["failures"])
        if summary.get("fleet_lost", 0) > 0:
            failures.append(f"router lost {summary['fleet_lost']} quer(ies)")
        summary["failures"] = failures
        print(json.dumps(summary))
        for f in failures:
            print(f"[loadgen] ASSERTION FAILED: {f}", file=sys.stderr)
        return 1 if failures else 0

    if args.platform:
        if args.platform.lower() == "cpu":
            from sbr_tpu.utils.platform import pin_cpu_platform

            pin_cpu_platform()
        else:
            # The only supported pin is cpu (the axon sitecustomize ignores
            # JAX_PLATFORMS; other backends are jax's default selection) —
            # silently ignoring a typo would misattribute the numbers.
            print(
                f"[loadgen] unsupported --platform {args.platform!r} "
                "(only 'cpu' can be pinned; omit the flag for the default backend)",
                file=sys.stderr,
            )
            return 2

    from sbr_tpu.serve.endpoint import ServeEndpoint
    from sbr_tpu.serve.engine import Engine, ServeConfig, default_buckets

    if args.buckets:
        try:
            buckets = tuple(sorted({int(v) for v in args.buckets.split(",") if v.strip()}))
            if not buckets or any(b <= 0 for b in buckets):
                raise ValueError(f"buckets must be positive ints, got {args.buckets!r}")
        except ValueError as err:
            print(f"[loadgen] bad --buckets: {err}", file=sys.stderr)
            return 2
    else:
        import os

        buckets = default_buckets() if os.environ.get("SBR_SERVE_BUCKETS") else (1, 8, 64)
    serve_cfg = ServeConfig.from_env(buckets=buckets, **(
        {"cache_dir": args.cache_dir} if args.cache_dir else {}
    ))
    config = SolverConfig(
        n_grid=args.n_grid, bisect_iters=args.bisect_iters, refine_crossings=False
    )

    pool = build_pool(args.seed, args.pool)
    mix = query_mix(args.seed, args.pool, args.queries)

    engine = Engine(config=config, serve=serve_cfg, run_dir=args.run_dir)
    engine.start()
    endpoint = ServeEndpoint(engine).start()
    print(f"[loadgen] endpoint on 127.0.0.1:{endpoint.port}", file=sys.stderr)
    try:
        # Warmup: every pool member once — compiles the bucket executables
        # and fills the result cache. Its counters are the baseline the
        # measured phase is compared against.
        for i in range(0, len(pool), args.group):
            engine.query_many(pool[i : i + args.group], scenario="warmup")
        _, warm_metrics = _scrape(endpoint.port, "/metrics")
        warm_compiles = _metric_value(warm_metrics, "sbr_serve_xla_compiles_total")
        warm_queries = _metric_value(warm_metrics, "sbr_serve_queries_total")
        warm_hits = _metric_value(warm_metrics, "sbr_serve_cache_hits_total")
        # Measured-phase quantiles via histogram delta (LogHistogram.delta):
        # lifetime quantiles would be dominated by the warmup's multi-second
        # compile latencies (the same isolation bench_serve uses).
        hist_before = engine.live.total_hist.copy()

        from sbr_tpu.obs import trace as qtrace

        tracing = qtrace.sample_rate() > 0
        trace_rows: List[Optional[dict]] = [None] * len(mix)
        writer = engine.trace_writer() if tracing else None
        t_slo = qtrace.slo_ms()
        for i in range(0, len(mix), args.group):
            span_pos = list(range(i, min(i + args.group, len(mix))))
            group = [pool[mix[p]] for p in span_pos]
            ctxs = None
            if tracing:
                # Direct engine drive: loadgen is the trace minter. The
                # `loadgen.query` root stands in for the router.request /
                # worker.request rungs of the fleet topology.
                ctxs = [qtrace.mint(service="loadgen") for _ in span_pos]
                for c in ctxs:
                    if c is not None:
                        c.parent_id = c.alloc_id()
            t0g_w, t0g_m = time.time(), time.monotonic()
            results = engine.query_many(
                group, scenario="mix",
                **({"traces": ctxs} if ctxs is not None else {}),
            )
            dur_g = time.monotonic() - t0g_m
            for k, p in enumerate(span_pos):
                r = results[k]
                row = {
                    "query": p, "pool": mix[p], "trace_id": None,
                    "latency_ms": round(dur_g * 1e3, 3), "status": 200,
                    "source": r.source, "degraded": bool(r.degraded),
                    # Demand-replay input contract (ISSUE 18) — see the
                    # fleet-mode row builder.
                    "beta": pool[mix[p]].learning.beta,
                    "u": pool[mix[p]].economic.u,
                    "scenario": "mix",
                    "kind": "plain",
                }
                c = ctxs[k] if ctxs is not None else None
                if c is not None:
                    c.add("loadgen.query", t0g_w, dur_g,
                          parent=c.remote_parent, span_id=c.parent_id,
                          pool=mix[p], source=r.source)
                    row["trace_id"] = c.trace_id
                    if writer is not None:
                        breach = t_slo is not None and dur_g * 1e3 > t_slo
                        writer.commit(c, exemplar=breach)
                trace_rows[p] = row

        _, metrics_text = _scrape(endpoint.port, "/metrics")
        health_code, health_body = _scrape(endpoint.port, "/healthz")
        try:  # /statz must serve a coherent document; a bad body is a
            statz = json.loads(_scrape(endpoint.port, "/statz")[1])  # finding,
            statz_ok = isinstance((statz.get("totals") or {}).get("queries"), (int, float))
        except (OSError, ValueError):  # not a loadgen traceback
            statz, statz_ok = {}, False

        post_compiles = _metric_value(metrics_text, "sbr_serve_xla_compiles_total")
        queries_total = _metric_value(metrics_text, "sbr_serve_queries_total")
        hits_total = _metric_value(metrics_text, "sbr_serve_cache_hits_total")
        measured_queries = queries_total - warm_queries
        measured_hits = hits_total - warm_hits
        hit_rate = measured_hits / measured_queries if measured_queries else 0.0
        compile_delta = post_compiles - warm_compiles

        lat = engine.live.total_hist.delta(hist_before).summary()
        summary = {
            "queries": int(measured_queries),
            "warmup_queries": int(warm_queries),
            "pool": args.pool,
            "seed": args.seed,
            "buckets": list(buckets),
            "cache_hit_rate": round(hit_rate, 4),
            "post_warmup_xla_compiles": int(compile_delta),
            "p50_ms": lat.get("p50"),
            "p99_ms": lat.get("p99"),
            "healthz": json.loads(health_body),
            "healthz_http": health_code,
            "statz_ok": statz_ok,
            "occupancy": (statz.get("totals") or {}).get("occupancy"),
            "endpoint_port": endpoint.port,
            "run_dir": args.run_dir,
        }
        if args.trace_out:
            _write_trace_rows(args.trace_out, trace_rows)
            summary["trace_out"] = args.trace_out
            summary["traced_queries"] = sum(
                1 for r in trace_rows if r is not None and r.get("trace_id")
            )
    finally:
        endpoint.close()
        engine.close()

    failures = []
    if args.assert_warm:
        if hit_rate < args.hit_floor:
            failures.append(
                f"measured cache hit rate {hit_rate:.3f} < floor {args.hit_floor}"
            )
        if compile_delta != 0:
            failures.append(
                f"{int(compile_delta)} XLA compile(s) after warmup (expected 0)"
            )
        if health_code != 200:
            failures.append(f"/healthz returned {health_code}")
        if not statz_ok:
            failures.append("/statz did not serve a coherent snapshot")
    summary["failures"] = failures
    print(json.dumps(summary))
    for f in failures:
        print(f"[loadgen] ASSERTION FAILED: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
