"""Serving-fleet membership, circuit breaking, and the degradation ladder's
tile-cache bridge (ISSUE 11 tentpole, part 1).

PR 6's engine is one process: a crash loses every in-flight query. This
module grows it into a *fleet* by reusing the PR 7 elastic substrate
verbatim (the ROADMAP's "apply verbatim to query routing" item):

- **Membership.** Serve workers announce themselves with the SAME
  heartbeat files the elastic sweep scheduler uses
  (`resilience.elastic.Heartbeat` — atomic rewrite, TTL aging, graceful
  release via `resilience.shutdown`), written into a shared *fleet dir*
  (``SBR_FLEET_DIR``) instead of a sweep checkpoint dir. A worker's beat
  carries its HTTP endpoint (``url``) plus live throughput stats (qps,
  p50 ms, inflight), so the router's cost model reads the same record
  the membership check does. `live_workers` is `elastic.live_hosts`
  filtered to records that announce an endpoint.
- **Circuit breaking.** `CircuitBreaker` is the shared
  closed → open → half-open state machine: ``threshold`` consecutive
  failures open it, ``cooldown_s`` later exactly one half-open probe is
  allowed through, a success closes it. The router holds one per worker
  (a dead worker stops absorbing traffic after ``threshold`` failed
  forwards); the engine holds one over its own device dispatch (a sick
  solver path short-circuits to the degradation ladder instead of
  burning the retry budget on every batch). Injectable clock, no
  threads: state advances lazily on `allow()` reads.
- **Tile-cache bridge.** `TileCacheBridge` answers a point query from the
  PR 7 cross-run global tile cache when the solver path is unavailable —
  the serving↔sweep bridge the ROADMAP asks for. Tile stores now leave a
  ``<key>.meta.json`` sidecar (base-economics *cell tag* + the tile's β/u
  axes); the bridge indexes those and serves the exact (β, u) cell when
  the query's economics/config/dtype tag matches a swept tile. Answers
  are labeled ``degraded`` (the sweep program computed them, not this
  engine's dispatch) and carry NaN for the fields a tile doesn't store
  (``tau_bar_in``, ``residual``).

Worker process entry: ``python -m sbr_tpu.serve.fleet --fleet-dir DIR``
runs one engine + endpoint + heartbeat loop under the graceful-shutdown
envelope — SIGTERM finishes in-flight batches, removes the heartbeat file
(peers reclaim instantly instead of waiting out the TTL), and finalizes
the obs manifest as ``"interrupted"`` (ISSUE 11 satellite).

Module import stays jax-free (stdlib + numpy): the router and the chaos
driver import it on boxes that must never wake a backend.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path
from typing import Callable, Dict, Optional

# ---------------------------------------------------------------------------
# Knobs
# ---------------------------------------------------------------------------


def fleet_dir(value=None) -> Optional[Path]:
    """The shared fleet rendezvous dir (``SBR_FLEET_DIR``); None = no fleet
    (single-process serving, the PR 6 shape)."""
    root = value or os.environ.get("SBR_FLEET_DIR", "").strip()
    return Path(root) if root else None


def default_deadline_ms() -> Optional[float]:
    """Fleet-wide default per-query deadline (``SBR_SERVE_DEADLINE_MS``);
    None when unset (queries without an explicit deadline never shed)."""
    raw = os.environ.get("SBR_SERVE_DEADLINE_MS", "").strip()
    return float(raw) if raw else None


def _env_float(name: str, default):
    """Float env override with a passthrough default (None allowed —
    shared by the router's knob resolution)."""
    raw = os.environ.get(name, "").strip()
    return float(raw) if raw else default


# ---------------------------------------------------------------------------
# Membership: elastic heartbeats in a shared fleet dir
# ---------------------------------------------------------------------------


class WorkerAnnouncer:
    """One serve worker's membership record: an `elastic.Heartbeat` in the
    fleet dir whose stats block carries the worker's endpoint and live
    throughput numbers. `beat` passes through the ``fleet.heartbeat``
    fault point, so chaos plans can silence a worker's beats (the router
    must then age it out via the TTL, exactly like a silent death)."""

    def __init__(self, fleet_root, url: str, ttl_s: Optional[float] = None,
                 host: Optional[str] = None) -> None:
        from sbr_tpu.resilience import elastic

        self.root = Path(fleet_root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.url = url
        self.hb = elastic.Heartbeat(self.root, host=host, ttl_s=ttl_s)
        self.host = self.hb.host

    def beat(self, **stats) -> None:
        from sbr_tpu.resilience import faults
        from sbr_tpu.resilience.faults import InjectedFault

        try:
            faults.fire("fleet.heartbeat", target=self.host)
        except InjectedFault:
            return  # a silenced beat = a stale heartbeat, aged out by TTL
        self.hb.beat(url=self.url, role="serve_worker", **stats)

    def withdraw(self) -> None:
        self.hb.withdraw()


def live_workers(fleet_root, now: Optional[float] = None) -> Dict[str, dict]:
    """{host_id: heartbeat record} for live serve workers — elastic's
    membership scan restricted to records announcing an HTTP endpoint (a
    sweep host sharing the dir never routes traffic)."""
    from sbr_tpu.resilience import elastic

    root = Path(fleet_root)
    if not root.is_dir():
        return {}
    return {
        h: rec
        for h, rec in elastic.live_hosts(root, now=now).items()
        if rec.get("url")
    }


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Consecutive-failure breaker: closed → open → half-open → closed.

    ``allow()`` is the single gate: True while closed; False while open
    until ``cooldown_s`` has elapsed, then exactly ONE True (the half-open
    probe) until its outcome lands — a success closes the breaker, a
    failure re-opens it (and restarts the cooldown). Lazy state (no timer
    thread), injectable ``clock`` so tests drive transitions
    deterministically. ``on_transition(old, new)`` observes state changes
    (the router logs them as obs ``fleet`` events).
    """

    def __init__(self, threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable] = None) -> None:
        self.threshold = int(threshold if threshold is not None
                             else _env_float("SBR_BREAKER_THRESHOLD", 3))
        self.cooldown_s = float(cooldown_s if cooldown_s is not None
                                else _env_float("SBR_BREAKER_COOLDOWN_S", 5.0))
        self._clock = clock
        self._on_transition = on_transition
        self.state = "closed"
        self.consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probe_inflight = False
        # Monotonic time of the last state change (None = never changed):
        # `report fleet` ages open breakers against this to tell a breaker
        # legitimately open over a dead peer from one STUCK open.
        self.last_transition_at: Optional[float] = None

    def age_s(self) -> Optional[float]:
        """Seconds since the last state transition (None = never moved)."""
        if self.last_transition_at is None:
            return None
        return self._clock() - self.last_transition_at

    def _transition(self, new: str) -> None:
        old = self.state
        if old == new:
            return
        self.state = new
        self.last_transition_at = self._clock()
        if self._on_transition is not None:
            try:
                self._on_transition(old, new)
            except Exception:
                pass  # observation must never sink the breaker

    def admissible(self) -> bool:
        """Side-effect-free view of `allow()`: would a request be admitted
        right now? Candidate *selection* must use this — `allow()` grants
        the single half-open probe, and granting it to a worker that is
        merely being RANKED (not forwarded to) would strand the breaker in
        half_open forever, since no outcome ever lands for that probe."""
        if self.state == "closed":
            return True
        if self.state == "open":
            return self._clock() - (self._opened_at or 0.0) >= self.cooldown_s
        return not self._probe_inflight

    def allow(self) -> bool:
        """Whether one request may proceed right now (see class docstring).
        Call this only when the request will actually be SENT — a True in
        half-open state grants the single probe, and the caller then owes
        the breaker a `record_success`/`record_failure` outcome."""
        if self.state == "closed":
            return True
        if self.state == "open":
            if self._clock() - (self._opened_at or 0.0) >= self.cooldown_s:
                self._transition("half_open")
                self._probe_inflight = True
                return True
            return False
        # half_open: one probe at a time — concurrent traffic keeps waiting
        if not self._probe_inflight:
            self._probe_inflight = True
            return True
        return False

    def record_success(self) -> None:
        self.consecutive_failures = 0
        self._probe_inflight = False
        self._transition("closed")

    def record_abandoned(self) -> None:
        """The request ended with no verdict on the PEER (e.g. the query's
        own deadline expired in flight): release a held half-open probe
        without moving the state machine in either direction."""
        self._probe_inflight = False

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        self._probe_inflight = False
        if self.state == "half_open" or (
            self.state == "closed" and self.consecutive_failures >= self.threshold
        ):
            self._opened_at = self._clock()
            self._transition("open")


# ---------------------------------------------------------------------------
# Degradation ladder: the global tile cache as a stale-answer source
# ---------------------------------------------------------------------------


def cell_tag(params, config, dtype_name: str) -> str:
    """Canonical cell tag — delegate to `resilience.elastic.cell_tag`, the
    ONE implementation the tile-store side also uses (a serve query and a
    swept tile match exactly when the shared function says so)."""
    from sbr_tpu.resilience import elastic

    return elastic.cell_tag(params, config, dtype_name)


class TileCacheBridge:
    """Point-query lookups against the PR 7 cross-run global tile cache.

    The cache's entries are content-addressed whole tiles; what makes them
    addressable per-cell is the ``<key>.meta.json`` sidecar every store
    now writes (`resilience.elastic.TileCache.store`): the cell tag plus
    the tile's actual β/u axes. The bridge keeps an mtime-invalidated
    in-memory index of those sidecars (ISSUE 19 satellite — prefetch
    makes the cache dir large, and the previous full rescan-and-parse
    per refresh put an O(files) cost on the outage hot path): only shard
    directories whose mtime moved since the last refresh are re-listed,
    and only new/rewritten sidecar files re-parsed. On `lookup` it
    returns the verified entry's exact (β, u) cell — or None on any
    miss, mismatch, or corruption (the ladder then falls through to
    503). All reads go through `TileCache.load`, so sha256
    verify-on-read and quarantine-on-mismatch apply unchanged."""

    #: Directories whose mtime is within this many seconds of "now" are
    #: re-listed even when the mtime looks unchanged — a store landing in
    #: the same filesystem-mtime-granularity tick as a scan must not be
    #: missed forever.
    MTIME_SLACK_S = 3.0

    def __init__(self, cache_dir=None, refresh_s: float = 5.0) -> None:
        from sbr_tpu.resilience.elastic import default_tile_cache

        self.cache = default_tile_cache(cache_dir)
        self.refresh_s = refresh_s
        self._index: Dict[str, list] = {}  # cell_tag -> [meta, ...]
        self._scanned_at: Optional[float] = None
        self._dir_mtimes: Dict[str, float] = {}  # dir -> mtime at last list
        # sidecar path -> {"mtime", "tag", "meta"} (tag None = torn/alien,
        # cached so the file is only re-parsed when its mtime moves)
        self._entries: Dict[str, dict] = {}

    @property
    def available(self) -> bool:
        return self.cache is not None

    def _scan(self) -> None:
        now_wall = time.time()
        root = self.cache.root
        dirs = [root]
        try:
            dirs += [p for p in root.iterdir() if p.is_dir()]
        except OSError:
            dirs = [root]
        seen_dirs = set()
        for d in dirs:
            dkey = str(d)
            seen_dirs.add(dkey)
            try:
                mtime = d.stat().st_mtime
            except OSError:
                continue
            prev = self._dir_mtimes.get(dkey)
            if prev is not None and mtime == prev \
                    and now_wall - mtime > self.MTIME_SLACK_S:
                continue  # nothing stored/removed here since the last list
            self._dir_mtimes[dkey] = mtime
            try:
                files = list(d.glob("*.meta.json"))
            except OSError:
                continue
            live = set()
            for meta_path in files:
                fkey = str(meta_path)
                live.add(fkey)
                try:
                    fm = meta_path.stat().st_mtime
                except OSError:
                    continue
                ent = self._entries.get(fkey)
                if ent is not None and ent["mtime"] == fm:
                    continue
                try:
                    meta = json.loads(meta_path.read_text())
                    parsed = {
                        "key": str(meta["key"]),
                        "betas": [float(b) for b in meta["betas"]],
                        "us": [float(u) for u in meta["us"]],
                    }
                    tag = str(meta["cell_tag"])
                except (OSError, ValueError, KeyError, TypeError):
                    tag, parsed = None, None  # torn/alien sidecar
                self._entries[fkey] = {"mtime": fm, "tag": tag, "meta": parsed}
            for fkey in [
                k for k in self._entries
                if os.path.dirname(k) == dkey and k not in live
            ]:
                del self._entries[fkey]  # sidecar removed (gc/quarantine)
        for dkey in [k for k in self._dir_mtimes if k not in seen_dirs]:
            del self._dir_mtimes[dkey]
            for fkey in [
                k for k in self._entries if os.path.dirname(k) == dkey
            ]:
                del self._entries[fkey]
        index: Dict[str, list] = {}
        for fkey in sorted(self._entries):
            ent = self._entries[fkey]
            if ent["tag"] is not None:
                index.setdefault(ent["tag"], []).append(ent["meta"])
        self._index = index
        self._scanned_at = time.monotonic()

    def lookup(self, params, config, dtype_name: str) -> Optional[dict]:
        """The degraded answer for one query, or None. Matches by exact
        cell tag + exact (β, u) membership in a swept tile's axes — the
        bridge serves only cells that are mathematically the query."""
        if self.cache is None:
            return None
        now = time.monotonic()
        if self._scanned_at is None or now - self._scanned_at >= self.refresh_s:
            self._scan()
        tag = cell_tag(params, config, dtype_name)
        beta = float(params.learning.beta)
        u = float(params.economic.u)
        for meta in self._index.get(tag, []):
            if beta not in meta["betas"] or u not in meta["us"]:
                continue
            arrays = self.cache.load(meta["key"], tile="serve-bridge")
            if arrays is None:
                continue  # quarantined/raced away — try another tile
            i = meta["betas"].index(beta)
            j = meta["us"].index(u)
            try:
                return {
                    "xi": float(arrays["xi"][i, j]),
                    "tau_bar_in": float("nan"),  # tiles don't store it
                    "aw_max": float(arrays["max_aw"][i, j]),
                    "status": int(arrays["status"][i, j]),
                    "flags": 0,
                    "residual": float("nan"),
                }
            except (IndexError, KeyError, ValueError):
                continue  # axes/meta drifted from the entry: not an answer
        return None


# ---------------------------------------------------------------------------
# Worker process entry
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    """One fleet worker: engine + HTTP endpoint + heartbeat announcements.

    Runs until SIGTERM/SIGINT; the graceful-shutdown envelope then drains
    in-flight batches (engine close), withdraws the heartbeat (peers
    reclaim instantly), and finalizes the obs manifest as "interrupted".
    Prints one JSON line ``{"url": ..., "host": ...}`` on stdout once
    ready — the fleet drivers (loadgen --fleet, chaos --fleet) read it.
    """
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.serve.fleet",
        description="Run one serving-fleet worker (engine + endpoint + "
        "heartbeat membership) until SIGTERM",
    )
    parser.add_argument("--fleet-dir", required=True,
                        help="shared fleet rendezvous dir (heartbeats)")
    parser.add_argument("--port", type=int, default=0,
                        help="HTTP port (default 0 = ephemeral)")
    parser.add_argument("--n-grid", type=int, default=192, dest="n_grid")
    parser.add_argument("--bisect-iters", type=int, default=40, dest="bisect_iters")
    parser.add_argument("--buckets", default="1,8,64")
    parser.add_argument("--cache-dir", default=None,
                        help="on-disk result/executable cache (shared across "
                        "the fleet: results are fingerprint-keyed and pure, "
                        "so concurrent writers are benign)")
    parser.add_argument("--run-dir", default=None,
                        help="obs run dir for this worker's telemetry")
    parser.add_argument("--heartbeat-ttl", type=float, default=None,
                        help="heartbeat TTL seconds (default SBR_HEARTBEAT_TTL_S)")
    parser.add_argument("--beat-s", type=float, default=0.5,
                        help="announcement cadence (default 0.5 s)")
    parser.add_argument("--platform", default=None,
                        help="pin a jax platform before backend init (cpu)")
    args = parser.parse_args(argv)

    if args.platform and args.platform.lower() == "cpu":
        from sbr_tpu.utils.platform import pin_cpu_platform

        pin_cpu_platform()

    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.resilience.shutdown import graceful_shutdown
    from sbr_tpu.serve.endpoint import ServeEndpoint
    from sbr_tpu.serve.engine import Engine, ServeConfig

    buckets = tuple(sorted({int(v) for v in args.buckets.split(",") if v.strip()}))
    serve_cfg = ServeConfig.from_env(
        buckets=buckets, **({"cache_dir": args.cache_dir} if args.cache_dir else {})
    )
    config = SolverConfig(
        n_grid=args.n_grid, bisect_iters=args.bisect_iters, refine_crossings=False
    )

    # The WORKER owns the obs run (not the engine): on SIGTERM the
    # graceful-shutdown envelope must finalize the manifest as
    # "interrupted" — an engine-owned run would be finalized "complete"
    # by the drain's engine.close() before the envelope ever saw it.
    run = None
    if args.run_dir:
        from sbr_tpu import obs

        run = obs.start_run(label="serve_worker", run_dir=args.run_dir)
    engine = Engine(config=config, serve=serve_cfg, run=run)
    engine.start()
    endpoint = ServeEndpoint(engine, port=args.port).start()
    url = f"http://127.0.0.1:{endpoint.port}"
    announcer = WorkerAnnouncer(args.fleet_dir, url, ttl_s=args.heartbeat_ttl)
    with graceful_shutdown(label="serve_worker"):
        try:
            announcer.beat(**_worker_stats(engine))
            print(json.dumps({"url": url, "host": announcer.host,
                              "pid": os.getpid()}), flush=True)
            while True:
                time.sleep(args.beat_s)
                announcer.beat(**_worker_stats(engine))
        finally:
            # Graceful drain (ISSUE 11 satellite): runs on SIGTERM while
            # unwinding toward graceful_shutdown's handler — in-flight
            # batches finish (engine.close drains the queue), the
            # heartbeat file is removed so router peers reclaim the slot
            # immediately, and only THEN does the envelope finalize the
            # obs manifest as "interrupted" and exit 143.
            endpoint.close()
            engine.close()
            announcer.withdraw()
    return 0


def _worker_stats(engine) -> dict:
    """The heartbeat stats block: what the router's cost model reads."""
    from sbr_tpu.obs import trace as qtrace

    window = engine.live.window()
    lat = window.get("latency_ms") or {}
    qps = (window.get("queries", 0) or 0) / max(engine.live.window_s, 1e-9)
    return {
        "qps": round(qps, 3),
        "p50_ms": lat.get("p50"),
        "inflight": engine.live.inflight,
        "queue_depth": engine.live.queue_depth,
        "healthz": engine.healthz(window=window)["status"],
        # Resolved at the worker, surfaced fleet-wide so `report slo` can
        # judge each run dir against the SLO its owner actually served
        # under (ISSUE 16 satellite).
        "slo_ms": qtrace.slo_ms(),
        # Numerics-audit canary status (ISSUE 17): absent entirely when
        # SBR_AUDIT is off; the router quarantines on status "drift".
        **({"audit": engine.audit.heartbeat_block()}
           if getattr(engine, "audit", None) is not None else {}),
        # Compact demand surface (ISSUE 18): this worker's rolling-window
        # (β, u) histogram + heavy-hitter sketch, absent entirely when
        # SBR_DEMAND is off; the router merges present blocks into the
        # fleet demand surface.
        **({"demand": engine.demand.heartbeat_block()}
           if getattr(engine, "demand", None) is not None else {}),
        # Prefetch-controller progress (ISSUE 19): plan fingerprint +
        # tiles done/abandoned, absent entirely when SBR_PREWARM is off;
        # the router rolls present blocks up into the /statz prewarm
        # summary.
        **({"prewarm": engine.prewarm.heartbeat_block()}
           if getattr(engine, "prewarm", None) is not None else {}),
        # Pipeline utilization (ISSUE 20): device-busy / host-gap fractions
        # from the flight recorder, absent entirely when SBR_FLIGHT is off;
        # the router rolls present blocks up into the fleet util surface.
        **({"flight": engine.flight.heartbeat_block()}
           if getattr(engine, "flight", None) is not None else {}),
    }


if __name__ == "__main__":
    sys.exit(main())
