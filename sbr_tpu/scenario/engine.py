"""Composed-scenario solve engine (ISSUE 14 tentpole).

`solve(spec, params)` runs the staged pipeline a `ScenarioSpec` describes:

- **Reducible specs** dispatch to the legacy stacks — the same structural
  trick as `solve_param_cell`: one shared cell, so a baseline / hetero /
  interest / social spec is bit-identical (ξ, status, Health) to the
  direct stack call by construction (pinned by tests/test_scenario.py and
  the CI ``scenario-parity`` step).
- **Genuine compositions** run through the stage-transformer hooks the
  four stacks now expose (`baseline.solver.solve_equilibrium_core` /
  `hetero.solver.solve_equilibrium_hetero` ``hazard_transform`` /
  ``kappa_transform``): policy modifiers and the interest HJB stage
  (`interest.solver.effective_hazard_stage`) splice into ANY pipeline —
  hetero × interest × social simultaneously is one composed program, not
  a fifth forked stack.
- **Multi-bank specs** (banks >= 2) route to `scenario.multibank`: the
  single-bank composed cell vmapped over the bank axis, iterated through
  cross-bank κ-erosion spillovers on the interbank exposure network.

The vmap/jit unit is `solve_scenario_cell` (SCENARIO_KEYS columns) — the
scenario analogue of `sweeps.baseline_sweeps.solve_param_cell`, shared by
`scenario_grid` (policy sweeps — plain grid sweeps over the composed
pipeline, so they inherit PR 7 elasticity tiling untouched), the
multi-bank batch program, and the serve engine's scenario route.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from sbr_tpu.baseline.learning import solve_learning
from sbr_tpu.baseline.solver import (
    get_aw,
    hazard_grid_is_uniform,
    solve_equilibrium_baseline,
    solve_equilibrium_core,
    warped_grid_index,
)
from sbr_tpu.grad.cell import BASE_KEYS
from sbr_tpu.hetero.solver import _cdf_rows_at, solve_equilibrium_hetero
from sbr_tpu.interest.solver import effective_hazard_stage
from sbr_tpu.models.params import ModelParams, SolverConfig
from sbr_tpu.models.results import LearningSolutionHetero
from sbr_tpu.obs import prof
from sbr_tpu.scenario.spec import ScenarioSpec, spec_fingerprint
from sbr_tpu.social.dynamics import solve_forced_learning
from sbr_tpu.sweeps.baseline_sweeps import _TracedLearning, solve_param_cell

# θ column order of one composed cell: the `solve_param_cell` columns,
# then interest's rate/maturity, then the policy knobs.
SCENARIO_KEYS = BASE_KEYS + ("r", "delta", "insurance_cap", "suspension_t", "lolr_rate")


@dataclasses.dataclass
class ScenarioResult:
    """One solved scenario: the headline scalars plus the underlying stack
    result in ``detail`` (an `EquilibriumResult`, `EquilibriumResultHetero`,
    `EquilibriumResultInterest`, `SocialFixedPointResult`, a composed-social
    dict, or a `multibank.MultiBankResult`)."""

    spec: ScenarioSpec
    fingerprint: str
    xi: object
    status: object
    bankrun: object
    health: object
    detail: object

    def __repr__(self) -> str:
        from sbr_tpu.models.results import _fmt

        return (
            f"ScenarioResult(spec={self.spec.learning}+{list(self.spec.modifiers)}"
            f"x{self.spec.banks}, ξ={_fmt(self.xi)}, status={_fmt(self.status)}, "
            f"fp={self.fingerprint[:12]})"
        )


def scenario_theta(params, dtype) -> dict:
    """The SCENARIO_KEYS scalar dict of one params struct (r/δ default to
    the inert 0 / 0.1 when the economics is not interest-typed)."""
    econ = params.economic
    lrn = params.learning
    vals = {
        "beta": lrn.beta,
        "u": econ.u,
        "p": econ.p,
        "kappa": econ.kappa,
        "lam": econ.lam,
        "eta": econ.eta,
        "t0": lrn.tspan[0],
        "t1": lrn.tspan[1],
        "x0": lrn.x0,
        "r": getattr(econ, "r", 0.0),
        "delta": getattr(econ, "delta", 0.1),
        "insurance_cap": econ.insurance_cap,
        "suspension_t": econ.suspension_t,
        "lolr_rate": econ.lolr_rate,
    }
    return {k: jnp.asarray(v, dtype) for k, v in vals.items()}


def _validate_params(spec: ScenarioSpec, params) -> None:
    """Loud spec × params compatibility checks (the composition matrix)."""
    if "interest" in spec.modifiers and not hasattr(params.economic, "r"):
        raise ValueError(
            "spec activates the 'interest' modifier but params carries no "
            "r/delta — build it with make_interest_params(...)"
        )
    if spec.learning == "hetero" and not hasattr(params.learning, "betas"):
        raise ValueError(
            "spec.learning='hetero' requires ModelParamsHetero (betas/dist "
            "group structure) — build it with make_hetero_params(...)"
        )
    if spec.learning == "baseline" and not hasattr(params.learning, "beta"):
        raise ValueError(
            "spec.learning='baseline' requires scalar-beta params "
            "(make_model_params / make_interest_params)"
        )
    if spec.learning == "social" and not (
        hasattr(params.learning, "beta") or hasattr(params.learning, "betas")
    ):
        raise ValueError(
            "spec.learning='social' requires scalar-beta params, or "
            "ModelParamsHetero for the social × hetero composition"
        )


# ---------------------------------------------------------------------------
# Stage-transformer builders (the pluggable slots)
# ---------------------------------------------------------------------------


def _make_hazard_transform(spec: ScenarioSpec, theta: dict, config: SolverConfig, ls):
    """The baseline-family hazard transformer for ``spec.modifiers`` —
    rewrites (hr, hazard_at) in spec order; None when no hazard modifier is
    active (the hook-free legacy path)."""
    mods = tuple(m for m in spec.modifiers if m != "lolr")
    if not mods:
        return None
    warped = not hazard_grid_is_uniform(ls, config)

    def transform(tau_grid, hr, hazard_at):
        dtype = hr.dtype
        extra = []
        for mod in mods:
            if mod == "interest":
                index_fn = None
                if warped and ls.closed_form:
                    index_fn = lambda t: warped_grid_index(
                        t, theta["eta"], ls.beta, ls.x0, config.n_grid, config.grid_warp
                    )
                hr, hazard_at, _v, v_health = effective_hazard_stage(
                    tau_grid, hr, theta["r"], theta["delta"], theta["u"], config,
                    hazard_at=hazard_at, uniform=not warped, index_fn=index_fn,
                )
                extra.append(v_health)
            elif mod == "insurance_cap":
                scale = 1.0 - theta["insurance_cap"]
                hr = scale * hr
                if hazard_at is not None:
                    hazard_at = (
                        lambda prev, s: (lambda t: s * prev(t))
                    )(hazard_at, scale)
            elif mod == "suspension":
                s = theta["suspension_t"]
                zero = jnp.zeros((), dtype)
                hr = jnp.where(tau_grid < s, hr, zero)
                if hazard_at is not None:
                    hazard_at = (
                        lambda prev, s_, z: (lambda t: jnp.where(t < s_, prev(t), z))
                    )(hazard_at, s, zero)
        return hr, hazard_at, tuple(extra)

    return transform


def _make_hazard_transform_hetero(spec: ScenarioSpec, theta: dict, config: SolverConfig):
    """The K-group variant: modifiers rewrite the (K, n) hazard rows; the
    interest stage solves one HJB per row (vmapped), and its per-group
    health flags OR-reduce into one scalar Health so the hetero stack's
    scalar-health contract is preserved."""
    mods = tuple(m for m in spec.modifiers if m != "lolr")
    if not mods:
        return None
    uniform = not (config.grid_warp > 0.0)  # mirrors hazard_rates_hetero

    def transform(tau_grid, hrs, _):
        from sbr_tpu.diag.health import Health, or_reduce_flags

        dtype = hrs.dtype
        extra = []
        for mod in mods:
            if mod == "interest":
                hrs, _none, _v, v_health = jax.vmap(
                    lambda row: effective_hazard_stage(
                        tau_grid, row, theta["r"], theta["delta"], theta["u"],
                        config, hazard_at=None, uniform=uniform,
                    )
                )(hrs)
                extra.append(
                    Health.of_flags(or_reduce_flags(v_health.flags), dtype)
                )
            elif mod == "insurance_cap":
                hrs = (1.0 - theta["insurance_cap"]) * hrs
            elif mod == "suspension":
                hrs = jnp.where(tau_grid < theta["suspension_t"], hrs, jnp.zeros((), dtype))
        return hrs, None, tuple(extra)

    return transform


def _make_kappa_transform(spec: ScenarioSpec, theta: dict):
    """κ_eff = κ·(1 + lolr_rate) when the LOLR modifier is active."""
    if "lolr" not in spec.modifiers:
        return None
    return lambda kappa: jnp.asarray(kappa) * (1.0 + theta["lolr_rate"])


# ---------------------------------------------------------------------------
# The composed cell — the vmap/jit unit (scenario analogue of
# solve_param_cell; baseline-family learning only)
# ---------------------------------------------------------------------------


def solve_scenario_cell(spec: ScenarioSpec, *cols, config: SolverConfig, dtype):
    """One composed cell from 14 traced scalars (SCENARIO_KEYS order) →
    (xi, tau_bar_in_unc, aw_max, status, health) — the lean per-cell
    outputs every batch program shares.

    Reducible specs route through the EXACT legacy cells (`solve_param_cell`
    / `solve_equilibrium_interest_core`), so a composed grid whose spec
    reduces is bit-identical to the legacy grid program cell for cell.
    """
    theta = dict(zip(SCENARIO_KEYS, cols))
    red = spec.reduces_to()
    if red == "baseline":
        return solve_param_cell(*cols[:9], config, dtype)
    ls = solve_learning(
        _TracedLearning(beta=theta["beta"], tspan=(theta["t0"], theta["t1"]), x0=theta["x0"]),
        config, dtype=dtype,
    )
    if red == "interest":
        from sbr_tpu.interest.solver import solve_equilibrium_interest_core

        res = solve_equilibrium_interest_core(
            ls, theta["u"], theta["p"], theta["kappa"], theta["lam"],
            theta["eta"], theta["r"], theta["delta"], theta["t1"], config,
        ).base
        return res.xi, res.tau_bar_in_unc, res.aw_max, res.status, res.health
    res = solve_equilibrium_core(
        ls, theta["u"], theta["p"], theta["kappa"], theta["lam"], theta["eta"],
        theta["t1"], config,
        hazard_transform=_make_hazard_transform(spec, theta, config, ls),
        kappa_transform=_make_kappa_transform(spec, theta),
    )
    return res.xi, res.tau_bar_in_unc, res.aw_max, res.status, res.health


def batch_fn(spec: ScenarioSpec, config: SolverConfig, dtype_name: str):
    """Jitted 1-D batch program over the 14 SCENARIO_KEYS columns — the
    multi-bank vmap unit and the serve engine's scenario dispatch. Cached
    per (cell-program spec, config, dtype): the key is the spec PROJECTED
    onto what the compiled program depends on (`cell_program_spec` —
    learning + modifiers), so specs differing only in host-side knobs
    (lgd, contagion_tol, ...) share one executable instead of compiling an
    identical program per wire-supplied float value."""
    return _batch_fn_cached(spec.cell_program_spec(), config, dtype_name)


@functools.lru_cache(maxsize=None)
def _batch_fn_cached(spec: ScenarioSpec, config: SolverConfig, dtype_name: str):
    dtype = jnp.dtype(dtype_name)

    def fn(*cols):
        prof.note_trace("scenario.batch")

        def cell(*c):
            return solve_scenario_cell(spec, *c, config=config, dtype=dtype)

        return jax.vmap(cell)(*cols)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _grid_fn_cached(spec: ScenarioSpec, config: SolverConfig, dtype_name: str):
    """Jitted β×u grid program over the composed cell — the scenario
    analogue of `baseline_sweeps._grid_fn` (12 broadcast scalars). Keyed
    on the cell-program projection (see `batch_fn`); callers normalize."""
    dtype = jnp.dtype(dtype_name)

    def cell(beta, u, *rest):
        prof.note_trace("scenario.grid")
        return solve_scenario_cell(spec, beta, u, *rest, config=config, dtype=dtype)

    bcast = (None,) * 12
    return jax.jit(
        jax.vmap(jax.vmap(cell, in_axes=(None, 0) + bcast), in_axes=(0, None) + bcast)
    )


def scenario_grid(
    spec: ScenarioSpec,
    beta_values,
    u_values,
    base: ModelParams,
    config: Optional[SolverConfig] = None,
    dtype=None,
):
    """β×u grid sweep through the composed pipeline — policy sweeps are
    JUST grid sweeps over a composed cell (the PR 7 remainder): same
    copy-constructor η/tspan pinning as `sweeps.beta_u_grid`, same
    `GridSweepResult` shape, same sweep-default config (refinement OFF).
    With a baseline-reducible spec the cell IS `solve_param_cell`, so the
    status grid matches `beta_u_grid` exactly (the CI ``scenario-parity``
    gate)."""
    from sbr_tpu import obs
    from sbr_tpu.sweeps.baseline_sweeps import GridSweepResult

    if spec.banks != 1:
        raise ValueError("scenario_grid sweeps single-bank specs; use multibank.solve for banks > 1")
    if spec.learning != "baseline":
        raise ValueError(
            f"scenario_grid requires learning='baseline' cells, got {spec.learning!r}"
        )
    if config is None:
        config = SolverConfig(refine_crossings=False)
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))
    _validate_params(spec, base)

    beta_values = jnp.asarray(beta_values, dtype=dtype)
    u_values = jnp.asarray(u_values, dtype=dtype)
    theta = scenario_theta(base, dtype)
    scalars = tuple(theta[k] for k in SCENARIO_KEYS[2:])  # broadcast: all but beta, u
    fp = spec_fingerprint(spec, None, config, dtype.name)
    fn = _grid_fn_cached(spec.cell_program_spec(), config, dtype.name)
    n_b, n_u = int(beta_values.shape[0]), int(u_values.shape[0])
    with obs.span(
        "scenario.grid", scenario=fp[:12], n_beta=n_b, n_u=n_u, dtype=dtype.name
    ) as sp:
        xi, tau_in, aw_max, status, health = obs.jit_call(
            "scenario.grid", fn, beta_values, u_values, *scalars
        )
        sp.sync(status)
    obs.log_status("scenario.grid", status)
    obs.log_health("scenario.grid", health, status, scenario=fp[:12])
    return GridSweepResult(
        beta_values=beta_values, u_values=u_values, max_aw=aw_max, xi=xi,
        status=status, health=health,
    )


def run_tiled_scenario_grid(
    spec: ScenarioSpec,
    beta_values,
    u_values,
    base: ModelParams,
    checkpoint_dir: Optional[str] = None,
    config: Optional[SolverConfig] = None,
    dtype=None,
    tile_shape=(256, 256),
    **kw,
):
    """β×u scenario sweep through the tiled elastic checkpoint driver
    (ISSUE 15 satellite — the PR 13 remainder): `scenario_grid` cells with
    `utils.checkpoint.run_tiled_grid`'s whole production stack — local
    checkpoint resume, the cross-run global tile cache, retry policy +
    budget, per-tile leases under the elastic scheduler
    (`resilience.elastic.run_elastic_grid(scenario_spec=spec, ...)` for
    multi-host sweeps), and degrade-ladder healing on baseline-reducible
    specs. The spec joins the sweep fingerprint and every tile-cache key,
    so composed and plain sweeps never share bytes — EXCEPT the exact
    baseline reduction, which is deliberately keyed as a plain sweep
    (cells are bit-identical by the parity contract, so a warm legacy
    cache answers it for free).

    Same spec constraints as `scenario_grid` (single bank, baseline-family
    learning); ``**kw`` passes through to `run_tiled_grid`
    (max_retries / tile_cache / heal_divergent / verbose / ...).
    """
    from sbr_tpu.utils.checkpoint import run_tiled_grid

    if spec.banks != 1:
        raise ValueError(
            "run_tiled_scenario_grid sweeps single-bank specs; use "
            "multibank.solve for banks > 1"
        )
    if spec.learning != "baseline":
        raise ValueError(
            f"run_tiled_scenario_grid requires learning='baseline' cells, "
            f"got {spec.learning!r}"
        )
    _validate_params(spec, base)
    passthrough = None if spec.reduces_to() == "baseline" else spec
    return run_tiled_grid(
        beta_values, u_values, base, config=config, tile_shape=tile_shape,
        checkpoint_dir=checkpoint_dir, dtype=dtype, scenario_spec=passthrough,
        **kw,
    )


# ---------------------------------------------------------------------------
# Composed social fixed point (social × {hetero, interest, policy})
# ---------------------------------------------------------------------------


class _ThetaEcon:
    """Duck-typed economics over a traced θ dict (scenario-internal)."""

    def __init__(self, theta: dict):
        self.u = theta["u"]
        self.p = theta["p"]
        self.kappa = theta["kappa"]
        self.lam = theta["lam"]
        self.eta = theta["eta"]


def _aw_curve_hetero(xi, tau_ins, tau_outs, grid, lsh: LearningSolutionHetero):
    """Dist-weighted aggregate AW(t) on ``grid`` — the hetero analogue of
    `baseline.solver.get_aw`'s cumulative curve (each branch zeroed before
    its own start, plus the aggregate G(0) offset)."""
    tau_in_con = jnp.minimum(tau_ins, xi)
    tau_out_con = jnp.minimum(tau_outs, xi)

    def branch(tau_con):
        shift = grid[None, :] - xi + tau_con[:, None]
        vals = _cdf_rows_at(lsh, jnp.maximum(shift, 0.0))
        return jnp.where(shift >= 0, vals, 0.0)

    aw = jnp.einsum("k,kn->n", lsh.dist, branch(tau_out_con) - branch(tau_in_con))
    return aw + jnp.dot(lsh.dist, lsh.cdfs[:, 0])


@functools.lru_cache(maxsize=None)
def _composed_social_fn(spec: ScenarioSpec, config: SolverConfig, dtype_name: str,
                        hetero: bool):
    """Jitted composed social fixed point: the legacy damped iteration
    (`social.solver`) with the INNER equilibrium generalized to any
    composed baseline-family or hetero pipeline. Plain damping only — the
    Anderson acceleration stays a legacy-stack specialization; the inner
    solves still honor ``config.numerics``. Callers key on
    `social_program_spec` (social knobs are trace-time constants here;
    contagion/multibank fields are not)."""
    dtype = jnp.dtype(dtype_name)
    tol = spec.social_tol
    max_iter = spec.social_max_iter
    alpha = spec.social_damping

    @jax.jit
    def run(theta, betas, dist, grid):
        prof.note_trace("scenario.social_fixed_point")
        tol_ = jnp.asarray(tol, dtype)
        a = jnp.asarray(alpha, dtype)
        eta = theta["eta"]
        x0 = theta["x0"]
        kt = _make_kappa_transform(spec, theta)

        def inner(aw):
            if hetero:
                from sbr_tpu.core.integrate import cumtrapz

                big_a = cumtrapz(aw, dx=grid[1] - grid[0])
                cdfs = 1.0 - (1.0 - x0) * jnp.exp(-betas[:, None] * big_a[None, :])
                pdfs = (1.0 - cdfs) * betas[:, None] * aw[None, :]
                lsh = LearningSolutionHetero(
                    grid=grid, cdfs=cdfs, pdfs=pdfs, t0=grid[0],
                    dt=grid[1] - grid[0], betas=betas, dist=dist,
                )
                res = solve_equilibrium_hetero(
                    lsh, _ThetaEcon(theta), config, tspan_end=eta,
                    hazard_transform=_make_hazard_transform_hetero(spec, theta, config),
                    kappa_transform=kt,
                )
                return res, lsh
            ls = solve_forced_learning(theta["beta"], aw, grid, x0)
            res = solve_equilibrium_core(
                ls, theta["u"], theta["p"], theta["kappa"], theta["lam"], eta,
                eta, config,
                hazard_transform=_make_hazard_transform(spec, theta, config, ls),
                kappa_transform=kt,
            )
            return res, ls

        def step(aw, xi_prev):
            res, lsol = inner(aw)
            xi_new = jnp.where(res.bankrun, res.xi, xi_prev + eta / 500.0)
            exceeded = jnp.logical_and(~res.bankrun, xi_new > eta)
            if hetero:
                aw_new = _aw_curve_hetero(
                    xi_new, res.tau_bar_in_uncs, res.tau_bar_out_uncs, grid, lsol
                )
            else:
                aw_new, _, _ = get_aw(
                    xi_new, res.tau_bar_in_unc, res.tau_bar_out_unc, grid, lsol
                )
            return res, xi_new, exceeded, aw_new

        def cond(s):
            return (s["it"] < max_iter) & (~s["conv"]) & (~s["abort"])

        def body(s):
            res, xi_new, exceeded, aw_new = step(s["aw"], s["xi"])
            err = jnp.max(jnp.abs(aw_new - s["aw"]))
            conv = jnp.logical_and(err < tol_, ~exceeded)
            aw_next = jnp.where(conv, aw_new, (1.0 - a) * s["aw"] + a * aw_new)
            aw_next = jnp.where(exceeded, s["aw"], aw_next)
            return dict(
                aw=aw_next, xi=xi_new, it=s["it"] + 1, conv=conv,
                abort=exceeded, err=err, res=res,
            )

        from sbr_tpu.baseline.learning import logistic_cdf

        aw0 = logistic_cdf(
            grid,
            (jnp.dot(dist, betas) if hetero else theta["beta"]),
            x0,
        )
        res_shape = jax.eval_shape(lambda a: step(a, jnp.zeros((), dtype))[0], aw0)
        res0 = jax.tree_util.tree_map(
            lambda sh: jnp.zeros(sh.shape, sh.dtype), res_shape
        )
        init = dict(
            aw=aw0,
            xi=jnp.zeros((), dtype),
            it=jnp.zeros((), jnp.int32),
            conv=jnp.zeros((), bool),
            abort=jnp.zeros((), bool),
            err=jnp.asarray(jnp.inf, dtype),
            res=res0,
        )
        final = jax.lax.while_loop(cond, body, init)

        from sbr_tpu.diag.health import FP_ABORTED, FP_NOT_CONVERGED, NAN_OUTPUT, Health

        not_conv = (~final["conv"]) & (~final["abort"])
        fp_flags = (
            jnp.where(not_conv, jnp.int32(FP_NOT_CONVERGED), jnp.int32(0))
            | jnp.where(final["abort"], jnp.int32(FP_ABORTED), jnp.int32(0))
            | jnp.where(
                jnp.any(~jnp.isfinite(final["aw"])), jnp.int32(NAN_OUTPUT), jnp.int32(0)
            )
        )
        nan = jnp.asarray(jnp.nan, dtype)
        fp_health = Health(
            residual=final["err"], bracket_width=nan,
            iterations=final["it"], flags=fp_flags,
        )
        return dict(
            equilibrium=final["res"],
            aw=final["aw"],
            xi=final["xi"],
            iterations=final["it"],
            converged=final["conv"],
            aborted=final["abort"],
            error=final["err"],
            health=final["res"].health.merge(fp_health),
        )

    return run


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------


def solve(
    spec: ScenarioSpec,
    params,
    config: Optional[SolverConfig] = None,
    dtype=None,
) -> ScenarioResult:
    """Solve one composed scenario (see module docstring for dispatch).

    ``params`` is a `ModelParams` (or Hetero/Interest variant matching the
    spec; for multi-bank, optionally a list of one params per bank). The
    result's ``fingerprint`` keys every cache a composed scenario touches.
    """
    from sbr_tpu import obs

    if spec.banks > 1:
        # Dispatch BEFORE defaulting config: multibank's own default is the
        # sweep-style SolverConfig(refine_crossings=False) (it dispatches
        # vmapped cells), and solve()/solve_multibank must agree on the
        # numerics — and therefore on the fingerprint — for the same call.
        from sbr_tpu.scenario.multibank import solve_multibank

        return solve_multibank(spec, params, config=config, dtype=dtype)

    if config is None:
        config = SolverConfig()
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))

    _validate_params(spec, params)
    fp = spec_fingerprint(spec, params, config, dtype.name)
    red = spec.reduces_to()

    with obs.span(
        "scenario.solve", scenario=fp[:12], learning=spec.learning,
        modifiers=",".join(spec.modifiers) or "-", banks=spec.banks,
        reduction=red or "composed",
    ) as sp:
        if red == "baseline":
            ls = solve_learning(params.learning, config, dtype=dtype)
            res = solve_equilibrium_baseline(ls, params.economic, config)
            out = ScenarioResult(spec, fp, res.xi, res.status, res.bankrun, res.health, res)
        elif red == "interest":
            from sbr_tpu.interest.solver import solve_equilibrium_interest

            ls = solve_learning(params.learning, config, dtype=dtype)
            res = solve_equilibrium_interest(ls, params.economic, config)
            out = ScenarioResult(
                spec, fp, res.base.xi, res.base.status, res.base.bankrun,
                res.base.health, res,
            )
        elif red == "hetero":
            from sbr_tpu.hetero.learning import solve_learning_hetero

            lsh = solve_learning_hetero(params.learning, config, dtype=dtype)
            res = solve_equilibrium_hetero(lsh, params.economic, config)
            out = ScenarioResult(spec, fp, res.xi, res.status, res.bankrun, res.health, res)
        elif red == "social" and hasattr(params.learning, "beta"):
            # social × hetero params (no scalar beta) falls through to the
            # composed fixed point even with no modifiers — the legacy
            # social stack is scalar-beta only.
            from sbr_tpu.social.solver import solve_equilibrium_social

            res = solve_equilibrium_social(
                params, config, tol=spec.social_tol, max_iter=spec.social_max_iter,
                damping=spec.social_damping, dtype=dtype,
            )
            out = ScenarioResult(
                spec, fp, res.equilibrium.xi, res.equilibrium.status,
                res.equilibrium.bankrun, res.health, res,
            )
        elif spec.learning == "social":
            out = _solve_composed_social(spec, params, config, dtype, fp)
        elif spec.learning == "hetero":
            from sbr_tpu.hetero.learning import solve_learning_hetero

            theta = scenario_theta_hetero(params, dtype)
            lsh = solve_learning_hetero(params.learning, config, dtype=dtype)
            res = solve_equilibrium_hetero(
                lsh, params.economic, config,
                hazard_transform=_make_hazard_transform_hetero(spec, theta, config),
                kappa_transform=_make_kappa_transform(spec, theta),
            )
            out = ScenarioResult(spec, fp, res.xi, res.status, res.bankrun, res.health, res)
        else:  # composed baseline-family
            theta = scenario_theta(params, dtype)
            ls = solve_learning(params.learning, config, dtype=dtype)
            res = solve_equilibrium_core(
                ls, theta["u"], theta["p"], theta["kappa"], theta["lam"],
                theta["eta"], ls.grid[-1], config,
                hazard_transform=_make_hazard_transform(spec, theta, config, ls),
                kappa_transform=_make_kappa_transform(spec, theta),
            )
            out = ScenarioResult(spec, fp, res.xi, res.status, res.bankrun, res.health, res)
        sp.sync(out.xi)

    obs.log_health("scenario.solve", out.health, out.status, scenario=fp[:12])
    return out


def scenario_theta_hetero(params, dtype) -> dict:
    """θ dict for hetero-family specs: SCENARIO_KEYS minus the scalar beta
    (group betas/dist ride the params struct into Stage 1 directly)."""
    econ = params.economic
    lrn = params.learning
    vals = {
        "u": econ.u, "p": econ.p, "kappa": econ.kappa, "lam": econ.lam,
        "eta": econ.eta, "t0": lrn.tspan[0], "t1": lrn.tspan[1], "x0": lrn.x0,
        "r": getattr(econ, "r", 0.0), "delta": getattr(econ, "delta", 0.1),
        "insurance_cap": econ.insurance_cap, "suspension_t": econ.suspension_t,
        "lolr_rate": econ.lolr_rate,
    }
    return {k: jnp.asarray(v, dtype) for k, v in vals.items()}


def _solve_composed_social(spec, params, config, dtype, fp) -> ScenarioResult:
    """Composed social fixed point: tspan overridden to (0, η) like the
    legacy stack, inner pipeline per spec."""
    hetero = spec.learning == "social" and hasattr(params.learning, "betas")
    if hetero:
        theta = scenario_theta_hetero(params, dtype)
        betas = jnp.asarray(params.learning.betas, dtype)
        dist = jnp.asarray(params.learning.dist, dtype)
        inner_spec = dataclasses.replace(spec.social_program_spec(), learning="hetero")
    else:
        theta = scenario_theta(params, dtype)
        betas = jnp.zeros((1,), dtype)
        dist = jnp.ones((1,), dtype)
        inner_spec = dataclasses.replace(spec.social_program_spec(), learning="baseline")
    eta = params.economic.eta
    grid = jnp.linspace(jnp.zeros((), dtype), jnp.asarray(eta, dtype), config.n_grid)
    run = _composed_social_fn(inner_spec, config, dtype.name, hetero)
    out = run(theta, betas, dist, grid)
    eq = out["equilibrium"]
    return ScenarioResult(
        spec, fp, eq.xi, eq.status, eq.bankrun, out["health"], out,
    )
