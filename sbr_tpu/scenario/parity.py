"""Composed-vs-legacy parity smoke — the CI ``scenario-parity`` gate.

    python -m sbr_tpu.scenario.parity [--n 48] [--banks 3]

Two checks, both exact:

1. **Grid parity**: an n×n f64 β×u grid through `scenario.scenario_grid`
   with the baseline-reducible spec must produce a status grid EXACTLY
   equal to `sweeps.beta_u_grid`'s (and bitwise-equal ξ where finite) —
   the composed cell IS `solve_param_cell`, so any divergence means the
   composition layer perturbed the shared cell.
2. **Multi-bank sanity**: N banks with an EMPTY exposure network must be
   bit-identical to N independent single-bank solves through the same
   vmapped cell — zero spillover must be a structural no-op, not an
   approximate one.

Exit 0 on success; an AssertionError (exit 1) names the first divergence.
"""

from __future__ import annotations

import argparse
import sys


def run_checks(n: int = 48, banks: int = 3) -> int:
    """Run both parity checks; raises AssertionError naming the first
    divergence, returns 0 on success (the audit legacy-CLI contract)."""
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from sbr_tpu import scenario
    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid

    base = make_model_params()
    config = SolverConfig(n_grid=512, bisect_iters=60, refine_crossings=False)
    betas = np.linspace(0.25, 3.0, n)
    us = np.linspace(0.01, 0.99, n)

    spec = scenario.ScenarioSpec()  # baseline-reducible
    composed = scenario.scenario_grid(spec, betas, us, base, config=config)
    legacy = beta_u_grid(betas, us, base, config=config)

    st_c = np.asarray(composed.status)
    st_l = np.asarray(legacy.status)
    assert np.array_equal(st_c, st_l), (
        f"composed-vs-legacy status grids diverged in {np.sum(st_c != st_l)} cells"
    )
    xi_c = np.asarray(composed.xi)
    xi_l = np.asarray(legacy.xi)
    assert np.array_equal(np.isfinite(xi_c), np.isfinite(xi_l)), "finite masks diverged"
    both = np.isfinite(xi_c)
    assert np.array_equal(xi_c[both], xi_l[both]), (
        "composed ξ grid is not bit-identical to the legacy grid"
    )
    print(f"grid parity ok: {st_c.size} cells, {int((st_c == 0).sum())} runs, "
          f"ξ bitwise equal")

    # Multi-bank sanity: empty exposure == independent solves, bitwise.
    n_banks = banks
    plist = [
        make_model_params(beta=1.0 + 0.2 * i, u=0.05 + 0.02 * i)
        for i in range(n_banks)
    ]
    mb_spec = scenario.ScenarioSpec(banks=n_banks)
    mb = scenario.solve_multibank(mb_spec, plist, config=config)
    assert mb.converged and mb.iterations == 1, (mb.converged, mb.iterations)

    cell_batch = scenario.engine.batch_fn(
        scenario.ScenarioSpec(), config, jnp.dtype(jnp.float64).name
    )
    cols = scenario.multibank._bank_columns(mb_spec, plist, jnp.float64)
    xi_i, tau_i, aw_i, st_i, _h = cell_batch(*cols)
    xi_mb = np.asarray(mb.xi)
    xi_ind = np.asarray(xi_i)
    assert np.array_equal(np.asarray(mb.status), np.asarray(st_i)), "multibank status diverged"
    both = np.isfinite(xi_mb)
    assert np.array_equal(both, np.isfinite(xi_ind)), "multibank finite masks diverged"
    assert np.array_equal(xi_mb[both], xi_ind[both]), (
        "empty-exposure multibank ξ is not bit-identical to independent solves"
    )
    assert np.array_equal(np.asarray(mb.kappa_eff),
                          np.asarray(cols[scenario.SCENARIO_KEYS.index("kappa")])), (
        "empty-exposure κ_eff drifted from the input κ column"
    )
    print(f"multibank sanity ok: {n_banks} banks, empty network bit-identical "
          f"to independent solves")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m sbr_tpu.scenario.parity")
    parser.add_argument("--n", type=int, default=48, help="grid side (default 48)")
    parser.add_argument("--banks", type=int, default=3, help="banks in the sanity check")
    parser.add_argument("--obs-dir", default=None,
                        help="record the verdict as an audit probe event "
                        "under this obs run root")
    args = parser.parse_args(argv)

    # Legacy entrypoint, audit protocol (ISSUE 17): the checks run through
    # the unified registry runner — an AssertionError becomes a drift
    # verdict + exit 1, exactly the historical contract.
    from sbr_tpu.obs import audit

    return audit.run_legacy_cli(
        "scenario.composed", lambda: run_checks(n=args.n, banks=args.banks),
        obs_dir=args.obs_dir,
    )


if __name__ == "__main__":
    sys.exit(main())
