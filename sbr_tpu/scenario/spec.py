"""`ScenarioSpec` — the frozen, hashable, fingerprintable description of a
composed solve pipeline (ISSUE 14 tentpole).

A spec is pure STRUCTURE: which learning-stage transformer runs Stage 1,
which hazard/buffer modifiers rewrite Stage 2, and how many banks couple
through which interbank exposure network. Parameter VALUES (β, u, κ, the
policy knobs insurance_cap / suspension_t / lolr_rate, the hetero group
structure betas/dist, interest's r/δ) live in the params structs
(`models.params`) exactly as before — a spec plus a params struct fully
determines a solve, and `spec_fingerprint` hashes the pair through the
same `utils.checkpoint.canonicalize` machinery every cache in the repo
keys on (serve result cache, global tile cache, AOT executables).

Composition matrix (what `__post_init__` accepts vs rejects loudly):

==========  ========  ============================  =====================
learning    banks     modifiers                     notes
==========  ========  ============================  =====================
baseline    1         any subset, any order         reduces to the legacy
                                                    baseline/interest
                                                    stacks when trivial
hetero      1         any subset                    interest V solved per
                                                    group row
social      1         any subset                    modifiers apply to
                                                    every inner iterate
baseline    >= 2      any subset                    multi-bank contagion
hetero      >= 2      REJECTED                      per-bank group axes
                                                    would need a ragged
                                                    vmap — explicit error
social      >= 2      REJECTED                      fixed point inside the
                                                    contagion loop is not
                                                    supported — explicit
                                                    error
==========  ========  ============================  =====================

Modifiers (applied to the hazard in spec order; ``lolr`` acts on κ at the
ξ stage regardless of position):

- ``"interest"``       — HJB value function, effective hazard h − r·V
  (`interest.solver.effective_hazard_stage`; requires params with r/δ).
- ``"insurance_cap"``  — h ← (1 − insurance_cap)·h: the insured deposit
  fraction abstains from the withdrawal race.
- ``"suspension"``     — h ← h·1[τ̄ < suspension_t]: convertibility is
  suspended from suspension_t on, so running past it has no value.
- ``"lolr"``           — κ_eff = κ·(1 + lolr_rate): lender-of-last-resort
  injections let the bank survive a larger withdrawal share.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

LEARNING_STAGES = ("baseline", "hetero", "social")
HAZARD_MODIFIERS = ("interest", "insurance_cap", "suspension", "lolr")


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """One composed scenario (see module docstring).

    Plain-python frozen dataclass: hashable (a static jit argument — every
    distinct spec compiles its own program, cached), and canonicalizable
    (`utils.checkpoint.canonicalize` renders dataclasses by sorted field
    name, so the spec drops into `params_fingerprint` unchanged).
    """

    learning: str = "baseline"
    # Ordered hazard/buffer modifiers; hazard rewrites apply in this order.
    modifiers: Tuple[str, ...] = ()
    # Multi-bank contagion (banks >= 2): interbank exposure edges
    # (src, dst, weight) — bank `dst` holds `weight` of exposure to bank
    # `src` and suffers when `src` fails. () = independent banks.
    banks: int = 1
    exposure: Tuple[Tuple[int, int, float], ...] = ()
    # Social fixed-point knobs (legacy `solve_equilibrium_social` defaults).
    social_tol: float = 1e-4
    social_max_iter: int = 250
    social_damping: float = 0.5
    # Contagion-loop knobs: damped κ-erosion iteration (multibank.py).
    contagion_max_iter: int = 32
    contagion_tol: float = 1e-10
    contagion_damping: float = 1.0
    # Loss-given-default on interbank exposure and the κ erosion floor.
    lgd: float = 0.5
    kappa_floor: float = 1e-3

    def __post_init__(self):
        if self.learning not in LEARNING_STAGES:
            raise ValueError(
                f"unknown learning stage {self.learning!r}; "
                f"expected one of {LEARNING_STAGES}"
            )
        mods = tuple(self.modifiers)
        object.__setattr__(self, "modifiers", mods)
        unknown = [m for m in mods if m not in HAZARD_MODIFIERS]
        if unknown:
            raise ValueError(
                f"unknown modifier(s) {unknown}; expected a subset of "
                f"{HAZARD_MODIFIERS}"
            )
        if len(set(mods)) != len(mods):
            raise ValueError(f"duplicate modifiers in {mods}")
        if self.banks < 1:
            raise ValueError(f"banks must be >= 1, got {self.banks}")
        if self.banks > 1 and self.learning != "baseline":
            # The composition matrix's loud rejections (module docstring).
            raise ValueError(
                f"multi-bank contagion supports learning='baseline' only "
                f"(got learning={self.learning!r} with banks={self.banks}); "
                f"see the composition matrix in sbr_tpu/scenario/spec.py"
            )
        exposure = tuple((int(s), int(d), float(w)) for s, d, w in self.exposure)
        object.__setattr__(self, "exposure", exposure)
        if exposure and self.banks < 2:
            raise ValueError("exposure edges require banks >= 2")
        for s, d, w in exposure:
            if not (0 <= s < self.banks and 0 <= d < self.banks):
                raise ValueError(
                    f"exposure edge ({s}, {d}) out of range for {self.banks} banks"
                )
            if s == d:
                raise ValueError(f"self-exposure edge ({s}, {d}) is not allowed")
            if w < 0:
                raise ValueError(f"exposure weight must be non-negative, got {w}")
        if not (self.social_tol > 0 and self.social_max_iter >= 1):
            raise ValueError("social_tol must be > 0 and social_max_iter >= 1")
        if not (0 < self.social_damping <= 1):
            raise ValueError(f"social_damping must be in (0, 1], got {self.social_damping}")
        if not (self.contagion_max_iter >= 1 and self.contagion_tol >= 0):
            raise ValueError("contagion_max_iter must be >= 1 and contagion_tol >= 0")
        if not (0 < self.contagion_damping <= 1):
            raise ValueError(
                f"contagion_damping must be in (0, 1], got {self.contagion_damping}"
            )
        if not (0 <= self.lgd <= 1):
            raise ValueError(f"lgd must be in [0, 1], got {self.lgd}")
        if not (0 < self.kappa_floor < 1):
            raise ValueError(f"kappa_floor must be in (0, 1), got {self.kappa_floor}")

    # -- reductions ----------------------------------------------------------
    def reduces_to(self) -> Optional[str]:
        """The legacy stack this spec is EXACTLY, or None for a genuine
        composition. Reducible specs route through the legacy entry points
        — one shared cell, forward bits equal by construction (the
        `solve_param_cell` structural trick the golden-parity suite pins).
        """
        if self.banks != 1:
            return None
        if self.learning == "baseline" and self.modifiers == ():
            return "baseline"
        if self.learning == "baseline" and self.modifiers == ("interest",):
            return "interest"
        if self.learning == "hetero" and self.modifiers == ():
            return "hetero"
        if self.learning == "social" and self.modifiers == ():
            return "social"
        return None

    @property
    def policy_modifiers(self) -> Tuple[str, ...]:
        """The policy subset of the active modifiers."""
        return tuple(m for m in self.modifiers if m != "interest")

    def cell_program_spec(self) -> "ScenarioSpec":
        """The spec projected onto the fields a compiled single-bank CELL
        program actually depends on (learning + modifiers). Jit caches key
        on this, not the full spec: the host-side knobs (contagion_*, lgd,
        kappa_floor, social_* for non-social cells, banks/exposure) never
        enter the traced program, and keying on them would compile one
        identical executable per wire-supplied float value — unbounded
        growth on a server accepting arbitrary scenario objects."""
        return ScenarioSpec(learning=self.learning, modifiers=self.modifiers)

    def social_program_spec(self) -> "ScenarioSpec":
        """Like `cell_program_spec`, for the composed social fixed-point
        program — the social knobs ARE baked into that while_loop (tol,
        max_iter, damping are trace-time constants), so they stay in the
        key; only the contagion/multibank fields are projected away."""
        return ScenarioSpec(
            learning=self.learning, modifiers=self.modifiers,
            social_tol=self.social_tol, social_max_iter=self.social_max_iter,
            social_damping=self.social_damping,
        )

    def grad_reduction(self) -> Optional[str]:
        """Which grad-covered stack this spec reduces to ("baseline" /
        "interest"), or None — the gradient-coverage matrix for
        `grad.api.scenario_xi_and_grad` (see README "Composable
        scenarios")."""
        red = self.reduces_to()
        return red if red in ("baseline", "interest") else None

    # -- wire form -----------------------------------------------------------
    def to_doc(self) -> dict:
        """JSON-ready document (the `POST /query` ``scenario`` field)."""
        doc = {"learning": self.learning, "modifiers": list(self.modifiers)}
        if self.banks != 1:
            doc["banks"] = self.banks
            doc["exposure"] = [list(e) for e in self.exposure]
        for f in (
            "social_tol", "social_max_iter", "social_damping",
            "contagion_max_iter", "contagion_tol", "contagion_damping",
            "lgd", "kappa_floor",
        ):
            if getattr(self, f) != getattr(type(self), "__dataclass_fields__")[f].default:
                doc[f] = getattr(self, f)
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "ScenarioSpec":
        """Parse the wire form; unknown keys are a loud error (a typo like
        ``"modfiers"`` must not silently serve the default pipeline)."""
        if not isinstance(doc, dict):
            raise ValueError(f"scenario must be a JSON object, got {type(doc).__name__}")
        known = set(cls.__dataclass_fields__)
        unknown = set(doc) - known
        if unknown:
            raise ValueError(f"unknown scenario field(s): {sorted(unknown)}")
        kw = dict(doc)
        if "modifiers" in kw:
            kw["modifiers"] = tuple(str(m) for m in kw["modifiers"])
        if "exposure" in kw:
            kw["exposure"] = tuple(tuple(e) for e in kw["exposure"])
        return cls(**kw)


# Bump when a composed cell's NUMERICS change (the scenario analogue of
# `sweeps.baseline_sweeps.GRID_PROGRAM_VERSION`): part of every scenario
# fingerprint, so caches can never serve bytes from older pipeline math.
SCENARIO_PROGRAM_VERSION = 1


def spec_fingerprint(spec: ScenarioSpec, params=None, config=None, dtype=None) -> str:
    """Stable sha256 of (spec[, params, config, dtype]) — THE key composed
    scenarios are cached and served under. Rides the exact canonical
    machinery of `utils.checkpoint.params_fingerprint`, so a scenario
    fingerprint can never collide with a plain params fingerprint (the
    dataclass name is part of the canonical form)."""
    from sbr_tpu.utils.checkpoint import params_fingerprint

    payload = [spec, SCENARIO_PROGRAM_VERSION]
    if params is not None:
        payload.append(params)
    if config is not None:
        payload.append(config)
    if dtype is not None:
        import jax.numpy as jnp

        payload.append(jnp.dtype(dtype).name)
    return params_fingerprint(tuple(payload))
