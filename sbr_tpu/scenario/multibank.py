"""Multi-bank contagion on an interbank exposure network (ISSUE 14).

N banks, each one composed single-bank cell (`engine.solve_scenario_cell`
vmapped over the bank axis — the same vmap-over-columns shape as the serve
engine's micro-batch program), coupled through cross-bank spillovers
iterated with the social fixed-point discipline (damped iteration to a
stable κ vector):

1. Solve all N banks with the current effective thresholds κ_eff.
2. Each RUN bank j inflicts a loss proportional to its peak withdrawal
   share AW_max_j on every counterparty holding exposure to it.
3. κ_eff_i ← clip(κ_i − lgd·Σ_{j→i} w_ij·loss_j, κ_floor, κ_i), damped by
   ``spec.contagion_damping`` — counterparty losses erode bank i's
   solvency buffer, so a run elsewhere makes bank i runnable at a smaller
   withdrawal share (`Status.NO_ROOT` cells can flip to RUN: contagion).
4. Repeat until the κ vector is stable (``contagion_tol``) or
   ``contagion_max_iter`` is exhausted.

The exposure network reuses the `social/` graph kernels: edges are
canonicalized dst-sorted through `native.sort_edges_by_dst` (the agent
engine's canonical layout) and the per-bank spillover aggregation is the
same exact-prefix-sum segmented reduction as `social.agents._seg_counts`,
generalized to weighted values — no scatter-add, TPU-friendly at any N.

An EMPTY exposure network converges in one iteration with κ_eff ≡ κ
bitwise, so N uncoupled banks are bit-identical to N independent
single-bank solves through the same vmapped cell (the CI multi-bank
sanity gate).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from sbr_tpu.models.params import SolverConfig
from sbr_tpu.models.results import Status
from sbr_tpu.scenario.spec import ScenarioSpec, spec_fingerprint


@dataclasses.dataclass
class MultiBankResult:
    """Per-bank grids plus the contagion-loop metadata."""

    spec: ScenarioSpec
    fingerprint: str
    xi: jnp.ndarray  # (N,) NaN-masked crash times
    tau_bar_in: jnp.ndarray  # (N,)
    aw_max: jnp.ndarray  # (N,)
    status: jnp.ndarray  # (N,) int32 Status codes
    kappa_eff: jnp.ndarray  # (N,) final effective thresholds
    spillover: jnp.ndarray  # (N,) final incoming exposure-weighted losses
    iterations: int
    converged: bool
    health: object  # batched diag.Health, leaves (N,)

    @property
    def bankrun(self):
        return self.status == jnp.int32(Status.RUN)

    def __repr__(self) -> str:
        import numpy as _np

        runs = int(_np.asarray(self.status == 0).sum())
        return (
            f"MultiBankResult(banks={self.spec.banks}, runs={runs}, "
            f"iterations={self.iterations}, converged={self.converged}, "
            f"fp={self.fingerprint[:12]})"
        )


def _seg_weighted(values: jnp.ndarray, row_ptr: jnp.ndarray) -> jnp.ndarray:
    """Weighted segmented sum over dst-sorted edge values — the
    `social.agents._seg_counts` prefix-sum idiom with float payloads:
    out[i] = Σ values[row_ptr[i] : row_ptr[i+1]]."""
    prefix = jnp.concatenate(
        [jnp.zeros((1,), values.dtype), jnp.cumsum(values)]
    )
    return prefix[row_ptr[1:]] - prefix[row_ptr[:-1]]


def _exposure_layout(spec: ScenarioSpec):
    """Canonical dst-sorted exposure layout via the agent engine's edge
    sorter: (src_sorted, w_sorted, row_ptr) with edges of bank i in
    [row_ptr[i], row_ptr[i+1]). The permutation comes from sorting edge
    ids as payload — `sort_edges_by_dst` is stable, so weights follow
    their edges exactly."""
    from sbr_tpu.native import sort_edges_by_dst

    if not spec.exposure:
        return None
    src = np.asarray([e[0] for e in spec.exposure], np.int32)
    dst = np.asarray([e[1] for e in spec.exposure], np.int32)
    w = np.asarray([e[2] for e in spec.exposure], np.float64)
    eids, _dst_sorted, _indeg, row_ptr = sort_edges_by_dst(
        np.arange(src.shape[0], dtype=np.int32), dst, spec.banks
    )
    return src[eids], w[eids], np.asarray(row_ptr, np.int64)


def _bank_columns(spec: ScenarioSpec, params, dtype) -> list:
    """(14, N) SCENARIO_KEYS columns from one shared params struct or a
    list of one per bank."""
    from sbr_tpu.scenario.engine import SCENARIO_KEYS, scenario_theta

    if isinstance(params, (list, tuple)):
        if len(params) != spec.banks:
            raise ValueError(
                f"got {len(params)} params structs for {spec.banks} banks"
            )
        plist = list(params)
    else:
        plist = [params] * spec.banks
    thetas = [scenario_theta(p, dtype) for p in plist]
    return [
        jnp.stack([t[k] for t in thetas]) for k in SCENARIO_KEYS
    ]


def solve_multibank(
    spec: ScenarioSpec,
    params,
    config: Optional[SolverConfig] = None,
    dtype=None,
) -> MultiBankResult:
    """Solve an N-bank contagion scenario (module docstring).

    The per-iteration solve is ONE vmapped dispatch of the composed cell;
    the κ-erosion update runs on host between dispatches (N is small —
    tens of banks — and the loop usually converges in a handful of
    rounds). Health is logged per bank with the scenario/bank obs tags so
    `report health` renders a per-bank census instead of one mixed grid.
    """
    from sbr_tpu import obs
    from sbr_tpu.scenario.engine import SCENARIO_KEYS, batch_fn, _validate_params

    if spec.banks < 2:
        raise ValueError("solve_multibank requires spec.banks >= 2")
    if config is None:
        config = SolverConfig(refine_crossings=False)
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))

    # Normalize to exactly one params struct per bank BEFORE fingerprinting:
    # a shared struct and an N-element list of identical structs describe
    # the SAME solve and must key identically in every cache.
    if isinstance(params, (list, tuple)):
        plist = list(params)
        if len(plist) != spec.banks:
            raise ValueError(f"got {len(plist)} params structs for {spec.banks} banks")
    else:
        plist = [params] * spec.banks
    for p in plist:
        _validate_params(dataclasses.replace(spec, banks=1, exposure=()), p)
    fp = spec_fingerprint(spec, tuple(plist), config, dtype.name)

    cols = _bank_columns(spec, plist, dtype)
    kappa_idx = SCENARIO_KEYS.index("kappa")
    kappa0 = cols[kappa_idx]
    layout = _exposure_layout(spec)
    # batch_fn keys on the cell-program projection, so the multibank spec
    # passes through directly — banks/exposure/contagion knobs never reach
    # the compiled cell.
    batch = batch_fn(spec, config, dtype.name)

    def dispatch(kappa_eff):
        args = list(cols)
        args[kappa_idx] = kappa_eff
        return batch(*args)

    alpha = jnp.asarray(spec.contagion_damping, dtype)
    floor = jnp.asarray(spec.kappa_floor, dtype)
    lgd = jnp.asarray(spec.lgd, dtype)

    kappa_eff = kappa0
    spill = jnp.zeros_like(kappa0)
    converged = False
    iterations = 0
    with obs.span(
        "scenario.multibank", scenario=fp[:12], banks=spec.banks,
        edges=len(spec.exposure),
    ) as sp:
        for it in range(1, spec.contagion_max_iter + 1):
            iterations = it
            xi, tau_in, aw_max, status, health = dispatch(kappa_eff)
            if layout is None:
                # No exposure: zero spillover by construction — the first
                # round IS the fixed point, κ_eff stays the κ column object
                # and the results are bit-identical to independent solves.
                converged = True
                break
            src_sorted, w_sorted, row_ptr = layout
            loss = jnp.where(status == jnp.int32(Status.RUN), aw_max, 0.0)
            vals = jnp.asarray(w_sorted, dtype) * loss[jnp.asarray(src_sorted)]
            spill = _seg_weighted(vals, jnp.asarray(row_ptr))
            target = jnp.clip(kappa0 - lgd * spill, floor, kappa0)
            new = (1.0 - alpha) * kappa_eff + alpha * target
            delta = float(jnp.max(jnp.abs(new - kappa_eff)))
            # <= so an EXACTLY stable vector converges at contagion_tol=0
            # (an all-no-run network has delta == 0.0 after round 1)
            if delta <= spec.contagion_tol:
                # κ stable: the results just computed ARE the fixed point's
                # (kappa_eff untouched, so it matches what was solved).
                converged = True
                break
            if it == spec.contagion_max_iter:
                # Budget exhausted: do NOT take the final update — the
                # reported kappa_eff must be the vector the reported
                # xi/status/aw_max were actually solved under (re-solving
                # at result.kappa_eff reproduces the result even when
                # converged=False).
                break
            kappa_eff = new
        sp.sync(status)

    res = MultiBankResult(
        spec=spec, fingerprint=fp, xi=xi, tau_bar_in=tau_in, aw_max=aw_max,
        status=status, kappa_eff=kappa_eff, spillover=spill,
        iterations=iterations, converged=converged, health=health,
    )
    # Per-bank health census with scenario + bank tags (report health
    # groups per scenario/bank instead of folding banks into one census).
    if obs.enabled():
        for i in range(spec.banks):
            h_i = jax.tree_util.tree_map(lambda leaf: leaf[i], health)
            obs.log_health(
                "scenario.multibank", h_i, status[i], scenario=fp[:12], bank=i
            )
    obs.log_status("scenario.multibank", status)
    return res
