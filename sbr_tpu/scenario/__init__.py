"""Composable scenario engine (ISSUE 14): `ScenarioSpec` describes a
staged solve pipeline — learning-stage transformer × ordered hazard/buffer
modifiers × N-bank contagion coupling — and `solve(spec, params)` runs it
through the stage algebra the four legacy stacks now share. Reducible
specs are bit-identical to the direct stack calls; genuine compositions
(hetero × interest × social, policy-modifier sweeps, interbank contagion)
are cheap data, not forked solver stacks. See README "Composable
scenarios" for the composition matrix and policy-knob table.
"""

from sbr_tpu.scenario.engine import (
    SCENARIO_KEYS,
    ScenarioResult,
    run_tiled_scenario_grid,
    scenario_grid,
    scenario_theta,
    solve,
    solve_scenario_cell,
)
from sbr_tpu.scenario.multibank import MultiBankResult, solve_multibank
from sbr_tpu.scenario.spec import (
    HAZARD_MODIFIERS,
    LEARNING_STAGES,
    SCENARIO_PROGRAM_VERSION,
    ScenarioSpec,
    spec_fingerprint,
)

__all__ = [
    "HAZARD_MODIFIERS",
    "LEARNING_STAGES",
    "SCENARIO_KEYS",
    "SCENARIO_PROGRAM_VERSION",
    "MultiBankResult",
    "ScenarioResult",
    "ScenarioSpec",
    "run_tiled_scenario_grid",
    "scenario_grid",
    "scenario_theta",
    "solve",
    "solve_multibank",
    "solve_scenario_cell",
    "spec_fingerprint",
]
