"""Information-model parity smoke — the CI ``infomodel-parity`` gate.

    python -m sbr_tpu.infomodels.parity [--n 600] [--obs-dir DIR]

Three checks:

1. **Gossip bitwise reduction**: the group-free static gossip
   `InfoModelSpec` must reproduce the legacy `simulate_agents` trajectory
   BYTE FOR BYTE across {gather, incremental} × {f32, f64} ×
   {lax, interpret} fused modes — `simulate_info` delegates to the same
   engines, so any divergence means the info layer perturbed the step.
2. **Bayes close-the-loop**: a tiny Bayesian-observer population must
   close against its mean-field fixed point (converged, bank run, errors
   inside the smoke tolerance) — the tier-1 contract at smoke scale.
3. **Population determinism**: the same population query twice must
   produce identical records and identical fingerprints (the cache key
   the serving layer relies on).

With ``--obs-dir`` the battery runs inside an obs run whose directory is
printed (CI feeds it to ``report infomodel`` as the exit-code gate).
Exit 0 on success; an AssertionError (exit 1) names the first divergence.
"""

from __future__ import annotations

import argparse
import sys


def run_checks(n: int = 600) -> int:
    """Run all three checks; raises AssertionError naming the first
    divergence, returns 0 on success (the audit legacy-CLI contract)."""
    import jax

    jax.config.update("jax_enable_x64", True)
    import numpy as np

    from sbr_tpu.infomodels import (
        InfoModelSpec,
        population_fingerprint,
        population_query,
    )
    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.social.agents import AgentSimConfig, simulate_agents
    from sbr_tpu.social.closure import close_loop
    from sbr_tpu.social.graphgen import ErdosRenyiSpec, prepare_generated_graph

    graph = ErdosRenyiSpec(n=n, avg_degree=8.0)
    spec = InfoModelSpec()  # gossip, static, homogeneous — the reduction
    from sbr_tpu.infomodels import simulate_info

    for engine in ("gather", "incremental"):
        for dtype in (np.float32, np.float64):
            for fused in ("lax", "interpret"):
                cfg = AgentSimConfig(n_steps=25, dt=0.1, fused=fused)
                r_info = simulate_info(
                    spec, graph, beta=1.2, x0=0.02, config=cfg, seed=7,
                    dtype=dtype, engine=engine,
                )
                pg = prepare_generated_graph(
                    graph, seed=7, betas=1.2, config=cfg, dtype=dtype,
                    engine=engine,
                )
                r_leg = simulate_agents(
                    prepared=pg, x0=0.02, config=cfg, seed=7
                )
                label = f"{engine}/{np.dtype(dtype).name}/{fused}"
                for f in ("informed", "t_inf", "informed_frac", "withdrawn_frac"):
                    a = np.asarray(getattr(r_info, f))
                    b = np.asarray(getattr(r_leg, f))
                    assert np.array_equal(a, b), (
                        f"gossip reduction diverged at {label}.{f}"
                    )
    print("gossip reduction ok: bitwise across "
          "{gather,incremental} x {f32,f64} x {lax,interpret}")

    # Bayes close-the-loop at smoke scale.
    model = make_model_params(
        beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25, lam=0.25
    )
    bayes = InfoModelSpec(channel="bayes")
    tol = 0.25
    comp = close_loop(
        model=model, infomodel=bayes, n_agents=4000, avg_degree=15.0,
        dt=0.05, g0=0.2, t_max=8.0, n_reps=2,
        config=SolverConfig(n_grid=512), tolerance=tol,
    )
    assert bool(comp.fp.converged), "bayes fixed point did not converge"
    assert bool(comp.fp.equilibrium.bankrun), "bayes fixed point has no run"
    assert comp.err_aw_sup < tol, (
        f"bayes closure err_aw_sup {comp.err_aw_sup:.4f} over {tol}"
    )
    print(f"bayes close-the-loop ok: err_aw_sup {comp.err_aw_sup:.4f} "
          f"(< {tol}), xi {float(comp.fp.xi):.4f}")

    # Population determinism + fingerprint stability.
    pop_graph = ErdosRenyiSpec(n=1500, avg_degree=10.0)
    rec1 = population_query(
        bayes, pop_graph, model, seeds=3, vary="sim", g0=None,
        config=SolverConfig(n_grid=256),
    )
    rec2 = population_query(
        bayes, pop_graph, model, seeds=3, vary="sim", g0=None,
        config=SolverConfig(n_grid=256),
    )
    assert rec1 == rec2, "population query is not deterministic"
    kw = {"spec": bayes, "graph": pop_graph, "seeds": 3, "vary": "sim",
          "seed": 0, "dt": 0.1}
    f1 = population_fingerprint(kw, model, SolverConfig(n_grid=256), "float64")
    f2 = population_fingerprint(kw, model, SolverConfig(n_grid=256), "float64")
    assert f1 == f2, "population fingerprint unstable"
    kw2 = {**kw, "seeds": 4}
    assert population_fingerprint(
        kw2, model, SolverConfig(n_grid=256), "float64"
    ) != f1, "population fingerprint ignores the seed count"
    print(f"population determinism ok: run_p {rec1['run_probability']:.2f}, "
          f"fingerprint {f1[:12]}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m sbr_tpu.infomodels.parity")
    parser.add_argument("--n", type=int, default=600, help="agents (default 600)")
    parser.add_argument(
        "--obs-dir", default=None,
        help="run the battery inside an obs run rooted here (dir printed)",
    )
    args = parser.parse_args(argv)

    # Legacy entrypoint, audit protocol (ISSUE 17): run_legacy_cli owns the
    # obs run (same "obs run dir:" line CI scrapes for `report infomodel`),
    # records the verdict as an audit probe event alongside the infomodel
    # events the checks emit, and keeps the AssertionError→exit-1 contract.
    from sbr_tpu.obs import audit

    return audit.run_legacy_cli(
        "infomodel.gossip", lambda: run_checks(n=args.n), obs_dir=args.obs_dir
    )


if __name__ == "__main__":
    sys.exit(main())
