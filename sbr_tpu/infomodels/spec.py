"""`InfoModelSpec` — the frozen, hashable, fingerprintable description of
an agent-level INFORMATION MODEL (ISSUE 15 tentpole).

The paper's agents learn by one mechanism only — SI gossip on a static
graph. This spec is the information-model algebra mirroring
`sbr_tpu/scenario`'s stage algebra: three orthogonal axes, every
combination a servable, close-the-loop-testable model.

Observation channel (``channel``):

- ``"gossip"`` — the legacy SI rumor channel: an uninformed agent is
  infected with the exact per-step hazard 1 − exp(−β_i·frac·dt). The
  static, homogeneous gossip spec reduces **bit-identically** to the
  pre-0.10 `social.agents` step (CI-gated): `simulate_info` simply
  delegates to `simulate_agents` on the same prepared graph.
- ``"bayes"`` — Bayesian withdrawal-observers: each agent carries a
  log-odds belief Λ_i updated from the observed WITHDRAWAL state of its
  in-neighbors (not rumor). One observation window contributes the
  naive-Bayes log-likelihood-ratio rate

      llr(w) = w·log(q_run/q_calm) + (1−w)·log((1−q_run)/(1−q_calm)),

  w = withdrawn-neighbor fraction ("Efficient Bayesian Social Learning
  on Trees" gives the tractable per-node recursion; on a dense directed
  graph the neighborhood evidence collapses to exactly this naive-Bayes
  fold). An agent joins the run the first time awareness_i·Λ_i crosses
  its private threshold θ_i ~ Logistic(θ_group, threshold_scale) — the
  logistic prior noise is what makes the population curve smooth and
  gives the mean-field limit a closed form
  (`infomodels.meanfield.info_learning_curve`).

Graph dynamics (``dynamics``):

- ``"static"`` — one graph for the whole run (the legacy behavior).
- ``"rewire"`` — panic rewiring: every ``epoch_steps`` steps the edge
  set is REGENERATED born-dst-sorted via `social.graphgen` with the
  source conditional tilted toward withdrawing agents,
  p(src = j) ∝ w_j·(1 + rewire_bias·withdrawn_j) — attention
  concentrates on the agents actually running. The destination marginal
  is untouched, so the regeneration reuses the destination-marginal ×
  source-conditional factoring and never pays a device sort
  (`graphgen.tilt_threshold_table` / `generate_tilted_sources`).

Per-agent heterogeneity (``groups``): K groups of (weight, threshold,
awareness) — the hetero stack's K-group structure
(`models.params.LearningParamsHetero` shape: a small K of types with a
probability vector) lifted to agent space. Each agent draws its group
from the weight vector via the counter RNG (deterministic in seed,
sharding-invariant), then its private threshold around the group mean.
In the gossip channel ``awareness`` acts RELATIVELY — β_i scales by
a_k/⟨a⟩, so the homogeneous scalar cancels (it is a bayes evidence-rate
knob whose default is calibrated for the observer cascade and must not
leak a hidden β multiplier into gossip runs); in the bayes channel it
scales the evidence rate directly. ``()`` means homogeneous (the scalar
``threshold``/``awareness`` apply to everyone).

A spec plus (`ModelParams`, graph spec) fully determines a run;
`infomodel_fingerprint` hashes the triple through the same
`utils.checkpoint.canonicalize` machinery every cache in the repo keys
on, with `INFOMODEL_PROGRAM_VERSION` baked in so stale engine math can
never be replayed from a cache.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Optional, Tuple

CHANNELS = ("gossip", "bayes")
DYNAMICS = ("static", "rewire")

# Bump when the engine's NUMERICS change (the infomodel analogue of
# `scenario.SCENARIO_PROGRAM_VERSION`): part of every infomodel
# fingerprint, so population-query caches can never serve bytes from an
# older belief/rewire law.
INFOMODEL_PROGRAM_VERSION = 1


@dataclasses.dataclass(frozen=True)
class InfoModelSpec:
    """One information model (see module docstring).

    Plain-python frozen dataclass: hashable (a static jit argument — the
    mean-field and belief programs key their lru caches on it) and
    canonicalizable (sorted-field rendering, so it drops into
    `params_fingerprint` unchanged, exactly like `ScenarioSpec`)."""

    channel: str = "gossip"
    dynamics: str = "static"
    # Bayes observation channel: per-observation withdrawal probabilities
    # under the run / calm hypotheses (llr constants derive from these).
    # The defaults are calibrated so the Figure-12 economics produce a
    # Bayesian run: q_calm ≪ q_run makes withdrawal sightings strong
    # evidence, and the logistic threshold tail supplies the panic-prone
    # cohort that bootstraps the cascade (see `meanfield`).
    q_run: float = 0.35
    q_calm: float = 1e-3
    # Group-mean log-odds threshold and the logistic spread of private
    # thresholds around it (s > 0 keeps the population curve smooth and
    # the mean-field CDF closed-form).
    threshold: float = 3.0
    threshold_scale: float = 1.5
    awareness: float = 3.0
    # K-group heterogeneity: ((weight, threshold, awareness), ...);
    # weights must sum to 1. () = homogeneous.
    groups: Tuple[Tuple[float, float, float], ...] = ()
    # Panic rewiring: steps per epoch and the attention tilt.
    epoch_steps: int = 25
    rewire_bias: float = 4.0

    def __post_init__(self):
        if self.channel not in CHANNELS:
            raise ValueError(
                f"unknown channel {self.channel!r}; expected one of {CHANNELS}"
            )
        if self.dynamics not in DYNAMICS:
            raise ValueError(
                f"unknown dynamics {self.dynamics!r}; expected one of {DYNAMICS}"
            )
        if not (0.0 < self.q_calm < self.q_run < 1.0):
            raise ValueError(
                f"need 0 < q_calm < q_run < 1, got q_calm={self.q_calm}, "
                f"q_run={self.q_run}"
            )
        if not (self.threshold_scale > 0):
            raise ValueError("threshold_scale must be positive")
        if not (self.awareness > 0):
            raise ValueError("awareness must be positive")
        groups = tuple(
            (float(w), float(t), float(a)) for w, t, a in self.groups
        )
        object.__setattr__(self, "groups", groups)
        if groups:
            if len(groups) < 2:
                raise ValueError(
                    "groups needs K >= 2 entries (use the scalar "
                    "threshold/awareness fields for a homogeneous model)"
                )
            if any(w < 0 for w, _, _ in groups):
                raise ValueError("group weights must be non-negative")
            if abs(sum(w for w, _, _ in groups) - 1.0) > 1e-10:
                raise ValueError(
                    f"group weights must sum to 1, got "
                    f"{sum(w for w, _, _ in groups)}"
                )
            if any(a <= 0 for _, _, a in groups):
                raise ValueError("group awareness values must be positive")
        if self.epoch_steps < 1:
            raise ValueError("epoch_steps must be >= 1")
        if self.rewire_bias < 0:
            raise ValueError("rewire_bias must be non-negative")

    # -- derived constants ---------------------------------------------------
    @property
    def llr(self) -> Tuple[float, float]:
        """(llr0, llr1): the log-likelihood-ratio contributions of a calm
        and a withdrawn neighbor observation (llr0 < 0 < llr1)."""
        llr1 = math.log(self.q_run / self.q_calm)
        llr0 = math.log((1.0 - self.q_run) / (1.0 - self.q_calm))
        return llr0, llr1

    def group_table(self) -> Tuple[Tuple[float, ...], ...]:
        """(weights, thresholds, awareness) — the K-group constants with
        the homogeneous case rendered as one group, so every consumer
        loops the same shape."""
        if self.groups:
            w = tuple(g[0] for g in self.groups)
            t = tuple(g[1] for g in self.groups)
            a = tuple(g[2] for g in self.groups)
            return w, t, a
        return (1.0,), (self.threshold,), (self.awareness,)

    @classmethod
    def from_hetero_params(
        cls, params, threshold: float = 3.0, threshold_scale: float = 1.5,
        **kw,
    ) -> "InfoModelSpec":
        """Lift a hetero-stack K-group structure
        (`models.params.LearningParamsHetero` betas/dist, or a
        `ModelParamsHetero` carrying one) into an infomodel: group
        weights = dist, group awareness = β_k/⟨β⟩ (relative information
        intake), thresholds shared. The satellite bridge from the
        equilibrium stack's heterogeneity to agent space."""
        lrn = getattr(params, "learning", params)
        betas, dist = tuple(lrn.betas), tuple(lrn.dist)
        mean_b = sum(b * d for b, d in zip(betas, dist))
        groups = tuple(
            (d, float(threshold), b / mean_b) for b, d in zip(betas, dist)
        )
        return cls(
            threshold=threshold, threshold_scale=threshold_scale,
            groups=groups, **kw,
        )

    # -- reductions ----------------------------------------------------------
    def reduces_to_gossip(self) -> bool:
        """True when this spec IS the legacy `social.agents` step — the
        bit-identity contract's domain: static SI gossip, homogeneous
        (group-free) population."""
        return (
            self.channel == "gossip"
            and self.dynamics == "static"
            and not self.groups
        )

    # -- wire form -----------------------------------------------------------
    def to_doc(self) -> dict:
        """JSON-ready document (the `POST /query` ``population.infomodel``
        field) — non-default fields only, like `ScenarioSpec.to_doc`."""
        doc = {}
        fields = type(self).__dataclass_fields__
        for f in fields:
            v = getattr(self, f)
            if v != fields[f].default:
                doc[f] = [list(g) for g in v] if f == "groups" else v
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "InfoModelSpec":
        """Parse the wire form; unknown keys are a loud error (a typo like
        ``"chanel"`` must not silently serve the default model)."""
        if not isinstance(doc, dict):
            raise ValueError(
                f"infomodel must be a JSON object, got {type(doc).__name__}"
            )
        unknown = set(doc) - set(cls.__dataclass_fields__)
        if unknown:
            raise ValueError(f"unknown infomodel field(s): {sorted(unknown)}")
        kw = dict(doc)
        if "groups" in kw:
            kw["groups"] = tuple(tuple(g) for g in kw["groups"])
        return cls(**kw)


def default_spec() -> InfoModelSpec:
    """The process-default information model: channel from
    ``SBR_INFOMODEL`` (``gossip``/``bayes``, default gossip), dynamics
    from ``SBR_INFOMODEL_DYNAMICS`` (``static``/``rewire``), epoch length
    from ``SBR_INFOMODEL_EPOCH_STEPS`` — the env surface bench/parity
    drivers consult when no explicit spec is given."""
    kw: dict = {}
    env = os.environ.get("SBR_INFOMODEL", "").strip().lower()
    if env:
        if env not in CHANNELS:
            raise ValueError(f"SBR_INFOMODEL must be one of {CHANNELS}, got {env!r}")
        kw["channel"] = env
    dyn = os.environ.get("SBR_INFOMODEL_DYNAMICS", "").strip().lower()
    if dyn:
        if dyn not in DYNAMICS:
            raise ValueError(
                f"SBR_INFOMODEL_DYNAMICS must be one of {DYNAMICS}, got {dyn!r}"
            )
        kw["dynamics"] = dyn
    ep = os.environ.get("SBR_INFOMODEL_EPOCH_STEPS", "").strip()
    if ep:
        kw["epoch_steps"] = int(ep)
    return InfoModelSpec(**kw)


def infomodel_fingerprint(
    spec: InfoModelSpec, params=None, config=None, dtype=None, extra=None
) -> str:
    """Stable sha256 of (spec[, params, config, dtype, extra]) — THE key
    infomodel products (population queries, mean-field curves) are cached
    and served under. Rides `utils.checkpoint.params_fingerprint`, so an
    infomodel fingerprint can never collide with a params or scenario
    fingerprint (the dataclass name enters the canonical form)."""
    from sbr_tpu.utils.checkpoint import params_fingerprint

    payload = [spec, INFOMODEL_PROGRAM_VERSION]
    if params is not None:
        payload.append(params)
    if config is not None:
        payload.append(config)
    if dtype is not None:
        import jax.numpy as jnp

        payload.append(jnp.dtype(dtype).name)
    if extra is not None:
        payload.append(extra)
    return params_fingerprint(tuple(payload))
