"""Agent-level simulation of information models (ISSUE 15 tentpole).

`simulate_info(spec, graph, ...)` runs N explicit agents under an
`InfoModelSpec` on a device-generated graph (`social.graphgen` spec):

- **gossip × static** delegates to the legacy engines wholesale
  (`prepare_generated_graph` + `simulate_agents`) — the group-free spec
  is BIT-IDENTICAL to the pre-0.10 `social.agents` trajectory by
  construction (the CI ``infomodel-parity`` gate pins it across
  {gather, incremental} × {f32, f64} × {lax, interpret} fused modes),
  and K-group specs ride the same engines with per-agent β_i drawn from
  the group table (per-agent β was always the engines' native form).
- **bayes** runs the belief kernel: one `lax.scan` whose per-step tail
  is `social.fused.belief_update` (lax / Pallas / interpret lowerings —
  the fused step path at mega-agent shapes), with the withdrawn-neighbor
  counts from the same `_seg_counts` prefix-sum reduction the gossip
  engines use. Beliefs are deterministic given the per-agent threshold
  draws, so there is no per-step RNG at all — the whole run's randomness
  is the graph + seeds + one counter-RNG block per agent at init
  (`_agent_fields`).
- **dynamics="rewire"** wraps either channel in a host-level epoch loop:
  each epoch regenerates the edge set born-dst-sorted via
  `graphgen.generate_tilted_sources` with the source conditional tilted
  toward the CURRENT withdrawing agents (`tilt_threshold_table`), then
  runs ``epoch_steps`` simulation steps carrying (informed, t_inf[,
  belief]) across the boundary. Step indices are global, so the gossip
  RNG stream continues across epochs exactly like launch chunking.

Per-agent heterogeneity (thresholds / awareness / group ids) is drawn on
device from the counter RNG keyed by SeedSequence((seed, 31)) — pure in
(seed, agent id): deterministic in-process, cross-process, and identical
under any future sharding of the belief kernel.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sbr_tpu.infomodels.spec import InfoModelSpec
from sbr_tpu.social.agents import (
    AgentSimConfig,
    PreparedAgentGraph,
    _draw_seeds,
    _seg_counts,
    _withdrawn,
    simulate_agents,
)
from sbr_tpu.social.fused import belief_update, resolve_belief_mode
from sbr_tpu.social.graphgen import (
    ErdosRenyiSpec,
    ScaleFreeSpec,
    _check_edges,
    epoch_indegrees,
    epoch_key_words,
    generate_tilted_sources,
    prepare_generated_graph,
    tilt_threshold_table,
)
from sbr_tpu.social.rng import _threefry2x32, _uniform_from_bits


@dataclasses.dataclass(frozen=True, eq=False)  # eq=False: device-array fields
class InfoSimResult:
    """Population trajectories + final per-agent state of one info-model
    run — the `AgentSimResult` shape plus the belief channel's state and
    the rewiring epoch count."""

    t_grid: object  # (n_steps,)
    informed_frac: object  # (n_steps,)
    withdrawn_frac: object  # (n_steps,)
    informed: object  # (N,) bool, final
    t_inf: object  # (N,) informed times
    belief: Optional[object] = None  # (N,) final log-odds evidence (bayes)
    epochs: int = 1  # distinct graphs the run saw (1 = static)
    agent_steps: int = 0
    belief_updates: int = 0  # N·steps through belief_update (bayes only)

    def __repr__(self) -> str:
        from sbr_tpu.models.results import _fmt

        return (
            f"InfoSimResult(N={self.informed.shape[-1]}, "
            f"steps={self.t_grid.shape[-1]}, epochs={self.epochs}, "
            f"final_G={_fmt(self.informed_frac[-1], 4)}, "
            f"final_AW={_fmt(self.withdrawn_frac[-1], 4)})"
        )


def _agent_fields(spec: InfoModelSpec, n: int, seed: int, beta: float, dtype):
    """Per-agent (betas, thresholds, awareness) device arrays from the
    K-group table — one Threefry block per agent keyed by
    SeedSequence((seed, 31)): word 0 draws the group, word 1 the private
    logistic threshold offset. Homogeneous specs skip the group draw but
    keep the threshold noise (it is what smooths the population curve
    into the mean-field CDF)."""
    weights, thresholds, awareness = spec.group_table()
    k0, k1 = np.random.SeedSequence((seed, 31)).generate_state(2, np.uint32)
    ids = jnp.arange(n, dtype=jnp.uint32)
    x0w, x1w = _threefry2x32(jnp.uint32(k0), jnp.uint32(k1), ids, jnp.zeros_like(ids))
    u_thr = _uniform_from_bits(x1w, x0w, jnp.float32)
    if len(weights) > 1:
        u_grp = _uniform_from_bits(x0w, x1w, jnp.float32)
        cum = jnp.asarray(np.cumsum(weights[:-1]), jnp.float32)
        grp = jnp.searchsorted(cum, u_grp, side="right").astype(jnp.int32)
    else:
        grp = jnp.zeros(n, jnp.int32)
    thr_g = jnp.asarray(np.asarray(thresholds), dtype)
    a_g = jnp.asarray(np.asarray(awareness), dtype)
    eps = jnp.float32(2.0**-23)
    u_c = jnp.clip(u_thr, eps, 1.0 - eps)
    noise = jnp.log(u_c / (1.0 - u_c)).astype(dtype)
    thr = thr_g[grp] + jnp.asarray(spec.threshold_scale, dtype) * noise
    aware = a_g[grp]
    # Gossip β scaling is RELATIVE: a_k/⟨a⟩ (dist-weighted mean), so the
    # homogeneous scalar awareness — a bayes evidence-rate knob whose
    # default is calibrated for the observer cascade — cancels entirely
    # and a bias-0 rewire of the default spec matches the static
    # trajectory in distribution instead of silently tripling β. Group
    # specs keep their relative intake (`from_hetero_params` emits
    # mean-1 awareness already). The bayes channel consumes ``aware``
    # raw — there the scalar IS the evidence rate.
    mean_a = float(sum(w * a for w, a in zip(weights, awareness)))
    betas = (jnp.asarray(beta, dtype) * aware / mean_a).astype(dtype)
    return betas, thr, aware


@functools.lru_cache(maxsize=None)
def _bayes_sim(config: AgentSimConfig, mode: str):
    """Event-free Bayesian observer kernel (single device): per step one
    `_seg_counts` recount over the dst-sorted edges plus one fused
    `belief_update`. The scan carry is (informed, t_inf, belief)."""
    dt = config.dt

    @jax.jit
    def run(src, row_ptr, indeg, awareness, thr, llr01, informed0, t_init,
            belief0, k0):
        from sbr_tpu.obs import prof

        prof.note_trace("infomodels.bayes_sim")
        dtype = awareness.dtype
        t_inf0 = jnp.where(informed0, t_init, jnp.inf).astype(dtype)
        safe_deg = jnp.maximum(indeg, 1.0)

        def step(carry, k):
            informed, t_inf, belief = carry
            t = k.astype(dtype) * dt
            wd = _withdrawn(informed, t_inf, t, config.exit_delay, config.reentry_delay)
            counts = _seg_counts(wd[src], row_ptr)
            informed2, t_inf2, belief2 = belief_update(
                informed, t_inf, belief, counts, awareness, safe_deg, thr,
                t, dt, llr01[0], llr01[1], mode,
            )
            obs_t = (jnp.mean(informed.astype(dtype)), jnp.mean(wd.astype(dtype)))
            return (informed2, t_inf2, belief2), obs_t

        (informed, t_inf, belief), (gs, aws) = lax.scan(
            step, (informed0, t_inf0, belief0), jnp.arange(config.n_steps) + k0
        )
        return gs, aws, informed, t_inf, belief

    return run


def _gather_prepared(n, e, betas, src, row_ptr, indeg, dtype) -> PreparedAgentGraph:
    """Package epoch arrays as a gather-engine `PreparedAgentGraph` so the
    rewired gossip epochs ride `simulate_agents` (and every fused-mode /
    resume contract it carries) unchanged."""
    return PreparedAgentGraph(
        n=n, n_gl=n, n_pad=0, n_edges=e, dtype=np.dtype(dtype), mesh=None,
        mesh_axis="agents", comm="scatter", engine="gather", budget=0,
        max_degree=64, betas=betas, src=src, row_ptr=row_ptr,
        indeg=indeg, inc=None,
    )


def _base_source_weights(graph, dtype):
    """The base SOURCE marginal the panic tilt multiplies: uniform for
    Erdős–Rényi, the Chung–Lu power-law weights for scale-free. SBM's
    source law conditions on the destination's block — not expressible
    as a marginal table — so rewiring rejects it loudly."""
    if isinstance(graph, ErdosRenyiSpec):
        return jnp.ones(graph.n, dtype)
    if isinstance(graph, ScaleFreeSpec):
        w = np.arange(1, graph.n + 1, dtype=np.float64) ** (
            -1.0 / (graph.gamma - 1.0)
        )
        return jnp.asarray(w / w.sum(), dtype)
    raise ValueError(
        f"dynamics='rewire' supports ErdosRenyiSpec/ScaleFreeSpec base "
        f"graphs (the SBM source law conditions on the destination block); "
        f"got {type(graph).__name__}"
    )


def simulate_info(
    spec: InfoModelSpec,
    graph,
    beta: float = 0.9,
    x0: float = 1e-4,
    config: AgentSimConfig = AgentSimConfig(),
    seed: int = 0,
    dtype=np.float32,
    engine: str = "auto",
    exact_seeds: bool = False,
    informed0=None,
    t_inf0=None,
    chunk_edges=None,
    prepared: Optional[PreparedAgentGraph] = None,
    belief0=None,
) -> InfoSimResult:
    """Simulate N explicit agents under information model ``spec`` on the
    device-generated graph ``graph`` (a `social.graphgen` spec).

    ``beta`` is the gossip learning rate (per-agent β_i = β·awareness_i
    under K-group heterogeneity); the bayes channel ignores it (evidence
    rates live in the spec). ``config`` carries the step grid and the
    equilibrium withdrawal window exactly as for `simulate_agents`;
    ``config.fused`` selects the fused lowering for BOTH channels (the
    belief kernel maps "unfused" to its lax form — same arithmetic).

    ``prepared`` (static dynamics only — rewiring regenerates per epoch
    by design and rejects it loudly): a `PreparedAgentGraph` to reuse
    across calls — the seeds-axis population sweep's way of not
    re-preparing the graph per member (`closure.close_loop(seeds=...)`).
    For the gossip channel it must carry the spec's per-agent β (the
    caller prepares with `_agent_fields` betas; `close_loop` does).

    Returns an `InfoSimResult`; for the gossip-reducible spec the
    (t_grid, fractions, informed, t_inf) fields are bit-identical to
    `simulate_agents` on the same prepared graph (tested)."""
    n = graph.n
    dtype = np.dtype(dtype)
    weights, thresholds, awareness = spec.group_table()
    hetero = len(weights) > 1
    if prepared is not None and spec.dynamics == "rewire":
        raise ValueError(
            "prepared= conflicts with dynamics='rewire': rewiring "
            "regenerates the edge set per epoch — there is no reusable "
            "graph object"
        )
    if belief0 is not None and spec.channel != "bayes":
        raise ValueError("belief0= only applies to channel='bayes'")

    from sbr_tpu import obs

    if spec.channel == "gossip" and spec.dynamics == "static":
        if prepared is not None:
            pg = prepared
        else:
            if hetero:
                betas_d, _, _ = _agent_fields(spec, n, seed, beta, dtype)
                betas_arg = np.asarray(betas_d)
            else:
                betas_arg = beta
            pg = prepare_generated_graph(
                graph, seed=seed, betas=betas_arg, config=config, dtype=dtype,
                engine=engine, chunk_edges=chunk_edges,
            )
        r = simulate_agents(
            prepared=pg, x0=x0, config=config, seed=seed,
            exact_seeds=exact_seeds, informed0=informed0, t_inf0=t_inf0,
        )
        return InfoSimResult(
            t_grid=r.t_grid, informed_frac=r.informed_frac,
            withdrawn_frac=r.withdrawn_frac, informed=r.informed,
            t_inf=r.t_inf, belief=None, epochs=1,
            agent_steps=r.agent_steps,
        )

    betas_d, thr_d, aware_d = _agent_fields(spec, n, seed, beta, dtype)
    mode = resolve_belief_mode(config.fused, dtype)
    llr01 = jnp.asarray(spec.llr, dtype)

    if informed0 is None:
        informed0 = _draw_seeds(np.random.default_rng(seed), n, x0, exact_seeds)
    informed_c = jnp.asarray(np.asarray(informed0, dtype=bool))
    if t_inf0 is None:
        t_init_c = jnp.zeros(n, dtype)
    else:
        t_init_c = jnp.asarray(np.asarray(t_inf0, dtype=dtype))

    if spec.dynamics == "static":
        pg = prepared
        if pg is None:
            pg = prepare_generated_graph(
                graph, seed=seed, betas=1.0, config=config, dtype=dtype,
                engine="gather", chunk_edges=chunk_edges,
            )
        belief_init = (
            jnp.asarray(np.broadcast_to(np.asarray(belief0, dtype), (n,)))
            if belief0 is not None
            else jnp.zeros(n, dtype)
        )
        run = _bayes_sim(_normalize(config), mode)
        gs, aws, informed, t_inf, belief = run(
            pg.src, pg.row_ptr, pg.indeg, aware_d, thr_d, llr01,
            informed_c, t_init_c, belief_init, jnp.int32(0),
        )
        t_grid = jnp.arange(config.n_steps).astype(gs.dtype) * config.dt
        if obs.enabled():
            obs.log_infomodel(
                "belief_census", channel="bayes", dynamics="static",
                crossed=int(jnp.sum(informed)) - int(jnp.sum(informed_c)),
                mean_belief=float(jnp.mean(belief)),
                max_belief=float(jnp.max(belief)),
            )
        return InfoSimResult(
            t_grid=t_grid, informed_frac=gs, withdrawn_frac=aws,
            informed=informed, t_inf=t_inf, belief=belief, epochs=1,
            agent_steps=n * config.n_steps,
            belief_updates=n * config.n_steps,
        )

    # -- panic rewiring: host epoch loop over regenerated graphs ------------
    e = _check_edges(graph.edge_count(seed))
    base_w = _base_source_weights(graph, jnp.float32)
    n_steps = config.n_steps
    epoch_steps = spec.epoch_steps
    belief_c = (
        jnp.asarray(np.broadcast_to(np.asarray(belief0, dtype), (n,)))
        if belief0 is not None
        else jnp.zeros(n, dtype)
    )
    t_inf_c = jnp.where(informed_c, t_init_c, jnp.inf).astype(dtype)
    gs_parts, aws_parts = [], []
    done = 0
    n_epochs = 0
    bayes_run = _bayes_sim if spec.channel == "bayes" else None
    while done < n_steps:
        this_len = min(epoch_steps, n_steps - done)
        t_now = done * config.dt
        wd_now = _withdrawn(
            informed_c, t_inf_c, jnp.asarray(t_now, dtype),
            config.exit_delay, config.reentry_delay,
        )
        thr_table = tilt_threshold_table(base_w, wd_now, spec.rewire_bias)
        src_ep = generate_tilted_sources(
            n, e, epoch_key_words(seed, n_epochs), thr_table, chunk_edges
        )
        indeg_h = epoch_indegrees(graph, seed, n_epochs, e)
        row_ptr = jnp.asarray(
            np.concatenate([[0], np.cumsum(indeg_h)]).astype(np.int32)
        )
        indeg_d = jnp.asarray(indeg_h.astype(dtype))
        cfg_ep = dataclasses.replace(
            config, n_steps=this_len, max_steps_per_launch=None
        )
        if spec.channel == "gossip":
            pg = _gather_prepared(n, e, betas_d, src_ep, row_ptr, indeg_d, dtype)
            part = simulate_agents(
                prepared=pg, config=cfg_ep, seed=seed, informed0=informed_c,
                t_inf0=jnp.where(jnp.isfinite(t_inf_c), t_inf_c, 0.0).astype(dtype),
                step_offset=done,
            )
            # carry: simulate_agents returns t_inf with inf for never-informed
            informed_c, t_inf_c = part.informed, part.t_inf
            gs_parts.append(part.informed_frac)
            aws_parts.append(part.withdrawn_frac)
        else:
            run = bayes_run(_normalize(cfg_ep), mode)
            gs, aws, informed_c, t_inf_full, belief_c = run(
                src_ep, row_ptr, indeg_d, aware_d, thr_d, llr01,
                informed_c, jnp.where(jnp.isfinite(t_inf_c), t_inf_c, 0.0).astype(dtype),
                belief_c, jnp.int32(done),
            )
            t_inf_c = t_inf_full
            gs_parts.append(gs)
            aws_parts.append(aws)
        if obs.enabled():
            obs.log_infomodel(
                "rewire_epoch", epoch=n_epochs, channel=spec.channel,
                steps=this_len, edges=e,
                withdrawing=int(jnp.sum(wd_now)),
            )
        done += this_len
        n_epochs += 1
        # scalar fence per epoch boundary (the launch-chunking discipline)
        float(gs_parts[-1][-1])
    t_grid = jnp.arange(n_steps).astype(gs_parts[0].dtype) * config.dt
    if spec.channel == "bayes" and obs.enabled():
        obs.log_infomodel(
            "belief_census", channel="bayes", dynamics="rewire",
            crossed=int(jnp.sum(informed_c)),
            mean_belief=float(jnp.mean(belief_c)),
            max_belief=float(jnp.max(belief_c)),
        )
    return InfoSimResult(
        t_grid=t_grid,
        informed_frac=jnp.concatenate(gs_parts),
        withdrawn_frac=jnp.concatenate(aws_parts),
        informed=informed_c,
        t_inf=t_inf_c,
        belief=belief_c if spec.channel == "bayes" else None,
        epochs=n_epochs,
        agent_steps=n * n_steps,
        belief_updates=n * n_steps if spec.channel == "bayes" else 0,
    )


def _normalize(config: AgentSimConfig) -> AgentSimConfig:
    """Drop fields the bayes kernel ignores from the lru key (a non-None
    launch cap or engine knobs must not compile duplicate programs)."""
    return dataclasses.replace(
        config, max_steps_per_launch=None, compact_impl="searchsorted",
        rng_stream="counter", fused="auto",
    )
