"""Population-level what-if queries (ISSUE 15 tentpole, serving leg).

A population query asks: *at these economics, under this information
model, on this graph family — what is the DISTRIBUTION of run outcomes
across realizations?* ("ξ distribution over S graph seeds at these
params", ROADMAP's mega-agent serving item.)

`population_query` answers it: solve the model's mean-field fixed point
once (the solver-curve anchor), then run S agent populations and reduce
each member's AW trajectory to its run-crossing time — the first t with
AW(t) ≥ κ, the agent-level face of the equilibrium condition AW(ξ) = κ.
The answer is a JSON-ready record: crossing-time quantiles (p10/p50/p90),
the run probability (share of members whose population actually crossed),
the mean-field ξ for reference, and per-member crossing times — cached by
`infomodel_fingerprint` like any tile (the serve engine's LRU + verified
disk layers, `Engine.query_population`).

Two seed-variation modes:

- ``vary="sim"`` (default): one graph (generated at the base seed,
  PREPARED ONCE via the `close_loop` seeds axis), S simulation seeds —
  the distribution of outcomes on a FIXED network.
- ``vary="graph"``: S (graph, sim) seed pairs — each member regenerates
  its graph on device (`prepare_generated_graph`; cheap at query shapes,
  and the fixed point is shared across members via ``fp=``) — the
  distribution over the graph family itself.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from sbr_tpu.infomodels.spec import InfoModelSpec, infomodel_fingerprint
from sbr_tpu.models.params import ModelParams, SolverConfig

POP_VARY = ("sim", "graph")

# Serving guardrail: a wire-supplied seed count multiplies whole agent
# simulations — cap it so one query cannot monopolize a worker.
MAX_POP_SEEDS = 256


def crossing_times(aw_rows: np.ndarray, t: np.ndarray, kappa: float) -> np.ndarray:
    """Per-member run-crossing time: the first grid time with
    AW(t) ≥ κ, linearly interpolated inside the crossing step; NaN for
    members that never cross (no run in that realization)."""
    aw_rows = np.asarray(aw_rows, np.float64)
    t = np.asarray(t, np.float64)
    out = np.full(aw_rows.shape[0], np.nan)
    for i, aw in enumerate(aw_rows):
        idx = np.nonzero(aw >= kappa)[0]
        if idx.size == 0:
            continue
        j = int(idx[0])
        if j == 0 or aw[j] == aw[j - 1]:
            out[i] = t[j]
        else:
            frac = (kappa - aw[j - 1]) / (aw[j] - aw[j - 1])
            out[i] = t[j - 1] + frac * (t[j] - t[j - 1])
    return out


def population_query(
    spec: InfoModelSpec,
    graph,
    model: ModelParams,
    seeds: int = 16,
    vary: str = "sim",
    seed: int = 0,
    dt: float = 0.1,
    g0: Optional[float] = 0.02,
    config: Optional[SolverConfig] = None,
    fp=None,
) -> dict:
    """Run one population-level what-if query (module docstring) and
    return the JSON-ready record. ``graph`` is a `social.graphgen` spec;
    ``seeds`` the number of members S (capped at `MAX_POP_SEEDS`)."""
    from sbr_tpu import obs
    from sbr_tpu.social.closure import close_loop

    if vary not in POP_VARY:
        raise ValueError(f"vary must be one of {POP_VARY}, got {vary!r}")
    seeds = int(seeds)
    if not (1 <= seeds <= MAX_POP_SEEDS):
        raise ValueError(f"seeds must be in [1, {MAX_POP_SEEDS}], got {seeds}")
    if spec.channel == "bayes" and g0 is not None:
        # The bayes model bootstraps through its own panic-prone threshold
        # tail; a mid-start adds nothing and the threshold-prefix seeding
        # is a per-member host pass — run populations from scratch.
        g0 = None

    kappa = float(model.economic.kappa)
    member_seeds = [seed + 1000 * s for s in range(seeds)]
    if vary == "sim":
        comp = close_loop(
            model=model, n_agents=graph.n, dt=dt, g0=g0, seed=seed,
            config=config, graph=graph, infomodel=spec, seeds=member_seeds,
            fp=fp,
        )
        t = comp.t
        aw_rows = comp.aw_seeds
        fp = comp.fp
        # the S-member MEAN trajectory vs the mean-field curve — the
        # natural population-level comparison on a fixed graph
        err_aw_sup = comp.err_aw_sup
    else:
        rows = []
        t = None
        err_aw_sup = 0.0
        for ms in member_seeds:
            comp = close_loop(
                model=model, n_agents=graph.n, dt=dt, g0=g0, seed=ms,
                config=config, graph=graph, infomodel=spec,
                seeds=[ms], fp=fp,
            )
            fp = comp.fp  # solve once, share across members
            rows.append(comp.aw_seeds[0])
            t = comp.t
            # WORST member vs the curve, not the last one's — each member
            # is a distinct graph realization, and a record that quoted
            # only the final member would read healthy when 15 of 16
            # realizations diverged
            err_aw_sup = max(err_aw_sup, comp.err_aw_sup)
        aw_rows = np.stack(rows)

    times = crossing_times(aw_rows, t, kappa)
    crossed = np.isfinite(times)
    run_p = float(np.mean(crossed))
    finite = times[crossed]

    def q(p: float) -> Optional[float]:
        if finite.size == 0:
            return None
        return float(np.quantile(finite, p))

    xi_mf = float(fp.xi)
    rec = {
        "kind": "population",
        "channel": spec.channel,
        "dynamics": spec.dynamics,
        "vary": vary,
        "seeds": seeds,
        "n_agents": int(graph.n),
        "kappa": kappa,
        "run_probability": run_p,
        "crossing_quantiles": {"p10": q(0.10), "p50": q(0.50), "p90": q(0.90)},
        "crossing_times": [
            None if not math.isfinite(v) else round(float(v), 6) for v in times
        ],
        "xi_meanfield": xi_mf if math.isfinite(xi_mf) else None,
        "fp_converged": bool(fp.converged),
        # vary="sim": the member-mean trajectory's error; vary="graph":
        # the WORST member's (per-realization comparisons, max-reduced)
        "err_aw_sup": round(err_aw_sup, 6),
    }
    if obs.enabled():
        obs.log_infomodel(
            "population_query", channel=spec.channel, dynamics=spec.dynamics,
            vary=vary, seeds=seeds, n_agents=int(graph.n),
            run_probability=run_p,
        )
    return rec


# -- wire form ---------------------------------------------------------------

GRAPH_MODELS = ("erdos_renyi", "scale_free", "stochastic_block")


def graph_spec_from_doc(doc: dict):
    """Parse the ``population.graph`` wire object into a graphgen spec.
    Unknown models/fields are loud errors, mirroring `ScenarioSpec`'s
    wire discipline."""
    from sbr_tpu.social import graphgen

    if not isinstance(doc, dict):
        raise ValueError(f"graph must be a JSON object, got {type(doc).__name__}")
    kw = dict(doc)
    m = kw.pop("model", "erdos_renyi")
    makers = {
        "erdos_renyi": graphgen.ErdosRenyiSpec,
        "scale_free": graphgen.ScaleFreeSpec,
        "stochastic_block": graphgen.StochasticBlockSpec,
    }
    if m not in makers:
        raise ValueError(f"unknown graph model {m!r}; expected one of {GRAPH_MODELS}")
    try:
        return makers[m](**kw)
    except TypeError as err:
        raise ValueError(f"bad graph spec: {err}") from err


def parse_population_doc(doc: dict) -> dict:
    """Parse + validate the `POST /query` ``population`` object into the
    `population_query` keyword set (specs instantiated, bounds checked).
    Raises ValueError on any malformed field — the endpoint maps it to a
    400."""
    if not isinstance(doc, dict):
        raise ValueError(
            f"population must be a JSON object, got {type(doc).__name__}"
        )
    known = {"graph", "infomodel", "seeds", "vary", "seed", "dt", "g0"}
    unknown = set(doc) - known
    if unknown:
        raise ValueError(f"unknown population field(s): {sorted(unknown)}")
    if "graph" not in doc:
        raise ValueError("population requires a 'graph' object")
    graph = graph_spec_from_doc(doc["graph"])
    spec = InfoModelSpec.from_doc(doc.get("infomodel") or {})
    kw = {
        "graph": graph,
        "spec": spec,
        "seeds": int(doc.get("seeds", 16)),
        "vary": str(doc.get("vary", "sim")),
        "seed": int(doc.get("seed", 0)),
        "dt": float(doc.get("dt", 0.1)),
    }
    if "g0" in doc:
        kw["g0"] = None if doc["g0"] is None else float(doc["g0"])
    if kw["vary"] not in POP_VARY:
        raise ValueError(f"vary must be one of {POP_VARY}, got {kw['vary']!r}")
    if not (1 <= kw["seeds"] <= MAX_POP_SEEDS):
        raise ValueError(f"seeds must be in [1, {MAX_POP_SEEDS}]")
    if not (kw["dt"] > 0):
        raise ValueError("dt must be positive")
    return kw


def population_fingerprint(kw: dict, params, config, dtype_name: str) -> str:
    """THE cache key of one population query: the parsed spec pair plus
    every value that shapes the answer, through `infomodel_fingerprint`
    (which bakes in INFOMODEL_PROGRAM_VERSION)."""
    extra = (
        kw["graph"], kw["seeds"], kw["vary"], kw["seed"], kw["dt"],
        kw.get("g0", 0.02),
    )
    return infomodel_fingerprint(
        kw["spec"], params=params, config=config, dtype=dtype_name, extra=extra
    )
