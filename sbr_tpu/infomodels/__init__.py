"""Information-model engine (ISSUE 15): Bayesian withdrawal-observers,
panic rewiring, per-agent heterogeneity — an information-model algebra
mirroring `sbr_tpu.scenario`'s stage algebra, with every model honoring
the close-the-loop contract and servable as a population-level what-if
product. See `infomodels.spec` for the axes."""

from sbr_tpu.infomodels.engine import InfoSimResult, simulate_info
from sbr_tpu.infomodels.meanfield import (
    info_learning_curve,
    observed_fraction,
    solve_fixed_point_info,
)
from sbr_tpu.infomodels.population import (
    MAX_POP_SEEDS,
    crossing_times,
    parse_population_doc,
    population_fingerprint,
    population_query,
)
from sbr_tpu.infomodels.spec import (
    CHANNELS,
    DYNAMICS,
    INFOMODEL_PROGRAM_VERSION,
    InfoModelSpec,
    default_spec,
    infomodel_fingerprint,
)

__all__ = [
    "CHANNELS",
    "DYNAMICS",
    "INFOMODEL_PROGRAM_VERSION",
    "InfoModelSpec",
    "InfoSimResult",
    "MAX_POP_SEEDS",
    "crossing_times",
    "default_spec",
    "infomodel_fingerprint",
    "info_learning_curve",
    "observed_fraction",
    "parse_population_doc",
    "population_fingerprint",
    "population_query",
    "simulate_info",
    "solve_fixed_point_info",
]
