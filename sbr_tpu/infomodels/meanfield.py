"""Mean-field fixed points for information models (ISSUE 15).

The tier-1 contract: EVERY information model closes the loop against a
solver curve the way the gossip channel always has — the agent
simulation's (G, AW) trajectories converge (in N, dense-graph limit) to
the curves of a mean-field fixed point. The gossip channel's fixed point
IS `social.solver.solve_equilibrium_social` (exact reduction, reused
verbatim); the other cells of the (channel × dynamics × heterogeneity)
matrix get their fixed point here, built from the same damped outer
iteration with the Stage-1 learning law generalized:

- **gossip × K-groups**: the forced ODE dG_k/dt = (1−G_k)·β·a_k·AW(t)
  is separable per group exactly like the homogeneous law, so
  G(t) = Σ_k w_k·[1 − (1−x0)·exp(−β·a_k·A(t))] with A = ∫AW.
- **bayes**: the evidence integral Λ(t) = ∫ llr(w_obs(s)) ds is shared
  by every agent in the dense limit (the observed withdrawn-neighbor
  fraction concentrates on the population value); an agent crosses when
  awareness·Λ first exceeds its private threshold, so with
  θ ~ Logistic(θ_k, s) the informed share is the closed form
  G(t) = x0 + (1−x0)·Σ_k w_k·σ((a_k·M(t) − θ_k)/s), M = running max Λ
  (first crossing ⟺ running-max threshold — crossing is absorbing).
- **rewire dynamics**: per-epoch regeneration with source tilt
  p(src=j) ∝ (1 + b·wd_j) biases the OBSERVED withdrawal fraction to
  w_obs = AW·(1+b)/(1 + b·AW) > AW — the mean-field face of "attention
  concentrates on withdrawing neighbors". Static specs have
  w_obs = AW. The tilt is exact in the dense limit for per-epoch
  redraws; within an epoch it is frozen at the epoch-start mask, so
  this curve is the EPOCH→0 limit and closure error scales with
  epoch_steps·dt relative to the run window (measured: gossip rewire
  sup 0.15→0.34 from epoch 0.2→1.0 time units at window ~1.5; the
  bayes window is short — ξ≈0.4 at the defaults — so bayes rewire
  needs epoch_steps·dt ≲ 0.05, where it closes to err_g_rms ~0.006;
  a stale-epoch bayes cascade can genuinely die where this curve
  runs).

Everything downstream of Stage 1 — the inner baseline equilibrium, the
no-run ξ-march, `get_aw`, damping, history ring, health — is the social
solver's structure unchanged, so the returned `SocialFixedPointResult`
drops into `closure.close_loop`'s comparison machinery as-is.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from sbr_tpu.baseline.solver import get_aw, solve_equilibrium_core
from sbr_tpu.core.integrate import cumtrapz
from sbr_tpu.infomodels.spec import InfoModelSpec
from sbr_tpu.models.params import ModelParams, SolverConfig
from sbr_tpu.models.results import LearningSolution
from sbr_tpu.social.solver import (
    HISTORY_LEN,
    SocialFixedPointResult,
    _log_fixed_point,
    _LoopState,
)


def observed_fraction(aw, spec: InfoModelSpec):
    """The withdrawn fraction an agent OBSERVES among its in-neighbors,
    given the population fraction ``aw`` — identity for static graphs,
    the attention tilt AW·(1+b)/(1+b·AW) under panic rewiring."""
    if spec.dynamics != "rewire" or spec.rewire_bias == 0.0:
        return aw
    b = spec.rewire_bias
    return aw * (1.0 + b) / (1.0 + b * aw)


def info_learning_curve(
    spec: InfoModelSpec, beta, aw_samples, grid, x0
) -> LearningSolution:
    """Stage 1 of the info fixed point: the population learning curve
    (CDF/PDF on ``grid``) induced by forcing ``aw_samples`` under
    ``spec``'s channel/heterogeneity/dynamics (module docstring for the
    laws). The gossip × homogeneous × static case is algebraically
    `social.dynamics.solve_forced_learning` (same cumtrapz + exp)."""
    dtype = jnp.asarray(aw_samples).dtype
    beta = jnp.asarray(beta, dtype)
    x0 = jnp.asarray(x0, dtype)
    dt = grid[1] - grid[0]
    weights, thresholds, awareness = spec.group_table()
    w_obs = observed_fraction(aw_samples, spec)
    if spec.channel == "gossip":
        big_a = cumtrapz(w_obs, dx=dt)
        cdf = jnp.zeros_like(w_obs)
        pdf = jnp.zeros_like(w_obs)
        # relative intake a_k/⟨a⟩ — the scalar awareness cancels in the
        # gossip channel (see `engine._agent_fields`), so the homogeneous
        # law is EXACTLY the legacy forced ODE at β regardless of the
        # bayes-calibrated awareness default
        mean_a = sum(w * a for w, a in zip(weights, awareness))
        for wk, _, ak in zip(weights, thresholds, awareness):
            g_k = 1.0 - (1.0 - x0) * jnp.exp(-beta * (ak / mean_a) * big_a)
            cdf = cdf + wk * g_k
            pdf = pdf + wk * (1.0 - g_k) * beta * (ak / mean_a) * w_obs
        eff_beta = beta
    else:
        llr0, llr1 = spec.llr
        llr = w_obs * llr1 + (1.0 - w_obs) * llr0
        lam = cumtrapz(llr, dx=dt)
        m = lax.cummax(lam)
        # dM/dt: positive llr while the integral sits at its running max
        mdot = jnp.where(lam >= m, jnp.maximum(llr, 0.0), 0.0)
        s = spec.threshold_scale
        cdf = jnp.zeros_like(w_obs)
        pdf = jnp.zeros_like(w_obs)
        for wk, tk, ak in zip(weights, thresholds, awareness):
            z = (ak * m - tk) / s
            sig = jax.nn.sigmoid(z)
            cdf = cdf + wk * sig
            pdf = pdf + wk * sig * (1.0 - sig) * (ak / s) * mdot
        cdf = x0 + (1.0 - x0) * cdf
        pdf = (1.0 - x0) * pdf
        eff_beta = jnp.asarray(spec.awareness, dtype)
    return LearningSolution(
        grid=grid, cdf=cdf, pdf=pdf, t0=grid[0], dt=dt, beta=eff_beta,
        x0=x0, closed_form=False,
    )


@functools.lru_cache(maxsize=None)
def _build_info_fixed_point(
    spec: InfoModelSpec, config: SolverConfig, tol: float, max_iter: int,
    damping: float,
):
    """Jitted info fixed point, cached per (spec, numerics config) — the
    social solver's damped while_loop with Stage 1 swapped for
    `info_learning_curve` (plain damping; the Anderson acceleration stays
    a legacy-stack specialization, same policy as the composed-scenario
    fixed point)."""

    @jax.jit
    def run(beta, x0, u, p, kappa, lam_, eta, grid):
        from sbr_tpu.obs import prof

        prof.note_trace("infomodels.fixed_point")
        dtype = grid.dtype
        tol_ = jnp.asarray(tol, dtype=dtype)
        alpha = jnp.asarray(damping, dtype=dtype)

        def step(aw, xi_prev):
            ls = info_learning_curve(spec, beta, aw, grid, x0)
            res = solve_equilibrium_core(ls, u, p, kappa, lam_, eta, eta, config)
            xi_new = jnp.where(res.bankrun, res.xi, xi_prev + eta / 500.0)
            exceeded = jnp.logical_and(~res.bankrun, xi_new > eta)
            aw_new, _, _ = get_aw(
                xi_new, res.tau_bar_in_unc, res.tau_bar_out_unc, grid, ls
            )
            # `get_aw` adds the reference's permanent +G(0) "initial
            # withdrawals" offset — a documented O(x0) bias in the legacy
            # model (closure module docstring) that the branch terms
            # already cover WITH re-entry. For observer models the t=0
            # cohort is the panic-prone threshold tail (G(0) ~ 0.1, not
            # 1e-4): keeping the offset both double-counts the cohort
            # while its window is open and parks it in AW forever after —
            # inflating the fixed point's own forcing. Drop it: the info
            # fixed point iterates on the honestly WINDOWED aggregate,
            # which is also the quantity the agent population realizes.
            aw_new = aw_new - ls.cdf[0]
            return ls, res, xi_new, exceeded, aw_new

        def cond(s: _LoopState):
            return (s.it < max_iter) & (~s.converged) & (~s.aborted)

        def body(s: _LoopState):
            ls, res, xi_new, exceeded, aw_new = step(s.aw, s.xi)
            err = jnp.max(jnp.abs(aw_new - s.aw))
            conv = jnp.logical_and(err < tol_, ~exceeded)
            aw_next = jnp.where(conv, aw_new, (1.0 - alpha) * s.aw + alpha * aw_new)
            aw_next = jnp.where(exceeded, s.aw, aw_next)
            slot = jnp.mod(s.it, HISTORY_LEN)
            return _LoopState(
                aw=aw_next, xi=xi_new, it=s.it + 1, converged=conv,
                aborted=exceeded, err=err,
                hist_err=s.hist_err.at[slot].set(err),
                hist_xi=s.hist_xi.at[slot].set(xi_new),
                res=res, ls=ls, prev_aw=s.prev_aw, prev_r=s.prev_r,
            )

        # Channel-matched bootstrap. Gossip: the word-of-mouth logistic at
        # the awareness-weighted rate — the legacy solver's init; a
        # zero-forcing init would be the DEGENERATE no-information fixed
        # point (AW ≡ x0 reproduces itself exactly and the loop "converges"
        # at iteration 1 with no run — observed, not hypothetical). Bayes:
        # the zero-evidence curve, which already carries the panic-prone
        # instant cohort (σ(−θ_k/s) mass) — exactly the bootstrap seed the
        # observer cascade needs, where word-of-mouth has no meaning.
        if spec.channel == "gossip":
            from sbr_tpu.baseline.learning import logistic_cdf

            # awareness is relative in the gossip channel (mean 1), so
            # the word-of-mouth bootstrap runs at the bare β
            aw0 = logistic_cdf(grid, beta, x0)
        else:
            aw0 = info_learning_curve(spec, beta, jnp.zeros_like(grid), grid, x0).cdf
        shapes = jax.eval_shape(lambda a, x: step(a, x)[:2], aw0, jnp.zeros((), dtype))
        ls0, res0 = jax.tree_util.tree_map(
            lambda sh: jnp.zeros(sh.shape, sh.dtype), shapes
        )
        init = _LoopState(
            aw=aw0,
            xi=jnp.zeros((), dtype),
            it=jnp.zeros((), jnp.int32),
            converged=jnp.zeros((), bool),
            aborted=jnp.zeros((), bool),
            err=jnp.asarray(jnp.inf, dtype),
            hist_err=jnp.full((HISTORY_LEN,), jnp.nan, dtype),
            hist_xi=jnp.full((HISTORY_LEN,), jnp.nan, dtype),
            res=res0,
            ls=ls0,
            prev_aw=jnp.zeros_like(aw0),
            prev_r=jnp.zeros_like(aw0),
        )
        final = jax.lax.while_loop(cond, body, init)

        from sbr_tpu.diag.health import FP_ABORTED, FP_NOT_CONVERGED, NAN_OUTPUT, Health

        not_conv = (~final.converged) & (~final.aborted)
        fp_flags = (
            jnp.where(not_conv, jnp.int32(FP_NOT_CONVERGED), jnp.int32(0))
            | jnp.where(final.aborted, jnp.int32(FP_ABORTED), jnp.int32(0))
            | jnp.where(
                jnp.any(~jnp.isfinite(final.aw)), jnp.int32(NAN_OUTPUT), jnp.int32(0)
            )
        )
        nan = jnp.asarray(jnp.nan, dtype)
        fp_health = Health(
            residual=final.err, bracket_width=nan,
            iterations=final.it, flags=fp_flags,
        )
        return SocialFixedPointResult(
            equilibrium=final.res,
            learning=final.ls,
            aw=final.aw,
            grid=grid,
            xi=final.xi,
            iterations=final.it,
            converged=final.converged,
            aborted=final.aborted,
            error=final.err,
            history_err=final.hist_err,
            history_xi=final.hist_xi,
            health=final.res.health.merge(fp_health),
        )

    return run


def solve_fixed_point_info(
    spec: InfoModelSpec,
    model: ModelParams,
    config: SolverConfig | None = None,
    tol: float = 1e-4,
    max_iter: int = 250,
    damping: float = 0.5,
    dtype=None,
) -> SocialFixedPointResult:
    """Solve the mean-field fixed point of ``spec`` at ``model``'s
    economics — the solver curve every `close_loop(infomodel=spec)` run
    compares against. A gossip-reducible spec dispatches to the legacy
    `solve_equilibrium_social` (EXACT reduction — same program, same
    bits); everything else runs the generalized Stage-1 iteration."""
    from sbr_tpu.social.solver import solve_equilibrium_social

    if spec.reduces_to_gossip():
        return solve_equilibrium_social(
            model, config=config, tol=tol, max_iter=max_iter, damping=damping,
            dtype=dtype,
        )
    if config is None:
        config = SolverConfig()
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    import time

    from sbr_tpu import obs
    from sbr_tpu.baseline.solver import _stamp_solve_time

    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))
    econ = model.economic
    eta = econ.eta
    grid = jnp.linspace(jnp.zeros((), dtype), jnp.asarray(eta, dtype), config.n_grid)
    run = _build_info_fixed_point(
        spec, config, float(tol), int(max_iter), float(damping)
    )
    args = (
        jnp.asarray(model.learning.beta, dtype),
        jnp.asarray(model.learning.x0, dtype),
        jnp.asarray(econ.u, dtype),
        jnp.asarray(econ.p, dtype),
        jnp.asarray(econ.kappa, dtype),
        jnp.asarray(econ.lam, dtype),
        jnp.asarray(eta, dtype),
        grid,
    )
    t0 = time.perf_counter()
    with obs.span(
        "infomodels.fixed_point", channel=spec.channel, dynamics=spec.dynamics,
        n_grid=config.n_grid, max_iter=int(max_iter),
    ) as sp:
        res = obs.jit_call("infomodels.fixed_point", run, *args)
        sp.sync(res.aw, res.xi)
    res = _stamp_solve_time(res, t0)
    _log_fixed_point(res)
    obs.log_infomodel(
        "fixed_point", channel=spec.channel, dynamics=spec.dynamics,
        groups=len(spec.groups) or 1, converged=bool(res.converged),
        aborted=bool(res.aborted), iterations=int(res.iterations),
        xi=float(res.xi), bankrun=bool(res.equilibrium.bankrun),
    )
    return res
