"""Resilience layer: deterministic fault injection, unified retry/backoff,
and self-healing execution.

The robustness counterpart of the `sbr_tpu.obs` observability stack. Four
modules:

- ``faults``   — seeded, env-driven fault plans (``SBR_FAULT_PLAN``) fired
  at named fault points planted in tile execution, checkpoint IO, the
  bench probe, and the multihost barrier; every firing is an obs ``fault``
  event, and the same seed always yields the same fault sequence.
- ``retry``    — the one retry policy engine (exponential backoff with
  jitter, deterministic-vs-transient classification, shared per-scope
  budgets) behind the tile loop and the bench probe ladder; attempts land
  as obs ``retry`` events with a manifest roll-up.
- ``heal``     — sha256 integrity sidecars with verify-on-load and
  quarantine-and-recompute for corrupt tiles, plus the per-cell degrade
  ladder that re-runs health-divergent cells at float64 with tightened
  tolerances (obs ``repair`` events + checkpoint ``repairs`` block).
- ``shutdown`` — graceful SIGTERM/SIGINT: finalize obs manifests as
  ``"interrupted"``, remove partial temp files, and release held
  coordination files (tile leases, the elastic heartbeat) before exit.
- ``elastic``  — the elastic sweep scheduler (ISSUE 8): heartbeat
  membership in the checkpoint dir, deterministic throughput-weighted
  claim plans over the remaining tile queue, and the cross-run global
  tile cache (``SBR_TILE_CACHE_DIR``) behind
  `parallel.run_tiled_grid_multihost`.
- ``chaos``    — the CI chaos smoke: a seeded fault plan (transient
  errors, a corrupted tile, a preemption) must yield a final grid
  bit-identical to the fault-free run (``python -m
  sbr_tpu.resilience.chaos``).

`faults` and `retry` are stdlib-only at import time: the bench harness
parent (which must never load jax) imports them standalone by file path.

Render what happened with ``python -m sbr_tpu.obs.report resilience
RUN_DIR`` (exit 1 on unrecovered failures).
"""

from sbr_tpu.resilience import elastic, faults, heal, retry, shutdown
from sbr_tpu.resilience.elastic import TileCache
from sbr_tpu.resilience.faults import FaultPlan, InjectedFault
from sbr_tpu.resilience.retry import RetryBudget, RetryError, RetryPolicy, policy_from_env
from sbr_tpu.resilience.shutdown import graceful_shutdown

__all__ = [
    "FaultPlan",
    "InjectedFault",
    "RetryBudget",
    "RetryError",
    "RetryPolicy",
    "TileCache",
    "elastic",
    "faults",
    "graceful_shutdown",
    "heal",
    "policy_from_env",
    "retry",
    "shutdown",
]
