"""Chaos smoke: prove the resilience layer recovers a sweep bit-for-bit.

``python -m sbr_tpu.resilience.chaos [--out DIR] [--json]`` runs three
worker subprocesses over one small β×u tiled sweep:

1. **baseline** — fault-free run; its grid is the ground truth.
2. **chaos** — the same sweep under a seeded fault plan that injects a
   transient dispatch error (retried), a NaN-poisoned tile result
   (degrade-ladder repaired in-run), a corrupted checkpoint file (a torn
   write, discovered later), and a preemption (SIGTERM → graceful
   shutdown: manifest status ``"interrupted"``, exit 143).
3. **resume** — a clean rerun against the chaos checkpoint dir: the
   corrupt tile is sha256-quarantined and recomputed, cached tiles are
   served, preempted tiles are computed fresh.

The smoke PASSES only if the resumed grid is **bit-identical** to the
baseline (`max_aw`/`xi`/`status` byte equality — recovery must change
nothing) and the recovery path is visible: fault events + an interrupted
manifest in the chaos run, a quarantine repair in the resume run, and
``report resilience`` exiting 0 on the resume run. CI runs this as the
chaos-smoke job; it is equally useful locally after touching any
resilience path.

The driver itself never imports jax (workers are subprocesses), so it can
run on a box whose accelerator stack is itself the thing being debugged.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
from pathlib import Path

import numpy as np

# One plan, seeded: same sequence every run (see resilience.faults).
# Tile order for the 4×4 grid under 2×2 tiles: (0,0) (0,2) (2,0) (2,2);
# tile.compute hit order with the retried first attempt: 1=(0,0) attempt 1
# (transient), 2=(0,0) attempt 2, 3=(0,2), 4=(2,0) → preempted.
FAULT_PLAN = {
    "seed": 0,
    "rules": [
        {"point": "tile.compute", "kind": "transient", "at_hits": [1]},
        {"point": "tile.result", "kind": "nan", "match": "b00000_u00002",
         "cells": 1, "max_fires": 1},
        {"point": "checkpoint.save", "kind": "corrupt", "match": "b00000_u00000",
         "max_fires": 1},
        {"point": "tile.compute", "kind": "preempt", "at_hits": [4]},
    ],
}

_FIELDS = ("max_aw", "xi", "status")


def _worker(ckpt_dir: str, out_npz: str) -> int:
    """One tiled sweep (fixed small shape), grids saved as npz."""
    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.utils.checkpoint import run_tiled_grid

    cfg = SolverConfig(n_grid=96, bisect_iters=40)
    grid = run_tiled_grid(
        np.linspace(0.5, 2.0, 4),
        np.linspace(0.05, 0.5, 4),
        make_model_params(),
        config=cfg,
        tile_shape=(2, 2),
        checkpoint_dir=ckpt_dir,
    )
    arrays = {f: np.asarray(getattr(grid, f)) for f in _FIELDS}
    with open(out_npz, "wb") as fh:
        np.savez(fh, **arrays)
    return 0


def _run_phase(name: str, out: Path, ckpt: Path, npz, fault_plan=None, timeout_s=600.0):
    """Run one worker subprocess; returns (rc, obs_run_dir_or_None)."""
    obs_root = out / f"obs_{name}"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "SBR_OBS": "1",
        "SBR_OBS_LABEL": name,
        "SBR_OBS_DIR": str(obs_root),
        "SBR_RETRY_BASE_DELAY_S": "0.05",
    }
    env.pop("SBR_FAULT_PLAN", None)
    if fault_plan is not None:
        env["SBR_FAULT_PLAN"] = json.dumps(fault_plan)
    argv = [
        sys.executable, "-m", "sbr_tpu.resilience.chaos",
        "--worker", str(ckpt), str(npz if npz else out / f"{name}.npz"),
    ]
    proc = subprocess.run(
        argv, env=env, timeout=timeout_s, capture_output=True, text=True
    )
    if proc.stderr:
        sys.stderr.write(proc.stderr)
    runs = sorted(obs_root.iterdir()) if obs_root.is_dir() else []
    return proc.returncode, (runs[0] if runs else None)


def _manifest(run_dir) -> dict:
    try:
        return json.loads((Path(run_dir) / "manifest.json").read_text())
    except (OSError, TypeError, json.JSONDecodeError):
        return {}


def _report_resilience(run_dir) -> tuple:
    """(exit_code, json_doc) from the report CLI — the user-facing gate."""
    proc = subprocess.run(
        [sys.executable, "-m", "sbr_tpu.obs.report", "resilience", str(run_dir), "--json"],
        capture_output=True, text=True, timeout=120.0,
    )
    try:
        return proc.returncode, json.loads(proc.stdout)
    except json.JSONDecodeError:
        return proc.returncode, {}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.resilience.chaos",
        description="Seeded chaos smoke: faulted+resumed sweep must be "
        "bit-identical to the fault-free run",
    )
    parser.add_argument("--out", default="/tmp/sbr_chaos", help="scratch/artifact dir")
    parser.add_argument("--json", action="store_true", help="machine-readable verdict")
    parser.add_argument("--worker", nargs=2, metavar=("CKPT", "NPZ"), help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    if args.worker:
        return _worker(*args.worker)

    out = Path(args.out)
    if out.exists():
        shutil.rmtree(out)
    out.mkdir(parents=True)
    checks: dict = {}

    def log(msg):
        if not args.json:
            print(msg)

    log("phase 1/3: fault-free baseline …")
    rc, _ = _run_phase("baseline", out, out / "ckpt_baseline", out / "baseline.npz")
    checks["baseline_rc0"] = rc == 0

    log("phase 2/3: chaos (transient + nan poison + corrupt tile + preemption) …")
    rc, chaos_run = _run_phase(
        "chaos", out, out / "ckpt_chaos", out / "chaos.npz", fault_plan=FAULT_PLAN
    )
    checks["chaos_preempted_143"] = rc == 143
    checks["chaos_manifest_interrupted"] = _manifest(chaos_run).get("status") == "interrupted"
    chaos_res = (_manifest(chaos_run).get("resilience") or {})
    faults_seen = chaos_res.get("faults") or {}
    checks["chaos_faults_visible"] = {
        "tile.compute:transient", "tile.result:nan", "checkpoint.save:corrupt",
        "tile.compute:preempt",
    } <= set(faults_seen)

    log("phase 3/3: clean resume against the chaos checkpoint …")
    rc, resume_run = _run_phase("resume", out, out / "ckpt_chaos", out / "resumed.npz")
    checks["resume_rc0"] = rc == 0
    checks["corrupt_tile_quarantined"] = any((out / "ckpt_chaos" / "quarantine").glob("*.npz"))
    report_rc, report_doc = _report_resilience(resume_run) if resume_run else (2, {})
    checks["report_resilience_rc0"] = report_rc == 0
    checks["resume_repairs_visible"] = "quarantine" in (report_doc.get("repairs") or {})

    if checks["baseline_rc0"] and checks["resume_rc0"]:
        want = np.load(out / "baseline.npz")
        got = np.load(out / "resumed.npz")
        checks["grid_bit_identical"] = all(
            want[f].tobytes() == got[f].tobytes() for f in _FIELDS
        )
    else:
        checks["grid_bit_identical"] = False

    ok = all(checks.values())
    if args.json:
        print(json.dumps({"ok": ok, "checks": checks, "out": str(out)}))
    else:
        for name, passed in checks.items():
            print(f"  {'PASS' if passed else 'FAIL'}  {name}")
        print(f"chaos smoke: {'OK — recovery is bit-exact' if ok else 'FAILED'} ({out})")
        if resume_run is not None:
            print(f"recovery path: python -m sbr_tpu.obs.report resilience {resume_run}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
