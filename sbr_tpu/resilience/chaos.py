"""Chaos smoke: prove the resilience layer recovers a sweep bit-for-bit.

``python -m sbr_tpu.resilience.chaos [--out DIR] [--json]`` runs three
worker subprocesses over one small β×u tiled sweep:

1. **baseline** — fault-free run; its grid is the ground truth.
2. **chaos** — the same sweep under a seeded fault plan that injects a
   transient dispatch error (retried), a NaN-poisoned tile result
   (degrade-ladder repaired in-run), a corrupted checkpoint file (a torn
   write, discovered later), and a preemption (SIGTERM → graceful
   shutdown: manifest status ``"interrupted"``, exit 143).
3. **resume** — a clean rerun against the chaos checkpoint dir: the
   corrupt tile is sha256-quarantined and recomputed, cached tiles are
   served, preempted tiles are computed fresh.

The smoke PASSES only if the resumed grid is **bit-identical** to the
baseline (`max_aw`/`xi`/`status` byte equality — recovery must change
nothing) and the recovery path is visible: fault events + an interrupted
manifest in the chaos run, a quarantine repair in the resume run, and
``report resilience`` exiting 0 on the resume run. CI runs this as the
chaos-smoke job; it is equally useful locally after touching any
resilience path.

``python -m sbr_tpu.resilience.chaos --churn`` runs the ELASTIC churn
smoke (ISSUE 8) instead: a fault-free single-host baseline, then an
elastic sweep whose first host is preempted mid-run (SIGTERM after two of
four tiles — graceful shutdown must release its leases and heartbeat
immediately), a late-joining replacement host that adopts the remaining
tiles and assembles, and finally a warm re-sweep of the SAME parameter
grid into a FRESH checkpoint dir with the cross-run global tile cache
(``SBR_TILE_CACHE_DIR``) now hot. It passes only if both the churned and
the warm grids are bit-identical to the baseline, the preempted host left
no leases or heartbeats behind, and ``report elastic`` proves the warm
sweep computed ZERO tiles (every tile a cache hit).

``python -m sbr_tpu.resilience.chaos --fleet`` runs the SERVING-FLEET
smoke (ISSUE 11): a fault-free single-worker fleet run records the
ground-truth answers for a seeded query mix, then a three-worker fleet
serves the SAME mix while one worker is SIGKILLed mid-loadgen. It passes
only if the fleet run lost ZERO queries, every answer is byte-identical
to the single-worker ground truth (failover re-dispatch is benign by
construction — results are pure and fingerprint-keyed), the failover and
breaker-open events are visible in the router's telemetry, and ``report
fleet`` exits 0 on both router run dirs.

``python -m sbr_tpu.resilience.chaos --prewarm`` runs the SELF-HEALING
PREFETCH smoke (ISSUE 19): a single-worker fleet run under ``SBR_DEMAND=1``
records ground-truth answers AND leaves a ranked ``advisor_plan.json``;
two standalone prewarm sweepers then drain that plan into a shared tile
cache — the first is hung mid-plan by a ``prewarm.sweep`` fault and
SIGKILLed while holding a tile lease, and the second must ADOPT the
stranded tile at the 2 s lease TTL and finish the plan warm. The payoff
is a total solver outage (``serve.dispatch`` transient, p=1.0): every
plan-covered pool point must be answered from the prefetched tile cache
(source "tilecache"), byte-identical to the live ground truth. A final
``SBR_PREWARM=0`` control proves the whole subsystem is a structural
no-op when off (module never imported, ``/metrics`` byte-free).

The driver itself never imports jax (workers are subprocesses), so it can
run on a box whose accelerator stack is itself the thing being debugged.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
from pathlib import Path

import numpy as np

# One plan, seeded: same sequence every run (see resilience.faults).
# Tile order for the 4×4 grid under 2×2 tiles: (0,0) (0,2) (2,0) (2,2);
# tile.compute hit order with the retried first attempt: 1=(0,0) attempt 1
# (transient), 2=(0,0) attempt 2, 3=(0,2), 4=(2,0) → preempted.
FAULT_PLAN = {
    "seed": 0,
    "rules": [
        {"point": "tile.compute", "kind": "transient", "at_hits": [1]},
        {"point": "tile.result", "kind": "nan", "match": "b00000_u00002",
         "cells": 1, "max_fires": 1},
        {"point": "checkpoint.save", "kind": "corrupt", "match": "b00000_u00000",
         "max_fires": 1},
        {"point": "tile.compute", "kind": "preempt", "at_hits": [4]},
    ],
}

_FIELDS = ("max_aw", "xi", "status")

# One fixed small sweep shared by every worker mode so grids are
# byte-comparable across phases: 4×4 β×u grid under 2×2 tiles.
_SWEEP = dict(n_grid=96, bisect_iters=40, tile_shape=(2, 2))


def _sweep_values():
    return np.linspace(0.5, 2.0, 4), np.linspace(0.05, 0.5, 4)


def _worker(ckpt_dir: str, out_npz: str) -> int:
    """One tiled sweep (fixed small shape), grids saved as npz."""
    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.utils.checkpoint import run_tiled_grid

    betas, us = _sweep_values()
    grid = run_tiled_grid(
        betas,
        us,
        make_model_params(),
        config=SolverConfig(n_grid=_SWEEP["n_grid"], bisect_iters=_SWEEP["bisect_iters"]),
        tile_shape=_SWEEP["tile_shape"],
        checkpoint_dir=ckpt_dir,
    )
    arrays = {f: np.asarray(getattr(grid, f)) for f in _FIELDS}
    with open(out_npz, "wb") as fh:
        np.savez(fh, **arrays)
    return 0


def _worker_elastic(ckpt_dir: str, out_npz: str) -> int:
    """The same sweep through the ELASTIC scheduler (heartbeats, claim
    plan, leases, global tile cache from SBR_TILE_CACHE_DIR)."""
    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.parallel import run_tiled_grid_multihost

    betas, us = _sweep_values()
    grid = run_tiled_grid_multihost(
        betas,
        us,
        make_model_params(),
        ckpt_dir,
        config=SolverConfig(n_grid=_SWEEP["n_grid"], bisect_iters=_SWEEP["bisect_iters"]),
        tile_shape=_SWEEP["tile_shape"],
        poll_s=0.2,
        timeout_s=300.0,
        elastic=True,
    )
    arrays = {f: np.asarray(getattr(grid, f)) for f in _FIELDS}
    with open(out_npz, "wb") as fh:
        np.savez(fh, **arrays)
    return 0


def _run_phase(name: str, out: Path, ckpt: Path, npz, fault_plan=None,
               timeout_s=600.0, mode: str = "--worker", extra_env=None):
    """Run one worker subprocess; returns (rc, obs_run_dir_or_None)."""
    obs_root = out / f"obs_{name}"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "SBR_OBS": "1",
        "SBR_OBS_LABEL": name,
        "SBR_OBS_DIR": str(obs_root),
        "SBR_RETRY_BASE_DELAY_S": "0.05",
    }
    env.pop("SBR_FAULT_PLAN", None)
    env.pop("SBR_TILE_CACHE_DIR", None)
    if fault_plan is not None:
        env["SBR_FAULT_PLAN"] = json.dumps(fault_plan)
    if extra_env:
        env.update(extra_env)
    argv = [
        sys.executable, "-m", "sbr_tpu.resilience.chaos",
        mode, str(ckpt), str(npz if npz else out / f"{name}.npz"),
    ]
    proc = subprocess.run(
        argv, env=env, timeout=timeout_s, capture_output=True, text=True
    )
    if proc.stderr:
        sys.stderr.write(proc.stderr)
    runs = sorted(obs_root.iterdir()) if obs_root.is_dir() else []
    return proc.returncode, (runs[0] if runs else None)


def _manifest(run_dir) -> dict:
    try:
        return json.loads((Path(run_dir) / "manifest.json").read_text())
    except (OSError, TypeError, json.JSONDecodeError):
        return {}


def _report(subcommand: str, run_dir) -> tuple:
    """(exit_code, json_doc) from a report subcommand — user-facing gates."""
    proc = subprocess.run(
        [sys.executable, "-m", "sbr_tpu.obs.report", subcommand, str(run_dir), "--json"],
        capture_output=True, text=True, timeout=120.0,
    )
    try:
        return proc.returncode, json.loads(proc.stdout)
    except json.JSONDecodeError:
        return proc.returncode, {}


def _report_resilience(run_dir) -> tuple:
    return _report("resilience", run_dir)


def _report_multi(subcommand: str, run_dirs) -> tuple:
    """(exit_code, json_doc) for a multi-dir report subcommand
    (`trace`/`slo` join spans across the router's AND every worker's dir)."""
    proc = subprocess.run(
        [sys.executable, "-m", "sbr_tpu.obs.report", subcommand,
         *[str(d) for d in run_dirs], "--json"],
        capture_output=True, text=True, timeout=120.0,
    )
    try:
        return proc.returncode, json.loads(proc.stdout)
    except json.JSONDecodeError:
        return proc.returncode, {}


def _bit_identical(a_npz, b_npz) -> bool:
    try:
        want, got = np.load(a_npz), np.load(b_npz)
    except OSError:
        return False
    return all(want[f].tobytes() == got[f].tobytes() for f in _FIELDS)


# Churn plan: preempt host A at its THIRD tile compute — two tiles land,
# the third is interrupted mid-flight; graceful shutdown must release A's
# lease + heartbeat so the late-joining replacement claims immediately.
CHURN_FAULT_PLAN = {
    "seed": 0,
    "rules": [
        {"point": "tile.compute", "kind": "preempt", "at_hits": [3]},
    ],
}


def main_churn(out: Path, as_json: bool) -> int:
    """The elastic churn smoke: kill one host mid-sweep, late-join a
    replacement, then warm-cache re-sweep — all three grids bit-identical,
    zero tiles computed warm. See the module docstring."""
    checks: dict = {}
    cache = out / "tile_cache"
    elastic_env = {
        "SBR_TILE_CACHE_DIR": str(cache),
        "SBR_ELASTIC": "1",
        "SBR_HEARTBEAT_TTL_S": "10",
    }

    def log(msg):
        if not as_json:
            print(msg)

    log("phase 1/4: fault-free single-host baseline (no cache) …")
    rc, _ = _run_phase("baseline", out, out / "ckpt_baseline", out / "baseline.npz")
    checks["baseline_rc0"] = rc == 0

    log("phase 2/4: elastic host A — preempted (SIGTERM) after 2 of 4 tiles …")
    ckpt_churn = out / "ckpt_churn"
    rc, run_a = _run_phase(
        "churn_a", out, ckpt_churn, out / "churn_a.npz",
        fault_plan=CHURN_FAULT_PLAN, mode="--worker-elastic", extra_env=elastic_env,
    )
    checks["host_a_preempted_143"] = rc == 143
    checks["host_a_manifest_interrupted"] = _manifest(run_a).get("status") == "interrupted"
    # Even a preempted host's departure is in the census: its "leave"
    # event lands before the obs run finalizes as interrupted.
    _, doc_a = _report("elastic", run_a) if run_a else (2, {})
    checks["host_a_leave_visible"] = (doc_a.get("scheduler") or {}).get("leave", 0) >= 1
    # The graceful-shutdown satellite: A's leases AND heartbeat are
    # RELEASED at SIGTERM, so the replacement never waits out a TTL.
    checks["host_a_released_leases"] = not list(ckpt_churn.glob("*.lease"))
    checks["host_a_released_heartbeat"] = not list(ckpt_churn.glob("host_*.hb"))
    checks["host_a_partial_tiles"] = len(list(ckpt_churn.glob("tile_*.npz"))) == 2

    log("phase 3/4: elastic host B late-joins, adopts the rest, assembles …")
    rc, run_b = _run_phase(
        "churn_b", out, ckpt_churn, out / "churn.npz",
        mode="--worker-elastic", extra_env=elastic_env,
    )
    checks["host_b_rc0"] = rc == 0
    rc_el, doc_b = _report("elastic", run_b) if run_b else (2, {})
    checks["host_b_report_elastic_rc0"] = rc_el == 0
    sched_b = doc_b.get("scheduler") or {}
    checks["host_b_join_leave_visible"] = (
        sched_b.get("join", 0) >= 1 and sched_b.get("leave", 0) >= 1
    )
    checks["host_b_adopted_tiles"] = doc_b.get("tiles_computed", 0) == 2
    checks["churn_grid_bit_identical"] = _bit_identical(
        out / "baseline.npz", out / "churn.npz"
    )

    log("phase 4/4: warm re-sweep — fresh checkpoint dir, hot global cache …")
    rc, run_w = _run_phase(
        "warm", out, out / "ckpt_warm", out / "warm.npz",
        mode="--worker-elastic", extra_env=elastic_env,
    )
    checks["warm_rc0"] = rc == 0
    rc_el, doc_w = _report("elastic", run_w) if run_w else (2, {})
    checks["warm_report_elastic_rc0"] = rc_el == 0
    # The bridge from "one sweep survives faults" to "a fleet serves
    # sweeps incrementally": a repeated sweep recomputes NOTHING.
    checks["warm_zero_tiles_computed"] = (
        doc_w.get("tiles_computed", 1) == 0
        and doc_w.get("tiles_from_cache", 0) >= 4
    )
    checks["warm_all_tiles_cache_hits"] = (doc_w.get("cache") or {}).get("hit", 0) >= 4
    checks["warm_grid_bit_identical"] = _bit_identical(
        out / "baseline.npz", out / "warm.npz"
    )

    ok = all(checks.values())
    if as_json:
        print(json.dumps({"ok": ok, "checks": checks, "out": str(out)}))
    else:
        for name, passed in checks.items():
            print(f"  {'PASS' if passed else 'FAIL'}  {name}")
        print(
            "churn smoke: "
            + ("OK — churn is bit-exact and the warm sweep computed 0 tiles"
               if ok else "FAILED")
            + f" ({out})"
        )
        if run_b is not None:
            print(f"scheduler story: python -m sbr_tpu.obs.report elastic {run_b}")
    return 0 if ok else 1


# Fleet smoke shape: small enough that three CPU workers compile their
# buckets in seconds, large enough that the mid-run kill lands while
# queries are still flowing (kill after 8 of 36).
_FLEET = dict(queries=36, pool=6, group=8, n_grid=96, bisect_iters=30,
              kill_after=8)

_ANSWER_FIELDS = ("xi", "tau_bar_in", "aw_max", "status", "flags")


def _run_loadgen_fleet(out: Path, name: str, n_workers: int,
                       kill_after=None, timeout_s: float = 900.0,
                       extra_env=None, trace_out=None,
                       extra_argv=None) -> tuple:
    """One `loadgen --fleet` subprocess; returns (rc, summary, answers,
    router_run_dir). ``extra_env`` overlays the scrubbed environment (the
    churn phase turns tracing on with it; the audit phases turn canaries
    on — workers inherit it); ``trace_out`` forwards ``--trace-out``;
    ``extra_argv`` appends raw loadgen flags (--audit-fault/--audit-wait)."""
    run_dir = out / f"obs_{name}"
    answers_path = out / f"{name}_answers.json"
    argv = [
        sys.executable, "-m", "sbr_tpu.serve.loadgen",
        "--fleet", str(n_workers),
        "--queries", str(_FLEET["queries"]),
        "--pool", str(_FLEET["pool"]),
        "--group", str(_FLEET["group"]),
        "--n-grid", str(_FLEET["n_grid"]),
        "--bisect-iters", str(_FLEET["bisect_iters"]),
        "--seed", "0",
        "--run-dir", str(run_dir),
        "--answers-out", str(answers_path),
    ]
    if kill_after is not None:
        argv += ["--fleet-kill-after", str(kill_after)]
    if trace_out is not None:
        argv += ["--trace-out", str(trace_out)]
    argv += [str(a) for a in (extra_argv or [])]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    for k in ("SBR_FAULT_PLAN", "SBR_SERVE_DEADLINE_MS", "SBR_FLEET_DIR",
              "SBR_SERVE_CACHE_DIR", "SBR_TILE_CACHE_DIR",
              "SBR_TRACE_SAMPLE", "SBR_SERVE_SLO_MS",
              "SBR_AUDIT", "SBR_AUDIT_INTERVAL_S", "SBR_AUDIT_PROBES",
              "SBR_AUDIT_REGISTRY_DIR",
              "SBR_DEMAND", "SBR_PREWARM", "SBR_PREWARM_PLAN",
              "SBR_PREWARM_STATE_DIR"):
        env.pop(k, None)
    env.update(extra_env or {})
    proc = subprocess.run(argv, env=env, timeout=timeout_s,
                          capture_output=True, text=True)
    if proc.stderr:
        sys.stderr.write(proc.stderr)
    try:
        summary = json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        summary = {}
    try:
        answers = json.loads(answers_path.read_text())
    except (OSError, ValueError):
        answers = None
    return proc.returncode, summary, answers, run_dir


def _answers_identical(a, b) -> bool:
    """Byte-identity of two answer lists: JSON floats round-trip Python's
    shortest repr exactly, so == on the parsed values IS bit equality of
    the served doubles (None encodes NaN on both sides)."""
    if not isinstance(a, list) or not isinstance(b, list) or len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if not isinstance(x, dict) or not isinstance(y, dict):
            return False
        if x.get("degraded") or y.get("degraded") or "shed" in x or "shed" in y:
            return False  # the fault-free contract: full-fidelity answers
        if any(x.get(f) != y.get(f) for f in _ANSWER_FIELDS):
            return False
    return True


def main_fleet(out: Path, as_json: bool) -> int:
    """The serving-fleet chaos smoke: kill one of three workers
    mid-loadgen — zero lost queries, byte-identical answers, visible
    failover + breaker events, `report fleet` exit 0 (module docstring)."""
    checks: dict = {}

    def log(msg):
        if not as_json:
            print(msg)

    log("phase 1/2: fault-free single-worker fleet (ground-truth answers) …")
    rc1, sum1, ans1, run1 = _run_loadgen_fleet(out, "fleet_solo", 1)
    checks["solo_rc0"] = rc1 == 0
    checks["solo_zero_lost"] = sum1.get("fleet_lost", 1) == 0
    rc_f1, doc1 = _report("fleet", run1)
    checks["solo_report_fleet_rc0"] = rc_f1 == 0

    log("phase 2/2: three workers, one SIGKILLed after "
        f"{_FLEET['kill_after']} of {_FLEET['queries']} queries "
        "(tracing on: every query must yield a joinable waterfall) …")
    # Tracing is ON only for the churn phase: the solo phase stays
    # untraced, so `answers_bit_identical` below doubles as the
    # SBR_TRACE_SAMPLE=0-vs-1 bit-identity witness (ISSUE 16 acceptance).
    rc2, sum2, ans2, run2 = _run_loadgen_fleet(
        out, "fleet_churn", 3, kill_after=_FLEET["kill_after"],
        extra_env={"SBR_TRACE_SAMPLE": "1"},
        trace_out=out / "fleet_churn_trace_rows.jsonl",
    )
    checks["churn_rc0"] = rc2 == 0
    checks["churn_zero_lost"] = sum2.get("fleet_lost", 1) == 0
    checks["churn_worker_killed"] = bool(sum2.get("killed_worker"))
    checks["churn_zero_shed"] = sum2.get("fleet_shed_rate", 1) == 0
    # The failover is the recovery path: it must have actually fired, and
    # the dead worker's breaker must have opened (sick workers stop
    # absorbing traffic) — all visible via report fleet, not logs.
    rc_f2, doc2 = _report("fleet", run2)
    checks["churn_report_fleet_rc0"] = rc_f2 == 0
    checks["churn_failover_visible"] = doc2.get("failover_count", 0) >= 1
    checks["churn_breaker_visible"] = (doc2.get("events") or {}).get(
        "breaker_open", 0
    ) >= 1
    checks["churn_workers_joined"] = (doc2.get("events") or {}).get(
        "worker_join", 0
    ) == 3
    # Distributed tracing (ISSUE 16): spans from the router's and every
    # worker's run dir must JOIN into waterfalls — including for the
    # queries that failed over off the killed worker (the dead worker's
    # final trace line may be torn; the join must survive that).
    worker_dirs = sorted((run2.parent / (run2.name + "_workers")).glob("w*"))
    rc_t, doc_t = _report_multi("trace", [run2, *worker_dirs])
    checks["churn_report_trace_rc0"] = rc_t == 0
    checks["churn_traces_joined"] = doc_t.get("joined", 0) >= 1
    checks["churn_failover_trace"] = doc_t.get("failover_traces", 0) >= 1
    # Acceptance floor: the joined span trees must explain >= 95% of the
    # TOTAL measured end-to-end latency (duration-weighted).  Per-query
    # coverage is only floored loosely — a millisecond cache hit's fixed
    # parse/respond slice is a big fraction of nothing.
    checks["churn_trace_coverage_95"] = (
        (doc_t.get("coverage_weighted") or 0) >= 0.95
        and (doc_t.get("coverage_min") or 0) >= 0.70
    )
    rc_s, _doc_s = _report_multi("slo", [run2, *worker_dirs])
    checks["churn_report_slo_rc0"] = rc_s == 0
    checks["churn_trace_rows_written"] = (
        out / "fleet_churn_trace_rows.jsonl"
    ).exists()
    # The headline: every answer the degraded fleet served is byte-
    # identical to the fault-free single-worker ground truth.
    checks["answers_bit_identical"] = _answers_identical(ans1, ans2)

    ok = all(checks.values())
    if as_json:
        print(json.dumps({"ok": ok, "checks": checks, "out": str(out)}))
    else:
        for name, passed in checks.items():
            print(f"  {'PASS' if passed else 'FAIL'}  {name}")
        print(
            "fleet smoke: "
            + ("OK — one of three workers died mid-loadgen, zero queries "
               "lost, answers byte-identical" if ok else "FAILED")
            + f" ({out})"
        )
        print(f"fleet story: python -m sbr_tpu.obs.report fleet {run2}")
        print(f"trace story: python -m sbr_tpu.obs.report trace {run2} "
              f"{run2}_workers/w*")
    return 0 if ok else 1


_AUDIT_ENV = dict(
    SBR_AUDIT="1",
    SBR_AUDIT_INTERVAL_S="1.5",
    # One cheap probe keeps the canary cycle short enough for a smoke
    # (the full battery is the CLI's job, not this proof's).
    SBR_AUDIT_PROBES="graphgen.layout",
)

#: Seeded audit.canary corruption, worker 0 only (via loadgen
#: --audit-fault): every graphgen.layout canary RESULT is perturbed
#: pre-comparison — the serving path never sees it, so answers must stay
#: byte-identical while the audit flags drift.
_AUDIT_FAULT_PLAN = {
    "seed": 7,
    "rules": [
        {"point": "audit.canary", "kind": "corrupt", "match": "graphgen.layout"},
    ],
}


def _audit_drift_cycles(worker_run_dir: Path) -> list:
    """Cycle numbers of drift probe verdicts in a worker's audit events."""
    cycles = []
    try:
        for line in (worker_run_dir / "events.jsonl").read_text().splitlines():
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if (isinstance(ev, dict) and ev.get("kind") == "audit"
                    and ev.get("action") == "probe"
                    and ev.get("verdict") == "drift"):
                cycles.append(int(ev.get("cycle") or 0))
    except OSError:
        pass
    return cycles


def main_audit(out: Path, as_json: bool) -> int:
    """The numerics-audit chaos proof (ISSUE 17): corrupt ONE of three
    workers' canaries — drift detected within 2 cycles, worker
    quarantined by the router, zero lost queries, answers byte-identical
    to the fault-free control; and on a clean audited run, zero drift
    verdicts (no false positives) with p99 inside the audit-off
    tolerance (canaries never ride the hot path)."""
    checks: dict = {}

    def log(msg):
        if not as_json:
            print(msg)

    # Goldens are generated under the WORKERS' numerics env (cpu pin, x64
    # off) — the audit CLI pins x64 on, which would land in a different
    # content-addressed golden set and every worker canary would read
    # "no_golden" forever.
    goldens = out / "audit_goldens"
    log("phase 0/3: generating canary goldens under the worker env …")
    gen = subprocess.run(
        [sys.executable, "-c",
         "from sbr_tpu.utils.platform import pin_cpu_platform;"
         "pin_cpu_platform();"
         "import sys;"
         "from sbr_tpu.obs.audit import run_battery;"
         "r = run_battery(update=True);"
         "sys.exit(0 if r.get('updated') else 1)"],
        env={**os.environ, **_AUDIT_ENV,
             "SBR_AUDIT_REGISTRY_DIR": str(goldens)},
        timeout=600, capture_output=True, text=True,
    )
    if gen.stderr and not as_json:
        sys.stderr.write(gen.stderr)
    checks["goldens_rc0"] = gen.returncode == 0

    audit_env = {**_AUDIT_ENV, "SBR_AUDIT_REGISTRY_DIR": str(goldens)}

    log("phase 1/3: audit-OFF control (ground-truth answers + p99) …")
    rc0, sum0, ans0, _run0 = _run_loadgen_fleet(out, "audit_off", 3)
    checks["control_rc0"] = rc0 == 0
    checks["control_zero_lost"] = sum0.get("fleet_lost", 1) == 0

    log("phase 2/3: clean 3-worker run, canaries ON (no false positives) …")
    rc1, sum1, ans1, run1 = _run_loadgen_fleet(
        out, "audit_clean", 3, extra_env=audit_env,
        extra_argv=["--audit-wait", "2"],
    )
    checks["clean_rc0"] = rc1 == 0
    checks["clean_zero_lost"] = sum1.get("fleet_lost", 1) == 0
    blocks1 = (sum1.get("audit") or {}).get("workers") or {}
    checks["clean_canaries_ran"] = len(blocks1) == 3 and all(
        (b or {}).get("cycles", 0) >= 2 for b in blocks1.values()
    )
    checks["clean_no_drift"] = len(blocks1) == 3 and all(
        (b or {}).get("status") == "pass" for b in blocks1.values()
    )
    checks["clean_none_quarantined"] = not (sum1.get("audit") or {}).get("quarantined")
    checks["clean_answers_identical"] = _answers_identical(ans0, ans1)
    # Hot-path isolation: canaries are idle-gated, so the audited fleet's
    # p99 must sit inside the usual cross-run tolerance of the audit-off
    # control (generous: subprocess fleets on shared CI boxes are noisy).
    p99_off, p99_on = sum0.get("fleet_p99_ms"), sum1.get("fleet_p99_ms")
    checks["clean_p99_within_tolerance"] = (
        p99_off is not None and p99_on is not None
        and p99_on <= p99_off * 1.5 + 250.0
    )
    w1_dirs = sorted((run1.parent / (run1.name + "_workers")).glob("w*"))
    audit_rcs1 = [_report("audit", d)[0] for d in w1_dirs]
    checks["clean_report_audit_rc0"] = len(audit_rcs1) == 3 and all(
        rc == 0 for rc in audit_rcs1
    )

    log("phase 3/3: corrupt worker 0's canaries (detect + quarantine) …")
    rc2, sum2, ans2, run2 = _run_loadgen_fleet(
        out, "audit_fault", 3, extra_env=audit_env,
        extra_argv=["--audit-fault", json.dumps(_AUDIT_FAULT_PLAN),
                    "--audit-wait", "2"],
    )
    checks["fault_rc0"] = rc2 == 0
    checks["fault_zero_lost"] = sum2.get("fleet_lost", 1) == 0
    blocks2 = (sum2.get("audit") or {}).get("workers") or {}
    drifted = sorted(h for h, b in blocks2.items()
                     if (b or {}).get("status") == "drift")
    checks["fault_one_worker_drifted"] = len(drifted) == 1
    checks["fault_quarantined"] = bool(
        drifted and (sum2.get("audit") or {}).get("quarantined") == drifted
    )
    # Detection latency: the corrupted worker's own audit events must show
    # the drift verdict within the first 2 canary cycles.
    w2_dirs = sorted((run2.parent / (run2.name + "_workers")).glob("w*"))
    w0_drifts = _audit_drift_cycles(w2_dirs[0]) if w2_dirs else []
    checks["fault_detected_within_2_cycles"] = bool(w0_drifts) and min(w0_drifts) <= 2
    # The faulted worker keeps serving CORRECT answers (the fault perturbs
    # only the canary's copy of the result) — byte-identical to control.
    checks["fault_answers_identical"] = _answers_identical(ans0, ans2)
    # The quarantine is visible to the fleet, not just the worker: an
    # audit_quarantine event in the router's run dir.
    rc_f, doc_f = _report("fleet", run2)
    checks["fault_quarantine_event"] = (doc_f.get("events") or {}).get(
        "audit_quarantine", 0
    ) >= 1
    # And to the drift gate: exit 1 on the corrupted worker's run dir,
    # exit 0 on both peers.
    audit_rcs2 = [_report("audit", d)[0] for d in w2_dirs]
    checks["fault_report_audit_w0_rc1"] = bool(audit_rcs2) and audit_rcs2[0] == 1
    checks["fault_report_audit_peers_rc0"] = len(audit_rcs2) == 3 and all(
        rc == 0 for rc in audit_rcs2[1:]
    )

    ok = all(checks.values())
    if as_json:
        print(json.dumps({"ok": ok, "checks": checks, "out": str(out)}))
    else:
        for name, passed in checks.items():
            print(f"  {'PASS' if passed else 'FAIL'}  {name}")
        print(
            "audit smoke: "
            + ("OK — corrupted canaries detected within 2 cycles, worker "
               "quarantined, zero lost, answers byte-identical" if ok
               else "FAILED")
            + f" ({out})"
        )
        if w2_dirs:
            print(f"drift story: python -m sbr_tpu.obs.report audit {w2_dirs[0]}")
    return 0 if ok else 1


# Prewarm drill shape: the sweeper pool/mix mirrors _FLEET so the replay
# can rebuild the ground-truth mapping with stdlib random alone.
#: Hang the victim sweeper's SECOND tile attempt (after one clean tile):
#: it dies by SIGKILL holding that tile's lease — the healer must adopt.
_PREWARM_HANG_PLAN = {
    "seed": 0,
    "rules": [
        {"point": "prewarm.sweep", "kind": "hang", "at_hits": [2],
         "duration_s": 600},
    ],
}

#: Total solver outage for the replay: every dispatch attempt fails, so
#: ONLY the degradation ladder's tile-cache rung can answer.
_PREWARM_OUTAGE_PLAN = {
    "seed": 0,
    "rules": [
        {"point": "serve.dispatch", "kind": "transient", "p": 1.0},
    ],
}


def _prewarm_pool_coords(seed: int, pool: int) -> list:
    """`serve.loadgen.build_pool`'s (β, u) stream, replicated with stdlib
    random so this jax-free driver can reason about plan coverage."""
    import random

    rng = random.Random(seed)
    return [
        (round(rng.uniform(0.5, 4.0), 6), round(rng.uniform(0.02, 0.9), 6))
        for _ in range(pool)
    ]


def _prewarm_expanded_tiles(plan: dict) -> int:
    """Executable (per-β) tile count of an advisor plan — the granularity
    the prewarm controller claims leases at."""
    return sum(
        len({float(b) for b in (t.get("betas") or [])})
        for t in plan.get("tiles") or []
        if (t.get("betas") and t.get("us"))
    )


def _prewarm_env(cache: Path, fault_plan=None) -> dict:
    """A scrubbed environment for prewarm-phase subprocesses: shared tile
    cache, 2 s lease/heartbeat TTLs (adoption must happen in seconds, not
    the production 900 s), fast retries."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "SBR_TILE_CACHE_DIR": str(cache),
        "SBR_STEAL_LEASE_TTL_S": "2",
        "SBR_HEARTBEAT_TTL_S": "2",
        "SBR_RETRY_BASE_DELAY_S": "0.05",
        "SBR_PREWARM_RETRY_BASE_DELAY_S": "0.05",
    }
    for k in ("SBR_FAULT_PLAN", "SBR_PREWARM", "SBR_PREWARM_PLAN",
              "SBR_PREWARM_STATE_DIR", "SBR_PREWARM_BUDGET_TILES",
              "SBR_PREWARM_BUDGET_SECONDS", "SBR_DEMAND", "SBR_OBS"):
        env.pop(k, None)
    if fault_plan is not None:
        env["SBR_FAULT_PLAN"] = json.dumps(fault_plan)
    return env


def _spawn_sweeper(plan_path: Path, cache: Path, run_dir=None,
                   fault_plan=None, timeout_s: float = 60.0):
    """Popen one ``python -m sbr_tpu.serve.prewarm --once`` sweeper and
    wait for its readiness line; returns the process."""
    import threading

    argv = [
        sys.executable, "-m", "sbr_tpu.serve.prewarm",
        "--plan", str(plan_path),
        "--n-grid", str(_FLEET["n_grid"]),
        "--bisect-iters", str(_FLEET["bisect_iters"]),
        "--platform", "cpu", "--once", "--json",
        "--timeout-s", "600",
    ]
    if run_dir is not None:
        argv += ["--run-dir", str(run_dir)]
    proc = subprocess.Popen(
        argv, env=_prewarm_env(cache, fault_plan),
        stdout=subprocess.PIPE, stderr=sys.stderr.fileno(), text=True,
    )
    ready: dict = {}

    def read_ready():
        try:
            ready.update(json.loads(proc.stdout.readline()))
        except Exception:
            pass

    t = threading.Thread(target=read_ready, daemon=True)
    t.start()
    t.join(timeout_s)
    if ready.get("role") != "prewarm":
        proc.kill()
        raise RuntimeError(f"sweeper failed to become ready in {timeout_s:.0f}s")
    return proc


def main_prewarm(out: Path, as_json: bool) -> int:
    """The self-healing prefetch smoke (ISSUE 19): demand run leaves a
    plan, a hung sweeper is SIGKILLed holding a lease, a peer adopts and
    finishes warm, and a breaker-open outage is answered 100% warm,
    byte-identical to the live ground truth. See the module docstring."""
    import signal as _sig
    import time as _time

    checks: dict = {}
    cache = out / "tile_cache"

    def log(msg):
        if not as_json:
            print(msg)

    log("phase 1/4: single-worker fleet under SBR_DEMAND=1 "
        "(ground-truth answers + advisor plan) …")
    rc1, sum1, _ans1, run1 = _run_loadgen_fleet(
        out, "prewarm_demand", 1, extra_env={"SBR_DEMAND": "1"},
    )
    checks["demand_rc0"] = rc1 == 0
    checks["demand_zero_lost"] = sum1.get("fleet_lost", 1) == 0
    plan_path = run1.parent / (run1.name + "_workers") / "w0" / "advisor_plan.json"
    try:
        plan = json.loads(plan_path.read_text())
    except (OSError, ValueError):
        plan = {}
    checks["advisor_plan_written"] = (
        plan.get("schema") == "sbr-demand-advisor/1"
        and bool(plan.get("plan_fingerprint"))
        and bool(plan.get("tiles"))
    )
    # The drill needs >= 2 executable (per-β) tiles: one to land cleanly
    # on the victim, one to be stranded under its lease and adopted.
    n_tiles = _prewarm_expanded_tiles(plan)
    checks["plan_multi_tile"] = n_tiles >= 2
    pool_coords = _prewarm_pool_coords(0, _FLEET["pool"])
    covered = set()
    for t in plan.get("tiles") or []:
        bs = {float(b) for b in (t.get("betas") or [])}
        us = {float(u) for u in (t.get("us") or [])}
        covered |= {i for i, (b, u) in enumerate(pool_coords)
                    if b in bs and u in us}
    checks["plan_covers_hot_points"] = len(covered) >= 1
    if not all(checks.values()):
        # The remaining phases would only cascade noise without a plan.
        if as_json:
            print(json.dumps({"ok": False, "checks": checks, "out": str(out)}))
        else:
            for name, passed in checks.items():
                print(f"  {'PASS' if passed else 'FAIL'}  {name}")
            print(f"prewarm smoke: FAILED in phase 1 ({out})")
        return 1

    fp = plan["plan_fingerprint"]
    plan_dir = cache / "_prewarm" / f"plan_{fp}"

    log(f"phase 2/4: sweeper A drains the plan ({n_tiles} tile(s)) until a "
        "prewarm.sweep hang, then SIGKILL while it holds a tile lease …")
    victim = _spawn_sweeper(plan_path, cache, fault_plan=_PREWARM_HANG_PLAN,
                            timeout_s=120.0)
    deadline = _time.monotonic() + 600.0
    stranded = False
    while _time.monotonic() < deadline:
        if victim.poll() is not None:
            break  # finished without hanging — plan_multi_tile guard failed us
        done = list(plan_dir.glob("done_*.json")) if plan_dir.is_dir() else []
        leases = list(plan_dir.glob("tile_*.lease")) if plan_dir.is_dir() else []
        if done and leases:
            stranded = True
            break
        _time.sleep(0.25)
    _time.sleep(1.0)  # let the hang engage inside the leased attempt
    checks["victim_hung_holding_lease"] = stranded and victim.poll() is None
    try:
        os.kill(victim.pid, _sig.SIGKILL)
    except OSError:
        pass
    victim.wait(timeout=30)
    checks["victim_stranded_lease"] = bool(list(plan_dir.glob("tile_*.lease")))

    log("phase 3/4: sweeper B adopts the stranded tile at the 2 s lease "
        "TTL and finishes the plan warm …")
    heal_run = out / "obs_prewarm_heal" / "run"
    healer = _spawn_sweeper(plan_path, cache, run_dir=heal_run,
                            timeout_s=120.0)
    heal_out, _ = healer.communicate(timeout=900)
    try:
        snap = json.loads(heal_out.strip().splitlines()[-1])
    except (ValueError, IndexError):
        snap = {}
    counts = snap.get("counts") or {}
    checks["healer_rc0"] = healer.returncode == 0
    checks["healer_plan_done"] = snap.get("status") == "done"
    checks["healer_adopted_stranded_tile"] = counts.get("adopted", 0) >= 1
    checks["healer_zero_failed"] = counts.get("failed", 0) == 0
    checks["healer_all_warm"] = (
        snap.get("warm") is not None
        and snap.get("warm") == snap.get("tiles_total") == n_tiles
    )
    rc_pw, doc_pw = _report("prewarm", heal_run)
    checks["report_prewarm_rc0"] = rc_pw == 0
    checks["report_adoption_visible"] = any(
        (p or {}).get("adopted", 0) >= 1
        for p in (doc_pw.get("plans") or {}).values()
    )

    log("phase 4/4: total solver outage — every plan-covered pool point "
        "must be served from the prefetched cache, byte-identical …")
    replay_json = out / "prewarm_replay.json"
    proc = subprocess.run(
        [sys.executable, "-m", "sbr_tpu.resilience.chaos",
         "--worker-prewarm-replay", str(plan_path),
         str(out / "prewarm_demand_answers.json"), str(replay_json)],
        env=_prewarm_env(cache, fault_plan=_PREWARM_OUTAGE_PLAN),
        timeout=900, capture_output=True, text=True,
    )
    if proc.stderr:
        sys.stderr.write(proc.stderr)
    checks["replay_rc0"] = proc.returncode == 0
    try:
        replay = json.loads(replay_json.read_text())
    except (OSError, ValueError):
        replay = {}
    checks["replay_all_covered_warm"] = (
        bool(replay.get("covered"))
        and replay.get("warm") == replay.get("covered")
    )
    checks["replay_answers_bit_identical"] = (
        replay.get("compared", 0) >= 1 and not replay.get("mismatches")
    )

    # SBR_PREWARM=0 control: the subsystem off is a STRUCTURAL no-op.
    ctrl = subprocess.run(
        [sys.executable, "-c",
         "from sbr_tpu.utils.platform import pin_cpu_platform;"
         "pin_cpu_platform();"
         "import sys;"
         "from sbr_tpu.models.params import SolverConfig;"
         "from sbr_tpu.serve.engine import Engine, ServeConfig;"
         "eng = Engine(config=SolverConfig(n_grid=64, bisect_iters=30,"
         " refine_crossings=False), serve=ServeConfig(buckets=(1,)));"
         "assert eng.prewarm is None;"
         "assert 'sbr_prewarm' not in eng.prometheus();"
         "assert 'prewarm' not in eng.statz();"
         "eng.close();"
         "assert 'sbr_tpu.serve.prewarm' not in sys.modules;"
         "print('structural-noop-ok')"],
        env=_prewarm_env(cache), timeout=300, capture_output=True, text=True,
    )
    if ctrl.stderr:
        sys.stderr.write(ctrl.stderr)
    checks["prewarm_off_structural_noop"] = (
        ctrl.returncode == 0 and "structural-noop-ok" in ctrl.stdout
    )

    ok = all(checks.values())
    if as_json:
        print(json.dumps({"ok": ok, "checks": checks, "out": str(out)}))
    else:
        for name, passed in checks.items():
            print(f"  {'PASS' if passed else 'FAIL'}  {name}")
        print(
            "prewarm smoke: "
            + ("OK — a killed sweeper's tile was adopted and the outage "
               "was answered 100% warm, byte-identical" if ok else "FAILED")
            + f" ({out})"
        )
        print(f"prefetch story: python -m sbr_tpu.obs.report prewarm {heal_run}")
    return 0 if ok else 1


def _worker_prewarm_replay(plan_path: str, answers_path: str,
                           out_json: str) -> int:
    """Hidden worker: breaker-open replay against the prefetched cache.

    Queries every loadgen pool point through an engine whose EVERY solver
    dispatch fails (the parent plants the serve.dispatch transient), and
    records, per plan-covered point, whether the degradation ladder
    answered it from the tile cache and whether (xi, aw_max, status)
    equal the fault-free fleet ground truth. NaN is normalized to None on
    both sides (the answers-JSON convention), so equality of the parsed
    values IS bit equality of the served doubles."""
    import math

    from sbr_tpu.utils.platform import pin_cpu_platform

    pin_cpu_platform()
    from sbr_tpu.models.params import SolverConfig
    from sbr_tpu.serve.engine import Engine, ServeConfig
    from sbr_tpu.serve.loadgen import build_pool, query_mix

    plan = json.loads(Path(plan_path).read_text())
    answers = json.loads(Path(answers_path).read_text())
    pool = build_pool(0, _FLEET["pool"])
    mix = query_mix(0, _FLEET["pool"], _FLEET["queries"])
    truth: dict = {}
    for pos, idx in enumerate(mix):
        a = answers[pos] if pos < len(answers) else None
        if isinstance(a, dict) and "xi" in a and not a.get("degraded"):
            truth.setdefault(idx, a)

    covered = set()
    for t in plan.get("tiles") or []:
        bs = {float(b) for b in (t.get("betas") or [])}
        us = {float(u) for u in (t.get("us") or [])}
        covered |= {
            i for i, p in enumerate(pool)
            if float(p.learning.beta) in bs and float(p.economic.u) in us
        }

    def norm(v):
        if v is None:
            return None
        f = float(v)
        return None if math.isnan(f) else f

    config = SolverConfig(
        n_grid=_FLEET["n_grid"], bisect_iters=_FLEET["bisect_iters"],
        refine_crossings=False,
    )
    results: dict = {}
    eng = Engine(config=config, serve=ServeConfig(buckets=(1,)))
    try:
        for i, p in enumerate(pool):
            try:
                r = eng.query(p)
            except Exception as err:  # noqa: BLE001 — a cold point, recorded
                results[i] = {"error": repr(err)}
                continue
            results[i] = {
                "xi": norm(r.xi), "aw_max": norm(r.aw_max),
                "status": int(r.status), "source": r.source,
                "degraded": bool(r.degraded),
            }
    finally:
        eng.close()

    doc = {
        "covered": sorted(covered),
        "warm": sorted(
            i for i in covered if (results.get(i) or {}).get("source") == "tilecache"
        ),
        "compared": 0,
        "mismatches": [],
        "results": {str(i): r for i, r in results.items()},
    }
    for i in sorted(covered):
        t = truth.get(i)
        r = results.get(i) or {}
        if t is None or "error" in r:
            continue
        doc["compared"] += 1
        for field in ("xi", "aw_max", "status"):
            tv = t.get(field) if field == "status" else norm(t.get(field))
            rv = r.get(field)
            if tv != rv:
                doc["mismatches"].append(
                    {"pool": i, "field": field, "truth": tv, "replay": rv}
                )
    Path(out_json).write_text(json.dumps(doc, indent=2, sort_keys=True))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.resilience.chaos",
        description="Seeded chaos smoke: faulted+resumed sweep must be "
        "bit-identical to the fault-free run",
    )
    parser.add_argument("--out", default="/tmp/sbr_chaos", help="scratch/artifact dir")
    parser.add_argument("--json", action="store_true", help="machine-readable verdict")
    parser.add_argument(
        "--churn", action="store_true",
        help="run the ELASTIC churn smoke instead: preempt one host "
        "mid-sweep, late-join a replacement, warm-cache re-sweep — "
        "bit-identical grids, zero warm recomputes (ISSUE 8)",
    )
    parser.add_argument(
        "--fleet", action="store_true",
        help="run the SERVING-FLEET smoke instead: SIGKILL one of three "
        "serve workers mid-loadgen — zero lost queries, answers "
        "byte-identical to a fault-free single-worker run, failover + "
        "breaker events visible via report fleet (ISSUE 11)",
    )
    parser.add_argument(
        "--audit", action="store_true",
        help="run the NUMERICS-AUDIT smoke instead: corrupt one of three "
        "workers' canary solves (audit.canary fault) — drift detected "
        "within 2 cycles, worker quarantined by the router, zero lost, "
        "answers byte-identical to the audit-off control (ISSUE 17)",
    )
    parser.add_argument(
        "--prewarm", action="store_true",
        help="run the SELF-HEALING-PREFETCH smoke instead: demand run "
        "leaves an advisor plan, a sweeper is SIGKILLed mid-plan holding "
        "a tile lease, a peer adopts and finishes warm, and a total "
        "solver outage is answered 100%% from the prefetched cache, "
        "byte-identical to the live ground truth (ISSUE 19)",
    )
    parser.add_argument("--worker", nargs=2, metavar=("CKPT", "NPZ"), help=argparse.SUPPRESS)
    parser.add_argument("--worker-elastic", nargs=2, metavar=("CKPT", "NPZ"), help=argparse.SUPPRESS)
    parser.add_argument(
        "--worker-prewarm-replay", nargs=3, metavar=("PLAN", "ANSWERS", "OUT"),
        help=argparse.SUPPRESS,
    )
    args = parser.parse_args(argv)

    if args.worker:
        return _worker(*args.worker)
    if args.worker_elastic:
        return _worker_elastic(*args.worker_elastic)
    if args.worker_prewarm_replay:
        return _worker_prewarm_replay(*args.worker_prewarm_replay)

    out = Path(args.out)
    if out.exists():
        shutil.rmtree(out)
    out.mkdir(parents=True)

    if args.churn:
        return main_churn(out, args.json)
    if args.fleet:
        return main_fleet(out, args.json)
    if args.audit:
        return main_audit(out, args.json)
    if args.prewarm:
        return main_prewarm(out, args.json)

    checks: dict = {}

    def log(msg):
        if not args.json:
            print(msg)

    log("phase 1/3: fault-free baseline …")
    rc, _ = _run_phase("baseline", out, out / "ckpt_baseline", out / "baseline.npz")
    checks["baseline_rc0"] = rc == 0

    log("phase 2/3: chaos (transient + nan poison + corrupt tile + preemption) …")
    rc, chaos_run = _run_phase(
        "chaos", out, out / "ckpt_chaos", out / "chaos.npz", fault_plan=FAULT_PLAN
    )
    checks["chaos_preempted_143"] = rc == 143
    checks["chaos_manifest_interrupted"] = _manifest(chaos_run).get("status") == "interrupted"
    chaos_res = (_manifest(chaos_run).get("resilience") or {})
    faults_seen = chaos_res.get("faults") or {}
    checks["chaos_faults_visible"] = {
        "tile.compute:transient", "tile.result:nan", "checkpoint.save:corrupt",
        "tile.compute:preempt",
    } <= set(faults_seen)

    log("phase 3/3: clean resume against the chaos checkpoint …")
    rc, resume_run = _run_phase("resume", out, out / "ckpt_chaos", out / "resumed.npz")
    checks["resume_rc0"] = rc == 0
    checks["corrupt_tile_quarantined"] = any((out / "ckpt_chaos" / "quarantine").glob("*.npz"))
    report_rc, report_doc = _report_resilience(resume_run) if resume_run else (2, {})
    checks["report_resilience_rc0"] = report_rc == 0
    checks["resume_repairs_visible"] = "quarantine" in (report_doc.get("repairs") or {})

    if checks["baseline_rc0"] and checks["resume_rc0"]:
        want = np.load(out / "baseline.npz")
        got = np.load(out / "resumed.npz")
        checks["grid_bit_identical"] = all(
            want[f].tobytes() == got[f].tobytes() for f in _FIELDS
        )
    else:
        checks["grid_bit_identical"] = False

    ok = all(checks.values())
    if args.json:
        print(json.dumps({"ok": ok, "checks": checks, "out": str(out)}))
    else:
        for name, passed in checks.items():
            print(f"  {'PASS' if passed else 'FAIL'}  {name}")
        print(f"chaos smoke: {'OK — recovery is bit-exact' if ok else 'FAILED'} ({out})")
        if resume_run is not None:
            print(f"recovery path: python -m sbr_tpu.obs.report resilience {resume_run}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
