"""Unified retry policy engine: exponential backoff with jitter, error
classification, and per-scope retry budgets.

Before this module the package had three ad-hoc failure defenses: one bare
retry loop per sweep tile (`utils/checkpoint.py`), a hand-rolled 3×300 s
probe ladder in `bench.py`, and nothing else. This engine is the single
policy they all share:

- **Classification.** Deterministic errors (shape/param/dtype bugs —
  ``ValueError``/``TypeError`` by default) are re-raised immediately:
  retrying the identical call just burns attempts. Everything else is
  treated as transient (device resets, tunnel drops, injected faults).
- **Backoff.** ``delay = min(max_delay_s, base_delay_s * multiplier**(k-1))``
  after failed attempt k, optionally widened by up to ``jitter`` fraction
  (drawn from a caller-supplied ``random.Random`` so chaos tests stay
  deterministic; jitter exists to de-synchronize a fleet of workers
  hammering shared storage after a common-mode failure).
- **Budgets.** A :class:`RetryBudget` caps the *total* extra attempts
  spent across every scope that shares it — a sweep of 400 tiles against
  a dead backend must fail fast, not retry 400×3 times.
- **Observability.** Every attempt outcome is reported to an ``observer``
  callable (default: the obs ``retry`` event + manifest roll-up via
  `obs.log_retry`, lazily imported); outcomes are ``retrying``,
  ``recovered``, ``gave_up``, ``deterministic``, and ``budget_exhausted``.

Like `resilience.faults`, this module is stdlib-only at import time so the
bench harness parent (which must never load jax) can import it standalone
by file path.
"""

from __future__ import annotations

import dataclasses
import os
import random
import sys
import time
from typing import Callable, Optional, Tuple

DETERMINISTIC_DEFAULT: Tuple[type, ...] = (ValueError, TypeError)


class RetryError(RuntimeError):
    """All attempts failed. ``__cause__`` is the last underlying error."""

    def __init__(self, scope: str, attempts: int, reason: str = "") -> None:
        msg = f"{scope} failed after {attempts} attempt{'s' if attempts != 1 else ''}"
        if reason:
            msg += f" ({reason})"
        super().__init__(msg)
        self.scope = scope
        self.attempts = attempts


class RetryBudget:
    """Shared pool of extra attempts across scopes (see module docstring).

    With ``refill_s`` the pool refreshes on a wall-clock cadence: a
    LONG-LIVED process (the serving engine, an elastic sweep host that
    outlives many tile batches) must not let a handful of recovered
    hiccups spread over days permanently latch the budget empty, while a
    genuinely dead backend still fail-fasts (many failures inside one
    refill window). Refill is applied lazily on `take`/`remaining` reads —
    no timer thread — against an injectable ``clock`` so tests drive it
    deterministically. ``refill_s=None`` (the default) keeps the historic
    one-shot semantics sweeps rely on."""

    def __init__(self, total: int, refill_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.total = int(total)
        self.used = 0
        self.refill_s = refill_s
        self._clock = clock
        self._epoch = clock()

    def maybe_refill(self) -> bool:
        """Reset the pool when the refill period has fully lapsed (>=, so a
        read exactly at the boundary refills). Returns True on a refill."""
        if not self.refill_s or self.refill_s <= 0:
            return False
        now = self._clock()
        if now - self._epoch >= self.refill_s:
            self._epoch = now
            self.used = 0
            return True
        return False

    def take(self) -> bool:
        """Consume one retry if any remain; False means the pool is dry."""
        self.maybe_refill()
        if self.used >= self.total:
            return False
        self.used += 1
        return True

    @property
    def remaining(self) -> int:
        self.maybe_refill()
        return max(self.total - self.used, 0)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Immutable retry configuration; `call` runs a function under it."""

    max_attempts: int = 3
    base_delay_s: float = 1.0
    multiplier: float = 2.0
    max_delay_s: float = 60.0
    jitter: float = 0.0  # widen each delay by up to this fraction
    deterministic: Tuple[type, ...] = DETERMINISTIC_DEFAULT

    def delay_s(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        """Backoff after failed attempt ``attempt`` (1-based), widened by up
        to ``jitter`` fraction (from ``rng`` when given — chaos tests pass a
        seeded one — else the module RNG, so the knob works out of the box)."""
        d = min(self.max_delay_s, self.base_delay_s * self.multiplier ** (attempt - 1))
        if self.jitter:
            d *= 1.0 + self.jitter * (rng.random() if rng is not None else random.random())
        return d

    def call(
        self,
        fn: Callable,
        *args,
        scope: str = "call",
        budget: Optional[RetryBudget] = None,
        observer: Optional[Callable] = None,
        sleep: Callable = time.sleep,
        rng: Optional[random.Random] = None,
        **kwargs,
    ):
        """Run ``fn(*args, **kwargs)``, retrying transient failures.

        Raises deterministic errors unchanged on the first occurrence and
        :class:`RetryError` (chained to the last error) when attempts or
        the shared ``budget`` run out.
        """
        if observer is None:
            observer = _default_observer
        last_err = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                out = fn(*args, **kwargs)
            except self.deterministic as err:
                observer(
                    scope=scope, outcome="deterministic", attempt=attempt,
                    max_attempts=self.max_attempts, error=repr(err),
                )
                raise
            except Exception as err:
                last_err = err
                if attempt >= self.max_attempts:
                    break
                if budget is not None and not budget.take():
                    observer(
                        scope=scope, outcome="budget_exhausted", attempt=attempt,
                        max_attempts=self.max_attempts, error=repr(err),
                    )
                    raise RetryError(scope, attempt, "shared retry budget exhausted") from err
                backoff = self.delay_s(attempt, rng)
                observer(
                    scope=scope, outcome="retrying", attempt=attempt,
                    max_attempts=self.max_attempts, error=repr(err),
                    backoff_s=round(backoff, 3),
                )
                if backoff > 0.0:
                    sleep(backoff)
            else:
                if attempt > 1:
                    observer(
                        scope=scope, outcome="recovered", attempt=attempt,
                        max_attempts=self.max_attempts,
                    )
                return out
        observer(
            scope=scope, outcome="gave_up", attempt=self.max_attempts,
            max_attempts=self.max_attempts, error=repr(last_err),
        )
        raise RetryError(scope, self.max_attempts) from last_err


def policy_from_env(prefix: str = "SBR_RETRY", **defaults) -> RetryPolicy:
    """Build a policy from ``{prefix}_MAX_ATTEMPTS`` / ``_BASE_DELAY_S`` /
    ``_MULTIPLIER`` / ``_MAX_DELAY_S`` / ``_JITTER`` env overrides layered
    over ``defaults`` (which themselves override the dataclass defaults).

    This is how each subsystem gets its own tunable scope with one shared
    mechanism: the tile loop reads ``SBR_RETRY_*``, the bench probe ladder
    ``SBR_BENCH_PROBE_*`` (where ``SBR_BENCH_PROBE_ATTEMPTS`` is accepted
    as the historical alias of ``_MAX_ATTEMPTS``).
    """
    fields = {
        "max_attempts": (int, ("MAX_ATTEMPTS", "ATTEMPTS")),
        "base_delay_s": (float, ("BASE_DELAY_S",)),
        "multiplier": (float, ("MULTIPLIER",)),
        "max_delay_s": (float, ("MAX_DELAY_S",)),
        "jitter": (float, ("JITTER",)),
    }
    kw = dict(defaults)
    for name, (cast, suffixes) in fields.items():
        for suffix in suffixes:
            raw = os.environ.get(f"{prefix}_{suffix}", "").strip()
            if raw:
                kw[name] = cast(raw)
                break
    return RetryPolicy(**kw)


def _default_observer(**record) -> None:
    """Report one attempt outcome as an obs ``retry`` event + manifest
    roll-up. Guarded like `faults._emit`: never the reason jax loads into
    a process that hasn't imported sbr_tpu (the bench parent supplies its
    own observer instead)."""
    if "sbr_tpu" not in sys.modules and os.environ.get("SBR_OBS", "").strip() in ("", "0"):
        return
    try:
        from sbr_tpu import obs

        obs.log_retry(**record)
    except Exception:
        pass  # telemetry must never sink the retried call
