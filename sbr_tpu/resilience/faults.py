"""Deterministic fault injection: seeded fault plans fired at named points.

Production-scale sweeps on real TPU pods meet preemptions, hung compiles,
flaky hosts, and corrupted checkpoint files as routine events — and the
only way to keep the recovery paths (retry, quarantine/recompute, work
stealing, graceful shutdown) from rotting is to exercise them on demand.
This module makes failure a *reproducible input*: a ``FaultPlan`` is a
seeded list of rules, each bound to a named **fault point** planted in the
execution paths that can really fail:

=====================  ====================================================
point                  planted in
=====================  ====================================================
``sweep.dispatch``     `sweeps.baseline_sweeps.beta_u_grid` /
                       `sweeps.policy_sweeps.policy_sweep_interest`, just
                       before the jitted grid program dispatches
``tile.compute``       `utils.checkpoint.run_tiled_grid`, per tile attempt
``tile.result``        same, after a tile computes (site poisons results)
``checkpoint.save``    after a tile's atomic save (site corrupts the file)
``checkpoint.load``    before a cached tile is read back
``tilecache.load``     `resilience.elastic.TileCache`, before a cross-run
                       global-cache entry is read (verify/quarantine path)
``barrier.poll``       `parallel.distributed` filesystem barrier and the
                       elastic scheduler's claim loop, per poll
``bench.probe``        `bench.py`'s accelerator probe, per attempt
``serve.dispatch``     `serve.engine.Engine._dispatch`, inside the retried
                       scope of every micro-batch device dispatch (drives
                       the breaker + degradation ladder)
``router.forward``     `serve.router.Router._forward`, per forward attempt
                       to a fleet worker (drives failover/hedging)
``fleet.heartbeat``    `serve.fleet.WorkerAnnouncer.beat`, per announcement
                       (a fired transient silences the beat — the worker
                       ages out via the TTL like a silent death)
``audit.canary``       `obs.audit.run_battery`, per probe execution with
                       the probe name as target, just before the golden
                       comparison (``nan``/``corrupt`` perturb the canary
                       result — the audit MUST flag drift and the router
                       MUST quarantine the worker; use ``match`` to hit
                       one probe)
``prewarm.plan_load``  `serve.prewarm.load_plan`, before every advisor-plan
                       read/parse (a fired transient = a torn/unreadable
                       plan; the controller must keep its current epoch)
``prewarm.sweep``      `serve.prewarm.PrewarmController._run_tile`, per
                       tile attempt with the tile id as target (inside the
                       retry scope; ``hang`` + SIGKILL is how the chaos
                       ``--prewarm`` drill strands a lease for a peer to
                       adopt)
=====================  ====================================================

Fault kinds:

- ``transient`` — raise :class:`InjectedFault` (a ``RuntimeError``): the
  retry engine must classify and absorb it.
- ``hang``      — sleep ``duration_s`` (default 30) then continue: models a
  stalled tunnel/compile; timeouts and kill -9 recovery are tested with it.
- ``preempt``   — send this process ``signal`` (default ``TERM``, ``KILL``
  for un-catchable death): models pod preemption; the graceful-shutdown
  handler (SIGTERM) or crash-resume path (SIGKILL) must recover.
- ``nan``       — returned to the call site, which poisons ``cells`` result
  cells with NaN and marks their health flags divergent: the degrade
  ladder (`resilience.heal`) must repair them.
- ``corrupt``   — returned to the call site, which truncates the
  just-written checkpoint file: sha256 verify-on-load must quarantine it.

Determinism contract: a plan with the same ``seed`` replayed against the
same sequence of fault-point invocations fires the same faults (per-rule
counters + a per-rule ``random.Random`` stream; nothing reads wall clock
or global RNG state). Asserted by ``tests/test_resilience.py``.

Configuration: ``SBR_FAULT_PLAN`` holds either inline JSON or a path to a
JSON file. Shape::

    {"seed": 0, "rules": [
      {"point": "tile.compute", "kind": "transient", "at_hits": [1]},
      {"point": "checkpoint.save", "kind": "corrupt", "match": "b00000",
       "max_fires": 1},
      {"point": "tile.compute", "kind": "preempt", "at_hits": [3]},
      {"point": "tile.result", "kind": "nan", "p": 0.5, "cells": 2}
    ]}

A rule fires on a matching invocation when its hit index is in
``at_hits``, or (without ``at_hits``) when its seeded stream draws below
``p`` (default 1.0); ``max_fires`` caps total firings, ``match`` restricts
to targets containing the substring. Every firing is emitted as an obs
``fault`` event (when telemetry is on) and appended to the plan's
``firings`` list.

This module is deliberately stdlib-only at import time: the bench harness
PARENT (which must never load jax) imports it standalone by file path.
"""

from __future__ import annotations

import json
import os
import random
import signal as _signal
import sys
import time
from typing import Optional

KINDS = ("transient", "hang", "preempt", "nan", "corrupt")


class InjectedFault(RuntimeError):
    """A deliberately injected transient error (retryable by design)."""


class Rule:
    """One fault rule plus its mutable firing state (see module docstring)."""

    __slots__ = (
        "index", "point", "kind", "p", "max_fires", "at_hits", "match",
        "duration_s", "signal", "cells", "hits", "fires", "_rng",
    )

    def __init__(self, index: int, seed: int, spec: dict) -> None:
        point = spec.get("point")
        kind = spec.get("kind")
        if not point or kind not in KINDS:
            raise ValueError(
                f"fault rule #{index} needs a 'point' and a 'kind' in {KINDS}: {spec!r}"
            )
        self.index = index
        self.point = point
        self.kind = kind
        self.p = float(spec.get("p", 1.0))
        self.max_fires = spec.get("max_fires")
        self.at_hits = [int(h) for h in spec["at_hits"]] if "at_hits" in spec else None
        self.match = spec.get("match", "")
        self.duration_s = float(spec.get("duration_s", 30.0))
        self.signal = str(spec.get("signal", "TERM")).upper()
        self.cells = int(spec.get("cells", 1))
        self.hits = 0
        self.fires = 0
        # One independent deterministic stream per rule: decisions depend
        # only on (seed, rule identity, hit order), never on other rules'
        # draws, wall clock, or the global random module.
        self._rng = random.Random(f"{seed}|{index}|{point}|{kind}")

    def should_fire(self, target: str, consume: bool = True) -> bool:
        """Advance this rule's hit counter and RNG stream for one matching
        invocation and decide whether it would fire. With ``consume=False``
        (the stream-alignment path when another rule already claimed the
        invocation) the decision is computed identically but the rule's
        ``fires`` budget is NOT charged — a planned fault must never be
        silently spent by an invocation it didn't act on."""
        if self.match and self.match not in target:
            return False
        self.hits += 1
        if self.at_hits is not None:
            fire = self.hits in self.at_hits
        else:
            # Draw unconditionally (even past max_fires) so the stream stays
            # aligned with a replay under any other rule interleaving.
            fire = self._rng.random() < self.p
        if self.max_fires is not None and self.fires >= int(self.max_fires):
            fire = False
        if fire and consume:
            self.fires += 1
        return fire


class FaultPlan:
    """A seeded set of fault rules; `fire` is the single injection gate."""

    def __init__(self, spec: dict) -> None:
        self.seed = int(spec.get("seed", 0))
        self.rules = [Rule(i, self.seed, r) for i, r in enumerate(spec.get("rules", []))]
        self.firings: list = []  # chronological (point, kind, target) record

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        """Parse inline JSON, or read a path to a JSON file."""
        text = text.strip()
        if not text.startswith("{"):
            with open(text) as fh:
                text = fh.read()
        return cls(json.loads(text))

    def fire(self, point: str, target: str = "") -> Optional[Rule]:
        """Evaluate every rule bound to ``point`` against this invocation.

        Side-effectful kinds act here (``transient`` raises,
        ``hang`` sleeps, ``preempt`` signals this process); cooperation
        kinds (``nan``, ``corrupt``) are returned for the site to apply.
        At most one rule acts per invocation (first firing rule in plan
        order wins); later rules still count the hit, keeping their
        streams aligned with the fault-free replay.
        """
        acted = None
        for rule in self.rules:
            if rule.point != point:
                continue
            if acted is not None:
                # keep hit/draw streams advancing identically whether or
                # not an earlier rule already claimed this invocation —
                # without spending the rule's own max_fires budget
                rule.should_fire(target, consume=False)
                continue
            if rule.should_fire(target):
                acted = rule
        if acted is None:
            return None
        record = {
            "point": point,
            "kind": acted.kind,
            "target": target,
            "rule": acted.index,
            "hit": acted.hits,
            "fire": acted.fires,
        }
        self.firings.append(record)
        _emit(**record)
        if acted.kind == "transient":
            raise InjectedFault(
                f"injected transient fault at {point} (rule {acted.index}, target {target!r})"
            )
        if acted.kind == "hang":
            time.sleep(acted.duration_s)
            return None
        if acted.kind == "preempt":
            os.kill(os.getpid(), getattr(_signal, f"SIG{acted.signal}"))
            # SIGTERM delivery is asynchronous: give the interpreter a
            # moment to run the handler before the site continues.
            time.sleep(0.5)
            return None
        return acted  # nan / corrupt: the site applies the damage


# ---------------------------------------------------------------------------
# Process-global plan: parsed once from SBR_FAULT_PLAN on first use.
# ---------------------------------------------------------------------------

_PLAN: Optional[FaultPlan] = None
_PARSED = False


def plan() -> Optional[FaultPlan]:
    """The active plan (lazy-parsed from ``SBR_FAULT_PLAN``), or None."""
    global _PLAN, _PARSED
    if not _PARSED:
        _PARSED = True
        text = os.environ.get("SBR_FAULT_PLAN", "").strip()
        if text:
            _PLAN = FaultPlan.parse(text)
    return _PLAN


def install(p: Optional[FaultPlan]) -> None:
    """Programmatically install (or clear, with None) the active plan."""
    global _PLAN, _PARSED
    _PLAN = p
    _PARSED = True


def reset() -> None:
    """Forget the active plan so the next `fire` re-reads SBR_FAULT_PLAN."""
    global _PLAN, _PARSED
    _PLAN = None
    _PARSED = False


def fire(point: str, target: str = "") -> Optional[Rule]:
    """Module-level fault point: near-zero cost (one None check) without a
    plan; with one, delegates to :meth:`FaultPlan.fire`."""
    p = plan()
    if p is None:
        return None
    return p.fire(point, target)


def corrupt_file(path, rule: Optional[Rule] = None) -> None:
    """Apply a ``corrupt`` injection: truncate ``path`` to half its size
    (a torn write — the checkpoint-corruption mode seen on real pods when
    a host dies mid-flush on shared storage)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(max(size // 2, 1))


def _emit(**fields) -> None:
    """Emit one obs ``fault`` event, without ever being the reason jax
    loads into a jax-free process: when `sbr_tpu` is not already imported
    (the bench parent loads this file standalone by path), only an
    explicit SBR_OBS opt-in justifies pulling the package in."""
    if "sbr_tpu" not in sys.modules and os.environ.get("SBR_OBS", "").strip() in ("", "0"):
        return
    try:
        from sbr_tpu import obs

        obs.log_fault(**fields)
    except Exception:
        pass  # telemetry must never sink an injection (or its test)
