"""Self-healing primitives: checkpoint integrity sidecars, quarantine, and
the per-cell degrade-ladder repair of divergent sweep cells.

Three healing mechanisms, used by `utils.checkpoint.run_tiled_grid` and
`parallel.distributed`:

**Integrity sidecars.** Every saved tile gains a ``<tile>.sha256`` sidecar
(hex digest of the file bytes, written after the tile's atomic rename).
`verify_file` re-hashes on load: a mismatch means torn/bit-rotted storage
and the tile is **quarantined** (moved into ``quarantine/`` next to the
checkpoint, never silently deleted — it is evidence) and recomputed.
Tiles written by pre-sidecar builds verify as ``"legacy"`` and are trusted,
so old checkpoint dirs keep resuming. The cross-run global tile cache
(`resilience.elastic.TileCache`) reuses the same sidecar + quarantine
machinery for its entries — a corrupt cache entry is quarantined beside
the cache and the tile recomputed, never served.

**Degrade ladder.** A cell whose `sbr_tpu.diag` health bitmask carries a
divergent bit (NaN poison, non-finite residual, fixed-point failure)
is re-run individually, climbing a ladder of increasingly conservative
numerics:

- rung 0 — identical config and dtype: repairs transient device garbage
  (and injected NaN poison) where the mathematics is actually fine; being
  deterministic, it cannot mask a genuine numerical failure — that
  recomputes identically divergent and escalates;
- rung 1 — float64 with tightened tolerances (doubled bisection
  halvings): the "paranoid precision" rung for cells that are genuinely
  marginal at sweep precision.

A repaired cell replaces the original **only when its recompute is
non-divergent** — the ladder strictly improves trust, never swaps one
untrusted number for another. Each outcome is emitted as an obs ``repair``
event and returned in a ``repairs`` report that call sites persist in the
checkpoint manifest.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from pathlib import Path
from typing import Optional

import numpy as np


def sidecar_path(path) -> Path:
    return Path(str(path) + ".sha256")


def _digest(path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_sidecar(path, source=None) -> Path:
    """Write (atomically) the sha256 sidecar for ``path``. ``source``
    (default ``path``) is the file whose bytes are hashed — the cross-run
    tile cache hashes its staged temp file and publishes the sidecar
    BEFORE renaming the entry into place, so a concurrent reader can never
    observe a cache entry without its sidecar (there is no "legacy
    trusted" grace for cache entries)."""
    side = sidecar_path(path)
    tmp = Path(str(side) + ".tmp")
    tmp.write_text(_digest(source if source is not None else path) + "\n")
    os.replace(tmp, side)
    return side


def verify_file(path) -> str:
    """``"ok"`` (digest matches), ``"legacy"`` (no sidecar — a pre-sidecar
    build wrote it; trusted), or ``"mismatch"`` (corrupt)."""
    side = sidecar_path(path)
    if not side.exists():
        return "legacy"
    try:
        stored = side.read_text().strip()
    except OSError:
        return "mismatch"
    return "ok" if stored and stored == _digest(path) else "mismatch"


def quarantine(path, reason: str = "sha256-mismatch") -> Optional[Path]:
    """Move a corrupt file (and its sidecar) into a ``quarantine/`` dir
    beside it — evidence is preserved, the slot is freed for recompute.
    Returns the quarantined path (None if the move itself failed)."""
    path = Path(path)
    qdir = path.parent / "quarantine"
    qdir.mkdir(exist_ok=True)
    dest, i = qdir / path.name, 0
    while dest.exists():
        i += 1
        dest = qdir / f"{path.name}.{i}"
    try:
        os.replace(path, dest)
    except OSError:
        return None
    side = sidecar_path(path)
    if side.exists():
        try:
            os.replace(side, Path(str(dest) + ".sha256"))
        except OSError:
            pass
    _log_repair(action="quarantine", target=path.name, ok=True, reason=reason)
    return dest


# ---------------------------------------------------------------------------
# Degrade ladder
# ---------------------------------------------------------------------------


def _ladder(config, dtype) -> list:
    """(config, dtype, needs_x64) rungs, mildest first (module docstring)."""
    import jax.numpy as jnp

    tight = dataclasses.replace(config, bisect_iters=max(config.bisect_iters * 2, 90))
    return [(config, dtype, False), (tight, jnp.float64, True)]


def _x64_context(needed: bool):
    """Rung 1 must run at REAL float64: with x64 disabled (the production
    default — tests force it on, so they can't catch this) a bare
    jnp.float64 request silently canonicalizes to f32 and the 'paranoid
    precision' rung would be a lie. jax.experimental.enable_x64 scopes
    genuine f64 to just the per-cell repair call."""
    import contextlib

    import jax

    if not needed or jax.config.jax_enable_x64:
        return contextlib.nullcontext()
    try:
        from jax.experimental import enable_x64

        return enable_x64()
    except Exception:  # very old jax without the context manager
        return contextlib.nullcontext()


def repair_divergent(
    beta_values,
    u_values,
    base,
    config,
    dtype,
    arrays: dict,
    flags,
    scope: str = "tile",
) -> list:
    """Re-run every divergent cell of one tile up the degrade ladder,
    patching ``arrays`` (the tile's field dict, modified in place) where a
    rung produces a non-divergent replacement.

    ``flags`` is the tile's health flag grid (host array, same shape as
    the arrays). Returns the repairs report: one dict per divergent cell
    with the cell index, the rung that fixed it (or None), and whether it
    was repaired.
    """
    from sbr_tpu.diag.health import DIVERGENT_MASK
    from sbr_tpu.sweeps.baseline_sweeps import beta_u_grid  # lazy: avoids cycle

    flags = np.asarray(flags)
    divergent = np.argwhere((flags & DIVERGENT_MASK) != 0)
    if divergent.size == 0:
        return []
    beta_values = np.asarray(beta_values)
    u_values = np.asarray(u_values)
    report = []
    for i, j in divergent:
        i, j = int(i), int(j)
        entry = {"cell": [i, j], "flags": int(flags[i, j]), "rung": None, "repaired": False}
        for rung, (cfg, dt, needs_x64) in enumerate(_ladder(config, dtype)):
            with _x64_context(needs_x64):
                cell = beta_u_grid(
                    beta_values[i : i + 1], u_values[j : j + 1], base, config=cfg, dtype=dt
                )
            new_flags = (
                int(np.asarray(cell.health.flags).reshape(())) if cell.health is not None else 0
            )
            if new_flags & DIVERGENT_MASK:
                continue  # still divergent — climb the ladder
            for f in arrays:
                arrays[f][i, j] = np.asarray(getattr(cell, f)).reshape(())
            entry.update(rung=rung, repaired=True, new_flags=new_flags)
            break
        report.append(entry)
        _log_repair(
            action="degrade_ladder",
            target=f"{scope}[{i},{j}]",
            ok=entry["repaired"],
            rung=entry["rung"],
            flags=entry["flags"],
        )
    return report


def _log_repair(**fields) -> None:
    try:
        from sbr_tpu import obs

        obs.log_repair(**fields)
    except Exception:
        pass  # telemetry must never sink a repair
